"""Turbine tree: who to send each shred to (the shred_dest layer).

Behavioral port of /root/reference/src/disco/shred/fd_shred_dest.c:

  - per-shred deterministic seed: sha256 over the packed 45-byte struct
    {slot u64, type u8 (0xA5 data / 0x5A code), idx u32, leader pubkey}
    (shred_dest_input, fd_shred_dest.c:24-31) — every validator computes
    the identical tree without coordination;
  - the seed keys the protocol ChaCha20Rng in SHIFT mode (Turbine's roll
    mode), driving a stake-weighted shuffle: staked validators sampled
    weighted-without-replacement first, then unstaked uniformly;
  - the leader sends each shred to the shuffle's root (compute_first,
    excluding itself from the candidates);
  - a non-leader at shuffled position i retransmits to: positions
    1..fanout if i == 0 (the root), positions i+fanout, i+2*fanout, ...,
    i+fanout^2 if 1 <= i <= fanout, nobody otherwise — the two-level
    fanout tree (fd_shred_dest.c:414-415).

The destination list is indexed in the caller's order: staked (stake
descending, the lsched order) first, then unstaked — index maps to full
contact info exactly like fd_shred_dest_idx_to_dest.
"""

from __future__ import annotations

import hashlib
import struct
from dataclasses import dataclass

from firedancer_tpu.ops.chacha20 import MODE_SHIFT, ChaCha20Rng
from firedancer_tpu.protocol import shred as fs
from firedancer_tpu.protocol.wsample import INDETERMINATE, WSample

NO_DEST = 0xFFFF
MAX_SHRED_CNT = 134  # DATA_SHREDS_MAX + PARITY_SHREDS_MAX

_SEED_STRUCT = struct.Struct("<QBI")  # slot, type byte, shred idx


@dataclass
class Dest:
    """One potential destination (contact info from gossip)."""

    pubkey: bytes
    stake: int = 0
    ip4: int = 0
    port: int = 0


def shred_seed(slot: int, shred_idx: int, is_data: bool, leader: bytes) -> bytes:
    t = 0xA5 if is_data else 0x5A
    return hashlib.sha256(
        _SEED_STRUCT.pack(slot, t, shred_idx) + leader
    ).digest()


class ShredDest:
    def __init__(
        self,
        dests: list[Dest],
        lsched,  # EpochLeaders
        source: bytes,
        excluded_stake: int = 0,
    ):
        staked = [d for d in dests if d.stake > 0]
        unstaked = [d for d in dests if d.stake == 0]
        if [d.pubkey for d in dests] != [d.pubkey for d in staked + unstaked]:
            raise ValueError("dests must be ordered staked-first")
        self.dests = dests
        self.staked_cnt = len(staked)
        self.unstaked_cnt = len(unstaked)
        self.lsched = lsched
        self.excluded_stake = excluded_stake
        self._idx_of = {d.pubkey: i for i, d in enumerate(dests)}
        if source not in self._idx_of:
            raise ValueError("source must be among dests")
        self.source_idx = self._idx_of[source]

    # -- shuffles -----------------------------------------------------------

    def _rng(self, seed: bytes) -> ChaCha20Rng:
        return ChaCha20Rng(seed, mode=MODE_SHIFT)

    def _sample_unstaked(self, rng: ChaCha20Rng, exclude: int | None) -> list[int]:
        """Uniform shuffle (without replacement) of unstaked indices."""
        pool = [
            self.staked_cnt + i
            for i in range(self.unstaked_cnt)
            if self.staked_cnt + i != exclude
        ]
        out = []
        while pool:
            out.append(pool.pop(rng.ulong_roll(len(pool))))
        return out

    def _shuffle(self, seed: bytes) -> list[int]:
        """Full Turbine ordering for one shred: staked weighted shuffle
        (INDETERMINATE truncates — excluded stake won a roll and the rest
        of the order is unknowable), then unstaked uniform."""
        rng = self._rng(seed)
        order: list[int] = []
        if self.staked_cnt:
            ws = WSample(
                rng,
                [self.dests[i].stake for i in range(self.staked_cnt)],
                excluded_weight=self.excluded_stake,
            )
            for _ in range(self.staked_cnt):
                idx = ws.sample_and_remove()
                if idx == INDETERMINATE:
                    return order  # poisoned: no further order is known
                order.append(idx)
        order.extend(self._sample_unstaked(rng, exclude=None))
        return order

    # -- public API ---------------------------------------------------------

    def first_for(self, slot: int, idx: int, is_data: bool) -> int:
        """Leader side, field-keyed: the Turbine root for one shred (dest
        index or NO_DEST).  The cluster harness's receipt-ledger audit
        recomputes trees from recorded (slot, idx, type) triples, so the
        tree query must not require the original wire bytes."""
        leader = self.lsched.leader_for_slot(slot)
        if leader is None:
            return NO_DEST
        rng = self._rng(shred_seed(slot, idx, is_data, leader))
        weights = [
            self.dests[i].stake
            for i in range(self.staked_cnt)
            if i != self.source_idx
        ]
        idx_map = [i for i in range(self.staked_cnt) if i != self.source_idx]
        if weights:
            ws = WSample(rng, weights, excluded_weight=self.excluded_stake)
            got = ws.sample()
            return NO_DEST if got == INDETERMINATE else idx_map[got]
        cands = self._sample_unstaked(rng, exclude=self.source_idx)
        return cands[0] if cands else NO_DEST

    def children_for(
        self, slot: int, idx: int, is_data: bool, *, fanout: int
    ) -> list[int]:
        """Non-leader side, field-keyed: this validator's retransmit
        targets for one shred."""
        leader = self.lsched.leader_for_slot(slot)
        if leader is None or leader == self.dests[self.source_idx].pubkey:
            return []  # the leader uses first_for/compute_first
        order = self._shuffle(shred_seed(slot, idx, is_data, leader))
        # the leader doesn't participate in its own tree
        leader_idx = self._idx_of.get(leader)
        order = [i for i in order if i != leader_idx]
        try:
            my = order.index(self.source_idx)
        except ValueError:
            return []  # we fell past a poisoned (truncated) order
        if my == 0:
            positions = range(1, fanout + 1)
        elif my <= fanout:
            positions = range(my + fanout, my + fanout * fanout + 1, fanout)
        else:
            positions = range(0)
        return [order[p] for p in positions if p < len(order)]

    def compute_first(self, shreds: list[bytes]) -> list[int]:
        """Leader side: the Turbine root for each shred (dest index or
        NO_DEST)."""
        out = []
        for buf in shreds:
            s = fs.parse(buf)
            out.append(self.first_for(s.slot, s.idx, s.is_data))
        return out

    def compute_children(
        self, shreds: list[bytes], *, fanout: int
    ) -> list[list[int]]:
        """Non-leader side: this validator's retransmit targets per shred."""
        out = []
        for buf in shreds:
            s = fs.parse(buf)
            out.append(self.children_for(s.slot, s.idx, s.is_data,
                                         fanout=fanout))
        return out
