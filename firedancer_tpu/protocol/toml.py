"""TOML parser — the framework's own, serving the config surface.

Capability parity with the reference's vendored TOML implementation
(/root/reference/src/ballet/toml/ — it ships its own parser rather than
depending on a system library, because the config file is operator
input parsed before anything else is up; no code shared).  Implements
the TOML 1.0 subset a validator config uses:

  - bare/quoted keys, dotted keys, [table] and [[array-of-table]]
    headers;
  - strings (basic + literal, single and multi-line, full escape set
    incl. \\uXXXX/\\UXXXXXXXX), integers (dec/hex/oct/bin, underscores),
    floats (incl. inf/nan), booleans;
  - arrays (nested, heterogeneous per TOML 1.1-draft tolerance is NOT
    accepted — values must parse, but mixed types are allowed as Python
    does not care), inline tables;
  - comments, \\r\\n, duplicate-definition rejection.

Dates are not implemented (no config key uses them) and raise a typed
error.  `loads` is differentially tested against stdlib tomllib in
tests/test_toml.py and fuzzed in tests/test_fuzz.py.
"""

from __future__ import annotations


class TomlError(ValueError):
    def __init__(self, msg: str, line: int):
        super().__init__(f"line {line}: {msg}")
        self.line = line


_WS = frozenset(" \t")
_BARE = set(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_-"
)


class _P:
    def __init__(self, text: str):
        self.s = text
        self.i = 0
        self.line = 1
        self.root: dict = {}
        # paths defined as [table] headers or assignment targets — for
        # duplicate rejection; array-of-table paths may repeat
        self.defined: set[tuple] = set()
        self.aot_paths: set[tuple] = set()

    # -- low-level ----------------------------------------------------------

    def err(self, msg):
        raise TomlError(msg, self.line)

    def peek(self):
        return self.s[self.i] if self.i < len(self.s) else ""

    def adv(self, n=1):
        for _ in range(n):
            if self.i < len(self.s) and self.s[self.i] == "\n":
                self.line += 1
            self.i += 1

    def skip_ws(self):
        while self.peek() in _WS:
            self.adv()

    def skip_comment(self):
        if self.peek() == "#":
            while self.peek() and self.peek() != "\n":
                if ord(self.peek()) < 0x20 and self.peek() != "\t":
                    self.err("control character in comment")
                self.adv()

    def expect_eol(self):
        self.skip_ws()
        self.skip_comment()
        c = self.peek()
        if c == "\r":
            self.adv()
            c = self.peek()
            if c != "\n":
                self.err("bare carriage return")
        if c == "\n":
            self.adv()
        elif c:
            self.err(f"expected end of line, got {c!r}")

    # -- keys ---------------------------------------------------------------

    def key_part(self) -> str:
        c = self.peek()
        if c == '"':
            return self.basic_string()
        if c == "'":
            return self.literal_string()
        out = []
        while self.peek() in _BARE:
            out.append(self.peek())
            self.adv()
        if not out:
            self.err("expected a key")
        return "".join(out)

    def dotted_key(self) -> list[str]:
        parts = [self.key_part()]
        while True:
            self.skip_ws()
            if self.peek() != ".":
                return parts
            self.adv()
            self.skip_ws()
            parts.append(self.key_part())

    # -- strings ------------------------------------------------------------

    def _escape(self) -> str:
        c = self.peek()
        self.adv()
        table = {"b": "\b", "t": "\t", "n": "\n", "f": "\f", "r": "\r",
                 '"': '"', "\\": "\\"}
        if c in table:
            return table[c]
        if c == "u" or c == "U":
            n = 4 if c == "u" else 8
            hexs = self.s[self.i : self.i + n]
            if len(hexs) != n or any(h not in "0123456789abcdefABCDEF"
                                     for h in hexs):
                self.err("bad unicode escape")
            self.adv(n)
            cp = int(hexs, 16)
            if 0xD800 <= cp <= 0xDFFF or cp > 0x10FFFF:
                self.err("invalid unicode scalar")
            return chr(cp)
        self.err(f"unknown escape \\{c}")

    def basic_string(self) -> str:
        if self.s[self.i : self.i + 3] == '"""':
            return self._ml_basic()
        self.adv()
        out = []
        while True:
            c = self.peek()
            if not c or c == "\n":
                self.err("unterminated string")
            self.adv()
            if c == '"':
                return "".join(out)
            if c == "\\":
                out.append(self._escape())
            elif ord(c) < 0x20 and c != "\t":
                self.err("control character in string")
            else:
                out.append(c)

    def _ml_basic(self) -> str:
        self.adv(3)
        if self.peek() == "\n":
            self.adv()
        out = []
        while True:
            if self.s[self.i : self.i + 3] == '"""':
                # up to two extra quotes belong to the content
                extra = 0
                while self.s[self.i + 3 + extra : self.i + 4 + extra] == '"' \
                        and extra < 2:
                    extra += 1
                out.append('"' * extra)
                self.adv(3 + extra)
                return "".join(out)
            c = self.peek()
            if not c:
                self.err("unterminated multi-line string")
            if c == "\\":
                self.adv()
                if self.peek() in _WS or self.peek() in ("\n", "\r"):
                    # line-ending backslash eats whitespace
                    while self.peek() and (self.peek() in _WS
                                           or self.peek() in "\r\n"):
                        self.adv()
                    continue
                out.append(self._escape())
                continue
            if ord(c) < 0x20 and c not in "\t\n\r":
                self.err("control character in string")
            out.append(c)
            self.adv()

    def literal_string(self) -> str:
        if self.s[self.i : self.i + 3] == "'''":
            self.adv(3)
            if self.peek() == "\n":
                self.adv()
            start = self.i
            end = self.s.find("'''", self.i)
            if end < 0:
                self.err("unterminated multi-line literal")
            # trailing quotes may extend the content by up to two
            while self.s[end + 3 : end + 4] == "'" and end + 3 - start >= 0 \
                    and self.s[end + 1 : end + 3] != "''":
                end += 1
            content = self.s[start:end]
            self.adv(end - start + 3)
            return content
        self.adv()
        end = self.s.find("'", self.i)
        nl = self.s.find("\n", self.i)
        if end < 0 or (0 <= nl < end):
            self.err("unterminated literal string")
        content = self.s[self.i : end]
        for ch in content:
            if ord(ch) < 0x20 and ch != "\t":
                self.err("control character in literal string")
        self.adv(end - self.i + 1)
        return content

    # -- values -------------------------------------------------------------

    def value(self):
        c = self.peek()
        if c == '"':
            return self.basic_string()
        if c == "'":
            return self.literal_string()
        if c == "[":
            return self.array()
        if c == "{":
            return self.inline_table()
        if c == "t" and self.s[self.i : self.i + 4] == "true":
            self.adv(4)
            return True
        if c == "f" and self.s[self.i : self.i + 5] == "false":
            self.adv(5)
            return False
        return self.number()

    def number(self):
        start = self.i
        while self.peek() and self.peek() not in set(" \t\n\r,]}#"):
            self.adv()
        tok = self.s[start : self.i]
        if not tok:
            self.err("expected a value")
        try:
            return _parse_number(tok)
        except ValueError:
            if any(ch in tok for ch in ":-") and tok[0].isdigit():
                self.err("dates are not supported")
            self.err(f"bad value {tok!r}")

    def array(self):
        self.adv()
        out = []
        while True:
            self._skip_ws_nl()
            if self.peek() == "]":
                self.adv()
                return out
            out.append(self.value())
            self._skip_ws_nl()
            if self.peek() == ",":
                self.adv()
            elif self.peek() != "]":
                self.err("expected , or ] in array")

    def inline_table(self):
        self.adv()
        out: dict = {}
        self.skip_ws()
        if self.peek() == "}":
            self.adv()
            return out
        while True:
            self.skip_ws()
            parts = self.dotted_key()
            self.skip_ws()
            if self.peek() != "=":
                self.err("expected = in inline table")
            self.adv()
            self.skip_ws()
            v = self.value()
            tgt = out
            for p in parts[:-1]:
                tgt = tgt.setdefault(p, {})
                if not isinstance(tgt, dict):
                    self.err("dotted key collides in inline table")
            if parts[-1] in tgt:
                self.err(f"duplicate key {parts[-1]!r} in inline table")
            tgt[parts[-1]] = v
            self.skip_ws()
            if self.peek() == ",":
                self.adv()
            elif self.peek() == "}":
                self.adv()
                return out
            else:
                self.err("expected , or } in inline table")

    def _skip_ws_nl(self):
        while True:
            self.skip_ws()
            self.skip_comment()
            if self.peek() and self.peek() in "\r\n":
                self.adv()
            else:
                return

    # -- document -----------------------------------------------------------

    def _navigate(self, parts: list[tuple], *, create_aot: bool):
        """Walk/create the table path for a header."""
        cur = self.root
        walked: tuple = ()
        for k in parts[:-1]:
            walked += (k,)
            nxt = cur.get(k)
            if nxt is None:
                nxt = cur[k] = {}
            if isinstance(nxt, list):
                nxt = nxt[-1]
            if not isinstance(nxt, dict):
                self.err(f"key {k!r} is not a table")
            cur = nxt
        last = parts[-1]
        walked += (last,)
        if create_aot:
            arr = cur.get(last)
            if arr is None:
                arr = cur[last] = []
                self.aot_paths.add(walked)
            if not isinstance(arr, list) or walked not in self.aot_paths:
                self.err(f"{last!r} is not an array of tables")
            fresh: dict = {}
            arr.append(fresh)
            # instance-discriminated path: each [[element]] is a fresh
            # namespace for duplicate tracking
            return fresh, walked + (len(arr) - 1,)
        nxt = cur.get(last)
        if walked in self.defined:
            self.err(f"table {last!r} already defined")
        self.defined.add(walked)
        if nxt is None:
            nxt = cur[last] = {}
        if isinstance(nxt, list):
            self.err(f"{last!r} is an array of tables")
        if not isinstance(nxt, dict):
            self.err(f"key {last!r} already holds a value")
        return nxt, walked

    def parse(self) -> dict:
        target = self.root
        prefix: tuple = ()
        while self.i < len(self.s):
            self.skip_ws()
            self.skip_comment()
            c = self.peek()
            if not c:
                break
            if c in ("\r", "\n"):
                self.expect_eol()
                continue
            if c == "[":
                aot = self.s[self.i : self.i + 2] == "[["
                self.adv(2 if aot else 1)
                self.skip_ws()
                parts = self.dotted_key()
                self.skip_ws()
                closer = "]]" if aot else "]"
                if self.s[self.i : self.i + len(closer)] != closer:
                    self.err(f"expected {closer}")
                self.adv(len(closer))
                target, prefix = self._navigate(parts, create_aot=aot)
                self.expect_eol()
                continue
            parts = self.dotted_key()
            self.skip_ws()
            if self.peek() != "=":
                self.err("expected = after key")
            self.adv()
            self.skip_ws()
            v = self.value()
            tgt = target
            walked = prefix
            for p in parts[:-1]:
                walked += (p,)
                nxt = tgt.get(p)
                if nxt is None:
                    nxt = tgt[p] = {}
                if not isinstance(nxt, dict) or walked in self.defined:
                    self.err(f"dotted key {p!r} collides")
                tgt = nxt
            walked += (parts[-1],)
            if parts[-1] in tgt or walked in self.defined:
                self.err(f"duplicate key {parts[-1]!r}")
            self.defined.add(walked)
            tgt[parts[-1]] = v
            self.expect_eol()
        return self.root


def _parse_number(tok: str):
    t = tok.replace("_", "") if _underscores_ok(tok) else None
    if t is None:
        raise ValueError(tok)
    low = t.lower()
    sign = 1
    body = low
    if body and body[0] in "+-":
        sign = -1 if body[0] == "-" else 1
        body = body[1:]
    if body in ("inf",):
        return sign * float("inf")
    if body in ("nan",):
        return float("nan")
    if body.startswith("0x"):
        return sign * int(body[2:], 16)
    if body.startswith("0o"):
        return sign * int(body[2:], 8)
    if body.startswith("0b"):
        return sign * int(body[2:], 2)
    if any(ch in body for ch in ".e"):
        if body.startswith(".") or body.endswith("."):
            raise ValueError(tok)
        if "." in body:
            frac = body.split(".", 1)[1]
            if not frac or not frac[0].isdigit():
                raise ValueError(tok)
        return float(t)
    if not body.isdigit():
        raise ValueError(tok)
    if len(body) > 1 and body[0] == "0":
        raise ValueError(tok)  # no leading zeros
    return sign * int(body)


def _underscores_ok(tok: str) -> bool:
    if "_" not in tok:
        return True
    if tok.startswith("_") or tok.endswith("_") or "__" in tok:
        return False
    for i, ch in enumerate(tok):
        if ch == "_":
            if not (tok[i - 1].isalnum() and tok[i + 1].isalnum()):
                return False
    return True


def loads(text: str | bytes) -> dict:
    if isinstance(text, (bytes, bytearray)):
        text = text.decode("utf-8")
    return _P(text).parse()


def load(f) -> dict:
    return loads(f.read())
