"""sBPF ELF loader + instruction decoder (the ballet/sbpf layer).

Capability parity with /root/reference/src/ballet/sbpf/fd_sbpf_loader.c:
parse and validate a Solana BPF program ELF (little-endian ELF64,
e_machine BPF/SBPF), locate .text / read-only sections and the
entrypoint, and apply the two load-time relocation kinds the protocol
uses (R_BPF_64_64 symbol addresses, R_BPF_64_RELATIVE rebasing into the
program's VM address space at MM_PROGRAM_START = 2^32).  The instruction
decoder covers the sBPF ISA encoding (8-byte slots: opcode, dst/src
registers, 16-bit offset, 32-bit immediate; lddw spans two slots) — the
VM interpreter builds on it.

ELF structure constants (magic, header offsets, section-header layout,
relocation encodings) are the public ELF-64 / Solana sBPF ABI.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

EM_BPF = 247
EM_SBPF = 263
MM_PROGRAM_START = 1 << 32

R_BPF_64_64 = 1
R_BPF_64_RELATIVE = 8

_EHDR = struct.Struct("<16sHHIQQQIHHHHHH")
_SHDR = struct.Struct("<IIQQQQIIQQ")
_REL = struct.Struct("<QQ")  # r_offset, r_info
_SYM = struct.Struct("<IBBHQQ")


class SbpfError(ValueError):
    pass


@dataclass
class Section:
    name: str
    sh_type: int
    flags: int
    addr: int
    offset: int
    size: int


@dataclass
class Program:
    rodata: bytearray      # the loaded program image (text + ro sections)
    text_off: int          # byte offset of .text within rodata
    text_sz: int
    entry_pc: int          # entrypoint as an instruction index into text
    sections: list[Section]

    def text(self) -> bytes:
        return bytes(self.rodata[self.text_off : self.text_off + self.text_sz])


def load(elf: bytes) -> Program:
    """Parse + validate + relocate (fd_sbpf_program_load)."""
    if len(elf) < _EHDR.size:
        raise SbpfError("truncated ELF header")
    (
        ident, e_type, e_machine, e_version, e_entry, _phoff, e_shoff,
        _flags, _ehsize, _phentsz, _phnum, e_shentsize, e_shnum, e_shstrndx,
    ) = _EHDR.unpack_from(elf, 0)
    if ident[:4] != b"\x7fELF":
        raise SbpfError("bad ELF magic")
    if ident[4] != 2 or ident[5] != 1:
        raise SbpfError("sBPF requires little-endian ELF64")
    if e_machine not in (EM_BPF, EM_SBPF):
        raise SbpfError(f"not a BPF machine type ({e_machine})")
    if e_shentsize != _SHDR.size or e_shoff + e_shnum * _SHDR.size > len(elf):
        raise SbpfError("malformed section table")

    raw_shdrs = [
        _SHDR.unpack_from(elf, e_shoff + i * _SHDR.size) for i in range(e_shnum)
    ]
    if e_shstrndx >= e_shnum:
        raise SbpfError("bad shstrndx")
    str_off, str_sz = raw_shdrs[e_shstrndx][4], raw_shdrs[e_shstrndx][5]

    def name_at(off: int) -> str:
        end = elf.find(b"\x00", str_off + off, str_off + str_sz)
        if end < 0:
            raise SbpfError("unterminated section name")
        return elf[str_off + off : end].decode(errors="replace")

    sections = []
    for sh in raw_shdrs:
        sh_name, sh_type, sh_flags, sh_addr, sh_offset, sh_size = sh[:6]
        sections.append(
            Section(name_at(sh_name), sh_type, sh_flags, sh_addr, sh_offset, sh_size)
        )

    text = next((s for s in sections if s.name == ".text"), None)
    if text is None or text.size == 0:
        raise SbpfError("missing .text")
    if not text.flags & 0x2:
        raise SbpfError(".text must be an ALLOC section")
    if text.offset + text.size > len(elf):
        raise SbpfError(".text out of bounds")
    if text.size % 8:
        raise SbpfError(".text not a whole number of instruction slots")

    # program image: every alloc section copied at its file offset (the
    # reference builds a contiguous rodata image indexed by file offset).
    # EVERY copy is bounds-checked: a slice assignment fed fewer bytes
    # than its target SHRINKS a bytearray silently, corrupting the image.
    alloc = [s for s in sections if s.flags & 0x2]
    if not alloc:
        raise SbpfError("no loadable sections")
    image_sz = max(s.offset + s.size for s in alloc)
    rodata = bytearray(image_sz)
    for s in alloc:
        if s.sh_type == 8:  # SHT_NOBITS carries no bytes
            continue
        if s.offset + s.size > len(elf):
            raise SbpfError(f"section '{s.name}' out of bounds")
        rodata[s.offset : s.offset + s.size] = elf[s.offset : s.offset + s.size]

    # entrypoint: e_entry is a VM address inside .text
    if not (text.addr <= e_entry < text.addr + text.size):
        raise SbpfError("entrypoint outside .text")
    if (e_entry - text.addr) % 8:
        raise SbpfError("entrypoint not slot aligned")
    entry_pc = (e_entry - text.addr) // 8

    # relocations (.rel.dyn): the two protocol kinds
    rel = next((s for s in sections if s.name in (".rel.dyn", ".rel.text")), None)
    symtab = next((s for s in sections if s.name in (".dynsym", ".symtab")), None)
    if rel is not None:
        if rel.offset + rel.size > len(elf):
            raise SbpfError("relocation table out of bounds")
        for off in range(rel.offset, rel.offset + rel.size - _REL.size + 1, _REL.size):
            r_offset, r_info = _REL.unpack_from(elf, off)
            r_type = r_info & 0xFFFFFFFF
            r_sym = r_info >> 32
            if r_type not in (R_BPF_64_RELATIVE, R_BPF_64_64):
                continue  # other kinds: skipped (reference rejects few)
            # both kinds write an lddw imm pair: low 32 bits at +4, high
            # 32 bits at +12 — the FULL range must be in bounds (a slice
            # assign past the end would silently GROW the bytearray)
            if r_offset + 16 > len(rodata):
                raise SbpfError("relocation out of bounds")
            if r_type == R_BPF_64_RELATIVE:
                lo = int.from_bytes(rodata[r_offset + 4 : r_offset + 8], "little")
                hi = int.from_bytes(rodata[r_offset + 12 : r_offset + 16], "little")
                addr = (lo | (hi << 32)) + MM_PROGRAM_START
            else:  # R_BPF_64_64: absolute symbol address
                if symtab is None:
                    raise SbpfError("symbol relocation without symtab")
                sym_off = symtab.offset + r_sym * _SYM.size
                if sym_off + _SYM.size > len(elf):
                    raise SbpfError("relocation symbol out of bounds")
                _n, _i, _o, _shn, st_value, _sz = _SYM.unpack_from(elf, sym_off)
                addr = st_value + MM_PROGRAM_START
            rodata[r_offset + 4 : r_offset + 8] = (addr & 0xFFFFFFFF).to_bytes(
                4, "little"
            )
            rodata[r_offset + 12 : r_offset + 16] = (
                (addr >> 32) & 0xFFFFFFFF
            ).to_bytes(4, "little")
            # other kinds: ignored (parity: the reference rejects few,
            # skips the rest)

    return Program(
        rodata=rodata,
        text_off=text.offset,
        text_sz=text.size,
        entry_pc=entry_pc,
        sections=sections,
    )


# -- instruction decode -------------------------------------------------------

OP_LDDW = 0x18

# opcode -> mnemonic for the common sBPF subset (public ISA encoding)
MNEMONICS = {
    0x07: "add64_imm", 0x0F: "add64_reg", 0x17: "sub64_imm", 0x1F: "sub64_reg",
    0x27: "mul64_imm", 0x2F: "mul64_reg", 0x37: "div64_imm", 0x3F: "div64_reg",
    0x47: "or64_imm", 0x4F: "or64_reg", 0x57: "and64_imm", 0x5F: "and64_reg",
    0x67: "lsh64_imm", 0x6F: "lsh64_reg", 0x77: "rsh64_imm", 0x7F: "rsh64_reg",
    0x87: "neg64", 0x97: "mod64_imm", 0x9F: "mod64_reg",
    0xA7: "xor64_imm", 0xAF: "xor64_reg", 0xB7: "mov64_imm", 0xBF: "mov64_reg",
    0x18: "lddw",
    0x61: "ldxw", 0x69: "ldxh", 0x71: "ldxb", 0x79: "ldxdw",
    0x62: "stw", 0x6A: "sth", 0x72: "stb", 0x7A: "stdw",
    0x63: "stxw", 0x6B: "stxh", 0x73: "stxb", 0x7B: "stxdw",
    0x05: "ja", 0x15: "jeq_imm", 0x1D: "jeq_reg", 0x25: "jgt_imm",
    0x2D: "jgt_reg", 0x35: "jge_imm", 0x3D: "jge_reg", 0xA5: "jlt_imm",
    0xAD: "jlt_reg", 0xB5: "jle_imm", 0xBD: "jle_reg", 0x45: "jset_imm",
    0x4D: "jset_reg", 0x55: "jne_imm", 0x5D: "jne_reg", 0x65: "jsgt_imm",
    0x6D: "jsgt_reg", 0x75: "jsge_imm", 0x7D: "jsge_reg", 0xC5: "jslt_imm",
    0xCD: "jslt_reg", 0xD5: "jsle_imm", 0xDD: "jsle_reg",
    0x85: "call", 0x8D: "callx", 0x95: "exit",
    # 32-bit ALU class
    0x04: "add32_imm", 0x0C: "add32_reg", 0x14: "sub32_imm", 0x1C: "sub32_reg",
    0x24: "mul32_imm", 0x2C: "mul32_reg", 0x34: "div32_imm", 0x3C: "div32_reg",
    0x44: "or32_imm", 0x4C: "or32_reg", 0x54: "and32_imm", 0x5C: "and32_reg",
    0x64: "lsh32_imm", 0x6C: "lsh32_reg", 0x74: "rsh32_imm", 0x7C: "rsh32_reg",
    0x84: "neg32", 0x94: "mod32_imm", 0x9C: "mod32_reg",
    0xA4: "xor32_imm", 0xAC: "xor32_reg", 0xB4: "mov32_imm", 0xBC: "mov32_reg",
    0xC4: "arsh32_imm", 0xCC: "arsh32_reg", 0xC7: "arsh64_imm", 0xCF: "arsh64_reg",
    0xD4: "le", 0xDC: "be",
}


@dataclass(frozen=True)
class Insn:
    pc: int
    opcode: int
    dst: int
    src: int
    off: int
    imm: int
    mnemonic: str


def decode(text: bytes) -> list[Insn]:
    """Decode .text into instructions; lddw consumes two slots."""
    if len(text) % 8:
        raise SbpfError("text not slot aligned")
    out = []
    pc = 0
    n = len(text) // 8
    while pc < n:
        slot = text[pc * 8 : pc * 8 + 8]
        opcode = slot[0]
        dst = slot[1] & 0x0F
        src = slot[1] >> 4
        if dst > 10 or src > 10:  # r0..r10 only (the sBPF verifier rule)
            raise SbpfError(f"bad register (dst={dst}, src={src}) at pc {pc}")
        off = int.from_bytes(slot[2:4], "little", signed=True)
        imm = int.from_bytes(slot[4:8], "little", signed=True)
        if opcode == OP_LDDW:
            if pc + 1 >= n:
                raise SbpfError("lddw at end of text")
            hi = int.from_bytes(text[pc * 8 + 12 : pc * 8 + 16], "little")
            imm = (imm & 0xFFFFFFFF) | (hi << 32)
            out.append(Insn(pc, opcode, dst, src, off, imm, "lddw"))
            pc += 2
            continue
        mn = MNEMONICS.get(opcode)
        if mn is None:
            raise SbpfError(f"unknown opcode 0x{opcode:02x} at pc {pc}")
        out.append(Insn(pc, opcode, dst, src, off, imm, mn))
        pc += 1
    return out
