"""JSON lexer/parser — the ballet/json counterpart.

Counterpart of /root/reference/src/ballet/json/ (cJSON-derived lexer
feeding the RPC server).  A recursive-descent parser with the strictness
an RPC boundary needs: depth-limited (stack safety against adversarial
nesting), duplicate-key detection optional, strict number grammar, and
\\uXXXX escapes incl. surrogate pairs.  `loads` returns plain Python
values; `dumps` is the matching compact encoder (sorted keys optional).

The point of owning this instead of the stdlib: the RPC and metrics
servers sit on untrusted sockets, and the parser's failure modes
(depth, size, grammar) must be explicit and tested — the same reason
the reference vendors its own lexer.
"""

from __future__ import annotations

MAX_DEPTH = 64
MAX_LEN = 16 * 1024 * 1024
MAX_NUMBER_DIGITS = 400  # int(text) past ~4300 digits raises ValueError
# on CPython >= 3.11; the contract here is JsonError for any bad input

_WS = " \t\n\r"
_ESC = {'"': '"', "\\": "\\", "/": "/", "b": "\b", "f": "\f",
        "n": "\n", "r": "\r", "t": "\t"}
_REV_ESC = {v: "\\" + k for k, v in _ESC.items() if k != "/"}


class JsonError(ValueError):
    def __init__(self, msg: str, pos: int):
        super().__init__(f"{msg} at offset {pos}")
        self.pos = pos


class _Parser:
    def __init__(self, s: str, *, reject_duplicate_keys: bool):
        self.s = s
        self.i = 0
        self.n = len(s)
        self.reject_dups = reject_duplicate_keys

    def err(self, msg):
        raise JsonError(msg, self.i)

    def skip_ws(self):
        while self.i < self.n and self.s[self.i] in _WS:
            self.i += 1

    def expect(self, ch):
        if self.i >= self.n or self.s[self.i] != ch:
            self.err(f"expected {ch!r}")
        self.i += 1

    def value(self, depth):
        if depth > MAX_DEPTH:
            self.err("nesting too deep")
        self.skip_ws()
        if self.i >= self.n:
            self.err("unexpected end of input")
        c = self.s[self.i]
        if c == "{":
            return self.obj(depth)
        if c == "[":
            return self.arr(depth)
        if c == '"':
            return self.string()
        if c == "t":
            return self.lit("true", True)
        if c == "f":
            return self.lit("false", False)
        if c == "n":
            return self.lit("null", None)
        if c == "-" or c.isdigit():
            return self.number()
        self.err(f"unexpected character {c!r}")

    def lit(self, word, val):
        if self.s[self.i : self.i + len(word)] != word:
            self.err(f"bad literal")
        self.i += len(word)
        return val

    def obj(self, depth):
        self.expect("{")
        out = {}
        self.skip_ws()
        if self.i < self.n and self.s[self.i] == "}":
            self.i += 1
            return out
        while True:
            self.skip_ws()
            key = self.string()
            if self.reject_dups and key in out:
                self.err(f"duplicate key {key!r}")
            self.skip_ws()
            self.expect(":")
            out[key] = self.value(depth + 1)
            self.skip_ws()
            if self.i >= self.n:
                self.err("unterminated object")
            if self.s[self.i] == ",":
                self.i += 1
                continue
            if self.s[self.i] == "}":
                self.i += 1
                return out
            self.err("expected ',' or '}'")

    def arr(self, depth):
        self.expect("[")
        out = []
        self.skip_ws()
        if self.i < self.n and self.s[self.i] == "]":
            self.i += 1
            return out
        while True:
            out.append(self.value(depth + 1))
            self.skip_ws()
            if self.i >= self.n:
                self.err("unterminated array")
            if self.s[self.i] == ",":
                self.i += 1
                continue
            if self.s[self.i] == "]":
                self.i += 1
                return out
            self.err("expected ',' or ']'")

    def string(self):
        self.expect('"')
        out = []
        while True:
            if self.i >= self.n:
                self.err("unterminated string")
            c = self.s[self.i]
            if c == '"':
                self.i += 1
                return "".join(out)
            if c == "\\":
                self.i += 1
                if self.i >= self.n:
                    self.err("bad escape")
                e = self.s[self.i]
                if e in _ESC:
                    out.append(_ESC[e])
                    self.i += 1
                elif e == "u":
                    out.append(self._unicode_escape())
                else:
                    self.err(f"bad escape \\{e}")
            elif ord(c) < 0x20:
                self.err("control character in string")
            else:
                out.append(c)
                self.i += 1

    def _unicode_escape(self):
        def hex4():
            h = self.s[self.i + 1 : self.i + 5]
            # explicit hex-digit check: int(h, 16) accepts '+', '_',
            # whitespace — all invalid JSON
            if len(h) != 4 or any(c not in "0123456789abcdefABCDEF"
                                  for c in h):
                self.err("bad \\u escape")
            v = int(h, 16)
            self.i += 5
            return v

        v = hex4()
        if 0xD800 <= v <= 0xDBFF:  # high surrogate: need the low half
            if self.s[self.i : self.i + 2] != "\\u":
                self.err("unpaired surrogate")
            self.i += 1
            lo = hex4()
            if not 0xDC00 <= lo <= 0xDFFF:
                self.err("bad low surrogate")
            v = 0x10000 + ((v - 0xD800) << 10) + (lo - 0xDC00)
        elif 0xDC00 <= v <= 0xDFFF:
            self.err("unpaired surrogate")
        return chr(v)

    def number(self):
        start = self.i
        s = self.s
        if self.i < self.n and s[self.i] == "-":
            self.i += 1
        if self.i >= self.n or not s[self.i].isdigit():
            self.err("bad number")
        if s[self.i] == "0":
            self.i += 1
            if self.i < self.n and s[self.i].isdigit():
                self.err("leading zero")
        else:
            while self.i < self.n and s[self.i].isdigit():
                self.i += 1
        is_float = False
        if self.i < self.n and s[self.i] == ".":
            is_float = True
            self.i += 1
            if self.i >= self.n or not s[self.i].isdigit():
                self.err("bad fraction")
            while self.i < self.n and s[self.i].isdigit():
                self.i += 1
        if self.i < self.n and s[self.i] in "eE":
            is_float = True
            self.i += 1
            if self.i < self.n and s[self.i] in "+-":
                self.i += 1
            if self.i >= self.n or not s[self.i].isdigit():
                self.err("bad exponent")
            while self.i < self.n and s[self.i].isdigit():
                self.i += 1
        text = s[start : self.i]
        if len(text) > MAX_NUMBER_DIGITS:
            self.err("number too long")
        return float(text) if is_float else int(text)


def loads(data: str | bytes, *, reject_duplicate_keys: bool = False):
    if isinstance(data, (bytes, bytearray)):
        data = data.decode("utf-8")
    if len(data) > MAX_LEN:
        raise JsonError("input too large", 0)
    p = _Parser(data, reject_duplicate_keys=reject_duplicate_keys)
    v = p.value(0)
    p.skip_ws()
    if p.i != p.n:
        p.err("trailing data")
    return v


def _esc_str(s: str) -> str:
    out = ['"']
    for c in s:
        if c in _REV_ESC:
            out.append(_REV_ESC[c])
        elif ord(c) < 0x20:
            out.append(f"\\u{ord(c):04x}")
        else:
            out.append(c)
    out.append('"')
    return "".join(out)


def dumps(v, *, sort_keys: bool = False) -> str:
    if v is None:
        return "null"
    if v is True:
        return "true"
    if v is False:
        return "false"
    if isinstance(v, int):
        return str(v)
    if isinstance(v, float):
        if v != v or v in (float("inf"), float("-inf")):
            raise TypeError("non-finite floats are not JSON")
        return repr(v)
    if isinstance(v, str):
        return _esc_str(v)
    if isinstance(v, (list, tuple)):
        return "[" + ",".join(dumps(x, sort_keys=sort_keys) for x in v) + "]"
    if isinstance(v, dict):
        items = sorted(v.items()) if sort_keys else v.items()
        return "{" + ",".join(
            _esc_str(str(k)) + ":" + dumps(x, sort_keys=sort_keys)
            for k, x in items
        ) + "}"
    raise TypeError(f"cannot encode {type(v).__name__}")
