"""Solana transaction wire-format parser and builder.

Clean-room implementation of the transaction anatomy
(https://docs.solana.com/developing/programming-model/transactions) with the
same validation rules and descriptor shape as the reference's parser
(/root/reference/src/ballet/txn/fd_txn.h, fd_txn_parse.c) so the verify /
dedup / pack stages see identical accept/reject behavior:

  - payload <= 1232 bytes (FD_TXN_MTU)
  - 1 <= signature_cnt <= 127, and it must equal the message header's count
  - readonly_signed_cnt < signature_cnt (fee payer must be a writable signer)
  - signature_cnt <= acct_addr_cnt <= 128; signature_cnt + ro_unsigned <= cnt
  - versioned txns: only v0; legacy txns: no address-table lookups
  - instructions: program_id index in (0, acct_addr_cnt) (fee payer can't be
    the program, programs can't come from tables), account indices within
    static + loaded addresses, <= 64 instructions
  - address-table lookups: <= 127 tables, each with >= 1 index, per-table and
    total loaded counts bounded by 128 - acct_addr_cnt
  - no trailing bytes

The descriptor stores *offsets into the payload* (not copies), mirroring
fd_txn_t, so downstream stages slice the original buffer zero-copy.
"""

from __future__ import annotations

from dataclasses import dataclass, field

SIGNATURE_SZ = 64
PUBKEY_SZ = 32
ACCT_ADDR_SZ = 32
BLOCKHASH_SZ = 32

TXN_MTU = 1232
SIG_MAX = 127        # wire-format bound (compact-u16 == u8 range)
ACTUAL_SIG_MAX = 12  # what fits in an MTU-sized payload
ACCT_ADDR_MAX = 128
ADDR_TABLE_LOOKUP_MAX = 127
INSTR_MAX = 64
MIN_SERIALIZED_SZ = 134

VLEGACY = 0xFF
V0 = 0x00

_MIN_INSTR_SZ = 3
_MIN_ADDR_LUT_SZ = 34


def compact_u16_decode(buf: bytes, i: int) -> tuple[int, int] | None:
    """Decode a compact-u16 at buf[i:]; returns (value, bytes) or None.

    Rejects non-minimal encodings and values > 0xFFFF, like fd_cu16_dec_sz.
    """
    n = len(buf)
    if i >= n:
        return None
    b0 = buf[i]
    if b0 < 0x80:
        return b0, 1
    if i + 1 >= n:
        return None
    b1 = buf[i + 1]
    if b1 < 0x80:
        if b1 == 0:  # non-minimal (would fit in 1 byte)
            return None
        return (b0 & 0x7F) | (b1 << 7), 2
    if i + 2 >= n:
        return None
    b2 = buf[i + 2]
    if b2 == 0 or b2 > 0x03:  # non-minimal / overflows 16 bits
        return None
    return (b0 & 0x7F) | ((b1 & 0x7F) << 7) | (b2 << 14), 3


def compact_u16_encode(v: int) -> bytes:
    if not 0 <= v <= 0xFFFF:
        raise ValueError("compact-u16 out of range")
    if v < 0x80:
        return bytes([v])
    if v < 0x4000:
        return bytes([(v & 0x7F) | 0x80, v >> 7])
    return bytes([(v & 0x7F) | 0x80, ((v >> 7) & 0x7F) | 0x80, v >> 14])


@dataclass(frozen=True)
class TxnInstr:
    """One instruction: offsets into the payload (fd_txn_instr_t)."""

    program_id: int  # index into account addresses
    acct_cnt: int
    data_sz: int
    acct_off: int
    data_off: int


@dataclass(frozen=True)
class TxnAddrLut:
    """One address-table lookup: offsets into the payload."""

    addr_off: int  # 32-byte table account address
    writable_cnt: int
    readonly_cnt: int
    writable_off: int
    readonly_off: int


@dataclass(frozen=True)
class Txn:
    """Parsed transaction descriptor (fd_txn_t analog, offsets only)."""

    transaction_version: int
    signature_cnt: int
    signature_off: int
    message_off: int
    readonly_signed_cnt: int
    readonly_unsigned_cnt: int
    acct_addr_cnt: int
    acct_addr_off: int
    recent_blockhash_off: int
    addr_table_lookup_cnt: int
    addr_table_adtl_writable_cnt: int
    addr_table_adtl_cnt: int
    instrs: tuple[TxnInstr, ...]
    addr_luts: tuple[TxnAddrLut, ...]

    # -- zero-copy accessors -------------------------------------------------

    def signatures(self, payload: bytes) -> list[bytes]:
        o = self.signature_off
        return [
            payload[o + SIGNATURE_SZ * i : o + SIGNATURE_SZ * (i + 1)]
            for i in range(self.signature_cnt)
        ]

    def message(self, payload: bytes) -> bytes:
        """The signed region: everything from the message header on."""
        return payload[self.message_off :]

    def acct_addrs(self, payload: bytes) -> list[bytes]:
        o = self.acct_addr_off
        return [
            payload[o + ACCT_ADDR_SZ * i : o + ACCT_ADDR_SZ * (i + 1)]
            for i in range(self.acct_addr_cnt)
        ]

    def signers(self, payload: bytes) -> list[bytes]:
        """Pubkeys that must have signed: the first signature_cnt addresses."""
        return self.acct_addrs(payload)[: self.signature_cnt]

    def recent_blockhash(self, payload: bytes) -> bytes:
        o = self.recent_blockhash_off
        return payload[o : o + BLOCKHASH_SZ]

    def total_acct_cnt(self) -> int:
        return self.acct_addr_cnt + self.addr_table_adtl_cnt

    def is_writable(self, idx: int) -> bool:
        """Account-index writability per the message header rules.

        Static accounts: writable unless in the readonly-signed tail of the
        signer range or the readonly-unsigned tail of the static range.
        Loaded accounts: table-writable indices come first (after statics).
        """
        if idx < self.acct_addr_cnt:
            if idx < self.signature_cnt:
                return idx < self.signature_cnt - self.readonly_signed_cnt
            return idx < self.acct_addr_cnt - self.readonly_unsigned_cnt
        return idx < self.acct_addr_cnt + self.addr_table_adtl_writable_cnt


def txn_parse(payload: bytes) -> Txn | None:
    """Parse + validate; None on any malformed input (fd_txn_parse)."""
    n = len(payload)
    if n > TXN_MTU:
        return None
    i = 0

    def left(k: int) -> bool:
        return k <= n - i

    if not left(1):
        return None
    signature_cnt = payload[i]
    i += 1
    if not (1 <= signature_cnt <= SIG_MAX):
        return None
    if not left(SIGNATURE_SZ * signature_cnt):
        return None
    signature_off = i
    i += SIGNATURE_SZ * signature_cnt

    message_off = i
    if not left(1):
        return None
    header_b0 = payload[i]
    i += 1
    if header_b0 & 0x80:
        transaction_version = header_b0 & 0x7F
        if transaction_version != V0:
            return None
        if not left(1) or payload[i] != signature_cnt:
            return None
        i += 1
    else:
        transaction_version = VLEGACY
        if signature_cnt != header_b0:
            return None

    if not left(1):
        return None
    ro_signed_cnt = payload[i]
    i += 1
    if not ro_signed_cnt < signature_cnt:
        return None
    if not left(1):
        return None
    ro_unsigned_cnt = payload[i]
    i += 1

    dec = compact_u16_decode(payload, i)
    if dec is None:
        return None
    acct_addr_cnt, sz = dec
    i += sz
    if not (signature_cnt <= acct_addr_cnt <= ACCT_ADDR_MAX):
        return None
    if signature_cnt + ro_unsigned_cnt > acct_addr_cnt:
        return None
    if not left(ACCT_ADDR_SZ * acct_addr_cnt):
        return None
    acct_addr_off = i
    i += ACCT_ADDR_SZ * acct_addr_cnt
    if not left(BLOCKHASH_SZ):
        return None
    recent_blockhash_off = i
    i += BLOCKHASH_SZ

    dec = compact_u16_decode(payload, i)
    if dec is None:
        return None
    instr_cnt, sz = dec
    i += sz
    if instr_cnt > INSTR_MAX:
        return None
    if not left(_MIN_INSTR_SZ * instr_cnt):
        return None
    if instr_cnt and acct_addr_cnt <= 1:
        return None

    instrs = []
    max_acct = 0
    for _ in range(instr_cnt):
        if not left(_MIN_INSTR_SZ):
            return None
        program_id = payload[i]
        i += 1
        dec = compact_u16_decode(payload, i)
        if dec is None:
            return None
        acct_cnt, sz = dec
        i += sz
        if not left(acct_cnt):
            return None
        acct_off = i
        for k in range(acct_cnt):
            max_acct = max(max_acct, payload[i + k])
        i += acct_cnt
        dec = compact_u16_decode(payload, i)
        if dec is None:
            return None
        data_sz, sz = dec
        i += sz
        if not left(data_sz):
            return None
        data_off = i
        i += data_sz
        if not (0 < program_id < acct_addr_cnt):
            return None
        instrs.append(TxnInstr(program_id, acct_cnt, data_sz, acct_off, data_off))

    addr_luts = []
    adtl_writable = 0
    adtl_total = 0
    if transaction_version == V0:
        dec = compact_u16_decode(payload, i)
        if dec is None:
            return None
        addr_table_cnt, sz = dec
        i += sz
        if addr_table_cnt > ADDR_TABLE_LOOKUP_MAX:
            return None
        if not left(_MIN_ADDR_LUT_SZ * addr_table_cnt):
            return None
        for _ in range(addr_table_cnt):
            if not left(ACCT_ADDR_SZ):
                return None
            addr_off = i
            i += ACCT_ADDR_SZ
            dec = compact_u16_decode(payload, i)
            if dec is None:
                return None
            writable_cnt, sz = dec
            i += sz
            if not left(writable_cnt):
                return None
            writable_off = i
            i += writable_cnt
            dec = compact_u16_decode(payload, i)
            if dec is None:
                return None
            readonly_cnt, sz = dec
            i += sz
            if not left(readonly_cnt):
                return None
            readonly_off = i
            i += readonly_cnt
            if writable_cnt > ACCT_ADDR_MAX - acct_addr_cnt:
                return None
            if readonly_cnt > ACCT_ADDR_MAX - acct_addr_cnt:
                return None
            if writable_cnt + readonly_cnt < 1:
                return None
            addr_luts.append(
                TxnAddrLut(
                    addr_off, writable_cnt, readonly_cnt, writable_off, readonly_off
                )
            )
            adtl_writable += writable_cnt
            adtl_total += writable_cnt + readonly_cnt

    if i != n:
        return None
    if acct_addr_cnt + adtl_total > ACCT_ADDR_MAX:
        return None
    if instrs and max_acct >= acct_addr_cnt + adtl_total:
        return None

    return Txn(
        transaction_version=transaction_version,
        signature_cnt=signature_cnt,
        signature_off=signature_off,
        message_off=message_off,
        readonly_signed_cnt=ro_signed_cnt,
        readonly_unsigned_cnt=ro_unsigned_cnt,
        acct_addr_cnt=acct_addr_cnt,
        acct_addr_off=acct_addr_off,
        recent_blockhash_off=recent_blockhash_off,
        addr_table_lookup_cnt=len(addr_luts),
        addr_table_adtl_writable_cnt=adtl_writable,
        addr_table_adtl_cnt=adtl_total,
        instrs=tuple(instrs),
        addr_luts=tuple(addr_luts),
    )


# -- packed binary descriptor (fd_txn_t's wire-able analog) ------------------
#
# The parsed descriptor rides behind the payload in every post-verify frag
# (the parsed-txn trailer convention, fd_disco_base.h:33-45 / fd_verify.c:
# 93-100), so it needs a fixed binary layout — not pickle — to be a wire
# format the native runtime can read.  All offsets fit u16 (payload <= 1232).
#
# Layout, little-endian, byte-packed:
#   header (17 B): version u8, sig_cnt u8, sig_off u16, msg_off u16,
#     ro_signed u8, ro_unsigned u8, acct_cnt u8, acct_off u16, bh_off u16,
#     lut_cnt u8, adtl_writable u8, adtl_cnt u8, instr_cnt u8
#   per instr (9 B):  program_id u8, acct_cnt u16, data_sz u16,
#                     acct_off u16, data_off u16
#   per lut  (10 B):  addr_off u16, writable_cnt u16, readonly_cnt u16,
#                     writable_off u16, readonly_off u16

import struct

_DESC_HDR = struct.Struct("<BBHHBBBHHBBBB")
_DESC_INSTR = struct.Struct("<BHHHH")
_DESC_LUT = struct.Struct("<HHHHH")


def txn_pack(t: Txn) -> bytes:
    """Serialize a descriptor to its packed binary form."""
    out = bytearray(
        _DESC_HDR.pack(
            t.transaction_version,
            t.signature_cnt,
            t.signature_off,
            t.message_off,
            t.readonly_signed_cnt,
            t.readonly_unsigned_cnt,
            t.acct_addr_cnt,
            t.acct_addr_off,
            t.recent_blockhash_off,
            t.addr_table_lookup_cnt,
            t.addr_table_adtl_writable_cnt,
            t.addr_table_adtl_cnt,
            len(t.instrs),
        )
    )
    for ins in t.instrs:
        out += _DESC_INSTR.pack(
            ins.program_id, ins.acct_cnt, ins.data_sz, ins.acct_off, ins.data_off
        )
    for lut in t.addr_luts:
        out += _DESC_LUT.pack(
            lut.addr_off,
            lut.writable_cnt,
            lut.readonly_cnt,
            lut.writable_off,
            lut.readonly_off,
        )
    return bytes(out)


def txn_packed_sz(instr_cnt: int, lut_cnt: int) -> int:
    return _DESC_HDR.size + _DESC_INSTR.size * instr_cnt + _DESC_LUT.size * lut_cnt


def txn_unpack(buf: bytes, off: int = 0) -> tuple[Txn, int]:
    """Deserialize a packed descriptor at buf[off:]; returns (Txn, end)."""
    (
        version,
        sig_cnt,
        sig_off,
        msg_off,
        ro_signed,
        ro_unsigned,
        acct_cnt,
        acct_off,
        bh_off,
        lut_cnt,
        adtl_writable,
        adtl_cnt,
        instr_cnt,
    ) = _DESC_HDR.unpack_from(buf, off)
    i = off + _DESC_HDR.size
    instrs = []
    for _ in range(instr_cnt):
        instrs.append(TxnInstr(*_DESC_INSTR.unpack_from(buf, i)))
        i += _DESC_INSTR.size
    luts = []
    for _ in range(lut_cnt):
        luts.append(TxnAddrLut(*_DESC_LUT.unpack_from(buf, i)))
        i += _DESC_LUT.size
    return (
        Txn(
            transaction_version=version,
            signature_cnt=sig_cnt,
            signature_off=sig_off,
            message_off=msg_off,
            readonly_signed_cnt=ro_signed,
            readonly_unsigned_cnt=ro_unsigned,
            acct_addr_cnt=acct_cnt,
            acct_addr_off=acct_off,
            recent_blockhash_off=bh_off,
            addr_table_lookup_cnt=lut_cnt,
            addr_table_adtl_writable_cnt=adtl_writable,
            addr_table_adtl_cnt=adtl_cnt,
            instrs=tuple(instrs),
            addr_luts=tuple(luts),
        ),
        i,
    )


def txn_desc_valid(t: Txn, payload_sz: int) -> bool:
    """Cheap structural validation of an *untrusted* unpacked descriptor:
    every count within protocol bounds and every offset range inside the
    payload — the invariants txn_parse guarantees for descriptors it built.
    A trailer that crossed a trust boundary must pass this before its
    accessors are used (slicing would silently truncate, not raise)."""
    if not 1 <= t.signature_cnt <= SIG_MAX:
        return False
    if not (t.signature_cnt <= t.acct_addr_cnt <= ACCT_ADDR_MAX):
        return False
    if t.readonly_signed_cnt >= t.signature_cnt:
        return False
    if t.signature_cnt + t.readonly_unsigned_cnt > t.acct_addr_cnt:
        return False
    if len(t.instrs) > INSTR_MAX or len(t.addr_luts) > ADDR_TABLE_LOOKUP_MAX:
        return False
    if t.addr_table_lookup_cnt != len(t.addr_luts):
        return False
    if t.acct_addr_cnt + t.addr_table_adtl_cnt > ACCT_ADDR_MAX:
        return False
    if t.addr_table_adtl_writable_cnt > t.addr_table_adtl_cnt:
        return False
    spans = [
        (t.signature_off, SIGNATURE_SZ * t.signature_cnt),
        (t.message_off, 1),
        (t.acct_addr_off, ACCT_ADDR_SZ * t.acct_addr_cnt),
        (t.recent_blockhash_off, BLOCKHASH_SZ),
    ]
    for ins in t.instrs:
        spans.append((ins.acct_off, ins.acct_cnt))
        spans.append((ins.data_off, ins.data_sz))
        if not 0 < ins.program_id < t.acct_addr_cnt:
            return False
    for lut in t.addr_luts:
        spans.append((lut.addr_off, ACCT_ADDR_SZ))
        spans.append((lut.writable_off, lut.writable_cnt))
        spans.append((lut.readonly_off, lut.readonly_cnt))
    return all(0 <= off and off + sz <= payload_sz for off, sz in spans)


# -- builder (fd_txn_generate analog, for tests and the synthetic load) ------


@dataclass
class InstrSpec:
    program_id: int
    accounts: bytes  # account indices
    data: bytes


@dataclass
class LutSpec:
    table_addr: bytes  # 32 bytes
    writable: bytes    # indices into the table
    readonly: bytes


def message_build(
    *,
    version: int,
    signature_cnt: int,
    readonly_signed_cnt: int,
    readonly_unsigned_cnt: int,
    acct_addrs: list[bytes],
    recent_blockhash: bytes,
    instrs: list[InstrSpec],
    luts: list[LutSpec] | None = None,
) -> bytes:
    """Serialize the signed message region."""
    out = bytearray()
    if version == V0:
        out.append(0x80 | V0)
        out.append(signature_cnt)
    elif version == VLEGACY:
        out.append(signature_cnt)
    else:
        raise ValueError("bad version")
    out.append(readonly_signed_cnt)
    out.append(readonly_unsigned_cnt)
    out += compact_u16_encode(len(acct_addrs))
    for a in acct_addrs:
        assert len(a) == ACCT_ADDR_SZ
        out += a
    assert len(recent_blockhash) == BLOCKHASH_SZ
    out += recent_blockhash
    out += compact_u16_encode(len(instrs))
    for ins in instrs:
        out.append(ins.program_id)
        out += compact_u16_encode(len(ins.accounts))
        out += ins.accounts
        out += compact_u16_encode(len(ins.data))
        out += ins.data
    if version == V0:
        luts = luts or []
        out += compact_u16_encode(len(luts))
        for lut in luts:
            out += lut.table_addr
            out += compact_u16_encode(len(lut.writable))
            out += lut.writable
            out += compact_u16_encode(len(lut.readonly))
            out += lut.readonly
    return bytes(out)


def txn_assemble(signatures: list[bytes], message: bytes) -> bytes:
    out = bytearray()
    out.append(len(signatures))
    for s in signatures:
        assert len(s) == SIGNATURE_SZ
        out += s
    out += message
    return bytes(out)


SYSTEM_PROGRAM = bytes(32)
# "Vote111..." — protocol constant; lives here (the protocol layer) so
# pack's cost model and the runtime's native program both import DOWN
VOTE_PROGRAM = bytes.fromhex(
    "0761481d357474bb7c4d7624ebd3bdb3d8355e73d11043fc0da3538000000000"
)


def vote_txn(
    voter_secret: bytes,
    vote_account: bytes,
    slot: int,
    recent_blockhash: bytes,
    *,
    voter_pubkey: bytes | None = None,
    bank_hash: bytes = b"\x00" * 32,
) -> bytes:
    """A simple vote (the shape pack routes to its vote lane and the
    runtime's vote program consumes): one VoteInstruction::Vote instr —
    data = u32 tag 2 | Vec<u64> slots | 32B bank hash | Option<i64> ts
    (the real wire; flamenco/vote_program.py executes it)."""
    from firedancer_tpu.ops.ref import ed25519_ref as ref

    voter = voter_pubkey if voter_pubkey is not None else ref.public_key(
        voter_secret
    )
    # the program's own encoder (function-scoped import: flamenco sits
    # above protocol, but a txn BUILDER legitimately speaks its wire)
    from firedancer_tpu.flamenco.vote_program import encode_vote_ix

    data = encode_vote_ix([slot], bank_hash)
    msg = message_build(
        version=VLEGACY,
        signature_cnt=1,
        readonly_signed_cnt=0,
        readonly_unsigned_cnt=1,
        acct_addrs=[voter, vote_account, VOTE_PROGRAM],
        recent_blockhash=recent_blockhash,
        instrs=[InstrSpec(program_id=2, accounts=bytes([1, 0]), data=data)],
    )
    return txn_assemble([ref.sign(voter_secret, msg)], msg)


def transfer_txn(
    from_secret: bytes,
    to_pubkey: bytes,
    lamports: int,
    recent_blockhash: bytes,
    *,
    sign_fn=None,
    from_pubkey: bytes | None = None,
) -> bytes:
    """A minimal legacy system-program transfer, signed (benchg analog:
    tiles/fd_benchg.c transfer mode)."""
    from firedancer_tpu.ops.ref import ed25519_ref as ref

    payer = from_pubkey if from_pubkey is not None else ref.public_key(from_secret)
    data = (2).to_bytes(4, "little") + lamports.to_bytes(8, "little")
    if to_pubkey == payer:
        # account lists are unique (AccountLoadedTwice rule): a
        # self-transfer references the payer entry from both slots
        addrs = [payer, SYSTEM_PROGRAM]
        accounts = bytes([0, 0])
        prog_idx = 1
    else:
        addrs = [payer, to_pubkey, SYSTEM_PROGRAM]
        accounts = bytes([0, 1])
        prog_idx = 2
    msg = message_build(
        version=VLEGACY,
        signature_cnt=1,
        readonly_signed_cnt=0,
        readonly_unsigned_cnt=1,
        acct_addrs=addrs,
        recent_blockhash=recent_blockhash,
        instrs=[InstrSpec(program_id=prog_idx, accounts=accounts, data=data)],
    )
    sig = (sign_fn or ref.sign)(from_secret, msg)
    return txn_assemble([sig], msg)
