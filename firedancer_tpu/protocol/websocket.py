"""WebSocket (RFC 6455) server-side framing for the RPC pubsub surface.

Counterpart of the reference rpcserver's websocket layer
(/root/reference/src/app/rpcserver serves account/slot subscriptions
over ws).  No code shared: handshake and framing are implemented from
RFC 6455 — Sec-WebSocket-Accept = b64(sha1(key || GUID)), client
frames masked, server frames unmasked, opcodes text/binary/close/ping.
"""

from __future__ import annotations

import base64
import hashlib
import struct

GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

OP_CONT = 0x0
OP_TEXT = 0x1
OP_BINARY = 0x2
OP_CLOSE = 0x8
OP_PING = 0x9
OP_PONG = 0xA

MAX_FRAME = 1 << 20


class WsError(ValueError):
    pass


def accept_key(sec_websocket_key: str) -> str:
    digest = hashlib.sha1((sec_websocket_key + GUID).encode()).digest()
    return base64.b64encode(digest).decode()


def handshake_response(sec_websocket_key: str) -> bytes:
    return (
        b"HTTP/1.1 101 Switching Protocols\r\n"
        b"upgrade: websocket\r\n"
        b"connection: Upgrade\r\n"
        b"sec-websocket-accept: " + accept_key(sec_websocket_key).encode()
        + b"\r\n\r\n"
    )


def encode_frame(payload: bytes, opcode: int = OP_TEXT) -> bytes:
    """Server frame: FIN set, unmasked."""
    head = bytes([0x80 | opcode])
    n = len(payload)
    if n < 126:
        head += bytes([n])
    elif n < (1 << 16):
        head += bytes([126]) + struct.pack(">H", n)
    else:
        head += bytes([127]) + struct.pack(">Q", n)
    return head + payload


def decode_frame(buf: bytes) -> tuple[int, bytes, int, bool] | None:
    """-> (opcode, payload, consumed, fin) or None when `buf` is short.
    Client frames MUST be masked (RFC 6455 §5.1)."""
    if len(buf) < 2:
        return None
    b0, b1 = buf[0], buf[1]
    opcode = b0 & 0x0F
    fin = bool(b0 & 0x80)
    masked = bool(b1 & 0x80)
    n = b1 & 0x7F
    off = 2
    if n == 126:
        if len(buf) < 4:
            return None
        n = struct.unpack_from(">H", buf, 2)[0]
        off = 4
    elif n == 127:
        if len(buf) < 10:
            return None
        n = struct.unpack_from(">Q", buf, 2)[0]
        off = 10
    if n > MAX_FRAME:
        raise WsError(f"frame too large ({n})")
    if not masked:
        raise WsError("client frame not masked")
    if len(buf) < off + 4 + n:
        return None
    mask = buf[off : off + 4]
    off += 4
    payload = bytes(b ^ mask[i % 4] for i, b in enumerate(
        buf[off : off + n]))
    return opcode, payload, off + n, fin


class WsConn:
    """A handshaken connection: text in/out with ping/close handling.
    `initial` carries bytes the client pipelined behind its handshake
    request (they are the first frames, not discardable)."""

    def __init__(self, sock, initial: bytes = b""):
        import threading

        self.sock = sock
        self._buf = initial
        self.open = True
        # writes come from BOTH the per-connection handler thread and
        # notifier threads: interleaved partial sendalls would corrupt
        # the frame stream permanently
        self._wlock = threading.Lock()

    def send_text(self, text: str) -> None:
        try:
            with self._wlock:
                self.sock.sendall(encode_frame(text.encode()))
        except OSError:
            self.open = False

    def recv_text(self) -> str | None:
        """Blocking read of the next complete text MESSAGE (fragmented
        frames reassembled per §5.4); None on close or protocol error."""
        fragments: list[bytes] = []
        frag_total = 0
        while self.open:
            try:
                got = decode_frame(self._buf)
            except WsError:
                # protocol violation (unmasked/oversized): fail the
                # connection, never leak the exception to the caller
                self.close()
                return None
            if got is None:
                try:
                    chunk = self.sock.recv(65536)
                except OSError:
                    self.open = False
                    return None
                if not chunk:
                    self.open = False
                    return None
                self._buf += chunk
                continue
            opcode, payload, consumed, fin = got
            self._buf = self._buf[consumed:]
            if opcode == OP_CLOSE:
                try:
                    self.sock.sendall(encode_frame(b"", OP_CLOSE))
                except OSError:
                    pass
                self.open = False
                return None
            if opcode == OP_PING:
                try:
                    self.sock.sendall(encode_frame(payload, OP_PONG))
                except OSError:
                    self.open = False
                continue
            if opcode in (OP_TEXT, OP_BINARY) or (
                opcode == OP_CONT and fragments
            ):
                if opcode != OP_CONT and fragments:
                    self.close()  # new message inside a fragment train
                    return None
                fragments.append(payload)
                frag_total += len(payload)
                # bound BOTH bytes and fragment count: an endless train
                # of zero-length non-FIN continuations must not grow the
                # list (memory) or re-sum it (CPU) forever
                if frag_total > MAX_FRAME or len(fragments) > 1024:
                    self.close()
                    return None
                if fin:
                    return b"".join(fragments).decode("utf-8", "replace")
                # FIN clear: keep collecting continuations
        return None

    def close(self) -> None:
        self.open = False
        try:
            self.sock.sendall(encode_frame(b"", OP_CLOSE))
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass
