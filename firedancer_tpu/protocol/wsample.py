"""Weighted random sampling (the wsample layer) + epoch leader schedule.

Capability parity with /root/reference/src/ballet/wsample/fd_wsample.h and
/root/reference/src/flamenco/leaders/fd_leaders.c:

  - WSample: sample indices with probability proportional to weight, with
    or without removal, driven by the protocol ChaCha20Rng.  The
    "poisoned"/excluded-stake contract matches fd_wsample: a roll landing
    in the excluded tail returns INDETERMINATE and (in removal mode)
    poisons the sampler — once the schedule diverges from the full stake
    list the rest is unknowable.  The reference organizes cumulative
    weights in a radix-8 tree for O(log n) search; semantically that is
    interval search over insertion-order cumulative sums, which is what
    the host model does (np.searchsorted over the prefix array).
  - epoch_leaders: the Solana leader schedule — seed = epoch number LE in
    a 32-byte key, MODE_MOD rng, one weighted sample (no removal) per
    4-slot rotation (fd_leaders.c:72-86, FD_EPOCH_SLOTS_PER_ROTATION).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from firedancer_tpu.ops.chacha20 import MODE_MOD, ChaCha20Rng

EMPTY = (1 << 64) - 1          # FD_WSAMPLE_EMPTY
INDETERMINATE = (1 << 64) - 2  # FD_WSAMPLE_INDETERMINATE

SLOTS_PER_ROTATION = 4


class WSample:
    def __init__(self, rng: ChaCha20Rng, weights, excluded_weight: int = 0):
        self.rng = rng
        self.weights = [int(w) for w in weights]
        if any(w <= 0 for w in self.weights):
            raise ValueError("weights must be positive")
        self.excluded_weight = int(excluded_weight)
        self.removed = [False] * len(self.weights)
        self.unremoved_weight = sum(self.weights)
        self.poisoned = False
        self._prefix = np.cumsum(self.weights, dtype=np.uint64)

    def _map_sample(self, x: int) -> int:
        """Index whose cumulative interval contains x (insertion order)."""
        return int(np.searchsorted(self._prefix, x, side="right"))

    def sample(self) -> int:
        if self.unremoved_weight == 0:
            return EMPTY
        if self.poisoned:
            return INDETERMINATE
        x = self.rng.ulong_roll(self.unremoved_weight + self.excluded_weight)
        if x >= self.unremoved_weight:
            return INDETERMINATE
        return self._map_sample(x)

    def sample_and_remove(self) -> int:
        if self.unremoved_weight == 0:
            return EMPTY
        if self.poisoned:
            return INDETERMINATE
        x = self.rng.ulong_roll(self.unremoved_weight + self.excluded_weight)
        if x >= self.unremoved_weight:
            self.poisoned = True
            return INDETERMINATE
        idx = self._map_sample(x)
        w = self.weights[idx]
        self.weights[idx] = 0
        self.removed[idx] = True
        self.unremoved_weight -= w
        self._prefix = np.cumsum(self.weights, dtype=np.uint64)
        return idx

    def sample_many(self, cnt: int) -> list[int]:
        return [self.sample() for _ in range(cnt)]

    def sample_and_remove_many(self, cnt: int) -> list[int]:
        return [self.sample_and_remove() for _ in range(cnt)]


@dataclass
class EpochLeaders:
    epoch: int
    slot0: int
    slot_cnt: int
    pubkeys: list[bytes]  # stake order; index pub_cnt = indeterminate marker
    sched: list[int]      # one pubkey index per rotation

    def leader_for_slot(self, slot: int) -> bytes | None:
        if not self.slot0 <= slot < self.slot0 + self.slot_cnt:
            return None
        idx = self.sched[(slot - self.slot0) // SLOTS_PER_ROTATION]
        if idx >= len(self.pubkeys):
            return None  # indeterminate (excluded stake won the roll)
        return self.pubkeys[idx]


def epoch_leaders(
    epoch: int,
    slot0: int,
    slot_cnt: int,
    stakes: list[tuple[bytes, int]],
    excluded_stake: int = 0,
) -> EpochLeaders:
    """Derive the leader schedule (fd_epoch_leaders_new).

    stakes: (pubkey, stake) pairs, pre-sorted by the caller the way the
    runtime hands them over (stake desc, then pubkey — Agave order).
    """
    seed = epoch.to_bytes(8, "little") + bytes(24)
    rng = ChaCha20Rng(seed, mode=MODE_MOD)
    ws = WSample(rng, [s for _, s in stakes], excluded_weight=excluded_stake)
    sched_cnt = (slot_cnt + SLOTS_PER_ROTATION - 1) // SLOTS_PER_ROTATION
    pub_cnt = len(stakes)
    sched = [min(ws.sample(), pub_cnt) for _ in range(sched_cnt)]
    return EpochLeaders(
        epoch=epoch,
        slot0=slot0,
        slot_cnt=slot_cnt,
        pubkeys=[k for k, _ in stakes],
        sched=sched,
    )
