"""Program-derived addresses (the PDA derivation the VM exposes).

The public Solana derivation served by sol_create_program_address /
sol_try_find_program_address (fd_vm syscalls in the reference): address
= sha256(seed_0 || .. || seed_n || program_id || "ProgramDerivedAddress"),
valid only when the digest is NOT a point on the ed25519 curve (PDAs must
have no private key); try_find appends a bump byte 255..0 until the
derivation falls off-curve.
"""

from __future__ import annotations

import hashlib

from firedancer_tpu.ops.ref import ed25519_ref as ref

_MARKER = b"ProgramDerivedAddress"
MAX_SEEDS = 16
MAX_SEED_LEN = 32


class PdaError(ValueError):
    pass


def _off_curve(addr: bytes) -> bool:
    return ref.point_decompress(addr) is None


def create_program_address(seeds: list[bytes], program_id: bytes) -> bytes:
    """Derive; raises PdaError if the result lands ON the curve (caller
    picks different seeds — the create syscall's error contract)."""
    if len(seeds) > MAX_SEEDS:
        raise PdaError("too many seeds")
    for s in seeds:
        if len(s) > MAX_SEED_LEN:
            raise PdaError("seed too long")
    if len(program_id) != 32:
        raise PdaError("bad program id")
    h = hashlib.sha256()
    for s in seeds:
        h.update(s)
    h.update(program_id)
    h.update(_MARKER)
    addr = h.digest()
    if not _off_curve(addr):
        raise PdaError("derived address is on the curve")
    return addr


def find_program_address(seeds: list[bytes], program_id: bytes) -> tuple[bytes, int]:
    """Append bump 255..0 until off-curve; -> (address, bump)."""
    for bump in range(255, -1, -1):
        try:
            return create_program_address(seeds + [bytes([bump])], program_id), bump
        except PdaError as e:
            if "on the curve" not in str(e):
                raise
    raise PdaError("no viable bump found")  # pragma: no cover (2^-255)
