"""Host-side Solana protocol wire formats (the reference's ballet layer's
parsers, re-implemented clean-room for the TPU framework's host stages)."""
