"""HTTP/1.1 request/response parsing — the ballet/http counterpart.

Counterpart of /root/reference/src/ballet/http/ (picohttpparser vendored
into fd_picohttpparser.c; used by the metrics server and the snapshot
download client).  Incremental semantics match picohttpparser's: feed
the bytes you have; the parser returns the parsed head + consumed length
once the blank line arrives, NEED_MORE while the head is incomplete, and
raises on malformed input.  Body framing supports Content-Length and
chunked transfer encoding (the two the reference's consumers meet).
"""

from __future__ import annotations

from dataclasses import dataclass, field

NEED_MORE = None
MAX_HEAD = 64 * 1024
MAX_HEADERS = 100

_TOKEN_OK = set(
    b"!#$%&'*+-.^_`|~0123456789"
    b"abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ"
)


class HttpError(ValueError):
    pass


@dataclass
class Request:
    method: str
    path: str
    version: str
    headers: list = field(default_factory=list)  # [(name-lower, value)]
    head_len: int = 0

    def header(self, name: str) -> str | None:
        name = name.lower()
        for k, v in self.headers:
            if k == name:
                return v
        return None


@dataclass
class Response:
    status: int
    reason: str
    version: str
    headers: list = field(default_factory=list)
    head_len: int = 0

    def header(self, name: str) -> str | None:
        name = name.lower()
        for k, v in self.headers:
            if k == name:
                return v
        return None


def _find_head_end(buf: bytes) -> int:
    i = buf.find(b"\r\n\r\n")
    if i < 0:
        if len(buf) > MAX_HEAD:
            raise HttpError("request head too large")
        return -1
    return i + 4


def _parse_headers(lines: list[bytes]) -> list:
    if len(lines) > MAX_HEADERS:
        raise HttpError("too many headers")
    out = []
    for ln in lines:
        if not ln:
            continue
        if ln[:1] in (b" ", b"\t"):  # obs-fold: continuation of previous
            if not out:
                raise HttpError("continuation before first header")
            k, v = out[-1]
            out[-1] = (k, v + " " + ln.strip().decode("latin-1"))
            continue
        sep = ln.find(b":")
        if sep <= 0:
            raise HttpError(f"malformed header line {ln[:40]!r}")
        name = ln[:sep]
        if any(c not in _TOKEN_OK for c in name):
            raise HttpError(f"bad header name {name[:40]!r}")
        out.append(
            (name.decode("latin-1").lower(),
             ln[sep + 1 :].strip().decode("latin-1"))
        )
    return out


def parse_request(buf: bytes) -> Request | None:
    """-> Request (head_len = bytes consumed), NEED_MORE, or raises."""
    end = _find_head_end(buf)
    if end < 0:
        return NEED_MORE
    lines = buf[: end - 4].split(b"\r\n")
    parts = lines[0].split(b" ")
    if len(parts) != 3:
        raise HttpError(f"malformed request line {lines[0][:60]!r}")
    method, path, version = parts
    if not method or any(c not in _TOKEN_OK for c in method):
        raise HttpError("bad method")
    if not version.startswith(b"HTTP/1."):
        raise HttpError(f"unsupported version {version!r}")
    return Request(
        method=method.decode("latin-1"),
        path=path.decode("latin-1"),
        version=version.decode("latin-1"),
        headers=_parse_headers(lines[1:]),
        head_len=end,
    )


def parse_response(buf: bytes) -> Response | None:
    end = _find_head_end(buf)
    if end < 0:
        return NEED_MORE
    lines = buf[: end - 4].split(b"\r\n")
    parts = lines[0].split(b" ", 2)
    if len(parts) < 2 or not parts[0].startswith(b"HTTP/1."):
        raise HttpError(f"malformed status line {lines[0][:60]!r}")
    try:
        status = int(parts[1])
    except ValueError as e:
        raise HttpError("bad status code") from e
    return Response(
        status=status,
        reason=parts[2].decode("latin-1") if len(parts) > 2 else "",
        version=parts[0].decode("latin-1"),
        headers=_parse_headers(lines[1:]),
        head_len=end,
    )


def body_length(msg: Request | Response) -> int | str | None:
    """Content-Length as int, 'chunked', or None (read-to-close /
    no body)."""
    te = msg.header("transfer-encoding")
    if te and "chunked" in te.lower():
        return "chunked"
    cl = msg.header("content-length")
    if cl is None:
        return None
    # ascii-digit check: str.isdigit() accepts unicode digits int() rejects
    if not cl or any(c not in "0123456789" for c in cl):
        raise HttpError(f"bad content-length {cl!r}")
    return int(cl)


def decode_chunked(buf: bytes) -> tuple[bytes, int] | None:
    """Decode a complete chunked body from `buf`; -> (body, consumed) or
    NEED_MORE if the terminal chunk hasn't arrived."""
    out = bytearray()
    off = 0
    while True:
        nl = buf.find(b"\r\n", off)
        if nl < 0:
            return NEED_MORE
        size_str = buf[off:nl].split(b";")[0].strip()
        try:
            size = int(size_str, 16)
        except ValueError as e:
            raise HttpError(f"bad chunk size {size_str[:20]!r}") from e
        off = nl + 2
        if size == 0:
            # trailer section ends with CRLF
            end = buf.find(b"\r\n", off)
            if end < 0:
                return NEED_MORE
            while end != off:  # skip trailers
                off = end + 2
                end = buf.find(b"\r\n", off)
                if end < 0:
                    return NEED_MORE
            return bytes(out), end + 2
        if off + size + 2 > len(buf):
            return NEED_MORE
        out += buf[off : off + size]
        if buf[off + size : off + size + 2] != b"\r\n":
            raise HttpError("chunk missing terminator")
        off += size + 2


MAX_BODY = 16 * 1024 * 1024


class MiniServer:
    """Threaded accept loop over the own parser: one request per
    connection, bounded body, HttpError -> 400.  `handler(request,
    body_bytes) -> response bytes` runs on a per-connection thread.
    Shared by the metrics and RPC servers so robustness fixes land
    once."""

    def __init__(self, handler, *, host: str = "127.0.0.1", port: int = 0,
                 timeout_s: float = 10.0, max_body: int = MAX_BODY,
                 ws_handler=None):
        import socket
        import threading

        self._handler = handler
        # ws_handler(request, socket): invoked after a successful RFC
        # 6455 upgrade handshake; owns the socket for the connection's
        # lifetime (the pubsub surface)
        self._ws_handler = ws_handler
        self._max_body = max_body
        self._timeout = timeout_s
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self._closing = False
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _serve(self):
        import threading
        import time

        while not self._closing:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                if self._closing:
                    return
                # transient accept errors (ECONNABORTED, EMFILE, ...)
                # must not kill the server for the process lifetime
                time.sleep(0.05)
                continue
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _handle(self, conn):
        conn.settimeout(self._timeout)
        buf = b""
        try:
            try:
                while True:
                    req = parse_request(buf)
                    if req is not NEED_MORE:
                        break
                    chunk = conn.recv(65536)
                    if not chunk:
                        return
                    buf += chunk
                if self._ws_handler is not None:
                    hdrs = {k.lower(): v for k, v in req.headers}
                    if hdrs.get("upgrade", "").lower() == "websocket":
                        from firedancer_tpu.protocol.websocket import (
                            handshake_response,
                        )

                        key = hdrs.get("sec-websocket-key")
                        if not key:
                            conn.sendall(build_response(
                                400, b"missing sec-websocket-key\n"))
                            return
                        conn.sendall(handshake_response(key))
                        conn.settimeout(None)  # long-lived subscription
                        # bytes pipelined behind the handshake are the
                        # client's first frames — hand them over too
                        self._ws_handler(req, conn,
                                         bytes(buf[req.head_len :]))
                        return
                need = body_length(req)
                if need == "chunked":
                    conn.sendall(build_response(400, b"no chunked bodies\n"))
                    return
                need = need or 0
                if need > self._max_body:
                    # cap BEFORE buffering: an attacker-controlled
                    # Content-Length must not grow memory unbounded
                    conn.sendall(build_response(400, b"body too large\n"))
                    return
                while len(buf) - req.head_len < need:
                    chunk = conn.recv(65536)
                    if not chunk:
                        return
                    buf += chunk
            except HttpError:
                try:
                    conn.sendall(build_response(400, b"bad request\n"))
                except OSError:
                    pass
                return
            body = buf[req.head_len : req.head_len + need]
            try:
                resp = self._handler(req, body)
            except Exception:
                # a handler bug must answer 500, not strand the client
                # until its timeout with a silent close
                resp = build_response(500, b"internal error\n")
            if isinstance(resp, tuple):
                # streaming response: (head bytes, chunk iterable) —
                # large bodies (snapshots) never materialize in memory
                head, chunks = resp
                conn.sendall(head)
                for chunk in chunks:
                    conn.sendall(chunk)
            else:
                conn.sendall(resp)
        except OSError:
            pass
        finally:
            conn.close()

    @property
    def addr(self):
        return self._sock.getsockname()

    def close(self):
        self._closing = True
        try:
            self._sock.close()
        except OSError:
            pass


def build_stream_head(status: int, body_len: int, *,
                      content_type: str = "text/plain",
                      headers: list | None = None) -> bytes:
    """Response head only, for MiniServer's (head, chunks) streaming
    form: content-length is declared up front, the body follows from an
    iterator so it never lives in memory whole."""
    reason = {200: "OK", 400: "Bad Request", 404: "Not Found",
              405: "Method Not Allowed", 500: "Internal Server Error"}.get(
        status, "")
    head = [f"HTTP/1.1 {status} {reason}".encode()]
    head.append(b"content-type: " + content_type.encode())
    head.append(b"content-length: " + str(body_len).encode())
    # MiniServer serves one request per connection; say so, or HTTP/1.1
    # keep-alive clients reuse the closed socket and flap
    head.append(b"connection: close")
    for k, v in headers or []:
        head.append(f"{k}: {v}".encode())
    return b"\r\n".join(head) + b"\r\n\r\n"


def build_response(status: int, body: bytes = b"", *,
                   content_type: str = "text/plain",
                   headers: list | None = None) -> bytes:
    return build_stream_head(status, len(body), content_type=content_type,
                             headers=headers) + body
