"""ctypes bindings for the native (C++) transaction parser.

native/fd_txn_parse.cpp implements protocol/txn.py's validation rules and
emits the packed descriptor format directly (txn_pack's layout), so the
two parsers are drop-in interchangeable — the differential tests assert
accept/reject AND descriptor equality over valid, malformed, and fuzzed
inputs.  The verify stage's per-packet parse is the host hot path this
accelerates (fd_txn_parse is C in the reference for the same reason).
"""

from __future__ import annotations

import ctypes
import os
import threading

from firedancer_tpu.utils.nativebuild import NativeUnavailable, build_so

from . import txn as ft

_SRC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
    "fd_txn_parse.cpp",
)
_SO = os.path.join(os.path.dirname(_SRC), "fd_txn_parse.so")

_lib = None
_OUT_CAP = 4096
# reusable PER-THREAD output buffer: this binding runs once per ingress
# packet (the verify hot path), and a fresh create_string_buffer per
# call was ~20% of the crossing's cost.  Thread-local because ctypes
# RELEASES the GIL for the foreign call — a shared buffer could be
# written by two threads' fd_txn_parse concurrently (the repo does run
# helper threads: rpc, http); the bytes are copied out before return.
_tls = threading.local()


def _load():
    global _lib
    if _lib is not None:
        return _lib
    build_so(_SRC, _SO)
    lib = ctypes.CDLL(_SO)
    lib.fd_txn_parse.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64, ctypes.c_char_p, ctypes.c_uint64,
    ]
    lib.fd_txn_parse.restype = ctypes.c_int64
    _lib = lib
    return lib


def txn_parse_packed(payload: bytes) -> bytes | None:
    """Native parse -> packed descriptor bytes (txn_pack layout), or None
    on malformed input."""
    lib = _load()
    out = getattr(_tls, "out", None)
    if out is None:
        out = _tls.out = ctypes.create_string_buffer(_OUT_CAP)
    n = lib.fd_txn_parse(payload, len(payload), out, _OUT_CAP)
    if n < 0:
        return None
    return out.raw[:n]


def txn_parse_native(payload: bytes) -> ft.Txn | None:
    """Native parse -> the same Txn descriptor object python's parser
    builds (unpacked from the shared binary layout)."""
    packed = txn_parse_packed(payload)
    if packed is None:
        return None
    desc, end = ft.txn_unpack(packed)
    if end != len(packed):
        return None
    return desc
