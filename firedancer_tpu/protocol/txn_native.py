"""ctypes bindings for the native (C++) transaction parser.

native/fd_txn_parse.cpp implements protocol/txn.py's validation rules and
emits the packed descriptor format directly (txn_pack's layout), so the
two parsers are drop-in interchangeable — the differential tests assert
accept/reject AND descriptor equality over valid, malformed, and fuzzed
inputs.  The verify stage's per-packet parse is the host hot path this
accelerates (fd_txn_parse is C in the reference for the same reason).
"""

from __future__ import annotations

import ctypes
import os
import threading

from firedancer_tpu.utils.nativebuild import NativeUnavailable, build_so

from . import txn as ft

_SRC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
    "fd_txn_parse.cpp",
)
_SO = os.path.join(os.path.dirname(_SRC), "fd_txn_parse.so")

_lib = None
_OUT_CAP = 4096
# reusable PER-THREAD output buffer: this binding runs once per ingress
# packet (the verify hot path), and a fresh create_string_buffer per
# call was ~20% of the crossing's cost.  Thread-local because ctypes
# RELEASES the GIL for the foreign call — a shared buffer could be
# written by two threads' fd_txn_parse concurrently (the repo does run
# helper threads: rpc, http); the bytes are copied out before return.
_tls = threading.local()


def _load():
    global _lib
    if _lib is not None:
        return _lib
    lib = ctypes.CDLL(build_so(_SRC, _SO))
    lib.fd_txn_parse.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64, ctypes.c_char_p, ctypes.c_uint64,
    ]
    lib.fd_txn_parse.restype = ctypes.c_int64
    lib.fd_txn_parse_burst.argtypes = [
        ctypes.c_char_p, ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p,
        ctypes.c_uint64, ctypes.c_void_p,
    ]
    lib.fd_txn_parse_burst.restype = ctypes.c_int64
    _lib = lib
    return lib


def txn_parse_packed(payload: bytes) -> bytes | None:
    """Native parse -> packed descriptor bytes (txn_pack layout), or None
    on malformed input."""
    lib = _load()
    out = getattr(_tls, "out", None)
    if out is None:
        out = _tls.out = ctypes.create_string_buffer(_OUT_CAP)
    n = lib.fd_txn_parse(payload, len(payload), out, _OUT_CAP)
    if n < 0:
        return None
    return out.raw[:n]


def txn_parse_native(payload: bytes) -> ft.Txn | None:
    """Native parse -> the same Txn descriptor object python's parser
    builds (unpacked from the shared binary layout)."""
    packed = txn_parse_packed(payload)
    if packed is None:
        return None
    desc, end = ft.txn_unpack(packed)
    if end != len(packed):
        return None
    return desc


class BurstParser:
    """Sweep-granularity parser (ISSUE 11): ONE fd_txn_parse_burst
    crossing parses every payload of a drained sweep, with the scratch
    buffers (rows table, descriptor arena, per-row meta) preallocated
    and REUSED — the per-sweep caller (verify's sweep_frags) must pay
    zero allocation beyond the returned descriptor bytes.  Single-owner
    by design: one instance per stage, never shared across threads."""

    def __init__(self, max_rows: int = 64):
        import numpy as np

        self._lib = _load()
        self._max = max_rows
        self._rows = np.zeros((max_rows, 2), dtype=np.uint64)
        self._rows_p = self._rows.ctypes.data
        self._meta = np.zeros((max_rows, 2), dtype=np.uint64)
        self._meta_p = self._meta.ctypes.data
        self._cap = max(_OUT_CAP, 512 * max_rows)
        self._out = ctypes.create_string_buffer(self._cap)

    def _grow(self, n: int) -> None:
        import numpy as np

        self._max = max(n, 2 * self._max)
        self._rows = np.zeros((self._max, 2), dtype=np.uint64)
        self._rows_p = self._rows.ctypes.data
        self._meta = np.zeros((self._max, 2), dtype=np.uint64)
        self._meta_p = self._meta.ctypes.data
        self._cap = max(self._cap, 512 * self._max)
        self._out = ctypes.create_string_buffer(self._cap)

    def parse(self, buf: bytes, rows) -> list[bytes | None]:
        """rows: iterable of drain-table rows (off at col 2, sz at col
        3).  Returns one packed descriptor (or None = rejected) per row,
        each byte-identical to txn_parse_packed on the same payload."""
        n = len(rows)
        if n == 0:
            return []
        if n > self._max:
            self._grow(n)
        rt = self._rows
        for i, row in enumerate(rows):
            rt[i, 0] = row[2]
            rt[i, 1] = row[3]
        while True:
            total = self._lib.fd_txn_parse_burst(
                buf, self._rows_p, n, self._out, self._cap, self._meta_p,
            )
            if total != -2:
                break
            self._cap *= 4
            self._out = ctypes.create_string_buffer(self._cap)
        raw = ctypes.string_at(self._out, total)
        meta = self._meta
        return [
            raw[int(meta[i, 0]): int(meta[i, 0]) + int(meta[i, 1])]
            if meta[i, 1] else None
            for i in range(n)
        ]
