"""Base58 encode/decode (Bitcoin alphabet) — reference: src/ballet/base58.

Host implementation (bigint); perf-sensitive users (logging pubkeys,
RPC) batch-amortize at a higher level.  Exact round-trip parity with the
reference's fixed-width 32/64-byte fast paths: leading zero bytes map to
leading '1's and vice versa.
"""

from __future__ import annotations

ALPHABET = "123456789ABCDEFGHJKLMNPQRSTUVWXYZabcdefghijkmnopqrstuvwxyz"
_INDEX = {c: i for i, c in enumerate(ALPHABET)}


def b58_encode(data: bytes) -> str:
    zeros = len(data) - len(data.lstrip(b"\x00"))
    n = int.from_bytes(data, "big")
    out = []
    while n:
        n, r = divmod(n, 58)
        out.append(ALPHABET[r])
    return "1" * zeros + "".join(reversed(out))


def b58_decode(s: str, length: int | None = None) -> bytes:
    n = 0
    for c in s:
        if c not in _INDEX:
            raise ValueError(f"invalid base58 char {c!r}")
        n = n * 58 + _INDEX[c]
    zeros = len(s) - len(s.lstrip("1"))
    body = n.to_bytes((n.bit_length() + 7) // 8, "big") if n else b""
    out = b"\x00" * zeros + body
    if length is not None:
        if len(out) > length:
            raise ValueError("decoded value too long")
        out = b"\x00" * (length - len(out)) + out
    return out


def b58_encode32(data: bytes) -> str:
    assert len(data) == 32
    return b58_encode(data)


def b58_decode32(s: str) -> bytes:
    return b58_decode(s, length=32)
