"""Shard router: ingress frags -> per-shard rings, deterministically.

The serving plane's host half: one stage consuming the ingress ring and
republishing every frag onto exactly one of N per-shard rings, so the
sharded step's lane assignment (ring i -> mesh device i, serve.py) is
decided HERE, once, by `seq % n_shards` — the reference's round-robin
verify-tile sharding (fd_verify.c:46) expressed as explicit links
instead of a shared-ring filter.  Explicit per-shard links buy what the
filter cannot: per-shard flow accounting (the frag-conservation
invariant is checkable from the shm metrics registries), downstream
consumption isolated per shard, and single-producer rings throughout
(fdlint FD101 stays green by construction).

The stage is credit-gated: because the assignment is by sequence (not
by whichever ring happens to have room — that would break determinism),
a full shard ring must stall ingress rather than skip or drop, so the
router never consumes a frag it cannot forward.  Credit-gating a pure
fan-out is deadlock-safe: no credit cycle runs through it (FD107's
criterion).
"""

from __future__ import annotations

from firedancer_tpu.tango.rings import MCache
from firedancer_tpu.runtime.stage import Stage
from firedancer_tpu.utils import metrics as fm


def shard_of(seq: int, n_shards: int) -> int:
    """THE frag->shard assignment, one place: deterministic in the frag's
    ingress sequence number, so a restarted router (or an auditor armed
    with the flight dump) reproduces the exact same routing."""
    return seq % n_shards


class ShardRouterStage(Stage):
    def __init__(self, *args, n_shards: int | None = None, **kwargs):
        super().__init__(*args, **kwargs)
        self.n_shards = n_shards if n_shards is not None else len(self.outs)
        if self.outs and len(self.outs) != self.n_shards:
            raise ValueError(
                f"router has {len(self.outs)} output rings for "
                f"{self.n_shards} shards (need exactly one per shard)"
            )
        self.require_credit = True  # never consume what we cannot forward
        # the ring sequence number of the frag being processed, captured
        # in before_frag: routing keys on the INGRESS seq (not a local
        # counter) so a restarted router resumes the exact assignment
        self._cur_seq = 0
        self.metrics = type(self.metrics)(
            self.metrics_schema_n(self.n_shards)
        )

    @classmethod
    def extra_schema(cls) -> fm.MetricsSchema:
        return fm.MetricsSchema().counter(
            "routed_total", "frags routed to any shard ring"
        )

    @classmethod
    def metrics_schema_n(cls, n_shards: int) -> fm.MetricsSchema:
        """Class schema + one routed counter per shard: the scrape-side
        half of the frag-conservation invariant (router routed_s{i} ==
        shard i's consumer frags_in, modulo in-flight)."""
        s = cls.metrics_schema()
        for i in range(n_shards):
            s.counter(f"routed_s{i}", f"frags routed to shard ring {i}")
        return s

    def before_frag(self, in_idx: int, seq: int, sig: int) -> bool:
        self._cur_seq = seq
        return True

    def after_frag(self, in_idx: int, meta, payload: bytes) -> None:
        shard = shard_of(self._cur_seq, self.n_shards)
        self.publish(
            shard,
            payload,
            sig=int(meta[MCache.COL_SIG]),
            tsorig=int(meta[MCache.COL_TSORIG]),
        )
        self.metrics.inc("routed_total")
        self.metrics.inc(self._shard_keys[shard])

    # per-shard counter names precomputed: the frag path must not format
    # strings per frag (the FD208 discipline, applied to inc() too)
    @property
    def _shard_keys(self) -> list[str]:
        keys = getattr(self, "_shard_keys_cache", None)
        if keys is None:
            keys = [f"routed_s{i}" for i in range(self.n_shards)]
            self._shard_keys_cache = keys
        return keys
