"""Mesh construction and sharded dispatch for multi-chip scale-out.

The data-parallel fan-out axis of the leader pipeline (the reference's
N-verify-tile round-robin, fd_verify.c:46) mapped onto a jax.sharding.Mesh
(mesh.py), and the SERVING plane that pushes real pipeline traffic through
it: the shard router (router.py) and the single-pjit-step serve plane +
stage (serve.py).

serve/router are imported lazily (not here): importing them pulls in the
runtime stage machinery, which pure mesh users (the dryrun, kernels-only
callers) must not pay for.
"""

from .mesh import (  # noqa: F401
    AXIS,
    batch_sharding,
    make_mesh,
    pad_to_multiple,
    shard_verify_args,
    sharded_verify,
)
