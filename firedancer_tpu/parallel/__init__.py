"""Mesh construction and sharded dispatch for multi-chip scale-out.

The data-parallel fan-out axis of the leader pipeline (the reference's
N-verify-tile round-robin, fd_verify.c:46) mapped onto a jax.sharding.Mesh;
see mesh.py.
"""

from .mesh import (  # noqa: F401
    AXIS,
    batch_sharding,
    make_mesh,
    pad_to_multiple,
    shard_verify_args,
    sharded_verify,
)
