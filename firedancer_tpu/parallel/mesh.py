"""Device-mesh construction and sharded kernel dispatch.

The reference scales sigverify by running N verify tiles that shard the
ingress stream round-robin by sequence number
(/root/reference/src/app/fdctl/run/tiles/fd_verify.c:46) — pure data
parallelism.  The TPU-native equivalent: a 1-D device mesh over the batch
axis, `jax.jit` + `NamedSharding` over it, and XLA inserting the ICI
collectives (the psum'd pass-count here stands in for the aggregated fseq
progress the reference's consumers publish).

Shapes are fixed per compile, so uneven loads are padded up to the mesh
divisor and pad lanes are masked out — same discipline the verify stage
already uses for partial device batches.
"""

from __future__ import annotations

import numpy as np

AXIS = "verify"


def make_mesh(n_devices: int | None = None, axis: str = AXIS):
    """1-D mesh over the first n_devices (default: all) local devices."""
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    if n_devices is None:
        n_devices = len(devs)
    if len(devs) < n_devices:
        raise ValueError(f"need {n_devices} devices, have {len(devs)}")
    return Mesh(np.array(devs[:n_devices]), (axis,))


def batch_sharding(mesh, axis: str = AXIS):
    """(rows_sharding, vec_sharding) for (rows, B) and (B,) arrays: shard the
    trailing batch axis across the mesh, replicate nothing else."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P(None, axis)), NamedSharding(mesh, P(axis))


def pad_to_multiple(n: int, k: int) -> int:
    """Smallest multiple of k that is >= max(n, 1)."""
    return -(-max(n, 1) // k) * k


def shard_verify_args(mesh, msg, msg_len, sig, pk, axis: str = AXIS):
    """Pad the batch up to the mesh size and device_put with batch sharding.

    Returns (args, n_real): args are committed sharded jax arrays; lanes at
    index >= n_real are zero pads whose results must be ignored.
    """
    import jax
    import jax.numpy as jnp

    n_dev = mesh.devices.size
    n_real = msg.shape[1]
    b = pad_to_multiple(n_real, n_dev)
    if b != n_real:
        pad = b - n_real
        msg = np.pad(np.asarray(msg), [(0, 0), (0, pad)])
        msg_len = np.pad(np.asarray(msg_len), [(0, pad)])
        sig = np.pad(np.asarray(sig), [(0, 0), (0, pad)])
        pk = np.pad(np.asarray(pk), [(0, 0), (0, pad)])
    rows_s, vec_s = batch_sharding(mesh, axis)
    args = (
        jax.device_put(jnp.asarray(msg), rows_s),
        jax.device_put(jnp.asarray(msg_len), vec_s),
        jax.device_put(jnp.asarray(sig), rows_s),
        jax.device_put(jnp.asarray(pk), rows_s),
    )
    return args, n_real


_sharded_step = None


def _get_sharded_step():
    """Module-level jitted step: n_real rides as a traced scalar so uneven
    fills of the same padded shape share ONE executable, and repeat calls
    hit jax.jit's cache instead of retracing a fresh closure."""
    global _sharded_step
    if _sharded_step is None:
        import functools

        import jax
        import jax.numpy as jnp

        from firedancer_tpu.ops import sigverify as sv

        @functools.partial(jax.jit, static_argnames=("max_msg_len",))
        def step(msg, msg_len, sig, pubkey, n_real, *, max_msg_len):
            ok = sv.ed25519_verify_batch(
                msg, msg_len, sig, pubkey, max_msg_len=max_msg_len
            )
            real = jnp.arange(ok.shape[0]) < n_real
            return ok, jnp.sum((ok & real).astype(jnp.int32))

        _sharded_step = step
    return _sharded_step


def sharded_verify(mesh, msg, msg_len, sig, pk, *, max_msg_len: int, axis: str = AXIS):
    """Batched sigverify sharded over `mesh`; returns (ok_mask, pass_count).

    ok_mask covers only the real (unpadded) lanes.  pass_count is computed
    on-device with a cross-shard sum (an ICI collective on real hardware)
    over real lanes only.
    """
    import jax.numpy as jnp

    args, n_real = shard_verify_args(mesh, msg, msg_len, sig, pk, axis)
    ok, total = _get_sharded_step()(
        *args, jnp.int32(n_real), max_msg_len=max_msg_len
    )
    return np.asarray(ok)[:n_real], int(total)


# -- the full leader compute step, sharded ------------------------------------

_leader_step = None


def _get_leader_step():
    """ONE jitted program covering every device-side op of the leader
    pipeline — sigverify (ingress), Reed-Solomon parity (shred), PoH
    segment verification (replay check) — each data-parallel over the
    mesh with a psum'd summary, the way the reference fans the same work
    across verify/shred tiles."""
    global _leader_step
    if _leader_step is None:
        import functools

        import jax
        import jax.numpy as jnp

        from firedancer_tpu.ops import reedsol as rs
        from firedancer_tpu.ops import sha256 as fsha
        from firedancer_tpu.ops import sigverify as sv

        @functools.partial(
            jax.jit, static_argnames=("max_msg_len", "poh_iters")
        )
        def step(
            msg, msg_len, sig, pubkey, rs_bits, shreds, poh_start, poh_end,
            n_real, *, max_msg_len, poh_iters,
        ):
            ok = sv.ed25519_verify_batch(
                msg, msg_len, sig, pubkey, max_msg_len=max_msg_len
            )
            real = jnp.arange(ok.shape[0]) < n_real
            n_ok = jnp.sum((ok & real).astype(jnp.int32))
            # RS parity for every FEC set in flight (sets sharded); the
            # layout lives in reedsol.encode_core, shared with encode()
            par = rs.encode_core(rs_bits, shreds)
            # PoH segments (chains sharded)
            got = fsha.sha256_iter32(poh_start, poh_iters)
            poh_ok = jnp.sum(jnp.all(got == poh_end, axis=0).astype(jnp.int32))
            return ok, n_ok, par, poh_ok

        _leader_step = step
    return _leader_step


def sharded_leader_step(
    mesh,
    msg, msg_len, sig, pk,
    fec_data, parity_cnt: int,
    poh_starts, poh_ends, poh_iters: int,
    *,
    max_msg_len: int,
    axis: str = AXIS,
):
    """Run the leader pipeline's device work in ONE sharded program.

    fec_data: (nsets, d, sz) uint8, nsets divisible by the mesh size;
    poh_starts/ends: (32, n_chains) byte rows, n_chains divisible too.
    Returns (ok_mask, n_ok, parity (nsets, p, sz), poh_ok_count).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from firedancer_tpu.ops import reedsol as rs

    args, n_real = shard_verify_args(mesh, msg, msg_len, sig, pk, axis)
    d = fec_data.shape[1]
    rs_bits = jax.device_put(
        rs._encode_bits(d, parity_cnt), NamedSharding(mesh, P(None, None))
    )
    sets_s = NamedSharding(mesh, P(axis, None, None))
    rows_s = NamedSharding(mesh, P(None, axis))
    fec = jax.device_put(jnp.asarray(fec_data, dtype=jnp.uint8), sets_s)
    p_start = jax.device_put(jnp.asarray(poh_starts, dtype=jnp.int32), rows_s)
    p_end = jax.device_put(jnp.asarray(poh_ends, dtype=jnp.int32), rows_s)
    ok, n_ok, par, poh_ok = _get_leader_step()(
        *args, rs_bits, fec, p_start, p_end, jnp.int32(n_real),
        max_msg_len=max_msg_len, poh_iters=poh_iters,
    )
    return np.asarray(ok)[:n_real], int(n_ok), np.asarray(par), int(poh_ok)
