"""Sharded serving plane: real leader-pipeline traffic over the device mesh.

`parallel/mesh.py` proved the sharded leader step compiles and reduces
correctly (the MULTICHIP dryruns); this module graduates it to SERVING:
a plane object that owns the mesh, the partition specs, and ONE compiled
pjit leader step, plus the stage that pushes live pipeline frags through
it.  The shape follows the pjit discipline of the SNIPPETS exemplars —
in_shardings and out_shardings pinned per hop and MATCHED across hops so
XLA never inserts a resharding collective between the verify, reedsol,
and PoH sections of the step:

  - verify inputs/outputs: batch axis sharded over the mesh, byte-row
    leading dims replicated (`P(None, axis)` rows / `P(axis)` lanes);
  - reedsol: FEC sets sharded over their leading axis
    (`P(axis, None, None)`), the bit-generator matrix replicated;
  - PoH: hash chains sharded over the lane axis (`P(None, axis)`);
  - scalar summaries (`n_ok`) come back replicated — the psum is the
    only cross-shard collective in the program, by construction.

Lane geometry is FIXED per compile (the verify-stage padding discipline):
each shard owns a contiguous `batch_per_shard` lane range, uneven final
fills are padded and masked ON DEVICE from the replicated per-shard real
counts, and the frag->shard assignment is deterministic (the router's
`seq % n_shards`, carried by which per-shard ring a frag arrived on).

Cold-start is a production concern (a leader that compiles for 2 minutes
misses its slot — MULTICHIP_r05's 2m15s jit_step): the plane supports
AOT warmup (`warmup()` lowers+compiles before traffic arrives) and the
repo-local persistent compilation cache (utils/platform.enable_serve_cache)
so a warmed host's next process boots the step from cache in seconds.
"""

from __future__ import annotations

import os
import sys
import time
from dataclasses import dataclass

import numpy as np

from .mesh import AXIS, make_mesh, pad_to_multiple


@dataclass(frozen=True)
class ServeConfig:
    """Static geometry of the serving step (one compile per config).

    The verify lanes carry the txn batch; the reedsol and PoH lanes are
    sized small by default — they carry the shredder's parity work and
    the PoH self-audit spans when those stages ride the plane, and cost
    placeholder compute when idle, so default shapes are the smallest
    useful ones.
    """

    n_devices: int
    batch_per_shard: int = 128  # verify elements per shard
    max_msg_len: int = 256
    fec_sets_per_shard: int = 1  # RS sets per shard per step
    fec_data_shreds: int = 32  # d (the normal-FEC-set shape)
    fec_parity_shreds: int = 32  # p = parity_cnt_for(32)
    fec_shred_sz: int = 1024  # per-shred byte capacity (sz-padded)
    poh_chains_per_shard: int = 1
    poh_iters: int = 64  # pure-append span length (hashes_per_tick)
    axis: str = AXIS

    @property
    def batch(self) -> int:
        return self.batch_per_shard * self.n_devices

    @property
    def fec_sets(self) -> int:
        return self.fec_sets_per_shard * self.n_devices

    @property
    def poh_chains(self) -> int:
        return self.poh_chains_per_shard * self.n_devices

    def cache_key(self) -> str:
        return (
            f"d{self.n_devices}_b{self.batch_per_shard}_m{self.max_msg_len}"
            f"_f{self.fec_sets_per_shard}x{self.fec_data_shreds}"
            f"p{self.fec_parity_shreds}s{self.fec_shred_sz}"
            f"_h{self.poh_chains_per_shard}i{self.poh_iters}"
        )


def lane_real_mask(lane_count: int, per_shard: int, n_real):
    """THE pad-lane mask, one place: lane j belongs to shard j//per and is
    real iff its intra-shard index is below that shard's fill.  Jittable
    (n_real a traced (n_devices,) int vector) — the serving step and the
    test-facing mask probe both call exactly this."""
    import jax.numpy as jnp

    lane = jnp.arange(lane_count, dtype=jnp.int32)
    return (lane % per_shard) < n_real[lane // per_shard]


@dataclass
class Pending:
    """One serving step in flight: device futures + the real-lane counts."""

    ok: object  # (batch,) bool, pad lanes already masked false on device
    n_ok: object  # scalar int32 (the psum)
    parity: object  # (fec_sets, p, sz) uint8
    poh_ok: object  # (poh_chains,) bool
    n_real: np.ndarray  # (n_devices,) verify fill per shard
    fec_real: int
    poh_real: int

    def ready(self) -> bool:
        return getattr(self.ok, "is_ready", lambda: True)()


class ServePlane:
    """The mesh + the one compiled serving step + its sharded arg plumbing."""

    def __init__(self, cfg: ServeConfig):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        self.cfg = cfg
        self.mesh = make_mesh(cfg.n_devices, cfg.axis)
        ax = cfg.axis
        ns = lambda *spec: NamedSharding(self.mesh, P(*spec))  # noqa: E731
        # one spec per hop, matched on the batch axis so the program has
        # no resharding between its verify/reedsol/PoH sections
        self.s_rows = ns(None, ax)  # (rows, batch) byte rows
        self.s_vec = ns(ax)  # (batch,) lanes
        self.s_sets = ns(ax, None, None)  # (fec_sets, d, sz)
        self.s_repl = ns()  # replicated (rs bits, counts)
        self._step = None  # compiled/jitted step
        self._aot = None  # AOT-compiled executable (warmup path)
        self._placeholder = None  # device-resident zero fec/poh args
        self.compile_s: float | None = None  # measured by warmup()
        # rider queue: PoH spans other stages park for the next step call
        self._poh_spans: list[tuple[bytes, bytes]] = []
        self._jax = jax

    # -- the single program -------------------------------------------------

    def _build_step(self):
        import functools

        import jax
        import jax.numpy as jnp

        from firedancer_tpu.ops import reedsol as rs
        from firedancer_tpu.ops import sha256 as fsha
        from firedancer_tpu.ops import sigverify as sv

        cfg = self.cfg
        per = cfg.batch_per_shard
        per_poh = cfg.poh_chains_per_shard

        @functools.partial(
            jax.jit,
            in_shardings=(
                self.s_rows, self.s_vec, self.s_rows, self.s_rows,  # verify
                self.s_repl,  # n_real (n_dev,)
                self.s_repl, self.s_sets, self.s_repl,  # rs bits, fec, fec_real
                self.s_rows, self.s_rows, self.s_repl,  # poh start/end, poh_real
            ),
            out_shardings=(self.s_vec, self.s_repl, self.s_sets, self.s_vec),
        )
        def step(msg, msg_len, sig, pk, n_real,
                 rs_bits, fec, fec_real, poh_start, poh_end, poh_real):
            ok = sv.ed25519_verify_batch(
                msg, msg_len, sig, pk, max_msg_len=cfg.max_msg_len
            )
            # pad-lane masking from the replicated per-shard fills —
            # computed on device so the psum'd count never sees a pad lane
            ok = ok & lane_real_mask(ok.shape[0], per, n_real)
            n_ok = jnp.sum(ok.astype(jnp.int32))
            par = rs.encode_core(rs_bits, fec)
            got = fsha.sha256_iter32(poh_start, cfg.poh_iters)
            poh_ok = jnp.all(got == poh_end, axis=0) & lane_real_mask(
                got.shape[1], per_poh, poh_real
            )
            del fec_real  # parity of zero-padded sets is zero: no mask needed
            return ok, n_ok, par, poh_ok

        return step

    def _get_step(self):
        if self._step is None:
            self._step = self._build_step()
        return self._step

    def _abstract_args(self):
        import jax
        import jax.numpy as jnp

        cfg = self.cfg
        S = lambda shape, dt: jax.ShapeDtypeStruct(shape, dt)  # noqa: E731
        return (
            S((cfg.max_msg_len, cfg.batch), jnp.uint8),
            S((cfg.batch,), jnp.int32),
            S((64, cfg.batch), jnp.uint8),
            S((32, cfg.batch), jnp.uint8),
            S((cfg.n_devices,), jnp.int32),
            # the bit-block generator matrix is int8 (gf_matrix_to_bits)
            S((8 * cfg.fec_parity_shreds, 8 * cfg.fec_data_shreds), jnp.int8),
            S((cfg.fec_sets, cfg.fec_data_shreds, cfg.fec_shred_sz), jnp.uint8),
            S((cfg.n_devices,), jnp.int32),
            S((32, cfg.poh_chains), jnp.int32),
            S((32, cfg.poh_chains), jnp.int32),
            S((cfg.n_devices,), jnp.int32),
        )

    def _sharding_tuples(self):
        in_sh = (
            self.s_rows, self.s_vec, self.s_rows, self.s_rows, self.s_repl,
            self.s_repl, self.s_sets, self.s_repl,
            self.s_rows, self.s_rows, self.s_repl,
        )
        out_sh = (self.s_vec, self.s_repl, self.s_sets, self.s_vec)
        return in_sh, out_sh

    def _mesh_platform(self) -> str:
        """The platform the step actually runs on (the plane's OWN mesh,
        not the process default — a CPU dryrun next to a TPU mesh must
        not pick the TPU lane)."""
        return self.mesh.devices.flat[0].platform

    def _use_serialized_executable(self) -> bool:
        """Warm-boot lane choice: serialize_executable on accelerator
        backends (deserialization is seconds — the 10 s warm_cold_start
        budget's path), jax.export + persistent cache on CPU where the
        executable round trip is known to fail (utils/platform
        .serialize_executable_ok)."""
        from firedancer_tpu.utils.platform import serialize_executable_ok

        return serialize_executable_ok(self._mesh_platform())

    def _exec_blob_path(self, cache_dir: str | None) -> str | None:
        if not cache_dir:
            return None
        return os.path.join(
            cache_dir,
            f"serve_step_{self.cfg.cache_key()}_{self._mesh_platform()}.xc",
        )

    def warmup(self) -> float:
        """AOT-compile the serving step before any traffic exists (the
        leader's boot-time obligation).  Returns seconds.

        Two warm-boot lanes, selected by backend
        (_use_serialized_executable):

          - accelerators: the COMPILED executable serializes
            (jax.experimental.serialize_executable) next to the cache as
            `serve_step_<key>_<platform>.xc`; a warm boot is pure
            deserialization — no trace, no XLA, no codegen — which is
            what fits the 10 s warm_cold_start budget;
          - CPU (the executable round trip fails there: "Symbols not
            found"): the jax.export lane below — the Python trace/lower
            (~20s on one core) is skipped by reloading the serialized
            StableHLO export (`serve_step_<key>.hlo`), and the XLA
            optimization pipeline by the persistent compilation cache.
            What remains is LLVM rehydration (~26s on one core).

        Measured ladder on this host class: ~175s cold / ~27s warm via
        the export lane."""
        import jax

        t0 = time.monotonic()
        cache_dir = jax.config.jax_compilation_cache_dir
        if self._use_serialized_executable():
            if self._warmup_serialized(cache_dir):
                self.compile_s = time.monotonic() - t0
                return self.compile_s
        self._warmup_export(cache_dir)
        self.compile_s = time.monotonic() - t0
        return self.compile_s

    def _warmup_serialized(self, cache_dir: str | None) -> bool:
        """The accelerator lane: load the serialized executable if one
        exists, else compile through the export lane and serialize the
        result for the next boot.  Returns False only when the blob
        machinery is unusable (no cache dir and nothing to gain)."""
        import pickle

        from jax.experimental import serialize_executable as se

        blob = self._exec_blob_path(cache_dir)
        if blob is None:
            return False
        if os.path.exists(blob):
            try:
                with open(blob, "rb") as f:
                    payload, in_tree, out_tree = pickle.load(f)
                self._aot = se.deserialize_and_load(payload, in_tree,
                                                    out_tree)
                return True
            except Exception as e:
                # a stale/incompatible blob (jaxlib upgrade, runtime
                # change) must cost ONE slow recompile, not the boot:
                # drop it and fall through to the export lane, which
                # rewrites a fresh blob below
                print(f"# warm-boot blob unusable ({type(e).__name__}: "
                      f"{e}); recompiling", file=sys.stderr)
                try:
                    os.remove(blob)
                except OSError:
                    pass
        self._warmup_export(cache_dir)
        payload, in_tree, out_tree = se.serialize(self._aot)
        tmp = f"{blob}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            pickle.dump((payload, in_tree, out_tree), f)
        os.replace(tmp, blob)
        return True

    def _warmup_export(self, cache_dir: str | None) -> None:
        """The CPU-safe lane: serialized StableHLO export (skips
        re-trace) + persistent compilation cache (skips
        re-optimization)."""
        import jax
        import jax.export

        blob = None
        if cache_dir:
            blob = os.path.join(
                cache_dir, f"serve_step_{self.cfg.cache_key()}.hlo"
            )
        exp = None
        if blob is not None and os.path.exists(blob):
            with open(blob, "rb") as f:
                exp = jax.export.deserialize(f.read())
        if exp is None:
            exp = jax.export.export(self._get_step())(*self._abstract_args())
            if blob is not None:
                os.makedirs(cache_dir, exist_ok=True)
                tmp = f"{blob}.tmp.{os.getpid()}"
                with open(tmp, "wb") as f:
                    f.write(exp.serialize())
                os.replace(tmp, blob)
        in_sh, out_sh = self._sharding_tuples()
        self._aot = jax.jit(
            exp.call, in_shardings=in_sh, out_shardings=out_sh
        ).lower(*self._abstract_args()).compile()

    # -- sharded argument plumbing -------------------------------------------

    def _placeholders(self):
        """Device-resident zero fec/poh args, built once: a verify-only
        step call must not pay a host->device transfer for lanes that
        carry no work."""
        if self._placeholder is None:
            import jax
            import jax.numpy as jnp

            from firedancer_tpu.ops import reedsol as rs

            cfg = self.cfg
            dp = jax.device_put
            self._rs_bits = dp(
                rs._encode_bits(cfg.fec_data_shreds, cfg.fec_parity_shreds),
                self.s_repl,
            )
            self._placeholder = (
                dp(jnp.zeros((cfg.fec_sets, cfg.fec_data_shreds,
                              cfg.fec_shred_sz), jnp.uint8), self.s_sets),
                dp(jnp.zeros((32, cfg.poh_chains), jnp.int32), self.s_rows),
                dp(jnp.zeros((32, cfg.poh_chains), jnp.int32), self.s_rows),
            )
            self._zero_real = dp(
                jnp.zeros((cfg.n_devices,), jnp.int32), self.s_repl
            )
        return self._placeholder

    def place_verify(self, msg, msg_len, sig, pk):
        """Commit pre-padded (rows, batch) verify arrays to the mesh with
        the step's OWN input shardings (pre-partitioned, per the pjit
        exemplar note: matching placement skips the implicit reshard)."""
        import jax
        import jax.numpy as jnp

        dp = jax.device_put
        return (
            dp(jnp.asarray(msg), self.s_rows),
            dp(jnp.asarray(msg_len), self.s_vec),
            dp(jnp.asarray(sig), self.s_rows),
            dp(jnp.asarray(pk), self.s_rows),
        )

    # -- rider queues (shredder / poh park work for the next step) ----------

    def queue_poh_span(self, start: bytes, end: bytes) -> bool:
        """Park one pure-append PoH span (exactly cfg.poh_iters hashes)
        for device re-verification on the next serving step.  Bounded:
        drops (returns False) when a slot's worth is already pending."""
        if len(self._poh_spans) >= 4 * self.cfg.poh_chains:
            return False
        self._poh_spans.append((start, end))
        return True

    def _take_poh(self):
        import jax
        import jax.numpy as jnp

        cfg = self.cfg
        if not self._poh_spans:
            ph = self._placeholders()
            return ph[1], ph[2], self._zero_real, 0
        take = self._poh_spans[: cfg.poh_chains]
        del self._poh_spans[: len(take)]
        starts = np.zeros((32, cfg.poh_chains), dtype=np.int32)
        ends = np.zeros((32, cfg.poh_chains), dtype=np.int32)
        for i, (s, e) in enumerate(take):
            starts[:, i] = np.frombuffer(s, dtype=np.uint8)
            ends[:, i] = np.frombuffer(e, dtype=np.uint8)
        per = cfg.poh_chains_per_shard
        real = np.asarray(
            [min(max(len(take) - d * per, 0), per)
             for d in range(cfg.n_devices)], dtype=np.int32
        )
        dp = jax.device_put
        return (
            dp(jnp.asarray(starts), self.s_rows),
            dp(jnp.asarray(ends), self.s_rows),
            dp(jnp.asarray(real), self.s_repl),
            len(take),
        )

    # -- dispatch ------------------------------------------------------------

    def submit(self, msg, msg_len, sig, pk, n_real_per_shard,
               riders: bool = True) -> Pending:
        """One serving step over pre-padded verify arrays (+ any parked
        PoH spans when riders=True).  Returns futures; pad lanes are
        already masked.  riders=False leaves the span queue alone — for
        callers that return only the verify mask and would otherwise
        consume the self-audit results without reporting them."""
        import jax
        import jax.numpy as jnp

        self._placeholders()
        fec, _, _ = self._placeholder
        if riders:
            p_start, p_end, p_real, n_poh = self._take_poh()
        else:
            ph = self._placeholder
            p_start, p_end, p_real, n_poh = ph[1], ph[2], self._zero_real, 0
        args = self.place_verify(msg, msg_len, sig, pk)
        n_real = np.asarray(n_real_per_shard, dtype=np.int32)
        fn = self._aot if self._aot is not None else self._get_step()
        ok, n_ok, par, poh_ok = fn(
            *args, jax.device_put(jnp.asarray(n_real), self.s_repl),
            self._rs_bits, fec, self._zero_real,
            p_start, p_end, p_real,
        )
        return Pending(ok, n_ok, par, poh_ok, n_real, 0, n_poh)

    def verify_batch(self, msg, msg_len, sig, pk):
        """Synchronous whole-batch verify through the serving step —
        drop-in for ops.sigverify.ed25519_verify_batch at the plane's
        exact batch shape (the VerifyStage plane hook).  Returns the
        (batch,) ok mask as a device array."""
        b = self.cfg.batch
        if msg.shape[1] != b:
            raise ValueError(
                f"plane step is compiled for batch {b}, got {msg.shape[1]}"
            )
        per = self.cfg.batch_per_shard
        full = np.full((self.cfg.n_devices,), per, dtype=np.int32)
        # riders=False: this caller returns only the mask, so consuming
        # parked PoH spans here would silently drop their audit results
        return self.submit(msg, msg_len, sig, pk, full, riders=False).ok

    def encode_parity(self, data: np.ndarray, parity_cnt: int) -> np.ndarray:
        """Sharded Reed-Solomon parity for (nsets, d, sz) FEC sets: sets
        padded up to the mesh divisor, sz zero-padded up to the compiled
        width (parity of a zero-padded column is zero — the GF(2^8) code
        is linear per byte column), dispatched with the step's matched
        set shardings.  Shapes outside the plane's compiled (d, p) fall
        back to the unsharded encoder."""
        import jax
        import jax.numpy as jnp

        from firedancer_tpu.ops import reedsol as rs

        cfg = self.cfg
        nsets, d, sz = data.shape
        if (d != cfg.fec_data_shreds or parity_cnt != cfg.fec_parity_shreds
                or sz > cfg.fec_shred_sz):
            # off-shape tails keep the shredder's HOST lane (parity-
            # identical, no device dispatch mid-slot for a fresh shape)
            return np.asarray(rs.encode_host(np.asarray(data), parity_cnt))
        pad_sets = pad_to_multiple(nsets, cfg.n_devices)
        buf = np.zeros((pad_sets, d, cfg.fec_shred_sz), dtype=np.uint8)
        buf[:nsets, :, :sz] = data
        fec = jax.device_put(jnp.asarray(buf), self.s_sets)
        # the sharded path only fires at the compiled (d, p), whose bit
        # matrix _placeholders() already committed once — reuse it
        self._placeholders()
        par = self._sharded_rs()(self._rs_bits, fec)
        return np.asarray(par)[:nsets, :, :sz]

    def _sharded_rs(self):
        """RS-only sharded program (the shredder's synchronous path): the
        same encode_core + set shardings as the serving step, compiled
        once per plane."""
        if getattr(self, "_rs_step", None) is None:
            import jax

            from firedancer_tpu.ops import reedsol as rs

            self._rs_step = jax.jit(
                rs.encode_core,
                in_shardings=(self.s_repl, self.s_sets),
                out_shardings=self.s_sets,
            )
        return self._rs_step

    def verify_poh_segments(self, starts, ends, iters: int) -> np.ndarray:
        """Sharded equal-length PoH segment verification: (32, n) int32
        start/end byte rows, n padded to the mesh divisor and pad chains
        masked.  Off-shape iter counts fall back to the host verifier's
        device path (runtime/poh.verify_segments_tpu)."""
        import jax
        import jax.numpy as jnp

        cfg = self.cfg
        if iters != cfg.poh_iters:
            from firedancer_tpu.runtime import poh as rpoh

            s = [bytes(np.asarray(starts[:, i], dtype=np.uint8))
                 for i in range(starts.shape[1])]
            e = [bytes(np.asarray(ends[:, i], dtype=np.uint8))
                 for i in range(ends.shape[1])]
            return np.asarray(rpoh.verify_segments_tpu(s, iters, e))
        n = starts.shape[1]
        pad = pad_to_multiple(n, cfg.n_devices)
        sb = np.zeros((32, pad), dtype=np.int32)
        eb = np.zeros((32, pad), dtype=np.int32)
        sb[:, :n] = starts
        eb[:, :n] = ends
        got = self._sharded_poh()(
            jax.device_put(jnp.asarray(sb), self.s_rows)
        )
        return np.asarray((np.asarray(got) == eb).all(axis=0))[:n]

    def real_mask(self, n_real_per_shard) -> np.ndarray:
        """The step's pad-lane mask, ON DEVICE with the step's own lane
        sharding — the cheap probe tier-1 uses to pin the masking logic
        without paying the verify kernel's compile."""
        import functools

        import jax
        import jax.numpy as jnp

        if getattr(self, "_mask_step", None) is None:
            self._mask_step = jax.jit(
                functools.partial(
                    lane_real_mask, self.cfg.batch, self.cfg.batch_per_shard
                ),
                in_shardings=(self.s_repl,),
                out_shardings=self.s_vec,
            )
        n_real = jnp.asarray(np.asarray(n_real_per_shard, dtype=np.int32))
        return np.asarray(
            self._mask_step(jax.device_put(n_real, self.s_repl))
        )

    def _sharded_poh(self):
        if getattr(self, "_poh_step", None) is None:
            import functools

            import jax

            from firedancer_tpu.ops import sha256 as fsha

            self._poh_step = jax.jit(
                functools.partial(fsha.sha256_iter32, n=self.cfg.poh_iters),
                in_shardings=(self.s_rows,),
                out_shardings=self.s_rows,
            )
        return self._poh_step


# -- the serving stage ---------------------------------------------------------


from firedancer_tpu.runtime.verify import (  # noqa: E402
    MCACHE_COL_TSORIG,
    VerifyStage,
    _Acc,
    _Pending as _VPending,
    sig_tag,
)
from firedancer_tpu.utils import metrics as fmet  # noqa: E402


class ShardedVerifyStage(VerifyStage):
    """The serving plane's pipeline position: ONE stage consuming the
    router's per-shard rings and dispatching ONE sharded step per batch.

    Each input ring IS a shard: frags that arrived on ring i fill shard
    i's contiguous lane range of the fixed-shape batch, so the router's
    deterministic `seq % n_shards` assignment carries through to device
    placement (ring i -> mesh device i) with no host-side reshuffle.

    The batch closes when any shard's lane range fills or the deadline
    passes (the VerifyStage deadline-close discipline); uneven fills pad
    and the step masks pad lanes on device from the per-shard counts.
    """

    def __init__(self, *args, plane: ServePlane, **kwargs):
        cfg = plane.cfg
        kwargs.setdefault("batch", cfg.batch_per_shard)
        kwargs["max_msg_len"] = cfg.max_msg_len
        kwargs["comb_slots"] = 0  # the plane step IS the kernel choice
        super().__init__(*args, **kwargs)
        self.plane = plane
        if self.batch != cfg.batch_per_shard:
            raise ValueError("stage batch must equal plane batch_per_shard")
        self.n_shards = cfg.n_devices
        # one accumulator per shard (per input ring); VerifyStage's _gen
        # acc is unused on this subclass
        self._shards = [_Acc() for _ in range(self.n_shards)]
        self.metrics = type(self.metrics)(self.metrics_schema_n(self.n_shards))

    # -- observability ------------------------------------------------------

    @classmethod
    def extra_schema(cls) -> fmet.MetricsSchema:
        s = VerifyStage.extra_schema()
        s.counter("poh_spans_ok", "PoH self-audit spans verified on-mesh")
        s.counter("poh_spans_fail", "PoH self-audit spans that FAILED")
        return s

    @classmethod
    def metrics_schema_n(cls, n_shards: int) -> fmet.MetricsSchema:
        """The class schema + per-shard element counters (the per-shard
        metrics the scrape surface labels by shard)."""
        s = cls.metrics_schema()
        for i in range(n_shards):
            s.counter(f"shard_elems_s{i}",
                      f"signature elements dispatched on shard {i}")
        return s

    # -- mux callbacks -------------------------------------------------------

    # this subclass accumulates per SHARD in after_frag below; the base
    # class's drain-table batch intake would route through the wrong
    # accumulator — keep the per-frag path
    sweep_frags = None

    def before_frag(self, in_idx: int, seq: int, sig: int) -> bool:
        return True  # the router already sharded; never re-filter

    def after_frag(self, in_idx: int, meta, payload: bytes) -> None:
        # the intake rules (parse incl. the packed-offset fast path,
        # dedup tag, length + fit guards) are VerifyStage._intake — one
        # implementation across both verify lanes
        got = self._intake(payload)
        if got is None:
            return
        sigs, msg, signers, t, packed = got
        acc = self._shards[in_idx]
        if acc.elems and len(acc.elems) + len(sigs) > self.batch:
            # this shard's lane range is full: close the WHOLE step (the
            # fixed shape ships every shard's partial fill, masked)
            self._close_batch()
            acc = self._shards[in_idx]
        start = len(acc.elems)
        for s, pk in zip(sigs, signers):
            acc.elems.append((msg, s, pk))
        acc.ranges.append((start, len(acc.elems)))
        acc.payloads.append(payload)
        acc.descs.append((t, packed))
        acc.tsorigs.append(int(meta[MCACHE_COL_TSORIG]))
        if len(acc.elems) >= self.batch:
            self._close_batch()

    def before_credit(self) -> None:
        for acc in self._shards:
            if acc.elems and acc.opened_at == 0.0:
                acc.opened_at = time.monotonic()

    def after_credit(self) -> None:
        now = time.monotonic()
        if any(
            acc.elems and acc.opened_at
            and now - acc.opened_at >= self.batch_deadline_s
            for acc in self._shards
        ):
            self._close_batch()
        self._drain(block=False)

    def during_housekeeping(self) -> None:
        self._drain(block=False)

    # -- the sharded dispatch ------------------------------------------------

    def _close_batch(self, acc=None) -> None:
        accs = self._shards
        n_elems = sum(len(a.elems) for a in accs)
        if n_elems == 0:
            return
        if len(self._inflight) >= self.max_inflight:
            self._drain(block=True)
        cfg = self.plane.cfg
        per = cfg.batch_per_shard
        b = cfg.batch
        mm = cfg.max_msg_len
        msg = np.zeros((mm, b), dtype=np.uint8)
        ln = np.zeros((b,), dtype=np.int32)
        sg = np.zeros((64, b), dtype=np.uint8)
        pk = np.zeros((32, b), dtype=np.uint8)
        n_real = np.zeros((self.n_shards,), dtype=np.int32)
        payloads, descs, ranges, tsorigs = [], [], [], []
        for s, acc in enumerate(accs):
            base = s * per
            n_real[s] = len(acc.elems)
            for j, (m, sig_b, pk_b) in enumerate(acc.elems):
                col = base + j
                mrow = np.frombuffer(m, dtype=np.uint8)
                msg[: len(mrow), col] = mrow
                ln[col] = len(mrow)
                sg[:, col] = np.frombuffer(sig_b, dtype=np.uint8)
                pk[:, col] = np.frombuffer(pk_b, dtype=np.uint8)
            payloads.extend(acc.payloads)
            descs.extend(acc.descs)
            ranges.extend((a + base, bb + base) for a, bb in acc.ranges)
            tsorigs.extend(acc.tsorigs)
            self.metrics.inc(f"shard_elems_s{s}", len(acc.elems))
            acc.clear()
        if self.precomputed_ok:
            result = _PrecomputedPending(b)
        else:
            result = self.plane.submit(msg, ln, sg, pk, n_real)
        self._inflight.append(
            _VPending(
                payloads=payloads,
                descs=descs,
                elem_ranges=ranges,
                tsorigs=tsorigs,
                n_elems=n_elems,
                result=result,
            )
        )
        self.metrics.inc("batches", 1)
        self.metrics.inc("batch_elems", n_elems)
        self.metrics.observe("batch_fill", n_elems)
        self.trace(fmet.EV_BATCH_SUBMIT, n_elems)

    # the drain loop itself is VerifyStage._drain (ONE implementation of
    # the txn-level pass-iff-all-pass rule); these hooks adapt it to the
    # Pending the serving step returns

    def _result_ready(self, head) -> bool:
        return head.result.ready()

    def _result_mask(self, head):
        pend: Pending = head.result
        if pend.poh_real:
            # the PoH self-audit spans that rode this step: account for
            # them exactly once, when the step's results are consumed
            n_ok = int(np.asarray(pend.poh_ok).sum())
            self.metrics.inc("poh_spans_ok", n_ok)
            self.metrics.inc("poh_spans_fail", pend.poh_real - n_ok)
            pend.poh_real = 0
        return np.asarray(pend.ok)

    def flush(self) -> None:
        self._close_batch()
        while self._inflight:
            self._drain(block=True)


class _PrecomputedPending(Pending):
    """Bench instrument: the all-pass mask with no device dispatch (the
    VerifyStage precomputed_ok analog for the sharded stage)."""

    def __init__(self, batch: int):
        super().__init__(
            ok=np.ones((batch,), dtype=bool), n_ok=batch,
            parity=None, poh_ok=None,
            n_real=np.zeros(0, dtype=np.int32), fec_real=0, poh_real=0,
        )

    def ready(self) -> bool:
        return True
