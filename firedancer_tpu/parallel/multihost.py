"""Multi-host distributed runtime: DCN-spanning meshes.

The multi-host half of SURVEY §5.8: the reference scales across
machines with its own wire protocols (gossip/turbine/repair over UDP);
the TPU-native equivalent for the *compute* plane is jax.distributed —
every host runs this same program, `initialize()` wires the hosts into
one runtime, and meshes span all chips with XLA routing collectives
over ICI within a pod slice and DCN between slices.

Environment contract (the standard jax.distributed one):

    coordinator   host:port of process 0
    num_processes total host processes
    process_id    this host's rank

On a single host this degenerates to the local device set — the same
code path the tests and the dryrun exercise; nothing about the mesh
construction changes, which is the point: stages written against
`global_mesh()` are multi-host-ready by construction.

Axis convention (matches parallel/mesh.py): "verify" is the
data-parallel fan-out axis for the sigverify pipeline; "host" is the
outer axis when a host-sharded ingress wants host-local batches.
"""

from __future__ import annotations

import os
from dataclasses import dataclass


@dataclass
class HostTopology:
    num_hosts: int
    host_id: int
    local_devices: int
    global_devices: int


def initialize(
    *,
    coordinator: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> HostTopology:
    """Join (or degenerate to) the multi-host runtime.

    Args default from JAX_COORDINATOR / JAX_NUM_PROCESSES / JAX_PROCESS_ID
    env vars; with none set this is a single-host no-op that still
    returns an accurate topology — callers never branch."""
    import jax

    coordinator = coordinator or os.environ.get("JAX_COORDINATOR")
    num_processes = num_processes or int(
        os.environ.get("JAX_NUM_PROCESSES", "0")
    )
    process_id = (
        process_id
        if process_id is not None
        else int(os.environ.get("JAX_PROCESS_ID", "0"))
    )
    if coordinator and num_processes > 1:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
    return HostTopology(
        num_hosts=max(1, num_processes),
        host_id=process_id,
        local_devices=jax.local_device_count(),
        global_devices=jax.device_count(),
    )


def global_mesh(axis: str = "verify"):
    """One flat mesh over every device in the (possibly multi-host)
    runtime; the verify fan-out shape."""
    import jax
    from jax.sharding import Mesh

    return Mesh(jax.devices(), (axis,))


def host_tiled_mesh(inner_axis: str = "verify"):
    """(host, inner) mesh: the outer axis crosses DCN, the inner axis
    rides ICI — shard batch by host at ingress, fan out within the
    slice, and the only cross-host traffic is the final reduction."""
    import numpy as np

    import jax
    from jax.sharding import Mesh

    devs = np.array(jax.devices())
    n_local = max(1, jax.local_device_count())
    n_hosts = max(1, len(devs) // n_local)
    grid = devs.reshape(n_hosts, n_local)
    return Mesh(grid, ("host", inner_axis))


def shard_counts(topology: HostTopology, batch: int) -> list[int]:
    """Per-host batch split, remainder to the low ranks (deterministic
    on every host: each computes the same answer from the topology)."""
    base = batch // topology.num_hosts
    rem = batch % topology.num_hosts
    return [base + (1 if h < rem else 0) for h in range(topology.num_hosts)]
