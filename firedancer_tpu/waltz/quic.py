"""QUIC v1 engine: packet protection + frames + connection machine.

Counterpart of /root/reference/src/waltz/quic/fd_quic.c (22.5k lines of
C) reduced to the profile the TPU ingress actually uses
(fd_quic.h:1-60): server accepts connections, client opens them; one
TLS handshake (waltz/tls13.py) rides CRYPTO frames across the initial/
handshake levels; application data arrives on unidirectional client
streams and feeds the TPU reassembler (runtime/tpu_reasm.py).  Like the
reference: single-threaded, fully in-memory, no dynamic allocation
after setup in the hot path.  The wire format is the real RFC 9000/9001
one:

  - Initial secrets from the client DCID with the v1 salt (§5.2)
  - AES-128-GCM packet protection, nonce = iv XOR packet-number
  - AES-ECB header protection over a 16-byte sample (§5.4)
  - long (Initial/Handshake) + short (1-RTT) headers, varint framing
  - packet-number reconstruction against largest received (§A.3)
  - CRYPTO / STREAM / multi-range ACK / flow-control / PING / PADDING /
    CONNECTION_CLOSE / HANDSHAKE_DONE frames

Reliability (the r3 gap; reference: fd_quic.c ack trees + loss recovery
around fd_quic.c:2147): every ack-eliciting packet is tracked per level
with its retransmittable frames; ACKs carry the full received-range set;
packets ≥3 below the largest acked are declared lost and their CRYPTO/
STREAM data re-queued; a PTO timer (exponential backoff) retransmits
when acks stop arriving.  Flow control: MAX_DATA / MAX_STREAM_DATA
windows enforced inbound and respected outbound (excess stream writes
queue until the peer opens the window).
"""

from __future__ import annotations

import os
import struct
import time as _time
from dataclasses import dataclass, field

from firedancer_tpu.ops.aes import Aes, AesGcm
from firedancer_tpu.waltz import tls13
from firedancer_tpu.waltz.tls13 import (
    APPLICATION,
    HANDSHAKE,
    INITIAL,
    hkdf_expand_label,
    hkdf_extract,
)

QUIC_V1 = 1
INITIAL_SALT_V1 = bytes.fromhex("38762cf7f55934b34d179ae6a4c80cadccbb7f0a")

FT_PADDING = 0x00
FT_PING = 0x01
FT_ACK = 0x02
FT_RESET_STREAM = 0x04
FT_STOP_SENDING = 0x05
FT_CRYPTO = 0x06
FT_STREAM_BASE = 0x08  # 0x08..0x0f: OFF/LEN/FIN bits
FT_MAX_DATA = 0x10
FT_MAX_STREAM_DATA = 0x11
FT_MAX_STREAMS_BIDI = 0x12
FT_MAX_STREAMS_UNI = 0x13
FT_DATA_BLOCKED = 0x14
FT_STREAM_DATA_BLOCKED = 0x15
FT_STREAMS_BLOCKED_BIDI = 0x16
FT_STREAMS_BLOCKED_UNI = 0x17
FT_NEW_CONNECTION_ID = 0x18
FT_RETIRE_CONNECTION_ID = 0x19
FT_PATH_CHALLENGE = 0x1A
FT_PATH_RESPONSE = 0x1B
FT_CONN_CLOSE = 0x1C
FT_HANDSHAKE_DONE = 0x1E

LONG_INITIAL = 0
LONG_HANDSHAKE = 2
LONG_RETRY = 3

# Retry Integrity Tag key/nonce for v1 (RFC 9001 §5.8 protocol constants)
RETRY_KEY_V1 = bytes.fromhex("be0c690b9f66575a1d766b54e368c84e")
RETRY_NONCE_V1 = bytes.fromhex("461599d35d632bf2239825bb")

MAX_DATAGRAM = 1452
MAX_FRAMES_PAYLOAD = 1200  # per-packet payload budget when packing frames

# loss recovery (RFC 9002-shaped): packet-threshold + time-threshold
# loss declaration, RTT-adaptive PTO (srtt + 4*rttvar) with exponential
# backoff.  PTO_INITIAL_S is only the pre-first-sample value (kInitialRtt
# territory); once acks flow the timer tracks the measured path.
ACK_REORDER_THRESH = 3
PTO_INITIAL_S = 0.2
PTO_BACKOFF_CAP = 5  # doubling cap: base * 2^5
# timer floor (kGranularity, scaled up for a Python engine: a 1 ms floor
# would let a same-host srtt≈0 path fire PTO storms between event-loop
# iterations)
PTO_GRANULARITY_S = 0.01
# time-threshold loss: outstanding packets older than 9/8 * rtt behind
# the largest acked are lost without waiting for the full PTO (§6.1.2)
TIME_THRESHOLD = 9 / 8

# flow control windows (our receive side / assumed peer until updated)
DEFAULT_MAX_DATA = 1 << 20
DEFAULT_MAX_STREAM_DATA = 1 << 18


class QuicError(RuntimeError):
    pass


# -- varint (RFC 9000 §16) ----------------------------------------------------


def varint_encode(v: int) -> bytes:
    if v < 1 << 6:
        return bytes([v])
    if v < 1 << 14:
        return (0x4000 | v).to_bytes(2, "big")
    if v < 1 << 30:
        return (0x8000_0000 | v).to_bytes(4, "big")
    if v < 1 << 62:
        return (0xC000_0000_0000_0000 | v).to_bytes(8, "big")
    raise QuicError("varint out of range")


def varint_decode(buf: bytes, off: int) -> tuple[int, int]:
    if off >= len(buf):
        raise QuicError("truncated varint")
    first = buf[off]
    ln = 1 << (first >> 6)
    if off + ln > len(buf):
        raise QuicError("truncated varint body")
    v = int.from_bytes(buf[off : off + ln], "big") & ((1 << (8 * ln - 2)) - 1)
    return v, off + ln


# -- per-level packet protection keys -----------------------------------------


@dataclass
class Keys:
    gcm: AesGcm
    iv: bytes
    hp: Aes

    @classmethod
    def from_secret(cls, secret: bytes) -> "Keys":
        key = hkdf_expand_label(secret, "quic key", b"", 16)
        iv = hkdf_expand_label(secret, "quic iv", b"", 12)
        hp = hkdf_expand_label(secret, "quic hp", b"", 16)
        return cls(AesGcm(key), iv, Aes(hp))

    def nonce(self, pn: int) -> bytes:
        n = bytearray(self.iv)
        for i in range(8):
            n[-1 - i] ^= (pn >> (8 * i)) & 0xFF
        return bytes(n)


def initial_secrets(dcid: bytes) -> tuple[bytes, bytes]:
    """(client_secret, server_secret) per RFC 9001 §5.2."""
    initial = hkdf_extract(INITIAL_SALT_V1, dcid)
    return (
        hkdf_expand_label(initial, "client in", b"", 32),
        hkdf_expand_label(initial, "server in", b"", 32),
    )


def _hp_mask(hp: Aes, sample: bytes) -> bytes:
    return hp.encrypt_block(sample)


def export_rx_app_keys(conn: "Connection") -> tuple[bytes, bytes, bytes] | None:
    """Raw (key, iv, hp) bytes of the connection's APPLICATION-level rx
    side, re-derived from the TLS secret (Keys keeps only the schedule
    objects, never the raw bytes).  The native net lane installs these
    into its interned connection table; None until the handshake has
    produced the application secrets."""
    sec = conn.tls.secrets.get(APPLICATION)
    if sec is None:
        return None
    s = sec[1] if conn.is_client else sec[0]
    return (
        hkdf_expand_label(s, "quic key", b"", 16),
        hkdf_expand_label(s, "quic iv", b"", 12),
        hkdf_expand_label(s, "quic hp", b"", 16),
    )


# -- packet sealing / opening -------------------------------------------------

PN_LEN = 2  # fixed 2-byte encoded packet numbers (valid per §17.1)


def decode_pn(truncated: int, pn_nbits: int, largest: int) -> int:
    """Reconstruct a full packet number from its truncated wire form
    against the largest pn received so far (RFC 9000 Appendix A.3)."""
    expected = largest + 1
    win = 1 << pn_nbits
    hwin = win >> 1
    cand = (expected & ~(win - 1)) | truncated
    if cand <= expected - hwin and cand + win < (1 << 62):
        return cand + win
    if cand > expected + hwin and cand >= win:
        return cand - win
    return cand


def _long_header(ptype: int, dcid: bytes, scid: bytes, token: bytes,
                 payload_len: int, pn: int) -> bytes:
    first = 0xC0 | (ptype << 4) | (PN_LEN - 1)
    hdr = bytes([first]) + struct.pack(">I", QUIC_V1)
    hdr += bytes([len(dcid)]) + dcid + bytes([len(scid)]) + scid
    if ptype == LONG_INITIAL:
        hdr += varint_encode(len(token)) + token
    hdr += varint_encode(payload_len + PN_LEN + 16)  # + GCM tag
    hdr += pn.to_bytes(PN_LEN, "big")
    return hdr


def seal_packet(keys: Keys, *, level: int, dcid: bytes, scid: bytes,
                pn: int, payload: bytes, token: bytes = b"") -> bytes:
    if level == APPLICATION:
        hdr = bytes([0x40 | (PN_LEN - 1)]) + dcid + pn.to_bytes(PN_LEN, "big")
        pn_off = 1 + len(dcid)
    else:
        ptype = LONG_INITIAL if level == INITIAL else LONG_HANDSHAKE
        hdr = _long_header(ptype, dcid, scid, token, len(payload), pn)
        pn_off = len(hdr) - PN_LEN
    ct, tag = keys.gcm.seal(keys.nonce(pn), payload, hdr)
    pkt = bytearray(hdr + ct + tag)
    sample = bytes(pkt[pn_off + 4 : pn_off + 4 + 16])
    mask = _hp_mask(keys.hp, sample)
    pkt[0] ^= mask[0] & (0x0F if pkt[0] & 0x80 else 0x1F)
    for i in range(PN_LEN):
        pkt[pn_off + i] ^= mask[1 + i]
    return bytes(pkt)


# -- Retry / version negotiation / stateless reset (RFC 9000 §17.2.5,
#    §6, §10.3 — the fd_quic.c retry path's counterpart) ----------------------


def retry_integrity_tag(odcid: bytes, retry_without_tag: bytes) -> bytes:
    """AES-128-GCM tag over the Retry pseudo-packet (RFC 9001 §5.8)."""
    pseudo = bytes([len(odcid)]) + odcid + retry_without_tag
    ct, tag = AesGcm(RETRY_KEY_V1).seal(RETRY_NONCE_V1, b"", aad=pseudo)
    assert ct == b""
    return tag


def build_retry(*, odcid: bytes, dcid: bytes, scid: bytes,
                token: bytes) -> bytes:
    """Server->client Retry: address validation before any state is
    allocated (the amplification defense)."""
    pkt = bytes([0xC0 | (LONG_RETRY << 4)])
    pkt += struct.pack(">I", QUIC_V1)
    pkt += bytes([len(dcid)]) + dcid
    pkt += bytes([len(scid)]) + scid
    pkt += token
    return pkt + retry_integrity_tag(odcid, pkt)


def parse_retry(buf: bytes) -> tuple[bytes, bytes, bytes, bytes] | None:
    """-> (dcid, scid, token, tag) for a well-formed Retry, else None."""
    if len(buf) < 7 + 16 or not buf[0] & 0x80:
        return None
    if (buf[0] >> 4) & 3 != LONG_RETRY:
        return None
    if struct.unpack_from(">I", buf, 1)[0] != QUIC_V1:
        return None
    p = 5
    dlen = buf[p]
    dcid = buf[p + 1 : p + 1 + dlen]
    p += 1 + dlen
    if p >= len(buf):
        return None
    slen = buf[p]
    scid = buf[p + 1 : p + 1 + slen]
    p += 1 + slen
    if len(buf) - p < 16:
        return None
    return dcid, scid, buf[p:-16], buf[-16:]


def peek_initial_token(buf: bytes) -> tuple[bytes, bytes, bytes] | None:
    """Cleartext header fields of an Initial: (dcid, scid, token) —
    the server's pre-handshake address-validation peek (no keys)."""
    if len(buf) < 7 or not buf[0] & 0x80:
        return None
    if (buf[0] >> 4) & 3 != LONG_INITIAL:
        return None
    try:
        p = 5
        dlen = buf[p]
        dcid = buf[p + 1 : p + 1 + dlen]
        p += 1 + dlen
        slen = buf[p]
        scid = buf[p + 1 : p + 1 + slen]
        p += 1 + slen
        tlen, p = varint_decode(buf, p)
        return dcid, scid, buf[p : p + tlen]
    except (IndexError, QuicError):
        return None


def packet_version(buf: bytes) -> int | None:
    """The long-header version field (None for short headers)."""
    if len(buf) < 5 or not buf[0] & 0x80:
        return None
    return struct.unpack_from(">I", buf, 1)[0]


def build_version_negotiation(dcid: bytes, scid: bytes,
                              versions=(QUIC_V1,)) -> bytes:
    """Version 0 long header listing what we speak (RFC 9000 §6)."""
    pkt = bytes([0x80 | (os.urandom(1)[0] & 0x7F)])
    pkt += struct.pack(">I", 0)
    pkt += bytes([len(dcid)]) + dcid
    pkt += bytes([len(scid)]) + scid
    for v in versions:
        pkt += struct.pack(">I", v)
    return pkt


def is_version_negotiation(buf: bytes) -> bool:
    return packet_version(buf) == 0


class RetryGate:
    """Stateless address-validation tokens: HMAC over (peer address,
    original DCID, expiry) — nothing allocated for unvalidated peers,
    the property the reference's retry path exists for."""

    def __init__(self, static_key: bytes, *, lifetime_s: float = 30.0):
        self.key = static_key
        self.lifetime_s = lifetime_s

    def _mac(self, addr_blob: bytes, odcid: bytes, expiry: int) -> bytes:
        import hashlib
        import hmac as _hmac

        return _hmac.new(
            self.key,
            b"retry:" + addr_blob + bytes([len(odcid)]) + odcid
            + expiry.to_bytes(8, "little"),
            hashlib.sha256,
        ).digest()[:16]

    @staticmethod
    def _addr_blob(addr) -> bytes:
        return repr(addr).encode()

    def make_token(self, addr, odcid: bytes,
                   now: float | None = None) -> bytes:
        now = _time.time() if now is None else now
        expiry = int(now + self.lifetime_s)
        blob = self._addr_blob(addr)
        return (bytes([len(odcid)]) + odcid + expiry.to_bytes(8, "little")
                + self._mac(blob, odcid, expiry))

    def validate(self, addr, token: bytes,
                 now: float | None = None) -> bytes | None:
        """-> the original DCID when the token is genuine and fresh."""
        import hmac as _hmac

        now = _time.time() if now is None else now
        if len(token) < 1 + 8 + 16:
            return None
        n = token[0]
        if len(token) != 1 + n + 8 + 16:
            return None
        odcid = token[1 : 1 + n]
        expiry = int.from_bytes(token[1 + n : 1 + n + 8], "little")
        mac = token[1 + n + 8 :]
        if now > expiry:
            return None
        good = self._mac(self._addr_blob(addr), odcid, expiry)
        if not _hmac.compare_digest(mac, good):
            return None
        return odcid


def stateless_reset_token(static_key: bytes, cid: bytes) -> bytes:
    """The 16-byte token a server commits to for each CID (§10.3.2)."""
    import hashlib
    import hmac as _hmac

    return _hmac.new(static_key, b"sreset:" + cid,
                     hashlib.sha256).digest()[:16]


def build_stateless_reset(token: bytes, rng=None) -> bytes:
    """Indistinguishable-from-short-header datagram ending in the token."""
    rnd = rng or os.urandom
    pad = rnd(20)
    first = bytes([0x40 | (pad[0] & 0x3F)])
    return first + pad[1:] + token


def looks_like_stateless_reset(buf: bytes, tokens) -> bool:
    """§10.3.1: short-header-shaped datagram whose last 16 bytes match a
    known peer reset token."""
    if len(buf) < 21 or buf[0] & 0x80:
        return False
    return bytes(buf[-16:]) in tokens


@dataclass
class Packet:
    level: int
    pn: int
    payload: bytes
    dcid: bytes
    scid: bytes


def open_packet(buf: bytes, off: int, key_for_level, *,
                short_dcid_len: int,
                largest_for_level=lambda lvl: -1) -> tuple[Packet | None, int]:
    """Unprotect one (possibly coalesced) packet starting at `off`.
    key_for_level(level, dcid) -> Keys | None.  Returns (packet, next
    offset); packet None when keys for that level are not ready (the
    rest of the datagram is dropped, as the reference does).
    largest_for_level(level) -> largest pn seen, for §A.3 pn
    reconstruction (without it any >16-bit pn derives wrong nonces)."""
    first = buf[off]
    if first & 0x80:  # long header
        if off + 7 > len(buf):
            raise QuicError("truncated long header")
        version = struct.unpack_from(">I", buf, off + 1)[0]
        if version != QUIC_V1:
            raise QuicError(f"unsupported version 0x{version:x}")
        p = off + 5
        dlen = buf[p]
        if p + 1 + dlen + 1 > len(buf):
            raise QuicError("truncated DCID")
        dcid = buf[p + 1 : p + 1 + dlen]
        p += 1 + dlen
        slen = buf[p]
        if p + 1 + slen > len(buf):
            raise QuicError("truncated SCID")
        scid = buf[p + 1 : p + 1 + slen]
        p += 1 + slen
        ptype = (first >> 4) & 3
        if ptype == LONG_INITIAL:
            tlen, p = varint_decode(buf, p)
            p += tlen
        elif ptype != LONG_HANDSHAKE:
            raise QuicError(f"unsupported long packet type {ptype}")
        plen, p = varint_decode(buf, p)
        level = INITIAL if ptype == LONG_INITIAL else HANDSHAKE
        pn_off = p
        end = p + plen
        if end > len(buf):
            raise QuicError("packet length past the datagram end")
    else:  # short header
        if off + 1 + short_dcid_len > len(buf):
            raise QuicError("truncated short header")
        dcid = buf[off + 1 : off + 1 + short_dcid_len]
        scid = b""
        level = APPLICATION
        pn_off = off + 1 + short_dcid_len
        end = len(buf)
    if pn_off + 4 + 16 > end:
        raise QuicError("packet too short for the header-protection sample")
    keys = key_for_level(level, dcid)
    if keys is None:
        return None, end
    work = bytearray(buf[off:end])
    rel = pn_off - off
    sample = bytes(work[rel + 4 : rel + 4 + 16])
    mask = _hp_mask(keys.hp, sample)
    work[0] ^= mask[0] & (0x0F if work[0] & 0x80 else 0x1F)
    pn_len = (work[0] & 0x03) + 1
    for i in range(pn_len):
        work[rel + i] ^= mask[1 + i]
    truncated = int.from_bytes(work[rel : rel + pn_len], "big")
    pn = decode_pn(truncated, 8 * pn_len, largest_for_level(level))
    hdr = bytes(work[: rel + pn_len])
    body = bytes(work[rel + pn_len :])
    if len(body) < 16:
        raise QuicError("packet too short for the GCM tag")
    ct, tag = body[:-16], body[-16:]
    pt = keys.gcm.open(keys.nonce(pn), ct, tag, hdr)
    if pt is None:
        raise QuicError("packet authentication failed")
    return Packet(level, pn, pt, dcid, scid), end


# -- frames -------------------------------------------------------------------


def crypto_frame(offset: int, data: bytes) -> bytes:
    return (
        bytes([FT_CRYPTO]) + varint_encode(offset)
        + varint_encode(len(data)) + data
    )


def stream_frame(stream_id: int, offset: int, data: bytes, fin: bool) -> bytes:
    ft = FT_STREAM_BASE | 0x02 | 0x04 | (0x01 if fin else 0)  # LEN+OFF bits
    return (
        bytes([ft]) + varint_encode(stream_id) + varint_encode(offset)
        + varint_encode(len(data)) + data
    )


def ack_frame(ranges: list[tuple[int, int]]) -> bytes:
    """ACK over [lo, hi] inclusive ranges (ascending order in), §19.3."""
    rs = sorted(ranges, key=lambda r: r[1], reverse=True)
    largest = rs[0][1]
    out = bytearray(
        bytes([FT_ACK]) + varint_encode(largest) + varint_encode(0)
        + varint_encode(len(rs) - 1) + varint_encode(rs[0][1] - rs[0][0])
    )
    prev_lo = rs[0][0]
    for lo, hi in rs[1:]:
        out += varint_encode(prev_lo - hi - 2)  # gap
        out += varint_encode(hi - lo)           # range length
        prev_lo = lo
    return bytes(out)


@dataclass
class StreamEvent:
    stream_id: int
    offset: int
    data: bytes
    fin: bool


def peek_dcid(datagram: bytes, *, short_dcid_len: int) -> bytes | None:
    """Destination CID of the first packet without unprotecting it —
    the connection-lookup key (a migrating peer keeps its CID while its
    address changes, RFC 9000 §9)."""
    if not datagram:
        return None
    first = datagram[0]
    if first & 0x80:  # long header
        if len(datagram) < 7:
            return None
        dlen = datagram[5]
        if len(datagram) < 6 + dlen:
            return None
        return bytes(datagram[6 : 6 + dlen])
    if len(datagram) < 1 + short_dcid_len:
        return None
    return bytes(datagram[1 : 1 + short_dcid_len])


def parse_frames(payload: bytes):
    """Yield ('crypto', off, data) | ('stream', StreamEvent) |
    ('ack', ranges) | ('max_data', n) | ('max_stream_data', sid, n) |
    ('handshake_done',) | ('close', code) events."""
    off = 0
    n = len(payload)
    while off < n:
        ft = payload[off]
        off += 1
        if ft == FT_PADDING:
            continue
        if ft in (FT_PATH_CHALLENGE, FT_PATH_RESPONSE):
            if off + 8 > n:
                raise QuicError("truncated path frame")
            kind = ("path_challenge" if ft == FT_PATH_CHALLENGE
                    else "path_response")
            yield (kind, payload[off : off + 8])
            off += 8
            continue
        if ft == FT_PING:
            # ack-eliciting (RFC 9002): a PING-only PTO probe that never
            # got acked would back the peer off into an idle timeout
            yield ("ping",)
            continue
        if ft in (FT_ACK, FT_ACK | 1):
            largest, off = varint_decode(payload, off)
            _delay, off = varint_decode(payload, off)
            range_cnt, off = varint_decode(payload, off)
            first, off = varint_decode(payload, off)
            hi = largest
            lo = largest - first
            ranges = [(lo, hi)]
            for _ in range(range_cnt):
                gap, off = varint_decode(payload, off)
                ln, off = varint_decode(payload, off)
                hi = lo - gap - 2
                lo = hi - ln
                if lo < 0:
                    raise QuicError("ACK range below zero")
                ranges.append((lo, hi))
            if ft & 1:  # ECN counts
                for _ in range(3):
                    _ecn, off = varint_decode(payload, off)
            yield ("ack", ranges)
        elif ft == FT_CRYPTO:
            coff, off = varint_decode(payload, off)
            clen, off = varint_decode(payload, off)
            if off + clen > n:
                # §12.4: a declared length past the packet end is
                # FRAME_ENCODING_ERROR, never a silent truncation (a
                # short slice would poison the reassembly offsets)
                raise QuicError("CRYPTO frame length past packet end")
            yield ("crypto", coff, payload[off : off + clen])
            off += clen
        elif FT_STREAM_BASE <= ft <= FT_STREAM_BASE | 0x07:
            sid, off = varint_decode(payload, off)
            soff = 0
            if ft & 0x04:
                soff, off = varint_decode(payload, off)
            if ft & 0x02:
                slen, off = varint_decode(payload, off)
                if off + slen > n:
                    raise QuicError("STREAM frame length past packet end")
            else:
                slen = n - off
            yield ("stream", StreamEvent(sid, soff, payload[off : off + slen],
                                         bool(ft & 0x01)))
            off += slen
        elif ft == FT_MAX_DATA:
            v, off = varint_decode(payload, off)
            yield ("max_data", v)
        elif ft == FT_MAX_STREAM_DATA:
            sid, off = varint_decode(payload, off)
            v, off = varint_decode(payload, off)
            yield ("max_stream_data", sid, v)
        elif ft in (FT_MAX_STREAMS_BIDI, FT_MAX_STREAMS_UNI,
                    FT_DATA_BLOCKED, FT_STREAMS_BLOCKED_BIDI,
                    FT_STREAMS_BLOCKED_UNI, FT_RETIRE_CONNECTION_ID):
            _v, off = varint_decode(payload, off)
        elif ft == FT_STREAM_DATA_BLOCKED:
            _sid, off = varint_decode(payload, off)
            _v, off = varint_decode(payload, off)
        elif ft in (FT_RESET_STREAM, FT_STOP_SENDING):
            _sid, off = varint_decode(payload, off)
            _code, off = varint_decode(payload, off)
            if ft == FT_RESET_STREAM:
                _final, off = varint_decode(payload, off)
        elif ft == FT_NEW_CONNECTION_ID:
            _seq, off = varint_decode(payload, off)
            _retire, off = varint_decode(payload, off)
            cid_len = payload[off]
            off += 1 + cid_len + 16  # cid + stateless reset token
        elif ft == FT_HANDSHAKE_DONE:
            yield ("handshake_done",)
        elif ft in (FT_CONN_CLOSE, 0x1D):
            code, off = varint_decode(payload, off)
            if ft == FT_CONN_CLOSE:
                _ftype, off = varint_decode(payload, off)
            rlen, off = varint_decode(payload, off)
            off += rlen
            yield ("close", code)
        else:
            raise QuicError(f"unhandled frame type 0x{ft:x}")


# -- ordered byte-stream reassembly (CRYPTO streams) ---------------------------


class _OrderedStream:
    def __init__(self):
        self.delivered = 0
        self.segments: dict[int, bytes] = {}
        self.fin_size: int | None = None

    def insert(self, off: int, data: bytes) -> bytes:
        if data and off + len(data) > self.delivered:
            self.segments[off] = max(
                self.segments.get(off, b""), data, key=len
            )
        out = bytearray()
        while True:
            seg = None
            for o, d in self.segments.items():
                if o + len(d) <= self.delivered:
                    seg = (o, None)  # fully stale duplicate: purge
                    break
                if o <= self.delivered:
                    seg = (o, d)
                    break
            if seg is None:
                break
            o, d = seg
            if d is not None:
                out += d[self.delivered - o :]
                self.delivered = o + len(d)
            del self.segments[o]
        return bytes(out)

    @property
    def finished(self) -> bool:
        return self.fin_size is not None and self.delivered >= self.fin_size


# -- received-pn tracking (feeds multi-range ACKs + duplicate drop) -----------


class _RecvTracker:
    def __init__(self):
        self.ranges: list[list[int]] = []  # ascending, disjoint [lo, hi]

    def seen(self, pn: int) -> bool:
        return any(lo <= pn <= hi for lo, hi in self.ranges)

    def add(self, pn: int) -> None:
        rs = self.ranges
        for i, r in enumerate(rs):
            if r[0] - 1 <= pn <= r[1] + 1:
                r[0] = min(r[0], pn)
                r[1] = max(r[1], pn)
                # merge with the next range if they now touch
                if i + 1 < len(rs) and rs[i + 1][0] <= r[1] + 1:
                    r[1] = max(r[1], rs[i + 1][1])
                    del rs[i + 1]
                return
            if pn < r[0] - 1:
                rs.insert(i, [pn, pn])
                return
        rs.append([pn, pn])
        if len(rs) > 32:  # bound state: forget the oldest ranges
            del rs[0 : len(rs) - 32]

    @property
    def largest(self) -> int:
        return self.ranges[-1][1] if self.ranges else -1


# -- sent-packet tracking (loss detection + PTO) ------------------------------


@dataclass
class SentPacket:
    pn: int
    time_sent: float
    frames: list  # ('crypto', off, bytes) | ('stream', sid, off, bytes, fin)
    # ack-eliciting bookkeeping (§2, §6.2.1): only ack-eliciting packets
    # arm the PTO timer and take RTT samples.  Pure-ACK packets are never
    # tracked at all (flush records nothing for them), so every tracked
    # packet is ack-eliciting today — the flag keeps the contract
    # explicit for future non-eliciting tracked kinds.
    ack_eliciting: bool = True


# -- connection ---------------------------------------------------------------


@dataclass
class Connection:
    """One QUIC connection endpoint.

    Drive it: feed inbound datagrams to `receive` (returns stream
    events), pull outbound datagrams from `flush`, write app data with
    `send_stream` once `established`, and call `poll_timers` + `flush`
    periodically so PTO retransmissions go out."""

    is_client: bool
    tls: tls13.Endpoint
    local_cid: bytes
    remote_cid: bytes
    keys_tx: dict = field(default_factory=dict)
    keys_rx: dict = field(default_factory=dict)

    @classmethod
    def client_new(cls, *, expected_peer=None, transport_params=b"",
                   rng=None) -> "Connection":
        rnd = rng or os.urandom
        local = rnd(8)
        remote = rnd(8)
        tls = tls13.client(transport_params=transport_params,
                           expected_peer=expected_peer, rng=rng)
        c = cls(True, tls, local, remote)
        csec, ssec = initial_secrets(remote)
        c.keys_tx[INITIAL] = Keys.from_secret(csec)
        c.keys_rx[INITIAL] = Keys.from_secret(ssec)
        c._post_init()
        return c

    @classmethod
    def server_new(cls, identity_secret: bytes, *, transport_params=b"",
                   rng=None) -> "Connection":
        rnd = rng or os.urandom
        tls = tls13.server(identity_secret,
                           transport_params=transport_params, rng=rng)
        c = cls(False, tls, rnd(8), b"")
        c._post_init()
        return c

    def _post_init(self):
        lvls = (INITIAL, HANDSHAKE, APPLICATION)
        self.pn_next = {lvl: 0 for lvl in lvls}
        self.crypto_sent = {lvl: 0 for lvl in lvls}
        self.crypto_rx = {lvl: _OrderedStream() for lvl in lvls}
        self.recv = {lvl: _RecvTracker() for lvl in lvls}
        self.ack_pending: set[int] = set()
        self.sent = {lvl: {} for lvl in lvls}  # pn -> SentPacket
        self.crypto_rtx = {lvl: [] for lvl in lvls}  # [(off, bytes)]
        self.stream_rtx: list[tuple[int, int, bytes, bool]] = []
        self.raw_rtx: list[bytes] = []  # lost ctrl frames (MAX_DATA...)
        self.pto_count = 0
        # RTT estimator (RFC 9002 §5): EWMA smoothed rtt + variance from
        # ack samples of newly-acked ack-eliciting packets.  None until
        # the first sample — poll_timers falls back to PTO_INITIAL_S.
        self.srtt: float | None = None
        self.rttvar: float = 0.0
        self.min_rtt: float | None = None
        self.latest_rtt: float | None = None
        # per-level send time of the LAST ack-eliciting packet: the PTO
        # timer re-arms from it (§6.2.1 — not from the oldest packet)
        self.last_ae_time = {lvl: None for lvl in lvls}
        self.stream_rx: dict[int, _OrderedStream] = {}
        self.send_offset: dict[int, int] = {}
        self.app_out: list[tuple] = []  # retransmittable stream tuples
        self.ctrl_out: list[bytes] = []  # fire-and-forget ctrl frames
        self.closed = False
        self.handshake_done_sent = False
        # address validation: the token a Retry handed us rides every
        # subsequent Initial; a client accepts at most ONE Retry (§17.2.5)
        self.initial_token = b""
        self.retry_seen = False
        self.original_dcid = self.remote_cid if self.is_client else b""
        # peer stateless-reset tokens we recognize (§10.3.1)
        self.peer_reset_tokens: set[bytes] = set()
        # §6.2: VN is only valid before the first processed packet
        self._processed_any = False
        # path validation (RFC 9000 §8.2/§9): responses we owe ride the
        # next flush; responses we RECEIVED surface for the transport
        # owner (the ingress stage) to complete a migration
        self.path_responses: list[bytes] = []
        # flow control: our receive windows (advertised to the peer)
        self.rx_max_data = DEFAULT_MAX_DATA
        self.rx_consumed = 0
        self.rx_data_total = 0  # sum of per-stream high-water offsets
        self.rx_stream_high: dict[int, int] = {}
        self.rx_stream_limit: dict[int, int] = {}
        # peer's windows (what we may send)
        self.tx_max_data = DEFAULT_MAX_DATA
        self.tx_data_total = 0
        self.tx_stream_limit: dict[int, int] = {}
        self.blocked_out: list[tuple[int, bytes, bool]] = []
        # stream ids with a parked write — O(1) ordering check in
        # _send_stream_inner (a linear scan there is O(n^2) under
        # sustained backpressure on the per-txn-stream ingress path)
        self._blocked_sids: set[int] = set()

    @property
    def established(self) -> bool:
        return self.tls.complete

    def has_unacked(self) -> bool:
        return any(self.sent[lvl] for lvl in self.sent) or bool(
            self.stream_rtx or self.blocked_out
        )

    # -- keys --

    def _maybe_install_keys(self):
        for lvl in (HANDSHAKE, APPLICATION):
            if lvl in self.keys_tx or lvl not in self.tls.secrets:
                continue
            csec, ssec = self.tls.secrets[lvl]
            if self.is_client:
                self.keys_tx[lvl] = Keys.from_secret(csec)
                self.keys_rx[lvl] = Keys.from_secret(ssec)
            else:
                self.keys_tx[lvl] = Keys.from_secret(ssec)
                self.keys_rx[lvl] = Keys.from_secret(csec)

    # -- inbound --

    def receive(self, datagram: bytes, now: float | None = None
                ) -> list[StreamEvent]:
        now = _time.monotonic() if now is None else now
        events: list[StreamEvent] = []
        if looks_like_stateless_reset(datagram, self.peer_reset_tokens):
            # §10.3.1: the peer lost state for this connection — enter
            # the draining state, nothing more goes out
            self.closed = True
            return events
        if self.is_client and is_version_negotiation(datagram):
            # §6.2: VN is honored only BEFORE any packet of this
            # connection has been processed — a spoofed unauthenticated
            # VN datagram must never kill an in-progress/live connection
            if self._processed_any:
                return events
            try:
                vstart = 7 + datagram[5] + datagram[6 + datagram[5]]
                vers = {struct.unpack_from(">I", datagram, p)[0]
                        for p in range(vstart, len(datagram) - 3, 4)}
            except (IndexError, struct.error):
                return events  # malformed VN: ignore (untrusted input)
            # we only speak v1; a VN LISTING v1 is a MITM replay (§6.2)
            if QUIC_V1 not in vers:
                self.closed = True
            return events
        if self.is_client and not self.established and \
                not self._processed_any and \
                len(datagram) > 5 and datagram[0] & 0x80 and \
                (datagram[0] >> 4) & 3 == LONG_RETRY and \
                packet_version(datagram) == QUIC_V1:
            # §17.2.5.2: a Retry is honored only before ANY packet has
            # been processed — Initial keys are wire-derivable, so a
            # later forged Retry could otherwise wedge the handshake
            self._handle_retry(datagram, now)
            return events
        off = 0
        while off < len(datagram):
            if datagram[off] == 0:  # trailing padding bytes
                off += 1
                continue
            if not self.is_client and not self.remote_cid and (
                datagram[off] & 0x80
            ):
                # first client Initial: adopt its DCID for our RX keys
                self._server_adopt(datagram, off)
            pkt, off = open_packet(
                datagram, off, self._rx_keys,
                short_dcid_len=len(self.local_cid),
                largest_for_level=lambda lvl: self.recv[lvl].largest,
            )
            if pkt is None:
                continue
            self._processed_any = True
            tracker = self.recv[pkt.level]
            if tracker.seen(pkt.pn):
                # duplicate (e.g. a spurious retransmission): re-ack only
                self.ack_pending.add(pkt.level)
                continue
            tracker.add(pkt.pn)
            if pkt.level == INITIAL and pkt.scid:
                # both sides route subsequent packets at the peer's SCID
                self.remote_cid = pkt.scid
            for ev in parse_frames(pkt.payload):
                if ev[0] != "ack":
                    self.ack_pending.add(pkt.level)
                if ev[0] == "crypto":
                    _, coff, data = ev
                    ready = self.crypto_rx[pkt.level].insert(coff, data)
                    if ready:
                        self.tls.consume(pkt.level, ready)
                        self._maybe_install_keys()
                elif ev[0] == "stream":
                    self._rx_flow_check(ev[1])
                    events.append(ev[1])
                elif ev[0] == "ack":
                    self._on_ack(pkt.level, ev[1], now)
                elif ev[0] == "max_data":
                    self.tx_max_data = max(self.tx_max_data, ev[1])
                    self._drain_blocked()
                elif ev[0] == "max_stream_data":
                    _, sid, v = ev
                    cur = self.tx_stream_limit.get(sid, DEFAULT_MAX_STREAM_DATA)
                    self.tx_stream_limit[sid] = max(cur, v)
                    self._drain_blocked()
                elif ev[0] == "path_challenge":
                    # §8.2.2: echo the 8 bytes in a PATH_RESPONSE
                    self.ctrl_out.append(
                        bytes([FT_PATH_RESPONSE]) + ev[1]
                    )
                elif ev[0] == "path_response":
                    self.path_responses.append(ev[1])
                elif ev[0] == "close":
                    self.closed = True
        return events

    def _rx_flow_check(self, ev: StreamEvent) -> None:
        """Enforce our advertised windows on inbound stream data."""
        end = ev.offset + len(ev.data)
        limit = self.rx_stream_limit.get(ev.stream_id, DEFAULT_MAX_STREAM_DATA)
        if end > limit:
            raise QuicError(
                f"stream {ev.stream_id} flow control violated "
                f"({end} > {limit})"
            )
        high = self.rx_stream_high.get(ev.stream_id, 0)
        if end > high:
            self.rx_data_total += end - high
            self.rx_stream_high[ev.stream_id] = end
            if self.rx_data_total > self.rx_max_data:
                raise QuicError("connection flow control violated")

    def _handle_retry(self, datagram: bytes, now: float) -> None:
        """§17.2.5 client side: verify the integrity tag against the
        ORIGINAL DCID, adopt the server's new CID, re-derive initial
        keys from it, and resend the first flight carrying the token."""
        if self.retry_seen or self.initial_token:
            return  # at most one Retry per attempt; later ones ignored
        got = parse_retry(datagram)
        if got is None:
            return
        _dcid, scid, token, _tag = got
        expect = retry_integrity_tag(self.original_dcid, datagram[:-16])
        if expect != datagram[-16:] or not token:
            return  # forged/corrupt Retry: drop silently (§17.2.5)
        self.retry_seen = True
        self.initial_token = token
        self.remote_cid = scid
        csec, ssec = initial_secrets(scid)
        self.keys_tx[INITIAL] = Keys.from_secret(csec)
        self.keys_rx[INITIAL] = Keys.from_secret(ssec)
        # the first flight was discarded by the server: re-queue every
        # in-flight INITIAL frame (pn sequence continues, §17.2.5.3)
        for pn, pkt in sorted(self.sent[INITIAL].items()):
            self._queue_rtx(INITIAL, pkt)
        self.sent[INITIAL].clear()

    def _server_adopt(self, datagram: bytes, off: int):
        if off + 6 > len(datagram):
            raise QuicError("truncated first Initial")
        dlen = datagram[off + 5]
        if off + 6 + dlen > len(datagram):
            raise QuicError("truncated first Initial DCID")
        dcid = datagram[off + 6 : off + 6 + dlen]
        csec, ssec = initial_secrets(dcid)
        self.keys_rx[INITIAL] = Keys.from_secret(csec)
        self.keys_tx[INITIAL] = Keys.from_secret(ssec)

    def _rx_keys(self, level: int, _dcid: bytes):
        return self.keys_rx.get(level)

    # -- loss recovery --

    def _on_ack(self, level: int, ranges: list[tuple[int, int]],
                now: float) -> None:
        sent = self.sent[level]
        newly = [
            pn for pn in sent
            if any(lo <= pn <= hi for lo, hi in ranges)
        ]
        largest_acked = max(hi for _lo, hi in ranges)
        # RTT sample (§5.1): only when the LARGEST acked pn is newly
        # acked and ack-eliciting — a stale range re-ack carries no
        # timing signal
        if largest_acked in sent and sent[largest_acked].ack_eliciting:
            sample = now - sent[largest_acked].time_sent
            if sample >= 0:
                self._rtt_update(sample)
        for pn in newly:
            del sent[pn]
        if newly:
            self.pto_count = 0
        # packet-threshold loss: anything ACK_REORDER_THRESH below the
        # largest acked that is still outstanding is lost; the TIME
        # threshold (§6.1.2) additionally catches small-gap losses a
        # packet count can never reach (e.g. the last packet of a burst)
        loss_delay = None
        rtt = self.latest_rtt if self.srtt is None else max(
            self.srtt, self.latest_rtt or 0.0
        )
        if rtt is not None:
            loss_delay = max(TIME_THRESHOLD * rtt, PTO_GRANULARITY_S)
        for pn in sorted(sent):
            if pn >= largest_acked:
                break
            if pn <= largest_acked - ACK_REORDER_THRESH or (
                loss_delay is not None
                and now - sent[pn].time_sent >= loss_delay
            ):
                self._queue_rtx(level, sent.pop(pn))

    def _rtt_update(self, sample: float) -> None:
        self.latest_rtt = sample
        if self.min_rtt is None or sample < self.min_rtt:
            self.min_rtt = sample
        if self.srtt is None:  # first sample (§5.3)
            self.srtt = sample
            self.rttvar = sample / 2
        else:
            self.rttvar = 0.75 * self.rttvar + 0.25 * abs(self.srtt - sample)
            self.srtt = 0.875 * self.srtt + 0.125 * sample

    def pto_interval(self) -> float:
        """The current probe timeout: srtt + max(4*rttvar, granularity)
        once the path is measured, PTO_INITIAL_S before the first RTT
        sample; doubled per consecutive PTO (capped)."""
        if self.srtt is None:
            base = PTO_INITIAL_S
        else:
            base = self.srtt + max(4 * self.rttvar, PTO_GRANULARITY_S)
            base = max(base, PTO_GRANULARITY_S)
        return base * (2 ** min(self.pto_count, PTO_BACKOFF_CAP))

    def _queue_rtx(self, level: int, pkt: SentPacket) -> None:
        for fr in pkt.frames:
            if fr[0] == "crypto":
                self.crypto_rtx[level].append((fr[1], fr[2]))
            elif fr[0] == "stream":
                self.stream_rtx.append((fr[1], fr[2], fr[3], fr[4]))
            elif fr[0] == "raw":
                # window updates / HANDSHAKE_DONE: cumulative-maximum
                # semantics make a stale resend harmless, and a LOST
                # MAX_DATA would otherwise deadlock the sender forever
                self.raw_rtx.append(fr[1])

    def poll_timers(self, now: float | None = None) -> None:
        """PTO (§6.2): when a level's last ack-eliciting packet has
        waited a full probe timeout with no ack, re-queue everything
        outstanding at that level (the next flush retransmits) and back
        off.  The timeout adapts to the measured RTT (pto_interval);
        levels with only non-eliciting state never arm the timer."""
        now = _time.monotonic() if now is None else now
        pto = self.pto_interval()
        fired = False
        for lvl, sent in self.sent.items():
            if not any(p.ack_eliciting for p in sent.values()):
                continue
            last_ae = self.last_ae_time[lvl]
            if last_ae is None:  # pre-tracking state: fall back to oldest
                last_ae = min(p.time_sent for p in sent.values())
            if now - last_ae >= pto:
                for pn in sorted(sent):
                    self._queue_rtx(lvl, sent.pop(pn))
                fired = True
        if fired:
            self.pto_count += 1

    # -- outbound --

    def send_stream(self, stream_id: int, data: bytes, *,
                    fin: bool = False) -> None:
        if not self.established:
            raise QuicError("stream before handshake completion")
        self._send_stream_inner(stream_id, data, fin)

    def _send_stream_inner(self, stream_id: int, data: bytes,
                           fin: bool) -> None:
        off = self.send_offset.get(stream_id, 0)
        slimit = self.tx_stream_limit.get(stream_id, DEFAULT_MAX_STREAM_DATA)
        if stream_id in self._blocked_sids or off + len(data) > slimit or (
            self.tx_data_total + len(data) > self.tx_max_data
        ):
            # peer window closed — or an EARLIER write on this stream is
            # already parked: a later smaller write must never overtake
            # it (stream bytes are ordered by offset)
            self.blocked_out.append((stream_id, data, fin))
            self._blocked_sids.add(stream_id)
            return
        self.app_out.append(("stream", stream_id, off, data, fin))
        self.send_offset[stream_id] = off + len(data)
        self.tx_data_total += len(data)

    def _drain_blocked(self) -> None:
        pending, self.blocked_out = self.blocked_out, []
        self._blocked_sids.clear()
        for sid, data, fin in pending:
            self._send_stream_inner(sid, data, fin)

    def _rx_window_updates(self, dirty: set[int]) -> None:
        """Advertise bigger windows once half the current one is used.
        Only `dirty` streams (delivered-count changed this batch) are
        examined — the TPU client opens a stream per txn, so scanning
        every stream ever seen would be O(N^2) over a batch."""
        if self.rx_consumed * 2 > self.rx_max_data:
            self.rx_max_data = self.rx_consumed + DEFAULT_MAX_DATA
            self.ctrl_out.append(
                bytes([FT_MAX_DATA]) + varint_encode(self.rx_max_data)
            )
        for sid in dirty:
            st = self.stream_rx.get(sid)
            if st is None:
                continue
            limit = self.rx_stream_limit.get(sid, DEFAULT_MAX_STREAM_DATA)
            if st.fin_size is None and st.delivered * 2 > limit:
                new = st.delivered + DEFAULT_MAX_STREAM_DATA
                self.rx_stream_limit[sid] = new
                self.ctrl_out.append(
                    bytes([FT_MAX_STREAM_DATA]) + varint_encode(sid)
                    + varint_encode(new)
                )

    def flush(self, now: float | None = None) -> list[bytes]:
        """Drain pending CRYPTO/ACK/ctrl/app frames into protected
        datagrams, recording every retransmittable frame for loss
        recovery."""
        now = _time.monotonic() if now is None else now
        out: list[bytes] = []
        if self.established and not self.is_client and (
            not self.handshake_done_sent
        ) and APPLICATION in self.keys_tx:
            self.ctrl_out.append(bytes([FT_HANDSHAKE_DONE]))
            self.handshake_done_sent = True
        for lvl in (INITIAL, HANDSHAKE, APPLICATION):
            if self.keys_tx.get(lvl) is None:
                continue
            pending: list[tuple[bytes, tuple | None]] = []
            # retransmissions first (they unblock the peer's progress)
            for coff, data in self.crypto_rtx[lvl]:
                pending.append((crypto_frame(coff, data),
                                ("crypto", coff, data)))
            self.crypto_rtx[lvl].clear()
            tls_pend = self.tls.pending[lvl]
            if tls_pend:
                data = bytes(tls_pend)
                coff = self.crypto_sent[lvl]
                pending.append((crypto_frame(coff, data),
                                ("crypto", coff, data)))
                self.crypto_sent[lvl] += len(data)
                tls_pend.clear()
            if lvl in self.ack_pending and self.recv[lvl].ranges:
                pending.append(
                    (ack_frame([tuple(r) for r in self.recv[lvl].ranges]),
                     None)
                )
                self.ack_pending.discard(lvl)
            if lvl == APPLICATION:
                for wire in self.raw_rtx:
                    pending.append((wire, ("raw", wire)))
                self.raw_rtx.clear()
                for wire in self.ctrl_out:
                    pending.append((wire, ("raw", wire)))
                self.ctrl_out.clear()
                for sid, soff, data, fin in self.stream_rtx:
                    pending.append((stream_frame(sid, soff, data, fin),
                                    ("stream", sid, soff, data, fin)))
                self.stream_rtx.clear()
                for item in self.app_out:
                    _, sid, soff, data, fin = item
                    pending.append((stream_frame(sid, soff, data, fin),
                                    ("stream", sid, soff, data, fin)))
                self.app_out.clear()
            # pack frames greedily into <= MAX_FRAMES_PAYLOAD packets (a
            # single frame larger than the budget still goes out alone —
            # CRYPTO flights exceed it and the link MTU tolerates them)
            while pending:
                frames = bytearray()
                record: list = []
                while pending and (
                    not frames
                    or len(frames) + len(pending[0][0]) <= MAX_FRAMES_PAYLOAD
                ):
                    wire, rec = pending.pop(0)
                    frames.extend(wire)
                    if rec is not None:
                        record.append(rec)
                payload = bytes(frames)
                if len(payload) < 4:
                    # §5.4.2: the ciphertext must cover the 16-byte HP
                    # sample at pn_off+4; PADDING frames make up the rest
                    payload += bytes(4 - len(payload))
                if lvl == INITIAL and self.is_client and len(payload) < 1200:
                    # §14.1: the whole DATAGRAM must be >= 1200 bytes;
                    # padding the payload itself to 1200 clears that with
                    # the ~30-byte header + 16-byte tag on top
                    payload += bytes(1200 - len(payload))
                pn = self.pn_next[lvl]
                self.pn_next[lvl] += 1
                out.append(seal_packet(
                    self.keys_tx[lvl], level=lvl, dcid=self.remote_cid,
                    scid=self.local_cid, pn=pn, payload=payload,
                    token=self.initial_token if lvl == INITIAL else b"",
                ))
                if record:
                    self.sent[lvl][pn] = SentPacket(pn, now, record)
                    self.last_ae_time[lvl] = now  # re-arm the PTO timer
        return out

    def probe_datagram(self, frames: bytes) -> bytes | None:
        """Seal ONE application packet carrying `frames` for an
        off-path probe (PATH_CHALLENGE to a migrating peer's new
        address).  Untracked: a lost probe is re-issued by the caller on
        the next datagram from that address, never retransmitted onto
        the wrong path by flush()."""
        if APPLICATION not in self.keys_tx:
            return None
        payload = frames if len(frames) >= 4 else frames + bytes(
            4 - len(frames)
        )
        pn = self.pn_next[APPLICATION]
        self.pn_next[APPLICATION] += 1
        return seal_packet(
            self.keys_tx[APPLICATION], level=APPLICATION,
            dcid=self.remote_cid, scid=self.local_cid, pn=pn,
            payload=payload,
        )

    def receive_stream_events(self, events: list[StreamEvent]):
        """Reassemble stream events into (stream_id, bytes, fin) chunks
        in order (the tpu_reasm feed).  fin is reported only once every
        byte up to the FIN offset has been delivered — a FIN frame
        arriving ahead of a gap must not finalize a short stream."""
        out = []
        dirty: set[int] = set()
        for ev in events:
            st = self.stream_rx.setdefault(ev.stream_id, _OrderedStream())
            if ev.fin:
                st.fin_size = ev.offset + len(ev.data)
            ready = st.insert(ev.offset, ev.data)
            if ready:
                self.rx_consumed += len(ready)
                dirty.add(ev.stream_id)
            if ready or st.finished:
                out.append((ev.stream_id, ready, st.finished))
        self._rx_window_updates(dirty)
        return out
