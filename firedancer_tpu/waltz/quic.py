"""QUIC v1 engine: packet protection + frames + connection machine.

Counterpart of /root/reference/src/waltz/quic/fd_quic.c (22.5k lines of
C) reduced to the profile the TPU ingress actually uses
(fd_quic.h:1-60): server accepts connections, client opens them; one
TLS handshake (waltz/tls13.py) rides CRYPTO frames across the initial/
handshake levels; application data arrives on unidirectional client
streams and feeds the TPU reassembler (runtime/tpu_reasm.py).  Like the
reference: single-threaded, fully in-memory, no dynamic allocation
after setup in the hot path — and the parts this build defers
(loss recovery timers, migration, flow-control windows) are exactly the
parts a reliable localnet link never exercises; the wire format is the
real RFC 9000/9001 one:

  - Initial secrets from the client DCID with the v1 salt (§5.2)
  - AES-128-GCM packet protection, nonce = iv XOR packet-number
  - AES-ECB header protection over a 16-byte sample (§5.4)
  - long (Initial/Handshake) + short (1-RTT) headers, varint framing
  - CRYPTO / STREAM / ACK / PING / PADDING / CONNECTION_CLOSE frames
"""

from __future__ import annotations

import os
import struct
from dataclasses import dataclass, field

from firedancer_tpu.ops.aes import Aes, AesGcm
from firedancer_tpu.waltz import tls13
from firedancer_tpu.waltz.tls13 import (
    APPLICATION,
    HANDSHAKE,
    INITIAL,
    hkdf_expand_label,
    hkdf_extract,
)

QUIC_V1 = 1
INITIAL_SALT_V1 = bytes.fromhex("38762cf7f55934b34d179ae6a4c80cadccbb7f0a")

FT_PADDING = 0x00
FT_PING = 0x01
FT_ACK = 0x02
FT_CRYPTO = 0x06
FT_STREAM_BASE = 0x08  # 0x08..0x0f: OFF/LEN/FIN bits
FT_CONN_CLOSE = 0x1C

LONG_INITIAL = 0
LONG_HANDSHAKE = 2

MAX_DATAGRAM = 1452


class QuicError(RuntimeError):
    pass


# -- varint (RFC 9000 §16) ----------------------------------------------------


def varint_encode(v: int) -> bytes:
    if v < 1 << 6:
        return bytes([v])
    if v < 1 << 14:
        return (0x4000 | v).to_bytes(2, "big")
    if v < 1 << 30:
        return (0x8000_0000 | v).to_bytes(4, "big")
    if v < 1 << 62:
        return (0xC000_0000_0000_0000 | v).to_bytes(8, "big")
    raise QuicError("varint out of range")


def varint_decode(buf: bytes, off: int) -> tuple[int, int]:
    if off >= len(buf):
        raise QuicError("truncated varint")
    first = buf[off]
    ln = 1 << (first >> 6)
    if off + ln > len(buf):
        raise QuicError("truncated varint body")
    v = int.from_bytes(buf[off : off + ln], "big") & ((1 << (8 * ln - 2)) - 1)
    return v, off + ln


# -- per-level packet protection keys -----------------------------------------


@dataclass
class Keys:
    gcm: AesGcm
    iv: bytes
    hp: Aes

    @classmethod
    def from_secret(cls, secret: bytes) -> "Keys":
        key = hkdf_expand_label(secret, "quic key", b"", 16)
        iv = hkdf_expand_label(secret, "quic iv", b"", 12)
        hp = hkdf_expand_label(secret, "quic hp", b"", 16)
        return cls(AesGcm(key), iv, Aes(hp))

    def nonce(self, pn: int) -> bytes:
        n = bytearray(self.iv)
        for i in range(8):
            n[-1 - i] ^= (pn >> (8 * i)) & 0xFF
        return bytes(n)


def initial_secrets(dcid: bytes) -> tuple[bytes, bytes]:
    """(client_secret, server_secret) per RFC 9001 §5.2."""
    initial = hkdf_extract(INITIAL_SALT_V1, dcid)
    return (
        hkdf_expand_label(initial, "client in", b"", 32),
        hkdf_expand_label(initial, "server in", b"", 32),
    )


def _hp_mask(hp: Aes, sample: bytes) -> bytes:
    return hp.encrypt_block(sample)


# -- packet sealing / opening -------------------------------------------------

PN_LEN = 2  # fixed 2-byte encoded packet numbers (valid per §17.1)


def _long_header(ptype: int, dcid: bytes, scid: bytes, token: bytes,
                 payload_len: int, pn: int) -> bytes:
    first = 0xC0 | (ptype << 4) | (PN_LEN - 1)
    hdr = bytes([first]) + struct.pack(">I", QUIC_V1)
    hdr += bytes([len(dcid)]) + dcid + bytes([len(scid)]) + scid
    if ptype == LONG_INITIAL:
        hdr += varint_encode(len(token)) + token
    hdr += varint_encode(payload_len + PN_LEN + 16)  # + GCM tag
    hdr += pn.to_bytes(PN_LEN, "big")
    return hdr


def seal_packet(keys: Keys, *, level: int, dcid: bytes, scid: bytes,
                pn: int, payload: bytes, token: bytes = b"") -> bytes:
    if level == APPLICATION:
        hdr = bytes([0x40 | (PN_LEN - 1)]) + dcid + pn.to_bytes(PN_LEN, "big")
        pn_off = 1 + len(dcid)
    else:
        ptype = LONG_INITIAL if level == INITIAL else LONG_HANDSHAKE
        hdr = _long_header(ptype, dcid, scid, token, len(payload), pn)
        pn_off = len(hdr) - PN_LEN
    ct, tag = keys.gcm.seal(keys.nonce(pn), payload, hdr)
    pkt = bytearray(hdr + ct + tag)
    sample = bytes(pkt[pn_off + 4 : pn_off + 4 + 16])
    mask = _hp_mask(keys.hp, sample)
    pkt[0] ^= mask[0] & (0x0F if pkt[0] & 0x80 else 0x1F)
    for i in range(PN_LEN):
        pkt[pn_off + i] ^= mask[1 + i]
    return bytes(pkt)


@dataclass
class Packet:
    level: int
    pn: int
    payload: bytes
    dcid: bytes
    scid: bytes


def open_packet(buf: bytes, off: int, key_for_level, *,
                short_dcid_len: int) -> tuple[Packet | None, int]:
    """Unprotect one (possibly coalesced) packet starting at `off`.
    key_for_level(level, dcid) -> Keys | None.  Returns (packet, next
    offset); packet None when keys for that level are not ready (the
    rest of the datagram is dropped, as the reference does)."""
    first = buf[off]
    if first & 0x80:  # long header
        if off + 7 > len(buf):
            raise QuicError("truncated long header")
        version = struct.unpack_from(">I", buf, off + 1)[0]
        if version != QUIC_V1:
            raise QuicError(f"unsupported version 0x{version:x}")
        p = off + 5
        dlen = buf[p]
        if p + 1 + dlen + 1 > len(buf):
            raise QuicError("truncated DCID")
        dcid = buf[p + 1 : p + 1 + dlen]
        p += 1 + dlen
        slen = buf[p]
        if p + 1 + slen > len(buf):
            raise QuicError("truncated SCID")
        scid = buf[p + 1 : p + 1 + slen]
        p += 1 + slen
        ptype = (first >> 4) & 3
        if ptype == LONG_INITIAL:
            tlen, p = varint_decode(buf, p)
            p += tlen
        elif ptype != LONG_HANDSHAKE:
            raise QuicError(f"unsupported long packet type {ptype}")
        plen, p = varint_decode(buf, p)
        level = INITIAL if ptype == LONG_INITIAL else HANDSHAKE
        pn_off = p
        end = p + plen
        if end > len(buf):
            raise QuicError("packet length past the datagram end")
    else:  # short header
        if off + 1 + short_dcid_len > len(buf):
            raise QuicError("truncated short header")
        dcid = buf[off + 1 : off + 1 + short_dcid_len]
        scid = b""
        level = APPLICATION
        pn_off = off + 1 + short_dcid_len
        end = len(buf)
    if pn_off + 4 + 16 > end:
        raise QuicError("packet too short for the header-protection sample")
    keys = key_for_level(level, dcid)
    if keys is None:
        return None, end
    work = bytearray(buf[off:end])
    rel = pn_off - off
    sample = bytes(work[rel + 4 : rel + 4 + 16])
    mask = _hp_mask(keys.hp, sample)
    work[0] ^= mask[0] & (0x0F if work[0] & 0x80 else 0x1F)
    pn_len = (work[0] & 0x03) + 1
    for i in range(pn_len):
        work[rel + i] ^= mask[1 + i]
    pn = int.from_bytes(work[rel : rel + pn_len], "big")
    hdr = bytes(work[: rel + pn_len])
    body = bytes(work[rel + pn_len :])
    if len(body) < 16:
        raise QuicError("packet too short for the GCM tag")
    ct, tag = body[:-16], body[-16:]
    pt = keys.gcm.open(keys.nonce(pn), ct, tag, hdr)
    if pt is None:
        raise QuicError("packet authentication failed")
    return Packet(level, pn, pt, dcid, scid), end


# -- frames -------------------------------------------------------------------


def crypto_frame(offset: int, data: bytes) -> bytes:
    return (
        bytes([FT_CRYPTO]) + varint_encode(offset)
        + varint_encode(len(data)) + data
    )


def stream_frame(stream_id: int, offset: int, data: bytes, fin: bool) -> bytes:
    ft = FT_STREAM_BASE | 0x02 | 0x04 | (0x01 if fin else 0)  # LEN+OFF bits
    return (
        bytes([ft]) + varint_encode(stream_id) + varint_encode(offset)
        + varint_encode(len(data)) + data
    )


def ack_frame(largest: int) -> bytes:
    return (
        bytes([FT_ACK]) + varint_encode(largest) + varint_encode(0)
        + varint_encode(0) + varint_encode(0)
    )


@dataclass
class StreamEvent:
    stream_id: int
    offset: int
    data: bytes
    fin: bool


def parse_frames(payload: bytes):
    """Yield ('crypto', off, data) | ('stream', StreamEvent) |
    ('ack', largest) | ('close', code) events."""
    off = 0
    n = len(payload)
    while off < n:
        ft = payload[off]
        off += 1
        if ft == FT_PADDING:
            continue
        if ft == FT_PING:
            continue
        if ft == FT_ACK:
            largest, off = varint_decode(payload, off)
            _delay, off = varint_decode(payload, off)
            range_cnt, off = varint_decode(payload, off)
            _first, off = varint_decode(payload, off)
            for _ in range(range_cnt):
                _gap, off = varint_decode(payload, off)
                _ln, off = varint_decode(payload, off)
            yield ("ack", largest)
        elif ft == FT_CRYPTO:
            coff, off = varint_decode(payload, off)
            clen, off = varint_decode(payload, off)
            if off + clen > n:
                # §12.4: a declared length past the packet end is
                # FRAME_ENCODING_ERROR, never a silent truncation (a
                # short slice would poison the reassembly offsets)
                raise QuicError("CRYPTO frame length past packet end")
            yield ("crypto", coff, payload[off : off + clen])
            off += clen
        elif FT_STREAM_BASE <= ft <= FT_STREAM_BASE | 0x07:
            sid, off = varint_decode(payload, off)
            soff = 0
            if ft & 0x04:
                soff, off = varint_decode(payload, off)
            if ft & 0x02:
                slen, off = varint_decode(payload, off)
                if off + slen > n:
                    raise QuicError("STREAM frame length past packet end")
            else:
                slen = n - off
            yield ("stream", StreamEvent(sid, soff, payload[off : off + slen],
                                         bool(ft & 0x01)))
            off += slen
        elif ft in (FT_CONN_CLOSE, 0x1D):
            code, off = varint_decode(payload, off)
            if ft == FT_CONN_CLOSE:
                _ftype, off = varint_decode(payload, off)
            rlen, off = varint_decode(payload, off)
            off += rlen
            yield ("close", code)
        else:
            raise QuicError(f"unhandled frame type 0x{ft:x}")


# -- ordered byte-stream reassembly (CRYPTO streams) ---------------------------


class _OrderedStream:
    def __init__(self):
        self.delivered = 0
        self.segments: dict[int, bytes] = {}
        self.fin_size: int | None = None

    def insert(self, off: int, data: bytes) -> bytes:
        if data and off + len(data) > self.delivered:
            self.segments[off] = max(
                self.segments.get(off, b""), data, key=len
            )
        out = bytearray()
        while True:
            seg = None
            for o, d in self.segments.items():
                if o + len(d) <= self.delivered:
                    seg = (o, None)  # fully stale duplicate: purge
                    break
                if o <= self.delivered:
                    seg = (o, d)
                    break
            if seg is None:
                break
            o, d = seg
            if d is not None:
                out += d[self.delivered - o :]
                self.delivered = o + len(d)
            del self.segments[o]
        return bytes(out)

    @property
    def finished(self) -> bool:
        return self.fin_size is not None and self.delivered >= self.fin_size


# -- connection ---------------------------------------------------------------


@dataclass
class Connection:
    """One QUIC connection endpoint.

    Drive it: feed inbound datagrams to `receive` (returns stream
    events), pull outbound datagrams from `flush`, write app data with
    `send_stream` once `established`."""

    is_client: bool
    tls: tls13.Endpoint
    local_cid: bytes
    remote_cid: bytes
    keys_tx: dict = field(default_factory=dict)
    keys_rx: dict = field(default_factory=dict)

    @classmethod
    def client_new(cls, *, expected_peer=None, transport_params=b"",
                   rng=None) -> "Connection":
        rnd = rng or os.urandom
        local = rnd(8)
        remote = rnd(8)
        tls = tls13.client(transport_params=transport_params,
                           expected_peer=expected_peer, rng=rng)
        c = cls(True, tls, local, remote)
        csec, ssec = initial_secrets(remote)
        c.keys_tx[INITIAL] = Keys.from_secret(csec)
        c.keys_rx[INITIAL] = Keys.from_secret(ssec)
        c._post_init()
        return c

    @classmethod
    def server_new(cls, identity_secret: bytes, *, transport_params=b"",
                   rng=None) -> "Connection":
        rnd = rng or os.urandom
        tls = tls13.server(identity_secret,
                           transport_params=transport_params, rng=rng)
        c = cls(False, tls, rnd(8), b"")
        c._post_init()
        return c

    def _post_init(self):
        self.pn_next = {INITIAL: 0, HANDSHAKE: 0, APPLICATION: 0}
        self.largest_rx = {INITIAL: -1, HANDSHAKE: -1, APPLICATION: -1}
        self.crypto_sent = {INITIAL: 0, HANDSHAKE: 0, APPLICATION: 0}
        self.crypto_rx = {lvl: _OrderedStream() for lvl in
                          (INITIAL, HANDSHAKE, APPLICATION)}
        self.stream_rx: dict[int, _OrderedStream] = {}
        self.send_offset: dict[int, int] = {}
        self.app_out: list[bytes] = []
        self.closed = False

    @property
    def established(self) -> bool:
        return self.tls.complete

    # -- keys --

    def _maybe_install_keys(self):
        for lvl in (HANDSHAKE, APPLICATION):
            if lvl in self.keys_tx or lvl not in self.tls.secrets:
                continue
            csec, ssec = self.tls.secrets[lvl]
            if self.is_client:
                self.keys_tx[lvl] = Keys.from_secret(csec)
                self.keys_rx[lvl] = Keys.from_secret(ssec)
            else:
                self.keys_tx[lvl] = Keys.from_secret(ssec)
                self.keys_rx[lvl] = Keys.from_secret(csec)

    # -- inbound --

    def receive(self, datagram: bytes) -> list[StreamEvent]:
        events: list[StreamEvent] = []
        off = 0
        while off < len(datagram):
            if datagram[off] == 0:  # trailing padding bytes
                off += 1
                continue
            if not self.is_client and not self.remote_cid and (
                datagram[off] & 0x80
            ):
                # first client Initial: adopt its DCID for our RX keys
                self._server_adopt(datagram, off)
            pkt, off = open_packet(
                datagram, off, self._rx_keys,
                short_dcid_len=len(self.local_cid),
            )
            if pkt is None:
                continue
            self.largest_rx[pkt.level] = max(self.largest_rx[pkt.level],
                                             pkt.pn)
            if pkt.level == INITIAL and pkt.scid:
                # both sides route subsequent packets at the peer's SCID
                self.remote_cid = pkt.scid
            for ev in parse_frames(pkt.payload):
                if ev[0] == "crypto":
                    _, coff, data = ev
                    ready = self.crypto_rx[pkt.level].insert(coff, data)
                    if ready:
                        self.tls.consume(pkt.level, ready)
                        self._maybe_install_keys()
                elif ev[0] == "stream":
                    events.append(ev[1])
                elif ev[0] == "close":
                    self.closed = True
        return events

    def _server_adopt(self, datagram: bytes, off: int):
        if off + 6 > len(datagram):
            raise QuicError("truncated first Initial")
        dlen = datagram[off + 5]
        if off + 6 + dlen > len(datagram):
            raise QuicError("truncated first Initial DCID")
        dcid = datagram[off + 6 : off + 6 + dlen]
        csec, ssec = initial_secrets(dcid)
        self.keys_rx[INITIAL] = Keys.from_secret(csec)
        self.keys_tx[INITIAL] = Keys.from_secret(ssec)

    def _rx_keys(self, level: int, _dcid: bytes):
        return self.keys_rx.get(level)

    # -- outbound --

    def send_stream(self, stream_id: int, data: bytes, *,
                    fin: bool = False) -> None:
        if not self.established:
            raise QuicError("stream before handshake completion")
        off = self.send_offset.get(stream_id, 0)
        self.app_out.append(stream_frame(stream_id, off, data, fin))
        self.send_offset[stream_id] = off + len(data)

    def flush(self) -> list[bytes]:
        """Drain pending CRYPTO/app frames into protected datagrams."""
        out: list[bytes] = []
        for lvl in (INITIAL, HANDSHAKE, APPLICATION):
            frames = bytearray()
            pend = self.tls.pending[lvl]
            if pend:
                frames += crypto_frame(self.crypto_sent[lvl], bytes(pend))
                self.crypto_sent[lvl] += len(pend)
                pend.clear()
            if self.largest_rx[lvl] >= 0:
                frames += ack_frame(self.largest_rx[lvl])
                self.largest_rx[lvl] = -1  # ack once
            if lvl == APPLICATION:
                for f in self.app_out:
                    frames += f
                self.app_out.clear()
            if not frames:
                continue
            keys = self.keys_tx.get(lvl)
            if keys is None:
                continue
            payload = bytes(frames)
            if lvl == INITIAL and self.is_client and len(payload) < 1200:
                # §14.1: the whole DATAGRAM must be >= 1200 bytes; padding
                # the payload itself to 1200 clears that with the ~30-byte
                # header + 16-byte tag on top
                payload += bytes(1200 - len(payload))
            pn = self.pn_next[lvl]
            self.pn_next[lvl] += 1
            out.append(seal_packet(
                keys, level=lvl, dcid=self.remote_cid, scid=self.local_cid,
                pn=pn, payload=payload,
            ))
        return out

    def receive_stream_events(self, events: list[StreamEvent]):
        """Reassemble stream events into (stream_id, bytes, fin) chunks
        in order (the tpu_reasm feed).  fin is reported only once every
        byte up to the FIN offset has been delivered — a FIN frame
        arriving ahead of a gap must not finalize a short stream."""
        out = []
        for ev in events:
            st = self.stream_rx.setdefault(ev.stream_id, _OrderedStream())
            if ev.fin:
                st.fin_size = ev.offset + len(ev.data)
            ready = st.insert(ev.offset, ev.data)
            if ready or st.finished:
                out.append((ev.stream_id, ready, st.finished))
        return out
