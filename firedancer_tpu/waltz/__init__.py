"""waltz: networking — QUIC + TLS 1.3 + UDP transports.

Counterpart of /root/reference/src/waltz/: the TPU ingress protocol
stack.  The datagram/stream UDP transports live in runtime/net.py (the
stage layer); this package holds the protocol engines: tls13 (the
fd_tls counterpart) and quic (the fd_quic counterpart).
"""

from . import quic, tls13  # noqa: F401
