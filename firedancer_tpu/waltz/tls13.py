"""Minimal TLS 1.3 handshake engine, purpose-built for QUIC.

Counterpart of /root/reference/src/waltz/tls/fd_tls.c — the reference's
from-scratch "fd_tls" supports exactly what QUIC needs and nothing
else; this engine keeps that profile:

  - cipher suite TLS_AES_128_GCM_SHA256 only
  - key exchange x25519 only (ops/x25519.py)
  - authentication: Ed25519 (ops/ref/ed25519_ref) over RFC 7250-style
    raw public keys — the certificate entry carries the server's
    32-byte Ed25519 public key directly, the profile fd_tls's
    generated X.509 reduces to (intra-cluster peers validate the key
    itself, not a CA chain)
  - no session resumption / 0-RTT / client auth / HelloRetryRequest

The engine is transport-agnostic: QUIC feeds handshake bytes per
encryption level through `consume`, collects outbound bytes from
`pending` per level, and reads traffic secrets from `secrets` as they
become available (RFC 8446 key schedule; RFC 9001 wires them to packet
protection keys).
"""

from __future__ import annotations

import hashlib
import hmac
import os
import struct
from dataclasses import dataclass, field

from firedancer_tpu.ops import x25519
from firedancer_tpu.ops.ref import ed25519_ref

HASH_LEN = 32

# encryption levels (QUIC's names)
INITIAL, HANDSHAKE, APPLICATION = 0, 1, 2

# handshake message types
MT_CLIENT_HELLO = 1
MT_SERVER_HELLO = 2
MT_ENCRYPTED_EXTENSIONS = 8
MT_CERTIFICATE = 11
MT_CERTIFICATE_VERIFY = 15
MT_FINISHED = 20

CIPHER_AES128_GCM_SHA256 = 0x1301
GROUP_X25519 = 0x001D
SIG_ED25519 = 0x0807

EXT_SUPPORTED_GROUPS = 0x000A
EXT_SIGNATURE_ALGS = 0x000D
EXT_SUPPORTED_VERSIONS = 0x002B
EXT_KEY_SHARE = 0x0033
EXT_QUIC_TRANSPORT_PARAMS = 0x0039


class TlsError(RuntimeError):
    pass


# -- HKDF (RFC 5869 / 8446 §7.1) ----------------------------------------------


def hkdf_extract(salt: bytes, ikm: bytes) -> bytes:
    return hmac.new(salt, ikm, hashlib.sha256).digest()


def hkdf_expand(prk: bytes, info: bytes, length: int) -> bytes:
    out = b""
    t = b""
    i = 1
    while len(out) < length:
        t = hmac.new(prk, t + info + bytes([i]), hashlib.sha256).digest()
        out += t
        i += 1
    return out[:length]


def hkdf_expand_label(secret: bytes, label: str, context: bytes,
                      length: int) -> bytes:
    full = b"tls13 " + label.encode()
    info = (
        struct.pack(">H", length)
        + bytes([len(full)]) + full
        + bytes([len(context)]) + context
    )
    return hkdf_expand(secret, info, length)


def derive_secret(secret: bytes, label: str, transcript: bytes) -> bytes:
    return hkdf_expand_label(
        secret, label, hashlib.sha256(transcript).digest(), HASH_LEN
    )


# -- handshake message building/parsing ----------------------------------------


def _u16(v):
    return struct.pack(">H", v)


def _vec8(b):
    return bytes([len(b)]) + b


def _vec16(b):
    return _u16(len(b)) + b


def _vec24(b):
    return len(b).to_bytes(3, "big") + b


def _msg(mt: int, body: bytes) -> bytes:
    return bytes([mt]) + _vec24(body)


def _ext(et: int, body: bytes) -> bytes:
    return _u16(et) + _vec16(body)


def _parse_exts(b: bytes) -> dict[int, bytes]:
    out = {}
    off = 0
    while off < len(b):
        if off + 4 > len(b):
            raise TlsError("truncated extension header")
        et, ln = struct.unpack_from(">HH", b, off)
        off += 4
        if off + ln > len(b):
            raise TlsError("truncated extension body")
        out[et] = b[off : off + ln]
        off += ln
    return out


def build_client_hello(pub: bytes, transport_params: bytes,
                       random: bytes) -> bytes:
    exts = b"".join([
        _ext(EXT_SUPPORTED_VERSIONS, _vec8(_u16(0x0304))),
        _ext(EXT_SUPPORTED_GROUPS, _vec16(_u16(GROUP_X25519))),
        _ext(EXT_SIGNATURE_ALGS, _vec16(_u16(SIG_ED25519))),
        _ext(EXT_KEY_SHARE,
             _vec16(_u16(GROUP_X25519) + _vec16(pub))),
        _ext(EXT_QUIC_TRANSPORT_PARAMS, transport_params),
    ])
    body = (
        _u16(0x0303) + random + _vec8(b"")
        + _vec16(_u16(CIPHER_AES128_GCM_SHA256)) + _vec8(b"\x00")
        + _vec16(exts)
    )
    return _msg(MT_CLIENT_HELLO, body)


def build_server_hello(pub: bytes, random: bytes) -> bytes:
    exts = b"".join([
        _ext(EXT_SUPPORTED_VERSIONS, _u16(0x0304)),
        _ext(EXT_KEY_SHARE, _u16(GROUP_X25519) + _vec16(pub)),
    ])
    body = (
        _u16(0x0303) + random + _vec8(b"")
        + _u16(CIPHER_AES128_GCM_SHA256) + b"\x00"
        + _vec16(exts)
    )
    return _msg(MT_SERVER_HELLO, body)


@dataclass
class _Hello:
    random: bytes
    key_share: bytes
    transport_params: bytes | None


def _parse_hello(body: bytes, *, client: bool) -> _Hello:
    off = 0
    if len(body) < 2 + 32:
        raise TlsError("short hello")
    off += 2
    random = body[off : off + 32]
    off += 32
    sid_len = body[off]
    off += 1 + sid_len
    if client:
        cs_len = struct.unpack_from(">H", body, off)[0]
        suites = body[off + 2 : off + 2 + cs_len]
        if _u16(CIPHER_AES128_GCM_SHA256) not in [
            suites[i : i + 2] for i in range(0, len(suites), 2)
        ]:
            raise TlsError("no common cipher suite")
        off += 2 + cs_len
        comp_len = body[off]
        off += 1 + comp_len
    else:
        off += 2  # selected cipher
        off += 1  # compression
    ext_len = struct.unpack_from(">H", body, off)[0]
    off += 2
    exts = _parse_exts(body[off : off + ext_len])
    ks = exts.get(EXT_KEY_SHARE)
    if ks is None:
        raise TlsError("missing key_share")
    if client:
        # ClientHello: vector of shares
        total = struct.unpack_from(">H", ks, 0)[0]
        p = 2
        share = None
        while p < 2 + total:
            grp, ln = struct.unpack_from(">HH", ks, p)
            p += 4
            if grp == GROUP_X25519:
                share = ks[p : p + ln]
            p += ln
        if share is None:
            raise TlsError("no x25519 key share")
    else:
        grp, ln = struct.unpack_from(">HH", ks, 0)
        if grp != GROUP_X25519:
            raise TlsError("server chose a different group")
        share = ks[4 : 4 + ln]
    if len(share) != 32:
        raise TlsError("bad x25519 share length")
    return _Hello(random, share, exts.get(EXT_QUIC_TRANSPORT_PARAMS))


_CERT_CONTEXT_SERVER = (
    b" " * 64 + b"TLS 1.3, server CertificateVerify" + b"\x00"
)


def _finished_mac(base_secret: bytes, transcript_hash: bytes) -> bytes:
    fk = hkdf_expand_label(base_secret, "finished", b"", HASH_LEN)
    return hmac.new(fk, transcript_hash, hashlib.sha256).digest()


# -- the engine -----------------------------------------------------------------


@dataclass
class Endpoint:
    """One side of the handshake.  Use `client(...)` / `server(...)`.

    Interface to QUIC:
      pending[level]      outbound handshake bytes to ship in CRYPTO frames
      consume(level, b)   inbound CRYPTO bytes (whole messages accumulate)
      secrets[level]      (client_secret, server_secret) once derived
      complete            True when Finished has been verified both ways
      peer_pubkey         server's raw Ed25519 key (client side, after cert)
    """

    is_client: bool
    identity_secret: bytes | None = None  # server: ed25519 signing key
    transport_params: bytes = b""
    expected_peer: bytes | None = None  # client: pin the server key
    rng: object = None

    def __post_init__(self):
        rnd = self.rng or os.urandom
        self._x_secret = rnd(32)
        self._x_public = x25519.public_key(self._x_secret)
        self.pending: dict[int, bytearray] = {
            INITIAL: bytearray(), HANDSHAKE: bytearray(),
            APPLICATION: bytearray(),
        }
        self._inbuf: dict[int, bytearray] = {
            INITIAL: bytearray(), HANDSHAKE: bytearray(),
            APPLICATION: bytearray(),
        }
        self.secrets: dict[int, tuple[bytes, bytes]] = {}
        self.complete = False
        self.peer_pubkey: bytes | None = None
        self._transcript = b""
        self._hs_secret = None
        self._master = None
        self._server_hs_done_transcript = None
        self.peer_transport_params: bytes | None = None
        self._random = rnd(32)
        if self.is_client:
            ch = build_client_hello(
                self._x_public, self.transport_params, self._random
            )
            self._transcript += ch
            self.pending[INITIAL] += ch

    # -- key schedule helpers --

    def _derive_handshake(self, shared: bytes):
        early = hkdf_extract(bytes(HASH_LEN), bytes(HASH_LEN))
        derived = derive_secret(early, "derived", b"")
        self._hs_secret = hkdf_extract(derived, shared)
        th = self._transcript
        c = derive_secret(self._hs_secret, "c hs traffic", th)
        s = derive_secret(self._hs_secret, "s hs traffic", th)
        self.secrets[HANDSHAKE] = (c, s)

    def _derive_application(self):
        derived = derive_secret(self._hs_secret, "derived", b"")
        self._master = hkdf_extract(derived, bytes(HASH_LEN))
        th = self._server_hs_done_transcript
        c = derive_secret(self._master, "c ap traffic", th)
        s = derive_secret(self._master, "s ap traffic", th)
        self.secrets[APPLICATION] = (c, s)

    # -- message pump --

    def consume(self, level: int, data: bytes) -> None:
        buf = self._inbuf[level]
        buf += data
        while len(buf) >= 4:
            mt = buf[0]
            ln = int.from_bytes(buf[1:4], "big")
            if len(buf) < 4 + ln:
                return
            msg = bytes(buf[: 4 + ln])
            del buf[: 4 + ln]
            self._handle(level, mt, msg)

    def _handle(self, level: int, mt: int, msg: bytes) -> None:
        body = msg[4:]
        if self.is_client:
            self._handle_client(level, mt, msg, body)
        else:
            self._handle_server(level, mt, msg, body)

    # -- server side --

    def _handle_server(self, level, mt, msg, body):
        if mt == MT_CLIENT_HELLO and level == INITIAL:
            hello = _parse_hello(body, client=True)
            self.peer_transport_params = hello.transport_params
            self._transcript += msg
            sh = build_server_hello(self._x_public, self._random)
            self._transcript += sh
            self.pending[INITIAL] += sh
            shared = x25519.shared_secret(self._x_secret, hello.key_share)
            self._derive_handshake(shared)
            # EncryptedExtensions (carries our transport params)
            ee = _msg(MT_ENCRYPTED_EXTENSIONS, _vec16(
                _ext(EXT_QUIC_TRANSPORT_PARAMS, self.transport_params)
            ))
            self._transcript += ee
            # Certificate: one raw-public-key entry
            if self.identity_secret is None:
                raise TlsError("server needs an identity key")
            ident_pub = ed25519_ref.public_key(self.identity_secret)
            cert = _msg(MT_CERTIFICATE, _vec8(b"") + _vec24(
                _vec24(ident_pub) + _vec16(b"")
            ))
            self._transcript += cert
            # CertificateVerify over the transcript so far
            tosign = _CERT_CONTEXT_SERVER + hashlib.sha256(
                self._transcript
            ).digest()
            sig = ed25519_ref.sign(self.identity_secret, tosign)
            cv = _msg(MT_CERTIFICATE_VERIFY, _u16(SIG_ED25519) + _vec16(sig))
            self._transcript += cv
            # server Finished
            fin_mac = _finished_mac(
                self.secrets[HANDSHAKE][1],
                hashlib.sha256(self._transcript).digest(),
            )
            fin = _msg(MT_FINISHED, fin_mac)
            self._transcript += fin
            self._server_hs_done_transcript = self._transcript
            self.pending[HANDSHAKE] += ee + cert + cv + fin
            self._derive_application()
        elif mt == MT_FINISHED and level == HANDSHAKE:
            want = _finished_mac(
                self.secrets[HANDSHAKE][0],
                hashlib.sha256(self._transcript).digest(),
            )
            if not hmac.compare_digest(want, body):
                raise TlsError("client Finished MAC mismatch")
            self._transcript += msg
            self.complete = True
        else:
            raise TlsError(f"unexpected message {mt} at level {level}")

    # -- client side --

    def _handle_client(self, level, mt, msg, body):
        if mt == MT_SERVER_HELLO and level == INITIAL:
            hello = _parse_hello(body, client=False)
            self._transcript += msg
            shared = x25519.shared_secret(self._x_secret, hello.key_share)
            self._derive_handshake(shared)
        elif mt == MT_ENCRYPTED_EXTENSIONS and level == HANDSHAKE:
            exts = _parse_exts(body[2:])
            self.peer_transport_params = exts.get(EXT_QUIC_TRANSPORT_PARAMS)
            self._transcript += msg
        elif mt == MT_CERTIFICATE and level == HANDSHAKE:
            # context (1B len) then cert list; first entry = raw pubkey
            off = 1 + body[0]
            if off + 3 > len(body):
                raise TlsError("short certificate list")
            off += 3  # list length
            if off + 3 > len(body):
                raise TlsError("empty certificate list")
            ln = int.from_bytes(body[off : off + 3], "big")
            off += 3
            cert = body[off : off + ln]
            if len(cert) != 32:
                raise TlsError("expected a raw 32-byte Ed25519 key")
            if self.expected_peer is not None and cert != self.expected_peer:
                raise TlsError("server key does not match the pinned key")
            self.peer_pubkey = cert
            self._transcript += msg
        elif mt == MT_CERTIFICATE_VERIFY and level == HANDSHAKE:
            alg = struct.unpack_from(">H", body, 0)[0]
            if alg != SIG_ED25519:
                raise TlsError("unexpected signature algorithm")
            sig_len = struct.unpack_from(">H", body, 2)[0]
            sig = body[4 : 4 + sig_len]
            tosign = _CERT_CONTEXT_SERVER + hashlib.sha256(
                self._transcript
            ).digest()
            if self.peer_pubkey is None or not ed25519_ref.verify(
                tosign, sig, self.peer_pubkey
            ):
                raise TlsError("CertificateVerify signature invalid")
            self._transcript += msg
        elif mt == MT_FINISHED and level == HANDSHAKE:
            want = _finished_mac(
                self.secrets[HANDSHAKE][1],
                hashlib.sha256(self._transcript).digest(),
            )
            if not hmac.compare_digest(want, body):
                raise TlsError("server Finished MAC mismatch")
            self._transcript += msg
            self._server_hs_done_transcript = self._transcript
            self._derive_application()
            # client Finished
            fin_mac = _finished_mac(
                self.secrets[HANDSHAKE][0],
                hashlib.sha256(self._transcript).digest(),
            )
            fin = _msg(MT_FINISHED, fin_mac)
            self._transcript += fin
            self.pending[HANDSHAKE] += fin
            self.complete = True
        else:
            raise TlsError(f"unexpected message {mt} at level {level}")


def client(*, transport_params: bytes = b"", expected_peer: bytes | None = None,
           rng=None) -> Endpoint:
    return Endpoint(True, transport_params=transport_params,
                    expected_peer=expected_peer, rng=rng)


def server(identity_secret: bytes, *, transport_params: bytes = b"",
           rng=None) -> Endpoint:
    return Endpoint(False, identity_secret=identity_secret,
                    transport_params=transport_params, rng=rng)
