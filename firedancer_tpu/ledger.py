"""Ledger tool: ingest shred captures, inspect, and replay a stored
ledger through the full runtime.

Capability parity with the reference's ledger binary
(/root/reference/src/app/ledger/ — drives the runtime against stored
ledgers, verifying bank hashes slot by slot; its test harness
run_ledger_test.sh compares replay results against recorded expected
hashes; no code shared).  The TPU build's ledger lives in the
file-backed Blockstore (flamenco/blockstore.py); captures come from
shredcap (flamenco/shredcap.py).

Replay walks complete slots in ascending order: deshred the slot's
entry batch, re-verify the PoH chain, execute every transaction on a
funk fork, chain bank hashes parent-to-child.  `--record` writes the
per-slot bank hashes to a JSON expectation file; `--check` replays and
diffs against one — the regression harness shape.
"""

from __future__ import annotations

import json
from dataclasses import dataclass

from firedancer_tpu.flamenco.blockstore import Blockstore
from firedancer_tpu.funk.funk import Funk
from firedancer_tpu.funk import make_funk


@dataclass
class SlotReplay:
    slot: int
    ok: bool
    bank_hash: bytes | None
    txn_cnt: int
    err: str = ""


def ingest_capture(store_dir: str, capture: str) -> int:
    """shredcap/pcap -> blockstore; returns shreds inserted."""
    from firedancer_tpu.flamenco import shredcap

    bs = Blockstore(store_dir)
    try:
        n = shredcap.replay(capture, bs.insert_shred)
    finally:
        bs.close()
    return n


def inventory(store_dir: str) -> list[dict]:
    bs = Blockstore(store_dir)
    try:
        out = []
        for slot in bs.slots():
            m = bs.slot_meta(slot)
            out.append({
                "slot": slot,
                "complete": m.complete,
                "received": len(m.received),
                "last_index": m.last_index,
                "missing": m.missing()[:8],
            })
        return out
    finally:
        bs.close()


def replay_ledger(
    store_dir: str,
    *,
    funk: Funk | None = None,
    poh_seed: bytes = b"\x00" * 32,
    publish: bool = True,
    stop_on_error: bool = False,
) -> list[SlotReplay]:
    """Replay every complete slot ascending; chain PoH seed and bank
    hash across slots (the replay-tile walk, offline)."""
    from firedancer_tpu.flamenco import runtime as rt
    from firedancer_tpu.runtime.poh_stage import parse_entry
    from firedancer_tpu.runtime.shred_stage import deshred_entry_batch

    funk = funk if funk is not None else make_funk()
    bs = Blockstore(store_dir)
    results: list[SlotReplay] = []
    parent_hash = b"\x00" * 32
    seed = poh_seed
    try:
        for slot in bs.slots():
            if not bs.is_complete(slot):
                continue
            try:
                frames = deshred_entry_batch(bs.entry_batch_bytes(slot))
                entries = [parse_entry(f) for f in frames]
            except Exception as e:
                results.append(SlotReplay(slot, False, None, 0,
                                          f"deshred: {type(e).__name__}"))
                if stop_on_error:
                    break
                continue
            n_txn = sum(len(t) for _n, _h, t in entries)
            res = rt.replay_block(
                funk, slot=slot, entries=entries, poh_seed=seed,
                parent_bank_hash=parent_hash, publish=publish,
            )
            if res is None:
                results.append(SlotReplay(slot, False, None, n_txn,
                                          "poh chain invalid"))
                if stop_on_error:
                    break
                continue
            results.append(SlotReplay(slot, True, res.bank_hash, n_txn))
            parent_hash = res.bank_hash
            if entries:
                seed = entries[-1][1]
    finally:
        bs.close()
    return results


def record_expectations(results: list[SlotReplay], path: str) -> None:
    with open(path, "w") as f:
        json.dump(
            {str(r.slot): r.bank_hash.hex() for r in results if r.ok}, f,
            indent=0, sort_keys=True,
        )


def check_expectations(results: list[SlotReplay], path: str) -> list[str]:
    """-> list of mismatch descriptions (empty = pass)."""
    with open(path) as f:
        want = json.load(f)
    got = {str(r.slot): r.bank_hash.hex() if r.ok else f"ERR:{r.err}"
           for r in results}
    problems = []
    for slot, h in sorted(want.items(), key=lambda kv: int(kv[0])):
        g = got.get(slot)
        if g is None:
            problems.append(f"slot {slot}: missing from replay")
        elif g != h:
            problems.append(f"slot {slot}: bank hash {g[:16]} != {h[:16]}")
    return problems


def main(args) -> int:
    if args.action == "show":
        for row in inventory(args.store):
            state = "complete" if row["complete"] else (
                f"missing {row['missing']}")
            print(f"slot {row['slot']}: {row['received']} shreds, "
                  f"last_index={row['last_index']}, {state}")
        return 0
    if args.action == "ingest":
        n = ingest_capture(args.store, args.capture)
        print(f"ingested {n} shreds into {args.store}")
        return 0
    if args.action == "replay":
        funk = None
        if args.funk_dir:
            from firedancer_tpu.funk.persist import PersistentFunk

            funk = PersistentFunk(args.funk_dir)
        seed = bytes.fromhex(args.poh_seed) if args.poh_seed else b"\x00" * 32
        results = replay_ledger(
            args.store, funk=funk, poh_seed=seed,
            stop_on_error=args.check is not None,
        )
        for r in results:
            tag = r.bank_hash.hex()[:16] if r.ok else f"FAILED ({r.err})"
            print(f"slot {r.slot}: {r.txn_cnt} txns, bank hash {tag}")
        if args.record:
            record_expectations(results, args.record)
            print(f"recorded {sum(r.ok for r in results)} expectations")
        rc = 0 if all(r.ok for r in results) else 1
        if args.check:
            problems = check_expectations(results, args.check)
            for pr in problems:
                print(f"MISMATCH {pr}")
            rc = rc or (1 if problems else 0)
            if not problems:
                print(f"all {len(results)} slots match expectations")
        return rc
    return 2
