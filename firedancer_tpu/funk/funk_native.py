"""ctypes binding for the native shm storage plane (native/fd_funk.cpp).

`NativeFunk` is the Python lane's thin view over the shared-memory
record map (ISSUE 19): the exact `funk/funk.py` API — fork-tree
prepare/publish/cancel with frozen/ancestry semantics, overlay queries,
tombstones, FunkError codes -1/-2/-3 — but every record lives inside
ONE shm segment that `native/fd_bank.cpp` writes into directly from its
sweep crossing.  Reads come back through a zero-copy memoryview over
the mapping; Python-lane batch writes cross the FFI once per batch
(`rec_insert_batch` / `_root_merge`), and the seal path's whole
before/after read-out is one `txn_diff` crossing.

This is a SEPARATE class, not a replacement of `Funk`:
`funk/persist.py`'s WAL journaling subclasses the dict-backed store and
stays on it.  `make_funk()` (funk/__init__.py) is the construction
funnel the topology builders use — native when the lane is enabled and
the toolchain builds the .so, dict-backed otherwise.

`FDTPU_NATIVE_FUNK=0` disables the lane; a missing toolchain degrades
to the Python store via NativeUnavailable.  Differential parity with
funk.py is the contract (tests/test_funk_native.py).

Because the map lives in shm under a public name (`shm_name`), an
uninvolved process can `attach_readonly()` the same store and observe a
seqlock-consistent view — the seed of the read-replica plane
(docs/OPERATIONS.md "Native funk plane").
"""

from __future__ import annotations

import ctypes
import os
import struct

from firedancer_tpu.utils.nativebuild import NativeUnavailable, build_so

from .funk import ERR_FROZEN, ERR_KEY, ERR_TXN, FunkError

_SRC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
    "fd_funk.cpp",
)
_SO = os.path.join(os.path.dirname(_SRC), "fd_funk.so")

ENV_SWITCH = "FDTPU_NATIVE_FUNK"

# error codes beyond the funk.py trio (fd_funk.cpp enum)
_ERR_FULL = -4
_ERR_OOM = -5
_ERR_RDONLY = -6
_ERR_RANGE = -7

_XID_MAX = 128  # FFK_XID_MAX

_DEFAULT_SZ = 1 << 28  # 256 MiB virtual; pages commit lazily
_DEFAULT_TXN_CAP = 1024

_lib = None


def _load():
    global _lib
    if _lib is None:
        lib = ctypes.CDLL(build_so(_SRC, _SO))
        u64 = ctypes.c_uint64
        i64 = ctypes.c_int64
        i32 = ctypes.c_int32
        vp = ctypes.c_void_p
        cp = ctypes.c_char_p
        u64p = ctypes.POINTER(ctypes.c_uint64)
        i64p = ctypes.POINTER(ctypes.c_int64)
        lib.ffk_create.argtypes = [cp, u64, i32]
        lib.ffk_create.restype = vp
        lib.ffk_attach.argtypes = [cp]
        lib.ffk_attach.restype = vp
        lib.ffk_close.argtypes = [vp, i32]
        lib.ffk_shm_name.argtypes = [vp]
        lib.ffk_shm_name.restype = cp
        for name in ("ffk_base", "ffk_map_sz", "ffk_seq", "ffk_arena_used"):
            getattr(lib, name).argtypes = [vp]
            getattr(lib, name).restype = u64
        lib.ffk_txn_prepare.argtypes = [vp, cp, i32, cp, i32]
        lib.ffk_txn_prepare.restype = i32
        for name in ("ffk_txn_is_frozen", "ffk_txn_wcheck", "ffk_txn_cancel",
                     "ffk_txn_publish", "ffk_txn_slot"):
            getattr(lib, name).argtypes = [vp, cp, i32]
            getattr(lib, name).restype = i32
        lib.ffk_txn_cnt.argtypes = [vp]
        lib.ffk_txn_cnt.restype = i32
        lib.ffk_txn_ancestry.argtypes = [vp, cp, i32, cp, i64]
        lib.ffk_txn_ancestry.restype = i64
        lib.ffk_last_publish.argtypes = [vp, cp, i32]
        lib.ffk_last_publish.restype = i32
        lib.ffk_rec_insert.argtypes = [vp, cp, i32, cp, i32, cp, i32]
        lib.ffk_rec_insert.restype = i32
        lib.ffk_rec_insert_slot.argtypes = [vp, i32, cp, i32, cp, i32]
        lib.ffk_rec_insert_slot.restype = i32
        lib.ffk_rec_remove.argtypes = [vp, cp, i32, cp, i32]
        lib.ffk_rec_remove.restype = i32
        lib.ffk_rec_query.argtypes = [vp, cp, i32, cp, i32, u64p, i64p]
        lib.ffk_rec_query.restype = i32
        lib.ffk_rec_cnt_root.argtypes = [vp]
        lib.ffk_rec_cnt_root.restype = i64
        lib.ffk_root_keys.argtypes = [vp, cp, i64]
        lib.ffk_root_keys.restype = i64
        lib.ffk_txn_keys.argtypes = [vp, cp, i32, cp, i64]
        lib.ffk_txn_keys.restype = i64
        lib.ffk_txn_diff.argtypes = [vp, cp, i32, cp, i64]
        lib.ffk_txn_diff.restype = i64
        lib.ffk_batch_apply.argtypes = [vp, cp, i32, cp, i64, i32]
        lib.ffk_batch_apply.restype = i32
        _lib = lib
    return _lib


def enabled() -> bool:
    """The env switch: FDTPU_NATIVE_FUNK=0 forces the dict-backed lane."""
    return os.environ.get(ENV_SWITCH, "1") != "0"


def available() -> bool:
    """enabled AND the .so loads (toolchain-less hosts degrade to the
    Python store gracefully)."""
    if not enabled():
        return False
    try:
        _load()
        return True
    except (NativeUnavailable, OSError, AttributeError):
        return False


def _raise(rc: int, what: str) -> None:
    if rc == ERR_TXN:
        raise FunkError(ERR_TXN, f"{what}: unknown/duplicate txn")
    if rc == ERR_FROZEN:
        raise FunkError(ERR_FROZEN, "txn has children; records frozen")
    if rc == ERR_KEY:
        raise FunkError(ERR_KEY, f"{what}: unknown key")
    if rc == _ERR_OOM:
        raise MemoryError(f"native funk arena exhausted ({what})")
    raise RuntimeError(f"native funk {what} failed: rc={rc}")


class _RecsProxy:
    """Write-through stand-in for `Funk.txn_recs_for_write`'s dict: the
    ancestry/frozen check ran once at acquisition; each __setitem__ is
    one insert into the shm overlay.  Batch writers should prefer
    NativeFunk.rec_insert_batch (one crossing for the whole batch)."""

    __slots__ = ("_f", "_slot")

    def __init__(self, f: "NativeFunk", slot: int):
        self._f = f
        self._slot = slot

    def __setitem__(self, key: bytes, val: bytes) -> None:
        rc = self._f._lib.ffk_rec_insert_slot(
            self._f._h, self._slot, bytes(key), len(key), bytes(val),
            len(val))
        if rc != 0:
            _raise(rc, "rec_insert")

    def update(self, items) -> None:
        for k, v in (items.items() if hasattr(items, "items") else items):
            self[k] = v


class NativeFunk:
    """The funk API over the native shm record map.  One authoritative
    store for both lanes: the bank sweep writes records in C inside its
    crossing; this class is the Python lane's batched-write + zero-copy
    read surface over the same segment."""

    def __init__(self, *, shm_name: str | None = None,
                 max_sz: int = _DEFAULT_SZ,
                 txn_cap: int = _DEFAULT_TXN_CAP):
        lib = _load()
        self._lib = lib
        self._h = lib.ffk_create(
            shm_name.encode() if shm_name else None, max_sz, txn_cap)
        if not self._h:
            raise NativeUnavailable("ffk_create failed")
        self._owns = True
        self._init_views()
        # cached out-cells for rec_query (no per-call ctypes churn)
        self._voff = ctypes.c_uint64(0)
        self._vlen = ctypes.c_int64(0)
        self._voff_ref = ctypes.byref(self._voff)
        self._vlen_ref = ctypes.byref(self._vlen)

    def _init_views(self) -> None:
        base = int(self._lib.ffk_base(self._h))
        sz = int(self._lib.ffk_map_sz(self._h))
        self._map = memoryview(
            (ctypes.c_uint8 * sz).from_address(base)).cast("B")

    @classmethod
    def attach_readonly(cls, shm_name: str) -> "NativeFunk":
        """Read-only attach from an uninvolved process (the metrics /
        read-replica shape).  Mutating calls raise RuntimeError."""
        lib = _load()
        self = cls.__new__(cls)
        self._lib = lib
        self._h = lib.ffk_attach(shm_name.encode())
        if not self._h:
            raise NativeUnavailable(f"ffk_attach({shm_name!r}) failed")
        self._owns = False
        self._init_views()
        self._voff = ctypes.c_uint64(0)
        self._vlen = ctypes.c_int64(0)
        self._voff_ref = ctypes.byref(self._voff)
        self._vlen_ref = ctypes.byref(self._vlen)
        return self

    # -- identity / shm surface ----------------------------------------------

    @property
    def shm_name(self) -> str:
        return self._lib.ffk_shm_name(self._h).decode()

    @property
    def handle(self) -> int:
        """The raw ffk handle fd_bank.cpp's set_funk crossing receives."""
        return int(self._h)

    def seq(self) -> int:
        return int(self._lib.ffk_seq(self._h))

    def arena_used(self) -> int:
        return int(self._lib.ffk_arena_used(self._h))

    # -- fork tree ------------------------------------------------------------

    def txn_prepare(self, parent: bytes | None, xid: bytes) -> bytes:
        if parent is None:
            rc = self._lib.ffk_txn_prepare(self._h, None, -1, bytes(xid),
                                           len(xid))
        else:
            rc = self._lib.ffk_txn_prepare(self._h, bytes(parent),
                                           len(parent), bytes(xid), len(xid))
        if rc != 0:
            _raise(rc, "txn_prepare")
        return xid

    def txn_is_frozen(self, xid: bytes) -> bool:
        rc = self._lib.ffk_txn_is_frozen(self._h, bytes(xid), len(xid))
        if rc < 0:
            _raise(rc, "txn_is_frozen")
        return bool(rc)

    def txn_cnt(self) -> int:
        return int(self._lib.ffk_txn_cnt(self._h))

    def txn_ancestry(self, xid: bytes) -> list[bytes]:
        lib = self._lib
        need = int(lib.ffk_txn_ancestry(self._h, bytes(xid), len(xid),
                                        None, 0))
        if need < 0:
            _raise(need, "txn_ancestry")
        buf = ctypes.create_string_buffer(need or 1)
        n = int(lib.ffk_txn_ancestry(self._h, bytes(xid), len(xid), buf,
                                     need))
        if n < 0:
            _raise(n, "txn_ancestry")
        out, p = [], 0
        raw = buf.raw[:n]
        while p < n:
            ln = raw[p] | (raw[p + 1] << 8)
            out.append(raw[p + 2: p + 2 + ln])
            p += 2 + ln
        return out

    def txn_cancel(self, xid: bytes) -> int:
        rc = self._lib.ffk_txn_cancel(self._h, bytes(xid), len(xid))
        if rc < 0:
            _raise(rc, "txn_cancel")
        return int(rc)

    def txn_publish(self, xid: bytes) -> int:
        rc = self._lib.ffk_txn_publish(self._h, bytes(xid), len(xid))
        if rc < 0:
            _raise(rc, "txn_publish")
        return int(rc)

    @property
    def last_publish(self) -> bytes | None:
        buf = ctypes.create_string_buffer(_XID_MAX)
        n = int(self._lib.ffk_last_publish(self._h, buf, _XID_MAX))
        if n <= 0:
            return None
        return buf.raw[:n]

    # -- records --------------------------------------------------------------

    def rec_insert(self, xid: bytes | None, key: bytes, val: bytes) -> None:
        if xid is None:
            rc = self._lib.ffk_rec_insert(self._h, None, -1, bytes(key),
                                          len(key), bytes(val), len(val))
        else:
            rc = self._lib.ffk_rec_insert(self._h, bytes(xid), len(xid),
                                          bytes(key), len(key), bytes(val),
                                          len(val))
        if rc != 0:
            _raise(rc, "rec_insert")

    def txn_recs_for_write(self, xid: bytes) -> _RecsProxy:
        slot = int(self._lib.ffk_txn_slot(self._h, bytes(xid), len(xid)))
        if slot < 0:
            _raise(slot, "txn_recs_for_write")
        return _RecsProxy(self, slot)

    def rec_insert_batch(self, xid: bytes | None, items) -> None:
        """One FFI crossing for a batch of (key, val-or-None) writes —
        the Python lane's hot write shape (None = tombstone/delete)."""
        parts = []
        n = 0
        for key, val in (items.items() if hasattr(items, "items")
                         else items):
            if val is None:
                parts.append(struct.pack("<Hi", len(key), -1))
                parts.append(bytes(key))
            else:
                parts.append(struct.pack("<Hi", len(key), len(val)))
                parts.append(bytes(key))
                parts.append(bytes(val))
            n += 1
        if not n:
            return
        blob = b"".join(parts)
        if xid is None:
            rc = self._lib.ffk_batch_apply(self._h, None, -1, blob,
                                           len(blob), n)
        else:
            rc = self._lib.ffk_batch_apply(self._h, bytes(xid), len(xid),
                                           blob, len(blob), n)
        if rc != 0:
            _raise(rc, "batch_apply")

    def rec_remove(self, xid: bytes | None, key: bytes) -> None:
        if xid is None:
            rc = self._lib.ffk_rec_remove(self._h, None, -1, bytes(key),
                                          len(key))
        else:
            rc = self._lib.ffk_rec_remove(self._h, bytes(xid), len(xid),
                                          bytes(key), len(key))
        if rc != 0:
            _raise(rc, "rec_remove")

    def rec_query(self, xid: bytes | None, key: bytes) -> bytes | None:
        rc = self._query(xid, key)
        if rc == 0:
            return None
        off = self._voff.value
        ln = self._vlen.value
        return bytes(self._map[off: off + ln]) if ln > 0 else b""

    def rec_query_view(self, xid: bytes | None,
                       key: bytes) -> memoryview | None:
        """Zero-copy read: a memoryview into the shm mapping.  Valid
        until the record is overwritten/published — consume before the
        next store mutation."""
        rc = self._query(xid, key)
        if rc == 0:
            return None
        off = self._voff.value
        ln = self._vlen.value
        return self._map[off: off + ln]

    def _query(self, xid: bytes | None, key: bytes) -> int:
        if xid is None:
            rc = self._lib.ffk_rec_query(self._h, None, -1, bytes(key),
                                         len(key), self._voff_ref,
                                         self._vlen_ref)
        else:
            rc = self._lib.ffk_rec_query(self._h, bytes(xid), len(xid),
                                         bytes(key), len(key),
                                         self._voff_ref, self._vlen_ref)
        if rc < 0:
            _raise(rc, "rec_query")
        return rc

    def rec_cnt_root(self) -> int:
        return int(self._lib.ffk_rec_cnt_root(self._h))

    def rec_keys(self, xid: bytes | None) -> list[bytes]:
        keys = set(self._root_keys())
        if xid is not None:
            for t_xid in self.txn_ancestry(xid):  # oldest -> newest
                for key, tomb in self._txn_keys(t_xid):
                    if tomb:
                        keys.discard(key)
                    else:
                        keys.add(key)
        return list(keys)

    def txn_diff(self, xid: bytes) -> list[tuple[bytes, bytes | None,
                                                 bytes | None]]:
        """The seal read-out in ONE crossing: [(key, before, after)] for
        every key in xid's own overlay, before = the parent view's value
        (start-of-slot), after = the overlay's (None = absent/tombstone)."""
        lib = self._lib
        bx = bytes(xid)
        need = int(lib.ffk_txn_diff(self._h, bx, len(bx), None, 0))
        if need < 0:
            _raise(need, "txn_diff")
        buf = ctypes.create_string_buffer(need or 1)
        n = int(lib.ffk_txn_diff(self._h, bx, len(bx), buf, need))
        if n < 0:
            _raise(n, "txn_diff")
        raw = buf.raw[:n]
        out = []
        p = 0
        while p < n:
            klen, blen, alen = struct.unpack_from("<Hqq", raw, p)
            p += 18
            key = raw[p: p + klen]
            p += klen
            before = None
            after = None
            if blen >= 0:
                before = raw[p: p + blen]
                p += blen
            if alen >= 0:
                after = raw[p: p + alen]
                p += alen
            out.append((key, before, after))
        return out

    # -- root iteration / merge funnel ----------------------------------------

    def _root_keys(self) -> list[bytes]:
        lib = self._lib
        need = int(lib.ffk_root_keys(self._h, None, 0))
        if need < 0:
            _raise(need, "root_keys")
        buf = ctypes.create_string_buffer(need or 1)
        n = int(lib.ffk_root_keys(self._h, buf, need))
        if n < 0:
            _raise(n, "root_keys")
        raw = buf.raw[:n]
        out, p = [], 0
        while p < n:
            ln = raw[p] | (raw[p + 1] << 8)
            out.append(raw[p + 2: p + 2 + ln])
            p += 2 + ln
        return out

    def _txn_keys(self, xid: bytes) -> list[tuple[bytes, bool]]:
        lib = self._lib
        bx = bytes(xid)
        need = int(lib.ffk_txn_keys(self._h, bx, len(bx), None, 0))
        if need < 0:
            _raise(need, "txn_keys")
        buf = ctypes.create_string_buffer(need or 1)
        n = int(lib.ffk_txn_keys(self._h, bx, len(bx), buf, need))
        if n < 0:
            _raise(n, "txn_keys")
        raw = buf.raw[:n]
        out, p = [], 0
        while p < n:
            ln = raw[p] | (raw[p + 1] << 8)
            tomb = bool(raw[p + 2])
            out.append((raw[p + 3: p + 3 + ln], tomb))
            p += 3 + ln
        return out

    @property
    def _root(self) -> dict[bytes, bytes]:
        """Dict view of the root store (the snapshot writer's iteration
        surface, utils/checkpt.funk_checkpt).  A COPY: cold-path only."""
        return {k: self.rec_query(None, k) for k in self._root_keys()}

    def _root_merge(self, items) -> None:
        """The single root-write funnel, one crossing per batch
        (None value = delete) — funk.py's contract, batched."""
        self.rec_insert_batch(None, items)

    # -- lifecycle ------------------------------------------------------------

    def close(self) -> None:
        if getattr(self, "_h", None):
            self._map = None
            self._lib.ffk_close(self._h, 1 if self._owns else 0)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
