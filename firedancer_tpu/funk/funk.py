"""funk: the fork-aware record database (accounts DB).

Behavioral port of /root/reference/src/funk/fd_funk.h (fd_funk_txn.c fork
tree, fd_funk_rec.c records): a flat key->value root store plus a tree of
in-preparation *transactions* — speculative overlays matching Solana's
bank-fork semantics:

  - txn_prepare(parent, xid): start a child fork off root or another
    in-prep txn.  A txn with children is FROZEN: its records can no
    longer change (children may be speculating off them,
    fd_funk_txn.h "frozen" discussion);
  - queries read through the overlay chain: nearest ancestor's version
    wins; a removal in a descendant is a tombstone hiding the ancestor /
    root version;
  - txn_publish(xid): the fork wins — its ancestor chain is merged into
    root oldest-first, and every competing sibling fork of each published
    ancestor is cancelled (fd_funk_txn_publish);
  - txn_cancel(xid): the fork loses — it and all descendants are
    discarded.

The reference implements this as wksp-backed index-compressed maps so the
whole DB is shared-memory-relocatable across processes; this build keeps
the same API surface and fork semantics over host dicts (the runtime's
accounts access pattern, not the allocator, is the capability under test
at this stage; values are bytes and the store is process-local).
"""

from __future__ import annotations

from dataclasses import dataclass, field

ERR_TXN = -1     # unknown / already published-or-cancelled txn
ERR_FROZEN = -2  # txn has children; records immutable
ERR_KEY = -3     # unknown key


class FunkError(RuntimeError):
    def __init__(self, code: int, msg: str):
        super().__init__(msg)
        self.code = code


_TOMBSTONE = object()


@dataclass
class _Txn:
    xid: bytes
    parent: bytes | None  # None = child of root
    children: set = field(default_factory=set)
    recs: dict = field(default_factory=dict)  # key -> bytes | _TOMBSTONE


class Funk:
    def __init__(self):
        self._root: dict[bytes, bytes] = {}
        self._txns: dict[bytes, _Txn] = {}
        self.last_publish: bytes | None = None

    # -- fork tree ----------------------------------------------------------

    def txn_prepare(self, parent: bytes | None, xid: bytes) -> bytes:
        """Begin a new in-prep txn forked off `parent` (None = root)."""
        if xid in self._txns:
            raise FunkError(ERR_TXN, f"xid {xid!r} already in prep")
        if parent is not None:
            p = self._txns.get(parent)
            if p is None:
                raise FunkError(ERR_TXN, f"unknown parent {parent!r}")
            p.children.add(xid)
        self._txns[xid] = _Txn(xid=xid, parent=parent)
        return xid

    def txn_is_frozen(self, xid: bytes) -> bool:
        return bool(self._get(xid).children)

    def txn_cnt(self) -> int:
        return len(self._txns)

    def txn_ancestry(self, xid: bytes) -> list[bytes]:
        """Root-ward chain [oldest .. xid]."""
        chain = []
        cur: bytes | None = xid
        while cur is not None:
            chain.append(cur)
            cur = self._get(cur).parent
        return chain[::-1]

    def txn_cancel(self, xid: bytes) -> int:
        """Discard this fork and every descendant; returns count removed."""
        t = self._get(xid)
        n = 0
        for child in list(t.children):
            n += self.txn_cancel(child)
        if t.parent is not None and t.parent in self._txns:
            self._txns[t.parent].children.discard(xid)
        del self._txns[xid]
        return n + 1

    def txn_publish(self, xid: bytes) -> int:
        """Merge xid's ancestor chain into root (oldest first), cancelling
        every competing sibling fork along the way; returns #published."""
        chain = self.txn_ancestry(xid)
        published = 0
        for step in chain:
            t = self._txns[step]
            # competing forks off the same parent lose (fd_funk_txn_publish)
            siblings = (
                self._txns[t.parent].children
                if t.parent is not None
                else {x for x, v in self._txns.items() if v.parent is None}
            )
            for sib in [s for s in siblings if s != step]:
                self.txn_cancel(sib)
            self._root_merge(
                [(key, None if val is _TOMBSTONE else val)
                 for key, val in t.recs.items()]
            )
            # step's children become children of root
            for child in t.children:
                self._txns[child].parent = None
            del self._txns[step]
            self.last_publish = step
            published += 1
        return published

    # -- records ------------------------------------------------------------

    def rec_insert(self, xid: bytes | None, key: bytes, val: bytes) -> None:
        """Insert-or-modify `key` in txn `xid` (None = straight to root)."""
        if xid is None:
            self._root_merge([(key, bytes(val))])
            return
        t = self._get(xid)
        if t.children:
            raise FunkError(ERR_FROZEN, "txn has children; records frozen")
        t.recs[key] = bytes(val)

    def txn_recs_for_write(self, xid: bytes) -> dict:
        """The txn's live record dict for a BATCH of insert-or-modify
        writes (the bank drain's per-sweep apply): the ancestry lookup
        and frozen check run once up front instead of once per record.
        Callers must store plain bytes values and must not hold the
        dict across a txn_publish/cancel."""
        t = self._get(xid)
        if t.children:
            raise FunkError(ERR_FROZEN, "txn has children; records frozen")
        return t.recs

    def rec_remove(self, xid: bytes | None, key: bytes) -> None:
        """Remove `key` as seen from `xid` (tombstones hide ancestors)."""
        if xid is None:
            if key not in self._root:
                raise FunkError(ERR_KEY, f"unknown key {key!r}")
            self._root_merge([(key, None)])
            return
        t = self._get(xid)
        if t.children:
            raise FunkError(ERR_FROZEN, "txn has children; records frozen")
        if self.rec_query(xid, key) is None:
            raise FunkError(ERR_KEY, f"unknown key {key!r}")
        t.recs[key] = _TOMBSTONE

    def rec_query(self, xid: bytes | None, key: bytes) -> bytes | None:
        """Value of `key` as seen from `xid`: nearest overlay wins."""
        cur = xid
        while cur is not None:
            t = self._get(cur)
            if key in t.recs:
                v = t.recs[key]
                return None if v is _TOMBSTONE else v
            cur = t.parent
        return self._root.get(key)

    def rec_cnt_root(self) -> int:
        return len(self._root)

    def rec_keys(self, xid: bytes | None) -> list[bytes]:
        """Every live record key visible from `xid` (root for None) —
        the snapshot writer's iteration surface."""
        if xid is None:
            return list(self._root)
        keys = set(self._root)
        for t_xid in self.txn_ancestry(xid):  # oldest -> newest overlay
            t = self._get(t_xid)
            for k, v in t.recs.items():
                if v is _TOMBSTONE:
                    keys.discard(k)
                else:
                    keys.add(k)
        return list(keys)

    # -- internals ----------------------------------------------------------

    def _root_merge(self, items: list[tuple[bytes, bytes | None]]) -> None:
        """Apply one atomic batch of root mutations (None value = delete).
        The single funnel for all root writes — the persistence layer
        (funk/persist.py) overrides it to journal the batch first."""
        for key, val in items:
            if val is None:
                self._root.pop(key, None)
            else:
                self._root[key] = val


    def _get(self, xid: bytes) -> _Txn:
        t = self._txns.get(xid)
        if t is None:
            raise FunkError(ERR_TXN, f"unknown txn {xid!r}")
        return t
