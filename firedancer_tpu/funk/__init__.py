from .funk import (  # noqa: F401
    ERR_FROZEN,
    ERR_KEY,
    ERR_TXN,
    Funk,
    FunkError,
)


def make_funk(**kwargs):
    """Construction funnel for the authoritative record store: the
    native shm-backed map when the lane is enabled and the toolchain
    builds it, the dict-backed `Funk` otherwise.  Topology builders go
    through here so FDTPU_NATIVE_FUNK toggles the whole tree."""
    from . import funk_native

    if funk_native.available():
        return funk_native.NativeFunk(**kwargs)
    return Funk()
