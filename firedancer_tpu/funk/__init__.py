from .funk import (  # noqa: F401
    ERR_FROZEN,
    ERR_KEY,
    ERR_TXN,
    Funk,
    FunkError,
)
