"""Durable funk: write-ahead journal + snapshot compaction.

Capability parity target: the reference's funk is wksp-backed —
published state lives in a persistent shared-memory workspace and
survives process restarts, with `fd_funk_archive.c` writing whole-DB
archives to files (/root/reference/src/funk/fd_funk.h:3-60,
fd_funk_archive.c; no code shared).  The TPU build's runtime is a
Python/XLA process, so durability is a file-system protocol instead of
shm relocation:

  - every ROOT mutation batch (a publish step's record set, or a direct
    root insert/remove) is appended to a write-ahead journal as one
    CRC-framed record before it is applied — a crash never splits a
    publish in half;
  - recovery = load the latest snapshot, then replay the journal,
    truncating at the first torn/corrupt frame (fsync'd frames before it
    are intact by construction);
  - when the journal outgrows the live root, compaction writes a fresh
    snapshot (utils/checkpt framed+compressed — the fd_checkpt analog)
    and resets the journal.  Rename-into-place keeps a crash during
    compaction recoverable from the previous snapshot+journal.

In-preparation fork-tree txns are NOT journaled: they are speculative
by definition and a restarted validator rebuilds them from replay —
only published (consensus-final) state must survive, which is also the
only state the reference can rely on across a machine reboot.
"""

from __future__ import annotations

import os
import struct
import zlib

from firedancer_tpu.funk.funk import Funk

_MAGIC = b"FDTPUWAL"
_FRAME_HDR = struct.Struct("<II")  # payload_len, crc32(payload)


def _enc_batch(items: list[tuple[bytes, bytes | None]]) -> bytes:
    out = [struct.pack("<I", len(items))]
    for key, val in items:
        if val is None:
            out.append(struct.pack("<Hi", len(key), -1))
            out.append(key)
        else:
            out.append(struct.pack("<Hi", len(key), len(val)))
            out.append(key)
            out.append(val)
    return b"".join(out)


def _dec_batch(payload: bytes) -> list[tuple[bytes, bytes | None]]:
    (n,) = struct.unpack_from("<I", payload, 0)
    off = 4
    items = []
    for _ in range(n):
        klen, vlen = struct.unpack_from("<Hi", payload, off)
        off += 6
        key = payload[off : off + klen]
        off += klen
        if vlen < 0:
            items.append((key, None))
        else:
            items.append((key, payload[off : off + vlen]))
            off += vlen
    return items


class PersistentFunk(Funk):
    """Funk whose published root survives process restarts.

    `PersistentFunk(dir)` recovers snapshot+journal from `dir` if
    present, else starts empty.  `compact_ratio` bounds journal growth:
    when journal bytes exceed max(min_compact_bytes, ratio x approximate
    live-root bytes) the store compacts.  `sync` fsyncs every journal
    append (durable against power loss, slower); sync=False leaves
    flushing to the OS (durable against process crash — the default, and
    the reference's own wksp guarantee level).
    """

    def __init__(self, dirpath: str, *, compact_ratio: int = 4,
                 min_compact_bytes: int = 1 << 20, sync: bool = False):
        super().__init__()
        self.dir = dirpath
        self.compact_ratio = compact_ratio
        self.min_compact_bytes = min_compact_bytes
        self.sync = sync
        os.makedirs(dirpath, exist_ok=True)
        self._snap_path = os.path.join(dirpath, "funk.snap")
        self._wal_path = os.path.join(dirpath, "funk.wal")
        self._root_bytes = 0  # approximate live size for compaction
        self._recover()
        self._wal = open(self._wal_path, "ab")
        if self._wal.tell() == 0:
            self._wal.write(_MAGIC)
            self._wal.flush()

    # -- recovery -----------------------------------------------------------

    def _recover(self) -> None:
        from firedancer_tpu.utils import checkpt as cp

        if os.path.exists(self._snap_path):
            restored = cp.funk_restore(self._snap_path, Funk)
            self._root = restored._root
        replayed, valid_end = 0, len(_MAGIC)
        if os.path.exists(self._wal_path):
            with open(self._wal_path, "rb") as f:
                blob = f.read()
            if blob[: len(_MAGIC)] != _MAGIC:
                # torn/garbage header: the whole journal is untrusted.
                # Truncate to ZERO (not just skip) — __init__ reopens in
                # append mode and only writes the magic at tell()==0, so
                # leaving the garbage in place would append frames after
                # it and every later recovery would drop them all.
                blob = b""
                valid_end = 0
            off = len(_MAGIC)
            while off + _FRAME_HDR.size <= len(blob):
                ln, crc = _FRAME_HDR.unpack_from(blob, off)
                payload = blob[off + _FRAME_HDR.size : off + _FRAME_HDR.size + ln]
                if len(payload) != ln or zlib.crc32(payload) != crc:
                    break  # torn tail: everything before it is intact
                for key, val in _dec_batch(payload):
                    if val is None:
                        self._root.pop(key, None)
                    else:
                        self._root[key] = val
                off += _FRAME_HDR.size + ln
                valid_end = off
                replayed += 1
            if valid_end < os.path.getsize(self._wal_path):
                with open(self._wal_path, "r+b") as f:
                    f.truncate(valid_end)
        self._root_bytes = sum(
            len(k) + len(v) for k, v in self._root.items()
        )
        self.recovered_frames = replayed

    # -- journaled root writes ---------------------------------------------

    def _root_merge(self, items) -> None:
        payload = _enc_batch(items)
        self._wal.write(_FRAME_HDR.pack(len(payload), zlib.crc32(payload)))
        self._wal.write(payload)
        self._wal.flush()
        if self.sync:
            os.fsync(self._wal.fileno())
        for key, val in items:
            old = self._root.get(key)
            if old is not None:
                self._root_bytes -= len(key) + len(old)
            if val is not None:
                self._root_bytes += len(key) + len(val)
        super()._root_merge(items)
        limit = max(self.min_compact_bytes,
                    self.compact_ratio * max(self._root_bytes, 1))
        if self._wal.tell() > limit:
            self.compact()

    # -- compaction ---------------------------------------------------------

    def compact(self) -> None:
        """Snapshot the live root and reset the journal.  Crash-safe:
        the snapshot lands via rename; the journal is truncated only
        after the snapshot is durable."""
        from firedancer_tpu.utils import checkpt as cp

        tmp = self._snap_path + ".tmp"
        cp.funk_checkpt(tmp, self)
        with open(tmp, "rb") as f:
            os.fsync(f.fileno())
        os.replace(tmp, self._snap_path)
        self._wal.close()
        self._wal = open(self._wal_path, "wb")
        self._wal.write(_MAGIC)
        self._wal.flush()
        if self.sync:
            os.fsync(self._wal.fileno())

    def close(self) -> None:
        self._wal.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def funk_from_config(cfg) -> Funk:
    """The boot-time funk factory: [ledger] funk_dir enables durability."""
    if getattr(cfg.ledger, "funk_dir", ""):
        return PersistentFunk(cfg.ledger.funk_dir)
    from firedancer_tpu.funk import make_funk
    return make_funk()
