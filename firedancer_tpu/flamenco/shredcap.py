"""shredcap: record and replay shred streams.

Capability parity with the reference's shred-capture subsystem
(/root/reference/src/flamenco/shredcap/ — records the incoming shred
stream to disk so a validator's ingest can be reproduced offline; no
code shared).  Container: pcap with UDP encapsulation (utils/pcap.py),
so standard tooling opens captures and the pipeline's pcap replay
harness drives them; shreds ride as the UDP payloads on a marker port.

Use: a `ShredCapWriter` tees the store/retransmit path's shreds to disk;
`replay` later drives them into any sink — a FecResolver, the store
stage, or a blockstore — at full speed or paced by the recorded
timestamps.  `replay_into_resolver` is the common offline-ingest recipe:
captured shreds -> FEC set completion -> recovered entry batches.
"""

from __future__ import annotations

from typing import Callable

from firedancer_tpu.utils import pcap

SHREDCAP_PORT = 8001  # marker dst port inside the capture


class ShredCapWriter:
    def __init__(self, path: str):
        self._w = pcap.PcapWriter(path)
        self.count = 0

    def write(self, shred: bytes, ts: float | None = None) -> None:
        self._w.write_udp(shred, dst=("127.0.0.1", SHREDCAP_PORT), ts=ts)
        self.count += 1

    def close(self) -> None:
        self._w.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def replay(path: str, sink: Callable[[bytes], None], *,
           pace: bool = False) -> int:
    """Feed every captured shred to `sink(shred_bytes)`; returns count."""
    return pcap.replay_udp(
        path, lambda payload, _src: sink(payload),
        pace=pace, port=SHREDCAP_PORT,
    )


def replay_into_resolver(path: str, resolver) -> list:
    """Offline ingest: drive a capture through a FecResolver; returns the
    completed FEC sets in arrival order."""
    done = []

    def sink(buf: bytes) -> None:
        s = resolver.add_shred(buf)
        if s is not None:
            done.append(s)

    replay(path, sink)
    return done
