"""The real Agave bank manifest: full bincode decode/encode + restore.

Capability parity target: the reference decodes the Solana snapshot
manifest with generated bincode (`fd_solana_manifest_decode`, schema
/root/reference/src/flamenco/types/fd_types.json `solana_manifest`) and
restores it into funk (/root/reference/src/flamenco/snapshot/
fd_snapshot_restore.c).  No code shared: here every type is a dataclass
bound to the bincode combinators in flamenco/types.py, mirroring the
WIRE layout (which is fixed by the Solana protocol) rather than the
reference's generated-struct machinery.

What this covers (the `snapshots/<slot>/<slot>` file inside a cluster
snapshot archive):

    SolanaManifest
      bank: VersionedBank          blockhash queue, ancestors, hashes,
                                   fee/rent params, epoch schedule,
                                   inflation, stakes (vote accounts +
                                   delegations + stake history),
                                   epoch stakes per epoch, ...
      accounts_db                  append-vec index: slot -> [(id, sz)],
                                   bank hash info
      lamports_per_signature
      + trailing optional fields (incremental persistence, epoch account
        hash, versioned epoch stakes) which older manifests simply omit
        — decoded tolerantly the way the reference marks them
        `ignore_underflow`.

`restore_manifest` walks the accounts_db storages and loads every
append-vec (flamenco/appendvec.py) into funk, newest slot winning a
pubkey, matching the snapshot restore dedup rule.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dfield

from firedancer_tpu.flamenco import types as T

# -- leaf types ---------------------------------------------------------------


@dataclass
class FeeCalculator:
    lamports_per_signature: int = 0


FEE_CALCULATOR = T.StructCodec(
    FeeCalculator, ("lamports_per_signature", T.U64)
)


@dataclass
class HashAge:
    fee_calculator: FeeCalculator
    hash_index: int
    timestamp: int


HASH_AGE = T.StructCodec(
    HashAge,
    ("fee_calculator", FEE_CALCULATOR),
    ("hash_index", T.U64),
    ("timestamp", T.U64),
)


@dataclass
class HashAgePair:
    key: bytes
    val: HashAge


HASH_AGE_PAIR = T.StructCodec(
    HashAgePair, ("key", T.Hash32), ("val", HASH_AGE)
)


@dataclass
class BlockhashQueue:
    last_hash_index: int = 0
    last_hash: bytes | None = None
    ages: list = dfield(default_factory=list)
    max_age: int = 300


BLOCKHASH_QUEUE = T.StructCodec(
    BlockhashQueue,
    ("last_hash_index", T.U64),
    ("last_hash", T.Option(T.Hash32)),
    ("ages", T.Vec(HASH_AGE_PAIR, max_len=1 << 16)),
    ("max_age", T.U64),
)


@dataclass
class SlotPair:
    slot: int
    val: int


SLOT_PAIR = T.StructCodec(SlotPair, ("slot", T.U64), ("val", T.U64))


@dataclass
class HardForks:
    hard_forks: list = dfield(default_factory=list)


HARD_FORKS = T.StructCodec(
    HardForks, ("hard_forks", T.Vec(SLOT_PAIR, max_len=1 << 16))
)


@dataclass
class FeeRateGovernor:
    target_lamports_per_signature: int = 10_000
    target_signatures_per_slot: int = 20_000
    min_lamports_per_signature: int = 5_000
    max_lamports_per_signature: int = 100_000
    burn_percent: int = 50


FEE_RATE_GOVERNOR = T.StructCodec(
    FeeRateGovernor,
    ("target_lamports_per_signature", T.U64),
    ("target_signatures_per_slot", T.U64),
    ("min_lamports_per_signature", T.U64),
    ("max_lamports_per_signature", T.U64),
    ("burn_percent", T.U8),
)


@dataclass
class RentCollector:
    epoch: int = 0
    epoch_schedule: T.EpochSchedule = dfield(default_factory=T.EpochSchedule)
    slots_per_year: float = 78892314.984
    rent: T.Rent = dfield(default_factory=T.Rent)


RENT_COLLECTOR = T.StructCodec(
    RentCollector,
    ("epoch", T.U64),
    ("epoch_schedule", T.EPOCH_SCHEDULE),
    ("slots_per_year", T.F64),
    ("rent", T.RENT),
)


@dataclass
class Inflation:
    initial: float = 0.08
    terminal: float = 0.015
    taper: float = 0.15
    foundation: float = 0.05
    foundation_term: float = 7.0
    unused: float = 0.0


INFLATION = T.StructCodec(
    Inflation,
    ("initial", T.F64),
    ("terminal", T.F64),
    ("taper", T.F64),
    ("foundation", T.F64),
    ("foundation_term", T.F64),
    ("unused", T.F64),
)


# -- stakes -------------------------------------------------------------------


@dataclass
class SolanaAccount:
    lamports: int = 0
    data: bytes = b""
    owner: bytes = b"\x00" * 32
    executable: bool = False
    rent_epoch: int = 0

    def to_value(self) -> bytes:
        from firedancer_tpu.flamenco.runtime import acct_build

        return acct_build(self.lamports, self.data, self.owner,
                          self.executable)


SOLANA_ACCOUNT = T.StructCodec(
    SolanaAccount,
    ("lamports", T.U64),
    ("data", T.VarBytes(max_len=1 << 27)),
    ("owner", T.Pubkey),
    ("executable", T.Bool),
    ("rent_epoch", T.U64),
)


@dataclass
class VoteAccountsPair:
    key: bytes
    stake: int
    value: SolanaAccount


VOTE_ACCOUNTS_PAIR = T.StructCodec(
    VoteAccountsPair,
    ("key", T.Pubkey),
    ("stake", T.U64),
    ("value", SOLANA_ACCOUNT),
)


@dataclass
class Delegation:
    voter_pubkey: bytes = b"\x00" * 32
    stake: int = 0
    activation_epoch: int = 0
    deactivation_epoch: int = (1 << 64) - 1
    warmup_cooldown_rate: float = 0.25


DELEGATION = T.StructCodec(
    Delegation,
    ("voter_pubkey", T.Pubkey),
    ("stake", T.U64),
    ("activation_epoch", T.U64),
    ("deactivation_epoch", T.U64),
    ("warmup_cooldown_rate", T.F64),
)


@dataclass
class DelegationPair:
    account: bytes
    delegation: Delegation


DELEGATION_PAIR = T.StructCodec(
    DelegationPair, ("account", T.Pubkey), ("delegation", DELEGATION)
)


@dataclass
class StakeHistoryEntry:
    epoch: int
    effective: int
    activating: int
    deactivating: int


STAKE_HISTORY_ENTRY = T.StructCodec(
    StakeHistoryEntry,
    ("epoch", T.U64),
    ("effective", T.U64),
    ("activating", T.U64),
    ("deactivating", T.U64),
)


@dataclass
class Stakes:
    """stakes with Delegation values (the manifest's `bank.stakes`)."""

    vote_accounts: list = dfield(default_factory=list)  # [VoteAccountsPair]
    stake_delegations: list = dfield(default_factory=list)  # [DelegationPair]
    unused: int = 0
    epoch: int = 0
    stake_history: list = dfield(default_factory=list)  # [StakeHistoryEntry]


STAKES = T.StructCodec(
    Stakes,
    ("vote_accounts", T.Vec(VOTE_ACCOUNTS_PAIR, max_len=1 << 20)),
    ("stake_delegations", T.Vec(DELEGATION_PAIR, max_len=1 << 22)),
    ("unused", T.U64),
    ("epoch", T.U64),
    ("stake_history", T.Vec(STAKE_HISTORY_ENTRY, max_len=1 << 12)),
)


@dataclass
class NodeVoteAccounts:
    vote_accounts: list = dfield(default_factory=list)  # [pubkey]
    total_stake: int = 0


NODE_VOTE_ACCOUNTS = T.StructCodec(
    NodeVoteAccounts,
    ("vote_accounts", T.Vec(T.Pubkey, max_len=1 << 16)),
    ("total_stake", T.U64),
)


@dataclass
class PubkeyNodeVoteAccountsPair:
    key: bytes
    value: NodeVoteAccounts


PUBKEY_NODE_VOTE_ACCOUNTS_PAIR = T.StructCodec(
    PubkeyNodeVoteAccountsPair,
    ("key", T.Pubkey),
    ("value", NODE_VOTE_ACCOUNTS),
)


@dataclass
class PubkeyPubkeyPair:
    key: bytes
    value: bytes


PUBKEY_PUBKEY_PAIR = T.StructCodec(
    PubkeyPubkeyPair, ("key", T.Pubkey), ("value", T.Pubkey)
)


@dataclass
class EpochStakes:
    stakes: Stakes
    total_stake: int = 0
    node_id_to_vote_accounts: list = dfield(default_factory=list)
    epoch_authorized_voters: list = dfield(default_factory=list)


EPOCH_STAKES = T.StructCodec(
    EpochStakes,
    ("stakes", STAKES),
    ("total_stake", T.U64),
    ("node_id_to_vote_accounts",
     T.Vec(PUBKEY_NODE_VOTE_ACCOUNTS_PAIR, max_len=1 << 16)),
    ("epoch_authorized_voters", T.Vec(PUBKEY_PUBKEY_PAIR, max_len=1 << 16)),
)


@dataclass
class EpochEpochStakesPair:
    key: int
    value: EpochStakes


EPOCH_EPOCH_STAKES_PAIR = T.StructCodec(
    EpochEpochStakesPair, ("key", T.U64), ("value", EPOCH_STAKES)
)


@dataclass
class UnusedAccounts:
    unused1: list = dfield(default_factory=list)
    unused2: list = dfield(default_factory=list)
    unused3: list = dfield(default_factory=list)  # [(pubkey, u64)]


class _PubkeyU64(T.Codec):
    def encode(self, v):
        return T.Pubkey.encode(v[0]) + T.U64.encode(v[1])

    def decode(self, buf, off=0):
        k, off = T.Pubkey.decode(buf, off)
        n, off = T.U64.decode(buf, off)
        return (k, n), off


UNUSED_ACCOUNTS = T.StructCodec(
    UnusedAccounts,
    ("unused1", T.Vec(T.Pubkey, max_len=1 << 16)),
    ("unused2", T.Vec(T.Pubkey, max_len=1 << 16)),
    ("unused3", T.Vec(_PubkeyU64(), max_len=1 << 16)),
)


# -- the versioned bank -------------------------------------------------------


@dataclass
class VersionedBank:
    blockhash_queue: BlockhashQueue = dfield(default_factory=BlockhashQueue)
    ancestors: list = dfield(default_factory=list)  # [SlotPair]
    hash: bytes = b"\x00" * 32
    parent_hash: bytes = b"\x00" * 32
    parent_slot: int = 0
    hard_forks: HardForks = dfield(default_factory=HardForks)
    transaction_count: int = 0
    tick_height: int = 0
    signature_count: int = 0
    capitalization: int = 0
    max_tick_height: int = 0
    hashes_per_tick: int | None = 12500
    ticks_per_slot: int = 64
    ns_per_slot: int = 400_000_000
    genesis_creation_time: int = 0
    slots_per_year: float = 78892314.984
    accounts_data_len: int = 0
    slot: int = 0
    epoch: int = 0
    block_height: int = 0
    collector_id: bytes = b"\x00" * 32
    collector_fees: int = 0
    fee_calculator: FeeCalculator = dfield(default_factory=FeeCalculator)
    fee_rate_governor: FeeRateGovernor = dfield(
        default_factory=FeeRateGovernor)
    collected_rent: int = 0
    rent_collector: RentCollector = dfield(default_factory=RentCollector)
    epoch_schedule: T.EpochSchedule = dfield(default_factory=T.EpochSchedule)
    inflation: Inflation = dfield(default_factory=Inflation)
    stakes: Stakes = dfield(default_factory=Stakes)
    unused_accounts: UnusedAccounts = dfield(default_factory=UnusedAccounts)
    epoch_stakes: list = dfield(default_factory=list)
    is_delta: bool = False


VERSIONED_BANK = T.StructCodec(
    VersionedBank,
    ("blockhash_queue", BLOCKHASH_QUEUE),
    ("ancestors", T.Vec(SLOT_PAIR, max_len=1 << 20)),
    ("hash", T.Hash32),
    ("parent_hash", T.Hash32),
    ("parent_slot", T.U64),
    ("hard_forks", HARD_FORKS),
    ("transaction_count", T.U64),
    ("tick_height", T.U64),
    ("signature_count", T.U64),
    ("capitalization", T.U64),
    ("max_tick_height", T.U64),
    ("hashes_per_tick", T.Option(T.U64)),
    ("ticks_per_slot", T.U64),
    ("ns_per_slot", T.U128),
    ("genesis_creation_time", T.U64),
    ("slots_per_year", T.F64),
    ("accounts_data_len", T.U64),
    ("slot", T.U64),
    ("epoch", T.U64),
    ("block_height", T.U64),
    ("collector_id", T.Pubkey),
    ("collector_fees", T.U64),
    ("fee_calculator", FEE_CALCULATOR),
    ("fee_rate_governor", FEE_RATE_GOVERNOR),
    ("collected_rent", T.U64),
    ("rent_collector", RENT_COLLECTOR),
    ("epoch_schedule", T.EPOCH_SCHEDULE),
    ("inflation", INFLATION),
    ("stakes", STAKES),
    ("unused_accounts", UNUSED_ACCOUNTS),
    ("epoch_stakes", T.Vec(EPOCH_EPOCH_STAKES_PAIR, max_len=1 << 8)),
    ("is_delta", T.Bool),
)


# -- accounts-db fields -------------------------------------------------------


@dataclass
class SnapshotAccVec:
    id: int
    file_sz: int


SNAPSHOT_ACC_VEC = T.StructCodec(
    SnapshotAccVec, ("id", T.U64), ("file_sz", T.U64)
)


@dataclass
class SnapshotSlotAccVecs:
    slot: int
    account_vecs: list


SNAPSHOT_SLOT_ACC_VECS = T.StructCodec(
    SnapshotSlotAccVecs,
    ("slot", T.U64),
    ("account_vecs", T.Vec(SNAPSHOT_ACC_VEC, max_len=1 << 16)),
)


@dataclass
class BankHashStats:
    num_updated_accounts: int = 0
    num_removed_accounts: int = 0
    num_lamports_stored: int = 0
    total_data_len: int = 0
    num_executable_accounts: int = 0


BANK_HASH_STATS = T.StructCodec(
    BankHashStats,
    ("num_updated_accounts", T.U64),
    ("num_removed_accounts", T.U64),
    ("num_lamports_stored", T.U64),
    ("total_data_len", T.U64),
    ("num_executable_accounts", T.U64),
)


@dataclass
class BankHashInfo:
    hash: bytes = b"\x00" * 32
    snapshot_hash: bytes = b"\x00" * 32
    stats: BankHashStats = dfield(default_factory=BankHashStats)


BANK_HASH_INFO = T.StructCodec(
    BankHashInfo,
    ("hash", T.Hash32),
    ("snapshot_hash", T.Hash32),
    ("stats", BANK_HASH_STATS),
)


@dataclass
class SlotMapPair:
    slot: int
    hash: bytes


SLOT_MAP_PAIR = T.StructCodec(
    SlotMapPair, ("slot", T.U64), ("hash", T.Hash32)
)


@dataclass
class AccountsDbFields:
    storages: list = dfield(default_factory=list)  # [SnapshotSlotAccVecs]
    version: int = 1
    slot: int = 0
    bank_hash_info: BankHashInfo = dfield(default_factory=BankHashInfo)
    historical_roots: list = dfield(default_factory=list)
    historical_roots_with_hash: list = dfield(default_factory=list)


ACCOUNTS_DB_FIELDS = T.StructCodec(
    AccountsDbFields,
    ("storages", T.Vec(SNAPSHOT_SLOT_ACC_VECS, max_len=1 << 20)),
    ("version", T.U64),
    ("slot", T.U64),
    ("bank_hash_info", BANK_HASH_INFO),
    ("historical_roots", T.Vec(T.U64, max_len=1 << 20)),
    ("historical_roots_with_hash", T.Vec(SLOT_MAP_PAIR, max_len=1 << 20)),
)


# -- incremental persistence + the manifest -----------------------------------


@dataclass
class BankIncrementalSnapshotPersistence:
    full_slot: int = 0
    full_hash: bytes = b"\x00" * 32
    full_capitalization: int = 0
    incremental_hash: bytes = b"\x00" * 32
    incremental_capitalization: int = 0


BANK_INCREMENTAL = T.StructCodec(
    BankIncrementalSnapshotPersistence,
    ("full_slot", T.U64),
    ("full_hash", T.Hash32),
    ("full_capitalization", T.U64),
    ("incremental_hash", T.Hash32),
    ("incremental_capitalization", T.U64),
)


@dataclass
class SolanaManifest:
    bank: VersionedBank = dfield(default_factory=VersionedBank)
    accounts_db: AccountsDbFields = dfield(default_factory=AccountsDbFields)
    lamports_per_signature: int = 5000
    bank_incremental_snapshot_persistence: (
        BankIncrementalSnapshotPersistence | None) = None
    epoch_account_hash: bytes | None = None
    # [(epoch, ("Current", EpochStakes-with-stake-values))] — decoded but
    # not interpreted further; current epoch stakes come from bank.stakes
    versioned_epoch_stakes: list = dfield(default_factory=list)


def manifest_encode(m: SolanaManifest) -> bytes:
    out = VERSIONED_BANK.encode(m.bank)
    out += ACCOUNTS_DB_FIELDS.encode(m.accounts_db)
    out += T.U64.encode(m.lamports_per_signature)
    out += T.Option(BANK_INCREMENTAL).encode(
        m.bank_incremental_snapshot_persistence)
    out += T.Option(T.Hash32).encode(m.epoch_account_hash)
    out += T.U64.encode(len(m.versioned_epoch_stakes))
    for epoch, (variant, payload) in m.versioned_epoch_stakes:
        out += T.U64.encode(epoch)
        out += T.U32.encode(0)  # Current
        out += EPOCH_STAKES.encode(payload)
    return out


def manifest_decode(blob: bytes) -> SolanaManifest:
    """Decode a manifest; the three trailing fields are `ignore_underflow`
    (absent in older snapshot versions — a clean end-of-buffer there is
    an older manifest, not corruption)."""
    bank, off = VERSIONED_BANK.decode(blob, 0)
    adb, off = ACCOUNTS_DB_FIELDS.decode(blob, off)
    lps, off = T.U64.decode(blob, off)
    m = SolanaManifest(bank=bank, accounts_db=adb,
                       lamports_per_signature=lps)
    if off == len(blob):
        return m
    m.bank_incremental_snapshot_persistence, off = T.Option(
        BANK_INCREMENTAL).decode(blob, off)
    if off == len(blob):
        return m
    m.epoch_account_hash, off = T.Option(T.Hash32).decode(blob, off)
    if off == len(blob):
        return m
    n, off = T.U64.decode(blob, off)
    if n > 1 << 8:
        raise T.CodecError("oversized versioned_epoch_stakes")
    ves = []
    for _ in range(n):
        epoch, off = T.U64.decode(blob, off)
        tag, off = T.U32.decode(blob, off)
        if tag != 0:
            raise T.CodecError(f"unknown versioned_epoch_stakes tag {tag}")
        payload, off = EPOCH_STAKES.decode(blob, off)
        ves.append((epoch, ("Current", payload)))
    m.versioned_epoch_stakes = ves
    if off != len(blob):
        raise T.CodecError(f"{len(blob) - off} trailing manifest bytes")
    return m


# -- restore ------------------------------------------------------------------


def restore_accounts(
    funk, storages: list, open_vec,
) -> int:
    """Load every append-vec into funk's root.  `open_vec(slot, id)` ->
    append-vec file bytes.  A pubkey stored in several slots resolves to
    the HIGHEST slot's version (the snapshot restore dedup rule); within
    one slot, the later entry (higher write_version) wins.  A
    zero-lamport store is a tombstone and REMOVES the key (an overlay
    restore onto a pre-populated funk must not resurrect deletions).
    Returns the number of distinct live accounts restored."""
    from firedancer_tpu.flamenco.appendvec import iter_appendvec

    best: dict[bytes, tuple[int, int, bytes | None]] = {}
    for store in sorted(storages, key=lambda s: s.slot):
        for av in store.account_vecs:
            blob = open_vec(store.slot, av.id)
            for ent in iter_appendvec(blob, current_len=av.file_sz):
                prev = best.get(ent.pubkey)
                key = (store.slot, ent.write_version)
                if prev is not None and prev[:2] > key:
                    continue
                if ent.lamports == 0:
                    # a zero-lamport store is a tombstone: the account
                    # was deleted in that slot
                    best[ent.pubkey] = (*key, None)
                else:
                    best[ent.pubkey] = (*key, ent.to_value())
    n = 0
    for pubkey, (_s, _wv, val) in best.items():
        if val is None:
            # tombstone: delete if present (overlay restore); a cold
            # boot simply never materializes the key
            if funk.rec_query(None, pubkey) is not None:
                funk.rec_remove(None, pubkey)
            continue
        funk.rec_insert(None, pubkey, val)
        n += 1
    return n


def restore_manifest(funk, m: SolanaManifest, open_vec) -> dict:
    """Restore accounts + the consensus-relevant bank state.  Returns a
    summary the caller (snapshot boot / CLI) reports: slot, bank hash,
    account count, registered blockhashes, stake/vote surface sizes."""
    n = restore_accounts(funk, m.accounts_db.storages, open_vec)
    return {
        "slot": m.bank.slot,
        "bank_hash": m.bank.hash,
        "parent_hash": m.bank.parent_hash,
        "accounts": n,
        "capitalization": m.bank.capitalization,
        "blockhashes": [
            (p.key, p.val.hash_index) for p in m.bank.blockhash_queue.ages
        ],
        "vote_accounts": len(m.bank.stakes.vote_accounts),
        "stake_delegations": len(m.bank.stakes.stake_delegations),
        "epoch": m.bank.epoch,
        "lamports_per_signature": m.lamports_per_signature,
    }
