"""Feature gates (counterpart of the reference's generated
fd_features.h table, /root/reference/src/flamenco/features/).

A feature is a named gate identified by a 32-byte id (here: sha256 of
the name, deterministic without an external registry) that activates at
a recorded slot.  Runtime code queries `features.is_active(name, slot)`
to pick behavior; the set is carried on the bank/epoch context and can
be extended at genesis or via feature accounts.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

U64_MAX = (1 << 64) - 1


def feature_id(name: str) -> bytes:
    return hashlib.sha256(b"feature:" + name.encode()).digest()


# the default gate table: every known feature starts inactive
KNOWN_FEATURES = (
    "stake_warmup_cooldown",
    "strict_ed25519_verify",
    "blake3_account_hash",
    "cpi_account_data_growth",
    "vote_state_credits",
    "fee_burn_half",
)


@dataclass
class FeatureSet:
    """name -> activation slot (U64_MAX = never)."""

    activated: dict[str, int] = field(default_factory=dict)

    @classmethod
    def all_enabled(cls) -> "FeatureSet":
        return cls({n: 0 for n in KNOWN_FEATURES})

    def activate(self, name: str, slot: int) -> None:
        if name not in KNOWN_FEATURES:
            raise KeyError(f"unknown feature {name!r}")
        cur = self.activated.get(name, U64_MAX)
        self.activated[name] = min(cur, slot)

    def is_active(self, name: str, slot: int) -> bool:
        return self.activated.get(name, U64_MAX) <= slot

    def ids(self) -> dict[bytes, int]:
        """Account-keyed view (feature accounts hold the activation
        slot on chain; this is the id -> slot projection)."""
        return {feature_id(n): s for n, s in self.activated.items()}
