"""Native programs: system and vote (stake lives in flamenco/stake.py).

Counterparts of /root/reference/src/flamenco/runtime/program/
fd_system_program.c and fd_vote_program.c, reduced to the instruction
surface this runtime exercises.  Handlers receive the executor (for CPI
re-entry by native code, unused here), the txn context, the program id,
the instruction accounts and raw data, and raise typed errors
(executor.InstrError subclasses) that the runtime maps onto its txn
status codes.

Instruction encodings are the protocol's own (bincode: u32 LE enum tag,
then the payload fields in order).
"""

from __future__ import annotations

from firedancer_tpu.flamenco.executor import (
    Account,
    InstrError,
    SYSTEM_PROGRAM,
)

MAX_PERMITTED_DATA_LENGTH = 10 * 1024 * 1024


class AcctError(InstrError):
    """Missing/readonly/unsigned account where one was required."""


class FundsError(InstrError):
    """Insufficient lamports for the requested movement."""


def _u32(b: bytes) -> int:
    return int.from_bytes(b[:4], "little")


def _u64(b: bytes) -> int:
    return int.from_bytes(b[:8], "little")


# -- system program -----------------------------------------------------------
# tags (SystemInstruction): 0 CreateAccount, 1 Assign, 2 Transfer,
# 4-7 nonce family (flamenco/nonce.py), 8 Allocate


def system_program(executor, ctx, program_id, iaccts, data, *, pda_signers):
    if len(data) < 4:
        return  # garbage instruction: no-op (legacy parity)
    tag = _u32(data)

    def acct(i) -> Account:
        if i >= len(iaccts):
            raise AcctError(f"system instr needs account {i}")
        return ctx.accounts[iaccts[i].txn_idx]

    def need_writable(i):
        if not iaccts[i].is_writable:
            raise AcctError(f"system account {i} not writable")

    def need_signer(i):
        ia = iaccts[i]
        key = ctx.accounts[ia.txn_idx].key
        if not (ia.is_signer or key in pda_signers):
            raise AcctError(f"system account {i} missing signature")

    if tag == 2:  # Transfer { lamports }
        if len(data) < 12 or len(iaccts) < 2:
            return
        lamports = _u64(data[4:])
        src, dst = acct(0), acct(1)
        need_writable(0)
        need_writable(1)
        need_signer(0)
        if src.owner != SYSTEM_PROGRAM:
            # owner-may-debit: the system program only moves lamports out
            # of its own accounts
            raise AcctError("transfer source not system-owned")
        if len(src.data) != 0:
            # Agave: `from` must carry no data (conformance fixture
            # transfer_from_data_acct; fd_system_program's transfer_verify)
            raise AcctError("transfer source carries data")
        if src.lamports < lamports:
            raise FundsError(
                f"transfer {lamports} from balance {src.lamports}"
            )
        if src.key == dst.key:
            return  # self-transfer: no-op, NOT a mint
        src.lamports -= lamports
        dst.lamports += lamports
    elif tag == 0:  # CreateAccount { lamports, space, owner }
        if len(data) < 4 + 8 + 8 + 32 or len(iaccts) < 2:
            raise AcctError("malformed create_account")
        lamports = _u64(data[4:])
        space = _u64(data[12:])
        owner = data[20:52]
        src, new = acct(0), acct(1)
        need_writable(0)
        need_writable(1)
        need_signer(0)
        need_signer(1)  # the new account signs (keypair or PDA seeds)
        if space > MAX_PERMITTED_DATA_LENGTH:
            raise AcctError(f"create_account space {space} too large")
        if src.owner != SYSTEM_PROGRAM:
            raise AcctError("create_account funder not system-owned")
        if new.exists:
            raise AcctError("create_account target already in use")
        if src.lamports < lamports:
            raise FundsError("create_account funding short")
        if src.key != new.key:
            src.lamports -= lamports
            new.lamports += lamports
        new.data = bytearray(space)
        new.owner = owner
    elif tag == 1:  # Assign { owner }
        if len(data) < 36 or len(iaccts) < 1:
            raise AcctError("malformed assign")
        a = acct(0)
        need_writable(0)
        need_signer(0)
        if a.owner != SYSTEM_PROGRAM:
            raise AcctError("assign target not system-owned")
        a.owner = data[4:36]
    elif tag in (4, 5, 6, 7):  # durable-nonce family (flamenco/nonce.py)
        from firedancer_tpu.flamenco import nonce as _nonce

        _nonce.handle(executor, ctx, tag, iaccts, data,
                      pda_signers=pda_signers)
    elif tag == 8:  # Allocate { space }
        if len(data) < 12 or len(iaccts) < 1:
            raise AcctError("malformed allocate")
        space = _u64(data[4:])
        a = acct(0)
        need_writable(0)
        need_signer(0)
        if space > MAX_PERMITTED_DATA_LENGTH:
            raise AcctError(f"allocate space {space} too large")
        if len(a.data) or a.owner != SYSTEM_PROGRAM:
            raise AcctError("allocate target already in use")
        a.data = bytearray(space)
    # other tags: no-op (unimplemented surface is inert, never fatal)


# -- compute budget program ---------------------------------------------------
# The limits themselves are applied at txn load (pack.cost.txn_budget ->
# TxnCtx.budget/heap_size); execution of the instruction only re-validates
# the payload (fd_compute_budget_program.c's processor is the same no-op).


def compute_budget_program(executor, ctx, program_id, iaccts, data,
                           *, pda_signers):
    if len(data) < 5 or data[0] > 3:
        raise AcctError("malformed compute budget instruction")


# The vote program lives in flamenco/vote_program.py: the REAL VoteState
# machine over the agave_state codec (lockout doubling, voter rotation,
# tower sync) — fd_vote_program.c parity, registered by the executor.
