"""Native programs: system and vote (stake lives in flamenco/stake.py).

Counterparts of /root/reference/src/flamenco/runtime/program/
fd_system_program.c and fd_vote_program.c, reduced to the instruction
surface this runtime exercises.  Handlers receive the executor (for CPI
re-entry by native code, unused here), the txn context, the program id,
the instruction accounts and raw data, and raise typed errors
(executor.InstrError subclasses) that the runtime maps onto its txn
status codes.

Instruction encodings are the protocol's own (bincode: u32 LE enum tag,
then the payload fields in order).
"""

from __future__ import annotations

from firedancer_tpu.flamenco.executor import (
    Account,
    InstrError,
    SYSTEM_PROGRAM,
)

MAX_PERMITTED_DATA_LENGTH = 10 * 1024 * 1024


class AcctError(InstrError):
    """Missing/readonly/unsigned account where one was required."""


class FundsError(InstrError):
    """Insufficient lamports for the requested movement."""


def _u32(b: bytes) -> int:
    return int.from_bytes(b[:4], "little")


def _u64(b: bytes) -> int:
    return int.from_bytes(b[:8], "little")


# -- system program -----------------------------------------------------------
# tags (SystemInstruction): 0 CreateAccount, 1 Assign, 2 Transfer,
# 4-7 nonce family (flamenco/nonce.py), 8 Allocate


def system_program(executor, ctx, program_id, iaccts, data, *, pda_signers):
    if len(data) < 4:
        return  # garbage instruction: no-op (legacy parity)
    tag = _u32(data)

    def acct(i) -> Account:
        if i >= len(iaccts):
            raise AcctError(f"system instr needs account {i}")
        return ctx.accounts[iaccts[i].txn_idx]

    def need_writable(i):
        if not iaccts[i].is_writable:
            raise AcctError(f"system account {i} not writable")

    def need_signer(i):
        ia = iaccts[i]
        key = ctx.accounts[ia.txn_idx].key
        if not (ia.is_signer or key in pda_signers):
            raise AcctError(f"system account {i} missing signature")

    if tag == 2:  # Transfer { lamports }
        if len(data) < 12 or len(iaccts) < 2:
            return
        lamports = _u64(data[4:])
        src, dst = acct(0), acct(1)
        need_writable(0)
        need_writable(1)
        need_signer(0)
        if src.owner != SYSTEM_PROGRAM:
            # owner-may-debit: the system program only moves lamports out
            # of its own accounts
            raise AcctError("transfer source not system-owned")
        if len(src.data) != 0:
            # Agave: `from` must carry no data (conformance fixture
            # transfer_from_data_acct; fd_system_program's transfer_verify)
            raise AcctError("transfer source carries data")
        if src.lamports < lamports:
            raise FundsError(
                f"transfer {lamports} from balance {src.lamports}"
            )
        if src.key == dst.key:
            return  # self-transfer: no-op, NOT a mint
        src.lamports -= lamports
        dst.lamports += lamports
    elif tag == 0:  # CreateAccount { lamports, space, owner }
        if len(data) < 4 + 8 + 8 + 32 or len(iaccts) < 2:
            raise AcctError("malformed create_account")
        lamports = _u64(data[4:])
        space = _u64(data[12:])
        owner = data[20:52]
        src, new = acct(0), acct(1)
        need_writable(0)
        need_writable(1)
        need_signer(0)
        need_signer(1)  # the new account signs (keypair or PDA seeds)
        if space > MAX_PERMITTED_DATA_LENGTH:
            raise AcctError(f"create_account space {space} too large")
        if src.owner != SYSTEM_PROGRAM:
            raise AcctError("create_account funder not system-owned")
        if new.exists:
            raise AcctError("create_account target already in use")
        if src.lamports < lamports:
            raise FundsError("create_account funding short")
        if src.key != new.key:
            src.lamports -= lamports
            new.lamports += lamports
        new.data = bytearray(space)
        new.owner = owner
    elif tag == 1:  # Assign { owner }
        if len(data) < 36 or len(iaccts) < 1:
            raise AcctError("malformed assign")
        a = acct(0)
        need_writable(0)
        need_signer(0)
        if a.owner != SYSTEM_PROGRAM:
            raise AcctError("assign target not system-owned")
        a.owner = data[4:36]
    elif tag in (4, 5, 6, 7):  # durable-nonce family (flamenco/nonce.py)
        from firedancer_tpu.flamenco import nonce as _nonce

        _nonce.handle(executor, ctx, tag, iaccts, data,
                      pda_signers=pda_signers)
    elif tag == 8:  # Allocate { space }
        if len(data) < 12 or len(iaccts) < 1:
            raise AcctError("malformed allocate")
        space = _u64(data[4:])
        a = acct(0)
        need_writable(0)
        need_signer(0)
        if space > MAX_PERMITTED_DATA_LENGTH:
            raise AcctError(f"allocate space {space} too large")
        if len(a.data) or a.owner != SYSTEM_PROGRAM:
            raise AcctError("allocate target already in use")
        a.data = bytearray(space)
    # other tags: no-op (unimplemented surface is inert, never fatal)


# -- compute budget program ---------------------------------------------------
# The limits themselves are applied at txn load (pack.cost.txn_budget ->
# TxnCtx.budget/heap_size); execution of the instruction only re-validates
# the payload (fd_compute_budget_program.c's processor is the same no-op).


def compute_budget_program(executor, ctx, program_id, iaccts, data,
                           *, pda_signers):
    if len(data) < 5 or data[0] > 3:
        raise AcctError("malformed compute budget instruction")


# -- vote program -------------------------------------------------------------
# account data layout: u64 last_voted_slot | u64 vote_count | 32B authority
#
# Votes feed tower/ghost fork choice, so vote forgery manipulates consensus
# weight; the reference's fd_vote_program requires the authorized voter's
# signature on every vote.  Here the authority binds on the first vote into
# a fresh account (the first signing instruction account becomes the
# authorized voter) and every later vote must carry that authority's
# signature.


def vote_program(executor, ctx, program_id, iaccts, data, *, pda_signers):
    from firedancer_tpu.protocol.txn import VOTE_PROGRAM

    if len(data) < 12 or _u32(data) != 1 or len(iaccts) < 1:
        return  # non-vote instruction: no-op
    if not iaccts[0].is_writable:
        raise AcctError("vote account not writable")
    vote_slot = _u64(data[4:])
    a = ctx.accounts[iaccts[0].txn_idx]
    if a.owner != VOTE_PROGRAM:
        # owner-may-modify: a foreign account's data is untouchable;
        # vote accounts are created/assigned to the vote program first
        raise AcctError("vote account not owned by the vote program")
    signers = [
        ctx.accounts[ia.txn_idx].key
        for ia in iaccts
        if ia.is_signer or ctx.accounts[ia.txn_idx].key in pda_signers
    ]
    if len(a.data) < 48:
        a.data = bytearray(bytes(a.data).ljust(48, b"\x00"))
    authority = bytes(a.data[16:48])
    cnt = _u64(bytes(a.data[8:16]))
    if authority == bytes(32):
        # Authority binds only on a FRESH account (no vote history).  An
        # account with votes but a zero authority is a legacy/corrupt
        # state that must not be hijackable by whoever votes next.
        if cnt != 0:
            raise AcctError("vote account has history but no authority")
        if not signers:
            raise AcctError("vote missing authorized-voter signature")
        authority = signers[0]
        a.data[16:48] = authority
    elif authority not in signers:
        raise AcctError("vote missing authorized-voter signature")
    a.data[0:8] = vote_slot.to_bytes(8, "little")
    a.data[8:16] = (cnt + 1).to_bytes(8, "little")
