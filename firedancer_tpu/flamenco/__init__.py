from .runtime import (  # noqa: F401
    BlockResult,
    TXN_SUCCESS,
    TXN_ERR_INSUFFICIENT_FUNDS,
    TXN_ERR_FEE,
    execute_block,
    generate_waves,
    replay_block,
)
