"""Address lookup table program + v0 transaction address resolution.

Counterpart of /root/reference/src/flamenco/runtime/program/
fd_address_lookup_table_program.c (instruction processing + state layout)
and the executor-side loaded-address resolution in
/root/reference/src/flamenco/runtime/fd_executor.c (account load path).
Capability parity target only — no code shared; the reference is C over
its own bincode types, this is the framework's host-side Python.

State layout (Solana's ProgramState bincode, LOOKUP_TABLE_META_SIZE = 56):

    u32  discriminant        0 = Uninitialized, 1 = LookupTable
    u64  deactivation_slot   u64::MAX = active
    u64  last_extended_slot
    u8   last_extended_slot_start_index
    u8   authority_some      Option<Pubkey>
    32B  authority
    u16  padding
    ...  addresses, 32 bytes each, from offset 56

Instructions (bincode enum, u32 tag):

    0 CreateLookupTable { recent_slot u64, bump u8 }
         [table w, authority s, payer s w, system]
    1 FreezeLookupTable     [table w, authority s]
    2 ExtendLookupTable { new_addresses Vec<Pubkey> }
         [table w, authority s, (payer s w, system)]
    3 DeactivateLookupTable [table w, authority s]
    4 CloseLookupTable      [table w, authority s, recipient w]

Resolution timing: a block resolves every txn's lookups against the state
at the START of the slot (the parent fork view), so a table extended in
slot N serves the new addresses from slot N+1 — the same visibility rule
Agave enforces via last_extended_slot, collapsed into resolve-at-block-
start (which also keeps wave generation exact: the resolved rw-sets are
known before any txn executes).
"""

from __future__ import annotations

from dataclasses import dataclass

from firedancer_tpu.flamenco.programs import AcctError, _u32, _u64
from firedancer_tpu.protocol import pda
from firedancer_tpu.protocol.base58 import b58_decode32
from firedancer_tpu.protocol.txn import SYSTEM_PROGRAM

ALT_PROGRAM = b58_decode32("AddressLookupTab1e1111111111111111111111111")

U64_MAX = (1 << 64) - 1
META_SIZE = 56
MAX_ADDRESSES = 256
# slots a deactivated table stays resolvable/uncloseable (the reference
# keys this off SlotHashes depth: ~512 slots of cooldown)
DEACTIVATE_COOLDOWN_SLOTS = 512


@dataclass
class TableState:
    deactivation_slot: int = U64_MAX
    last_extended_slot: int = 0
    last_extended_start: int = 0
    authority: bytes | None = None
    addresses: list[bytes] = None  # type: ignore[assignment]

    def __post_init__(self):
        if self.addresses is None:
            self.addresses = []

    def encode(self) -> bytes:
        out = bytearray()
        out += (1).to_bytes(4, "little")
        out += self.deactivation_slot.to_bytes(8, "little")
        out += self.last_extended_slot.to_bytes(8, "little")
        out += bytes([self.last_extended_start])
        if self.authority is None:
            out += bytes([0]) + bytes(32)
        else:
            out += bytes([1]) + self.authority
        out += bytes(2)  # padding
        assert len(out) == META_SIZE
        for a in self.addresses:
            out += a
        return bytes(out)

    @classmethod
    def decode(cls, data: bytes) -> "TableState":
        if len(data) < META_SIZE:
            raise AcctError("lookup table account too small")
        if _u32(data) != 1:
            raise AcctError("account is not an initialized lookup table")
        n = (len(data) - META_SIZE) // 32
        return cls(
            deactivation_slot=_u64(data[4:]),
            last_extended_slot=_u64(data[12:]),
            last_extended_start=data[20],
            authority=data[22:54] if data[21] else None,
            addresses=[
                data[META_SIZE + 32 * i : META_SIZE + 32 * (i + 1)]
                for i in range(n)
            ],
        )


def _clock_slot(ctx) -> int:
    from firedancer_tpu.flamenco import types as T

    blob = ctx.sysvars.get("clock")
    if not blob:
        raise AcctError("lookup table instruction requires the clock sysvar")
    clock, _ = T.CLOCK.decode(blob, 0)
    return clock.slot


def alt_program(executor, ctx, program_id, iaccts, data, *, pda_signers):
    if len(data) < 4:
        raise AcctError("malformed lookup table instruction")
    tag = _u32(data)

    def acct(i):
        if i >= len(iaccts):
            raise AcctError(f"lookup table instr needs account {i}")
        return ctx.accounts[iaccts[i].txn_idx]

    def need_writable(i):
        if i >= len(iaccts):
            raise AcctError(f"lookup table instr needs account {i}")
        if not iaccts[i].is_writable:
            raise AcctError(f"lookup table account {i} not writable")

    def need_signer(i):
        if i >= len(iaccts):
            raise AcctError(f"lookup table instr needs account {i}")
        ia = iaccts[i]
        if not (ia.is_signer or ctx.accounts[ia.txn_idx].key in pda_signers):
            raise AcctError(f"lookup table account {i} must sign")

    def authority_check(st):
        if st.authority is None:
            raise AcctError("lookup table is frozen")
        need_signer(1)
        if acct(1).key != st.authority:
            raise AcctError("wrong lookup table authority")

    if tag == 0:  # CreateLookupTable { recent_slot u64, bump u8 }
        if len(data) < 4 + 9:
            raise AcctError("malformed create_lookup_table")
        recent_slot = _u64(data[4:])
        bump = data[12]
        table, authority = acct(0), acct(1)
        need_writable(0)
        need_signer(2)  # payer
        if recent_slot > _clock_slot(ctx):
            raise AcctError(f"recent_slot {recent_slot} is not a past slot")
        try:
            expect = pda.create_program_address(
                [authority.key, recent_slot.to_bytes(8, "little"),
                 bytes([bump])],
                ALT_PROGRAM,
            )
        except pda.PdaError as e:
            # an on-curve bump is attacker-reachable input, not a bug:
            # typed failure, never a block abort
            raise AcctError(f"bad table derivation: {e}") from e
        if expect != table.key:
            raise AcctError("lookup table address derivation mismatch")
        if table.owner == ALT_PROGRAM and len(table.data):
            raise AcctError("lookup table already exists")
        if table.owner != SYSTEM_PROGRAM and table.owner != ALT_PROGRAM:
            raise AcctError("lookup table account has a foreign owner")
        st = TableState(authority=authority.key)
        table.owner = ALT_PROGRAM
        table.data = bytearray(st.encode())
    elif tag == 1:  # FreezeLookupTable
        table = acct(0)
        need_writable(0)
        if table.owner != ALT_PROGRAM:
            raise AcctError("freeze target not a lookup table")
        st = TableState.decode(bytes(table.data))
        authority_check(st)
        if not st.addresses:
            raise AcctError("cannot freeze an empty lookup table")
        st.authority = None
        table.data = bytearray(st.encode())
    elif tag == 2:  # ExtendLookupTable { new_addresses Vec<Pubkey> }
        if len(data) < 4 + 8:
            raise AcctError("malformed extend_lookup_table")
        n = _u64(data[4:])
        if n == 0:
            raise AcctError("extend with no addresses")
        if len(data) < 12 + 32 * n:
            raise AcctError("short extend_lookup_table payload")
        table = acct(0)
        need_writable(0)
        if table.owner != ALT_PROGRAM:
            raise AcctError("extend target not a lookup table")
        st = TableState.decode(bytes(table.data))
        authority_check(st)
        if st.deactivation_slot != U64_MAX:
            raise AcctError("cannot extend a deactivated lookup table")
        if len(st.addresses) + n > MAX_ADDRESSES:
            raise AcctError("lookup table address limit exceeded")
        slot = _clock_slot(ctx)
        if st.last_extended_slot != slot:
            st.last_extended_slot = slot
            st.last_extended_start = len(st.addresses)
        for i in range(n):
            st.addresses.append(data[12 + 32 * i : 12 + 32 * (i + 1)])
        table.data = bytearray(st.encode())
    elif tag == 3:  # DeactivateLookupTable
        table = acct(0)
        need_writable(0)
        if table.owner != ALT_PROGRAM:
            raise AcctError("deactivate target not a lookup table")
        st = TableState.decode(bytes(table.data))
        authority_check(st)
        if st.deactivation_slot != U64_MAX:
            raise AcctError("lookup table already deactivated")
        st.deactivation_slot = _clock_slot(ctx)
        table.data = bytearray(st.encode())
    elif tag == 4:  # CloseLookupTable
        table, recipient = acct(0), acct(2)
        need_writable(0)
        need_writable(2)
        if table.owner != ALT_PROGRAM:
            raise AcctError("close target not a lookup table")
        st = TableState.decode(bytes(table.data))
        authority_check(st)
        if st.deactivation_slot == U64_MAX:
            raise AcctError("cannot close an active lookup table")
        if _clock_slot(ctx) <= st.deactivation_slot + DEACTIVATE_COOLDOWN_SLOTS:
            raise AcctError("lookup table still in deactivation cooldown")
        if table.key == recipient.key:
            raise AcctError("cannot close table into itself")
        recipient.lamports += table.lamports
        table.lamports = 0
        table.data = bytearray()
        table.owner = SYSTEM_PROGRAM
    else:
        raise AcctError(f"unknown lookup table instruction {tag}")


# -- executor-side resolution -------------------------------------------------


class LookupError_(AcctError):
    """A v0 lookup could not resolve (missing/foreign/short table, index
    out of range) — fails the TRANSACTION, never the block."""


def _load_table(key: bytes, load, cache: dict | None) -> TableState:
    if cache is not None and key in cache:
        hit = cache[key]
        if isinstance(hit, LookupError_):
            raise hit
        return hit
    try:
        st = _load_table_uncached(key, load)
    except LookupError_ as e:
        if cache is not None:
            cache[key] = e
        raise
    if cache is not None:
        cache[key] = st
    return st


def _load_table_uncached(key: bytes, load) -> TableState:
    from firedancer_tpu.flamenco.executor import acct_decode

    val = load(key)
    if val is None:
        raise LookupError_("lookup table account missing")
    _, owner, _, data = acct_decode(val)
    if owner != ALT_PROGRAM:
        raise LookupError_("lookup table owned by a foreign program")
    try:
        return TableState.decode(data)
    except AcctError as e:
        raise LookupError_(str(e)) from e


def resolve_lookups(
    payload: bytes, desc, load, *, slot: int | None = None,
    table_cache: dict | None = None,
) -> tuple[list[bytes], list[bytes]]:
    """Resolve a parsed v0 txn's address-table lookups.

    load(key: bytes) -> account value bytes | None (the funk record at the
    start of the slot).  Returns (writable_addrs, readonly_addrs) in
    lookup order — the combined account list is
    static + writable_addrs + readonly_addrs, matching Txn.is_writable's
    index space.  Raises LookupError_ on any unresolvable lookup.

    slot: when given, tables whose deactivation completed (past the
    cooldown) no longer resolve — the reference's Deactivated status.
    table_cache: optional per-block memo (key -> TableState | LookupError_)
    so N txns on one table decode it once; callers own its lifetime
    (resolution is start-of-slot, so reuse within a block is exact).
    """
    writable: list[bytes] = []
    readonly: list[bytes] = []
    for lut in desc.addr_luts:
        key = payload[lut.addr_off : lut.addr_off + 32]
        st = _load_table(key, load, table_cache)
        if slot is not None and st.deactivation_slot != U64_MAX and (
            slot > st.deactivation_slot + DEACTIVATE_COOLDOWN_SLOTS
        ):
            raise LookupError_("lookup table is deactivated")
        for off, cnt, sink in (
            (lut.writable_off, lut.writable_cnt, writable),
            (lut.readonly_off, lut.readonly_cnt, readonly),
        ):
            for i in range(cnt):
                idx = payload[off + i]
                if idx >= len(st.addresses):
                    raise LookupError_(
                        f"lookup index {idx} out of range "
                        f"({len(st.addresses)} addresses)"
                    )
                sink.append(st.addresses[idx])
    return writable, readonly
