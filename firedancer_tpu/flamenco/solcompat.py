"""Agave-conformance fixture harness (the sol_compat shape).

The reference's heavyweight correctness strategy replays the public
test-vectors corpus through instruction-level harnesses
(/root/reference/src/flamenco/runtime/tests/fd_exec_sol_compat.c:36-42,
fd_exec_instr_test.c fd_exec_instr_fixture_run); fixtures are protobuf
`InstrFixture` messages (schema: org.solana.sealevel.v1, field tags
mirrored from the nanopb descriptors in
/root/reference/src/flamenco/runtime/tests/generated/{invoke,context}.pb.h).

This module is the TPU build's adapter: a self-contained protobuf wire
codec (no protoc dependency), the fixture schema, and a runner that
replays an InstrContext through flamenco.executor and diffs the observed
effects against InstrEffects.  Pointing it at the real corpus (the
`dump/test-vectors` tree the reference's CI fetches) is zero further
work; the committed mini-corpus under tests/fixtures/instr/ was authored
with encode_fixture() in the same wire format and pins the rule edges
this build has implemented.

Comparison semantics follow fd_exec_instr_test.c:_diff_effects:
  - result compares as zero/nonzero ("error codes are not relevant to
    consensus" — invoke.pb.h:46-48); custom_err compares exactly when
    the fixture expects one;
  - modified_accounts: every listed account must match the post-state
    (lamports, owner, executable, data) exactly; accounts not listed
    must be unchanged;
  - cu_avail compares exactly when the fixture sets it (>0).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

from firedancer_tpu.protocol.base58 import b58_decode32

# -- protobuf wire codec ------------------------------------------------------

WT_VARINT = 0
WT_I64 = 1
WT_LEN = 2
WT_I32 = 5


def _uvarint(buf: bytes, off: int) -> tuple[int, int]:
    x = 0
    sh = 0
    while True:
        b = buf[off]
        off += 1
        x |= (b & 0x7F) << sh
        if not b & 0x80:
            return x, off
        sh += 7
        if sh > 70:
            raise ValueError("varint overflow")


def _enc_uvarint(x: int) -> bytes:
    out = bytearray()
    while True:
        b = x & 0x7F
        x >>= 7
        if x:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def wire_decode(buf: bytes) -> list[tuple[int, int, object]]:
    """-> [(field_no, wire_type, value)]; LEN values are bytes."""
    out = []
    off = 0
    while off < len(buf):
        key, off = _uvarint(buf, off)
        fno, wt = key >> 3, key & 7
        if wt == WT_VARINT:
            v, off = _uvarint(buf, off)
        elif wt == WT_I64:
            v = int.from_bytes(buf[off : off + 8], "little")
            off += 8
        elif wt == WT_I32:
            v = int.from_bytes(buf[off : off + 4], "little")
            off += 4
        elif wt == WT_LEN:
            ln, off = _uvarint(buf, off)
            v = buf[off : off + ln]
            if len(v) != ln:
                raise ValueError("truncated LEN field")
            off += ln
        else:
            raise ValueError(f"unsupported wire type {wt}")
        out.append((fno, wt, v))
    return out


def enc_field(fno: int, wt: int, v) -> bytes:
    key = _enc_uvarint((fno << 3) | wt)
    if wt == WT_VARINT:
        return key + _enc_uvarint(v)
    if wt == WT_I64:
        return key + int(v).to_bytes(8, "little")
    if wt == WT_LEN:
        return key + _enc_uvarint(len(v)) + bytes(v)
    raise ValueError(f"unsupported wire type {wt}")


# -- fixture schema -----------------------------------------------------------


@dataclass
class AcctState:
    address: bytes = b"\x00" * 32
    lamports: int = 0
    data: bytes = b""
    executable: bool = False
    rent_epoch: int = 0
    owner: bytes = b"\x00" * 32

    @classmethod
    def decode(cls, buf: bytes) -> "AcctState":
        a = cls()
        for fno, _wt, v in wire_decode(buf):
            if fno == 1:
                a.address = bytes(v)
            elif fno == 2:
                a.lamports = v
            elif fno == 3:
                a.data = bytes(v)
            elif fno == 4:
                a.executable = bool(v)
            elif fno == 5:
                a.rent_epoch = v
            elif fno == 6:
                a.owner = bytes(v)
        return a

    def encode(self) -> bytes:
        out = enc_field(1, WT_LEN, self.address)
        if self.lamports:
            out += enc_field(2, WT_VARINT, self.lamports)
        if self.data:
            out += enc_field(3, WT_LEN, self.data)
        if self.executable:
            out += enc_field(4, WT_VARINT, 1)
        if self.rent_epoch:
            out += enc_field(5, WT_VARINT, self.rent_epoch)
        out += enc_field(6, WT_LEN, self.owner)
        return out


@dataclass
class InstrAcctRef:
    index: int = 0
    is_writable: bool = False
    is_signer: bool = False

    @classmethod
    def decode(cls, buf: bytes) -> "InstrAcctRef":
        a = cls()
        for fno, _wt, v in wire_decode(buf):
            if fno == 1:
                a.index = v
            elif fno == 2:
                a.is_writable = bool(v)
            elif fno == 3:
                a.is_signer = bool(v)
        return a

    def encode(self) -> bytes:
        out = enc_field(1, WT_VARINT, self.index)
        if self.is_writable:
            out += enc_field(2, WT_VARINT, 1)
        if self.is_signer:
            out += enc_field(3, WT_VARINT, 1)
        return out


@dataclass
class InstrContext:
    program_id: bytes = b"\x00" * 32
    accounts: list[AcctState] = field(default_factory=list)
    instr_accounts: list[InstrAcctRef] = field(default_factory=list)
    data: bytes = b""
    cu_avail: int = 0
    slot: int = 10  # SlotContext.slot
    features: list[int] = field(default_factory=list)  # EpochContext ids

    @classmethod
    def decode(cls, buf: bytes) -> "InstrContext":
        c = cls(slot=0)
        for fno, _wt, v in wire_decode(buf):
            if fno == 1:
                c.program_id = bytes(v)
            elif fno == 3:
                c.accounts.append(AcctState.decode(v))
            elif fno == 4:
                c.instr_accounts.append(InstrAcctRef.decode(v))
            elif fno == 5:
                c.data = bytes(v)
            elif fno == 6:
                c.cu_avail = v
            elif fno == 8:  # SlotContext
                for f2, _w2, v2 in wire_decode(v):
                    if f2 == 1:
                        c.slot = v2
            elif fno == 9:  # EpochContext { FeatureSet features = 1 }
                for f2, _w2, v2 in wire_decode(v):
                    if f2 == 1:
                        for f3, w3, v3 in wire_decode(v2):
                            if f3 != 1:
                                continue
                            if w3 == WT_I64:
                                c.features.append(v3)
                            elif w3 == WT_LEN:
                                # proto3 packs repeated fixed64 (protoc/
                                # nanopb corpora); 8-byte LE chunks
                                for i in range(0, len(v3) - 7, 8):
                                    c.features.append(
                                        int.from_bytes(v3[i : i + 8],
                                                       "little")
                                    )
        return c

    def encode(self) -> bytes:
        out = enc_field(1, WT_LEN, self.program_id)
        for a in self.accounts:
            out += enc_field(3, WT_LEN, a.encode())
        for ia in self.instr_accounts:
            out += enc_field(4, WT_LEN, ia.encode())
        if self.data:
            out += enc_field(5, WT_LEN, self.data)
        if self.cu_avail:
            out += enc_field(6, WT_VARINT, self.cu_avail)
        out += enc_field(8, WT_LEN, enc_field(1, WT_VARINT, self.slot))
        if self.features:
            feats = b"".join(enc_field(1, WT_I64, f) for f in self.features)
            out += enc_field(9, WT_LEN, enc_field(1, WT_LEN, feats))
        return out


@dataclass
class InstrEffects:
    result: int = 0
    custom_err: int = 0
    modified_accounts: list[AcctState] = field(default_factory=list)
    cu_avail: int = 0
    return_data: bytes = b""

    @classmethod
    def decode(cls, buf: bytes) -> "InstrEffects":
        e = cls()
        for fno, _wt, v in wire_decode(buf):
            if fno == 1:
                # int32 result rides as a varint (possibly sign-extended)
                e.result = v - (1 << 64) if v >= 1 << 63 else v
            elif fno == 2:
                e.custom_err = v
            elif fno == 3:
                e.modified_accounts.append(AcctState.decode(v))
            elif fno == 4:
                e.cu_avail = v
            elif fno == 5:
                e.return_data = bytes(v)
        return e

    def encode(self) -> bytes:
        out = b""
        if self.result:
            out += enc_field(1, WT_VARINT, self.result & ((1 << 64) - 1))
        if self.custom_err:
            out += enc_field(2, WT_VARINT, self.custom_err)
        for a in self.modified_accounts:
            out += enc_field(3, WT_LEN, a.encode())
        if self.cu_avail:
            out += enc_field(4, WT_VARINT, self.cu_avail)
        if self.return_data:
            out += enc_field(5, WT_LEN, self.return_data)
        return out


@dataclass
class InstrFixture:
    input: InstrContext
    output: InstrEffects

    @classmethod
    def decode(cls, buf: bytes) -> "InstrFixture":
        inp, outp = InstrContext(), InstrEffects()
        for fno, _wt, v in wire_decode(buf):
            if fno == 1:
                inp = InstrContext.decode(v)
            elif fno == 2:
                outp = InstrEffects.decode(v)
        return cls(inp, outp)

    def encode(self) -> bytes:
        return enc_field(1, WT_LEN, self.input.encode()) + enc_field(
            2, WT_LEN, self.output.encode()
        )


def load_fixture(path: str) -> InstrFixture:
    with open(path, "rb") as f:
        return InstrFixture.decode(f.read())


# -- runner -------------------------------------------------------------------

# canonical sysvar account addresses -> the names flamenco's TxnCtx uses
SYSVAR_NAMES = {
    b58_decode32("SysvarC1ock11111111111111111111111111111111"): "clock",
    b58_decode32("SysvarRent111111111111111111111111111111111"): "rent",
    b58_decode32("SysvarEpochSchedu1e111111111111111111111111"):
        "epoch_schedule",
    b58_decode32("SysvarS1otHashes111111111111111111111111111"): "slot_hashes",
}


@dataclass
class FixtureDiff:
    ok: bool
    mismatches: list[str]


def run_instr_fixture(fix: InstrFixture) -> FixtureDiff:
    """Replay fix.input through the executor; diff against fix.output."""
    from firedancer_tpu.flamenco.executor import (
        Account, Executor, InstrAccount, InstrError, TxnCtx,
    )
    from firedancer_tpu.flamenco.runtime import default_sysvars

    ctx_accounts = []
    signer = []
    writable = []
    for a in fix.input.accounts:
        ctx_accounts.append(
            Account(
                key=a.address,
                lamports=a.lamports,
                owner=a.owner,
                executable=a.executable,
                data=bytearray(a.data),
            )
        )
        signer.append(False)
        writable.append(False)
    iaccts = []
    for ia in fix.input.instr_accounts:
        if ia.index >= len(ctx_accounts):
            return FixtureDiff(False, ["instr account index out of range"])
        iaccts.append(
            InstrAccount(
                txn_idx=ia.index,
                is_signer=ia.is_signer,
                is_writable=ia.is_writable,
            )
        )
        signer[ia.index] = signer[ia.index] or ia.is_signer
        writable[ia.index] = writable[ia.index] or ia.is_writable

    sysvars = dict(default_sysvars(fix.input.slot))
    for a in fix.input.accounts:
        name = SYSVAR_NAMES.get(a.address)
        if name is not None and a.data:
            sysvars[name] = bytes(a.data)

    cu = fix.input.cu_avail or 200_000
    ctx = TxnCtx(
        accounts=ctx_accounts,
        signer=signer,
        writable=writable,
        budget=cu,
        sysvars=sysvars,
    )
    ex = Executor()
    err: InstrError | None = None
    try:
        ex.execute_instr(ctx, fix.input.program_id, iaccts, fix.input.data)
    except InstrError as e:
        err = e
    except Exception as e:  # untyped escape = harness-visible bug
        return FixtureDiff(
            False, [f"untyped {type(e).__name__}: {e}"]
        )

    mism: list[str] = []
    want = fix.output
    # result: zero/nonzero parity; exact custom code when expected
    if bool(want.result) != bool(err):
        mism.append(
            f"result: expected {'error' if want.result else 'success'}, "
            f"got {'error: ' + str(err) if err else 'success'}"
        )
    if want.custom_err and (err is None or err.custom != want.custom_err):
        mism.append(
            f"custom_err: expected {want.custom_err}, "
            f"got {getattr(err, 'custom', None)}"
        )
    # modified accounts listed must match exactly
    by_addr = {a.key: a for a in ctx_accounts}
    for m in want.modified_accounts:
        got = by_addr.get(m.address)
        if got is None:
            mism.append(f"modified acct {m.address[:4].hex()} not in ctx")
            continue
        if got.lamports != m.lamports:
            mism.append(
                f"acct {m.address[:4].hex()} lamports "
                f"{got.lamports} != {m.lamports}"
            )
        if bytes(got.data) != m.data:
            mism.append(f"acct {m.address[:4].hex()} data differs")
        if got.owner != m.owner:
            mism.append(f"acct {m.address[:4].hex()} owner differs")
        if bool(got.executable) != bool(m.executable):
            mism.append(f"acct {m.address[:4].hex()} executable differs")
    # accounts NOT listed must be unchanged (success paths only: Agave
    # rolls back all writes on error, and so does the txn-level caller
    # here — instruction-level state is only committed on success)
    if not want.result and not err:
        listed = {m.address for m in want.modified_accounts}
        for orig in fix.input.accounts:
            if orig.address in listed:
                continue
            got = by_addr[orig.address]
            if (
                got.lamports != orig.lamports
                or bytes(got.data) != orig.data
                or got.owner != orig.owner
            ):
                mism.append(
                    f"acct {orig.address[:4].hex()} changed but not in "
                    "modified_accounts"
                )
    if want.cu_avail:
        got_avail = cu - ctx.cu_used
        if got_avail != want.cu_avail:
            mism.append(f"cu_avail {got_avail} != {want.cu_avail}")
    if want.return_data:
        if ctx.return_data[1] != want.return_data:
            mism.append("return_data differs")
    return FixtureDiff(not mism, mism)


def run_corpus(root: str) -> dict:
    """Run every .fix under `root`; -> {path: FixtureDiff} (sorted)."""
    out = {}
    for dirpath, _dirs, files in os.walk(root):
        for f in sorted(files):
            if not f.endswith(".fix"):
                continue
            p = os.path.join(dirpath, f)
            try:
                out[p] = run_instr_fixture(load_fixture(p))
            except Exception as e:
                out[p] = FixtureDiff(False, [f"load/run: {e}"])
    return out
