"""Blockstore: persistent shred/block store + status cache (txncache).

Counterparts of /root/reference/src/flamenco/runtime/fd_blockstore.c
(wksp-backed shred/block map with slot metadata) and fd_txncache.c (the
consensus-critical "has this txn already landed / is this blockhash
still current" checks).  Capability parity targets, no code shared: the
reference stores into relocatable shared memory with lock-free maps;
this build is a host Python library over an append-only log file —
restart-safe, which is the property the r3 verdict asked for.

Blockstore layout: one append-only log of framed records

    u32 magic 'FDBS' | u8 kind | u64 slot | u32 idx | u32 len | bytes

kind 0 = shred (idx = shred index within the slot, bytes = wire shred).
On open the log replays into the in-memory index; inserts append + index.
Torn tails (a crash mid-write) truncate at the last whole record.

Status cache: entries (blockhash, signature) -> slot, plus the recent-
blockhash registry with the protocol's 150-slot max age.  Fork awareness
is ancestor-set filtering (the reference's per-fork rooted slices serve
the same query shape); purging below the root bounds memory.
"""

from __future__ import annotations

import os
import struct
from dataclasses import dataclass, field

from firedancer_tpu.protocol import shred as fshred

_REC = struct.Struct("<IBQII")
_MAGIC = 0x53424446  # 'FDBS'

KIND_SHRED = 0


@dataclass
class SlotMeta:
    """Per-slot bookkeeping (fd_blockstore's slot meta analog)."""

    slot: int
    received: set = field(default_factory=set)  # shred indices present
    last_index: int | None = None  # index of the LAST data shred (flag)

    @property
    def complete(self) -> bool:
        # contents check, not cardinality: a stray index above last_index
        # (adversarial or repair-path shred) must not fake completeness
        return self.last_index is not None and all(
            i in self.received for i in range(self.last_index + 1)
        )

    def missing(self, upto: int | None = None) -> list[int]:
        """Absent indices below the highest seen (repair's request list)."""
        hi = self.last_index
        if hi is None:
            hi = (max(self.received) if self.received else -1)
        if upto is not None:
            hi = min(hi, upto)
        return [i for i in range(hi + 1) if i not in self.received]


class Blockstore:
    def __init__(self, path: str | None = None):
        self.path = path
        self._log = None
        self.shreds: dict[tuple[int, int], bytes] = {}
        self.meta: dict[int, SlotMeta] = {}
        if path is not None:
            self._open_log(path)

    # -- persistence --

    def _open_log(self, path: str) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        if os.path.exists(path):
            self._replay(path)
        self._log = open(path, "ab")

    def _replay(self, path: str) -> None:
        with open(path, "rb") as f:
            buf = f.read()
        off = 0
        good_end = 0
        while off + _REC.size <= len(buf):
            magic, kind, slot, idx, ln = _REC.unpack_from(buf, off)
            if magic != _MAGIC or off + _REC.size + ln > len(buf):
                break  # torn tail: keep everything before it
            payload = buf[off + _REC.size : off + _REC.size + ln]
            if kind == KIND_SHRED:
                self._index_shred(slot, idx, payload)
            off += _REC.size + ln
            good_end = off
        if good_end != len(buf):
            with open(path, "ab") as f:
                f.truncate(good_end)

    def close(self) -> None:
        if self._log is not None:
            self._log.close()
            self._log = None

    # -- inserts / queries --

    def _index_shred(self, slot: int, idx: int, payload: bytes) -> None:
        self.shreds[(slot, idx)] = payload
        m = self.meta.setdefault(slot, SlotMeta(slot))
        m.received.add(idx)
        sh = fshred.parse(payload)
        if sh is not None and sh.is_data and (
            sh.flags & fshred.DATA_FLAG_SLOT_COMPLETE
        ):
            m.last_index = idx

    def insert_shred(self, payload: bytes) -> None:
        """Store one wire DATA shred (idempotent by (slot, index)); code
        shreds live in the FEC resolver, not the block history."""
        sh = fshred.parse(payload)
        if sh is None:
            raise ValueError("malformed shred")
        if not sh.is_data:
            return
        slot, idx = sh.slot, sh.idx
        if (slot, idx) in self.shreds:
            return
        if self._log is not None:
            self._log.write(
                _REC.pack(_MAGIC, KIND_SHRED, slot, idx, len(payload))
            )
            self._log.write(payload)
            self._log.flush()
        self._index_shred(slot, idx, payload)

    def slot_meta(self, slot: int) -> SlotMeta | None:
        return self.meta.get(slot)

    def slots(self) -> list[int]:
        return sorted(self.meta)

    def is_complete(self, slot: int) -> bool:
        m = self.meta.get(slot)
        return m is not None and m.complete

    def entry_batch_bytes(self, slot: int) -> bytes:
        """Concatenated data-shred payloads for a complete slot, in
        index order (what replay consumes)."""
        m = self.meta.get(slot)
        if m is None or not m.complete:
            raise KeyError(f"slot {slot} incomplete in blockstore")
        out = bytearray()
        for idx in range(m.last_index + 1):
            buf = self.shreds[(slot, idx)]
            sh = fshred.parse(buf)
            out += sh.payload(buf)
        return bytes(out)

    def prune_below(self, slot: int) -> None:
        """Drop in-memory state for slots < `slot` (rooted history); the
        log keeps the bytes until the next compaction (rewrite)."""
        for s in [s for s in self.meta if s < slot]:
            m = self.meta.pop(s)
            for idx in m.received:
                self.shreds.pop((s, idx), None)

    def compact(self) -> None:
        """Rewrite the log with only the live (unpruned) records."""
        if self.path is None:
            return
        self.close()
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            for (slot, idx), payload in sorted(self.shreds.items()):
                f.write(_REC.pack(_MAGIC, KIND_SHRED, slot, idx,
                                  len(payload)))
                f.write(payload)
        os.replace(tmp, self.path)
        self._log = open(self.path, "ab")


# -- status cache (txncache) --------------------------------------------------

MAX_BLOCKHASH_AGE = 150  # slots a recent blockhash stays usable


class StatusCache:
    """(blockhash, signature) -> slot executed, + the recent-blockhash
    registry.  fd_txncache.c's two consensus questions:

      - is this txn's recent_blockhash still current?  (age <= 150 slots
        behind the executing bank)
      - did this signature already land on this fork?  (ancestor-filtered
        duplicate rejection)
    """

    def __init__(self):
        # bumped whenever the blockhash registry changes, so callers
        # caching a derived view (the native gate's valid set) can
        # re-ship only on change
        self.version = 0
        self.blockhash_slot: dict[bytes, int] = {}
        self.seen: dict[tuple[bytes, bytes], list[int]] = {}
        # signature-keyed index for the RPC's getSignatureStatuses (a hot
        # polling endpoint must not scan the whole cache per query)
        self.by_sig: dict[bytes, list[int]] = {}
        # speculative execution stages per-block inserts here until the
        # fork is chosen: commit_block merges, drop_block discards — an
        # abandoned competing block must never gate a sibling at the same
        # slot (fd_txncache's per-fork slices serve the same isolation)
        self._staged: dict[bytes, tuple[int, list, list[bytes]]] = {}
        # set view over each staged block's (blockhash, sig) inserts so
        # contains_staged is O(ancestors), not O(inserts) — a leader
        # extending a chain of unrooted blocks gates against every one
        self._staged_seen: dict[bytes, set] = {}

    def register_blockhash(self, blockhash: bytes, slot: int) -> None:
        if blockhash not in self.blockhash_slot:
            self.blockhash_slot[blockhash] = slot
            self.version += 1

    # -- speculative block staging --

    def begin_block(self, xid: bytes, slot: int) -> None:
        self._staged[xid] = (slot, [], [])
        self._staged_seen[xid] = set()

    def stage_insert(self, xid: bytes, blockhash: bytes, sig: bytes) -> None:
        self._staged[xid][1].append((blockhash, sig))
        self._staged_seen[xid].add((blockhash, sig))

    def stage_blockhash(self, xid: bytes, blockhash: bytes) -> None:
        self._staged[xid][2].append(blockhash)

    def contains_staged(self, blockhash: bytes, sig: bytes, xids) -> bool:
        """Did this signature land in any of the (unrooted, still-staged)
        blocks named by `xids`?  The per-fork half of the duplicate gate:
        a block extending a chain of not-yet-published ancestors must
        reject what those ancestors already carry, or a txn re-submitted
        across a leader handoff lands twice (committed entries answer
        via `contains`; xids that already committed/dropped answer
        False here and True there)."""
        key = (blockhash, sig)
        return any(
            key in s
            for x in xids
            if (s := self._staged_seen.get(x)) is not None
        )

    def commit_block(self, xid: bytes) -> None:
        """The fork containing this block was chosen: merge its entries."""
        slot, inserts, hashes = self._staged.pop(xid)
        self._staged_seen.pop(xid, None)
        for bh, sig in inserts:
            self.insert(bh, sig, slot)
        for bh in hashes:
            self.register_blockhash(bh, slot)

    def drop_block(self, xid: bytes) -> None:
        """The block's fork was abandoned: discard its staged entries."""
        self._staged.pop(xid, None)
        self._staged_seen.pop(xid, None)

    def is_blockhash_valid(self, blockhash: bytes, current_slot: int) -> bool:
        s = self.blockhash_slot.get(blockhash)
        return s is not None and current_slot - s <= MAX_BLOCKHASH_AGE

    def insert(self, blockhash: bytes, sig: bytes, slot: int) -> None:
        self.seen.setdefault((blockhash, sig), []).append(slot)
        self.by_sig.setdefault(sig, []).append(slot)

    def contains(self, blockhash: bytes, sig: bytes,
                 ancestors: set[int] | None = None) -> bool:
        hits = self.seen.get((blockhash, sig))
        if not hits:
            return False
        if ancestors is None:
            return True
        return any(s in ancestors for s in hits)

    def purge_below(self, root_slot: int) -> None:
        self.blockhash_slot = {
            bh: s for bh, s in self.blockhash_slot.items()
            if s >= root_slot - MAX_BLOCKHASH_AGE
        }
        self.version += 1
        for index in (self.seen, self.by_sig):
            dead = []
            for key, slots in index.items():
                slots[:] = [s for s in slots if s >= root_slot]
                if not slots:
                    dead.append(key)
            for key in dead:
                del index[key]
