"""The ZK ElGamal proof program.

Capability parity target:
/root/reference/src/flamenco/runtime/program/fd_zk_elgamal_proof_program.c
+ zksdk/fd_zksdk.c (Agave's programs/zk-elgamal-proof).  No code shared:
instruction dispatch, proof-data sourcing (instruction data or an
account at an offset), context-state account creation, and
CloseContextState are implemented from the program's documented
behavior over the zksdk modules (sigma proofs, bulletproof range
proofs, merlin transcripts, twisted ElGamal over ristretto255).

Instructions (u8 tag):
    0  CloseContextState
    1  VerifyZeroCiphertext
    2  VerifyCiphertextCiphertextEquality
    3  VerifyCiphertextCommitmentEquality
    4  VerifyPubkeyValidity
    5  VerifyPercentageWithCap
    6  VerifyBatchedRangeProofU64
    7  VerifyBatchedRangeProofU128
    8  VerifyBatchedRangeProofU256
    9  VerifyGroupedCiphertext2HandlesValidity
    10 VerifyBatchedGroupedCiphertext2HandlesValidity
    11 VerifyGroupedCiphertext3HandlesValidity
    12 VerifyBatchedGroupedCiphertext3HandlesValidity

A Verify* instruction takes its context+proof either inline
(data = tag || context || proof) or from account 0's data at a u32
offset (data = tag || u32 offset).  If extra accounts follow, the
verified CONTEXT is written into a proof-context-state account
(authority pubkey 32 | proof_type u8 | context), owned by this program,
closeable later via CloseContextState.
"""

from __future__ import annotations

from firedancer_tpu.protocol.base58 import b58_decode32

ZK_ELGAMAL_PROOF_PROGRAM = b58_decode32(
    "ZkE1Gama1Proof11111111111111111111111111111"
)

CTX_HEAD_SZ = 33  # authority pubkey + proof_type byte

# per-instruction CU charges (the protocol's fixed builtin costs —
# reference fd_zk_elgamal_proof_program.h FD_ZKSDK_INSTR_*_COMPUTE_UNITS)
INSTR_COMPUTE_UNITS = {
    0: 3_300,
    1: 6_000,
    2: 8_000,
    3: 6_400,
    4: 2_600,
    5: 6_500,
    6: 111_000,
    7: 200_000,
    8: 368_000,
    9: 6_400,
    10: 13_000,
    11: 8_100,
    12: 16_400,
}

# tag -> (context size, proof size, verifier)


def _sizes():
    from firedancer_tpu.flamenco.zksdk import sigma

    return {
        1: (96, 96, sigma.verify_zero_ciphertext),
        2: (192, 224, sigma.verify_ciphertext_ciphertext_equality),
        3: (128, 192, sigma.verify_ciphertext_commitment_equality),
        4: (32, 64, sigma.verify_pubkey_validity),
        5: (104, 256, sigma.verify_percentage_with_cap),
        6: (264, 672, _verify_range(6)),
        7: (264, 736, _verify_range(7)),
        8: (264, 800, _verify_range(8)),
        9: (160, 160, sigma.verify_grouped_ciphertext_2_handles_validity),
        10: (256, 160,
             sigma.verify_batched_grouped_ciphertext_2_handles_validity),
        11: (224, 192, sigma.verify_grouped_ciphertext_3_handles_validity),
        12: (352, 192,
             sigma.verify_batched_grouped_ciphertext_3_handles_validity),
    }


def _verify_range(logn: int):
    def verify(context: bytes, proof: bytes) -> None:
        from firedancer_tpu.flamenco.zksdk import rangeproof as rp
        from firedancer_tpu.flamenco.zksdk.merlin import Transcript
        from firedancer_tpu.flamenco.zksdk.sigma import ZkError

        comms_blob = context[: 8 * 32]
        bits_blob = context[8 * 32 : 8 * 32 + 8]
        # batch length = first all-zero commitment (Agave's rule)
        batch = 0
        while batch < 8 and comms_blob[32 * batch : 32 * (batch + 1)] != \
                bytes(32):
            batch += 1
        if batch == 0:
            raise ZkError("empty commitment batch")
        t = Transcript(b"batched-range-proof-instruction")
        t.append_message(b"commitments", comms_blob)
        t.append_message(b"bit-lengths", bits_blob)
        rp.verify_range_proof(
            [comms_blob[32 * i : 32 * (i + 1)] for i in range(batch)],
            list(bits_blob[:batch]),
            proof, t, logn,
        )

    return verify


def zk_elgamal_program(executor, ctx, program_id, iaccts, data, *,
                       pda_signers):
    from firedancer_tpu.flamenco.programs import AcctError
    from firedancer_tpu.flamenco.executor import InstrError
    from firedancer_tpu.flamenco.zksdk.sigma import ZkError

    if not data:
        raise InstrError("zk: empty instruction")
    tag = data[0]
    # the protocol's fixed per-instruction CU charge (bulletproof range
    # verifies are the most expensive builtins — an unpriced verify
    # would bypass the block cost model entirely)
    ctx.charge(INSTR_COMPUTE_UNITS.get(tag, 6_000))
    if tag == 0:
        return _close_context_state(ctx, iaccts)
    table = _sizes()
    if tag not in table:
        raise InstrError(f"zk: unknown instruction {tag}")
    ctx_sz, proof_sz, verify = table[tag]

    accessed = 0
    if len(data) == 5:
        # proof data from account 0 at a u32 offset
        if not iaccts:
            raise AcctError("zk: missing proof-data account")
        off = int.from_bytes(data[1:5], "little")
        acct = ctx.accounts[iaccts[0].txn_idx]
        blob = bytes(acct.data)
        if off + ctx_sz + proof_sz > len(blob):
            raise InstrError("zk: proof data out of account bounds")
        context = blob[off : off + ctx_sz]
        proof = blob[off + ctx_sz : off + ctx_sz + proof_sz]
        accessed = 1
    else:
        if len(data) != 1 + ctx_sz + proof_sz:
            raise InstrError("zk: bad instruction data size")
        context = data[1 : 1 + ctx_sz]
        proof = data[1 + ctx_sz :]

    try:
        verify(context, proof)
    except ZkError as e:
        raise InstrError(f"zk: {e}")

    # optional context-state creation
    if len(iaccts) > accessed:
        if len(iaccts) < accessed + 2:
            raise AcctError("zk: context state needs authority account")
        authority = ctx.accounts[iaccts[accessed + 1].txn_idx].key
        state_ia = iaccts[accessed]
        state = ctx.accounts[state_ia.txn_idx]
        if state.owner != ZK_ELGAMAL_PROOF_PROGRAM:
            raise AcctError("zk: context account not program-owned")
        if len(state.data) >= CTX_HEAD_SZ and state.data[32] != 0:
            raise InstrError("zk: context account already initialized")
        if len(state.data) != CTX_HEAD_SZ + ctx_sz:
            raise InstrError("zk: context account wrong size")
        if not state_ia.is_writable:
            raise AcctError("zk: context account not writable")
        state.data = bytearray(authority + bytes([tag]) + context)


def _close_context_state(ctx, iaccts):
    from firedancer_tpu.flamenco.programs import AcctError
    from firedancer_tpu.flamenco.executor import InstrError
    from firedancer_tpu.protocol.txn import SYSTEM_PROGRAM

    if len(iaccts) < 3:
        raise AcctError("zk close: needs proof, dest, owner accounts")
    proof_ia, dest_ia, owner_ia = iaccts[0], iaccts[1], iaccts[2]
    if not owner_ia.is_signer:
        raise AcctError("zk close: owner must sign")
    proof_acct = ctx.accounts[proof_ia.txn_idx]
    dest_acct = ctx.accounts[dest_ia.txn_idx]
    owner = ctx.accounts[owner_ia.txn_idx].key
    if proof_acct.owner != ZK_ELGAMAL_PROOF_PROGRAM:
        # only THIS program's accounts may be drained/reassigned here —
        # native programs mutate accounts directly, so the BPF-side
        # owner-may-debit backstop never runs for them
        raise AcctError("zk close: account not owned by the zk program")
    if proof_acct.key == dest_acct.key:
        raise InstrError("zk close: dest == proof account")
    if len(proof_acct.data) < CTX_HEAD_SZ:
        raise InstrError("zk close: not a context account")
    if bytes(proof_acct.data[:32]) != owner:
        raise AcctError("zk close: wrong context authority")
    if not proof_ia.is_writable or not dest_ia.is_writable:
        raise AcctError("zk close: accounts not writable")
    dest_acct.lamports += proof_acct.lamports
    proof_acct.lamports = 0
    proof_acct.data = bytearray()
    proof_acct.owner = SYSTEM_PROGRAM
