"""sBPF virtual machine interpreter (the flamenco/vm layer).

Counterpart of /root/reference/src/flamenco/vm/fd_vm_interp_core.c (the
872-line computed-goto loop) and the fd_vm memory map (fd_vm.h:22-42):
eleven 64-bit registers, a compute budget charged per instruction, and a
segmented virtual address space —

    0x1_0000_0000  program rodata     (read-only)
    0x2_0000_0000  stack              (read-write)
    0x3_0000_0000  heap               (read-write)
    0x4_0000_0000  input (accounts)   (read-write)

Every load/store translates through the region table with bounds checks;
faults, division by zero, bad calls and budget exhaustion abort cleanly
with a typed error (the VM is branchy host-side work by design — SURVEY
§7.1 keeps it off the TPU; the device-batchable pieces, sigverify and
hashing, are syscalls into the ops layer).

Syscalls are registered by 32-bit id (the reference hashes syscall names
into ids; registration is the deployer's choice here) and receive
(vm, r1..r5), returning the new r0.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from firedancer_tpu.protocol import sbpf

MM_PROGRAM = 1 << 32
MM_STACK = 2 << 32
MM_HEAP = 3 << 32
MM_INPUT = 4 << 32

STACK_SZ = 64 * 1024
HEAP_SZ = 32 * 1024
DEFAULT_BUDGET = 200_000

_M64 = (1 << 64) - 1
_M32 = (1 << 32) - 1


class VmError(RuntimeError):
    pass


class VmFault(VmError):
    """Memory access violation."""


class VmBudget(VmError):
    """Compute budget exhausted."""


@dataclass
class Region:
    start: int
    data: bytearray
    writable: bool


@dataclass
class Vm:
    program: sbpf.Program
    input_data: bytes = b""
    budget: int = DEFAULT_BUDGET
    syscalls: dict[int, object] = field(default_factory=dict)

    def __post_init__(self):
        self.regs = [0] * 11
        self.pc = self.program.entry_pc
        self.cu_used = 0
        self.insns = {i.pc: i for i in sbpf.decode(self.program.text())}
        self.regions = [
            Region(MM_PROGRAM, bytearray(self.program.rodata), False),
            Region(MM_STACK, bytearray(STACK_SZ), True),
            Region(MM_HEAP, bytearray(HEAP_SZ), True),
            Region(MM_INPUT, bytearray(self.input_data), True),
        ]
        self.regs[10] = MM_STACK + STACK_SZ  # frame pointer at stack top
        self.regs[1] = MM_INPUT

    # -- memory -------------------------------------------------------------

    def _region(self, vaddr: int, sz: int, write: bool) -> tuple[Region, int]:
        for r in self.regions:
            off = vaddr - r.start
            if 0 <= off and off + sz <= len(r.data):
                if write and not r.writable:
                    raise VmFault(f"write to read-only 0x{vaddr:x}")
                return r, off
        raise VmFault(f"access violation at 0x{vaddr:x} sz {sz}")

    def mem_read(self, vaddr: int, sz: int) -> int:
        r, off = self._region(vaddr, sz, write=False)
        return int.from_bytes(r.data[off : off + sz], "little")

    def mem_read_bytes(self, vaddr: int, sz: int) -> bytes:
        r, off = self._region(vaddr, sz, write=False)
        return bytes(r.data[off : off + sz])

    def mem_write(self, vaddr: int, sz: int, val: int) -> None:
        r, off = self._region(vaddr, sz, write=True)
        r.data[off : off + sz] = (val & ((1 << (8 * sz)) - 1)).to_bytes(sz, "little")

    # -- execution ----------------------------------------------------------

    @staticmethod
    def _s64(v: int) -> int:
        return v - (1 << 64) if v >> 63 else v

    @staticmethod
    def _s32(v: int) -> int:
        v &= _M32
        return v - (1 << 32) if v >> 31 else v

    def run(self) -> int:
        """Execute until exit; returns r0."""
        regs = self.regs
        while True:
            self.cu_used += 1
            if self.cu_used > self.budget:
                raise VmBudget(f"compute budget exceeded ({self.budget})")
            ins = self.insns.get(self.pc)
            if ins is None:
                raise VmError(f"bad pc {self.pc}")
            mn = ins.mnemonic
            dst, src, off, imm = ins.dst, ins.src, ins.off, ins.imm
            nxt = self.pc + (2 if mn == "lddw" else 1)

            if mn == "exit":
                return regs[0]
            elif mn == "lddw":
                regs[dst] = imm & _M64
            elif mn == "call":
                fn = self.syscalls.get(imm & _M32)
                if fn is None:
                    raise VmError(f"unknown syscall 0x{imm & _M32:x}")
                regs[0] = fn(self, *regs[1:6]) & _M64
            elif mn == "callx":
                raise VmError("callx unsupported")
            elif mn.startswith("j"):
                taken = self._jump_taken(mn, regs, dst, src, imm)
                if taken:
                    nxt = self.pc + 1 + off
            elif mn.startswith(("ldx",)):
                sz = {"ldxb": 1, "ldxh": 2, "ldxw": 4, "ldxdw": 8}[mn]
                regs[dst] = self.mem_read((regs[src] + off) & _M64, sz)
            elif mn.startswith("stx"):
                sz = {"stxb": 1, "stxh": 2, "stxw": 4, "stxdw": 8}[mn]
                self.mem_write((regs[dst] + off) & _M64, sz, regs[src])
            elif mn.startswith("st"):
                sz = {"stb": 1, "sth": 2, "stw": 4, "stdw": 8}[mn]
                self.mem_write((regs[dst] + off) & _M64, sz, imm & _M64)
            else:
                self._alu(mn, regs, dst, src, imm)
            self.pc = nxt

    def _jump_taken(self, mn, regs, dst, src, imm) -> bool:
        if mn == "ja":
            return True
        kind, mode = mn[1:].rsplit("_", 1)
        b = regs[src] if mode == "reg" else imm & _M64
        a = regs[dst]
        sa, sb = self._s64(a), self._s64(b)
        return {
            "eq": a == b, "ne": a != b, "set": bool(a & b),
            "gt": a > b, "ge": a >= b, "lt": a < b, "le": a <= b,
            "sgt": sa > sb, "sge": sa >= sb, "slt": sa < sb, "sle": sa <= sb,
        }[kind]

    def _alu(self, mn, regs, dst, src, imm) -> None:
        is32 = "32" in mn
        mask = _M32 if is32 else _M64
        if mn in ("neg64", "neg32"):
            regs[dst] = (-regs[dst]) & mask
            return
        if mn in ("le", "be"):  # byte-order ops: widths via imm (16/32/64)
            width = imm
            if width not in (16, 32, 64):
                raise VmError(f"bad byte-order width {width}")
            v = regs[dst] & ((1 << width) - 1)
            if mn == "be":
                v = int.from_bytes(
                    v.to_bytes(width // 8, "little"), "big"
                )
            regs[dst] = v
            return
        op, mode = mn.rsplit("_", 1)
        b = (regs[src] if mode == "reg" else imm) & mask
        a = regs[dst] & mask
        if op.startswith("add"):
            r = a + b
        elif op.startswith("sub"):
            r = a - b
        elif op.startswith("mul"):
            r = a * b
        elif op.startswith("div"):
            if b == 0:
                raise VmError("division by zero")
            r = a // b
        elif op.startswith("mod"):
            if b == 0:
                raise VmError("division by zero")
            r = a % b
        elif op.startswith("or"):
            r = a | b
        elif op.startswith("and"):
            r = a & b
        elif op.startswith("xor"):
            r = a ^ b
        elif op.startswith("lsh"):
            r = a << (b & (31 if is32 else 63))
        elif op.startswith("rsh"):
            r = a >> (b & (31 if is32 else 63))
        elif op.startswith("arsh"):
            s = self._s32(a) if is32 else self._s64(a)
            r = s >> (b & (31 if is32 else 63))
        elif op.startswith("mov"):
            r = b
        else:
            raise VmError(f"unhandled alu {mn}")
        regs[dst] = r & mask


# -- the device-backed syscalls (the TPU bridge) ------------------------------

SYSCALL_SOL_SHA256 = 0x11F49D86
SYSCALL_SOL_KECCAK256 = 0xD7793ABB
SYSCALL_SOL_LOG = 0x207559BD
SYSCALL_SOL_SECP256K1_RECOVER = 0x17E40350
SYSCALL_SOL_CREATE_PROGRAM_ADDRESS = 0x9377323C
SYSCALL_SOL_TRY_FIND_PROGRAM_ADDRESS = 0x48504A38


def register_default_syscalls(vm: Vm, *, log_sink: list | None = None) -> None:
    """sol_sha256 / sol_keccak256 / sol_log — the hashing syscalls route
    into the ops layer (host path here; the batched device path serves
    bulk callers), mirroring fd_vm_syscall_sol_sha256 etc."""
    import hashlib

    from firedancer_tpu.ops import keccak256 as kk

    def sol_sha256(vm_, vals_addr, vals_len, result_addr, *_):
        data = b""
        for i in range(vals_len):
            addr = vm_.mem_read(vals_addr + 16 * i, 8)
            sz = vm_.mem_read(vals_addr + 16 * i + 8, 8)
            data += vm_.mem_read_bytes(addr, sz)
        digest = hashlib.sha256(data).digest()
        for j, byte in enumerate(digest):
            vm_.mem_write(result_addr + j, 1, byte)
        return 0

    def sol_keccak256(vm_, vals_addr, vals_len, result_addr, *_):
        data = b""
        for i in range(vals_len):
            addr = vm_.mem_read(vals_addr + 16 * i, 8)
            sz = vm_.mem_read(vals_addr + 16 * i + 8, 8)
            data += vm_.mem_read_bytes(addr, sz)
        digest = kk.keccak256_host(data)
        for j, byte in enumerate(digest):
            vm_.mem_write(result_addr + j, 1, byte)
        return 0

    def sol_log(vm_, addr, sz, *_):
        msg = vm_.mem_read_bytes(addr, sz)
        if log_sink is not None:
            log_sink.append(msg)
        return 0

    def sol_secp256k1_recover(vm_, hash_addr, recovery_id, sig_addr, result_addr, *_):
        from firedancer_tpu.ops import secp256k1 as sk

        h = vm_.mem_read_bytes(hash_addr, 32)
        sig = vm_.mem_read_bytes(sig_addr, 64)
        try:
            pub = sk.recover(h, recovery_id, sig)
        except sk.RecoverError:
            return 1  # the syscall's error convention: nonzero r0
        for j, byte in enumerate(pub):
            vm_.mem_write(result_addr + j, 1, byte)
        return 0

    def _read_seeds(vm_, seeds_addr, seeds_len):
        from firedancer_tpu.protocol import pda

        if seeds_len > pda.MAX_SEEDS:
            return None
        seeds = []
        for i in range(seeds_len):
            addr = vm_.mem_read(seeds_addr + 16 * i, 8)
            sz = vm_.mem_read(seeds_addr + 16 * i + 8, 8)
            if sz > pda.MAX_SEED_LEN:
                return None
            seeds.append(vm_.mem_read_bytes(addr, sz))
        return seeds

    def sol_create_program_address(vm_, seeds_addr, seeds_len, prog_addr,
                                   result_addr, *_):
        from firedancer_tpu.protocol import pda

        seeds = _read_seeds(vm_, seeds_addr, seeds_len)
        if seeds is None:
            return 1
        try:
            addr = pda.create_program_address(
                seeds, vm_.mem_read_bytes(prog_addr, 32)
            )
        except pda.PdaError:
            return 1
        for j, byte in enumerate(addr):
            vm_.mem_write(result_addr + j, 1, byte)
        return 0

    def sol_try_find_program_address(vm_, seeds_addr, seeds_len, prog_addr,
                                     result_addr, bump_addr):
        from firedancer_tpu.protocol import pda

        seeds = _read_seeds(vm_, seeds_addr, seeds_len)
        if seeds is None:
            return 1
        try:  # e.g. 16 guest seeds + the bump seed exceeds MAX_SEEDS
            addr, bump = pda.find_program_address(
                seeds, vm_.mem_read_bytes(prog_addr, 32)
            )
        except pda.PdaError:
            return 1
        for j, byte in enumerate(addr):
            vm_.mem_write(result_addr + j, 1, byte)
        vm_.mem_write(bump_addr, 1, bump)
        return 0

    vm.syscalls[SYSCALL_SOL_SHA256] = sol_sha256
    vm.syscalls[SYSCALL_SOL_KECCAK256] = sol_keccak256
    vm.syscalls[SYSCALL_SOL_LOG] = sol_log
    vm.syscalls[SYSCALL_SOL_SECP256K1_RECOVER] = sol_secp256k1_recover
    vm.syscalls[SYSCALL_SOL_CREATE_PROGRAM_ADDRESS] = sol_create_program_address
    vm.syscalls[SYSCALL_SOL_TRY_FIND_PROGRAM_ADDRESS] = sol_try_find_program_address
