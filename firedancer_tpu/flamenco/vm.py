"""sBPF virtual machine interpreter (the flamenco/vm layer).

Counterpart of /root/reference/src/flamenco/vm/fd_vm_interp_core.c (the
872-line computed-goto loop) and the fd_vm memory map (fd_vm.h:22-42):
eleven 64-bit registers, a compute budget charged per instruction, and a
segmented virtual address space —

    0x1_0000_0000  program rodata     (read-only)
    0x2_0000_0000  stack              (read-write)
    0x3_0000_0000  heap               (read-write)
    0x4_0000_0000  input (accounts)   (read-write)

Every load/store translates through the region table with bounds checks;
faults, division by zero, bad calls and budget exhaustion abort cleanly
with a typed error (the VM is branchy host-side work by design — SURVEY
§7.1 keeps it off the TPU; the device-batchable pieces, sigverify and
hashing, are syscalls into the ops layer).

Syscalls are registered by 32-bit id (murmur3_32 of the name, Solana's
own derivation — ops/smallhash.syscall_id) and receive (vm, r1..r5),
returning the new r0.

sBPF function calls (fd_vm_interp_core.c's CALL_IMM/CALL_REG paths):
`call` with src==1 is a bpf-to-bpf call to pc+imm+1; `callx` jumps to a
code address held in the register named by imm.  Each call pushes the
caller's r6-r9 + return pc and advances the frame pointer by one 4 KiB
stack frame (FD_VM_STACK_FRAME_SZ semantics); `exit` pops a frame if one
is live, and only returns to the host from the outermost frame.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from firedancer_tpu.protocol import sbpf

MM_PROGRAM = 1 << 32
MM_STACK = 2 << 32
MM_HEAP = 3 << 32
MM_INPUT = 4 << 32

FRAME_SZ = 4096
MAX_CALL_DEPTH = 64
STACK_SZ = FRAME_SZ * MAX_CALL_DEPTH
# single source of truth for the default heap: the cost model's constant
from firedancer_tpu.pack.cost import DEFAULT_HEAP_SIZE as HEAP_SZ
DEFAULT_BUDGET = 200_000

_M64 = (1 << 64) - 1
_M32 = (1 << 32) - 1


class VmError(RuntimeError):
    pass


class VmFault(VmError):
    """Memory access violation."""


class VmBudget(VmError):
    """Compute budget exhausted."""


@dataclass
class Region:
    start: int
    data: bytearray
    writable: bool


@dataclass
class Vm:
    program: sbpf.Program
    input_data: bytes = b""
    budget: int = DEFAULT_BUDGET
    syscalls: dict[int, object] = field(default_factory=dict)
    heap_size: int = HEAP_SZ  # RequestHeapFrame-controlled (32K default)

    def __post_init__(self):
        self.regs = [0] * 11
        self.pc = self.program.entry_pc
        self.cu_used = 0
        self.insns = {i.pc: i for i in sbpf.decode(self.program.text())}
        self.regions = [
            Region(MM_PROGRAM, bytearray(self.program.rodata), False),
            Region(MM_STACK, bytearray(STACK_SZ), True),
            Region(MM_HEAP, bytearray(self.heap_size), True),
            Region(MM_INPUT, bytearray(self.input_data), True),
        ]
        self.regs[10] = MM_STACK + FRAME_SZ  # frame 0's top; grows UP per call
        self.regs[1] = MM_INPUT
        self.call_stack: list[tuple[int, int, int, int, int]] = []  # (ret_pc, r6..r9)
        self.heap_pos = 0  # bump cursor for sol_alloc_free_
        self.logs: list[bytes] = []
        # sysvars the runtime exposes to the program (bincode-encoded
        # blobs keyed "clock"/"rent"/"epoch_schedule"); return data is the
        # (program_id, bytes) pair CPI callers read back; program_id is
        # the executing program (sol_set_return_data attributes to it)
        self.sysvars: dict[str, bytes] = {}
        self.return_data: tuple[bytes, bytes] = (bytes(32), b"")
        self.program_id: bytes = bytes(32)
        # invoke-stack height of the executing instruction (top level = 1)
        # and the txn's processed-instruction trace
        # [(stack_height, program_id, [(pubkey, signer, writable)], data)]
        # — sol_get_stack_height / sol_get_processed_sibling_instruction
        self.stack_height: int = 1
        self.instr_trace: list = []

    def charge(self, n: int) -> None:
        """Charge `n` compute units; syscalls use this for their fixed +
        per-byte costs (fd_vm's FD_VM_CONSUME_CU shape)."""
        self.cu_used += n
        if self.cu_used > self.budget:
            raise VmBudget(f"compute budget exceeded ({self.budget})")

    # -- memory -------------------------------------------------------------

    def _region(self, vaddr: int, sz: int, write: bool) -> tuple[Region, int]:
        for r in self.regions:
            off = vaddr - r.start
            if 0 <= off and off + sz <= len(r.data):
                if write and not r.writable:
                    raise VmFault(f"write to read-only 0x{vaddr:x}")
                return r, off
        raise VmFault(f"access violation at 0x{vaddr:x} sz {sz}")

    def mem_read(self, vaddr: int, sz: int) -> int:
        r, off = self._region(vaddr, sz, write=False)
        return int.from_bytes(r.data[off : off + sz], "little")

    def mem_read_bytes(self, vaddr: int, sz: int) -> bytes:
        r, off = self._region(vaddr, sz, write=False)
        return bytes(r.data[off : off + sz])

    def mem_write(self, vaddr: int, sz: int, val: int) -> None:
        r, off = self._region(vaddr, sz, write=True)
        r.data[off : off + sz] = (val & ((1 << (8 * sz)) - 1)).to_bytes(sz, "little")

    def _write_span(self, vaddr: int, data: bytes) -> None:
        if not data:
            return
        r, off = self._region(vaddr, len(data), write=True)
        r.data[off : off + len(data)] = data

    # -- execution ----------------------------------------------------------

    @staticmethod
    def _s64(v: int) -> int:
        return v - (1 << 64) if v >> 63 else v

    @staticmethod
    def _s32(v: int) -> int:
        v &= _M32
        return v - (1 << 32) if v >> 31 else v

    def run(self) -> int:
        """Execute until exit; returns r0."""
        regs = self.regs
        while True:
            self.cu_used += 1
            if self.cu_used > self.budget:
                raise VmBudget(f"compute budget exceeded ({self.budget})")
            ins = self.insns.get(self.pc)
            if ins is None:
                raise VmError(f"bad pc {self.pc}")
            mn = ins.mnemonic
            dst, src, off, imm = ins.dst, ins.src, ins.off, ins.imm
            nxt = self.pc + (2 if mn == "lddw" else 1)

            if mn == "exit":
                if not self.call_stack:
                    return regs[0]
                ret_pc, r6, r7, r8, r9 = self.call_stack.pop()
                regs[6], regs[7], regs[8], regs[9] = r6, r7, r8, r9
                regs[10] -= FRAME_SZ
                nxt = ret_pc
            elif mn == "lddw":
                regs[dst] = imm & _M64
            elif mn == "call":
                if ins.src == 1:  # bpf-to-bpf: pc-relative target
                    nxt = self._call_enter(self.pc + 1, self.pc + 1 + imm)
                else:
                    fn = self.syscalls.get(imm & _M32)
                    if fn is None:
                        # Solana also routes registered-function calls
                        # through CALL_IMM with a pc hash; unknown ids
                        # land here either way
                        raise VmError(f"unknown syscall 0x{imm & _M32:x}")
                    regs[0] = fn(self, *regs[1:6]) & _M64
            elif mn == "callx":
                addr = regs[imm & 0xF] if (imm & 0xF) <= 10 else None
                if addr is None:
                    raise VmError("callx bad register")
                off_b = addr - MM_PROGRAM - self.program.text_off
                if off_b % 8:
                    raise VmError(f"callx to unaligned 0x{addr:x}")
                nxt = self._call_enter(self.pc + 1, off_b // 8)
            elif mn.startswith("j"):
                taken = self._jump_taken(mn, regs, dst, src, imm)
                if taken:
                    nxt = self.pc + 1 + off
            elif mn.startswith(("ldx",)):
                sz = {"ldxb": 1, "ldxh": 2, "ldxw": 4, "ldxdw": 8}[mn]
                regs[dst] = self.mem_read((regs[src] + off) & _M64, sz)
            elif mn.startswith("stx"):
                sz = {"stxb": 1, "stxh": 2, "stxw": 4, "stxdw": 8}[mn]
                self.mem_write((regs[dst] + off) & _M64, sz, regs[src])
            elif mn.startswith("st"):
                sz = {"stb": 1, "sth": 2, "stw": 4, "stdw": 8}[mn]
                self.mem_write((regs[dst] + off) & _M64, sz, imm & _M64)
            else:
                self._alu(mn, regs, dst, src, imm)
            self.pc = nxt

    def _call_enter(self, ret_pc: int, target_pc: int) -> int:
        if len(self.call_stack) >= MAX_CALL_DEPTH - 1:
            raise VmError(f"call depth exceeded ({MAX_CALL_DEPTH})")
        if target_pc not in self.insns:
            raise VmError(f"call to bad pc {target_pc}")
        r = self.regs
        self.call_stack.append((ret_pc, r[6], r[7], r[8], r[9]))
        r[10] += FRAME_SZ
        return target_pc

    def _jump_taken(self, mn, regs, dst, src, imm) -> bool:
        if mn == "ja":
            return True
        kind, mode = mn[1:].rsplit("_", 1)
        b = regs[src] if mode == "reg" else imm & _M64
        a = regs[dst]
        sa, sb = self._s64(a), self._s64(b)
        return {
            "eq": a == b, "ne": a != b, "set": bool(a & b),
            "gt": a > b, "ge": a >= b, "lt": a < b, "le": a <= b,
            "sgt": sa > sb, "sge": sa >= sb, "slt": sa < sb, "sle": sa <= sb,
        }[kind]

    def _alu(self, mn, regs, dst, src, imm) -> None:
        is32 = "32" in mn
        mask = _M32 if is32 else _M64
        if mn in ("neg64", "neg32"):
            regs[dst] = (-regs[dst]) & mask
            return
        if mn in ("le", "be"):  # byte-order ops: widths via imm (16/32/64)
            width = imm
            if width not in (16, 32, 64):
                raise VmError(f"bad byte-order width {width}")
            v = regs[dst] & ((1 << width) - 1)
            if mn == "be":
                v = int.from_bytes(
                    v.to_bytes(width // 8, "little"), "big"
                )
            regs[dst] = v
            return
        op, mode = mn.rsplit("_", 1)
        b = (regs[src] if mode == "reg" else imm) & mask
        a = regs[dst] & mask
        if op.startswith("add"):
            r = a + b
        elif op.startswith("sub"):
            r = a - b
        elif op.startswith("mul"):
            r = a * b
        elif op.startswith("div"):
            if b == 0:
                raise VmError("division by zero")
            r = a // b
        elif op.startswith("mod"):
            if b == 0:
                raise VmError("division by zero")
            r = a % b
        elif op.startswith("or"):
            r = a | b
        elif op.startswith("and"):
            r = a & b
        elif op.startswith("xor"):
            r = a ^ b
        elif op.startswith("lsh"):
            r = a << (b & (31 if is32 else 63))
        elif op.startswith("rsh"):
            r = a >> (b & (31 if is32 else 63))
        elif op.startswith("arsh"):
            s = self._s32(a) if is32 else self._s64(a)
            r = s >> (b & (31 if is32 else 63))
        elif op.startswith("mov"):
            r = b
        else:
            raise VmError(f"unhandled alu {mn}")
        regs[dst] = r & mask


# -- the device-backed syscalls (the TPU bridge) ------------------------------

from firedancer_tpu.ops.smallhash import syscall_id as _sid

SYSCALL_SOL_SHA256 = 0x11F49D86
SYSCALL_SOL_KECCAK256 = 0xD7793ABB
SYSCALL_SOL_LOG = 0x207559BD
SYSCALL_SOL_SECP256K1_RECOVER = 0x17E40350
SYSCALL_SOL_CREATE_PROGRAM_ADDRESS = 0x9377323C
SYSCALL_SOL_TRY_FIND_PROGRAM_ADDRESS = 0x48504A38
SYSCALL_SOL_MEMCPY = _sid("sol_memcpy_")
SYSCALL_SOL_MEMMOVE = _sid("sol_memmove_")
SYSCALL_SOL_MEMSET = _sid("sol_memset_")
SYSCALL_SOL_MEMCMP = _sid("sol_memcmp_")
SYSCALL_SOL_ALLOC_FREE = _sid("sol_alloc_free_")
SYSCALL_SOL_LOG_64 = _sid("sol_log_64_")
SYSCALL_SOL_LOG_PUBKEY = _sid("sol_log_pubkey")
SYSCALL_SOL_LOG_CU = _sid("sol_log_compute_units_")
SYSCALL_SOL_LOG_DATA = _sid("sol_log_data")
SYSCALL_SOL_PANIC = _sid("sol_panic_")
SYSCALL_SOL_INVOKE_SIGNED_C = _sid("sol_invoke_signed_c")
SYSCALL_SOL_INVOKE_SIGNED_RUST = _sid("sol_invoke_signed_rust")
SYSCALL_SOL_ALT_BN128 = _sid("sol_alt_bn128_group_op")
SYSCALL_SOL_GET_CLOCK = _sid("sol_get_clock_sysvar")
SYSCALL_SOL_GET_RENT = _sid("sol_get_rent_sysvar")
SYSCALL_SOL_GET_EPOCH_SCHEDULE = _sid("sol_get_epoch_schedule_sysvar")
SYSCALL_SOL_SET_RETURN_DATA = _sid("sol_set_return_data")
SYSCALL_SOL_GET_RETURN_DATA = _sid("sol_get_return_data")
SYSCALL_SOL_BLAKE3 = _sid("sol_blake3")
SYSCALL_SOL_POSEIDON = _sid("sol_poseidon")
SYSCALL_SOL_BIG_MOD_EXP = _sid("sol_big_mod_exp")
SYSCALL_SOL_ALT_BN128_COMPRESSION = _sid("sol_alt_bn128_compression")
SYSCALL_SOL_CURVE_VALIDATE_POINT = _sid("sol_curve_validate_point")
SYSCALL_SOL_CURVE_GROUP_OP = _sid("sol_curve_group_op")
SYSCALL_SOL_CURVE_MULTISCALAR_MUL = _sid("sol_curve_multiscalar_mul")
SYSCALL_SOL_GET_STACK_HEIGHT = _sid("sol_get_stack_height")
SYSCALL_SOL_REMAINING_CU = _sid("sol_remaining_compute_units")
SYSCALL_SOL_GET_SIBLING_INSTR = _sid("sol_get_processed_sibling_instruction")
SYSCALL_SOL_GET_FEES = _sid("sol_get_fees_sysvar")
SYSCALL_SOL_GET_EPOCH_REWARDS = _sid("sol_get_epoch_rewards_sysvar")
SYSCALL_SOL_GET_LAST_RESTART_SLOT = _sid("sol_get_last_restart_slot")

# curve25519 syscall selectors (fd_vm_syscall_curve.c's convention)
CURVE25519_EDWARDS = 0
CURVE25519_RISTRETTO = 1
CURVE_OP_ADD = 0
CURVE_OP_SUB = 1
CURVE_OP_MUL = 2
CURVE_MSM_MAX_POINTS = 512
# per-op CU costs (the reference/Agave cost table shape)
CURVE_COSTS = {
    (CURVE25519_EDWARDS, "validate"): 159,
    (CURVE25519_RISTRETTO, "validate"): 169,
    (CURVE25519_EDWARDS, CURVE_OP_ADD): 473,
    (CURVE25519_EDWARDS, CURVE_OP_SUB): 475,
    (CURVE25519_EDWARDS, CURVE_OP_MUL): 2177,
    (CURVE25519_RISTRETTO, CURVE_OP_ADD): 521,
    (CURVE25519_RISTRETTO, CURVE_OP_SUB): 519,
    (CURVE25519_RISTRETTO, CURVE_OP_MUL): 2208,
}
CURVE_MSM_BASE = {CURVE25519_EDWARDS: 2273, CURVE25519_RISTRETTO: 2303}
CURVE_MSM_INCR = {CURVE25519_EDWARDS: 758, CURVE25519_RISTRETTO: 788}
BIG_MOD_EXP_MAX_LEN = 512
ALT_BN128_COMPRESSION_COSTS = {0: 30, 1: 398, 2: 86, 3: 13610}

MAX_RETURN_DATA = 1024

# sol_alt_bn128_group_op op selectors (Solana's ALT_BN128_* convention)
ALT_BN128_ADD = 0
ALT_BN128_MUL = 2
ALT_BN128_PAIRING = 3
ALT_BN128_COSTS = {ALT_BN128_ADD: 334, ALT_BN128_MUL: 3_840,
                   ALT_BN128_PAIRING: 36_364}  # + per-pair for pairing

# fd_vm cost model constants (FD_VM_*_COST shape): a fixed base per
# syscall plus per-byte for the bulk ops
SYSCALL_BASE_COST = 100
CPI_BYTES_PER_CU = 250
MEM_OP_BASE_COST = 10
LOG_PUBKEY_COST = 100
HASH_BASE_COST = 85
HASH_BYTE_COST_DIV = 2  # 1 CU per 2 bytes hashed


def register_default_syscalls(vm: Vm, *, log_sink: list | None = None) -> None:
    """sol_sha256 / sol_keccak256 / sol_log — the hashing syscalls route
    into the ops layer (host path here; the batched device path serves
    bulk callers), mirroring fd_vm_syscall_sol_sha256 etc."""
    import hashlib

    from firedancer_tpu.ops import keccak256 as kk

    def _write_bytes(vm_, addr, data):
        vm_._write_span(addr, data)

    def _gather(vm_, vals_addr, vals_len):
        data = b""
        for i in range(vals_len):
            addr = vm_.mem_read(vals_addr + 16 * i, 8)
            sz = vm_.mem_read(vals_addr + 16 * i + 8, 8)
            data += vm_.mem_read_bytes(addr, sz)
        return data

    def sol_sha256(vm_, vals_addr, vals_len, result_addr, *_):
        data = _gather(vm_, vals_addr, vals_len)
        vm_.charge(HASH_BASE_COST + len(data) // HASH_BYTE_COST_DIV)
        digest = hashlib.sha256(data).digest()
        _write_bytes(vm_, result_addr, digest)
        return 0

    def sol_keccak256(vm_, vals_addr, vals_len, result_addr, *_):
        data = _gather(vm_, vals_addr, vals_len)
        vm_.charge(HASH_BASE_COST + len(data) // HASH_BYTE_COST_DIV)
        digest = kk.keccak256_host(data)
        _write_bytes(vm_, result_addr, digest)
        return 0

    def _emit(vm_, msg: bytes):
        vm_.logs.append(msg)
        if log_sink is not None:
            log_sink.append(msg)

    def sol_log(vm_, addr, sz, *_):
        vm_.charge(max(SYSCALL_BASE_COST, sz))
        _emit(vm_, vm_.mem_read_bytes(addr, sz))
        return 0

    def sol_log_64(vm_, a, b, c, d, e):
        vm_.charge(SYSCALL_BASE_COST)
        _emit(vm_, b"0x%x, 0x%x, 0x%x, 0x%x, 0x%x" % (a, b, c, d, e))
        return 0

    def sol_log_pubkey(vm_, addr, *_):
        from firedancer_tpu.protocol import base58

        vm_.charge(LOG_PUBKEY_COST)
        _emit(vm_, base58.b58_encode32(vm_.mem_read_bytes(addr, 32)).encode())
        return 0

    def sol_log_compute_units(vm_, *_):
        vm_.charge(SYSCALL_BASE_COST)
        _emit(vm_, b"consumed %d of %d" % (vm_.cu_used, vm_.budget))
        return 0

    def sol_log_data(vm_, vals_addr, vals_len, *_):
        import base64 as b64

        data = _gather(vm_, vals_addr, vals_len)
        vm_.charge(SYSCALL_BASE_COST + len(data))
        _emit(vm_, b"data: " + b64.b64encode(data))
        return 0

    def sol_panic(vm_, file_addr, file_sz, line, col, *_):
        fname = b"?"
        try:
            fname = vm_.mem_read_bytes(file_addr, file_sz)
        except VmFault:
            pass
        raise VmError(
            f"program panicked at {fname.decode('utf-8', 'replace')}:{line}:{col}"
        )

    # -- memops (fd_vm_syscall_sol_mem{cpy,move,set,cmp}_) --------------------

    def _mem_cost(vm_, n):
        vm_.charge(max(MEM_OP_BASE_COST, n // CPI_BYTES_PER_CU))

    def sol_memcpy(vm_, dst, src, n, *_):
        _mem_cost(vm_, n)
        if n and not (dst + n <= src or src + n <= dst):
            raise VmError("memcpy overlapping ranges")
        vm_._write_span(dst, vm_.mem_read_bytes(src, n))
        return 0

    def sol_memmove(vm_, dst, src, n, *_):
        _mem_cost(vm_, n)
        vm_._write_span(dst, vm_.mem_read_bytes(src, n))
        return 0

    def sol_memset(vm_, dst, c, n, *_):
        _mem_cost(vm_, n)
        vm_._write_span(dst, bytes([c & 0xFF]) * n)
        return 0

    def sol_memcmp(vm_, a_addr, b_addr, n, result_addr, *_):
        _mem_cost(vm_, n)
        a = vm_.mem_read_bytes(a_addr, n)
        b = vm_.mem_read_bytes(b_addr, n)
        r = 0
        for x, y in zip(a, b):
            if x != y:
                r = x - y
                break
        vm_.mem_write(result_addr, 4, r & _M32)
        return 0

    def sol_alloc_free(vm_, sz, free_addr, *_):
        # bump allocator over the heap region; free is a no-op (the
        # reference's fd_vm_syscall_sol_alloc_free_ behaves identically)
        if free_addr != 0:
            return 0
        align = 8
        pos = (vm_.heap_pos + align - 1) & ~(align - 1)
        if pos + sz > vm_.heap_size:
            return 0  # NULL: allocation failure, not a fault
        vm_.heap_pos = pos + sz
        return MM_HEAP + pos

    def sol_secp256k1_recover(vm_, hash_addr, recovery_id, sig_addr, result_addr, *_):
        from firedancer_tpu.ops import secp256k1 as sk

        h = vm_.mem_read_bytes(hash_addr, 32)
        sig = vm_.mem_read_bytes(sig_addr, 64)
        try:
            pub = sk.recover(h, recovery_id, sig)
        except sk.RecoverError:
            return 1  # the syscall's error convention: nonzero r0
        for j, byte in enumerate(pub):
            vm_.mem_write(result_addr + j, 1, byte)
        return 0

    def _read_seeds(vm_, seeds_addr, seeds_len):
        from firedancer_tpu.protocol import pda

        if seeds_len > pda.MAX_SEEDS:
            return None
        seeds = []
        for i in range(seeds_len):
            addr = vm_.mem_read(seeds_addr + 16 * i, 8)
            sz = vm_.mem_read(seeds_addr + 16 * i + 8, 8)
            if sz > pda.MAX_SEED_LEN:
                return None
            seeds.append(vm_.mem_read_bytes(addr, sz))
        return seeds

    def sol_create_program_address(vm_, seeds_addr, seeds_len, prog_addr,
                                   result_addr, *_):
        from firedancer_tpu.protocol import pda

        seeds = _read_seeds(vm_, seeds_addr, seeds_len)
        if seeds is None:
            return 1
        try:
            addr = pda.create_program_address(
                seeds, vm_.mem_read_bytes(prog_addr, 32)
            )
        except pda.PdaError:
            return 1
        for j, byte in enumerate(addr):
            vm_.mem_write(result_addr + j, 1, byte)
        return 0

    def sol_try_find_program_address(vm_, seeds_addr, seeds_len, prog_addr,
                                     result_addr, bump_addr):
        from firedancer_tpu.protocol import pda

        seeds = _read_seeds(vm_, seeds_addr, seeds_len)
        if seeds is None:
            return 1
        try:  # e.g. 16 guest seeds + the bump seed exceeds MAX_SEEDS
            addr, bump = pda.find_program_address(
                seeds, vm_.mem_read_bytes(prog_addr, 32)
            )
        except pda.PdaError:
            return 1
        for j, byte in enumerate(addr):
            vm_.mem_write(result_addr + j, 1, byte)
        vm_.mem_write(bump_addr, 1, bump)
        return 0

    vm.syscalls[SYSCALL_SOL_SHA256] = sol_sha256
    vm.syscalls[SYSCALL_SOL_KECCAK256] = sol_keccak256
    vm.syscalls[SYSCALL_SOL_LOG] = sol_log
    vm.syscalls[SYSCALL_SOL_LOG_64] = sol_log_64
    vm.syscalls[SYSCALL_SOL_LOG_PUBKEY] = sol_log_pubkey
    vm.syscalls[SYSCALL_SOL_LOG_CU] = sol_log_compute_units
    vm.syscalls[SYSCALL_SOL_LOG_DATA] = sol_log_data
    vm.syscalls[SYSCALL_SOL_PANIC] = sol_panic
    vm.syscalls[SYSCALL_SOL_MEMCPY] = sol_memcpy
    vm.syscalls[SYSCALL_SOL_MEMMOVE] = sol_memmove
    vm.syscalls[SYSCALL_SOL_MEMSET] = sol_memset
    vm.syscalls[SYSCALL_SOL_MEMCMP] = sol_memcmp
    vm.syscalls[SYSCALL_SOL_ALLOC_FREE] = sol_alloc_free
    def sol_alt_bn128_group_op(vm_, op, input_addr, input_len, result_addr, *_):
        from firedancer_tpu.ops import bn254 as bn

        cost = ALT_BN128_COSTS.get(op)
        if cost is None:
            return 1
        if op == ALT_BN128_PAIRING:
            cost += 12_121 * max(0, input_len // 192 - 1)
        vm_.charge(cost)
        data = vm_.mem_read_bytes(input_addr, input_len) if input_len else b""
        try:
            if op == ALT_BN128_ADD:
                out = bn.alt_bn128_addition(data)
            elif op == ALT_BN128_MUL:
                out = bn.alt_bn128_multiplication(data)
            else:
                out = bn.alt_bn128_pairing(data)
        except bn.Bn254Error:
            return 1
        vm_._write_span(result_addr, out)
        return 0

    # -- sysvars + return data ------------------------------------------------

    def _sysvar_getter(name):
        def getter(vm_, out_addr, *_):
            vm_.charge(SYSCALL_BASE_COST)
            blob = vm_.sysvars.get(name)
            if blob is None:
                return 1  # sysvar not provided by the runtime context
            vm_._write_span(out_addr, blob)
            return 0

        return getter

    def sol_set_return_data(vm_, addr, sz, *_):
        vm_.charge(SYSCALL_BASE_COST + sz // CPI_BYTES_PER_CU)
        if sz > MAX_RETURN_DATA:
            raise VmError(f"return data too long ({sz})")
        data = vm_.mem_read_bytes(addr, sz) if sz else b""
        # attribution happens HERE (the setter's program id), so clears
        # (sz=0) take effect and inherited data is never re-attributed
        vm_.return_data = (vm_.program_id, data)
        return 0

    def sol_get_return_data(vm_, addr, sz, program_id_addr, *_):
        vm_.charge(SYSCALL_BASE_COST)
        pid, data = vm_.return_data
        if not data:
            return 0
        n = min(sz, len(data))
        if n:
            vm_._write_span(addr, data[:n])
            vm_._write_span(program_id_addr, pid)
        return len(data)

    # -- blake3 / poseidon / big_mod_exp / bn254 compression ------------------
    # (fd_vm_syscall_hash.c sol_blake3; fd_vm_syscall_crypto.c the rest)

    def sol_blake3(vm_, vals_addr, vals_len, result_addr, *_):
        from firedancer_tpu.ops import blake3 as b3

        data = _gather(vm_, vals_addr, vals_len)
        vm_.charge(HASH_BASE_COST + len(data) // HASH_BYTE_COST_DIV)
        _write_bytes(vm_, result_addr, b3.blake3_host(data))
        return 0

    def sol_poseidon(vm_, params, endianness, vals_addr, vals_len,
                     result_addr):
        from firedancer_tpu.ops import poseidon as pos

        if params != 0:  # only Bn254X5 exists
            return 1
        if not 1 <= vals_len <= pos.MAX_INPUTS:
            return 1
        # Agave's cost curve is superlinear in the input count
        vm_.charge(SYSCALL_BASE_COST + 61 * vals_len * vals_len + 542)
        try:
            inputs = []
            for i in range(vals_len):
                addr = vm_.mem_read(vals_addr + 16 * i, 8)
                sz = vm_.mem_read(vals_addr + 16 * i + 8, 8)
                inputs.append(vm_.mem_read_bytes(addr, sz))
            # endianness selector: 0 = big endian, 1 = little endian
            out = pos.poseidon_hash(inputs, big_endian=(endianness == 0))
        except pos.PoseidonError:
            return 1
        _write_bytes(vm_, result_addr, out)
        return 0

    def sol_big_mod_exp(vm_, params_addr, return_addr, *_):
        # BigModExpParams: 3 x (u64 addr, u64 len) for base/exponent/mod
        fields = [vm_.mem_read(params_addr + 8 * i, 8) for i in range(6)]
        base_addr, base_len, exp_addr, exp_len, mod_addr, mod_len = fields
        if max(base_len, exp_len, mod_len) > BIG_MOD_EXP_MAX_LEN:
            return 1
        vm_.charge(SYSCALL_BASE_COST + 33 * max(base_len, exp_len, mod_len))
        base = int.from_bytes(vm_.mem_read_bytes(base_addr, base_len), "big")
        exp = int.from_bytes(vm_.mem_read_bytes(exp_addr, exp_len), "big")
        mod = int.from_bytes(vm_.mem_read_bytes(mod_addr, mod_len), "big")
        if mod == 0:
            return 1
        out = pow(base, exp, mod).to_bytes(mod_len, "big")
        _write_bytes(vm_, return_addr, out)
        return 0

    def sol_alt_bn128_compression(vm_, op, input_addr, input_len,
                                  result_addr, *_):
        from firedancer_tpu.ops import bn254 as bn

        cost = ALT_BN128_COMPRESSION_COSTS.get(op)
        if cost is None:
            return 1
        vm_.charge(cost)
        data = vm_.mem_read_bytes(input_addr, input_len) if input_len else b""
        try:
            if op == 0:
                out = bn.g1_compress(data)
            elif op == 1:
                out = bn.g1_decompress(data)
            elif op == 2:
                out = bn.g2_compress(data)
            else:
                out = bn.g2_decompress(data)
        except bn.Bn254Error:
            return 1
        vm_._write_span(result_addr, out)
        return 0

    # -- curve25519 group syscalls (fd_vm_syscall_curve.c) --------------------

    def _ed_decode(data):
        from firedancer_tpu.ops.ref import ed25519_ref as ed

        return ed.point_decompress(data)

    def _curve_decode(curve_id, data):
        from firedancer_tpu.ops import ristretto as ri

        if curve_id == CURVE25519_EDWARDS:
            return _ed_decode(data)
        try:
            return ri.decode(data)
        except ri.RistrettoError:
            return None

    def _curve_encode(curve_id, p):
        from firedancer_tpu.ops import ristretto as ri
        from firedancer_tpu.ops.ref import ed25519_ref as ed

        if curve_id == CURVE25519_EDWARDS:
            return ed.point_compress(p)
        return ri.encode(p)

    def sol_curve_validate_point(vm_, curve_id, point_addr, *_):
        cost = CURVE_COSTS.get((curve_id, "validate"))
        if cost is None:
            return 1
        vm_.charge(cost)
        data = vm_.mem_read_bytes(point_addr, 32)
        return 0 if _curve_decode(curve_id, data) is not None else 1

    def sol_curve_group_op(vm_, curve_id, group_op, left_addr, right_addr,
                           result_addr):
        from firedancer_tpu.ops.ref import ed25519_ref as ed

        cost = CURVE_COSTS.get((curve_id, group_op))
        if cost is None:
            return 1
        vm_.charge(cost)
        if group_op == CURVE_OP_MUL:
            # left = 32-byte scalar (LE, reduced mod L), right = point
            s = int.from_bytes(vm_.mem_read_bytes(left_addr, 32), "little")
            if s >= ed.L:
                return 1
            p = _curve_decode(curve_id, vm_.mem_read_bytes(right_addr, 32))
            if p is None:
                return 1
            out = ed.point_mul(s, p)
        else:
            p = _curve_decode(curve_id, vm_.mem_read_bytes(left_addr, 32))
            q = _curve_decode(curve_id, vm_.mem_read_bytes(right_addr, 32))
            if p is None or q is None:
                return 1
            if group_op == CURVE_OP_SUB:
                q = ed.point_neg(q)
            out = ed.point_add(p, q)
        _write_bytes(vm_, result_addr, _curve_encode(curve_id, out))
        return 0

    def sol_curve_multiscalar_mul(vm_, curve_id, scalars_addr, points_addr,
                                  points_len, result_addr):
        from firedancer_tpu.ops.ref import ed25519_ref as ed

        if curve_id not in (CURVE25519_EDWARDS, CURVE25519_RISTRETTO):
            return 1
        if not 1 <= points_len <= CURVE_MSM_MAX_POINTS:
            return 1
        vm_.charge(CURVE_MSM_BASE[curve_id]
                   + CURVE_MSM_INCR[curve_id] * (points_len - 1))
        acc = ed.IDENT
        for i in range(points_len):
            s = int.from_bytes(
                vm_.mem_read_bytes(scalars_addr + 32 * i, 32), "little")
            if s >= ed.L:
                return 1
            p = _curve_decode(
                curve_id, vm_.mem_read_bytes(points_addr + 32 * i, 32))
            if p is None:
                return 1
            acc = ed.point_add(acc, ed.point_mul(s, p))
        _write_bytes(vm_, result_addr, _curve_encode(curve_id, acc))
        return 0

    # -- introspection (fd_vm_syscall.c) --------------------------------------

    def sol_get_stack_height(vm_, *_):
        vm_.charge(SYSCALL_BASE_COST)
        return vm_.stack_height

    def sol_remaining_compute_units(vm_, *_):
        vm_.charge(SYSCALL_BASE_COST)
        return max(0, vm_.budget - vm_.cu_used)

    def sol_get_processed_sibling_instruction(
        vm_, index, meta_addr, program_id_addr, data_addr, accounts_addr
    ):
        vm_.charge(SYSCALL_BASE_COST)
        # siblings: walk the trace BACKWARDS collecting entries at THIS
        # instruction's stack height, STOPPING at the first entry below
        # it — a shallower entry is a different parent's boundary, and
        # its children must stay invisible (the reference breaks there
        # too, fd_vm_syscall_runtime.c sibling walk)
        sibs = []
        for e in reversed(vm_.instr_trace):
            if e[0] < vm_.stack_height:
                break
            if e[0] == vm_.stack_height:
                sibs.append(e)
        if index >= len(sibs):
            return 0  # not found
        _h, pid, metas, data = sibs[index]
        # meta in/out: u64 data_len | u64 accounts_len; the payload is
        # copied ONLY when the caller's lengths EXACTLY match (Agave's
        # equality gate) — otherwise just the true lengths write back
        # so the caller can re-issue with right-sized buffers
        cap_data = vm_.mem_read(meta_addr, 8)
        cap_accts = vm_.mem_read(meta_addr + 8, 8)
        if cap_data == len(data) and cap_accts == len(metas):
            vm_._write_span(program_id_addr, pid)
            if data:
                vm_._write_span(data_addr, data)
            for i, (pk, signer, writable) in enumerate(metas):
                off = accounts_addr + 34 * i
                vm_._write_span(off, pk)
                vm_.mem_write(off + 32, 1, 1 if signer else 0)
                vm_.mem_write(off + 33, 1, 1 if writable else 0)
        vm_.mem_write(meta_addr, 8, len(data))
        vm_.mem_write(meta_addr + 8, 8, len(metas))
        return 1

    vm.syscalls[SYSCALL_SOL_GET_CLOCK] = _sysvar_getter("clock")
    vm.syscalls[SYSCALL_SOL_GET_RENT] = _sysvar_getter("rent")
    vm.syscalls[SYSCALL_SOL_GET_EPOCH_SCHEDULE] = _sysvar_getter(
        "epoch_schedule"
    )
    vm.syscalls[SYSCALL_SOL_GET_FEES] = _sysvar_getter("fees")
    vm.syscalls[SYSCALL_SOL_GET_EPOCH_REWARDS] = _sysvar_getter(
        "epoch_rewards"
    )
    vm.syscalls[SYSCALL_SOL_GET_LAST_RESTART_SLOT] = _sysvar_getter(
        "last_restart_slot"
    )
    vm.syscalls[SYSCALL_SOL_SET_RETURN_DATA] = sol_set_return_data
    vm.syscalls[SYSCALL_SOL_GET_RETURN_DATA] = sol_get_return_data
    vm.syscalls[SYSCALL_SOL_ALT_BN128] = sol_alt_bn128_group_op
    vm.syscalls[SYSCALL_SOL_SECP256K1_RECOVER] = sol_secp256k1_recover
    vm.syscalls[SYSCALL_SOL_CREATE_PROGRAM_ADDRESS] = sol_create_program_address
    vm.syscalls[SYSCALL_SOL_TRY_FIND_PROGRAM_ADDRESS] = sol_try_find_program_address
    vm.syscalls[SYSCALL_SOL_BLAKE3] = sol_blake3
    vm.syscalls[SYSCALL_SOL_POSEIDON] = sol_poseidon
    vm.syscalls[SYSCALL_SOL_BIG_MOD_EXP] = sol_big_mod_exp
    vm.syscalls[SYSCALL_SOL_ALT_BN128_COMPRESSION] = sol_alt_bn128_compression
    vm.syscalls[SYSCALL_SOL_CURVE_VALIDATE_POINT] = sol_curve_validate_point
    vm.syscalls[SYSCALL_SOL_CURVE_GROUP_OP] = sol_curve_group_op
    vm.syscalls[SYSCALL_SOL_CURVE_MULTISCALAR_MUL] = sol_curve_multiscalar_mul
    vm.syscalls[SYSCALL_SOL_GET_STACK_HEIGHT] = sol_get_stack_height
    vm.syscalls[SYSCALL_SOL_REMAINING_CU] = sol_remaining_compute_units
    vm.syscalls[SYSCALL_SOL_GET_SIBLING_INSTR] = (
        sol_get_processed_sibling_instruction
    )
