"""Stake program + epoch stakes/rewards (flamenco/runtime/program/
fd_stake_program.c and the stakes/rewards subsystem fd_stakes.c /
fd_rewards.c counterparts).

Stake account data layout (this framework's own fixed encoding):

    u32 state      0 = uninitialized, 1 = initialized, 2 = delegated
    32B staker     authority allowed to delegate/deactivate
    32B withdrawer authority allowed to withdraw
    32B voter      vote account delegated to (state 2)
    u64 stake      delegated lamports
    u64 activation_epoch    (state 2; UINT64_MAX = not yet)
    u64 deactivation_epoch  (UINT64_MAX = active)

Activation/deactivation follow the protocol's warmup/cooldown ramp: at
most WARMUP_RATE (25%) of the cluster's total effective stake may
activate or deactivate per epoch boundary; `effective_stake` walks the
epochs from activation to the target epoch applying the ramp — the same
history-walk the reference does against fd_stake_history (simplified to
a uniform per-account fraction, no per-epoch cluster history record).

Rewards: `epoch_rewards` distributes an inflation pot over (stake ×
vote-credits) points, the fd_rewards.c shape: each stake account earns
pot * its_points / total_points, paid onto the stake account and
auto-compounded into the delegation.
"""

from __future__ import annotations

from dataclasses import dataclass

from firedancer_tpu.flamenco.executor import InstrError
from firedancer_tpu.flamenco.programs import AcctError, FundsError, _u32, _u64

STAKE_PROGRAM = b"Stake11111" + bytes(22)

U64_MAX = (1 << 64) - 1
WARMUP_DIV = 4  # a quarter of delegated stake (de)activates per epoch

STATE_UNINIT = 0
STATE_INIT = 1
STATE_DELEGATED = 2

_DATA_LEN = 4 + 32 * 3 + 8 * 3


@dataclass
class StakeState:
    state: int = STATE_UNINIT
    staker: bytes = bytes(32)
    withdrawer: bytes = bytes(32)
    voter: bytes = bytes(32)
    stake: int = 0
    activation_epoch: int = U64_MAX
    deactivation_epoch: int = U64_MAX

    def encode(self) -> bytes:
        return (
            self.state.to_bytes(4, "little")
            + self.staker
            + self.withdrawer
            + self.voter
            + self.stake.to_bytes(8, "little")
            + self.activation_epoch.to_bytes(8, "little")
            + self.deactivation_epoch.to_bytes(8, "little")
        )

    @classmethod
    def decode(cls, data: bytes) -> "StakeState":
        if len(data) < _DATA_LEN:
            return cls()
        return cls(
            state=_u32(data),
            staker=data[4:36],
            withdrawer=data[36:68],
            voter=data[68:100],
            stake=_u64(data[100:]),
            activation_epoch=_u64(data[108:]),
            deactivation_epoch=_u64(data[116:]),
        )


def effective_stake(st: StakeState, epoch: int) -> int:
    """Delegated lamports counted at `epoch`, after the warmup/cooldown
    ramp.  Full stake takes 1/WARMUP_RATE epoch boundaries.  Integer
    arithmetic throughout — this value feeds consensus (leader schedule,
    rewards), so float rounding above 2^53 lamports is unacceptable."""
    if st.state != STATE_DELEGATED or epoch < st.activation_epoch:
        return 0
    # warmup: a quarter of the target per boundary crossed since activation
    boundaries = epoch - st.activation_epoch
    eff = min(st.stake, st.stake * boundaries // WARMUP_DIV)
    if st.deactivation_epoch != U64_MAX and epoch >= st.deactivation_epoch:
        gone = st.stake * (epoch - st.deactivation_epoch) // WARMUP_DIV
        eff = max(0, eff - gone)
    return eff


def locked_stake(st: StakeState, epoch: int) -> int:
    """Lamports a Withdraw may NOT touch: the whole delegation while it
    is active or warming up (warming stake is committed even though not
    yet effective — otherwise freshly delegated lamports could be
    withdrawn leaving phantom stake in the epoch snapshots), ramping to
    zero through cooldown after deactivation."""
    if st.state != STATE_DELEGATED:
        return 0
    if st.deactivation_epoch == U64_MAX or epoch < st.deactivation_epoch:
        return st.stake
    released = st.stake * (epoch - st.deactivation_epoch) // WARMUP_DIV
    return max(0, st.stake - released)


# -- the stake native program -------------------------------------------------
# instruction tags: 0 Initialize{staker,withdrawer} | 1 Delegate |
# 2 Deactivate | 3 Withdraw{lamports} | 4 Split{lamports}
#
# Epochs come from the Clock sysvar (ctx.sysvars["clock"]), never from
# instruction data — the reference's fd_stake_program reads clock.epoch the
# same way.  An attacker-controlled epoch would let a withdrawer skip the
# warmup/cooldown ramp entirely (pass a far-future epoch so locked_stake
# ramps to zero) or make stake instantly effective.


def _clock_epoch(ctx) -> int:
    """Current epoch per the Clock sysvar.  Fails CLOSED: a context without
    a clock cannot run time-sensitive stake instructions — defaulting to
    epoch 0 would re-open the cooldown-skip (deactivation_epoch=0 followed
    by a real-clock withdraw drains an actively-cooling delegation)."""
    from firedancer_tpu.flamenco import types as T

    blob = ctx.sysvars.get("clock")
    if not blob:
        raise AcctError("stake instruction requires the clock sysvar")
    clock, _ = T.CLOCK.decode(blob, 0)
    return clock.epoch


def stake_program(executor, ctx, program_id, iaccts, data, *, pda_signers):
    if len(data) < 4:
        return
    tag = _u32(data)

    def acct(i, *, owned: bool = True):
        if i >= len(iaccts):
            raise AcctError(f"stake instr needs account {i}")
        a = ctx.accounts[iaccts[i].txn_idx]
        if owned and a.owner != STAKE_PROGRAM:
            # the owner-may-modify/debit rule: the stake program only
            # touches its own accounts (blocks draining foreign accounts
            # through the uninitialized-state paths)
            raise AcctError(f"account {i} not owned by the stake program")
        return a

    def signed_by(key: bytes) -> bool:
        for ia in iaccts:
            if ctx.accounts[ia.txn_idx].key == key and (
                ia.is_signer
                or ctx.accounts[ia.txn_idx].key in pda_signers
            ):
                return True
        return False

    def need_writable(i):
        if not iaccts[i].is_writable:
            raise AcctError(f"stake account {i} not writable")

    if tag == 0:  # Initialize { staker 32 | withdrawer 32 }
        if len(data) < 4 + 64:
            raise AcctError("malformed stake initialize")
        a = acct(0)
        need_writable(0)
        st = StakeState.decode(bytes(a.data))
        if st.state != STATE_UNINIT:
            raise AcctError("stake account already initialized")
        if len(a.data) < _DATA_LEN:
            raise AcctError("stake account too small")
        st = StakeState(
            state=STATE_INIT, staker=data[4:36], withdrawer=data[36:68]
        )
        a.data[:_DATA_LEN] = st.encode()
    elif tag == 1:  # Delegate; accounts: [stake, vote]
        a, vote = acct(0), acct(1, owned=False)
        need_writable(0)
        st = StakeState.decode(bytes(a.data))
        if st.state == STATE_UNINIT:
            raise AcctError("delegate of uninitialized stake")
        if not signed_by(st.staker):
            raise AcctError("delegate missing staker signature")
        epoch = _clock_epoch(ctx)
        st.state = STATE_DELEGATED
        st.voter = vote.key
        st.stake = a.lamports  # whole balance delegates (rent exempt 0 here)
        st.activation_epoch = epoch
        st.deactivation_epoch = U64_MAX
        a.data[:_DATA_LEN] = st.encode()
    elif tag == 2:  # Deactivate
        a = acct(0)
        need_writable(0)
        st = StakeState.decode(bytes(a.data))
        if st.state != STATE_DELEGATED:
            raise AcctError("deactivate of undelegated stake")
        if not signed_by(st.staker):
            raise AcctError("deactivate missing staker signature")
        st.deactivation_epoch = _clock_epoch(ctx)
        a.data[:_DATA_LEN] = st.encode()
    elif tag == 3:  # Withdraw { lamports u64 }; [stake, dest]
        if len(data) < 12:
            raise AcctError("malformed withdraw")
        lamports = _u64(data[4:])
        a, dest = acct(0), acct(1, owned=False)
        need_writable(0)
        need_writable(1)
        st = StakeState.decode(bytes(a.data))
        if st.state == STATE_UNINIT:
            # an uninitialized stake account withdraws under its OWN key
            if not signed_by(a.key):
                raise AcctError("withdraw missing stake-account signature")
        elif not signed_by(st.withdrawer):
            raise AcctError("withdraw missing withdrawer signature")
        locked = locked_stake(st, _clock_epoch(ctx)) \
            if st.state == STATE_DELEGATED else 0
        if a.lamports - locked < lamports:
            raise FundsError(
                f"withdraw {lamports} exceeds free balance "
                f"({a.lamports} - {locked} locked)"
            )
        if a.key == dest.key:
            return
        a.lamports -= lamports
        dest.lamports += lamports
    elif tag == 4:  # Split { lamports u64 }; [stake, new_stake]
        if len(data) < 12:
            raise AcctError("malformed split")
        lamports = _u64(data[4:])
        a, new = acct(0), acct(1)
        need_writable(0)
        need_writable(1)
        st = StakeState.decode(bytes(a.data))
        if st.state != STATE_DELEGATED:
            raise AcctError("split of undelegated stake")
        if not signed_by(st.staker):
            raise AcctError("split missing staker signature")
        if lamports > st.stake or lamports > a.lamports:
            raise FundsError("split larger than delegation")
        if len(new.data) < _DATA_LEN:
            raise AcctError("split target too small")
        nst = StakeState.decode(bytes(new.data))
        if nst.state != STATE_UNINIT:
            raise AcctError("split target already in use")
        st.stake -= lamports
        a.lamports -= lamports
        a.data[:_DATA_LEN] = st.encode()
        new.lamports += lamports
        nst = StakeState(
            state=STATE_DELEGATED, staker=st.staker,
            withdrawer=st.withdrawer, voter=st.voter, stake=lamports,
            activation_epoch=st.activation_epoch,
            deactivation_epoch=st.deactivation_epoch,
        )
        new.data[:_DATA_LEN] = nst.encode()
    # other tags: no-op


# -- epoch stakes + rewards ---------------------------------------------------


@dataclass
class StakeEntry:
    stake_key: bytes
    state: StakeState


def collect_stakes(entries: list[StakeEntry], epoch: int) -> dict[bytes, int]:
    """voter pubkey -> total effective stake at `epoch` (the per-epoch
    snapshot fd_stakes.c maintains; feeds the leader schedule via
    protocol/wsample.epoch_leaders)."""
    out: dict[bytes, int] = {}
    for e in entries:
        eff = effective_stake(e.state, epoch)
        if eff > 0:
            out[e.state.voter] = out.get(e.state.voter, 0) + eff
    return out


def epoch_rewards(
    entries: list[StakeEntry],
    credits: dict[bytes, int],
    *,
    epoch: int,
    pot: int,
) -> dict[bytes, int]:
    """Distribute `pot` lamports over stake accounts by points =
    effective_stake × voter credits (fd_rewards.c's point model).
    Returns stake_key -> reward; remainder lamports stay undistributed
    (burned), matching the integer-division convention."""
    points: dict[bytes, int] = {}
    total = 0
    for e in entries:
        p = effective_stake(e.state, epoch) * credits.get(e.state.voter, 0)
        if p > 0:
            points[e.stake_key] = p
            total += p
    if total == 0:
        return {}
    return {k: pot * p // total for k, p in points.items()}


def apply_rewards(accounts: dict[bytes, "object"], rewards: dict[bytes, int]):
    """Pay rewards onto stake accounts, compounding the delegation (the
    auto-compound rule: a delegated stake's reward joins its stake)."""
    for key, amount in rewards.items():
        a = accounts[key]
        a.lamports += amount
        st = StakeState.decode(bytes(a.data))
        if st.state == STATE_DELEGATED:
            st.stake += amount
            a.data[:_DATA_LEN] = st.encode()


# -- partitioned rewards distribution -----------------------------------------
# The reference distributes epoch rewards over the first slots of the new
# epoch instead of one giant slot-boundary write burst
# (/root/reference/src/flamenco/runtime/sysvar/fd_sysvar_epoch_rewards.h +
# fd_rewards.c partitioned path; Agave's epoch_rewards partitioning).
# Accounts hash into partitions; partition i pays out in slot
# epoch_start + 1 + i; the EpochRewards sysvar stays `active` until the
# last partition lands.

PARTITION_TARGET_ACCOUNTS = 4096  # Agave's per-partition sizing target


def reward_partition_count(n_accounts: int) -> int:
    return max(1, (n_accounts + PARTITION_TARGET_ACCOUNTS - 1)
               // PARTITION_TARGET_ACCOUNTS)


def reward_partition_of(stake_key: bytes, n_partitions: int,
                        parent_blockhash: bytes) -> int:
    """Deterministic partition assignment: hash(address, seed) — every
    validator derives the same schedule from the epoch-boundary state."""
    import hashlib as _hl

    digest = _hl.sha256(b"epoch-rewards-partition:" + parent_blockhash
                        + stake_key).digest()
    return int.from_bytes(digest[:8], "little") % n_partitions


def partition_rewards(
    rewards: dict[bytes, int],
    parent_blockhash: bytes,
) -> list[dict[bytes, int]]:
    """Split a computed reward set into per-slot payout partitions."""
    n = reward_partition_count(len(rewards))
    parts: list[dict[bytes, int]] = [{} for _ in range(n)]
    for key, amount in rewards.items():
        parts[reward_partition_of(key, n, parent_blockhash)][key] = amount
    return parts


def epoch_rewards_sysvar(
    *,
    distribution_starting_block_height: int,
    num_partitions: int,
    parent_blockhash: bytes,
    total_points: int,
    total_rewards: int,
    distributed_rewards: int,
    active: bool,
) -> bytes:
    """The EpochRewards sysvar blob (the layout runtime.default_sysvars
    zero-fills when no distribution is in flight)."""
    return (
        distribution_starting_block_height.to_bytes(8, "little")
        + num_partitions.to_bytes(8, "little")
        + parent_blockhash
        + total_points.to_bytes(16, "little")
        + total_rewards.to_bytes(8, "little")
        + distributed_rewards.to_bytes(8, "little")
        + (b"\x01" if active else b"\x00")
    )


def distribute_reward_partition(
    funk,
    xid: bytes | None,
    partition: dict[bytes, int],
) -> int:
    """Pay out ONE partition onto funk accounts with the compounding
    rule — slot epoch_start+1+i pays exactly partitions[i], so calling
    once per slot can never double-pay.  Accounts that vanished between
    reward computation and payout are SKIPPED (paying a missing record
    would mint lamports into a fresh system account).  Returns lamports
    paid."""
    from firedancer_tpu.flamenco.executor import acct_decode, acct_encode

    paid = 0
    for key, amount in partition.items():
        val = funk.rec_query(xid, key)
        if val is None:
            continue  # closed since the epoch boundary: no destination
        lam, owner, ex, data = acct_decode(val)
        data = bytearray(data)
        if len(data) >= _DATA_LEN:
            st = StakeState.decode(bytes(data))
            if st.state == STATE_DELEGATED:
                st.stake += amount
                data[:_DATA_LEN] = st.encode()
        funk.rec_insert(xid, key,
                        acct_encode(lam + amount, owner, ex, bytes(data)))
        paid += amount
    return paid
