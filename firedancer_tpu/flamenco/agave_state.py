"""Agave on-chain account-state layouts: VoteState and StakeStateV2.

Capability parity target: the reference generates ~42k lines of bincode
(de)serializers for Solana's on-chain types
(/root/reference/src/flamenco/types/ from fd_types.json; no code
shared).  This module hand-builds the two layouts that gate reading a
REAL cluster's accounts — vote accounts (consensus weight, leader
schedule) and stake accounts (delegations, rewards) — in the exact
bincode wire format Agave stores, plus converters into this framework's
internal runtime views (flamenco/stake.StakeState; the vote program's
compact record).

Layouts are the public protocol's (solana-sdk vote_state/stake_state
definitions, stable on mainnet):

  VoteStateVersions  = enum { 0: V0_23_5, 1: V1_14_11, 2: Current }
  VoteState(Current) = node_pubkey | authorized_withdrawer | commission
      u8 | votes VecDeque<LandedVote{latency u8, Lockout{slot u64,
      conf u32}}> | root Option<u64> | authorized_voters BTreeMap<u64,
      Pubkey> | prior_voters CircBuf{[(Pubkey,u64,u64); 32], idx u64,
      is_empty bool} | epoch_credits Vec<(u64,u64,u64)> |
      last_timestamp {slot u64, ts i64}

  StakeStateV2 = enum { 0: Uninitialized, 1: Initialized(Meta),
      2: Stake(Meta, Stake, StakeFlags u8), 3: RewardsPool }
  Meta  = rent_exempt_reserve u64 | Authorized{staker, withdrawer} |
      Lockup{unix_timestamp i64, epoch u64, custodian}
  Stake = Delegation{voter, stake u64, activation_epoch u64,
      deactivation_epoch u64, warmup_cooldown_rate f64} |
      credits_observed u64
"""

from __future__ import annotations

from dataclasses import dataclass, field

from firedancer_tpu.flamenco import types as T

U64_MAX = (1 << 64) - 1


# -- vote state ----------------------------------------------------------------


@dataclass
class Lockout:
    slot: int = 0
    confirmation_count: int = 0


LOCKOUT = T.StructCodec(
    Lockout, ("slot", T.U64), ("confirmation_count", T.U32),
)


@dataclass
class LandedVote:
    latency: int = 0
    lockout: Lockout = field(default_factory=Lockout)


LANDED_VOTE = T.StructCodec(
    LandedVote, ("latency", T.U8), ("lockout", LOCKOUT),
)


class _BTreeMapU64Pubkey(T.Codec):
    """BTreeMap<u64, Pubkey>: u64 count + sorted (u64, 32B) pairs."""

    def encode(self, v: dict) -> bytes:
        out = T.U64.encode(len(v))
        for k in sorted(v):
            out += T.U64.encode(k) + bytes(v[k])
        return out

    def decode(self, buf, off=0):
        n, off = T.U64.decode(buf, off)
        if n > 1024:
            raise T.CodecError(f"authorized_voters map too large ({n})")
        out = {}
        for _ in range(n):
            k, off = T.U64.decode(buf, off)
            pk, off = T.Pubkey.decode(buf, off)
            out[k] = pk
        return out, off


@dataclass
class PriorVoters:
    buf: list = field(default_factory=lambda: [(bytes(32), 0, 0)] * 32)
    idx: int = 31
    is_empty: bool = True


class _PriorVotersCodec(T.Codec):
    def encode(self, v: PriorVoters) -> bytes:
        out = b""
        for pk, start, end in v.buf:
            out += bytes(pk) + T.U64.encode(start) + T.U64.encode(end)
        return out + T.U64.encode(v.idx) + T.Bool.encode(v.is_empty)

    def decode(self, buf, off=0):
        entries = []
        for _ in range(32):
            pk, off = T.Pubkey.decode(buf, off)
            a, off = T.U64.decode(buf, off)
            b, off = T.U64.decode(buf, off)
            entries.append((pk, a, b))
        idx, off = T.U64.decode(buf, off)
        empty, off = T.Bool.decode(buf, off)
        return PriorVoters(entries, idx, empty), off


@dataclass
class BlockTimestamp:
    slot: int = 0
    timestamp: int = 0


BLOCK_TIMESTAMP = T.StructCodec(
    BlockTimestamp, ("slot", T.U64), ("timestamp", T.I64),
)


class _EpochCredits(T.Codec):
    """Vec<(epoch u64, credits u64, prev_credits u64)>."""

    def encode(self, v: list) -> bytes:
        out = T.U64.encode(len(v))
        for epoch, credits, prev in v:
            out += T.U64.encode(epoch) + T.U64.encode(credits) \
                + T.U64.encode(prev)
        return out

    def decode(self, buf, off=0):
        n, off = T.U64.decode(buf, off)
        if n > 4096:
            raise T.CodecError(f"epoch_credits too large ({n})")
        out = []
        for _ in range(n):
            e, off = T.U64.decode(buf, off)
            c, off = T.U64.decode(buf, off)
            p, off = T.U64.decode(buf, off)
            out.append((e, c, p))
        return out, off


@dataclass
class VoteState:
    node_pubkey: bytes = bytes(32)
    authorized_withdrawer: bytes = bytes(32)
    commission: int = 0
    votes: list = field(default_factory=list)  # [LandedVote]
    root_slot: int | None = None
    authorized_voters: dict = field(default_factory=dict)  # epoch -> pk
    prior_voters: PriorVoters = field(default_factory=PriorVoters)
    epoch_credits: list = field(default_factory=list)
    last_timestamp: BlockTimestamp = field(default_factory=BlockTimestamp)

    def authorized_voter_for(self, epoch: int) -> bytes | None:
        """The voter authorized at `epoch`: the entry with the greatest
        key <= epoch (Agave's AuthorizedVoters::get_authorized_voter)."""
        best = None
        for e in sorted(self.authorized_voters):
            if e <= epoch:
                best = self.authorized_voters[e]
        return best

    def credits(self) -> int:
        return self.epoch_credits[-1][1] if self.epoch_credits else 0


_VOTE_STATE_BODY = T.StructCodec(
    VoteState,
    ("node_pubkey", T.Pubkey),
    ("authorized_withdrawer", T.Pubkey),
    ("commission", T.U8),
    ("votes", T.Vec(LANDED_VOTE, max_len=64)),
    ("root_slot", T.Option(T.U64)),
    ("authorized_voters", _BTreeMapU64Pubkey()),
    ("prior_voters", _PriorVotersCodec()),
    ("epoch_credits", _EpochCredits()),
    ("last_timestamp", BLOCK_TIMESTAMP),
)


# VoteState1_14_11: identical body except votes is VecDeque<Lockout>
# (no latency byte).  Still present in real cluster snapshots, so the
# decoder must accept it (vote_state_versions converters in the
# reference do the same upgrade-on-read).
_VOTE_STATE_BODY_1_14_11 = T.StructCodec(
    VoteState,
    ("node_pubkey", T.Pubkey),
    ("authorized_withdrawer", T.Pubkey),
    ("commission", T.U8),
    ("votes", T.Vec(LOCKOUT, max_len=64)),
    ("root_slot", T.Option(T.U64)),
    ("authorized_voters", _BTreeMapU64Pubkey()),
    ("prior_voters", _PriorVotersCodec()),
    ("epoch_credits", _EpochCredits()),
    ("last_timestamp", BLOCK_TIMESTAMP),
)


def _decode_v0_23_5(data: bytes, off: int) -> VoteState:
    """VoteState0_23_5: single (voter, epoch) pair instead of the
    authorized_voters map; prior_voters entries are 4-tuples and the
    CircBuf has no is_empty flag."""
    node, off = T.Pubkey.decode(data, off)
    voter, off = T.Pubkey.decode(data, off)
    voter_epoch, off = T.U64.decode(data, off)
    prior = []
    for _ in range(32):
        pk, off = T.Pubkey.decode(data, off)
        a, off = T.U64.decode(data, off)
        b, off = T.U64.decode(data, off)
        _slot, off = T.U64.decode(data, off)
        prior.append((pk, a, b))
    idx, off = T.U64.decode(data, off)
    withdrawer, off = T.Pubkey.decode(data, off)
    commission, off = T.U8.decode(data, off)
    votes, off = T.Vec(LOCKOUT, max_len=64).decode(data, off)
    root, off = T.Option(T.U64).decode(data, off)
    credits, off = _EpochCredits().decode(data, off)
    ts, off = BLOCK_TIMESTAMP.decode(data, off)
    return VoteState(
        node_pubkey=node,
        authorized_withdrawer=withdrawer,
        commission=commission,
        votes=[LandedVote(0, lk) for lk in votes],
        root_slot=root,
        authorized_voters={voter_epoch: voter},
        prior_voters=PriorVoters(prior, idx,
                                 all(pk == bytes(32) for pk, _, _ in prior)),
        epoch_credits=credits,
        last_timestamp=ts,
    )


def vote_state_encode(vs: VoteState) -> bytes:
    """Current-version envelope (enum tag 2)."""
    return T.U32.encode(2) + _VOTE_STATE_BODY.encode(vs)


def vote_state_decode(data: bytes) -> VoteState:
    """Decode ANY VoteStateVersions envelope, upgrading old layouts to
    the current view (the reference's vote_state_versions convert)."""
    tag, off = T.U32.decode(data, 0)
    if tag == 2:
        vs, _ = _VOTE_STATE_BODY.decode(data, off)
        return vs
    if tag == 1:
        vs, _ = _VOTE_STATE_BODY_1_14_11.decode(data, off)
        vs.votes = [LandedVote(0, lk) for lk in vs.votes]
        return vs
    if tag == 0:
        return _decode_v0_23_5(data, off)
    raise T.CodecError(f"unsupported VoteState version {tag}")


# -- stake state ---------------------------------------------------------------


@dataclass
class Authorized:
    staker: bytes = bytes(32)
    withdrawer: bytes = bytes(32)


AUTHORIZED = T.StructCodec(
    Authorized, ("staker", T.Pubkey), ("withdrawer", T.Pubkey),
)


@dataclass
class Lockup:
    unix_timestamp: int = 0
    epoch: int = 0
    custodian: bytes = bytes(32)


LOCKUP = T.StructCodec(
    Lockup, ("unix_timestamp", T.I64), ("epoch", T.U64),
    ("custodian", T.Pubkey),
)


@dataclass
class Meta:
    rent_exempt_reserve: int = 0
    authorized: Authorized = field(default_factory=Authorized)
    lockup: Lockup = field(default_factory=Lockup)


META = T.StructCodec(
    Meta, ("rent_exempt_reserve", T.U64), ("authorized", AUTHORIZED),
    ("lockup", LOCKUP),
)


@dataclass
class Delegation:
    voter_pubkey: bytes = bytes(32)
    stake: int = 0
    activation_epoch: int = 0
    deactivation_epoch: int = U64_MAX
    warmup_cooldown_rate: float = 0.25


DELEGATION = T.StructCodec(
    Delegation,
    ("voter_pubkey", T.Pubkey),
    ("stake", T.U64),
    ("activation_epoch", T.U64),
    ("deactivation_epoch", T.U64),
    ("warmup_cooldown_rate", T.F64),
)


@dataclass
class StakeV2:
    delegation: Delegation = field(default_factory=Delegation)
    credits_observed: int = 0


STAKE_V2 = T.StructCodec(
    StakeV2, ("delegation", DELEGATION), ("credits_observed", T.U64),
)


@dataclass
class StakeMetaPair:
    meta: Meta = field(default_factory=Meta)
    stake: StakeV2 = field(default_factory=StakeV2)
    flags: int = 0


class _StakePairCodec(T.Codec):
    def encode(self, v: StakeMetaPair) -> bytes:
        return META.encode(v.meta) + STAKE_V2.encode(v.stake) \
            + T.U8.encode(v.flags)

    def decode(self, buf, off=0):
        meta, off = META.decode(buf, off)
        stake, off = STAKE_V2.decode(buf, off)
        flags, off = T.U8.decode(buf, off)
        return StakeMetaPair(meta, stake, flags), off


STAKE_STATE_V2 = T.Enum(
    (0, "uninitialized", None),
    (1, "initialized", META),
    (2, "stake", _StakePairCodec()),
    (3, "rewards_pool", None),
)


# -- converters into the runtime's internal views ------------------------------


def to_internal_stake(data: bytes):
    """Agave StakeStateV2 account bytes -> flamenco/stake.StakeState
    (the runtime's compact view); None for uninitialized/rewards-pool."""
    from firedancer_tpu.flamenco import stake as S

    (kind, payload), _ = STAKE_STATE_V2.decode(data, 0)
    if kind == "initialized":
        return S.StakeState(
            state=S.STATE_INIT,
            staker=payload.authorized.staker,
            withdrawer=payload.authorized.withdrawer,
        )
    if kind == "stake":
        d = payload.stake.delegation
        return S.StakeState(
            state=S.STATE_DELEGATED,
            staker=payload.meta.authorized.staker,
            withdrawer=payload.meta.authorized.withdrawer,
            voter=d.voter_pubkey,
            stake=d.stake,
            activation_epoch=d.activation_epoch,
            deactivation_epoch=d.deactivation_epoch,
        )
    return None


def vote_account_summary(data: bytes, *, epoch: int) -> dict:
    """The fields consensus consumes from a real vote account: node
    identity, the epoch's authorized voter, credits, last vote."""
    vs = vote_state_decode(data)
    return {
        "node_pubkey": vs.node_pubkey,
        "authorized_voter": vs.authorized_voter_for(epoch),
        "authorized_withdrawer": vs.authorized_withdrawer,
        "commission": vs.commission,
        "credits": vs.credits(),
        "last_voted_slot": (
            vs.votes[-1].lockout.slot if vs.votes else None
        ),
        "root_slot": vs.root_slot,
    }
