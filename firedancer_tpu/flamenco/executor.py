"""Transaction executor: program dispatch, BPF serialization, CPI.

Counterpart of /root/reference/src/flamenco/runtime/fd_executor.c (per-txn
account loading + instruction dispatch) and the CPI syscall machinery in
/root/reference/src/flamenco/vm/syscall/fd_vm_syscall_cpi.c.  The runtime
(flamenco/runtime.py) calls `execute_txn_instrs` per transaction; each
instruction resolves to either

  - a *native program* registered by program id (system, vote, and the
    stake program in flamenco/stake.py), a plain Python callable over the
    instruction context; or
  - an *sBPF program*: the program account's ELF is loaded
    (protocol/sbpf.py), the instruction accounts are serialized into the
    VM's input region in the BPF-loader "aligned" layout, the VM runs
    (flamenco/vm.py), and account effects are deserialized back with
    privilege + lamport-conservation checks.

Cross-program invocation (`sol_invoke_signed_c`) re-enters this executor:
the callee instruction is read out of VM memory, PDA signer seeds are
resolved against the *caller's* program id (protocol/pda.py), privilege
escalation is rejected (a callee account can be signer/writable only if
the caller could already sign/write it), and on return the caller's
serialized view of every shared account is refreshed — the same
translate→invoke→sync shape as fd_vm_syscall_cpi_c.

Account encoding in funk record values (grows the round-2 u64||data
layout): `u64 lamports | 32B owner | u8 executable | data`.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from firedancer_tpu.pack.cost import DEFAULT_HEAP_SIZE
from firedancer_tpu.protocol import sbpf
from firedancer_tpu.protocol.txn import SYSTEM_PROGRAM, VOTE_PROGRAM

MAX_INSTR_STACK = 5  # Solana's max invoke stack height (top level = 1)
MAX_PERMITTED_DATA_INCREASE = 10 * 1024
MAX_CPI_INSTRUCTION_DATA_LEN = 10 * 1024
MAX_CPI_ACCOUNT_INFOS = 128
MAX_CPI_INSTRUCTION_ACCOUNTS = 255  # u8::MAX — metas may duplicate txn accounts

# loader v2: accounts owned by it with executable=1 hold sBPF ELFs
# directly; the upgradeable loader (flamenco/bpf_loader.py) adds the
# program -> programdata indirection resolved at invoke time
from firedancer_tpu.protocol.base58 import b58_decode32 as _b58d

BPF_LOADER_PROGRAM = _b58d("BPFLoader2111111111111111111111111111111111")

ACCT_HDR = 8 + 32 + 1  # lamports | owner | executable


def acct_encode(lamports: int, owner: bytes = SYSTEM_PROGRAM,
                executable: bool = False, data: bytes = b"") -> bytes:
    assert len(owner) == 32
    return (
        lamports.to_bytes(8, "little") + owner + bytes([1 if executable else 0])
        + data
    )


def acct_decode(val: bytes | None) -> tuple[int, bytes, bool, bytes]:
    """-> (lamports, owner, executable, data); a missing/short record is
    the zero account owned by the system program."""
    if not val:
        return 0, SYSTEM_PROGRAM, False, b""
    if len(val) < ACCT_HDR:  # legacy u64||data records: data after lamports
        return int.from_bytes(val[:8], "little"), SYSTEM_PROGRAM, False, val[8:]
    return (
        int.from_bytes(val[:8], "little"),
        val[8:40],
        val[40] != 0,
        val[41:],
    )


@dataclass
class Account:
    key: bytes
    lamports: int
    owner: bytes
    executable: bool
    data: bytearray

    @classmethod
    def from_value(cls, key: bytes, val: bytes | None) -> "Account":
        lam, owner, ex, data = acct_decode(val)
        return cls(key, lam, owner, ex, bytearray(data))

    def to_value(self) -> bytes:
        return acct_encode(self.lamports, self.owner, self.executable,
                          bytes(self.data))

    @property
    def exists(self) -> bool:
        return self.lamports > 0 or len(self.data) > 0 or self.owner != SYSTEM_PROGRAM


@dataclass
class InstrAccount:
    txn_idx: int
    is_signer: bool
    is_writable: bool


class InstrError(Exception):
    """Typed instruction failure; aborts the transaction (fee still paid)."""

    def __init__(self, msg: str, custom: int | None = None):
        super().__init__(msg)
        self.custom = custom


@dataclass
class TxnCtx:
    """Per-transaction execution context: the unique account set with
    txn-level privileges, the shared compute budget, the invoke stack."""

    accounts: list[Account]
    signer: list[bool]
    writable: list[bool]
    budget: int = 200_000
    heap_size: int = DEFAULT_HEAP_SIZE  # RequestHeapFrame-controlled
    cu_used: int = 0
    logs: list[bytes] = field(default_factory=list)
    stack: list[bytes] = field(default_factory=list)  # program ids
    return_data: tuple[bytes, bytes] = (bytes(32), b"")
    sysvars: dict = field(default_factory=dict)  # name -> bincode blob
    # upgradeable programs resolved at txn load: program key ->
    # (elf bytes, deploy slot); populated by the runtime's account loader
    program_elfs: dict = field(default_factory=dict)
    # every top-level instruction's data, in txn order — the precompile
    # programs' offset tables reference across instructions
    instr_datas: list = field(default_factory=list)
    # processed-instruction trace: (stack_height, program_id,
    # [(pubkey, signer, writable)], data) per completed instruction —
    # sol_get_processed_sibling_instruction's source
    instr_trace: list = field(default_factory=list)

    def charge(self, n: int) -> None:
        self.cu_used += n
        if self.cu_used > self.budget:
            raise InstrError(f"compute budget exceeded ({self.budget})")

    def index_of(self, key: bytes) -> int | None:
        for i, a in enumerate(self.accounts):
            if a.key == key:
                return i
        return None


class Executor:
    """Program registry + instruction dispatch."""

    def __init__(self):
        from firedancer_tpu.flamenco import alt, programs, stake, vote_program
        from firedancer_tpu.pack.cost import COMPUTE_BUDGET_PROGRAM

        from firedancer_tpu.flamenco import bpf_loader

        from firedancer_tpu.flamenco import config_program, precompiles
        from firedancer_tpu.flamenco import zk_elgamal

        self.native = {
            SYSTEM_PROGRAM: programs.system_program,
            config_program.CONFIG_PROGRAM: config_program.config_program,
            precompiles.ED25519_PROGRAM: precompiles.ed25519_program,
            precompiles.SECP256K1_PROGRAM: precompiles.secp256k1_program,
            VOTE_PROGRAM: vote_program.vote_program,
            stake.STAKE_PROGRAM: stake.stake_program,
            alt.ALT_PROGRAM: alt.alt_program,
            COMPUTE_BUDGET_PROGRAM: programs.compute_budget_program,
            bpf_loader.UPGRADEABLE_LOADER_PROGRAM:
                bpf_loader.upgradeable_loader_program,
            zk_elgamal.ZK_ELGAMAL_PROOF_PROGRAM:
                zk_elgamal.zk_elgamal_program,
        }

    def register(self, program_id: bytes, fn) -> None:
        self.native[program_id] = fn

    def execute_instr(
        self,
        ctx: TxnCtx,
        program_id: bytes,
        iaccts: list[InstrAccount],
        data: bytes,
        *,
        pda_signers: frozenset[bytes] = frozenset(),
    ) -> None:
        if len(ctx.stack) >= MAX_INSTR_STACK:
            raise InstrError("max instruction stack depth")
        ctx.stack.append(program_id)
        uniq = {ia.txn_idx for ia in iaccts}
        lam_before = sum(ctx.accounts[i].lamports for i in uniq)
        try:
            fn = self.native.get(program_id)
            if fn is not None:
                # builtins charge their fixed CU cost up front (the
                # reference's DEFAULT_COMPUTE_UNITS per native program,
                # same table pack's cost model uses)
                from firedancer_tpu.pack.cost import BUILTIN_COST

                ctx.charge(BUILTIN_COST.get(program_id, 0))
                fn(self, ctx, program_id, iaccts, data,
                   pda_signers=pda_signers)
            else:
                prog_idx = ctx.index_of(program_id)
                if prog_idx is None:
                    return  # unknown program not present: no-op (pre-VM parity)
                pacct = ctx.accounts[prog_idx]
                from firedancer_tpu.flamenco.bpf_loader import (
                    UPGRADEABLE_LOADER_PROGRAM,
                )

                if pacct.owner not in (
                    BPF_LOADER_PROGRAM, UPGRADEABLE_LOADER_PROGRAM
                ):
                    return  # data account as program target: no-op
                if not pacct.executable:
                    # a closed/undeployed loader-owned account is not a
                    # silent no-op (InvalidProgramForExecution parity)
                    raise InstrError("program account is not executable")
                self._execute_bpf(ctx, pacct, program_id, iaccts, data,
                                  pda_signers)
            # instruction-level lamport conservation over the UNIQUE
            # account set (duplicate metas are legal and must not double-
            # count; fd_executor's sum check)
            lam_after = sum(ctx.accounts[i].lamports for i in uniq)
            if lam_after != lam_before:
                raise InstrError(
                    f"lamport sum changed {lam_before} -> {lam_after}"
                )
            # record the PROCESSED instruction for sibling introspection
            # (sol_get_processed_sibling_instruction reads this trace)
            ctx.instr_trace.append((
                len(ctx.stack), program_id,
                [(ctx.accounts[ia.txn_idx].key, ia.is_signer,
                  ia.is_writable) for ia in iaccts],
                bytes(data),
            ))
        finally:
            ctx.stack.pop()

    # -- sBPF dispatch --------------------------------------------------------

    def _resolve_program_elf(self, ctx, pacct) -> bytes:
        """The ELF to run for a program account: direct bytes for loader
        v2; the programdata indirection (+ deploy-slot visibility rule)
        for the upgradeable loader."""
        from firedancer_tpu.flamenco import bpf_loader as bl

        if pacct.owner == BPF_LOADER_PROGRAM:
            return bytes(pacct.data)
        hit = ctx.program_elfs.get(pacct.key)
        if hit is not None:
            elf, deploy_slot = hit
        else:
            # fall back to a programdata account present in the txn
            pd_addr = bl.program_programdata(bytes(pacct.data))
            idx = ctx.index_of(pd_addr)
            if idx is None:
                raise InstrError("programdata account unavailable")
            pd_data = bytes(ctx.accounts[idx].data)
            deploy_slot, _auth = bl.programdata_meta(pd_data)
            elf = bl.programdata_elf(pd_data)
        blob = ctx.sysvars.get("clock")
        if blob is not None:
            from firedancer_tpu.flamenco import types as T

            if T.CLOCK.decode(blob, 0)[0].slot == deploy_slot:
                # LoaderV3 delay rule: a program (re)deployed in slot N
                # is invokable from slot N+1
                raise InstrError("program was deployed in this slot")
        return elf

    def _execute_bpf(self, ctx, pacct, program_id, iaccts, data, pda_signers):
        from firedancer_tpu.flamenco import vm as fvm

        try:
            prog = sbpf.load(self._resolve_program_elf(ctx, pacct))
        except sbpf.SbpfError as e:
            raise InstrError(f"program load failed: {e}") from e
        blob, smap = serialize_aligned(ctx, iaccts, data, program_id)
        v = fvm.Vm(program=prog, input_data=blob,
                   budget=ctx.budget - ctx.cu_used,
                   heap_size=ctx.heap_size)
        v.sysvars = ctx.sysvars
        v.return_data = ctx.return_data
        v.program_id = program_id
        v.stack_height = len(ctx.stack)
        v.instr_trace = ctx.instr_trace
        fvm.register_default_syscalls(v, log_sink=ctx.logs)
        register_cpi_syscall(self, v, ctx, iaccts, program_id, smap,
                             pda_signers)
        try:
            r0 = v.run()
        except fvm.VmError as e:
            ctx.cu_used += min(v.cu_used, ctx.budget - ctx.cu_used)
            raise InstrError(f"vm error: {e}") from e
        ctx.cu_used += v.cu_used
        if ctx.cu_used > ctx.budget:
            ctx.cu_used = ctx.budget
            raise InstrError("compute budget exceeded")
        if r0 != 0:
            raise InstrError(f"program error 0x{r0:x}", custom=r0)
        # attribution already correct (set inside the syscall); clears
        # (empty data) propagate too
        ctx.return_data = v.return_data
        writeback_aligned(ctx, v, smap, program_id)


# -- BPF loader "aligned" account serialization -------------------------------
#
# Layout per unique account (dups reference the first occurrence):
#   u8 0xFF | u8 is_signer | u8 is_writable | u8 executable | 4B pad |
#   32B key | 32B owner | u64 lamports | u64 data_len | data |
#   MAX_PERMITTED_DATA_INCREASE spare | pad to 8 | u64 rent_epoch
# then u64 instr_data_len | instr_data | 32B program_id.


@dataclass
class SerialEntry:
    txn_idx: int
    lamports_off: int
    owner_off: int
    data_len_off: int
    data_off: int
    orig_data_len: int
    writable: bool


def serialize_aligned(
    ctx: TxnCtx, iaccts: list[InstrAccount], data: bytes, program_id: bytes
) -> tuple[bytes, list[SerialEntry]]:
    out = bytearray()
    out += len(iaccts).to_bytes(8, "little")
    seen: dict[int, int] = {}  # txn_idx -> serial position
    smap: list[SerialEntry] = []
    for pos, ia in enumerate(iaccts):
        if ia.txn_idx in seen:
            out += bytes([seen[ia.txn_idx]]) + bytes(7)
            continue
        seen[ia.txn_idx] = pos
        a = ctx.accounts[ia.txn_idx]
        out += bytes([0xFF, 1 if ia.is_signer else 0,
                      1 if ia.is_writable else 0, 1 if a.executable else 0])
        out += bytes(4)
        out += a.key
        owner_off = len(out)
        out += a.owner
        lam_off = len(out)
        out += a.lamports.to_bytes(8, "little")
        dlen_off = len(out)
        out += len(a.data).to_bytes(8, "little")
        d_off = len(out)
        out += bytes(a.data)
        out += bytes(MAX_PERMITTED_DATA_INCREASE)
        pad = (-len(out)) % 8
        out += bytes(pad)
        out += (0).to_bytes(8, "little")  # rent_epoch
        smap.append(SerialEntry(ia.txn_idx, lam_off, owner_off, dlen_off,
                                d_off, len(a.data), ia.is_writable))
    out += len(data).to_bytes(8, "little")
    out += data
    out += program_id
    return bytes(out), smap


def writeback_aligned(ctx: TxnCtx, v, smap: list[SerialEntry],
                      program_id: bytes) -> None:
    """Deserialize account effects out of the VM input region.  Only
    writable accounts read back; data growth is capped at
    MAX_PERMITTED_DATA_INCREASE over the serialized length; and the
    owner-may-debit/modify rule holds (fd_executor's account checks): a
    program may credit any writable account, but debiting lamports,
    changing data, or reassigning the owner requires owning it."""
    region = v.regions[3].data  # input region backing store
    for e in smap:
        a = ctx.accounts[e.txn_idx]
        if not e.writable:
            # a read-only account's serialized image must come back
            # byte-identical — silently dropping a program's writes
            # would let it "succeed" while its effects vanish
            # (ReadonlyDataModified parity; caught by the vm conformance
            # fixture store_readonly_faults)
            if (
                int.from_bytes(region[e.lamports_off : e.lamports_off + 8],
                               "little") != a.lamports
                or bytes(region[e.owner_off : e.owner_off + 32]) != a.owner
                or region[e.data_off : e.data_off + e.orig_data_len]
                != bytes(a.data)
            ):
                raise InstrError(
                    "program modified a read-only account's image"
                )
            continue
        owns = a.owner == program_id
        new_lam = int.from_bytes(region[e.lamports_off : e.lamports_off + 8],
                                 "little")
        new_owner = bytes(region[e.owner_off : e.owner_off + 32])
        new_len = int.from_bytes(
            region[e.data_len_off : e.data_len_off + 8], "little"
        )
        if new_len > e.orig_data_len + MAX_PERMITTED_DATA_INCREASE:
            raise InstrError(
                f"account data grew past the permitted increase ({new_len})"
            )
        new_data = bytearray(region[e.data_off : e.data_off + new_len])
        if not owns:
            if new_lam < a.lamports:
                raise InstrError("program debited an account it does not own")
            if new_owner != a.owner:
                raise InstrError("program reassigned a foreign account")
            if new_data != a.data:
                raise InstrError("program modified foreign account data")
        a.lamports = new_lam
        a.owner = new_owner
        a.data = new_data


def sync_into_vm(ctx: TxnCtx, v, smap: list[SerialEntry]) -> None:
    """Refresh the caller VM's serialized view after a CPI returns
    (lamports/owner/data of shared accounts may have changed)."""
    region = v.regions[3].data
    for e in smap:
        a = ctx.accounts[e.txn_idx]
        region[e.lamports_off : e.lamports_off + 8] = a.lamports.to_bytes(
            8, "little"
        )
        region[e.owner_off : e.owner_off + 32] = a.owner
        cap = e.orig_data_len + MAX_PERMITTED_DATA_INCREASE
        if len(a.data) > cap:
            raise InstrError("callee grew account past caller's capacity")
        region[e.data_len_off : e.data_len_off + 8] = len(a.data).to_bytes(
            8, "little"
        )
        region[e.data_off : e.data_off + len(a.data)] = a.data
        # zero the tail so stale caller bytes don't leak past the new length
        region[e.data_off + len(a.data) : e.data_off + cap] = bytes(
            cap - len(a.data)
        )


# -- CPI: sol_invoke_signed_c / sol_invoke_signed_rust ------------------------
#
# C ABI structs read out of VM memory (fd_vm_syscall_cpi.c's C path):
#   SolInstruction  { u64 program_id_addr; u64 accounts_addr; u64 accounts_len;
#                     u64 data_addr; u64 data_len; }
#   SolAccountMeta  { u64 pubkey_addr; u8 is_writable; u8 is_signer; }
#   SolSignerSeedsC { u64 addr; u64 len; }  of  SolSignerSeedC { addr; len; }
#
# Rust ABI (the StableInstruction layout fd_vm_syscall_cpi.c's rust path
# translates): Instruction { accounts: StableVec<AccountMeta>, data:
# StableVec<u8>, program_id: Pubkey } where StableVec = { addr u64,
# cap u64, len u64 } and AccountMeta = { pubkey 32 | is_signer u8 |
# is_writable u8 } (34 bytes packed).  Both paths share the translate +
# privilege + invoke + sync core below.


def register_cpi_syscall(executor, v, ctx, caller_iaccts, caller_program_id,
                         smap, caller_pda_signers):
    from firedancer_tpu.flamenco import vm as fvm
    from firedancer_tpu.protocol import pda

    caller_priv: dict[int, InstrAccount] = {}
    for ia in caller_iaccts:
        cur = caller_priv.get(ia.txn_idx)
        if cur is None:
            caller_priv[ia.txn_idx] = InstrAccount(
                ia.txn_idx, ia.is_signer, ia.is_writable
            )
        else:  # privileges union over duplicate listings
            cur.is_signer |= ia.is_signer
            cur.is_writable |= ia.is_writable

    def _read_pda_signers(vm_, seeds_addr, seeds_len):
        """Seeds sign for addresses derived from the CALLER's program."""
        pda_signers = set(caller_pda_signers)
        for i in range(seeds_len):
            arr_addr = vm_.mem_read(seeds_addr + 16 * i, 8)
            arr_len = vm_.mem_read(seeds_addr + 16 * i + 8, 8)
            if arr_len > pda.MAX_SEEDS:
                raise fvm.VmError("too many signer seeds")
            seeds = []
            for j in range(arr_len):
                s_addr = vm_.mem_read(arr_addr + 16 * j, 8)
                s_len = vm_.mem_read(arr_addr + 16 * j + 8, 8)
                if s_len > pda.MAX_SEED_LEN:
                    raise fvm.VmError("signer seed too long")
                seeds.append(vm_.mem_read_bytes(s_addr, s_len))
            try:
                pda_signers.add(
                    pda.create_program_address(seeds, caller_program_id)
                )
            except pda.PdaError as e:
                raise fvm.VmError(f"bad signer seeds: {e}") from e
        return pda_signers

    def _cpi_core(vm_, callee_prog, metas, data, pda_signers):
        """Shared translate + privilege check + invoke + sync.
        metas: [(pubkey, is_signer, is_writable)]."""
        iaccts: list[InstrAccount] = []
        for key, m_signer, m_writable in metas:
            idx = ctx.index_of(key)
            if idx is None:
                raise fvm.VmError("cpi account not in transaction")
            prv = caller_priv.get(idx)
            may_sign = (prv is not None and prv.is_signer) or key in pda_signers
            may_write = prv is not None and prv.is_writable
            if m_signer and not may_sign:
                raise fvm.VmError("cpi signer privilege escalation")
            if m_writable and not may_write:
                raise fvm.VmError("cpi writable privilege escalation")
            iaccts.append(InstrAccount(idx, m_signer, m_writable))

        # the program may have mutated its serialized accounts before the
        # CPI — pull the current state into ctx first (same owner rules);
        # likewise its return data (a callee that never sets return data
        # must observe — and preserve — the caller's current value)
        writeback_aligned(ctx, vm_, smap, caller_program_id)
        ctx.return_data = vm_.return_data
        ctx.cu_used += vm_.cu_used  # budget is shared across the stack
        try:
            executor.execute_instr(
                ctx, callee_prog, iaccts, data,
                pda_signers=frozenset(pda_signers),
            )
        except InstrError as e:
            raise fvm.VmError(f"cpi failed: {e}") from e
        finally:
            ctx.cu_used -= vm_.cu_used
            sync_into_vm(ctx, vm_, smap)
        vm_.return_data = ctx.return_data  # callee's return data visible
        return 0

    def sol_invoke_signed_c(vm_, instr_addr, _infos_addr, infos_len,
                            seeds_addr, seeds_len):
        vm_.charge(fvm.SYSCALL_BASE_COST * 10)
        if infos_len > MAX_CPI_ACCOUNT_INFOS:
            raise fvm.VmError("too many account infos")
        prog_addr = vm_.mem_read(instr_addr, 8)
        metas_addr = vm_.mem_read(instr_addr + 8, 8)
        metas_len = vm_.mem_read(instr_addr + 16, 8)
        data_addr = vm_.mem_read(instr_addr + 24, 8)
        data_len = vm_.mem_read(instr_addr + 32, 8)
        if data_len > MAX_CPI_INSTRUCTION_DATA_LEN:
            raise fvm.VmError("cpi instruction data too long")
        if metas_len > MAX_CPI_INSTRUCTION_ACCOUNTS:
            raise fvm.VmError("too many account metas")
        callee_prog = vm_.mem_read_bytes(prog_addr, 32)
        data = vm_.mem_read_bytes(data_addr, data_len) if data_len else b""
        metas = []
        for i in range(metas_len):
            m_addr = metas_addr + 10 * i  # packed C layout: u64 + u8 + u8
            pk_addr = vm_.mem_read(m_addr, 8)
            m_writable = vm_.mem_read(m_addr + 8, 1) != 0
            m_signer = vm_.mem_read(m_addr + 9, 1) != 0
            metas.append((vm_.mem_read_bytes(pk_addr, 32), m_signer,
                          m_writable))
        pda_signers = _read_pda_signers(vm_, seeds_addr, seeds_len)
        return _cpi_core(vm_, callee_prog, metas, data, pda_signers)

    def sol_invoke_signed_rust(vm_, instr_addr, _infos_addr, infos_len,
                               seeds_addr, seeds_len):
        vm_.charge(fvm.SYSCALL_BASE_COST * 10)
        if infos_len > MAX_CPI_ACCOUNT_INFOS:
            raise fvm.VmError("too many account infos")
        # StableInstruction: accounts StableVec | data StableVec | Pubkey
        metas_addr = vm_.mem_read(instr_addr, 8)
        metas_len = vm_.mem_read(instr_addr + 16, 8)  # skip cap at +8
        data_addr = vm_.mem_read(instr_addr + 24, 8)
        data_len = vm_.mem_read(instr_addr + 40, 8)  # skip cap at +32
        callee_prog = vm_.mem_read_bytes(instr_addr + 48, 32)
        if data_len > MAX_CPI_INSTRUCTION_DATA_LEN:
            raise fvm.VmError("cpi instruction data too long")
        if metas_len > MAX_CPI_INSTRUCTION_ACCOUNTS:
            raise fvm.VmError("too many account metas")
        data = vm_.mem_read_bytes(data_addr, data_len) if data_len else b""
        metas = []
        for i in range(metas_len):
            m_addr = metas_addr + 34 * i  # AccountMeta: pubkey | u8 | u8
            key = vm_.mem_read_bytes(m_addr, 32)
            m_signer = vm_.mem_read(m_addr + 32, 1) != 0
            m_writable = vm_.mem_read(m_addr + 33, 1) != 0
            metas.append((key, m_signer, m_writable))
        pda_signers = _read_pda_signers(vm_, seeds_addr, seeds_len)
        return _cpi_core(vm_, callee_prog, metas, data, pda_signers)

    v.syscalls[fvm.SYSCALL_SOL_INVOKE_SIGNED_C] = sol_invoke_signed_c
    v.syscalls[fvm.SYSCALL_SOL_INVOKE_SIGNED_RUST] = sol_invoke_signed_rust
