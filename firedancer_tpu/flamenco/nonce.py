"""Durable nonce accounts: the system program's nonce instruction
family plus the runtime's durable-nonce transaction gate.

Capability parity with the reference's nonce support
(/root/reference/src/flamenco/runtime/program/fd_system_program_nonce.c
and the executor's durable-nonce check; no code shared).  A nonce
account lets a transaction carry a STORED hash as its recent_blockhash:
offline signers can hold a signed txn indefinitely, and each use
advances the nonce so the txn cannot replay.

Account data layout (this framework's own fixed encoding, like stake):

    u32  state      0 = uninitialized, 1 = initialized
    32B  authority  may advance/withdraw/authorize
    32B  nonce      the durable hash txns may use as recent_blockhash

System-program instruction tags (Agave numbering):
    4 AdvanceNonceAccount            accounts [nonce]; authority signs
    5 WithdrawNonceAccount {u64}     [nonce, dest]; authority signs
    6 InitializeNonceAccount {auth}  [nonce]
    7 AuthorizeNonceAccount {auth}   [nonce]; current authority signs

The DURABLE GATE (`durable_nonce_ok`) is the consensus-critical piece:
a txn whose recent_blockhash fails the 150-slot currency check is still
valid iff its FIRST instruction is AdvanceNonceAccount and the named
nonce account's stored hash equals the txn's blockhash — and executing
that advance rotates the hash so the txn can never land twice.
"""

from __future__ import annotations

import hashlib

from firedancer_tpu.flamenco.programs import (
    AcctError, FundsError, _u32, _u64,
)
from firedancer_tpu.protocol.txn import SYSTEM_PROGRAM

STATE_UNINIT = 0
STATE_INIT = 1
DATA_LEN = 4 + 32 + 32

TAG_ADVANCE = 4
TAG_WITHDRAW = 5
TAG_INITIALIZE = 6
TAG_AUTHORIZE = 7


def encode_state(state: int, authority: bytes, nonce: bytes) -> bytes:
    return state.to_bytes(4, "little") + authority + nonce


def decode_state(data: bytes) -> tuple[int, bytes, bytes]:
    if len(data) < DATA_LEN:
        return STATE_UNINIT, bytes(32), bytes(32)
    return _u32(data), bytes(data[4:36]), bytes(data[36:68])


def next_nonce(recent_blockhash: bytes, nonce_key: bytes) -> bytes:
    """The advanced durable hash: domain-separated over the slot's
    blockhash and the account (distinct accounts advancing in the same
    slot must diverge)."""
    return hashlib.sha256(
        b"fdtpu:durable-nonce" + recent_blockhash + nonce_key
    ).digest()


def _recent_blockhash(ctx) -> bytes:
    bh = ctx.sysvars.get("recent_blockhash")
    if not bh:
        # fail CLOSED: advancing to a predictable value would let a
        # durable txn replay
        raise AcctError("nonce instruction requires the blockhash sysvar")
    return bh


def handle(executor, ctx, tag, iaccts, data, *, pda_signers):
    """Dispatch one nonce-family system instruction (called from
    programs.system_program for tags 4..7)."""

    def acct(i):
        if i >= len(iaccts):
            raise AcctError(f"nonce instr needs account {i}")
        return ctx.accounts[iaccts[i].txn_idx]

    def need_writable(i):
        if not iaccts[i].is_writable:
            raise AcctError(f"nonce account {i} not writable")

    def signed_by(key: bytes) -> bool:
        for ia in iaccts:
            a = ctx.accounts[ia.txn_idx]
            if a.key == key and (ia.is_signer or a.key in pda_signers):
                return True
        return False

    a = acct(0)
    need_writable(0)
    if a.owner != SYSTEM_PROGRAM:
        raise AcctError("nonce account not system-owned")
    state, authority, nonce = decode_state(bytes(a.data))

    if tag == TAG_INITIALIZE:
        if len(data) < 4 + 32:
            raise AcctError("malformed initialize_nonce")
        if state != STATE_UNINIT:
            raise AcctError("nonce account already initialized")
        if len(a.data) < DATA_LEN:
            raise AcctError("nonce account too small")
        a.data[:DATA_LEN] = encode_state(
            STATE_INIT, data[4:36], next_nonce(_recent_blockhash(ctx), a.key)
        )
    elif tag == TAG_ADVANCE:
        if state != STATE_INIT:
            raise AcctError("advance of uninitialized nonce")
        if not signed_by(authority):
            raise AcctError("advance missing nonce authority signature")
        new = next_nonce(_recent_blockhash(ctx), a.key)
        if new == nonce:
            # same-slot double advance: the durable hash must move
            raise AcctError("nonce unchanged (same blockhash)")
        a.data[:DATA_LEN] = encode_state(STATE_INIT, authority, new)
    elif tag == TAG_WITHDRAW:
        if len(data) < 12:
            raise AcctError("malformed withdraw_nonce")
        lamports = _u64(data[4:])
        dest = acct(1)
        need_writable(1)
        who = authority if state == STATE_INIT else a.key
        if not signed_by(who):
            raise AcctError("withdraw missing authority signature")
        if a.lamports < lamports:
            raise FundsError("nonce withdraw exceeds balance")
        if state == STATE_INIT:
            if lamports == a.lamports:
                # full drain: refuse while the stored nonce is still the
                # CURRENT durable hash (Agave's NonceBlockhashNotExpired)
                # — a drained-but-initialized account must never keep
                # satisfying durable_nonce_ok, so the state clears too
                if nonce == next_nonce(_recent_blockhash(ctx), a.key):
                    raise AcctError("nonce blockhash not expired")
                a.data[:DATA_LEN] = encode_state(
                    STATE_UNINIT, bytes(32), bytes(32)
                )
            else:
                # partial: the remainder must stay rent-exempt
                from firedancer_tpu.flamenco import types as T

                rent_blob = ctx.sysvars.get("rent")
                rent = (T.RENT.decode(rent_blob, 0)[0] if rent_blob
                        else T.Rent())
                floor = T.rent_exempt_minimum(rent, len(a.data))
                if a.lamports - lamports < floor:
                    raise FundsError("nonce withdraw below rent floor")
        if a.key == dest.key:
            return
        a.lamports -= lamports
        dest.lamports += lamports
    elif tag == TAG_AUTHORIZE:
        if len(data) < 4 + 32:
            raise AcctError("malformed authorize_nonce")
        if state != STATE_INIT:
            raise AcctError("authorize of uninitialized nonce")
        if not signed_by(authority):
            raise AcctError("authorize missing authority signature")
        a.data[:DATA_LEN] = encode_state(STATE_INIT, data[4:36], nonce)
    else:
        raise AcctError(f"unknown nonce tag {tag}")


# -- the runtime's durable gate -----------------------------------------------


def durable_nonce_ok(funk, xid, payload: bytes, desc) -> bool:
    """May this stale-blockhash txn run as a durable-nonce txn?

    First instruction must be system AdvanceNonceAccount, its nonce
    account (first instruction account) must be a WRITABLE initialized
    nonce whose stored hash equals the txn's recent_blockhash, and the
    nonce AUTHORITY must be a txn signer (the reference's
    check_transaction_age / load_message_nonce_account path).  The
    authority + writability checks live HERE — not just in the advance
    instruction — because a failed durable txn still rotates the nonce:
    without them, any fee-payer could rotate a victim's nonce account
    (invalidating their outstanding offline-signed txns) by submitting
    a txn whose advance instruction fails."""
    from firedancer_tpu.flamenco.runtime import acct_decode

    if not desc.instrs:
        return False
    ins = desc.instrs[0]
    addrs = desc.acct_addrs(payload)
    if ins.program_id >= len(addrs):
        return False
    if addrs[ins.program_id] != SYSTEM_PROGRAM:
        return False
    data = payload[ins.data_off : ins.data_off + ins.data_sz]
    if len(data) < 4 or _u32(data) != TAG_ADVANCE or ins.acct_cnt < 1:
        return False
    idx = payload[ins.acct_off]
    if idx >= len(addrs) or not desc.is_writable(idx):
        return False
    _lam, owner, _ex, acc_data = acct_decode(
        funk.rec_query(xid, addrs[idx])
    )
    if owner != SYSTEM_PROGRAM:
        return False
    state, auth, nonce = decode_state(acc_data)
    if state != STATE_INIT or nonce != desc.recent_blockhash(payload):
        return False
    signers = set(addrs[: desc.signature_cnt])
    return auth in signers
