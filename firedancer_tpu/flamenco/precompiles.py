"""Precompile programs: ed25519 and secp256k1 signature-verification
instructions.

Capability parity with the reference's precompiles
(/root/reference/src/flamenco/runtime/fd_precompiles.c; no code
shared): these programs carry OFFSET TABLES, not payloads — each entry
points at a signature, a pubkey, and a message that live in some
instruction's data within the SAME transaction (instruction index
u16::MAX = "this instruction").  The program verifies every entry and
fails the whole instruction on the first bad signature; programs
downstream in the txn can then trust the verified relationship.

Wire format (Agave layout):

  ed25519:   u8 count | u8 pad | count x {
                 sig_off u16, sig_ix u16, pk_off u16, pk_ix u16,
                 msg_off u16, msg_sz u16, msg_ix u16 }
  secp256k1: u8 count | count x {
                 sig_off u16, sig_ix u8, eth_off u16, eth_ix u8,
                 msg_off u16, msg_sz u16, msg_ix u8 }
             where sig is 64B+recovery_id and eth is the 20-byte
             keccak address the recovered key must hash to.
"""

from __future__ import annotations

import struct

from firedancer_tpu.flamenco.programs import AcctError
from firedancer_tpu.protocol.base58 import b58_decode32

ED25519_PROGRAM = b58_decode32("Ed25519SigVerify111111111111111111111111111")
SECP256K1_PROGRAM = b58_decode32("KeccakSecp256k11111111111111111111111111111")

_SELF_IX16 = 0xFFFF
_SELF_IX8 = 0xFF

_ED_ENTRY = struct.Struct("<HHHHHHH")
_SECP_ENTRY = struct.Struct("<HBHBHHB")


def _ref(ctx, data: bytes, ix: int, off: int, ln: int,
         self_marker: int) -> bytes:
    """Fetch `ln` bytes at `off` of instruction `ix`'s data (the current
    instruction's own data for the self marker)."""
    if ix == self_marker:
        src = data
    else:
        if ix >= len(ctx.instr_datas):
            raise AcctError(f"precompile references instruction {ix}")
        src = ctx.instr_datas[ix]
    if off + ln > len(src):
        raise AcctError("precompile offset out of range")
    return bytes(src[off : off + ln])


def ed25519_program(executor, ctx, program_id, iaccts, data, *,
                    pda_signers):
    from firedancer_tpu.ops.ref import ed25519_ref as ref

    if len(data) < 2:
        raise AcctError("short ed25519 precompile data")
    count = data[0]
    need = 2 + count * _ED_ENTRY.size
    if len(data) < need:
        raise AcctError("truncated ed25519 precompile entries")
    for k in range(count):
        (sig_off, sig_ix, pk_off, pk_ix, msg_off, msg_sz, msg_ix) = (
            _ED_ENTRY.unpack_from(data, 2 + k * _ED_ENTRY.size)
        )
        sig = _ref(ctx, data, sig_ix, sig_off, 64, _SELF_IX16)
        pk = _ref(ctx, data, pk_ix, pk_off, 32, _SELF_IX16)
        msg = _ref(ctx, data, msg_ix, msg_off, msg_sz, _SELF_IX16)
        if not ref.verify(msg, sig, pk):
            raise AcctError(f"ed25519 precompile entry {k} invalid")


def secp256k1_program(executor, ctx, program_id, iaccts, data, *,
                      pda_signers):
    from firedancer_tpu.ops import keccak256, secp256k1 as secp

    if len(data) < 1:
        raise AcctError("short secp256k1 precompile data")
    count = data[0]
    need = 1 + count * _SECP_ENTRY.size
    if len(data) < need:
        raise AcctError("truncated secp256k1 precompile entries")
    for k in range(count):
        (sig_off, sig_ix, eth_off, eth_ix, msg_off, msg_sz, msg_ix) = (
            _SECP_ENTRY.unpack_from(data, 1 + k * _SECP_ENTRY.size)
        )
        sig_rec = _ref(ctx, data, sig_ix, sig_off, 65, _SELF_IX8)
        eth = _ref(ctx, data, eth_ix, eth_off, 20, _SELF_IX8)
        msg = _ref(ctx, data, msg_ix, msg_off, msg_sz, _SELF_IX8)
        digest = keccak256.keccak256_host(msg)
        try:
            pub = secp.recover(digest, sig_rec[64], sig_rec[:64])
        except secp.RecoverError as e:
            raise AcctError(
                f"secp256k1 precompile entry {k}: {e}"
            ) from e
        if keccak256.keccak256_host(pub)[-20:] != eth:
            raise AcctError(f"secp256k1 precompile entry {k} wrong address")
