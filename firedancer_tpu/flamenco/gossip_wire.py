"""Solana-exact gossip wire format (CRDS protocol messages).

Counterpart of the wire layer in /root/reference/src/flamenco/gossip/
fd_gossip.c: the bincode `Protocol` enum exchanged between validators —

    0 PullRequest(CrdsFilter, CrdsValue)
    1 PullResponse(Pubkey, Vec<CrdsValue>)
    2 PushMessage(Pubkey, Vec<CrdsValue>)
    3 PruneMessage(Pubkey, PruneData)
    4 PingMessage(Ping)
    5 PongMessage(Pong)

built from the bincode combinators in flamenco/types.py.  A CrdsValue
is `signature(64) | CrdsData`, where the Ed25519 signature covers the
bincode serialization of the CrdsData — exactly the signable-data rule
CRDS uses.  CrdsData variants implemented: LegacyContactInfo (tag 0),
the variant cluster discovery runs on; other tags decode to a rejection
(they cannot be skipped — bincode carries no length prefix for enum
payloads — and this node never produces them).

The PullRequest filter is encoded faithfully (Bloom { keys, Option
bits, num_bits_set } + mask/mask_bits); this node sends the match-all
filter and ignores received filters (serving every record is always
protocol-legal, just less bandwidth-optimal).

Ping/Pong follow the token scheme: Pong.hash = sha256("SOLANA_PING_PONG"
|| ping.token), both signed by their sender.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

from firedancer_tpu.flamenco import types as T
from firedancer_tpu.ops.ref import ed25519_ref as ref

PING_PONG_PREFIX = b"SOLANA_PING_PONG"

# -- CrdsData -----------------------------------------------------------------

CRDS_DATA = T.Enum(
    (0, "legacy_contact_info", T.LEGACY_CONTACT_INFO),
)


@dataclass
class CrdsValue:
    signature: bytes
    data: tuple  # ("legacy_contact_info", LegacyContactInfo)

    def signable(self) -> bytes:
        return CRDS_DATA.encode(self.data)

    def verify(self) -> bool:
        kind, payload = self.data
        return ref.verify(self.signable(), self.signature, payload.id)

    @property
    def pubkey(self) -> bytes:
        return self.data[1].id

    @property
    def wallclock(self) -> int:
        return self.data[1].wallclock


class _CrdsValueCodec(T.Codec):
    def encode(self, v: CrdsValue) -> bytes:
        return T.Signature.encode(v.signature) + CRDS_DATA.encode(v.data)

    def decode(self, buf, off=0):
        sig, off = T.Signature.decode(buf, off)
        data, off = CRDS_DATA.decode(buf, off)
        return CrdsValue(sig, data), off


CRDS_VALUE = _CrdsValueCodec()


def sign_value(secret: bytes, data: tuple) -> CrdsValue:
    return CrdsValue(ref.sign(secret, CRDS_DATA.encode(data)), data)


def contact_info_value(
    secret: bytes,
    *,
    gossip: tuple,
    tvu: tuple,
    repair: tuple,
    tpu: tuple,
    wallclock: int,
    shred_version: int = 1,
) -> CrdsValue:
    """Build + sign this node's LegacyContactInfo CrdsValue.  Unused
    sockets carry the unspecified v4 address (the protocol's
    convention for 'not serving this')."""
    unspec = ("v4", T.SockAddr(bytes(4), 0))
    ci = T.LegacyContactInfo(
        id=ref.public_key(secret),
        gossip=gossip, tvu=tvu, tvu_forwards=unspec, repair=repair,
        tpu=tpu, tpu_forwards=unspec, tpu_vote=unspec, rpc=unspec,
        rpc_pubsub=unspec, serve_repair=repair,
        wallclock=wallclock, shred_version=shred_version,
    )
    return sign_value(secret, ("legacy_contact_info", ci))


# -- Ping / Pong --------------------------------------------------------------


@dataclass
class Ping:
    from_: bytes
    token: bytes
    signature: bytes


PING = T.StructCodec(
    Ping, ("from_", T.Pubkey), ("token", T.FixedBytes(32)),
    ("signature", T.Signature),
)


def ping_make(secret: bytes, token: bytes) -> Ping:
    return Ping(ref.public_key(secret), token, ref.sign(secret, token))


def ping_verify(p: Ping) -> bool:
    return ref.verify(p.token, p.signature, p.from_)


@dataclass
class Pong:
    from_: bytes
    hash: bytes
    signature: bytes


PONG = T.StructCodec(
    Pong, ("from_", T.Pubkey), ("hash", T.Hash32),
    ("signature", T.Signature),
)


def pong_make(secret: bytes, ping_token: bytes) -> Pong:
    h = hashlib.sha256(PING_PONG_PREFIX + ping_token).digest()
    return Pong(ref.public_key(secret), h, ref.sign(secret, h))


def pong_verify(p: Pong, ping_token: bytes) -> bool:
    want = hashlib.sha256(PING_PONG_PREFIX + ping_token).digest()
    return p.hash == want and ref.verify(p.hash, p.signature, p.from_)


# -- PullRequest filter -------------------------------------------------------
# CrdsFilter { filter: Bloom { keys: Vec<u64>, bits: BitVec<u64>
# (Option<Vec<u64>> + u64 len), num_bits_set: u64 }, mask: u64,
# mask_bits: u32 }


class _BloomCodec(T.Codec):
    def encode(self, v) -> bytes:
        keys, bits, num_set = v
        out = T.Vec(T.U64).encode(keys)
        out += T.Option(T.Vec(T.U64)).encode(bits)
        out += T.U64.encode(len(bits) * 64 if bits is not None else 0)
        out += T.U64.encode(num_set)
        return out

    def decode(self, buf, off=0):
        keys, off = T.Vec(T.U64).decode(buf, off)
        bits, off = T.Option(T.Vec(T.U64)).decode(buf, off)
        _len, off = T.U64.decode(buf, off)
        num_set, off = T.U64.decode(buf, off)
        return (keys, bits, num_set), off


@dataclass
class CrdsFilter:
    bloom: tuple = ((), None, 0)
    mask: int = (1 << 64) - 1  # match-all
    mask_bits: int = 0


CRDS_FILTER = T.StructCodec(
    CrdsFilter, ("bloom", _BloomCodec()), ("mask", T.U64),
    ("mask_bits", T.U32),
)



# -- value hashing + bloom filters --------------------------------------------
# A CrdsValue's identity in the pull protocol is the sha256 of its
# serialized bytes.  Bloom bit positions use the FNV-1a-shaped fold the
# protocol specifies (fd_gossip.c:802-810 documents the same rule); the
# filter set partitions the hash space by the TOP mask_bits of the
# hash's first 8 bytes read little-endian, one filter per partition
# (fd_gossip.c:920, 1565-1570 — behavior mirrored, no code shared).

BLOOM_NUM_BITS = 512 * 8  # bits per outgoing filter packet
BLOOM_MAX_KEYS = 32
BLOOM_MAX_PACKETS = 32


def value_hash(value_bytes: bytes) -> bytes:
    import hashlib

    return hashlib.sha256(value_bytes).digest()


def bloom_pos(hash32: bytes, key: int, nbits: int) -> int:
    for b in hash32:
        key ^= b
        key = (key * 1099511628211) & ((1 << 64) - 1)
    return key % nbits


def _hash_u64(hash32: bytes) -> int:
    return int.from_bytes(hash32[:8], "little")


def build_filters(hashes: list[bytes], *, rng=None,
                  num_bits: int = BLOOM_NUM_BITS) -> list[CrdsFilter]:
    """Bloom-filter packets covering `hashes` (everything I already
    hold).  Scales packets/keys like the protocol: ~n/packets items per
    filter, k = (m/n) ln 2 keys, doubling packets until the false-pos
    rate clears 0.1%."""
    import math
    import os as _os

    rand = rng or (lambda: int.from_bytes(_os.urandom(8), "little"))
    nitems = len(hashes)
    nkeys, npackets, nmaskbits = 1, 1, 0
    if nitems > 0:
        while True:
            n = nitems / npackets
            m = float(num_bits)
            nkeys = max(1, min(int((m / max(n, 1e-9)) * math.log(2)),
                               BLOOM_MAX_KEYS))
            if npackets == BLOOM_MAX_PACKETS:
                break
            e = (1.0 - math.exp(-nkeys * n / m)) ** nkeys
            if e < 0.001:
                break
            nmaskbits += 1
            npackets = 1 << nmaskbits
    keys = [rand() & ((1 << 64) - 1) for _ in range(nkeys)]
    words = num_bits // 64
    bits = [[0] * words for _ in range(npackets)]
    nset = [0] * npackets
    for h in hashes:
        idx = 0 if nmaskbits == 0 else _hash_u64(h) >> (64 - nmaskbits)
        chunk = bits[idx]
        for k in keys:
            pos = bloom_pos(h, k, num_bits)
            w, bit = pos >> 6, 1 << (pos & 63)
            if not chunk[w] & bit:
                chunk[w] |= bit
                nset[idx] += 1
    out = []
    ones = ((1 << 64) - 1) >> nmaskbits if nmaskbits else (1 << 64) - 1
    for i in range(npackets):
        mask = (i << (64 - nmaskbits)) | ones if nmaskbits else ones
        out.append(CrdsFilter(
            bloom=(keys, bits[i], nset[i]), mask=mask,
            mask_bits=nmaskbits,
        ))
    return out


def filter_contains(filt: CrdsFilter, hash32: bytes) -> bool | None:
    """True = the requester already holds this value; False = send it;
    None = outside this filter's mask partition (skip)."""
    keys, bits, _nset = filt.bloom
    if filt.mask_bits:
        ones = ((1 << 64) - 1) >> filt.mask_bits
        if (_hash_u64(hash32) | ones) != filt.mask:
            return None
    if bits is None or not keys:
        return False
    nbits = len(bits) * 64
    for k in keys:
        pos = bloom_pos(hash32, k, nbits)
        if not (bits[pos >> 6] >> (pos & 63)) & 1:
            return False
    return True


# -- PruneMessage -------------------------------------------------------------
# Protocol tag 3: PruneMsg(Pubkey, PruneData { pubkey, prunes Vec<Pubkey>,
# signature, destination, wallclock }).  The signature covers the bincode
# of (pubkey, prunes, destination, wallclock) — the serialized payload
# minus the signature field (fd_gossip.c:1322-1329 verifies the same
# region).  A verified prune from peer P for origins O tells the push
# side: stop forwarding O's values to P.


@dataclass
class PruneData:
    pubkey: bytes
    prunes: list
    signature: bytes
    destination: bytes
    wallclock: int

    def signable(self) -> bytes:
        return (T.Pubkey.encode(self.pubkey)
                + T.Vec(T.Pubkey).encode(self.prunes)
                + T.Pubkey.encode(self.destination)
                + T.U64.encode(self.wallclock))

    def verify(self) -> bool:
        return ref.verify(self.signable(), self.signature, self.pubkey)


PRUNE_DATA = T.StructCodec(
    PruneData,
    ("pubkey", T.Pubkey),
    ("prunes", T.Vec(T.Pubkey, max_len=8192)),
    ("signature", T.Signature),
    ("destination", T.Pubkey),
    ("wallclock", T.U64),
)


def prune_make(secret: bytes, prunes: list, destination: bytes,
               wallclock: int) -> PruneData:
    me = ref.public_key(secret)
    pd = PruneData(me, list(prunes), bytes(64), destination, wallclock)
    pd.signature = ref.sign(secret, pd.signable())
    return pd


# -- the Protocol enum --------------------------------------------------------


class _Pair(T.Codec):
    def __init__(self, a: T.Codec, b: T.Codec):
        self.a, self.b = a, b

    def encode(self, v) -> bytes:
        return self.a.encode(v[0]) + self.b.encode(v[1])

    def decode(self, buf, off=0):
        x, off = self.a.decode(buf, off)
        y, off = self.b.decode(buf, off)
        return (x, y), off


PROTOCOL = T.Enum(
    (0, "pull_request", _Pair(CRDS_FILTER, CRDS_VALUE)),
    (1, "pull_response", _Pair(T.Pubkey, T.Vec(CRDS_VALUE, max_len=4096))),
    (2, "push_message", _Pair(T.Pubkey, T.Vec(CRDS_VALUE, max_len=4096))),
    (3, "prune_message", _Pair(T.Pubkey, PRUNE_DATA)),
    (4, "ping", PING),
    (5, "pong", PONG),
)


def encode_message(name: str, payload) -> bytes:
    return PROTOCOL.encode((name, payload))


def decode_message(buf: bytes):
    """-> (name, payload) or None on any malformed input (gossip drops
    bad datagrams silently; counters belong to the node)."""
    import struct

    try:
        return PROTOCOL.loads(buf)
    except (T.CodecError, ValueError, struct.error):
        return None
