"""The zk-sdk sigma proofs: verifiers (consensus surface) + provers.

Capability parity target: the reference's zksdk/instructions/*.c —
each verifier below names its counterpart and implements the SAME
verification equation and transcript protocol (Agave
zk-sdk/src/sigma_proofs), over ops/ristretto and the merlin transcript.
No code shared: the multiscalar equations are re-derived from the
protocol comments and checked by round-tripping our own provers plus
the real-transaction fixture embedded in the reference's test suite.

All functions raise ZkError on malformed input and return None on
success (verification failure also raises — callers map to the typed
instruction error).
"""

from __future__ import annotations

from firedancer_tpu.flamenco.zksdk.elgamal import G, H
from firedancer_tpu.flamenco.zksdk.merlin import Transcript
from firedancer_tpu.ops import ristretto as ri
from firedancer_tpu.ops.ref.ed25519_ref import L, point_mul

ZERO32 = bytes(32)


class ZkError(ValueError):
    pass


# -- transcript conventions (zksdk/transcript/fd_zksdk_transcript.h) ----------


def scalar_validate(b: bytes) -> int:
    v = int.from_bytes(b, "little")
    if v >= L:
        raise ZkError("non-canonical scalar")
    return v


def challenge_scalar(t: Transcript, label: bytes) -> int:
    return int.from_bytes(t.challenge_bytes(label, 64), "little") % L


def validate_and_append_point(t: Transcript, label: bytes, p: bytes) -> None:
    if p == ZERO32:
        raise ZkError("identity point in transcript")
    t.append_message(label, p)


def decompress(b: bytes):
    try:
        return ri.decode(b)
    except ri.RistrettoError as e:
        raise ZkError(f"bad point: {e}") from e


def msm(scalars: list[int], points: list) -> object:
    return ri.multiscalar_mul(scalars, points)


def _check(res, expect) -> None:
    if not ri.eq(res, expect):
        raise ZkError("proof verification failed")


# -- pubkey validity (fd_zksdk_pubkey_validity.c) -----------------------------
# context: pubkey 32 | proof: Y 32, z 32.  Equation: z H == c P + Y.


def verify_pubkey_validity(context: bytes, proof: bytes) -> None:
    if len(context) != 32 or len(proof) != 64:
        raise ZkError("bad sizes")
    pubkey, y_bytes, z_bytes = context, proof[:32], proof[32:]
    z = scalar_validate(z_bytes)
    p = decompress(pubkey)
    y = decompress(y_bytes)
    t = Transcript(b"pubkey-validity-instruction")
    t.append_message(b"pubkey", pubkey)
    t.append_message(b"dom-sep", b"pubkey-proof")
    validate_and_append_point(t, b"Y", y_bytes)
    c = challenge_scalar(t, b"c")
    _check(msm([z, L - c], [H, p]), y)


def prove_pubkey_validity(secret: int, pubkey: bytes, rnd: bytes) -> bytes:
    """Prover (client side): knows s with P = s^-1 H."""
    import hashlib

    s_inv = pow(secret, L - 2, L)
    k = int.from_bytes(hashlib.sha512(b"pkv:" + rnd).digest(), "little") % L
    y_bytes = ri.encode(point_mul(k, H))
    t = Transcript(b"pubkey-validity-instruction")
    t.append_message(b"pubkey", pubkey)
    t.append_message(b"dom-sep", b"pubkey-proof")
    validate_and_append_point(t, b"Y", y_bytes)
    c = challenge_scalar(t, b"c")
    z = (c * s_inv + k) % L
    return y_bytes + z.to_bytes(32, "little")


# -- zero ciphertext (fd_zksdk_zero_ciphertext.c) -----------------------------
# context: pubkey 32 | ciphertext 64.  proof: Y_P 32 | Y_D 32 | z 32.
# Equations: (z P == c H + Y_P) * 1;  (z D == c C + Y_D) * w.


def _zero_ciphertext_transcript(pubkey: bytes, ciphertext: bytes) -> Transcript:
    t = Transcript(b"zero-ciphertext-instruction")
    t.append_message(b"pubkey", pubkey)
    t.append_message(b"ciphertext", ciphertext)
    t.append_message(b"dom-sep", b"zero-ciphertext-proof")
    return t


def verify_zero_ciphertext(context: bytes, proof: bytes) -> None:
    if len(context) != 96 or len(proof) != 96:
        raise ZkError("bad sizes")
    pubkey, ciphertext = context[:32], context[32:]
    yp_b, yd_b, z_b = proof[:32], proof[32:64], proof[64:]
    z = scalar_validate(z_b)
    p = decompress(pubkey)
    cc = decompress(ciphertext[:32])
    d = decompress(ciphertext[32:])
    yd = decompress(yd_b)
    yp = decompress(yp_b)
    t = _zero_ciphertext_transcript(pubkey, ciphertext)
    validate_and_append_point(t, b"Y_P", yp_b)
    t.append_message(b"Y_D", yd_b)
    c = challenge_scalar(t, b"c")
    w = challenge_scalar(t, b"w")
    _check(
        msm([L - c, z, (L - c) * w % L, w * z % L, L - w],
            [H, p, cc, d, yd]),
        yp,
    )


def prove_zero_ciphertext(secret: int, pubkey: bytes, ciphertext: bytes,
                          rnd: bytes) -> bytes:
    """Knows s with H = s P and D s = r H (ciphertext of 0: C = r H)."""
    import hashlib

    p = decompress(pubkey)
    d = decompress(ciphertext[32:])
    k = int.from_bytes(hashlib.sha512(b"zc:" + rnd).digest(), "little") % L
    yp_b = ri.encode(point_mul(k, p))
    yd_b = ri.encode(point_mul(k, d))
    t = _zero_ciphertext_transcript(pubkey, ciphertext)
    validate_and_append_point(t, b"Y_P", yp_b)
    t.append_message(b"Y_D", yd_b)
    c = challenge_scalar(t, b"c")
    z = (c * secret + k) % L
    return yp_b + yd_b + z.to_bytes(32, "little")


# -- ciphertext-commitment equality (fd_zksdk_ciphertext_commitment_equality.c)
# context: pubkey 32 | ciphertext 64 | commitment 32.
# proof: Y_0 Y_1 Y_2 | z_s z_x z_r.
# Equations: (z_s P == c H + Y_0) * w^2
#            (z_x G + z_s D == c C + Y_1) * w
#            (z_x G + z_r H == c C_dst + Y_2) * 1


def verify_ciphertext_commitment_equality(context: bytes,
                                          proof: bytes) -> None:
    if len(context) != 128 or len(proof) != 192:
        raise ZkError("bad sizes")
    pubkey, ciphertext, commitment = (
        context[:32], context[32:96], context[96:])
    y0_b, y1_b, y2_b = proof[:32], proof[32:64], proof[64:96]
    zs = scalar_validate(proof[96:128])
    zx = scalar_validate(proof[128:160])
    zr = scalar_validate(proof[160:192])
    p = decompress(pubkey)
    c_src = decompress(ciphertext[:32])
    d_src = decompress(ciphertext[32:])
    c_dst = decompress(commitment)
    y0 = decompress(y0_b)
    y1 = decompress(y1_b)
    y2 = decompress(y2_b)
    t = Transcript(b"ciphertext-commitment-equality-instruction")
    t.append_message(b"pubkey", pubkey)
    t.append_message(b"ciphertext", ciphertext)
    t.append_message(b"commitment", commitment)
    t.append_message(b"dom-sep", b"ciphertext-commitment-equality-proof")
    validate_and_append_point(t, b"Y_0", y0_b)
    validate_and_append_point(t, b"Y_1", y1_b)
    validate_and_append_point(t, b"Y_2", y2_b)
    c = challenge_scalar(t, b"c")
    w = challenge_scalar(t, b"w")
    ww = w * w % L
    _check(
        msm(
            [
                (zx * w + zx) % L,            # G
                (zr - c * ww) % L,            # H
                (L - ww) % L,                 # Y_0
                (L - w) % L,                  # Y_1
                zs * ww % L,                  # P_src
                (L - c) * w % L,              # C_src
                zs * w % L,                   # D_src
                (L - c) % L,                  # C_dst
            ],
            [G, H, y0, y1, p, c_src, d_src, c_dst],
        ),
        y2,
    )


# -- ciphertext-ciphertext equality (fd_zksdk_ciphertext_ciphertext_equality.c)
# context: pk1 32 | pk2 32 | ct1 64 | ct2 64.
# proof: Y_0..Y_3 | z_s z_x z_r.


def verify_ciphertext_ciphertext_equality(context: bytes,
                                          proof: bytes) -> None:
    if len(context) != 192 or len(proof) != 224:
        raise ZkError("bad sizes")
    pk1, pk2 = context[:32], context[32:64]
    ct1, ct2 = context[64:128], context[128:192]
    y_b = [proof[32 * i : 32 * (i + 1)] for i in range(4)]
    zs = scalar_validate(proof[128:160])
    zx = scalar_validate(proof[160:192])
    zr = scalar_validate(proof[192:224])
    p1 = decompress(pk1)
    p2 = decompress(pk2)
    c1, d1 = decompress(ct1[:32]), decompress(ct1[32:])
    c2, d2 = decompress(ct2[:32]), decompress(ct2[32:])
    y = [decompress(b) for b in y_b]
    t = Transcript(b"ciphertext-ciphertext-equality-instruction")
    t.append_message(b"first-pubkey", pk1)
    t.append_message(b"second-pubkey", pk2)
    t.append_message(b"first-ciphertext", ct1)
    t.append_message(b"second-ciphertext", ct2)
    t.append_message(b"dom-sep", b"ciphertext-ciphertext-equality-proof")
    for i in range(4):
        validate_and_append_point(t, b"Y_%d" % i, y_b[i])
    c = challenge_scalar(t, b"c")
    w = challenge_scalar(t, b"w")
    ww = w * w % L
    www = ww * w % L
    _check(
        msm(
            [
                zx * (w + ww) % L,        # G
                (zr * ww - c) % L,        # H
                zs,                       # P1
                zs * w % L,               # D1
                (L - w) % L,              # Y_1
                (L - w) * c % L,          # C1
                (L - ww) % L,             # Y_2
                (L - ww) * c % L,         # C2
                (L - www) % L,            # Y_3
                (L - www) * c % L,        # D2
                www * zr % L,             # P2
            ],
            [G, H, p1, d1, y[1], c1, y[2], c2, y[3], d2, p2],
        ),
        y[0],
    )


# -- percentage with cap (fd_zksdk_percentage_with_cap.c) ---------------------
# context: percentage_commitment 32 | delta_commitment 32 |
#          claimed_commitment 32 | max_value u64 LE.
# proof: (y_max 32 | z_max 32 | c_max 32) + (y_delta 32 | y_claimed 32 |
#         z_x 32 | z_delta 32 | z_claimed 32)


def verify_percentage_with_cap(context: bytes, proof: bytes) -> None:
    if len(context) != 104 or len(proof) != 256:
        raise ZkError("bad sizes")
    c_max_comm, c_delta_comm, c_claim_comm = (
        context[:32], context[32:64], context[64:96])
    max_value = int.from_bytes(context[96:104], "little")
    y_max_b = proof[:32]
    z_max = scalar_validate(proof[32:64])
    c_max = scalar_validate(proof[64:96])
    y_delta_b = proof[96:128]
    y_claim_b = proof[128:160]
    z_x = scalar_validate(proof[160:192])
    z_delta = scalar_validate(proof[192:224])
    z_claimed = scalar_validate(proof[224:256])
    pts = [decompress(b) for b in
           (c_max_comm, y_delta_b, c_delta_comm, y_claim_b, c_claim_comm,
            y_max_b)]
    p_max, y_delta, c_delta, y_claim, c_claim, y_max = pts
    t = Transcript(b"percentage-with-cap-instruction")
    t.append_message(b"percentage-commitment", c_max_comm)
    t.append_message(b"delta-commitment", c_delta_comm)
    t.append_message(b"claimed-commitment", c_claim_comm)
    t.append_u64(b"max-value", max_value)
    t.append_message(b"dom-sep", b"percentage-with-cap-proof")
    validate_and_append_point(t, b"Y_max_proof", y_max_b)
    validate_and_append_point(t, b"Y_delta", y_delta_b)
    validate_and_append_point(t, b"Y_claimed", y_claim_b)
    c = challenge_scalar(t, b"c")
    w = challenge_scalar(t, b"w")
    ww = w * w % L
    c_eq = (c - c_max) % L
    _check(
        msm(
            [
                (c_max * max_value - (w + ww) * z_x) % L,        # G
                (z_max - (w * z_delta + ww * z_claimed)) % L,    # H
                (L - c_max) % L,                                 # C_max
                w,                                               # Y_delta
                w * c_eq % L,                                    # C_delta
                ww,                                              # Y_claim
                ww * c_eq % L,                                   # C_claim
            ],
            [G, H, p_max, y_delta, c_delta, y_claim, c_claim],
        ),
        y_max,
    )


# -- grouped-ciphertext validity, 2/3 handles, plain + batched ----------------
# (fd_zksdk_batched_grouped_ciphertext_{2,3}_handles_validity.c)


def _grouped_verify(
    pubkeys: list[bytes],
    comm: bytes,
    handles: list[bytes],
    comm_hi: bytes | None,
    handles_hi: list[bytes] | None,
    proof: bytes,
    transcript: Transcript,
    batched: bool,
) -> None:
    n = len(pubkeys)
    y_b = [proof[32 * i : 32 * (i + 1)] for i in range(n + 1)]
    zr = scalar_validate(proof[32 * (n + 1) : 32 * (n + 2)])
    zx = scalar_validate(proof[32 * (n + 2) : 32 * (n + 3)])

    pubkey_n_zero = n == 2 and pubkeys[-1] == ZERO32
    if pubkey_n_zero:
        # last pubkey zero: its handle(s) and Y must be zero too
        if handles[-1] != ZERO32 or y_b[-1] != ZERO32 or (
            batched and handles_hi[-1] != ZERO32
        ):
            raise ZkError("zero-pubkey consistency")

    y0 = decompress(y_b[0])
    points = [G, H]
    scalars: list[int] = []

    tcr = transcript
    t_chal = 0
    if batched:
        tcr.append_message(b"dom-sep", b"batched-validity-proof")
        tcr.append_u64(b"handles", n)
        t_chal = challenge_scalar(tcr, b"t")
    tcr.append_message(b"dom-sep", b"validity-proof")
    tcr.append_u64(b"handles", n)
    validate_and_append_point(tcr, b"Y_0", y_b[0])
    validate_and_append_point(tcr, b"Y_1", y_b[1])
    if n == 2:
        tcr.append_message(b"Y_2", y_b[2])  # may be zero
    else:
        validate_and_append_point(tcr, b"Y_2", y_b[2])
        tcr.append_message(b"Y_3", y_b[3])  # may be zero
    c = challenge_scalar(tcr, b"c")
    w = challenge_scalar(tcr, b"w")

    # base MSM: G z_x + H z_r + Σ_i (pub_i z_r w^i + Y_i (-w^i) + h_i (-c w^i))
    # + C (-c) [+ batched hi-terms scaled by t]
    scalars = [zx, zr]
    points = [G, H]
    scalars.append((L - c) % L)
    points.append(decompress(comm))
    wi = 1
    for i in range(n):
        if n == 2 and i == n - 1 and pubkey_n_zero:
            break
        wi = wi * w % L
        scalars.append(zr * wi % L)
        points.append(decompress(pubkeys[i]))
        scalars.append((L - wi) % L)
        points.append(decompress(y_b[i + 1]))
        scalars.append((L - c) * wi % L)
        points.append(decompress(handles[i]))
    if batched:
        scalars.append((L - c) * t_chal % L)
        points.append(decompress(comm_hi))
        wi = 1
        for i in range(n):
            if n == 2 and i == n - 1 and pubkey_n_zero:
                break
            wi = wi * w % L
            scalars.append((L - c) * wi % L * t_chal % L)
            points.append(decompress(handles_hi[i]))
    _check(msm(scalars, points), y0)


def verify_grouped_ciphertext_2_handles_validity(context: bytes,
                                                 proof: bytes) -> None:
    if len(context) != 160 or len(proof) != 160:
        raise ZkError("bad sizes")
    pk1, pk2, gc = context[:32], context[32:64], context[64:]
    t = Transcript(b"grouped-ciphertext-validity-2-handles-instruction")
    t.append_message(b"first-pubkey", pk1)
    t.append_message(b"second-pubkey", pk2)
    t.append_message(b"grouped-ciphertext", gc)
    _grouped_verify([pk1, pk2], gc[:32], [gc[32:64], gc[64:96]],
                    None, None, proof, t, batched=False)


def verify_batched_grouped_ciphertext_2_handles_validity(
    context: bytes, proof: bytes
) -> None:
    if len(context) != 256 or len(proof) != 160:
        raise ZkError("bad sizes")
    pk1, pk2 = context[:32], context[32:64]
    lo, hi = context[64:160], context[160:256]
    t = Transcript(
        b"batched-grouped-ciphertext-validity-2-handles-instruction")
    t.append_message(b"first-pubkey", pk1)
    t.append_message(b"second-pubkey", pk2)
    t.append_message(b"grouped-ciphertext-lo", lo)
    t.append_message(b"grouped-ciphertext-hi", hi)
    _grouped_verify([pk1, pk2], lo[:32], [lo[32:64], lo[64:96]],
                    hi[:32], [hi[32:64], hi[64:96]], proof, t,
                    batched=True)


def verify_grouped_ciphertext_3_handles_validity(context: bytes,
                                                 proof: bytes) -> None:
    if len(context) != 224 or len(proof) != 192:
        raise ZkError("bad sizes")
    pk1, pk2, pk3, gc = (context[:32], context[32:64], context[64:96],
                         context[96:])
    t = Transcript(b"grouped-ciphertext-validity-3-handles-instruction")
    t.append_message(b"first-pubkey", pk1)
    t.append_message(b"second-pubkey", pk2)
    t.append_message(b"third-pubkey", pk3)
    t.append_message(b"grouped-ciphertext", gc)
    _grouped_verify([pk1, pk2, pk3], gc[:32],
                    [gc[32:64], gc[64:96], gc[96:128]],
                    None, None, proof, t, batched=False)


def verify_batched_grouped_ciphertext_3_handles_validity(
    context: bytes, proof: bytes
) -> None:
    if len(context) != 352 or len(proof) != 192:
        raise ZkError("bad sizes")
    pk1, pk2, pk3 = context[:32], context[32:64], context[64:96]
    lo, hi = context[96:224], context[224:352]
    t = Transcript(
        b"batched-grouped-ciphertext-validity-3-handles-instruction")
    t.append_message(b"first-pubkey", pk1)
    t.append_message(b"second-pubkey", pk2)
    t.append_message(b"third-pubkey", pk3)
    t.append_message(b"grouped-ciphertext-lo", lo)
    t.append_message(b"grouped-ciphertext-hi", hi)
    _grouped_verify([pk1, pk2, pk3], lo[:32],
                    [lo[32:64], lo[64:96], lo[96:128]],
                    hi[:32], [hi[32:64], hi[64:96], hi[96:128]],
                    proof, t, batched=True)
