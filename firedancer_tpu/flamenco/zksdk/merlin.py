"""Merlin transcripts (STROBE-128 over keccak-f[1600]).

Capability parity target: the reference's
zksdk/merlin/fd_merlin.{c,h}, itself a port of zkcrypto/merlin 3.0.0.
No code shared: this is written from the STROBE v1.0.2 specification
(operations lite profile, sec=128 -> R = 166) and merlin's documented
framing (meta-AD of `label || LE32(len)` around each operation), reusing
the repo's keccak-f permutation (ops/keccak256).

Test anchor: merlin 3.0.0's own equivalence vector ("test protocol" /
"some label" / "some data" -> challenge d5a21972...) — the same vector
the reference's test_merlin.c pins.
"""

from __future__ import annotations

from firedancer_tpu.ops.keccak256 import _keccak_f_host

STROBE_R = 166  # rate bytes for the 128-bit security profile
FLAG_I = 1
FLAG_A = 1 << 1
FLAG_C = 1 << 2
FLAG_T = 1 << 3
FLAG_M = 1 << 4
FLAG_K = 1 << 5


class Strobe128:
    def __init__(self, protocol_label: bytes):
        st = bytearray(200)
        st[0:6] = bytes([1, STROBE_R + 2, 1, 0, 1, 96])
        st[6:18] = b"STROBEv1.0.2"
        self.state = self._permute(st)
        self.pos = 0
        self.pos_begin = 0
        self.cur_flags = 0
        self.meta_ad(protocol_label, False)

    @staticmethod
    def _permute(st: bytearray) -> bytearray:
        lanes = [int.from_bytes(st[8 * i : 8 * i + 8], "little")
                 for i in range(25)]
        lanes = _keccak_f_host(lanes)
        out = bytearray(200)
        for i, v in enumerate(lanes):
            out[8 * i : 8 * i + 8] = v.to_bytes(8, "little")
        return out

    def _run_f(self) -> None:
        self.state[self.pos] ^= self.pos_begin
        self.state[self.pos + 1] ^= 0x04
        self.state[STROBE_R + 1] ^= 0x80
        self.state = self._permute(self.state)
        self.pos = 0
        self.pos_begin = 0

    def _absorb(self, data: bytes) -> None:
        for b in data:
            self.state[self.pos] ^= b
            self.pos += 1
            if self.pos == STROBE_R:
                self._run_f()

    def _squeeze(self, n: int) -> bytes:
        out = bytearray()
        for _ in range(n):
            out.append(self.state[self.pos])
            self.state[self.pos] = 0
            self.pos += 1
            if self.pos == STROBE_R:
                self._run_f()
        return bytes(out)

    def _begin_op(self, flags: int, more: bool) -> None:
        if more:
            assert self.cur_flags == flags, "inconsistent continued op"
            return
        assert not (flags & FLAG_T), "transport ops unsupported"
        old_begin = self.pos_begin
        self.pos_begin = self.pos + 1
        self.cur_flags = flags
        self._absorb(bytes([old_begin, flags]))
        force_f = bool(flags & (FLAG_C | FLAG_K))
        if force_f and self.pos != 0:
            self._run_f()

    def meta_ad(self, data: bytes, more: bool) -> None:
        self._begin_op(FLAG_M | FLAG_A, more)
        self._absorb(data)

    def ad(self, data: bytes, more: bool) -> None:
        self._begin_op(FLAG_A, more)
        self._absorb(data)

    def prf(self, n: int, more: bool = False) -> bytes:
        self._begin_op(FLAG_I | FLAG_A | FLAG_C, more)
        return self._squeeze(n)


class Transcript:
    """merlin::Transcript semantics."""

    def __init__(self, protocol_label: bytes):
        self.strobe = Strobe128(b"Merlin v1.0")
        self.append_message(b"dom-sep", protocol_label)

    def append_message(self, label: bytes, message: bytes) -> None:
        self.strobe.meta_ad(
            label + len(message).to_bytes(4, "little"), False)
        self.strobe.ad(message, False)

    def append_u64(self, label: bytes, x: int) -> None:
        self.append_message(label, x.to_bytes(8, "little"))

    def challenge_bytes(self, label: bytes, n: int) -> bytes:
        self.strobe.meta_ad(label + n.to_bytes(4, "little"), False)
        return self.strobe.prf(n)
