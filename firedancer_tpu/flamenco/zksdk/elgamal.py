"""Twisted ElGamal over ristretto255 (the zk-sdk's encryption scheme).

Capability parity target: the reference zksdk's ElGamal layer (Agave
zk-sdk/src/encryption) — no code shared; the scheme is implemented from
its published definition:

    keypair:     secret s (scalar);  pubkey P = s^{-1} * H
    ciphertext:  commitment C = m*G + r*H   (Pedersen commitment)
                 handle     D = r*P
    decryption:  m*G = C - s*D

G is the ristretto basepoint; H is the Pedersen base (hash-to-ristretto
of sha3-512(G), derived in ops/ristretto + verified against the
protocol constant).  Wire format: ciphertext = C || D (32+32 bytes).
"""

from __future__ import annotations

import hashlib

from firedancer_tpu.ops import ristretto as ri
from firedancer_tpu.ops.ref.ed25519_ref import L, point_add, point_mul

G = ri.BASE_POINT
H = ri.from_uniform_bytes(hashlib.sha3_512(ri.BASE_BYTES).digest())
H_BYTES = ri.encode(H)
assert H_BYTES.hex() == (
    "8c9240b456a9e6dc65c377a1048d745f94a08cdb7f44cbcd7b46f34048871134"
)


def keygen(seed: bytes) -> tuple[int, bytes]:
    """-> (secret scalar, compressed pubkey P = s^-1 H)."""
    s = int.from_bytes(hashlib.sha512(b"zk-elgamal:" + seed).digest(),
                       "little") % L
    if s == 0:
        s = 1
    pub = point_mul(pow(s, L - 2, L), H)
    return s, ri.encode(pub)


def encrypt(pubkey: bytes, amount: int, r: int) -> bytes:
    """-> 64-byte ciphertext C || D for amount under randomness r."""
    p = ri.decode(pubkey)
    c = point_add(point_mul(amount % L, G), point_mul(r % L, H))
    d = point_mul(r % L, p)
    return ri.encode(c) + ri.encode(d)


def commit(amount: int, r: int) -> bytes:
    """Plain Pedersen commitment m*G + r*H."""
    return ri.encode(point_add(point_mul(amount % L, G),
                               point_mul(r % L, H)))


def decrypt_to_point(secret: int, ciphertext: bytes):
    """-> the group element m*G (amount recovery needs a dlog lookup)."""
    c = ri.decode(ciphertext[:32])
    d = ri.decode(ciphertext[32:])
    return point_add(c, point_mul((L - secret) % L, d))
