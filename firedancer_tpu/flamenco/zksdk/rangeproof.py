"""Bulletproof batched range proofs (the zk-sdk's u64/u128/u256 family).

Capability parity target: the reference's
zksdk/rangeproofs/fd_rangeproofs.c (itself following Agave
zk-sdk/src/range_proof, the dalek bulletproofs protocol).  No code
shared: the verifier below implements the same single-MSM verification
equation (res == -A) and transcript protocol, re-derived from the
protocol; the prover is the standard aggregated bulletproof prover
(needed for tests and the client side — Agave's zk-sdk ships one too).

Generators: the dalek `GeneratorsChain` derivation — shake256 of
"GeneratorsChain" || label, 64 XOF bytes per point through the
ristretto one-way map; our chain reproduces the reference's table
(G[0] = e4d54971..., H[0] = 5a85e848...) exactly.

Wire format (all 32-byte LE):
    range_proof: A S T_1 T_2 | t_x t_x_blinding e_blinding
    ipp:         (L_i R_i) * logn | a b
"""

from __future__ import annotations

import hashlib

from firedancer_tpu.flamenco.zksdk.elgamal import G, H
from firedancer_tpu.flamenco.zksdk.merlin import Transcript
from firedancer_tpu.flamenco.zksdk.sigma import (
    ZkError,
    challenge_scalar,
    decompress,
    msm,
    scalar_validate,
    validate_and_append_point,
)
from firedancer_tpu.ops import ristretto as ri
from firedancer_tpu.ops.ref.ed25519_ref import (
    IDENT,
    L,
    point_add,
    point_mul,
    point_neg,
)

MAX_COMMITMENTS = 8
MAX_NM = 256


def _gen_chain(label: bytes, n: int) -> list:
    sh = hashlib.shake_256()
    sh.update(b"GeneratorsChain" + label)
    stream = sh.digest(64 * n)
    return [ri.from_uniform_bytes(stream[64 * i : 64 * (i + 1)])
            for i in range(n)]


_GENS: dict[str, list] = {}


def generators(n: int) -> tuple[list, list]:
    if not _GENS:
        _GENS["G"] = _gen_chain(b"G", MAX_NM)
        _GENS["H"] = _gen_chain(b"H", MAX_NM)
    return _GENS["G"][:n], _GENS["H"][:n]


def _delta(nm: int, y: int, z: int, bit_lengths: list[int]) -> int:
    """(z - z^2) * sum_{j<nm} y^j - sum_i z^{3+i} (2^{b_i} - 1)."""
    sum_y = 0
    yj = 1
    for _ in range(nm):
        sum_y = (sum_y + yj) % L
        yj = yj * y % L
    zz = z * z % L
    d = (z - zz) % L * sum_y % L
    exp_z = zz
    for b in bit_lengths:
        exp_z = exp_z * z % L
        d = (d - exp_z * ((1 << b) - 1)) % L
    return d


def _validate_bits(b: int) -> None:
    if b not in (1, 2, 4, 8, 16, 32, 64, 128):
        raise ZkError(f"bad bit length {b}")


def verify_range_proof(
    commitments: list[bytes],
    bit_lengths: list[int],
    proof: bytes,
    transcript: Transcript,
    logn: int,
) -> None:
    """The single-MSM batched verification (fd_rangeproofs_verify)."""
    n = 1 << logn
    if len(proof) != 224 + 64 * logn + 64:
        raise ZkError("bad range proof size")
    for b in bit_lengths:
        _validate_bits(b)
    nm = sum(bit_lengths)
    if nm != n:
        raise ZkError("bit lengths do not sum to the proof size")

    a_b, s_b, t1_b, t2_b = (proof[:32], proof[32:64], proof[64:96],
                            proof[96:128])
    tx = scalar_validate(proof[128:160])
    txb = scalar_validate(proof[160:192])
    eb = scalar_validate(proof[192:224])
    lr = proof[224 : 224 + 64 * logn]
    l_b = [lr[64 * i : 64 * i + 32] for i in range(logn)]
    r_b = [lr[64 * i + 32 : 64 * i + 64] for i in range(logn)]
    a_sc = scalar_validate(proof[224 + 64 * logn : 256 + 64 * logn])
    b_sc = scalar_validate(proof[256 + 64 * logn : 288 + 64 * logn])

    a_pt = decompress(a_b)
    s_pt = decompress(s_b)
    t1 = decompress(t1_b)
    t2 = decompress(t2_b)
    comm_pts = [decompress(cb) for cb in commitments]
    l_pts = [decompress(b) for b in l_b]
    r_pts = [decompress(b) for b in r_b]
    gens_g, gens_h = generators(n)

    t = transcript
    t.append_message(b"dom-sep", b"range-proof")
    t.append_u64(b"n", nm)
    validate_and_append_point(t, b"A", a_b)
    validate_and_append_point(t, b"S", s_b)
    y = challenge_scalar(t, b"y")
    z = challenge_scalar(t, b"z")
    validate_and_append_point(t, b"T_1", t1_b)
    validate_and_append_point(t, b"T_2", t2_b)
    x = challenge_scalar(t, b"x")
    t.append_message(b"t_x", proof[128:160])
    t.append_message(b"t_x_blinding", proof[160:192])
    t.append_message(b"e_blinding", proof[192:224])
    w = challenge_scalar(t, b"w")
    c = challenge_scalar(t, b"c")
    t.append_message(b"dom-sep", b"inner-product")
    t.append_u64(b"n", nm)
    u = []
    for i in range(logn):
        validate_and_append_point(t, b"L", l_b[i])
        validate_and_append_point(t, b"R", r_b[i])
        u.append(challenge_scalar(t, b"u"))

    y_inv = pow(y, L - 2, L)
    u_inv = [pow(ui, L - 2, L) for ui in u]

    # s_i: s[0] = prod(u_inv); s[i] = s[i - 2^k] * u[logn-1-k]^2
    s = [0] * n
    s[0] = 1
    for ui in u_inv:
        s[0] = s[0] * ui % L
    u_sq = [ui * ui % L for ui in u]
    for k in range(logn):
        powk = 1 << k
        for j in range(powk):
            s[powk + j] = s[j] * u_sq[logn - 1 - k] % L

    zz = z * z % L
    scalars: list[int] = []
    points: list = []
    # G: w (t_x - a b) + c (delta - t_x)
    scalars.append((w * (tx - a_sc * b_sc) + c * (
        _delta(nm, y, z, bit_lengths) - tx)) % L)
    points.append(G)
    # H: -(eb + c txb)
    scalars.append((L - (eb + c * txb) % L) % L)
    points.append(H)
    # S, T_1, T_2
    scalars += [x, c * x % L, c * x % L * x % L]
    points += [s_pt, t1, t2]
    # commitments: c z^2, c z^3, ...
    cz = zz * c % L
    for pt in comm_pts:
        scalars.append(cz)
        points.append(pt)
        cz = cz * z % L
    # L_i: u_i^2;  R_i: u_i^-2
    for i in range(logn):
        scalars.append(u_sq[i])
        points.append(l_pts[i])
    for i in range(logn):
        scalars.append(u_inv[i] * u_inv[i] % L)
        points.append(r_pts[i])
    # generators_H[i]: (z^{2+m} 2^j - b s_{n-1-i}) * y^-i + z
    # (position i sits at bit j of commitment m)
    exp_z = zz
    z_and_2 = exp_z
    j = 0
    m = 0
    yi = 1
    for i in range(n):
        if j == bit_lengths[m]:
            j = 0
            m += 1
            exp_z = exp_z * z % L
            z_and_2 = exp_z
        if j != 0:
            z_and_2 = z_and_2 * 2 % L
        scalars.append(
            (((z_and_2 - b_sc * s[n - 1 - i]) % L) * yi + z) % L
        )
        points.append(gens_h[i])
        yi = yi * y_inv % L
        j += 1
    # generators_G: -a s_i - z
    for i in range(n):
        scalars.append((L - (a_sc * s[i] + z) % L) % L)
        points.append(gens_g[i])

    res = msm(scalars, points)
    if not ri.eq(res, point_neg(a_pt)):
        raise ZkError("range proof verification failed")


# -- prover (client side / tests) ---------------------------------------------


def _rand_scalar(seed: bytes, tag: bytes) -> int:
    return int.from_bytes(
        hashlib.sha512(b"rp:" + tag + b":" + seed).digest(), "little") % L


def prove_range(
    amounts: list[int],
    blindings: list[int],
    bit_lengths: list[int],
    transcript: Transcript,
    seed: bytes,
) -> bytes:
    """Aggregated bulletproof over commitments C_j = v_j G + gamma_j H."""
    nm = sum(bit_lengths)
    logn = nm.bit_length() - 1
    if 1 << logn != nm:
        raise ZkError("total bits must be a power of two")
    n = nm
    gens_g, gens_h = generators(n)

    # bit vectors
    a_l: list[int] = []
    for v, b in zip(amounts, bit_lengths):
        if not 0 <= v < (1 << b):
            raise ZkError("amount out of range")
        a_l += [(v >> k) & 1 for k in range(b)]
    a_r = [(x - 1) % L for x in a_l]

    alpha = _rand_scalar(seed, b"alpha")
    rho = _rand_scalar(seed, b"rho")
    s_l = [_rand_scalar(seed, b"sl%d" % i) for i in range(n)]
    s_r = [_rand_scalar(seed, b"sr%d" % i) for i in range(n)]

    def vec_commit(blind, lvec, rvec):
        return msm([blind] + lvec + rvec, [H] + gens_g + gens_h)

    a_pt = vec_commit(alpha, a_l, a_r)
    s_pt = vec_commit(rho, s_l, s_r)
    a_b, s_b = ri.encode(a_pt), ri.encode(s_pt)

    t = transcript
    t.append_message(b"dom-sep", b"range-proof")
    t.append_u64(b"n", nm)
    validate_and_append_point(t, b"A", a_b)
    validate_and_append_point(t, b"S", s_b)
    y = challenge_scalar(t, b"y")
    z = challenge_scalar(t, b"z")
    zz = z * z % L

    # l(X) = (a_L - z) + s_L X ; r(X) = y^i (a_R + z + s_R X) + zeta_i
    # zeta_i = z^{2+j} 2^k at position i = (commitment j, bit k)
    zeta = []
    exp_z = zz
    for j, b in enumerate(bit_lengths):
        for k in range(b):
            zeta.append(exp_z * pow(2, k, L) % L)
        exp_z = exp_z * z % L
    yv = [pow(y, i, L) for i in range(n)]
    l0 = [(a_l[i] - z) % L for i in range(n)]
    l1 = s_l
    r0 = [(yv[i] * ((a_r[i] + z) % L) + zeta[i]) % L for i in range(n)]
    r1 = [yv[i] * s_r[i] % L for i in range(n)]

    t0 = sum(l0[i] * r0[i] for i in range(n)) % L
    t1_sc = (sum(l0[i] * r1[i] for i in range(n))
             + sum(l1[i] * r0[i] for i in range(n))) % L
    t2_sc = sum(l1[i] * r1[i] for i in range(n)) % L

    tau1 = _rand_scalar(seed, b"tau1")
    tau2 = _rand_scalar(seed, b"tau2")
    t1_pt = point_add(point_mul(t1_sc, G), point_mul(tau1, H))
    t2_pt = point_add(point_mul(t2_sc, G), point_mul(tau2, H))
    t1_b, t2_b = ri.encode(t1_pt), ri.encode(t2_pt)
    validate_and_append_point(t, b"T_1", t1_b)
    validate_and_append_point(t, b"T_2", t2_b)
    x = challenge_scalar(t, b"x")

    l_vec = [(l0[i] + l1[i] * x) % L for i in range(n)]
    r_vec = [(r0[i] + r1[i] * x) % L for i in range(n)]
    t_x = (t0 + t1_sc * x + t2_sc * x * x) % L
    tau_x = (tau2 * x * x + tau1 * x) % L
    exp_z = zz
    for gamma in blindings:
        tau_x = (tau_x + exp_z * gamma) % L
        exp_z = exp_z * z % L
    mu = (alpha + rho * x) % L

    t.append_message(b"t_x", t_x.to_bytes(32, "little"))
    t.append_message(b"t_x_blinding", tau_x.to_bytes(32, "little"))
    t.append_message(b"e_blinding", mu.to_bytes(32, "little"))
    w = challenge_scalar(t, b"w")
    _c = challenge_scalar(t, b"c")  # verifier-side combiner

    # inner-product argument over G_i and H'_i = y^-i H_i with Q = w G
    t.append_message(b"dom-sep", b"inner-product")
    t.append_u64(b"n", nm)
    y_inv = pow(y, L - 2, L)
    hp = [point_mul(pow(y_inv, i, L), gens_h[i]) for i in range(n)]
    gv = list(gens_g)
    av = list(l_vec)
    bv = list(r_vec)
    q = point_mul(w, G)
    lr_out = b""
    while len(av) > 1:
        half = len(av) // 2
        a_lo, a_hi = av[:half], av[half:]
        b_lo, b_hi = bv[:half], bv[half:]
        g_lo, g_hi = gv[:half], gv[half:]
        h_lo, h_hi = hp[:half], hp[half:]
        c_l = sum(a_lo[i] * b_hi[i] for i in range(half)) % L
        c_r = sum(a_hi[i] * b_lo[i] for i in range(half)) % L
        l_pt = point_add(msm(a_lo + b_hi, g_hi + h_lo),
                         point_mul(c_l, q))
        r_pt = point_add(msm(a_hi + b_lo, g_lo + h_hi),
                         point_mul(c_r, q))
        l_b, r_b = ri.encode(l_pt), ri.encode(r_pt)
        validate_and_append_point(t, b"L", l_b)
        validate_and_append_point(t, b"R", r_b)
        ui = challenge_scalar(t, b"u")
        ui_inv = pow(ui, L - 2, L)
        lr_out += l_b + r_b
        av = [(a_lo[i] * ui + a_hi[i] * ui_inv) % L for i in range(half)]
        bv = [(b_lo[i] * ui_inv + b_hi[i] * ui) % L for i in range(half)]
        gv = [point_add(point_mul(ui_inv, g_lo[i]), point_mul(ui, g_hi[i]))
              for i in range(half)]
        hp = [point_add(point_mul(ui, h_lo[i]), point_mul(ui_inv, h_hi[i]))
              for i in range(half)]

    return (
        a_b + s_b + t1_b + t2_b
        + t_x.to_bytes(32, "little")
        + tau_x.to_bytes(32, "little")
        + mu.to_bytes(32, "little")
        + lr_out
        + av[0].to_bytes(32, "little")
        + bv[0].to_bytes(32, "little")
    )
