"""zk-sdk: the ZK ElGamal proof program's cryptographic core.

Counterpart of /root/reference/src/flamenco/runtime/program/zksdk/
(merlin transcript, twisted-ElGamal encryption, sigma proofs, bulletproof
range proofs) — no code shared; each module cites the spec or protocol it
implements from.
"""
