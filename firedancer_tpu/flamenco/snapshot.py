"""Snapshots in the Solana container format: zstd tar + append-vecs.

Counterpart of /root/reference/src/flamenco/snapshot/ (fd_snapshot.h:
6-25 — load/restore of zstd-compressed tar streams of accounts +
manifest into funk).  The container layout matches the protocol's:

    version                      "1.2.0"
    snapshots/<slot>/<slot>      the bank manifest (bincode)
    accounts/<slot>.<id>         append-vec account storage files

Append-vec entries use the canonical storage record layout, 8-aligned:

    StoredMeta  { write_version u64 | data_len u64 | pubkey 32 }
    AccountMeta { lamports u64 | rent_epoch u64 | owner 32 | executable u8
                  | 7B pad }
    hash 32     (account hash; this build stores sha256 of the fields)
    data        data_len bytes, padded to 8

Two manifest dialects share the container:

  - this framework's reduced manifest (slot, bank_hash, parent hash,
    account count) via `snapshot_write`/`snapshot_load` — the compact
    internal checkpoint format; and
  - the REAL Agave bank manifest (flamenco/agave_manifest.py: versioned
    bank, stakes, epoch stakes, blockhash queue, accounts-db index) via
    `agave_snapshot_write`/`agave_snapshot_load` — genuine cluster
    snapshot ingestion, the fd_snapshot_restore.c capability.

Incremental snapshots diff a full base: only accounts whose bytes
changed (or appeared) since the base land in the archive, restored by
overlaying base then incremental — the reference's two-archive scheme.
"""

from __future__ import annotations

import gzip
import hashlib
import io
import struct
import tarfile
from dataclasses import dataclass

try:
    import zstandard
except ImportError:  # pragma: no cover - environment-dependent
    # zstd is the protocol's container compression, but hosts without the
    # binding still need working snapshots (the cluster harness's cold
    # boot): fall back to stdlib gzip on write and SNIFF the magic on
    # read, so archives stay interchangeable where both codecs exist.
    zstandard = None

from firedancer_tpu.flamenco import types as T
from firedancer_tpu.flamenco.executor import acct_decode, acct_encode
from firedancer_tpu.funk import Funk, make_funk

SNAPSHOT_VERSION = b"1.2.0"
_ZSTD_MAGIC = b"\x28\xb5\x2f\xfd"
_GZIP_MAGIC = b"\x1f\x8b"


def _compress(raw: bytes, level: int) -> bytes:
    if zstandard is not None:
        return zstandard.ZstdCompressor(level=level).compress(raw)
    # mtime=0: gzip.compress() would stamp wall-clock time into the
    # header, making same-seed archives byte-different (the determinism
    # contract the zstd path gives for free)
    buf = io.BytesIO()
    with gzip.GzipFile(fileobj=buf, mode="wb",
                       compresslevel=min(max(level, 1), 9), mtime=0) as gz:
        gz.write(raw)
    return buf.getvalue()


def _decompress(raw: bytes) -> bytes:
    if raw[:4] == _ZSTD_MAGIC:
        if zstandard is None:
            raise SnapshotError(
                "zstd-compressed snapshot but the zstandard module is "
                "unavailable on this host"
            )
        return zstandard.ZstdDecompressor().decompress(
            raw, max_output_size=1 << 31
        )
    if raw[:2] == _GZIP_MAGIC:
        return gzip.decompress(raw)
    raise SnapshotError("unrecognized snapshot compression magic")


def _stream_reader(f):
    """Streaming decompressor over an open binary file, codec-sniffed
    (the agave loader path; cluster snapshots are tens of GiB)."""
    head = f.read(4)
    f.seek(0)
    if head[:4] == _ZSTD_MAGIC:
        if zstandard is None:
            raise SnapshotError(
                "zstd-compressed snapshot but the zstandard module is "
                "unavailable on this host"
            )
        return zstandard.ZstdDecompressor().stream_reader(f)
    if head[:2] == _GZIP_MAGIC:
        return gzip.GzipFile(fileobj=f, mode="rb")
    raise SnapshotError("unrecognized snapshot compression magic")


class SnapshotError(RuntimeError):
    pass


@dataclass
class Manifest:
    slot: int
    bank_hash: bytes
    parent_hash: bytes
    account_cnt: int
    base_slot: int = 0  # nonzero marks an incremental snapshot
    deleted: list = None  # incremental: accounts removed since the base

    def __post_init__(self):
        if self.deleted is None:
            self.deleted = []


MANIFEST = T.StructCodec(
    Manifest,
    ("slot", T.U64),
    ("bank_hash", T.Hash32),
    ("parent_hash", T.Hash32),
    ("account_cnt", T.U64),
    ("base_slot", T.U64),
    ("deleted", T.Vec(T.Pubkey, max_len=1 << 24)),
)

_STORED_META = struct.Struct("<QQ32s")
_ACCT_META = struct.Struct("<QQ32sB7x")


def _entry_encode(pubkey: bytes, val: bytes, write_version: int) -> bytes:
    lamports, owner, executable, data = acct_decode(val)
    h = hashlib.sha256(
        pubkey + lamports.to_bytes(8, "little") + owner
        + bytes([executable]) + data
    ).digest()
    out = _STORED_META.pack(write_version, len(data), pubkey)
    out += _ACCT_META.pack(lamports, 0, owner, 1 if executable else 0)
    out += h
    out += data
    out += bytes((-len(out)) % 8)
    return out


def _entries_decode(buf: bytes):
    """Yield (pubkey, value bytes) from an append-vec blob."""
    off = 0
    n = len(buf)
    while off + _STORED_META.size + _ACCT_META.size + 32 <= n:
        wv, data_len, pubkey = _STORED_META.unpack_from(buf, off)
        off += _STORED_META.size
        lamports, _rent, owner, execb = _ACCT_META.unpack_from(buf, off)
        off += _ACCT_META.size
        h = buf[off : off + 32]
        off += 32
        if off + data_len > n:
            raise SnapshotError("append-vec entry data past end")
        data = bytes(buf[off : off + data_len])
        off += data_len
        off += (-off) % 8
        want = hashlib.sha256(
            pubkey + lamports.to_bytes(8, "little") + owner
            + bytes([execb & 1]) + data
        ).digest()
        if want != h:
            raise SnapshotError("account hash mismatch in append-vec")
        yield pubkey, acct_encode(lamports, owner, bool(execb & 1), data)


def _root_accounts(funk: Funk) -> dict[bytes, bytes]:
    """Every live record at the funk root (published state)."""
    out = {}
    for key in funk.rec_keys(None):
        val = funk.rec_query(None, key)
        if val is not None:
            out[key] = val
    return out


def snapshot_write(
    funk: Funk,
    path: str,
    *,
    slot: int,
    bank_hash: bytes = b"\x00" * 32,
    parent_hash: bytes = b"\x00" * 32,
    base: dict[bytes, bytes] | None = None,
    base_slot: int = 0,
    level: int = 3,
) -> int:
    """Write the funk root into a snapshot archive; returns the account
    count written.  With `base` (pubkey -> value from a full snapshot),
    writes an incremental: only new/changed accounts."""
    accounts = _root_accounts(funk)
    deleted: list[bytes] = []
    if base is not None:
        deleted = sorted(k for k in base if k not in accounts)
        accounts = {
            k: v for k, v in accounts.items() if base.get(k) != v
        }
    blob = bytearray()
    for i, (k, v) in enumerate(sorted(accounts.items())):
        blob += _entry_encode(k, v, write_version=i)
    man = Manifest(slot, bank_hash, parent_hash, len(accounts),
                   base_slot=base_slot, deleted=deleted)

    tar_buf = io.BytesIO()
    with tarfile.open(fileobj=tar_buf, mode="w") as tar:
        def add(name: str, payload: bytes):
            info = tarfile.TarInfo(name)
            info.size = len(payload)
            tar.addfile(info, io.BytesIO(payload))

        add("version", SNAPSHOT_VERSION)
        add(f"snapshots/{slot}/{slot}", MANIFEST.encode(man))
        add(f"accounts/{slot}.0", bytes(blob))
    comp = _compress(tar_buf.getvalue(), level)
    with open(path, "wb") as f:
        f.write(comp)
    return len(accounts)


def snapshot_read(path: str) -> tuple[Manifest, dict[bytes, bytes]]:
    """-> (manifest, pubkey -> account value bytes)."""
    with open(path, "rb") as f:
        raw = _decompress(f.read())
    accounts: dict[bytes, bytes] = {}
    manifest = None
    version = None
    with tarfile.open(fileobj=io.BytesIO(raw), mode="r") as tar:
        for member in tar.getmembers():
            payload = tar.extractfile(member)
            if payload is None:
                continue
            body = payload.read()
            if member.name == "version":
                version = body
            elif member.name.startswith("snapshots/"):
                manifest = MANIFEST.loads(body)
            elif member.name.startswith("accounts/"):
                for pubkey, val in _entries_decode(body):
                    accounts[pubkey] = val
    if version != SNAPSHOT_VERSION:
        raise SnapshotError(f"unsupported snapshot version {version!r}")
    if manifest is None:
        raise SnapshotError("snapshot has no manifest")
    if manifest.account_cnt != len(accounts):
        raise SnapshotError(
            f"manifest count {manifest.account_cnt} != {len(accounts)}"
        )
    return manifest, accounts


def snapshot_load(
    path: str, funk: Funk | None = None,
    incremental_path: str | None = None,
) -> tuple[Funk, Manifest]:
    """Restore a full snapshot (+ optional incremental overlay) into a
    funk root; the blocking-loader API shape (fd_snapshot.h:6-25)."""
    manifest, accounts = snapshot_read(path)
    if manifest.base_slot:
        raise SnapshotError("full snapshot required (got an incremental)")
    if incremental_path is not None:
        inc_man, inc_accounts = snapshot_read(incremental_path)
        if inc_man.base_slot != manifest.slot:
            raise SnapshotError(
                f"incremental base {inc_man.base_slot} != full {manifest.slot}"
            )
        accounts.update(inc_accounts)
        for k in inc_man.deleted:  # removals since the base must not
            accounts.pop(k, None)  # resurrect on restore
        manifest = inc_man
    funk = funk or make_funk()
    for k, v in accounts.items():
        funk.rec_insert(None, k, v)
    return funk, manifest


# -- real Agave-format archives ----------------------------------------------


def agave_snapshot_write(
    path: str,
    manifest,
    vecs: dict[tuple[int, int], bytes],
    *,
    level: int = 3,
) -> None:
    """Write an Agave-format archive: the full bank manifest bincode +
    append-vec files laid out exactly as a cluster snapshot
    (`snapshots/<slot>/<slot>`, `accounts/<slot>.<id>`).  `manifest` is
    an agave_manifest.SolanaManifest whose accounts_db.storages index
    the `vecs` {(slot, id): appendvec bytes}."""
    from firedancer_tpu.flamenco.agave_manifest import manifest_encode

    tar_buf = io.BytesIO()
    with tarfile.open(fileobj=tar_buf, mode="w") as tar:
        def add(name: str, payload: bytes):
            info = tarfile.TarInfo(name)
            info.size = len(payload)
            tar.addfile(info, io.BytesIO(payload))

        add("version", SNAPSHOT_VERSION)
        slot = manifest.bank.slot
        add(f"snapshots/{slot}/{slot}", manifest_encode(manifest))
        for (vslot, vid), blob in sorted(vecs.items()):
            add(f"accounts/{vslot}.{vid}", blob)
    comp = _compress(tar_buf.getvalue(), level)
    with open(path, "wb") as f:
        f.write(comp)


def _is_bank_manifest_member(name: str) -> bool:
    """`snapshots/<slot>/<slot>` only — genuine archives also carry
    `snapshots/status_cache` (and possibly other metadata), which must
    not be fed to the bank-manifest decoder."""
    parts = name.split("/")
    return (
        len(parts) == 3
        and parts[0] == "snapshots"
        and parts[1].isdigit()
        and parts[2] == parts[1]
    )


def agave_snapshot_load(
    path: str, funk: Funk | None = None,
) -> tuple[Funk, "object", dict]:
    """Boot from a REAL Agave-format snapshot archive: decode the full
    bank manifest, then restore every append-vec the accounts-db index
    names into the funk root (newest slot wins a pubkey; zero-lamport
    stores tombstone).  Returns (funk, SolanaManifest, restore summary)
    — the capability fd_snapshot_restore.c provides the reference.

    The archive is processed as a STREAM (zstd stream_reader + pipe-mode
    tar): cluster snapshots decompress to tens of GiB, so nothing holds
    the whole image in memory — account vecs spill to a temp dir one
    member at a time and are consumed after the manifest arrives."""
    import os
    import shutil
    import tempfile

    from firedancer_tpu.flamenco.agave_manifest import (
        manifest_decode,
        restore_manifest,
    )

    manifest = None
    spill = tempfile.mkdtemp(prefix="fdtpu_snapload_")
    try:
        with open(path, "rb") as f, _stream_reader(f) as zr, \
                tarfile.open(fileobj=zr, mode="r|") as tar:
            for member in tar:
                payload = tar.extractfile(member)
                if payload is None:
                    continue
                if _is_bank_manifest_member(member.name):
                    manifest = manifest_decode(payload.read())
                elif member.name.startswith("accounts/"):
                    stem = member.name.rsplit("/", 1)[-1]
                    try:
                        vslot, vid = (int(x) for x in stem.split(".", 1))
                    except ValueError:
                        raise SnapshotError(
                            f"bad accounts member name {member.name!r}"
                        )
                    with open(os.path.join(spill, f"{vslot}.{vid}"),
                              "wb") as out:
                        shutil.copyfileobj(payload, out)
        if manifest is None:
            raise SnapshotError("archive has no bank manifest")

        def open_vec(slot: int, vid: int) -> bytes:
            try:
                with open(os.path.join(spill, f"{slot}.{vid}"), "rb") as f:
                    return f.read()
            except FileNotFoundError:
                raise SnapshotError(
                    f"manifest names missing vec {slot}.{vid}"
                )

        funk = funk or make_funk()
        summary = restore_manifest(funk, manifest, open_vec)
        return funk, manifest, summary
    finally:
        shutil.rmtree(spill, ignore_errors=True)
