"""solcap: execution-effect capture for differential debugging.

Counterpart of /root/reference/src/flamenco/capture/ (fd_solcap_writer.h:
8-11 and fd_solcap_diff.c): record per-slot execution effects — bank
hash inputs and every modified account's post-state — so two runtimes
replaying the same block can be diffed account-by-account instead of
staring at mismatched bank hashes.

Format: length-framed records in one capture file (the reference uses
protobuf; this build frames its bincode types the same way):

    "SOLCAP1\\0" file magic, then per record: u32 LE length | record

Record = SlotCap { slot, bank_hash, accounts_delta_hash, signature_cnt,
fees, accounts: Vec<AccountCap { pubkey, lamports, owner, executable,
data_hash (sha256; data itself stays out of the capture) } > }.

`diff` compares two captures slot-by-slot and reports the first
divergence with the exact accounts that differ — the fd_solcap_diff
workflow.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

from firedancer_tpu.flamenco import types as T

MAGIC = b"SOLCAP1\x00"


@dataclass
class AccountCap:
    pubkey: bytes
    lamports: int
    owner: bytes
    executable: bool
    data_hash: bytes


ACCOUNT_CAP = T.StructCodec(
    AccountCap,
    ("pubkey", T.Pubkey),
    ("lamports", T.U64),
    ("owner", T.Pubkey),
    ("executable", T.Bool),
    ("data_hash", T.Hash32),
)


@dataclass
class SlotCap:
    slot: int
    bank_hash: bytes
    accounts_delta_hash: bytes
    signature_cnt: int
    fees: int
    accounts: list = field(default_factory=list)


SLOT_CAP = T.StructCodec(
    SlotCap,
    ("slot", T.U64),
    ("bank_hash", T.Hash32),
    ("accounts_delta_hash", T.Hash32),
    ("signature_cnt", T.U64),
    ("fees", T.U64),
    ("accounts", T.Vec(ACCOUNT_CAP, max_len=1 << 20)),
)


def account_cap(pubkey: bytes, value: bytes | None) -> AccountCap:
    from firedancer_tpu.flamenco.executor import acct_decode

    lamports, owner, executable, data = acct_decode(value)
    return AccountCap(
        pubkey, lamports, owner, executable,
        hashlib.sha256(data).digest(),
    )


class SolcapWriter:
    """Streamed writer; hook it into execute_block's caller: after each
    block, `write_slot` with the BlockResult and the touched accounts."""

    def __init__(self, fileobj):
        self._f = fileobj
        self._f.write(MAGIC)

    def write_slot(self, cap: SlotCap) -> None:
        rec = SLOT_CAP.encode(cap)
        self._f.write(len(rec).to_bytes(4, "little") + rec)

    def capture_block(self, funk, result, payloads_desc=None) -> SlotCap:
        """Build + write a SlotCap from a runtime BlockResult: every
        account any txn touched, post-state as seen from the fork."""
        from firedancer_tpu.protocol import txn as ft

        touched: set[bytes] = set()
        if payloads_desc:
            for payload, desc in payloads_desc:
                touched.update(desc.acct_addrs(payload))
        _ = ft

        def query(key):
            from firedancer_tpu.funk import FunkError

            # a published block's xid is gone (merged into root): the
            # post-state lives at the root then
            try:
                return funk.rec_query(result.xid, key)
            except FunkError:
                return funk.rec_query(None, key)

        cap = SlotCap(
            slot=result.slot,
            bank_hash=result.bank_hash,
            accounts_delta_hash=hashlib.sha256(
                result.accounts_delta.tobytes()
            ).digest(),
            signature_cnt=result.signature_cnt,
            fees=result.fees,
            accounts=[
                account_cap(a, query(a)) for a in sorted(touched)
            ],
        )
        self.write_slot(cap)
        return cap


def read_capture(fileobj) -> list[SlotCap]:
    if fileobj.read(len(MAGIC)) != MAGIC:
        raise ValueError("not a solcap file")
    out = []
    while True:
        hdr = fileobj.read(4)
        if not hdr:
            break
        ln = int.from_bytes(hdr, "little")
        out.append(SLOT_CAP.loads(fileobj.read(ln)))
    return out


def diff(a: list[SlotCap], b: list[SlotCap]) -> list[str]:
    """First-divergence report between two captures (fd_solcap_diff's
    output shape); empty = identical."""
    report: list[str] = []
    by_slot_b = {c.slot: c for c in b}
    for ca in a:
        cb = by_slot_b.get(ca.slot)
        if cb is None:
            report.append(f"slot {ca.slot}: missing from capture B")
            break  # first divergent slot only
        if ca.bank_hash != cb.bank_hash:
            report.append(
                f"slot {ca.slot}: bank hash {ca.bank_hash.hex()[:16]} != "
                f"{cb.bank_hash.hex()[:16]}"
            )
        if ca.accounts_delta_hash != cb.accounts_delta_hash:
            report.append(f"slot {ca.slot}: accounts delta hash differs")
        accts_b = {x.pubkey: x for x in cb.accounts}
        for x in ca.accounts:
            y = accts_b.get(x.pubkey)
            if y is None:
                report.append(
                    f"slot {ca.slot}: account {x.pubkey.hex()[:16]} only in A"
                )
            elif (x.lamports, x.owner, x.executable, x.data_hash) != (
                y.lamports, y.owner, y.executable, y.data_hash
            ):
                report.append(
                    f"slot {ca.slot}: account {x.pubkey.hex()[:16]} differs "
                    f"(lamports {x.lamports} vs {y.lamports})"
                )
        if report:
            break  # first divergent slot is the actionable one
    return report
