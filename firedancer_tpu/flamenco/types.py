"""Solana wire types: bincode codec combinators + core types.

Counterpart of /root/reference/src/flamenco/types/ — there, ~42k lines
of *generated* bincode (de)serializers (fd_types.c from fd_types.json
via gen_stubs.py).  Here the same capability is a combinator library: a
`Codec` composes from primitives exactly as bincode does (little-endian
fixed-width ints, u64 length-prefixed vecs, 1-byte Option tags, enums
as u32 tag + payload), so each type is declared in a few lines and the
encoder/decoder pair can never disagree.

Concrete types provided: the sysvars (Clock, Rent, EpochSchedule,
SlotHash(es)), the vote instruction (Vote / VoteInstruction), and
gossip's LegacyContactInfo with SocketAddr — the types the gossip,
repair and runtime layers exchange on the wire.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, fields, is_dataclass


class CodecError(ValueError):
    pass


class Codec:
    def encode(self, v) -> bytes:
        raise NotImplementedError

    def decode(self, buf: bytes, off: int = 0):
        """-> (value, new_off)"""
        raise NotImplementedError

    def loads(self, buf: bytes):
        v, off = self.decode(buf, 0)
        if off != len(buf):
            raise CodecError(f"{len(buf) - off} trailing bytes")
        return v


class _Int(Codec):
    def __init__(self, size: int, signed: bool = False):
        self.size, self.signed = size, signed

    def encode(self, v) -> bytes:
        return int(v).to_bytes(self.size, "little", signed=self.signed)

    def decode(self, buf, off=0):
        if off + self.size > len(buf):
            raise CodecError("short int")
        return (
            int.from_bytes(buf[off : off + self.size], "little",
                           signed=self.signed),
            off + self.size,
        )


U8, U16, U32, U64 = _Int(1), _Int(2), _Int(4), _Int(8)
U128 = _Int(16)
I64 = _Int(8, signed=True)


class _F64(Codec):
    def encode(self, v) -> bytes:
        return struct.pack("<d", float(v))

    def decode(self, buf, off=0):
        if off + 8 > len(buf):
            raise CodecError("short f64")
        return struct.unpack_from("<d", buf, off)[0], off + 8


F64 = _F64()


class _Bool(Codec):
    def encode(self, v) -> bytes:
        return b"\x01" if v else b"\x00"

    def decode(self, buf, off=0):
        if off >= len(buf):
            raise CodecError("short bool")
        if buf[off] > 1:
            raise CodecError(f"bad bool byte {buf[off]}")
        return buf[off] == 1, off + 1


Bool = _Bool()


class FixedBytes(Codec):
    def __init__(self, n: int):
        self.n = n

    def encode(self, v) -> bytes:
        if len(v) != self.n:
            raise CodecError(f"need {self.n} bytes, got {len(v)}")
        return bytes(v)

    def decode(self, buf, off=0):
        if off + self.n > len(buf):
            raise CodecError("short fixed bytes")
        return bytes(buf[off : off + self.n]), off + self.n


Pubkey = FixedBytes(32)
Hash32 = FixedBytes(32)
Signature = FixedBytes(64)


class Vec(Codec):
    """bincode Vec<T>: u64 count + elements."""

    def __init__(self, inner: Codec, max_len: int = 1 << 20):
        self.inner, self.max_len = inner, max_len

    def encode(self, v) -> bytes:
        out = U64.encode(len(v))
        for x in v:
            out += self.inner.encode(x)
        return out

    def decode(self, buf, off=0):
        n, off = U64.decode(buf, off)
        if n > self.max_len:
            raise CodecError(f"vec too long ({n})")
        out = []
        for _ in range(n):
            x, off = self.inner.decode(buf, off)
            out.append(x)
        return out, off


class VarBytes(Codec):
    """Vec<u8> without per-element dispatch."""

    def __init__(self, max_len: int = 1 << 20):
        self.max_len = max_len

    def encode(self, v) -> bytes:
        return U64.encode(len(v)) + bytes(v)

    def decode(self, buf, off=0):
        n, off = U64.decode(buf, off)
        if n > self.max_len or off + n > len(buf):
            raise CodecError("bad byte vec")
        return bytes(buf[off : off + n]), off + n


class String(Codec):
    def encode(self, v) -> bytes:
        raw = v.encode("utf-8")
        return U64.encode(len(raw)) + raw

    def decode(self, buf, off=0):
        raw, off = VarBytes().decode(buf, off)
        return raw.decode("utf-8"), off


class Option(Codec):
    def __init__(self, inner: Codec):
        self.inner = inner

    def encode(self, v) -> bytes:
        if v is None:
            return b"\x00"
        return b"\x01" + self.inner.encode(v)

    def decode(self, buf, off=0):
        if off >= len(buf):
            raise CodecError("short option")
        tag = buf[off]
        if tag == 0:
            return None, off + 1
        if tag != 1:
            raise CodecError(f"bad option tag {tag}")
        return self.inner.decode(buf, off + 1)


class StructCodec(Codec):
    """Binds a dataclass to an ordered (name, codec) field list."""

    def __init__(self, cls, *spec):
        self.cls, self.spec = cls, spec
        if is_dataclass(cls):
            names = [f.name for f in fields(cls)]
            assert [n for n, _ in spec] == names, (
                f"{cls.__name__} codec fields {names} != spec"
            )

    def encode(self, v) -> bytes:
        return b"".join(c.encode(getattr(v, n)) for n, c in self.spec)

    def decode(self, buf, off=0):
        kw = {}
        for n, c in self.spec:
            kw[n], off = c.decode(buf, off)
        return self.cls(**kw), off


class Enum(Codec):
    """bincode enum: u32 LE tag + variant payload."""

    def __init__(self, *variants):
        """variants: (tag, name, codec-or-None)"""
        self.by_tag = {t: (n, c) for t, n, c in variants}
        self.by_name = {n: (t, c) for t, n, c in variants}

    def encode(self, v) -> bytes:
        name, payload = v
        t, c = self.by_name[name]
        return U32.encode(t) + (c.encode(payload) if c else b"")

    def decode(self, buf, off=0):
        t, off = U32.decode(buf, off)
        if t not in self.by_tag:
            raise CodecError(f"unknown enum tag {t}")
        name, c = self.by_tag[t]
        if c is None:
            return (name, None), off
        payload, off = c.decode(buf, off)
        return (name, payload), off


# -- sysvars ------------------------------------------------------------------


@dataclass
class Clock:
    slot: int = 0
    epoch_start_timestamp: int = 0
    epoch: int = 0
    leader_schedule_epoch: int = 0
    unix_timestamp: int = 0


CLOCK = StructCodec(
    Clock,
    ("slot", U64),
    ("epoch_start_timestamp", I64),
    ("epoch", U64),
    ("leader_schedule_epoch", U64),
    ("unix_timestamp", I64),
)


@dataclass
class Rent:
    lamports_per_byte_year: int = 3480
    exemption_threshold: float = 2.0
    burn_percent: int = 50


RENT = StructCodec(
    Rent,
    ("lamports_per_byte_year", U64),
    ("exemption_threshold", F64),
    ("burn_percent", U8),
)


def rent_exempt_minimum(rent: Rent, data_len: int) -> int:
    """The balance making an account of `data_len` bytes rent-exempt
    (the 128-byte account-storage overhead included, the protocol's
    constant)."""
    return int(
        (data_len + 128) * rent.lamports_per_byte_year
        * rent.exemption_threshold
    )


@dataclass
class EpochSchedule:
    slots_per_epoch: int = 432_000
    leader_schedule_slot_offset: int = 432_000
    warmup: bool = False
    first_normal_epoch: int = 0
    first_normal_slot: int = 0


EPOCH_SCHEDULE = StructCodec(
    EpochSchedule,
    ("slots_per_epoch", U64),
    ("leader_schedule_slot_offset", U64),
    ("warmup", Bool),
    ("first_normal_epoch", U64),
    ("first_normal_slot", U64),
)


def epoch_of_slot(sched: EpochSchedule, slot: int) -> tuple[int, int]:
    """(epoch, slot_index) for a post-warmup schedule."""
    if slot < sched.first_normal_slot:
        raise CodecError("warmup epochs not modeled")
    rel = slot - sched.first_normal_slot
    return (
        sched.first_normal_epoch + rel // sched.slots_per_epoch,
        rel % sched.slots_per_epoch,
    )


@dataclass
class SlotHash:
    slot: int
    hash: bytes


SLOT_HASH = StructCodec(SlotHash, ("slot", U64), ("hash", Hash32))
SLOT_HASHES = Vec(SLOT_HASH, max_len=512)


# -- vote instruction ---------------------------------------------------------


@dataclass
class Vote:
    slots: list
    hash: bytes
    timestamp: int | None = None


VOTE = StructCodec(
    Vote,
    ("slots", Vec(U64, max_len=1 << 16)),
    ("hash", Hash32),
    ("timestamp", Option(I64)),
)

# VoteInstruction enum (the tags the reference's vote program handles;
# 2 = Vote is the one the leader pipeline sees constantly)
VOTE_INSTRUCTION = Enum(
    (2, "vote", VOTE),
)


# -- gossip: LegacyContactInfo ------------------------------------------------

# SocketAddr: enum { V4(u32 tag 0: [u8;4], u16 port), V6(tag 1: [u8;16],
# u16 port) } — ports in LE like every bincode int
@dataclass
class SockAddr:
    ip: bytes
    port: int


SOCKET_ADDR = Enum(
    (0, "v4", StructCodec(SockAddr, ("ip", FixedBytes(4)), ("port", U16))),
    (1, "v6", StructCodec(SockAddr, ("ip", FixedBytes(16)), ("port", U16))),
)


def sockaddr_v4(ip: str, port: int):
    return ("v4", SockAddr(bytes(int(x) for x in ip.split(".")), port))


@dataclass
class LegacyContactInfo:
    id: bytes
    gossip: tuple
    tvu: tuple
    tvu_forwards: tuple
    repair: tuple
    tpu: tuple
    tpu_forwards: tuple
    tpu_vote: tuple
    rpc: tuple
    rpc_pubsub: tuple
    serve_repair: tuple
    wallclock: int = 0
    shred_version: int = 0


LEGACY_CONTACT_INFO = StructCodec(
    LegacyContactInfo,
    ("id", Pubkey),
    ("gossip", SOCKET_ADDR),
    ("tvu", SOCKET_ADDR),
    ("tvu_forwards", SOCKET_ADDR),
    ("repair", SOCKET_ADDR),
    ("tpu", SOCKET_ADDR),
    ("tpu_forwards", SOCKET_ADDR),
    ("tpu_vote", SOCKET_ADDR),
    ("rpc", SOCKET_ADDR),
    ("rpc_pubsub", SOCKET_ADDR),
    ("serve_repair", SOCKET_ADDR),
    ("wallclock", U64),
    ("shred_version", U16),
)
