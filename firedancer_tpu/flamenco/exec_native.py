"""ctypes binding for the native executor fast lane (native/fd_exec_native.cpp).

The bank stage's per-microblock hot path: a drained burst of verified
frags goes through ONE fd_exec_batch call — payloads + packed descriptors
(the verify stage's trailer, fd_txn_parse's layout) + current funk values
in, record writes + per-txn (status, fee) out.  The FFI crossing
amortizes over the burst the same way stage.py's burst draining amortized
loop overhead (fdlint FD207 enforces that discipline).

Parity and fallback contract:

  - `eligible_packed` is the Executor's routing classifier: a txn whose
    every instruction is in the native subset (the full system surface
    including the durable-nonce family, stake ops, vote vote/
    vote_state_update/tower_sync) routes native; CPI, BPF, lookup
    tables and unsupported variants go through the Python lane
    byte-for-byte.
  - the C++ side may still PUNT any txn it is not sure about (old vote
    state versions, arithmetic Python's big ints would survive, bounds
    surprises); the batch stops before that txn mutates anything and the
    caller re-runs it in Python, then resubmits the remainder.
  - `FDTPU_NATIVE_EXEC=0` disables the lane; a missing toolchain degrades
    to the Python lane via NativeUnavailable (skip, never fail).
"""

from __future__ import annotations

import ctypes
import os
import struct

from firedancer_tpu.utils.nativebuild import NativeUnavailable, build_so
from firedancer_tpu.protocol.txn import (
    SYSTEM_PROGRAM,
    VOTE_PROGRAM,
    _DESC_HDR,
    _DESC_INSTR,
)

_SRC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
    "fd_exec_native.cpp",
)
_SO = os.path.join(os.path.dirname(_SRC), "fd_exec_native.so")

ENV_SWITCH = "FDTPU_NATIVE_EXEC"

_REQ_MAGIC = 0x42584446  # 'FDXB'
_REQ2_MAGIC = 0x32584446  # 'FDX2' (session + native gate)
_RESP_MAGIC = 0x52584446  # 'FDXR'

_U32 = struct.Struct("<I")
_TXN_HEAD = struct.Struct("<HHB")
_REC_HEAD = struct.Struct("<bQB")

_lib = None


def _load():
    global _lib
    if _lib is None:
        lib = ctypes.CDLL(build_so(_SRC, _SO))
        lib.fd_exec_batch.argtypes = [
            ctypes.c_char_p, ctypes.c_uint64, ctypes.c_char_p, ctypes.c_uint64,
        ]
        lib.fd_exec_batch.restype = ctypes.c_int64
        lib.fd_exec_session_new.restype = ctypes.c_void_p
        lib.fd_exec_session_delete.argtypes = [ctypes.c_void_p]
        lib.fd_exec_batch2.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
            ctypes.c_char_p, ctypes.c_uint64,
        ]
        lib.fd_exec_batch2.restype = ctypes.c_int64
        _lib = lib
    return _lib


class Session:
    """One slot's native execution session (native/fd_exec_native.cpp
    Session): the status-cache gate (valid blockhashes + landed
    (blockhash, signature) pairs) and the cross-microblock account-value
    overlay live on the C++ side, so the per-txn Python gate and the
    per-call funk value marshalling disappear from the bank hot path."""

    def __init__(self):
        self._lib = _load()
        self._h = self._lib.fd_exec_session_new()
        if not self._h:
            raise NativeUnavailable("fd_exec_session_new failed")

    def close(self) -> None:
        if self._h:
            self._lib.fd_exec_session_delete(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def enabled() -> bool:
    """The env switch: FDTPU_NATIVE_EXEC=0 forces the Python lane."""
    return os.environ.get(ENV_SWITCH, "1") != "0"


def available() -> bool:
    """enabled AND the .so loads (builds on demand; toolchain-less or
    .so-less hosts degrade gracefully to the Python lane)."""
    if not enabled():
        return False
    try:
        _load()
        return True
    except (NativeUnavailable, OSError, AttributeError):
        # AttributeError: a stale/foreign .so that CDLL loads but that
        # lacks fd_exec_batch must degrade, not kill the bank stage
        return False


# -- eligibility classifier ----------------------------------------------------

_HDR_SZ = _DESC_HDR.size  # 17
_INSTR_SZ = _DESC_INSTR.size  # 9

# VoteInstruction tags the native lane executes (Vote/VoteSwitch,
# UpdateVoteState(Switch), TowerSync(Switch))
NATIVE_VOTE_TAGS = frozenset((2, 6, 8, 9, 14, 15))
# the stake program address (flamenco/stake.py STAKE_PROGRAM)
_STAKE_PROGRAM = b"Stake11111" + bytes(22)


def eligible_packed(payload: bytes, desc_bytes: bytes) -> bool:
    """May this txn route native?  Works on the packed descriptor so the
    zero-copy bank path never unpacks a Txn object for native traffic.
    Conservative by design: the C++ side re-checks and punts."""
    if len(desc_bytes) < _HDR_SZ or desc_bytes[13] != 0:  # lut_cnt
        return False
    acct_cnt = desc_bytes[8]
    acct_off = desc_bytes[9] | (desc_bytes[10] << 8)
    o = _HDR_SZ
    for _ in range(desc_bytes[16]):  # instr_cnt
        prog, _acnt, dsz, _aoff, doff = _DESC_INSTR.unpack_from(desc_bytes, o)
        o += _INSTR_SZ
        if prog >= acct_cnt:
            return False
        pa = acct_off + 32 * prog
        pk = payload[pa : pa + 32]
        if pk == SYSTEM_PROGRAM or pk == _STAKE_PROGRAM:
            # the whole native surface, durable-nonce family included
            # (the session's in-line durable gate owns the stale-
            # blockhash decision); stake tags 0..4 execute, others no-op
            pass
        elif pk == VOTE_PROGRAM:
            if dsz >= 4:
                tag = int.from_bytes(payload[doff : doff + 4], "little")
                if tag not in NATIVE_VOTE_TAGS:
                    return False
            # dsz < 4: both lanes fail the txn with the same status
        else:
            return False  # BPF / other builtins / unknown programs
    return True


# -- batch runner --------------------------------------------------------------


class BatchContext:
    """One slot's native execution context: the request header (fee rate,
    clock, slot-hashes sysvar) prebuilt once, reused per microblock."""

    def __init__(
        self,
        *,
        lamports_per_sig: int,
        clock_slot: int | None = None,
        clock_epoch: int | None = None,
        slot_hashes: bytes | None = None,
        session: Session | None = None,
        recent_blockhash: bytes | None = None,
        rent: tuple[int, int, float] | None = None,
    ):
        self._lib = _load()
        self._session = session
        sh = bytes(slot_hashes or b"")
        rbh = bytes(recent_blockhash or b"")
        # (flag, lamports_per_byte_year, exemption_threshold); flag 2 =
        # the rent sysvar blob exists but does not decode — the C++ side
        # punts nonce partial withdraws instead of guessing a floor
        rent_flag, rent_lpby, rent_et = rent if rent is not None \
            else (1, 3480, 2.0)
        self._fixed = (
            struct.pack(
                "<QBQQB",
                lamports_per_sig,
                1 if clock_slot is not None else 0,
                clock_slot or 0,
                clock_epoch or 0,
                1 if sh else 0,
            )
            + _U32.pack(len(sh))
            + sh
            + struct.pack("<B32sBQd", 1 if rbh else 0, rbh,
                          rent_flag, rent_lpby, rent_et)
        )
        # request arena + response buffer, REUSED across microblocks
        # (ISSUE 11 bank-lane residual): the session path marshals with
        # pack_into/slice-assign into one bytearray instead of building
        # ~6 bytes objects per txn and joining per call — the ~5 us/txn
        # of Python allocation around fd_exec_batch2.  Lazily built:
        # only the session hot path uses them.
        self._arena: bytearray | None = None
        self._arena_view = None
        self._resp_cap = 1 << 16
        self._resp = None

    def _ensure_arena(self, need: int) -> None:
        if self._arena is None or need > len(self._arena):
            cap = 1 << 16 if self._arena is None else len(self._arena)
            while cap < need:
                cap *= 2
            self._arena_view = None  # drop the old from_buffer pin first
            self._arena = bytearray(cap)
            self._arena_view = (ctypes.c_char * cap).from_buffer(self._arena)
        if self._resp is None:
            self._resp = ctypes.create_string_buffer(self._resp_cap)

    def run(self, entries, *, gate=None, refresh=None) -> tuple[int, bool, list]:
        """One fd_exec_batch(2) call.  entries: [payload, desc_bytes,
        addrs, vals, ...] lists — only the first four fields are read
        here.  Returns (n_done, punted, [(status, fee, [(idx, value)])]).

        Session mode (constructed with one): vals entries may be None,
        meaning "the session already holds this account's current value"
        — only first-touch/dirtied values cross the FFI (Python-lane
        writes resync the same way: the dirty set forces the next touch
        to ship a fresh have=1 value).  `gate` arms the native
        status-cache gate: (valid_blockhashes | None = unchanged,
        seen_delta) where seen_delta is an iterable of 96-byte
        blockhash||signature entries landed OUTSIDE the session since
        the last call.  `refresh` (session mode) is an iterable of
        (key, value) records merged into the session overlay before any
        txn runs — the bank sweep's dirty-account resync, which has no
        per-txn have=1 slot to ride."""
        if self._session is not None:
            return self._run_session_arena(entries, gate, refresh)
        parts = [struct.pack("<II", _REQ_MAGIC, len(entries)), self._fixed]
        req_sz = 0
        for e in entries:
            payload, desc_bytes, _addrs, vals = e[0], e[1], e[2], e[3]
            parts.append(_TXN_HEAD.pack(len(payload), len(desc_bytes),
                                        len(vals)))
            parts.append(payload)
            parts.append(desc_bytes)
            for v in vals:
                v = v or b""
                parts.append(_U32.pack(len(v)))
                parts.append(v)
                req_sz += len(v)
            req_sz += len(payload) + 64
        req = b"".join(parts)
        cap = 4096 + 2 * req_sz
        while True:
            buf = ctypes.create_string_buffer(cap)
            rc = self._lib.fd_exec_batch(req, len(req), buf, cap)
            if rc == -2:
                # a CreateAccount/Allocate burst can outgrow the heuristic
                # capacity; the call did not commit (v1 is stateless, v2
                # commits only after serializing), so retry bigger
                cap *= 4
                if cap > 1 << 28:
                    raise NativeUnavailable("fd_exec_batch response > 256MB")
                continue
            if rc < 0:
                raise NativeUnavailable(f"fd_exec_batch rc={rc}")
            return self._parse(buf.raw[:rc])

    def _run_session_arena(self, entries, gate,
                           refresh=None) -> tuple[int, bool, list]:
        """Session-mode crossing through the preallocated request arena:
        one capacity pass (plain int sums), then pack_into/slice-assign
        into the reused bytearray — no per-txn bytes construction, no
        per-call join, no per-call response allocation."""
        fixed = self._fixed
        # -- capacity pass ----------------------------------------------------
        need = 8 + len(fixed) + 5 + 4 + 4  # headers + gate flag + counts
        if gate is not None:
            valid_bh, seen_delta = gate
            if valid_bh is not None:
                need += 32 * len(valid_bh)
            need += 96 * len(seen_delta)
        if refresh:
            for _k, v in refresh:
                need += 36 + len(v)
        for e in entries:
            need += _TXN_HEAD.size + len(e[0]) + len(e[1])
            for v in e[3]:
                need += 1 if v is None else 5 + len(v)
        self._ensure_arena(need)
        a = self._arena
        # -- serialize --------------------------------------------------------
        struct.pack_into("<II", a, 0, _REQ2_MAGIC, len(entries))
        o = 8
        a[o : o + len(fixed)] = fixed
        o += len(fixed)
        if gate is not None:
            valid_bh, seen_delta = gate
            if valid_bh is None:
                # gate on, valid set unchanged since last shipped
                # (flag 2): the session keeps its current set
                a[o] = 2
                struct.pack_into("<I", a, o + 1, 0)
                o += 5
            else:
                a[o] = 1
                struct.pack_into("<I", a, o + 1, len(valid_bh))
                o += 5
                for bh in valid_bh:
                    a[o : o + 32] = bh
                    o += 32
            struct.pack_into("<I", a, o, len(seen_delta))
            o += 4
            for s in seen_delta:
                a[o : o + 96] = s
                o += 96
        else:
            a[o] = 0
            struct.pack_into("<II", a, o + 1, 0, 0)
            o += 9
        # refresh records: session-overlay merges with no txn to ride
        # (the bank sweep's dirty-account resync); empty on the
        # execute_batch path, whose per-txn have=1 values carry resyncs
        struct.pack_into("<I", a, o, len(refresh) if refresh else 0)
        o += 4
        if refresh:
            for k, v in refresh:
                a[o : o + 32] = k
                struct.pack_into("<I", a, o + 32, len(v))
                o += 36
                a[o : o + len(v)] = v
                o += len(v)
        for e in entries:
            payload, desc_bytes, vals = e[0], e[1], e[3]
            _TXN_HEAD.pack_into(a, o, len(payload), len(desc_bytes),
                                len(vals))
            o += _TXN_HEAD.size
            a[o : o + len(payload)] = payload
            o += len(payload)
            a[o : o + len(desc_bytes)] = desc_bytes
            o += len(desc_bytes)
            for v in vals:
                if v is None:  # session-known: nothing crosses
                    a[o] = 0
                    o += 1
                else:
                    a[o] = 1
                    struct.pack_into("<I", a, o + 1, len(v))
                    o += 5
                    a[o : o + len(v)] = v
                    o += len(v)
        # -- the crossing (response buffer reused; grown on -2) ---------------
        while True:
            rc = self._lib.fd_exec_batch2(self._session._h, self._arena_view,
                                          o, self._resp, self._resp_cap)
            if rc == -2:
                self._resp_cap *= 4
                if self._resp_cap > 1 << 28:
                    raise NativeUnavailable("fd_exec_batch response > 256MB")
                self._resp = ctypes.create_string_buffer(self._resp_cap)
                continue
            if rc < 0:
                raise NativeUnavailable(f"fd_exec_batch rc={rc}")
            return self._parse(ctypes.string_at(self._resp, rc))

    @staticmethod
    def _parse(buf: bytes) -> tuple[int, bool, list]:
        magic, n_done = struct.unpack_from("<II", buf, 0)
        if magic != _RESP_MAGIC:
            raise NativeUnavailable("fd_exec_batch bad response magic")
        punted = buf[8] != 0
        o = 9
        out = []
        for _ in range(n_done):
            status, fee, n_w = _REC_HEAD.unpack_from(buf, o)
            o += _REC_HEAD.size
            writes = []
            for _ in range(n_w):
                idx = buf[o]
                (vlen,) = _U32.unpack_from(buf, o + 1)
                o += 5
                writes.append((idx, buf[o : o + vlen]))
                o += vlen
            out.append((status, fee, writes))
        return n_done, punted, out
