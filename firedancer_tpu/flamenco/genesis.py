"""Genesis: create/parse the cluster's slot-0 configuration.

Counterpart of /root/reference/src/flamenco/genesis/fd_genesis_create.c
(+ fd_genesis_cluster.h): the genesis blob seeds the accounts DB with
the faucet, validator identity/vote/stake accounts and fixes the
cluster constants (hashes-per-tick, ticks-per-slot, …).  Encoded with
the bincode combinators; `genesis_hash` (sha256 of the blob) is the
chain's root "blockhash" — PoH seeds from it and slot 0's bank hash
chains from it, exactly the bootstrap the reference's fddev `dev`
command performs (genesis + keys before the validator boots).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

from firedancer_tpu.flamenco import types as T
from firedancer_tpu.flamenco.executor import acct_decode, acct_encode
from firedancer_tpu.funk import Funk, make_funk


@dataclass
class GenesisAccount:
    pubkey: bytes
    lamports: int
    owner: bytes
    executable: bool
    data: bytes


GENESIS_ACCOUNT = T.StructCodec(
    GenesisAccount,
    ("pubkey", T.Pubkey),
    ("lamports", T.U64),
    ("owner", T.Pubkey),
    ("executable", T.Bool),
    ("data", T.VarBytes()),
)


@dataclass
class Genesis:
    creation_time: int = 0
    hashes_per_tick: int = 12_500
    ticks_per_slot: int = 64
    slots_per_epoch: int = 432_000
    faucet_pubkey: bytes = bytes(32)
    accounts: list = field(default_factory=list)


GENESIS = T.StructCodec(
    Genesis,
    ("creation_time", T.I64),
    ("hashes_per_tick", T.U64),
    ("ticks_per_slot", T.U64),
    ("slots_per_epoch", T.U64),
    ("faucet_pubkey", T.Pubkey),
    ("accounts", T.Vec(GENESIS_ACCOUNT, max_len=1 << 16)),
)


def genesis_create(
    *,
    faucet_pubkey: bytes,
    faucet_lamports: int = 500_000_000_000_000,
    validator_accounts: list[GenesisAccount] = (),
    creation_time: int = 0,
    hashes_per_tick: int = 12_500,
    ticks_per_slot: int = 64,
    slots_per_epoch: int = 432_000,
) -> bytes:
    g = Genesis(
        creation_time=creation_time,
        hashes_per_tick=hashes_per_tick,
        ticks_per_slot=ticks_per_slot,
        slots_per_epoch=slots_per_epoch,
        faucet_pubkey=faucet_pubkey,
        accounts=[
            GenesisAccount(faucet_pubkey, faucet_lamports, bytes(32),
                           False, b""),
            *validator_accounts,
        ],
    )
    return GENESIS.encode(g)


def genesis_parse(blob: bytes) -> Genesis:
    return GENESIS.loads(blob)


def genesis_hash(blob: bytes) -> bytes:
    return hashlib.sha256(blob).digest()


def genesis_boot(blob: bytes, funk: Funk | None = None) -> tuple[Funk, Genesis, bytes]:
    """Seed a funk root from genesis; -> (funk, genesis, genesis_hash).
    The boot path fddev takes before the first leader slot."""
    g = genesis_parse(blob)
    funk = funk or make_funk()
    for a in g.accounts:
        funk.rec_insert(
            None, a.pubkey,
            acct_encode(a.lamports, a.owner, a.executable, a.data),
        )
    return funk, g, genesis_hash(blob)
