"""The REAL vote program: VoteState machine + full instruction surface.

Capability parity target: /root/reference/src/flamenco/runtime/program/
fd_vote_program.c (2,958 lines — VoteState versions, lockout doubling,
authorized voter rotation with the prior-voters circular buffer,
commission updates, tower sync).  No code shared: state is the
agave_state.VoteState codec (the exact on-chain bincode real cluster
snapshots carry), and the rules below are implemented from the protocol
semantics, each function naming the behavior it mirrors.

Instruction set (bincode u32 enum tag — VoteInstruction):

    0  InitializeAccount { node, authorized_voter, authorized_withdrawer,
                           commission }
    1  Authorize(Pubkey, VoteAuthorize)
    2  Vote { slots: Vec<u64>, hash, timestamp: Option<i64> }
    3  Withdraw(lamports)
    4  UpdateValidatorIdentity
    5  UpdateCommission(u8)
    6  VoteSwitch(Vote, Hash)           (proof hash unchecked, as Agave)
    7  AuthorizeChecked(VoteAuthorize)
    8  UpdateVoteState(VoteStateUpdate)
    9  UpdateVoteStateSwitch(VoteStateUpdate, Hash)
    14 TowerSync { lockouts, root, hash, timestamp, block_id }
    15 TowerSyncSwitch(TowerSync, Hash)

Core rules implemented (each against its Agave/reference analog):
  - process_next_vote_slot: expired-lockout pop, root promotion at 31
    deep with credit award, lockout DOUBLING via double_lockouts.
  - check_slots_are_valid: votes only for slots in the SlotHashes sysvar,
    vote hash must match the slot's entry.
  - timely vote credits: latency-graded credit (grace 2 slots, max 16).
  - authorized voter rotation takes effect NEXT epoch, one pending
    rotation at a time, prior voter recorded in the circular buffer.
  - withdraw: rent-floor on partial, full drain only with no recent
    epoch credits (active-account close guard), state cleared.
  - commission increase only in the first half of the epoch.
  - process_new_vote_state (TowerSync/UpdateVoteState): monotonic slots,
    strictly-decreasing confirmation counts, no root rollback, last
    slot's hash checked against SlotHashes, credits for newly-rooted
    slots.
"""

from __future__ import annotations

from firedancer_tpu.flamenco import types as T
from firedancer_tpu.flamenco.agave_state import (
    LandedVote,
    Lockout,
    VoteState,
    vote_state_decode,
    vote_state_encode,
)

MAX_LOCKOUT_HISTORY = 31
INITIAL_LOCKOUT = 2
VOTE_STATE_SIZE = 3762  # size_of::<VoteStateVersions>() — fixed account size
VOTE_CREDITS_GRACE_SLOTS = 2
VOTE_CREDITS_MAXIMUM_PER_SLOT = 16
MAX_EPOCH_CREDITS_HISTORY = 64

AUTHORIZE_VOTER = 0
AUTHORIZE_WITHDRAWER = 1


class VoteError(Exception):
    """Typed vote failure; the program wrapper maps it to InstrError."""


# -- instruction payload codecs ----------------------------------------------

from dataclasses import dataclass, field as dfield


@dataclass
class VoteInit:
    node_pubkey: bytes
    authorized_voter: bytes
    authorized_withdrawer: bytes
    commission: int


VOTE_INIT = T.StructCodec(
    VoteInit,
    ("node_pubkey", T.Pubkey),
    ("authorized_voter", T.Pubkey),
    ("authorized_withdrawer", T.Pubkey),
    ("commission", T.U8),
)


@dataclass
class VoteIx:
    slots: list
    hash: bytes
    timestamp: int | None


VOTE_IX = T.StructCodec(
    VoteIx,
    ("slots", T.Vec(T.U64, max_len=64)),
    ("hash", T.Hash32),
    ("timestamp", T.Option(T.I64)),
)


@dataclass
class VoteStateUpdate:
    lockouts: list  # [Lockout]
    root: int | None
    hash: bytes
    timestamp: int | None


from firedancer_tpu.flamenco.agave_state import LOCKOUT

VOTE_STATE_UPDATE = T.StructCodec(
    VoteStateUpdate,
    ("lockouts", T.Vec(LOCKOUT, max_len=64)),
    ("root", T.Option(T.U64)),
    ("hash", T.Hash32),
    ("timestamp", T.Option(T.I64)),
)


@dataclass
class TowerSync:
    lockouts: list  # [Lockout]
    root: int | None
    hash: bytes
    timestamp: int | None
    block_id: bytes


TOWER_SYNC = T.StructCodec(
    TowerSync,
    ("lockouts", T.Vec(LOCKOUT, max_len=64)),
    ("root", T.Option(T.U64)),
    ("hash", T.Hash32),
    ("timestamp", T.Option(T.I64)),
    ("block_id", T.Hash32),
)


def encode_vote_ix(slots: list[int], hash32: bytes,
                   timestamp: int | None = None) -> bytes:
    """Wire data for VoteInstruction::Vote (what voters emit)."""
    return T.U32.encode(2) + VOTE_IX.encode(VoteIx(slots, hash32, timestamp))


def encode_tower_sync_ix(lockouts: list[tuple[int, int]], root: int | None,
                         hash32: bytes, block_id: bytes = b"\x00" * 32,
                         timestamp: int | None = None) -> bytes:
    return T.U32.encode(14) + TOWER_SYNC.encode(TowerSync(
        [Lockout(s, c) for s, c in lockouts], root, hash32, timestamp,
        block_id))


def encode_initialize_ix(node: bytes, voter: bytes, withdrawer: bytes,
                         commission: int = 0) -> bytes:
    return T.U32.encode(0) + VOTE_INIT.encode(
        VoteInit(node, voter, withdrawer, commission))


# -- state machine ------------------------------------------------------------


def lockout_expired(lk: Lockout, next_slot: int) -> bool:
    """is_locked_out_at_slot inverted: lockout on `lk.slot` lasts
    2^confirmation_count slots."""
    return lk.slot + (INITIAL_LOCKOUT ** lk.confirmation_count) < next_slot


def credits_for_latency(latency: int) -> int:
    """Timely vote credits: full credit inside the grace window, then
    one fewer per extra slot of latency, floor 1 (vote_state credits_for
    _vote_at_index rule)."""
    if latency == 0:  # legacy votes with no recorded latency
        return 1
    if latency <= VOTE_CREDITS_GRACE_SLOTS:
        return VOTE_CREDITS_MAXIMUM_PER_SLOT
    return max(
        VOTE_CREDITS_MAXIMUM_PER_SLOT - (latency - VOTE_CREDITS_GRACE_SLOTS),
        1,
    )


def increment_credits(vs: VoteState, epoch: int, credits: int) -> None:
    if not vs.epoch_credits:
        vs.epoch_credits.append((epoch, 0, 0))
    elif epoch != vs.epoch_credits[-1][0]:
        _e, c, p = vs.epoch_credits[-1]
        if c != p:
            vs.epoch_credits.append((epoch, c, c))
        else:
            # the previous epoch earned NOTHING: replace its entry
            # rather than stacking zero-credit rows (Agave's encoding —
            # byte-parity with on-chain state demands it)
            vs.epoch_credits[-1] = (epoch, c, c)
        if len(vs.epoch_credits) > MAX_EPOCH_CREDITS_HISTORY:
            vs.epoch_credits.pop(0)
    e, c, p = vs.epoch_credits[-1]
    vs.epoch_credits[-1] = (e, c + credits, p)


def double_lockouts(vs: VoteState) -> None:
    """Every vote deeper in the stack than its confirmation count gets
    its confirmation count bumped — the lockout-doubling rule."""
    depth = len(vs.votes)
    for i, lv in enumerate(vs.votes):
        if depth > i + lv.lockout.confirmation_count:
            lv.lockout.confirmation_count += 1


def pop_expired_votes(vs: VoteState, next_slot: int) -> None:
    while vs.votes and lockout_expired(vs.votes[-1].lockout, next_slot):
        vs.votes.pop()


def process_next_vote_slot(vs: VoteState, next_slot: int, epoch: int,
                           current_slot: int) -> None:
    """The heart of the program: one new vote slot onto the tower."""
    if vs.votes and vs.votes[-1].lockout.slot >= next_slot:
        return
    pop_expired_votes(vs, next_slot)
    latency = max(0, current_slot - next_slot) if current_slot else 0
    lv = LandedVote(min(latency, 255), Lockout(next_slot, 1))
    if len(vs.votes) == MAX_LOCKOUT_HISTORY:
        rooted = vs.votes.pop(0)
        vs.root_slot = rooted.lockout.slot
        increment_credits(vs, epoch, credits_for_latency(rooted.latency))
    vs.votes.append(lv)
    double_lockouts(vs)


def check_slots_are_valid(vs: VoteState, slots: list[int], vote_hash: bytes,
                          slot_hashes: list[tuple[int, bytes]]) -> list[int]:
    """Filter to slots newer than the last vote AND present in
    SlotHashes; the vote's hash must match the newest voted slot's
    entry.  Returns the accepted slots (VoteError on none/mismatch)."""
    sh = dict(slot_hashes)
    last = vs.votes[-1].lockout.slot if vs.votes else -1
    accepted = [s for s in slots if s > last and s in sh]
    if not accepted:
        raise VoteError("VotesTooOldAllFiltered/SlotsMismatch")
    if sh[accepted[-1]] != vote_hash:
        raise VoteError("SlotHashMismatch")
    return accepted


def process_vote(vs: VoteState, vote: VoteIx,
                 slot_hashes: list[tuple[int, bytes]],
                 epoch: int, current_slot: int) -> None:
    if not vote.slots:
        raise VoteError("EmptySlots")
    for s in check_slots_are_valid(vs, vote.slots, vote.hash, slot_hashes):
        process_next_vote_slot(vs, s, epoch, current_slot)
    if vote.timestamp is not None:
        slot = vote.slots[-1]
        _check_and_set_timestamp(vs, slot, vote.timestamp)


def _check_and_set_timestamp(vs: VoteState, slot: int, ts: int) -> None:
    """process_timestamp: monotone in slot and time; the same slot may
    only re-assert the identical timestamp."""
    lt = vs.last_timestamp
    if (
        slot < lt.slot
        or ts < lt.timestamp
        or (slot == lt.slot and (slot, ts) != (lt.slot, lt.timestamp)
            and lt.slot != 0)
    ):
        # same slot may only RE-ASSERT the identical timestamp
        raise VoteError("TimestampTooOld")
    lt.slot = slot
    lt.timestamp = ts


def process_new_vote_state(
    vs: VoteState,
    new_lockouts: list[Lockout],
    new_root: int | None,
    vote_hash: bytes,
    slot_hashes: list[tuple[int, bytes]],
    epoch: int,
    current_slot: int,
) -> None:
    """TowerSync / UpdateVoteState: replace the tower wholesale after
    validating its internal structure and consistency with this fork."""
    if not new_lockouts:
        raise VoteError("EmptySlots")
    if len(new_lockouts) > MAX_LOCKOUT_HISTORY:
        raise VoteError("TooManyVotes")
    if vs.votes and new_lockouts[-1].slot <= vs.votes[-1].lockout.slot:
        # a new state may never REWIND the last voted slot — else the
        # voter could shrink its tower and re-vote 16..30 on another
        # fork, breaking lockout safety (Agave's VoteTooOld)
        raise VoteError("VoteTooOld")
    if new_root is not None and vs.root_slot is not None \
            and new_root < vs.root_slot:
        raise VoteError("RootRollBack")
    if new_root is None and vs.root_slot is not None:
        raise VoteError("RootRollBack")
    for i, lk in enumerate(new_lockouts):
        if not 1 <= lk.confirmation_count <= MAX_LOCKOUT_HISTORY:
            raise VoteError("ConfirmationOutOfBounds")
        if new_root is not None and lk.slot <= new_root:
            raise VoteError("SlotSmallerThanRoot")
        if i > 0:
            prev = new_lockouts[i - 1]
            if lk.slot <= prev.slot:
                raise VoteError("SlotsNotOrdered")
            if lk.confirmation_count >= prev.confirmation_count:
                raise VoteError("ConfirmationsNotOrdered")
    sh = dict(slot_hashes)
    last_slot = new_lockouts[-1].slot
    if last_slot not in sh:
        raise VoteError("SlotsMismatch")
    if sh[last_slot] != vote_hash:
        raise VoteError("SlotHashMismatch")
    # credits for slots the new state roots that the old one hadn't:
    # every old vote at or below the new root earns its landing credit
    if new_root is not None:
        old_root = vs.root_slot if vs.root_slot is not None else -1
        for lv in vs.votes:
            if old_root < lv.lockout.slot <= new_root:
                increment_credits(vs, epoch,
                                  credits_for_latency(lv.latency))
    # carry landing latencies for slots surviving into the new tower
    latency_by_slot = {lv.lockout.slot: lv.latency for lv in vs.votes}
    vs.votes = [
        LandedVote(
            latency_by_slot.get(
                lk.slot,
                min(max(0, current_slot - lk.slot), 255) if current_slot
                else 0,
            ),
            lk,
        )
        for lk in new_lockouts
    ]
    vs.root_slot = new_root


def set_new_authorized_voter(vs: VoteState, new_voter: bytes,
                             current_epoch: int, target_epoch: int) -> None:
    """Rotation lands at `target_epoch` (next): one pending rotation at
    a time; the outgoing voter is recorded in the prior-voters circular
    buffer."""
    if any(e > current_epoch for e in vs.authorized_voters):
        raise VoteError("TooSoonToReauthorize")
    current = vs.authorized_voter_for(current_epoch)
    if current == new_voter:
        return
    pv = vs.prior_voters
    if current is not None:
        epoch_of_last_rotation = max(
            (e for e in vs.authorized_voters if e <= current_epoch),
            default=0,
        )
        pv.idx = (pv.idx + 1) % 32
        pv.buf[pv.idx] = (current, epoch_of_last_rotation, target_epoch)
        pv.is_empty = False
    # drop map entries older than the latest one still <= current_epoch
    keep_from = max((e for e in vs.authorized_voters if e <= current_epoch),
                    default=None)
    vs.authorized_voters = {
        e: v for e, v in vs.authorized_voters.items()
        if keep_from is None or e >= keep_from
    }
    vs.authorized_voters[target_epoch] = new_voter


# -- the program entry --------------------------------------------------------


def _clock(ctx):
    blob = ctx.sysvars.get("clock")
    if not blob:
        raise VoteError("clock sysvar unavailable")
    return T.CLOCK.loads(blob)


def _slot_hashes(ctx) -> list[tuple[int, bytes]]:
    blob = ctx.sysvars.get("slot_hashes")
    if not blob:
        return []
    return [(e.slot, e.hash) for e in T.SLOT_HASHES.loads(blob)]


def _state_load(acct) -> VoteState | None:
    data = bytes(acct.data)
    if not data.strip(b"\x00"):
        return None  # uninitialized (all zero — V0_23_5 default state)
    return vote_state_decode(data)


def _state_store(acct, vs: VoteState) -> None:
    blob = vote_state_encode(vs)
    if len(blob) > len(acct.data):
        # the account's space is FIXED at creation: set_state must never
        # grow it (no realloc / rent re-check path here, as Agave)
        raise VoteError("vote state overflows the account data size")
    acct.data = bytearray(blob.ljust(len(acct.data), b"\x00"))


def vote_program(executor, ctx, program_id, iaccts, data, *,
                 pda_signers):
    """Native-program entry (executor registry signature)."""
    from firedancer_tpu.flamenco.programs import AcctError
    from firedancer_tpu.flamenco.executor import InstrError
    from firedancer_tpu.protocol.txn import VOTE_PROGRAM

    try:
        tag, off = T.U32.decode(data, 0)
    except T.CodecError:
        raise InstrError("vote: truncated instruction")

    if not iaccts:
        raise AcctError("vote: missing vote account")
    vote_acct = ctx.accounts[iaccts[0].txn_idx]
    if vote_acct.owner != VOTE_PROGRAM:
        raise AcctError("vote account not owned by the vote program")
    if not iaccts[0].is_writable:
        raise AcctError("vote account not writable")

    def signers() -> set[bytes]:
        out = set(pda_signers)
        for ia in iaccts:
            if ia.is_signer:
                out.add(ctx.accounts[ia.txn_idx].key)
        return out

    def require_sig(pk: bytes | None, what: str) -> None:
        if pk is None or pk not in signers():
            raise AcctError(f"vote: missing {what} signature")

    try:
        clock = _clock(ctx)
        if tag == 0:  # InitializeAccount
            init, _ = VOTE_INIT.decode(data, off)
            if len(vote_acct.data) != VOTE_STATE_SIZE:
                raise VoteError("vote account has wrong data size")
            if bytes(vote_acct.data).strip(b"\x00"):
                raise VoteError("vote account already initialized")
            # the node (validator identity) must sign account creation
            require_sig(init.node_pubkey, "node")
            vs = VoteState(
                node_pubkey=init.node_pubkey,
                authorized_withdrawer=init.authorized_withdrawer,
                commission=init.commission,
                authorized_voters={clock.epoch: init.authorized_voter},
            )
            _state_store(vote_acct, vs)
            return

        vs = _state_load(vote_acct)
        if vs is None:
            raise VoteError("vote account uninitialized")

        if tag in (2, 6):  # Vote / VoteSwitch
            vote, _ = VOTE_IX.decode(data, off)
            require_sig(vs.authorized_voter_for(clock.epoch),
                        "authorized-voter")
            process_vote(vs, vote, _slot_hashes(ctx), clock.epoch,
                         clock.slot)
        elif tag in (8, 9, 14, 15):  # UpdateVoteState / TowerSync (+Switch)
            if tag in (8, 9):
                upd, _ = VOTE_STATE_UPDATE.decode(data, off)
            else:
                upd, _ = TOWER_SYNC.decode(data, off)
            require_sig(vs.authorized_voter_for(clock.epoch),
                        "authorized-voter")
            process_new_vote_state(vs, upd.lockouts, upd.root, upd.hash,
                                   _slot_hashes(ctx), clock.epoch,
                                   clock.slot)
            if upd.timestamp is not None and upd.lockouts:
                _check_and_set_timestamp(vs, upd.lockouts[-1].slot,
                                         upd.timestamp)
        elif tag == 1:  # Authorize(new_pubkey, which)
            new_pk, o2 = T.Pubkey.decode(data, off)
            which, _ = T.U32.decode(data, o2)
            _authorize(vs, new_pk, which, clock, require_sig)
        elif tag == 7:  # AuthorizeChecked: new authority is account 3 + signs
            which, _ = T.U32.decode(data, off)
            if len(iaccts) < 4:
                raise AcctError("vote authorize-checked needs 4 accounts")
            new_acct = ctx.accounts[iaccts[3].txn_idx]
            if not iaccts[3].is_signer:
                raise AcctError("vote: new authority must sign (checked)")
            _authorize(vs, new_acct.key, which, clock, require_sig)
        elif tag == 3:  # Withdraw(lamports)
            lamports, _ = T.U64.decode(data, off)
            if len(iaccts) < 2:
                raise AcctError("vote withdraw needs recipient")
            if not iaccts[1].is_writable:
                raise AcctError("vote withdraw recipient not writable")
            recipient = ctx.accounts[iaccts[1].txn_idx]
            require_sig(vs.authorized_withdrawer, "withdrawer")
            _withdraw(vote_acct, vs, recipient, lamports, clock, ctx)
            return  # _withdraw stores/clears state itself
        elif tag == 4:  # UpdateValidatorIdentity
            if len(iaccts) < 2:
                raise AcctError("vote identity update needs node account")
            node = ctx.accounts[iaccts[1].txn_idx]
            if not iaccts[1].is_signer:
                raise AcctError("vote: new node must sign")
            require_sig(vs.authorized_withdrawer, "withdrawer")
            vs.node_pubkey = node.key
        elif tag == 5:  # UpdateCommission(u8)
            new_commission, _ = T.U8.decode(data, off)
            require_sig(vs.authorized_withdrawer, "withdrawer")
            if new_commission > vs.commission:
                # increases land only in the first half of the epoch, so
                # a validator cannot raise its cut right before rewards
                sched = T.EPOCH_SCHEDULE.loads(ctx.sysvars["epoch_schedule"]) \
                    if ctx.sysvars.get("epoch_schedule") else T.EpochSchedule()
                try:
                    # epoch-relative index honoring first_normal_slot
                    _e, into_epoch = T.epoch_of_slot(sched, clock.slot)
                except T.CodecError:  # warmup epochs: modulo fallback
                    into_epoch = clock.slot % max(sched.slots_per_epoch, 1)
                if into_epoch > sched.slots_per_epoch // 2:
                    raise VoteError("CommissionUpdateTooLate")
            vs.commission = new_commission
        else:
            raise InstrError(f"vote: unsupported instruction {tag}")
        _state_store(vote_acct, vs)
    except VoteError as e:
        raise InstrError(f"vote: {e}")
    except T.CodecError as e:
        raise InstrError(f"vote: malformed instruction ({e})")


def _authorize(vs: VoteState, new_pk: bytes, which: int, clock,
               require_sig) -> None:
    if which == AUTHORIZE_VOTER:
        # current voter OR the withdrawer may rotate the voter
        current = vs.authorized_voter_for(clock.epoch)
        try:
            require_sig(current, "authorized-voter")
        except Exception:
            require_sig(vs.authorized_withdrawer, "withdrawer")
        set_new_authorized_voter(vs, new_pk, clock.epoch, clock.epoch + 1)
    elif which == AUTHORIZE_WITHDRAWER:
        require_sig(vs.authorized_withdrawer, "withdrawer")
        vs.authorized_withdrawer = new_pk
    else:
        raise VoteError("bad VoteAuthorize")


def _withdraw(vote_acct, vs: VoteState, recipient, lamports: int, clock,
              ctx) -> None:
    from firedancer_tpu.flamenco.programs import FundsError

    if lamports > vote_acct.lamports:
        raise FundsError("vote withdraw exceeds balance")
    remaining = vote_acct.lamports - lamports
    if remaining == 0:
        # closing an ACTIVE vote account is rejected: credits earned in
        # this or the previous epoch mean stakes still reference it
        if any(e >= clock.epoch - 1 for e, _c, _p in vs.epoch_credits):
            raise VoteError("ActiveVoteAccountClose")
        vote_acct.data = bytearray(len(vote_acct.data))  # deinitialize
    else:
        rent_blob = ctx.sysvars.get("rent")
        rent = T.RENT.loads(rent_blob) if rent_blob else T.Rent()
        floor = T.rent_exempt_minimum(rent, len(vote_acct.data))
        if remaining < floor:
            raise FundsError("vote withdraw below rent-exempt floor")
        _state_store(vote_acct, vs)
    vote_acct.lamports = remaining
    recipient.lamports += lamports
