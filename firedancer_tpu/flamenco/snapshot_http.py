"""Snapshot distribution over HTTP: serve + download + boot.

Capability parity with the reference's snapshot HTTP client
(/root/reference/src/flamenco/snapshot/fd_snapshot_http.c — a validator
bootstraps by downloading `/snapshot.tar.bz2`-style archives from a
serving peer, then restoring; no code shared).  Both sides run on this
framework's own HTTP stack (protocol/http.py):

  - `SnapshotServer` exposes a snapshot directory at the cluster's
    conventional paths: `/snapshot.tar.zst` (latest full),
    `/incremental-snapshot.tar.zst` (latest incremental for that full),
    plus exact `/snapshot-<slot>.tar.zst` names;
  - `download_snapshot` is a streaming GET client with a size cap and
    atomic rename-into-place — a half-downloaded archive can never be
    mistaken for a snapshot;
  - `bootstrap_from_peer` = download full (+ incremental when offered)
    then `snapshot_load` into a funk: the cold-boot recipe.
"""

from __future__ import annotations

import os
import re
import socket

from firedancer_tpu.protocol import http as H

MAX_SNAPSHOT_BYTES = 64 << 30
_NAME_RE = re.compile(r"^(incremental-)?snapshot-(\d+)(?:-(\d+))?\.tar\.zst$")


class SnapshotHttpError(RuntimeError):
    pass


def _scan(directory: str):
    """-> (fulls {slot: name}, incrementals {base_slot: (slot, name)})."""
    fulls: dict[int, str] = {}
    incs: dict[int, tuple[int, str]] = {}
    for fn in os.listdir(directory):
        m = _NAME_RE.match(fn)
        if not m:
            continue
        if m.group(1):  # incremental-snapshot-<base>-<slot>.tar.zst
            base, slot = int(m.group(2)), int(m.group(3) or 0)
            if base not in incs or slot > incs[base][0]:
                incs[base] = (slot, fn)
        else:
            fulls[int(m.group(2))] = fn
    return fulls, incs


def full_snapshot_name(slot: int) -> str:
    return f"snapshot-{slot}.tar.zst"


def incremental_snapshot_name(base_slot: int, slot: int) -> str:
    return f"incremental-snapshot-{base_slot}-{slot}.tar.zst"


class SnapshotServer:
    """Serves a directory of snapshot archives (the peer a bootstrapping
    validator downloads from)."""

    def __init__(self, directory: str, *, host: str = "127.0.0.1",
                 port: int = 0):
        import threading

        self.directory = directory
        self._hash_cache: dict = {}  # (name, mtime_ns, size) -> hex sha256
        self._hash_lock = threading.Lock()
        self._hash_inflight: dict = {}  # key -> Event while being hashed

        def handler(req, _body):
            if req.method != "GET":
                return H.build_response(405, b"GET only\n")
            name = req.path.lstrip("/")
            fulls, incs = _scan(self.directory)
            if name == "snapshot.tar.zst":
                if not fulls:
                    return H.build_response(404, b"no snapshot\n")
                name = fulls[max(fulls)]
            elif name == "incremental-snapshot.tar.zst":
                if not fulls or max(fulls) not in incs:
                    return H.build_response(404, b"no incremental\n")
                name = incs[max(fulls)][1]
            if "/" in name or not _NAME_RE.match(name):
                return H.build_response(404, b"not found\n")
            path = os.path.join(self.directory, name)
            if not os.path.exists(path):
                return H.build_response(404, b"not found\n")
            st = os.stat(path)
            digest = self._content_sha256(path, name, st)
            head = H.build_stream_head(
                200, st.st_size,
                content_type="application/octet-stream",
                headers=[("x-snapshot-name", name),
                         ("x-snapshot-sha256", digest)],
            )

            def chunks(path=path, size=st.st_size):
                # stream in bounded chunks: a 64 GB archive must never
                # be materialized per request (the old f.read() did).
                # Reads cap at size - sent: if the file GREW between
                # stat and open, the response still matches its
                # declared content-length
                sent = 0
                with open(path, "rb") as f:
                    while sent < size:
                        blob = f.read(min(1 << 20, size - sent))
                        if not blob:
                            break
                        sent += len(blob)
                        yield blob

            return head, chunks()

        self._srv = H.MiniServer(handler, host=host, port=port)

    def _content_sha256(self, path: str, name: str, st) -> str:
        """Hex sha256 of the archive, cached by (name, mtime, size) so a
        steady-state serving loop hashes each archive once."""
        import hashlib
        import threading

        key = (name, st.st_mtime_ns, st.st_size)
        # one hash pass per archive, WITHOUT holding a global lock for
        # the (potentially minutes-long) pass: the lock only guards the
        # cache + in-flight map; concurrent cold requests for the same
        # key wait on the owner's event, other keys proceed freely
        while True:
            with self._hash_lock:
                got = self._hash_cache.get(key)
                if got is not None:
                    return got
                ev = self._hash_inflight.get(key)
                if ev is None:
                    ev = threading.Event()
                    self._hash_inflight[key] = ev
                    break  # this thread owns the computation
            ev.wait()  # owner finished (or failed): re-check the cache
        try:
            h = hashlib.sha256()
            # hash EXACTLY the st_size bytes the stream path serves: a
            # file growing mid-pass must not advertise a digest over
            # bytes the response never carries
            remaining = st.st_size
            with open(path, "rb") as f:
                while remaining > 0:
                    blob = f.read(min(1 << 20, remaining))
                    if not blob:
                        break
                    h.update(blob)
                    remaining -= len(blob)
            digest = h.hexdigest()
            with self._hash_lock:
                if len(self._hash_cache) > 16:  # stale (name,mtime) keys
                    self._hash_cache.clear()
                self._hash_cache[key] = digest
            return digest
        finally:
            with self._hash_lock:
                self._hash_inflight.pop(key, None)
            ev.set()

    @property
    def addr(self):
        return self._srv.addr

    def close(self):
        self._srv.close()


def download_snapshot(addr: tuple[str, int], name: str, dest_dir: str, *,
                      max_bytes: int = MAX_SNAPSHOT_BYTES,
                      timeout_s: float = 60.0) -> str:
    """GET /<name> from a peer into dest_dir; returns the final path.
    Streams to `<name>.partial` and renames only on a complete body, so
    an interrupted transfer never poses as a snapshot.  When the peer
    advertises `x-snapshot-sha256`, the streamed bytes are hashed on the
    way down and a mismatch (transfer corruption, truncating middlebox)
    rejects the archive; an advertised `x-snapshot-name` renames alias
    downloads (snapshot.tar.zst) to their canonical slot-exact name."""
    import hashlib

    os.makedirs(dest_dir, exist_ok=True)
    adv_name = None
    sock = socket.create_connection(addr, timeout=timeout_s)
    try:
        sock.sendall(
            f"GET /{name} HTTP/1.1\r\nHost: {addr[0]}\r\n"
            f"Connection: close\r\n\r\n".encode()
        )
        buf = b""
        resp = None
        while resp is None or resp is H.NEED_MORE:
            chunk = sock.recv(65536)
            if not chunk:
                raise SnapshotHttpError("peer closed during headers")
            buf += chunk
            if len(buf) > 1 << 20:
                raise SnapshotHttpError("oversized response head")
            resp = H.parse_response(buf)
        if resp.status != 200:
            raise SnapshotHttpError(f"peer answered {resp.status}")
        need = H.body_length(resp)
        if not isinstance(need, int) or need <= 0:
            raise SnapshotHttpError("peer sent no content length")
        if need > max_bytes:
            raise SnapshotHttpError(f"snapshot {need} bytes > cap")
        want_sha = resp.header("x-snapshot-sha256")
        adv_name = resp.header("x-snapshot-name")
        if adv_name:
            # the advertised name is PEER INPUT: it may only rename an
            # alias request to a canonical name of the SAME kind —
            # answering the incremental alias with a full-snapshot name
            # (or vice versa) would let a lying peer clobber the other
            # archive in dest_dir
            m = _NAME_RE.match(adv_name)
            base = name.rsplit("/", 1)[-1]
            if base == "snapshot.tar.zst":
                ok = bool(m) and not m.group(1)
            elif base == "incremental-snapshot.tar.zst":
                ok = bool(m) and bool(m.group(1))
            else:
                ok = adv_name == base
            if "/" in adv_name or not ok:
                raise SnapshotHttpError(
                    f"peer advertised bad name {adv_name!r}")
        final = os.path.join(dest_dir, (adv_name or name).rsplit("/", 1)[-1])
        tmp = final + ".partial"
        got = len(buf) - resp.head_len
        if got > need:
            # excess arriving WITH the head must hit the same guard as
            # excess arriving later
            raise SnapshotHttpError("peer sent excess bytes")
        hasher = hashlib.sha256(buf[resp.head_len:])
        with open(tmp, "wb") as f:
            f.write(buf[resp.head_len:])
            while got < need:
                chunk = sock.recv(65536)
                if not chunk:
                    raise SnapshotHttpError(
                        f"peer closed at {got}/{need} bytes"
                    )
                got += len(chunk)
                if got > need:
                    raise SnapshotHttpError("peer sent excess bytes")
                hasher.update(chunk)
                f.write(chunk)
        if want_sha and hasher.hexdigest() != want_sha.lower():
            os.remove(tmp)
            raise SnapshotHttpError("snapshot content hash mismatch")
        os.replace(tmp, final)
        return final
    finally:
        sock.close()
        for leftover in {name, adv_name or name}:
            try:
                os.remove(os.path.join(
                    dest_dir, leftover.rsplit("/", 1)[-1] + ".partial"))
            except OSError:
                pass


def bootstrap_from_peer(addr: tuple[str, int], dest_dir: str, *,
                        funk=None):
    """Cold boot: download the peer's latest full snapshot (+ its
    incremental when offered), restore into a funk.  Returns
    (funk, manifest, paths)."""
    from firedancer_tpu.flamenco.snapshot import snapshot_load, snapshot_read

    full = download_snapshot(addr, "snapshot.tar.zst", dest_dir)
    man, _ = snapshot_read(full)
    # a canonically-named download (peer advertised x-snapshot-name)
    # must AGREE with the manifest inside it — name/content divergence
    # means a confused or lying peer, not a bootable archive
    m = _NAME_RE.match(os.path.basename(full))
    if m and not m.group(1) and int(m.group(2)) != man.slot:
        os.remove(full)
        raise SnapshotHttpError(
            f"snapshot name says slot {m.group(2)}, manifest says "
            f"{man.slot}"
        )
    # rename to the slot-exact convention for re-serving
    exact = os.path.join(dest_dir, full_snapshot_name(man.slot))
    os.replace(full, exact)
    inc_path = None
    try:
        inc = download_snapshot(addr, "incremental-snapshot.tar.zst",
                                dest_dir)
        inc_man, _ = snapshot_read(inc)
        if inc_man.base_slot == man.slot:
            inc_path = os.path.join(
                dest_dir,
                incremental_snapshot_name(inc_man.base_slot, inc_man.slot),
            )
            os.replace(inc, inc_path)
        else:
            os.remove(inc)
    except SnapshotHttpError:
        pass  # peer offers no incremental: full alone is a valid boot
    funk, manifest = snapshot_load(exact, funk,
                                   incremental_path=inc_path)
    return funk, manifest, (exact, inc_path)
