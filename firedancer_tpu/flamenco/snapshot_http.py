"""Snapshot distribution over HTTP: serve + download + boot.

Capability parity with the reference's snapshot HTTP client
(/root/reference/src/flamenco/snapshot/fd_snapshot_http.c — a validator
bootstraps by downloading `/snapshot.tar.bz2`-style archives from a
serving peer, then restoring; no code shared).  Both sides run on this
framework's own HTTP stack (protocol/http.py):

  - `SnapshotServer` exposes a snapshot directory at the cluster's
    conventional paths: `/snapshot.tar.zst` (latest full),
    `/incremental-snapshot.tar.zst` (latest incremental for that full),
    plus exact `/snapshot-<slot>.tar.zst` names;
  - `download_snapshot` is a streaming GET client with a size cap and
    atomic rename-into-place — a half-downloaded archive can never be
    mistaken for a snapshot;
  - `bootstrap_from_peer` = download full (+ incremental when offered)
    then `snapshot_load` into a funk: the cold-boot recipe.
"""

from __future__ import annotations

import os
import re
import socket

from firedancer_tpu.protocol import http as H

MAX_SNAPSHOT_BYTES = 64 << 30
_NAME_RE = re.compile(r"^(incremental-)?snapshot-(\d+)(?:-(\d+))?\.tar\.zst$")


class SnapshotHttpError(RuntimeError):
    pass


def _scan(directory: str):
    """-> (fulls {slot: name}, incrementals {base_slot: (slot, name)})."""
    fulls: dict[int, str] = {}
    incs: dict[int, tuple[int, str]] = {}
    for fn in os.listdir(directory):
        m = _NAME_RE.match(fn)
        if not m:
            continue
        if m.group(1):  # incremental-snapshot-<base>-<slot>.tar.zst
            base, slot = int(m.group(2)), int(m.group(3) or 0)
            if base not in incs or slot > incs[base][0]:
                incs[base] = (slot, fn)
        else:
            fulls[int(m.group(2))] = fn
    return fulls, incs


def full_snapshot_name(slot: int) -> str:
    return f"snapshot-{slot}.tar.zst"


def incremental_snapshot_name(base_slot: int, slot: int) -> str:
    return f"incremental-snapshot-{base_slot}-{slot}.tar.zst"


class SnapshotServer:
    """Serves a directory of snapshot archives (the peer a bootstrapping
    validator downloads from)."""

    def __init__(self, directory: str, *, host: str = "127.0.0.1",
                 port: int = 0):
        self.directory = directory

        def handler(req, _body):
            if req.method != "GET":
                return H.build_response(405, b"GET only\n")
            name = req.path.lstrip("/")
            fulls, incs = _scan(self.directory)
            if name == "snapshot.tar.zst":
                if not fulls:
                    return H.build_response(404, b"no snapshot\n")
                name = fulls[max(fulls)]
            elif name == "incremental-snapshot.tar.zst":
                if not fulls or max(fulls) not in incs:
                    return H.build_response(404, b"no incremental\n")
                name = incs[max(fulls)][1]
            if "/" in name or not _NAME_RE.match(name):
                return H.build_response(404, b"not found\n")
            path = os.path.join(self.directory, name)
            if not os.path.exists(path):
                return H.build_response(404, b"not found\n")
            with open(path, "rb") as f:
                blob = f.read()
            return H.build_response(
                200, blob, content_type="application/octet-stream",
            )

        self._srv = H.MiniServer(handler, host=host, port=port)

    @property
    def addr(self):
        return self._srv.addr

    def close(self):
        self._srv.close()


def download_snapshot(addr: tuple[str, int], name: str, dest_dir: str, *,
                      max_bytes: int = MAX_SNAPSHOT_BYTES,
                      timeout_s: float = 60.0) -> str:
    """GET /<name> from a peer into dest_dir; returns the final path.
    Streams to `<name>.partial` and renames only on a complete body, so
    an interrupted transfer never poses as a snapshot."""
    os.makedirs(dest_dir, exist_ok=True)
    sock = socket.create_connection(addr, timeout=timeout_s)
    try:
        sock.sendall(
            f"GET /{name} HTTP/1.1\r\nHost: {addr[0]}\r\n"
            f"Connection: close\r\n\r\n".encode()
        )
        buf = b""
        resp = None
        while resp is None or resp is H.NEED_MORE:
            chunk = sock.recv(65536)
            if not chunk:
                raise SnapshotHttpError("peer closed during headers")
            buf += chunk
            if len(buf) > 1 << 20:
                raise SnapshotHttpError("oversized response head")
            resp = H.parse_response(buf)
        if resp.status != 200:
            raise SnapshotHttpError(f"peer answered {resp.status}")
        need = H.body_length(resp)
        if not isinstance(need, int) or need <= 0:
            raise SnapshotHttpError("peer sent no content length")
        if need > max_bytes:
            raise SnapshotHttpError(f"snapshot {need} bytes > cap")
        final = os.path.join(dest_dir, name.rsplit("/", 1)[-1])
        tmp = final + ".partial"
        got = len(buf) - resp.head_len
        with open(tmp, "wb") as f:
            f.write(buf[resp.head_len:])
            while got < need:
                chunk = sock.recv(65536)
                if not chunk:
                    raise SnapshotHttpError(
                        f"peer closed at {got}/{need} bytes"
                    )
                got += len(chunk)
                if got > need:
                    raise SnapshotHttpError("peer sent excess bytes")
                f.write(chunk)
        os.replace(tmp, final)
        return final
    finally:
        sock.close()
        try:
            os.remove(os.path.join(dest_dir,
                                   name.rsplit("/", 1)[-1] + ".partial"))
        except OSError:
            pass


def bootstrap_from_peer(addr: tuple[str, int], dest_dir: str, *,
                        funk=None):
    """Cold boot: download the peer's latest full snapshot (+ its
    incremental when offered), restore into a funk.  Returns
    (funk, manifest, paths)."""
    from firedancer_tpu.flamenco.snapshot import snapshot_load, snapshot_read

    full = download_snapshot(addr, "snapshot.tar.zst", dest_dir)
    man, _ = snapshot_read(full)
    # rename to the slot-exact convention for re-serving
    exact = os.path.join(dest_dir, full_snapshot_name(man.slot))
    os.replace(full, exact)
    inc_path = None
    try:
        inc = download_snapshot(addr, "incremental-snapshot.tar.zst",
                                dest_dir)
        inc_man, _ = snapshot_read(inc)
        if inc_man.base_slot == man.slot:
            inc_path = os.path.join(
                dest_dir,
                incremental_snapshot_name(inc_man.base_slot, inc_man.slot),
            )
            os.replace(inc, inc_path)
        else:
            os.remove(inc)
    except SnapshotHttpError:
        pass  # peer offers no incremental: full alone is a valid boot
    funk, manifest = snapshot_load(exact, funk,
                                   incremental_path=inc_path)
    return funk, manifest, (exact, inc_path)
