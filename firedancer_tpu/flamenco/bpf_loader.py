"""Upgradeable BPF loader: deploy/upgrade/close programs THROUGH txns.

Counterpart of /root/reference/src/flamenco/runtime/program/
fd_bpf_loader_program.c (instruction processing, account state machine,
and the programdata indirection the executor resolves at invoke time).
Capability parity target only — no code shared.

Account states (bincode u32 discriminant):

    0 Uninitialized
    1 Buffer      { authority: Option<Pubkey> }            data from 37
    2 Program     { programdata_address: Pubkey }          (36 bytes)
    3 ProgramData { slot u64, upgrade_authority: Option }  ELF from 45

Instructions (bincode u32 tag):

    0 InitializeBuffer                     [buffer w, authority]
    1 Write { offset u32, bytes Vec<u8> }  [buffer w, authority s]
    2 DeployWithMaxDataLen { max u64 }     [payer s w, programdata w,
                                            program w, buffer w,
                                            authority s]
    3 Upgrade                              [programdata w, program w,
                                            buffer w, spill w,
                                            authority s]
    4 SetAuthority                         [target w, cur auth s,
                                            (new authority)]
    5 Close                                [target w, recipient w,
                                            authority s, (program w)]

Deploy-slot visibility: a program (re)deployed in slot N is invokable
from slot N+1 (ProgramData.slot records the deploy; the executor rejects
same-slot invocation) — LoaderV3's delay rule.
"""

from __future__ import annotations

from firedancer_tpu.flamenco.programs import AcctError, _u32, _u64
from firedancer_tpu.protocol import pda, sbpf
from firedancer_tpu.protocol.base58 import b58_decode32
from firedancer_tpu.protocol.txn import SYSTEM_PROGRAM

UPGRADEABLE_LOADER_PROGRAM = b58_decode32(
    "BPFLoaderUpgradeab1e11111111111111111111111"
)

ST_UNINITIALIZED = 0
ST_BUFFER = 1
ST_PROGRAM = 2
ST_PROGRAMDATA = 3

BUFFER_META_SIZE = 4 + 1 + 32          # disc | authority option
PROGRAM_SIZE = 4 + 32                  # disc | programdata address
PROGRAMDATA_META_SIZE = 4 + 8 + 1 + 32  # disc | slot | authority option


def _opt_key(some: bool, key: bytes) -> bytes:
    return bytes([1]) + key if some else bytes([0]) + bytes(32)


def buffer_encode(authority: bytes | None, payload: bytes = b"") -> bytes:
    return (
        ST_BUFFER.to_bytes(4, "little")
        + _opt_key(authority is not None, authority or bytes(32))
        + payload
    )


def program_encode(programdata: bytes) -> bytes:
    return ST_PROGRAM.to_bytes(4, "little") + programdata


def programdata_encode(slot: int, authority: bytes | None,
                       elf: bytes = b"") -> bytes:
    return (
        ST_PROGRAMDATA.to_bytes(4, "little")
        + slot.to_bytes(8, "little")
        + _opt_key(authority is not None, authority or bytes(32))
        + elf
    )


def state_of(data: bytes) -> int:
    if len(data) < 4:
        return ST_UNINITIALIZED
    return _u32(data)


def buffer_authority(data: bytes) -> bytes | None:
    if len(data) < BUFFER_META_SIZE or state_of(data) != ST_BUFFER:
        raise AcctError("not a buffer account")
    return bytes(data[5:37]) if data[4] else None


def program_programdata(data: bytes) -> bytes:
    if len(data) < PROGRAM_SIZE or state_of(data) != ST_PROGRAM:
        raise AcctError("not a program account")
    return bytes(data[4:36])


def programdata_meta(data: bytes) -> tuple[int, bytes | None]:
    """-> (deploy_slot, upgrade_authority)."""
    if len(data) < PROGRAMDATA_META_SIZE or state_of(data) != ST_PROGRAMDATA:
        raise AcctError("not a programdata account")
    auth = bytes(data[13:45]) if data[12] else None
    return _u64(data[4:]), auth


def programdata_elf(data: bytes) -> bytes:
    if len(data) < PROGRAMDATA_META_SIZE or state_of(data) != ST_PROGRAMDATA:
        raise AcctError("not a programdata account")
    return bytes(data[PROGRAMDATA_META_SIZE:])


def _clock_slot(ctx) -> int:
    from firedancer_tpu.flamenco import types as T

    blob = ctx.sysvars.get("clock")
    if not blob:
        raise AcctError("loader instruction requires the clock sysvar")
    clock, _ = T.CLOCK.decode(blob, 0)
    return clock.slot


def upgradeable_loader_program(executor, ctx, program_id, iaccts, data,
                               *, pda_signers):
    if len(data) < 4:
        raise AcctError("malformed loader instruction")
    tag = _u32(data)

    def acct(i, *, owned: bool = True):
        if i >= len(iaccts):
            raise AcctError(f"loader instr needs account {i}")
        a = ctx.accounts[iaccts[i].txn_idx]
        if owned and a.owner != UPGRADEABLE_LOADER_PROGRAM:
            raise AcctError(f"account {i} not owned by the loader")
        return a

    def need_writable(i):
        if i >= len(iaccts):
            raise AcctError(f"loader instr needs account {i}")
        if not iaccts[i].is_writable:
            raise AcctError(f"loader account {i} not writable")

    def need_signer(i):
        if i >= len(iaccts):
            raise AcctError(f"loader instr needs account {i}")
        ia = iaccts[i]
        if not (ia.is_signer or ctx.accounts[ia.txn_idx].key in pda_signers):
            raise AcctError(f"loader account {i} must sign")

    if tag == 0:  # InitializeBuffer; [buffer w, authority]
        buf = acct(0)
        need_writable(0)
        if state_of(bytes(buf.data)) != ST_UNINITIALIZED:
            raise AcctError("buffer already initialized")
        if len(buf.data) < BUFFER_META_SIZE:
            raise AcctError("buffer account too small")
        authority = acct(1, owned=False).key if len(iaccts) > 1 else None
        meta = buffer_encode(authority)
        buf.data[: len(meta)] = meta
    elif tag == 1:  # Write { offset u32, bytes Vec<u8> }; [buffer w, auth s]
        if len(data) < 4 + 4 + 8:
            raise AcctError("malformed loader write")
        offset = _u32(data[4:])
        n = _u64(data[8:])
        if len(data) < 16 + n:
            raise AcctError("short loader write payload")
        payload = data[16 : 16 + n]
        buf = acct(0)
        need_writable(0)
        auth = buffer_authority(bytes(buf.data))
        if auth is None:
            raise AcctError("buffer is immutable")
        need_signer(1)
        if acct(1, owned=False).key != auth:
            raise AcctError("wrong buffer authority")
        end = BUFFER_META_SIZE + offset + n
        if end > len(buf.data):
            raise AcctError("write past end of buffer account")
        buf.data[BUFFER_META_SIZE + offset : end] = payload
    elif tag == 2:  # DeployWithMaxDataLen { max_data_len u64 }
        # [payer s w, programdata w, program w, buffer w, authority s]
        if len(data) < 12:
            raise AcctError("malformed deploy")
        max_len = _u64(data[4:])
        need_signer(0)
        need_writable(0)
        progdata, program, buf = acct(1, owned=False), acct(2), acct(3)
        need_writable(1)
        need_writable(2)
        need_writable(3)
        need_signer(4)
        authority = acct(4, owned=False)
        if state_of(bytes(program.data)) != ST_UNINITIALIZED:
            raise AcctError("program account already deployed")
        if len(program.data) < PROGRAM_SIZE:
            raise AcctError("program account too small")
        buf_auth = buffer_authority(bytes(buf.data))
        if buf_auth is None or buf_auth != authority.key:
            raise AcctError("deploy authority does not match buffer")
        elf = bytes(buf.data[BUFFER_META_SIZE:])
        if max_len < len(elf):
            raise AcctError("max_data_len smaller than buffer contents")
        expect, _bump = pda.find_program_address(
            [program.key], UPGRADEABLE_LOADER_PROGRAM
        )
        if expect != progdata.key:
            raise AcctError("programdata address derivation mismatch")
        if progdata.owner not in (SYSTEM_PROGRAM, UPGRADEABLE_LOADER_PROGRAM):
            raise AcctError("programdata account has a foreign owner")
        if state_of(bytes(progdata.data)) not in (ST_UNINITIALIZED,):
            raise AcctError("programdata already in use")
        _validate_elf(elf)
        slot = _clock_slot(ctx)
        progdata.owner = UPGRADEABLE_LOADER_PROGRAM
        progdata.data = bytearray(
            programdata_encode(slot, authority.key, elf)
            + bytes(max_len - len(elf))
        )
        program.data = bytearray(program_encode(progdata.key))
        program.executable = True
        # buffer is consumed: lamports to the payer, account cleared
        ctx.accounts[iaccts[0].txn_idx].lamports += buf.lamports
        buf.lamports = 0
        buf.data = bytearray()
        buf.owner = SYSTEM_PROGRAM
    elif tag == 3:  # Upgrade; [programdata w, program w, buffer w, spill w,
        #            authority s]
        progdata, program, buf = acct(0), acct(1), acct(2)
        need_writable(0)
        need_writable(1)
        need_writable(2)
        need_writable(3)
        spill = acct(3, owned=False)
        need_signer(4)
        authority = acct(4, owned=False)
        pd_addr = program_programdata(bytes(program.data))
        if pd_addr != progdata.key:
            raise AcctError("program does not reference this programdata")
        _slot0, upgrade_auth = programdata_meta(bytes(progdata.data))
        if upgrade_auth is None:
            raise AcctError("program is not upgradeable")
        if upgrade_auth != authority.key:
            raise AcctError("wrong upgrade authority")
        buf_auth = buffer_authority(bytes(buf.data))
        if buf_auth is None or buf_auth != authority.key:
            raise AcctError("upgrade authority does not match buffer")
        elf = bytes(buf.data[BUFFER_META_SIZE:])
        cap = len(progdata.data) - PROGRAMDATA_META_SIZE
        if len(elf) > cap:
            raise AcctError("upgrade larger than programdata capacity")
        _validate_elf(elf)
        slot = _clock_slot(ctx)
        progdata.data = bytearray(
            programdata_encode(slot, authority.key, elf)
            + bytes(cap - len(elf))
        )
        spill.lamports += buf.lamports
        buf.lamports = 0
        buf.data = bytearray()
        buf.owner = SYSTEM_PROGRAM
    elif tag == 4:  # SetAuthority; [target w, cur authority s, (new)]
        target = acct(0)
        need_writable(0)
        need_signer(1)
        cur = acct(1, owned=False)
        new_auth = acct(2, owned=False).key if len(iaccts) > 2 else None
        st = state_of(bytes(target.data))
        if st == ST_BUFFER:
            auth = buffer_authority(bytes(target.data))
            if auth is None:
                raise AcctError("buffer is immutable")
            if auth != cur.key:
                raise AcctError("wrong buffer authority")
            if new_auth is None:
                raise AcctError("buffers cannot drop their authority")
            payload = bytes(target.data[BUFFER_META_SIZE:])
            target.data = bytearray(buffer_encode(new_auth, payload))
        elif st == ST_PROGRAMDATA:
            slot0, auth = programdata_meta(bytes(target.data))
            if auth is None:
                raise AcctError("program is final (no authority)")
            if auth != cur.key:
                raise AcctError("wrong upgrade authority")
            elf = bytes(target.data[PROGRAMDATA_META_SIZE:])
            target.data = bytearray(programdata_encode(slot0, new_auth, elf))
        else:
            raise AcctError("set-authority target is neither buffer nor "
                            "programdata")
    elif tag == 5:  # Close; [target w, recipient w, authority s, (program w)]
        target = acct(0)
        need_writable(0)
        need_writable(1)
        recipient = acct(1, owned=False)
        st = state_of(bytes(target.data))
        if target.key == recipient.key:
            raise AcctError("cannot close an account into itself")
        if st == ST_UNINITIALIZED:
            pass  # uninitialized closes freely
        elif st == ST_BUFFER:
            auth = buffer_authority(bytes(target.data))
            need_signer(2)
            if auth is None or acct(2, owned=False).key != auth:
                raise AcctError("wrong buffer authority")
        elif st == ST_PROGRAMDATA:
            _slot0, auth = programdata_meta(bytes(target.data))
            need_signer(2)
            if auth is None or acct(2, owned=False).key != auth:
                raise AcctError("wrong upgrade authority")
            program = acct(3)
            need_writable(3)
            if program_programdata(bytes(program.data)) != target.key:
                raise AcctError("program does not reference this programdata")
            # the program account is dead from the next slot on: the
            # executor fails invocations whose programdata is closed
            program.executable = False
        else:
            raise AcctError("close target must be buffer or programdata")
        recipient.lamports += target.lamports
        target.lamports = 0
        target.data = bytearray()
        target.owner = SYSTEM_PROGRAM
    else:
        raise AcctError(f"unknown loader instruction {tag}")


def _validate_elf(elf: bytes) -> None:
    try:
        sbpf.load(elf)
    except sbpf.SbpfError as e:
        raise AcctError(f"deploy of invalid ELF: {e}") from e
