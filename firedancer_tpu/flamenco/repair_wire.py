"""Solana-exact repair (ServeRepair) wire format.

Counterpart of the wire layer in /root/reference/src/flamenco/repair/
fd_repair.c: the bincode `RepairProtocol` enum —

     9 WindowIndex        { header, slot: u64, shred_index: u64 }
    10 HighestWindowIndex { header, slot: u64, shred_index: u64 }
    11 Orphan             { header, slot: u64 }

with RepairRequestHeader { signature(64), sender, recipient, timestamp
u64 ms, nonce u32 }.  The signature covers the serialized request with
the signature bytes EXCISED: the 4-byte enum tag followed by everything
after the 64-byte signature field (Solana's ServeRepair signing rule —
the signature cannot cover itself).

A repair response is the raw shred bytes with the u32 LE nonce appended
(the nonce ties the response to the request so off-path attackers can't
inject shreds they merely guessed a slot for).
"""

from __future__ import annotations

from dataclasses import dataclass

from firedancer_tpu.flamenco import types as T
from firedancer_tpu.ops.ref import ed25519_ref as ref


@dataclass
class RepairRequestHeader:
    signature: bytes
    sender: bytes
    recipient: bytes
    timestamp: int
    nonce: int


HEADER = T.StructCodec(
    RepairRequestHeader,
    ("signature", T.Signature),
    ("sender", T.Pubkey),
    ("recipient", T.Pubkey),
    ("timestamp", T.U64),
    ("nonce", T.U32),
)


@dataclass
class WindowIndex:
    header: RepairRequestHeader
    slot: int
    shred_index: int


@dataclass
class HighestWindowIndex:
    header: RepairRequestHeader
    slot: int
    shred_index: int


@dataclass
class Orphan:
    header: RepairRequestHeader
    slot: int


_WINDOW = T.StructCodec(
    WindowIndex, ("header", HEADER), ("slot", T.U64), ("shred_index", T.U64)
)
_HIGHEST = T.StructCodec(
    HighestWindowIndex, ("header", HEADER), ("slot", T.U64),
    ("shred_index", T.U64),
)
_ORPHAN = T.StructCodec(Orphan, ("header", HEADER), ("slot", T.U64))

PROTOCOL = T.Enum(
    (9, "window_index", _WINDOW),
    (10, "highest_window_index", _HIGHEST),
    (11, "orphan", _ORPHAN),
)

_SIG_START = 4  # after the u32 enum tag
_SIG_END = 4 + 64


def signable_bytes(encoded: bytes) -> bytes:
    """Tag + everything after the signature field."""
    return encoded[:_SIG_START] + encoded[_SIG_END:]


def sign_request(secret: bytes | None, name: str, payload, *,
                 signer=None) -> bytes:
    """Fill payload.header.signature over the serialized request.  Pass
    `signer` (payload -> 64B sig) to keep the key out-of-process (the
    keyguard pattern); otherwise `secret` signs locally."""
    payload.header.signature = bytes(64)
    enc = PROTOCOL.encode((name, payload))
    if signer is None:
        signer = lambda msg: ref.sign(secret, msg)  # noqa: E731
    payload.header.signature = signer(signable_bytes(enc))
    return PROTOCOL.encode((name, payload))


def verify_request(encoded: bytes):
    """-> (name, payload) with a valid header signature, else None."""
    import struct

    try:
        name, payload = PROTOCOL.loads(encoded)
    except (T.CodecError, ValueError, struct.error):
        return None
    h = payload.header
    if not ref.verify(signable_bytes(encoded), h.signature, h.sender):
        return None
    return name, payload


def encode_response(shred: bytes, nonce: int) -> bytes:
    return shred + nonce.to_bytes(4, "little")


def decode_response(buf: bytes):
    """-> (shred bytes, nonce) or None."""
    if len(buf) < 5:
        return None
    return buf[:-4], int.from_bytes(buf[-4:], "little")
