"""Runtime: slot execution over funk with conflict-wave parallelism.

The execution-side slice of the reference's flamenco runtime
(/root/reference/src/flamenco/runtime/fd_runtime.c): a block's
transactions execute against a funk fork in *waves* — maximal groups of
transactions with disjoint account rw-sets (wave generation
fd_runtime.c:1717-1736, fd_runtime_execute_txns_in_waves_tpool :1815) —
and the slot finalizes into a bank hash chaining the parent hash, the
accounts-delta lattice hash, the signature count and the PoH hash
(fd_hashes.c's formula shape).

TPU-native twist: a wave's txns are executable in any order — the same
property the reference exploits with a tpool is what batches device
work here: per-wave sigverify batches ride ops/sigverify, and the
accounts-delta hash sums every modified account's lattice hash in ONE
device reduction (ops/lthash.combine_device) instead of a sequential
accumulation.

Account model: funk value bytes = `u64 lamports | 32B owner |
u8 executable | data` (executor.acct_encode/decode).  Program dispatch
goes through flamenco/executor.py — native programs (system, vote,
stake) plus sBPF programs with CPI; a failed txn still pays its fee,
errors never abort the block.
"""

from __future__ import annotations

import hashlib
import itertools
from dataclasses import dataclass, field

import numpy as np

_xid_seq = itertools.count()

from firedancer_tpu.flamenco import executor as fexec
from firedancer_tpu.flamenco.executor import (
    Account,
    Executor,
    InstrAccount,
    InstrError,
    TxnCtx,
    acct_decode,
    acct_encode,
)
from firedancer_tpu.funk import Funk
from firedancer_tpu.ops import lthash as lt
from firedancer_tpu.protocol import txn as ft

LAMPORTS_PER_SIGNATURE = 5000

TXN_SUCCESS = 0
TXN_ERR_FEE = -1                 # payer cannot cover the fee: txn dropped
TXN_ERR_INSUFFICIENT_FUNDS = -2  # program failed: fee charged, no effects
TXN_ERR_ACCT = -3                # unresolvable account index (ALT accounts
                                 # need the address-resolution stage)
TXN_ERR_PROGRAM = -4             # program/VM error: fee charged, no effects
TXN_ERR_BLOCKHASH = -5           # recent_blockhash unknown/expired: no fee
TXN_ERR_ALREADY_PROCESSED = -6   # signature already landed on this fork


def acct_lamports(val: bytes | None) -> int:
    return acct_decode(val)[0]


def acct_build(lamports: int, data: bytes = b"",
               owner: bytes = ft.SYSTEM_PROGRAM,
               executable: bool = False) -> bytes:
    return acct_encode(lamports, owner, executable, data)


@dataclass
class TxnResult:
    status: int
    fee: int


@dataclass
class BlockResult:
    slot: int
    bank_hash: bytes
    accounts_delta: np.ndarray  # (1024,) uint16 lattice value
    signature_cnt: int
    fees: int
    results: list[TxnResult]
    waves: list[list[int]]  # txn indices per wave
    xid: bytes


def _rw_sets(
    payload: bytes, desc: ft.Txn,
    extra: tuple[list[bytes], list[bytes]] | None = None,
) -> tuple[set[bytes], set[bytes]]:
    addrs = desc.acct_addrs(payload)
    w, r = set(), set()
    for i, a in enumerate(addrs):
        (w if desc.is_writable(i) else r).add(a)
    if extra is not None:
        # resolved ALT addresses: exact rw sets, plus a READ lock on each
        # table so an in-block extend/close serializes against its users
        ew, er = extra
        w.update(ew)
        r.update(er)
        for lut in desc.addr_luts:
            r.add(payload[lut.addr_off : lut.addr_off + 32])
    else:
        # unresolved (failed lookup or legacy caller without resolution):
        # conservatively WRITE-lock the table address itself so two txns
        # loading from one table never share a wave (the same rule the
        # pack scheduler applies, pack/scheduler.py acct_sets)
        for lut in desc.addr_luts:
            w.add(payload[lut.addr_off : lut.addr_off + 32])
    return w, r


def generate_waves(
    txns: list[tuple[bytes, ft.Txn]],
    extras: list[tuple[list[bytes], list[bytes]] | None] | None = None,
) -> list[list[int]]:
    """Partition txn indices into conflict-free waves, equivalent to
    serial block order: a writer lands strictly after every earlier
    reader AND writer of each of its accounts; a reader lands strictly
    after every earlier writer (readers may share a wave).  No
    gap-filling below a conflict — that would let a later txn's effects
    become visible to an earlier txn (the property the reference's wave
    generation preserves, fd_runtime.c:1717-1736)."""
    waves: list[list[int]] = []
    last_w: dict[bytes, int] = {}  # acct -> last wave with a writer
    last_r: dict[bytes, int] = {}  # acct -> last wave with a reader
    for i, (payload, desc) in enumerate(txns):
        w, r = _rw_sets(payload, desc,
                        extras[i] if extras is not None else None)
        wi = 0
        for a in w:
            wi = max(wi, last_w.get(a, -1) + 1, last_r.get(a, -1) + 1)
        for a in r:
            wi = max(wi, last_w.get(a, -1) + 1)
        while wi >= len(waves):
            waves.append([])
        waves[wi].append(i)
        for a in w:
            last_w[a] = max(last_w.get(a, -1), wi)
        for a in r:
            last_r[a] = max(last_r.get(a, -1), wi)
    return waves


_DEFAULT_EXECUTOR: Executor | None = None


def default_executor() -> Executor:
    global _DEFAULT_EXECUTOR
    if _DEFAULT_EXECUTOR is None:
        _DEFAULT_EXECUTOR = Executor()
    return _DEFAULT_EXECUTOR


def default_sysvars(slot: int) -> dict:
    """The sysvar blobs programs read via sol_get_*_sysvar: clock at the
    executing slot, default rent and epoch schedule (grows alongside the
    bank state)."""
    from firedancer_tpu.flamenco import types as T

    import hashlib as _hl

    sched = T.EpochSchedule()
    epoch = slot // sched.slots_per_epoch
    return {
        "clock": T.CLOCK.encode(T.Clock(slot=slot, epoch=epoch)),
        "rent": T.RENT.encode(T.Rent()),
        "epoch_schedule": T.EPOCH_SCHEDULE.encode(sched),
        # recent bank hashes the vote program validates against; the
        # caller (replay/consensus) supplies real entries via
        # execute_block(slot_hashes=...) — empty means votes reject
        "slot_hashes": T.SLOT_HASHES.encode([]),
        # Fees { fee_calculator: { lamports_per_signature } }
        "fees": LAMPORTS_PER_SIGNATURE.to_bytes(8, "little"),
        # EpochRewards: distribution_starting_block_height u64 |
        # num_partitions u64 | parent_blockhash 32 | total_points u128 |
        # total_rewards u64 | distributed_rewards u64 | active bool —
        # inactive outside the distribution window
        "epoch_rewards": bytes(8 + 8 + 32 + 16 + 8 + 8 + 1),
        "last_restart_slot": (0).to_bytes(8, "little"),
        # the slot's blockhash view for the nonce family; execute_block
        # overrides with the real parent bank hash
        "recent_blockhash": _hl.sha256(
            b"fdtpu:rbh:" + slot.to_bytes(8, "little")
        ).digest(),
    }


def _advance_nonce_account(funk, xid, payload, desc, addrs, sysvars) -> None:
    """A FAILED durable-nonce txn still advances its nonce account: the
    fee debit and the rotated nonce are the txn's on-chain footprint
    (fd_runtime.c saves the advanced nonce for failed txns too) — else,
    once StatusCache.purge_below prunes the signature, the identical
    signed txn passes durable_nonce_ok again and re-lands."""
    from firedancer_tpu.flamenco import nonce as _n

    ins = desc.instrs[0]
    key = addrs[payload[ins.acct_off]]
    lam, owner, ex, data = acct_decode(funk.rec_query(xid, key))
    state, auth, _cur = _n.decode_state(data)
    if state != _n.STATE_INIT:
        return
    bh = (sysvars or {}).get("recent_blockhash")
    if not bh:
        return
    data = bytearray(data)
    data[: _n.DATA_LEN] = _n.encode_state(
        _n.STATE_INIT, auth, _n.next_nonce(bh, key)
    )
    funk.rec_insert(xid, key, acct_encode(lam, owner, ex, bytes(data)))


def _execute_txn(
    funk: Funk, xid: bytes, payload: bytes, desc: ft.Txn,
    executor: Executor | None = None,
    sysvars: dict | None = None,
    extra: tuple[list[bytes], list[bytes]] | None = None,
    durable_nonce: bool = False,
) -> TxnResult:
    from firedancer_tpu.flamenco.programs import AcctError, FundsError

    executor = executor or default_executor()
    addrs = desc.acct_addrs(payload)
    if desc.addr_luts:
        if extra is None:
            # lookup resolution failed (missing/foreign/short table or
            # index out of range): typed per-txn failure, block continues
            return TxnResult(TXN_ERR_ACCT, 0)
        # combined index space: static, then loaded-writable, then
        # loaded-readonly — matching Txn.is_writable
        addrs = addrs + extra[0] + extra[1]
    if len(set(addrs)) != len(addrs):
        # AccountLoadedTwice analog: duplicate addresses would load as
        # independent copies — stale reads + lamport mint/burn at commit
        return TxnResult(TXN_ERR_ACCT, 0)
    payer = addrs[0]
    fee = LAMPORTS_PER_SIGNATURE * desc.signature_cnt
    payer_val = funk.rec_query(xid, payer)
    if acct_lamports(payer_val) < fee:
        return TxnResult(TXN_ERR_FEE, 0)
    # charge the fee unconditionally (failed txns still pay, fd_executor);
    # written straight to funk so program failure cannot roll it back
    plam, powner, pex, pdata = acct_decode(payer_val)
    funk.rec_insert(xid, payer, acct_encode(plam - fee, powner, pex, pdata))

    def _fail(status: int) -> TxnResult:
        # fee-charged failure: a durable-nonce txn's nonce must rotate
        # even though every other program effect is discarded
        if durable_nonce:
            _advance_nonce_account(funk, xid, payload, desc, addrs, sysvars)
        return TxnResult(status, fee)

    # load the unique account set into host objects; program effects land
    # in funk only at commit, so failure = skip the writeback (fee stays)
    accounts = [
        Account.from_value(a, funk.rec_query(xid, a)) for a in addrs
    ]
    signer = [i < desc.signature_cnt for i in range(len(addrs))]
    writable = [desc.is_writable(i) for i in range(len(addrs))]
    baseline = [a.to_value() for a in accounts]
    # the txn's requested compute budget + heap (SetComputeUnitLimit /
    # RequestHeapFrame) drive execution — pack only *costs* them; here
    # they are ENFORCED (the r3 gap: VM budget was fixed at 200k)
    from firedancer_tpu.pack.cost import txn_budget

    budget = txn_budget(payload, desc)
    if budget is None:
        # malformed compute-budget instruction: typed failure, fee stays
        # charged (pack's cost model would have dropped it pre-block)
        return _fail(TXN_ERR_PROGRAM)
    cu_limit, heap_size = budget
    # resolve upgradeable programs' programdata up front (the reference's
    # account loader does the same indirection, fd_executor.c load path);
    # a broken indirection surfaces as a typed failure at invoke time
    from firedancer_tpu.flamenco import bpf_loader as bl

    program_elfs: dict = {}
    for a in accounts:
        if a.executable and a.owner == bl.UPGRADEABLE_LOADER_PROGRAM:
            try:
                pd_addr = bl.program_programdata(bytes(a.data))
                pd_val = funk.rec_query(xid, pd_addr)
                _lam, _owner, _ex, pd_data = acct_decode(pd_val)
                deploy_slot, _auth = bl.programdata_meta(pd_data)
                program_elfs[a.key] = (bl.programdata_elf(pd_data),
                                       deploy_slot)
            except InstrError:
                pass  # left unresolved: invocation fails typed
    ctx = TxnCtx(accounts=accounts, signer=signer, writable=writable,
                 sysvars=sysvars or {}, budget=cu_limit,
                 heap_size=heap_size, program_elfs=program_elfs,
                 instr_datas=[
                     payload[i.data_off : i.data_off + i.data_sz]
                     for i in desc.instrs
                 ])

    for ins in desc.instrs:
        if ins.program_id >= len(addrs):
            return _fail(TXN_ERR_ACCT)
        prog = addrs[ins.program_id]
        data = payload[ins.data_off : ins.data_off + ins.data_sz]
        idx = payload[ins.acct_off : ins.acct_off + ins.acct_cnt]
        if any(i >= len(addrs) for i in idx):
            # ALT-loaded index: unresolvable until the address-resolution
            # stage exists — a typed failure, never an abort of the block
            return _fail(TXN_ERR_ACCT)
        iaccts = [InstrAccount(i, signer[i], writable[i]) for i in idx]
        try:
            executor.execute_instr(ctx, prog, iaccts, data)
        except FundsError:
            return _fail(TXN_ERR_INSUFFICIENT_FUNDS)
        except AcctError:
            return _fail(TXN_ERR_ACCT)
        except InstrError:
            return _fail(TXN_ERR_PROGRAM)
        except (ValueError, IndexError, KeyError, OverflowError):
            # instruction data/accounts are ATTACKER input; a native
            # program tripping an untyped exception is a failed txn,
            # never a block abort (defense in depth on top of the typed
            # errors — one crafted txn must not kill replay)
            return _fail(TXN_ERR_PROGRAM)

    # commit: writes may only land on accounts the wave generator saw as
    # writable, or concurrent wave execution diverges from serial order.
    # Validate EVERYTHING before the first insert — a partial commit
    # would break the "fee charged, no effects" failure contract.
    changed = []
    for i, a in enumerate(accounts):
        val = a.to_value()
        if val == baseline[i]:
            continue
        if not writable[i]:
            return _fail(TXN_ERR_ACCT)
        changed.append((a.key, val))
    for key, val in changed:
        funk.rec_insert(xid, key, val)
    return TxnResult(TXN_SUCCESS, fee)


class SlotExecution:
    """Incremental slot execution: the per-txn gate + execute + seal
    machinery shared by `execute_block` (the batch/replay path) and the
    pipeline's bank stages (the streaming leader path — the reference's
    bank tile commits into one live bank the same way,
    /root/reference/src/app/fdctl/run/tiles/fd_bank.c:186-241).

    Lifecycle: construct (prepares a funk fork), `execute()` txns as they
    arrive, `seal(poh_hash)` to finalize the bank hash, then `publish()`
    or `abandon()` once consensus picks the fork."""

    def __init__(
        self,
        funk: Funk,
        *,
        slot: int,
        parent_bank_hash: bytes = b"\x00" * 32,
        parent_xid: bytes | None = None,
        executor: Executor | None = None,
        status_cache=None,
        ancestors: set[int] | None = None,
        slot_hashes: list[tuple[int, bytes]] | None = None,
    ):
        self.funk = funk
        self.slot = slot
        self.parent_bank_hash = parent_bank_hash
        self.parent_xid = parent_xid
        self.executor = executor
        self.status_cache = status_cache
        self.ancestors = ancestors
        # xid carries a nonce: competing blocks for the SAME slot off the
        # same parent are distinct forks (consensus decides which
        # publishes).  The parent rides along as a digest, not verbatim —
        # embedding the full parent xid grows the key by ~15 bytes per
        # unpublished ancestor, and a partitioned fork chain blows past
        # the native funk's FFK_XID_MAX (128) within a handful of slots.
        self.xid = b"slot:%d:%d:%s" % (
            slot, next(_xid_seq),
            hashlib.sha256(parent_xid).hexdigest()[:24].encode()
            if parent_xid else b"root")
        funk.txn_prepare(parent_xid, self.xid)
        self.sysvars = default_sysvars(slot)
        # durable nonces advance against the PARENT's bank hash: fresh,
        # deterministic, and fixed before any txn in this block runs
        self.sysvars["recent_blockhash"] = parent_bank_hash
        if slot_hashes is not None:
            from firedancer_tpu.flamenco import types as T

            self.sysvars["slot_hashes"] = T.SLOT_HASHES.encode(
                [T.SlotHash(s, h) for s, h in slot_hashes]
            )
        if status_cache is not None:
            status_cache.begin_block(self.xid, slot)
        # intra-block duplicates are tracked locally, NOT via the cache
        # with a widened ancestor set: cache insertions from a speculative
        # competing block at this same slot must never gate this block
        self._block_seen: set[tuple[bytes, bytes]] = set()
        # unrooted ancestor blocks gate too: their entries are still
        # STAGED in the status cache (publish hasn't folded them), but a
        # txn one of them carries must answer ALREADY_PROCESSED here —
        # the exactly-once contract across leader handoffs on one fork
        self._ancestor_xids: tuple[bytes, ...] = (
            tuple(funk.txn_ancestry(parent_xid))
            if parent_xid is not None else ()
        )
        # native executor fast lane (flamenco/exec_native.py), built
        # lazily on the first execute_batch; False = unavailable/disabled
        self._native_ctx = None
        self._native_sh_blob = None
        # slot-scoped native session (ISSUE 9 bank-lane residual): the
        # C++ side keeps the status-cache gate + an account-value overlay
        # across microblocks, so Python ships each account's value ONCE
        # (first touch, or after a Python-lane write dirties it) and
        # skips the per-txn gate checks entirely
        self._native_session = None
        self._native_poisoned = False  # a failed call leaves the session
        #                                stale: python lane for the rest
        self._gate_seen_delta: list[bytes] = []  # 96B bh||sig, py-landed
        self._gate_seeded = False
        self._gate_shipped_version = None  # StatusCache.version last sent
        self._native_known: set[bytes] = set()  # addrs the session holds
        self._native_dirty: set[bytes] = set()  # py-written since sync
        self._table_cache: dict = {}  # ALT decode, once per block
        self._before: dict[bytes, bytes | None] = {}  # start-of-slot view
        # native shm funk: seal() reads before/after pairs from the fork
        # overlay in one txn_diff crossing, so the per-write _before
        # snapshot maintenance on the drain path is dead weight
        self._funk_diff = hasattr(funk, "txn_diff")
        self.results: list[TxnResult] = []
        # interned TxnResults for the sweep drain: a burst of landed
        # transfers repeats a handful of (status, fee) pairs
        self._txnres_cache: dict[tuple, TxnResult] = {}
        # native-lane accounting, read by the bank stage's metrics: txns
        # committed by the C++ lane vs. punted back to the Python lane
        self.native_done_cnt = 0
        self.native_punt_cnt = 0
        self.signature_cnt = 0
        self.sealed: BlockResult | None = None

    def resolve(self, payload: bytes, desc: ft.Txn):
        """Resolve v0 address-table lookups against the START-of-slot
        state (in-block table extensions become visible next slot —
        Agave's visibility rule).  None = typed lookup failure."""
        if not desc.addr_luts:
            return ([], [])
        from firedancer_tpu.flamenco import alt as falt

        try:
            return falt.resolve_lookups(
                payload, desc,
                lambda k: self.funk.rec_query(self.parent_xid, k),
                slot=self.slot, table_cache=self._table_cache,
            )
        except falt.LookupError_:
            return None

    def execute(
        self, payload: bytes, desc: ft.Txn,
        extra: tuple[list[bytes], list[bytes]] | None | bool = False,
    ) -> TxnResult:
        """Gate + execute one txn on this slot's fork.  `extra` is the
        pre-resolved ALT addresses (pass the default to resolve here)."""
        if extra is False:
            extra = self.resolve(payload, desc)
        # snapshot the start-of-slot value of every account this txn can
        # touch, for the accounts-delta hash (query the PARENT view: an
        # earlier in-block writer must not shift this txn's "before")
        touched = desc.acct_addrs(payload) + (
            extra[0] + extra[1] if extra else []
        )
        for a in touched:
            if a not in self._before:
                self._before[a] = self.funk.rec_query(self.parent_xid, a)
        durable = False
        bh = sig = None
        if self.status_cache is not None:
            bh = desc.recent_blockhash(payload)
            sig = desc.signatures(payload)[0]
            if not self.status_cache.is_blockhash_valid(bh, self.slot):
                from firedancer_tpu.flamenco import nonce as _nonce

                if not _nonce.durable_nonce_ok(self.funk, self.xid,
                                               payload, desc):
                    r = TxnResult(TXN_ERR_BLOCKHASH, 0)
                    self.results.append(r)
                    return r
                durable = True
            if (bh, sig) in self._block_seen or self.status_cache.contains(
                bh, sig, self.ancestors
            ) or self.status_cache.contains_staged(
                bh, sig, self._ancestor_xids
            ):
                r = TxnResult(TXN_ERR_ALREADY_PROCESSED, 0)
                self.results.append(r)
                return r
        if self._native_session is not None:
            # this Python-lane execution may write any touched account:
            # the native session's cached values go stale until resynced
            # on next touch (the dirty set ships a fresh have=1 value).
            # Marked HERE — after the gate — so gated-out txns (which
            # can never write) don't churn the session's value cache.
            self._native_dirty.update(touched)
        r = _execute_txn(self.funk, self.xid, payload, desc,
                         executor=self.executor, sysvars=self.sysvars,
                         extra=extra, durable_nonce=durable)
        return self._finish(r, desc.signature_cnt, bh, sig)

    def _finish(self, r: TxnResult, sig_cnt: int, bh, sig,
                native: bool = False) -> TxnResult:
        """Post-execution bookkeeping shared by the Python and native
        lanes — the two must never disagree on the landed predicate."""
        if r.fee > 0:
            # the bank hash's signature count covers txns that LANDED
            # (fee-charged; dropped/gated txns leave no on-chain
            # footprint) — so a streaming leader and a replayer counting
            # only the recorded txns agree on the hash
            self.signature_cnt += sig_cnt
            if self.status_cache is not None:
                # any fee-charged txn occupies its signature (failed txns
                # landed on chain too — fd_txncache records both); staged
                # until the fork is chosen
                self._block_seen.add((bh, sig))
                self.status_cache.stage_insert(self.xid, bh, sig)
                if not native and self._native_session is not None \
                        and bh is not None and sig is not None:
                    # python-lane landing: the native gate learns it on
                    # the next crossing (native landings were inserted by
                    # the C++ side already)
                    self._gate_seen_delta.append(bh + sig)
        self.results.append(r)
        return r

    # -- native fast lane (flamenco/exec_native.py) ---------------------------

    def _native_for_batch(self):
        """The slot's native BatchContext, or None (disabled/unavailable).
        Rebuilt if the slot-hashes sysvar blob was swapped out."""
        if self._native_poisoned:
            return None
        sh = self.sysvars.get("slot_hashes")
        if self._native_ctx is None or self._native_sh_blob is not sh:
            from firedancer_tpu.flamenco import exec_native

            self._native_sh_blob = sh
            self._native_ctx = False
            if exec_native.available():
                clock_slot = clock_epoch = None
                blob = self.sysvars.get("clock")
                if blob:
                    from firedancer_tpu.flamenco import types as T

                    try:
                        c = T.CLOCK.decode(blob, 0)[0]
                        clock_slot, clock_epoch = c.slot, c.epoch
                    except T.CodecError:
                        pass  # no clock: vote txns fail typed, both lanes
                # rent env for the nonce partial-withdraw floor: flag 2
                # = blob present but undecodable (the C++ side punts at
                # the point of use; the Python lane owns that path)
                from firedancer_tpu.flamenco import types as T

                _rd = T.Rent()  # absent blob -> defaults (nonce.py)
                rent_flag = 1
                rent_lpby = _rd.lamports_per_byte_year
                rent_et = _rd.exemption_threshold
                rent_blob = self.sysvars.get("rent")
                if rent_blob:
                    try:
                        r = T.RENT.decode(rent_blob, 0)[0]
                        rent_lpby = r.lamports_per_byte_year
                        rent_et = r.exemption_threshold
                    except T.CodecError:
                        rent_flag = 2
                try:
                    if self._native_session is None:
                        # one session per SlotExecution: the overlay and
                        # gate survive a BatchContext rebuild (only the
                        # sysvar header changes)
                        self._native_session = exec_native.Session()
                    self._native_ctx = exec_native.BatchContext(
                        lamports_per_sig=LAMPORTS_PER_SIGNATURE,
                        clock_slot=clock_slot,
                        clock_epoch=clock_epoch,
                        slot_hashes=sh,
                        session=self._native_session,
                        recent_blockhash=self.sysvars.get(
                            "recent_blockhash"),
                        rent=(rent_flag, rent_lpby, rent_et),
                    )
                except exec_native.NativeUnavailable:
                    pass
        return self._native_ctx or None

    def _gate_args(self):
        """(valid_blockhashes | None, seen_delta) for the next native
        crossing — valid_blockhashes is None when the registry hasn't
        changed since last shipped (the session keeps its set; flag 2 on
        the wire), so steady state ships only the seen delta.  Returns
        None when there is no status cache (the Python lane does not
        gate either, so neither should the native side)."""
        sc = self.status_cache
        if sc is None:
            return None
        if sc.version == self._gate_shipped_version:
            valid = None
        else:
            valid = [bh for bh in sc.blockhash_slot
                     if sc.is_blockhash_valid(bh, self.slot)]
        if not self._gate_seeded:
            if valid is None:  # first call always ships the set
                valid = [bh for bh in sc.blockhash_slot
                         if sc.is_blockhash_valid(bh, self.slot)]
            # one-time seed: everything already visible to contains()
            # on this fork (committed ancestor entries + anything this
            # block landed before the session armed)
            self._gate_seeded = True
            vs = set(valid)
            for (bh, sig), slots in sc.seen.items():
                if bh in vs and (
                    self.ancestors is None
                    or any(s in self.ancestors for s in slots)
                ):
                    self._gate_seen_delta.append(bh + sig)
            # unrooted ancestor blocks' staged landings gate natively too
            # (the Python gate's contains_staged, shipped once)
            staged = getattr(sc, "_staged_seen", {})
            for x in self._ancestor_xids:
                for bh, sig in staged.get(x, ()):
                    if bh in vs:
                        self._gate_seen_delta.append(bh + sig)
            for bh, sig in self._block_seen:
                self._gate_seen_delta.append(bh + sig)
        if valid is not None:
            self._gate_shipped_version = sc.version
        return (valid, self._gate_seen_delta)

    def _poison_native(self) -> None:
        """A failed native call leaves the session overlay unsynced:
        disable the lane for the rest of this slot (python lane owns it)."""
        self._native_poisoned = True
        self._native_ctx = False
        if self._native_session is not None:
            self._native_session.close()
            self._native_session = None

    # -- bank sweep client (native/fd_bank.cpp via runtime/bank_native) -------

    def native_sync(self) -> bool:
        """Re-arm the C session before a bank sweep with ONE zero-txn
        crossing: the status-cache gate delta (Python-lane landings +
        valid-set changes) and refresh records for every dirty account
        (Python-lane writes since the last sync).  The sweep client
        builds its own requests with no per-account values (the session
        overlay is its only source), so this is the lane's whole
        coherence protocol.  No-op when already coherent; returns False
        when the native lane is unavailable/poisoned (the caller must
        not let the sweep run)."""
        nat = self._native_for_batch()
        if nat is None or self._native_session is None:
            return False
        sc = self.status_cache
        dirty = self._native_dirty
        need_gate = sc is not None and (
            not self._gate_seeded
            or sc.version != self._gate_shipped_version
            or bool(self._gate_seen_delta)
        )
        if not need_gate and not dirty:
            return True
        from firedancer_tpu.flamenco import exec_native

        gate = self._gate_args()
        n_delta = len(gate[1]) if gate is not None else 0
        refresh = []
        if dirty:
            q = self.funk.rec_query
            for a in dirty:
                refresh.append((a, q(self.xid, a) or b""))
        try:
            nat.run([], gate=gate, refresh=refresh)
        except exec_native.NativeUnavailable:
            self._poison_native()
            return False
        if n_delta:
            del self._gate_seen_delta[:n_delta]
        if refresh:
            self._native_known.update(a for a, _v in refresh)
            dirty.clear()
        return True

    def native_apply_rec(self, payload: bytes, desc_bytes: bytes,
                         status: int, fee: int, writes) -> TxnResult:
        """Apply one sweep-committed txn record (the C side already ran
        it against the session): funk writes, start-of-slot snapshots,
        and the shared landed bookkeeping.  writes: [(acct_idx, value)]
        with indices into the packed descriptor's account table."""
        db = desc_bytes
        bh = sig = None
        if fee > 0 and self.status_cache is not None:
            sig_off = db[2] | (db[3] << 8)
            bh_off = db[11] | (db[12] << 8)
            bh = payload[bh_off : bh_off + 32]
            sig = payload[sig_off : sig_off + 64]
        if writes:
            acct_off = db[9] | (db[10] << 8)
            before = self._before
            q = self.funk.rec_query
            known = self._native_known
            dirty = self._native_dirty
            for idx, val in writes:
                a = payload[acct_off + 32 * idx : acct_off + 32 * (idx + 1)]
                if not self._funk_diff and a not in before:
                    before[a] = q(self.parent_xid, a)
                self.funk.rec_insert(self.xid, a, val)
                known.add(a)
                dirty.discard(a)
        self.native_done_cnt += 1
        return self._finish(TxnResult(status, fee), db[1], bh, sig,
                            native=True)

    def native_apply_batch(self, txns) -> list[TxnResult]:
        """One sweep group's committed records in a single pass —
        semantically native_apply_rec over each (payload, desc_bytes,
        status, fee, writes) tuple, but the funk txn resolves/validates
        once for the whole batch and every per-txn attribute chase is
        hoisted to a local.  This is the drain's per-txn floor: the C
        side already ran the txns, so everything left here is
        authoritative-state application."""
        before = self._before
        q = self.funk.rec_query
        recs_d = self.funk.txn_recs_for_write(self.xid)
        known = self._native_known
        dirty = self._native_dirty
        pxid = self.parent_xid
        xid = self.xid
        sc = self.status_cache
        block_seen = self._block_seen
        stage_insert = sc.stage_insert if sc is not None else None
        results = self.results
        track_before = not self._funk_diff
        out = []
        sig_cnt = 0
        for payload, db, status, fee, writes in txns:
            if writes:
                acct_off = db[9] | (db[10] << 8)
                for idx, val in writes:
                    a = payload[acct_off + 32 * idx:acct_off + 32 * (idx + 1)]
                    if track_before and a not in before:
                        before[a] = q(pxid, a)
                    recs_d[a] = val if type(val) is bytes else bytes(val)
                    known.add(a)
                    dirty.discard(a)
            self.native_done_cnt += 1
            r = TxnResult(status, fee)
            if fee > 0:
                sig_cnt += db[1]
                if stage_insert is not None:
                    sig_off = db[2] | (db[3] << 8)
                    bh_off = db[11] | (db[12] << 8)
                    bh = payload[bh_off : bh_off + 32]
                    sig = payload[sig_off : sig_off + 64]
                    block_seen.add((bh, sig))
                    stage_insert(xid, bh, sig)
            results.append(r)
            out.append(r)
        self.signature_cnt += sig_cnt
        return out

    def native_apply_group(self, frags, recs) -> tuple:
        """One FULLY-published sweep group straight off the frag bytes —
        semantically native_apply_batch over (frag[:psz], frag[psz:-2],
        status, fee, writes) tuples, but the drain's published!=0 path
        needs only the accounting, so the payload/descriptor slices are
        never materialized.  With the native funk plane armed the record
        stream arrives stripped (the values already live in the shm map)
        and the only per-txn slices left are the bh/sig pair the status
        cache keys on.  Returns (n_ok, n_fail, n_rej)."""
        before = self._before
        q = self.funk.rec_query
        recs_d = self.funk.txn_recs_for_write(self.xid)
        known = self._native_known
        dirty = self._native_dirty
        pxid = self.parent_xid
        xid = self.xid
        sc = self.status_cache
        if sc is not None:
            # stage_insert unrolled: the two per-xid structure probes
            # hoist out of the loop (one staged batch per group)
            staged_append = sc._staged[xid][1].append
            staged_add = sc._staged_seen[xid].add
        else:
            staged_append = None
        seen_add = self._block_seen.add
        res_append = self.results.append
        # landed transfers repeat the same (status, fee) almost every
        # txn: intern the TxnResults (readers never mutate them — the
        # dataclass exists to carry the pair out of the slot)
        res_cache = self._txnres_cache
        track_before = not self._funk_diff
        n_ok = n_fail = n_rej = 0
        sig_cnt = 0
        for frag, (status, fee, writes) in zip(frags, recs):
            psz = frag[-2] | (frag[-1] << 8)
            if writes:
                acct_off = frag[psz + 9] | (frag[psz + 10] << 8)
                for idx, val in writes:
                    a = frag[acct_off + 32 * idx : acct_off + 32 * (idx + 1)]
                    if track_before and a not in before:
                        before[a] = q(pxid, a)
                    recs_d[a] = val if type(val) is bytes else bytes(val)
                    known.add(a)
                    dirty.discard(a)
            if fee > 0:
                n_ok += 1
                if status != TXN_SUCCESS:
                    n_fail += 1
                sig_cnt += frag[psz + 1]
                if staged_append is not None:
                    sig_off = frag[psz + 2] | (frag[psz + 3] << 8)
                    bh_off = frag[psz + 11] | (frag[psz + 12] << 8)
                    t = (frag[bh_off : bh_off + 32],
                         frag[sig_off : sig_off + 64])
                    seen_add(t)
                    staged_append(t)
                    staged_add(t)
            else:
                n_rej += 1
            r = res_cache.get((status, fee))
            if r is None:
                r = TxnResult(status, fee)
                if len(res_cache) < 64:
                    res_cache[(status, fee)] = r
            res_append(r)
        self.native_done_cnt += n_ok + n_rej
        self.signature_cnt += sig_cnt
        return n_ok, n_fail, n_rej

    @staticmethod
    def _unpack_trailer(payload: bytes, desc_bytes: bytes) -> ft.Txn:
        """Packed trailer -> validated Txn (decode_verified's contract)."""
        try:
            desc, end = ft.txn_unpack(desc_bytes)
        except Exception as e:
            raise ValueError(f"packed descriptor unparseable: {e}") from e
        if end != len(desc_bytes):
            raise ValueError("packed descriptor trailer size mismatch")
        if not ft.txn_desc_valid(desc, len(payload)):
            raise ValueError("packed descriptor fails validation")
        return desc

    def execute_batch(self, items) -> list[TxnResult]:
        """Execute a burst of txns in block order, routing runs of
        native-eligible txns through one FFI call each (the bank stage's
        per-microblock commit path).  items: (payload, desc, desc_bytes)
        tuples — desc (a Txn) or desc_bytes (the packed trailer) may be
        None, not both.  Anything the native lane cannot take — Python
        lane programs, lookup tables, stale blockhashes (durable-nonce
        candidates), duplicate signatures — flushes the pending run and
        goes through `execute` unchanged."""
        base = len(self.results)
        nat = self._native_for_batch()
        if nat is not None:
            from firedancer_tpu.flamenco.exec_native import eligible_packed
        # session mode: the C++ side owns the status-cache gate + the
        # account-value overlay, so the per-txn python gate checks and
        # the per-call funk value marshalling disappear (ISSUE 9)
        session = self._native_session if nat is not None else None
        pend: list[list] = []   # [payload, desc_bytes, addrs, vals, bh, sig, sig_cnt]
        pend_keys: set = set()

        def fallback(payload, desc, desc_bytes):
            if desc is None:
                desc = self._unpack_trailer(payload, desc_bytes)
            self.execute(payload, desc)

        def flush():
            if pend:
                self._flush_native(nat, pend, session)
                pend.clear()
                pend_keys.clear()

        for payload, desc, desc_bytes in items:
            if nat is None or self._native_poisoned:
                # poisoned mid-batch: the cached locals point at a dead
                # session — stop marshalling into it and finish on the
                # Python lane immediately
                fallback(payload, desc, desc_bytes)
                continue
            if desc_bytes is None:
                desc_bytes = ft.txn_pack(desc)
            psz = len(payload)
            db = desc_bytes
            if len(db) < 17:
                flush()
                fallback(payload, desc, desc_bytes)
                continue
            sig_cnt = db[1]
            sig_off = db[2] | (db[3] << 8)
            acct_cnt = db[8]
            acct_off = db[9] | (db[10] << 8)
            bh_off = db[11] | (db[12] << 8)
            if (
                db[13]  # lut_cnt: the ALT-resolution path is Python's
                or sig_cnt == 0
                or acct_cnt == 0
                or sig_off + 64 > psz
                or bh_off + 32 > psz
                or acct_off + 32 * acct_cnt > psz
                or not eligible_packed(payload, db)
            ):
                flush()
                fallback(payload, desc, desc_bytes)
                continue
            bh = payload[bh_off : bh_off + 32]
            sig = payload[sig_off : sig_off + 64]
            if session is None and self.status_cache is not None and (
                not self.status_cache.is_blockhash_valid(bh, self.slot)
                or (bh, sig) in pend_keys
                or (bh, sig) in self._block_seen
                or self.status_cache.contains(bh, sig, self.ancestors)
                or self.status_cache.contains_staged(bh, sig,
                                                     self._ancestor_xids)
            ):
                # legacy (session-less) path: stale blockhash
                # (durable-nonce candidate) or duplicate — the Python
                # gate owns these; a pending-run twin must land first so
                # the duplicate gate sees it.  With a session the C++
                # gate decides in-line instead.
                flush()
                fallback(payload, desc, desc_bytes)
                continue
            addrs = []
            vals = []
            q = self.funk.rec_query
            before = self._before
            if session is not None:
                known = self._native_known
                dirty = self._native_dirty
                for i in range(acct_cnt):
                    a = payload[acct_off + 32 * i : acct_off + 32 * (i + 1)]
                    addrs.append(a)
                    if a not in before:
                        before[a] = q(self.parent_xid, a)
                    if a in known and a not in dirty:
                        vals.append(None)  # the session holds it current
                    else:
                        vals.append(q(self.xid, a) or b"")
                        known.add(a)
                        dirty.discard(a)
            else:
                for i in range(acct_cnt):
                    a = payload[acct_off + 32 * i : acct_off + 32 * (i + 1)]
                    addrs.append(a)
                    if a not in before:
                        before[a] = q(self.parent_xid, a)
                    vals.append(q(self.xid, a))
                pend_keys.add((bh, sig))
            pend.append([payload, desc_bytes, addrs, vals, bh, sig, sig_cnt])
        flush()
        return self.results[base:]

    def _run_gated(self, entry) -> None:
        """Python-lane execution for an already-gated native entry (a
        C++ punt on the legacy session-less path): fresh blockhash, not
        a duplicate, no lookup tables."""
        payload, desc_bytes, _addrs, _vals, bh, sig, sig_cnt = entry
        desc = self._unpack_trailer(payload, desc_bytes)
        r = _execute_txn(self.funk, self.xid, payload, desc,
                         executor=self.executor, sysvars=self.sysvars,
                         extra=([], []), durable_nonce=False)
        self._finish(r, sig_cnt, bh, sig)

    def _run_ungated(self, entry) -> None:
        """Python-lane execution for an UNGATED native entry (a session
        punt: the C++ gate stopped before deciding — possibly a stale
        blockhash / durable-nonce candidate): the full execute() path
        owns gating, _before snapshots, and dirty-marking."""
        payload, desc_bytes = entry[0], entry[1]
        desc = self._unpack_trailer(payload, desc_bytes)
        self.execute(payload, desc, ([], []))

    def _flush_native(self, nat, pend: list, session=None) -> None:
        """Run the pending native-eligible txns in order: one FFI call
        per run, punts re-routed through the Python lane, and the
        remainder resubmitted.  Session mode: account values live in
        the C++ overlay across calls, so no per-call refresh loop; the
        gate delta rides the same crossing."""
        from firedancer_tpu.flamenco import exec_native

        i = 0
        while i < len(pend):
            chunk = pend[i:]
            gate = self._gate_args() if session is not None else None
            n_delta = len(gate[1]) if gate else 0
            try:
                if session is not None:
                    n_done, punted, recs = nat.run(chunk, gate=gate)
                else:
                    n_done, punted, recs = nat.run(chunk)
            except exec_native.NativeUnavailable:
                if session is not None:
                    # the session overlay may be out of sync with funk
                    # now: retire it for the rest of the slot
                    self._poison_native()
                    for entry in chunk:
                        self._run_ungated(entry)
                else:
                    # oversized response / native wedge: finish in Python
                    for entry in chunk:
                        self._run_gated(entry)
                return
            if n_delta:
                # the session absorbed these python-lane landings
                del self._gate_seen_delta[:n_delta]
            for entry, (status, fee, writes) in zip(chunk, recs):
                addrs = entry[2]
                for idx, val in writes:
                    self.funk.rec_insert(self.xid, addrs[idx], val)
                self._finish(TxnResult(status, fee), entry[6], entry[4],
                             entry[5], native=True)
            i += n_done
            self.native_done_cnt += n_done
            if punted and i < len(pend):
                self.native_punt_cnt += 1
                if session is not None:
                    self._run_ungated(pend[i])
                    i += 1
                    # the punt ran on the Python lane and dirtied its
                    # accounts: remainder entries marked session-known
                    # (vals None) for those accounts must re-ship fresh
                    # values — the first shipper re-syncs the session
                    dirty = self._native_dirty
                    if dirty:
                        for entry in pend[i:]:
                            vals = entry[3]
                            for j, a in enumerate(entry[2]):
                                if a in dirty:
                                    vals[j] = self.funk.rec_query(
                                        self.xid, a) or b""
                                    dirty.discard(a)
                else:
                    self._run_gated(pend[i])
                    i += 1
            elif n_done == 0 and not punted:
                # defensive: a native lane that makes no progress must
                # not spin — finish the remainder in Python
                for entry in pend[i:]:
                    if session is not None:
                        self._run_ungated(entry)
                    else:
                        self._run_gated(entry)
                return
            if i < len(pend) and session is None:
                # legacy path only: refresh the remainder's funk values
                # (the stateless overlay restarts empty each call); the
                # session keeps its own writes and the punt txn's
                # accounts were dirty-marked by execute()
                for entry in pend[i:]:
                    entry[3] = [self.funk.rec_query(self.xid, a)
                                for a in entry[2]]

    def seal(self, poh_hash: bytes = b"\x00" * 32,
             waves: list[list[int]] | None = None) -> BlockResult:
        """Finalize: accounts-delta lattice hash (one device reduction
        over +new / -old) chained into the bank hash."""
        vals = []
        signs = []
        diff_fn = getattr(self.funk, "txn_diff", None)
        if diff_fn is not None:
            # native shm store: the slot's whole before/after read-out is
            # ONE FFI crossing over the fork's own overlay.  Equivalent
            # to the _before walk — an account touched but never written
            # has before == after and cancels out of the lattice sum, and
            # the overlay's parent view IS the start-of-slot value
            # (parent overlays freeze while this fork is live).
            pairs = ((a, bef, aft) for a, bef, aft in diff_fn(self.xid))
        else:
            q = self.funk.rec_query
            pairs = ((a, self._before[a], q(self.xid, a))
                     for a in self._before)
        for a, before, after in sorted(pairs):
            if after == before:
                continue
            if before is not None:
                vals.append(lt.lthash_of(a + before))
                signs.append(-1)
            if after is not None:
                vals.append(lt.lthash_of(a + after))
                signs.append(1)
        if vals:
            # pad the row count to a power of two (zero rows, sign 0 —
            # the lattice sum is unchanged): a cluster of banks sealing
            # blocks of varying account counts would otherwise compile
            # one XLA reduction per distinct N
            cap = 1 << (len(vals) - 1).bit_length()
            if cap != len(vals):
                vals.extend([lt.lthash_zero()] * (cap - len(vals)))
                signs.extend([0] * (cap - len(signs)))
            delta = np.asarray(
                lt.combine_device(np.stack(vals), np.asarray(signs))
            )
        else:
            delta = lt.lthash_zero()
        bank_hash = hashlib.sha256(
            self.parent_bank_hash
            + hashlib.sha256(delta.tobytes()).digest()
            + self.signature_cnt.to_bytes(8, "little")
            + poh_hash
        ).digest()
        if self.status_cache is not None:
            self.status_cache.stage_blockhash(self.xid, poh_hash)
        self.sealed = BlockResult(
            slot=self.slot,
            bank_hash=bank_hash,
            accounts_delta=delta,
            signature_cnt=self.signature_cnt,
            fees=sum(r.fee for r in self.results),
            results=list(self.results),
            waves=waves if waves is not None else [],
            xid=self.xid,
        )
        return self.sealed

    def publish(self) -> None:
        """Consensus chose this fork: fold it into funk's root."""
        if self.status_cache is not None:
            self.status_cache.commit_block(self.xid)
        self.funk.txn_publish(self.xid)

    def abandon(self) -> None:
        if self.status_cache is not None:
            self.status_cache.drop_block(self.xid)
        self.funk.txn_cancel(self.xid)


def execute_block(
    funk: Funk,
    *,
    slot: int,
    txns: list[bytes],
    parent_bank_hash: bytes = b"\x00" * 32,
    poh_hash: bytes = b"\x00" * 32,
    parent_xid: bytes | None = None,
    publish: bool = False,
    status_cache=None,
    ancestors: set[int] | None = None,
    slot_hashes: list[tuple[int, bytes]] | None = None,
) -> BlockResult:
    """Execute a block's txns on a fresh funk fork; compute the bank hash.

    The fork stays in-prep (consensus decides) unless publish=True.
    status_cache (flamenco/blockstore.StatusCache) arms the two
    consensus-critical txn gates: recent-blockhash currency (150-slot
    age) and cross-slot duplicate-signature rejection (filtered by
    `ancestors` when given — fork awareness).  Executed signatures are
    recorded, and this slot's poh_hash registers as a usable blockhash."""
    parsed = []
    for p in txns:
        t = ft.txn_parse(p)
        if t is None:
            raise ValueError("malformed txn in block")
        parsed.append((p, t))
    sx = SlotExecution(
        funk, slot=slot, parent_bank_hash=parent_bank_hash,
        parent_xid=parent_xid, status_cache=status_cache,
        ancestors=ancestors, slot_hashes=slot_hashes,
    )
    extras = [sx.resolve(p, t) for p, t in parsed]
    waves = generate_waves(parsed, extras)
    order = [i for wave in waves for i in wave]
    # wave txns are conflict-free: host executes in index order, a
    # tpool/device executes them concurrently — same result either way
    for i in order:
        p, t = parsed[i]
        sx.execute(p, t, extra=extras[i])
    # sx.results is in execution order; BlockResult keeps block order
    by_block_order = [None] * len(parsed)
    for pos, i in enumerate(order):
        by_block_order[i] = sx.results[pos]
    sx.results = by_block_order
    result = sx.seal(poh_hash, waves=waves)
    if publish:
        sx.publish()
    # else: the caller owns the fork decision — commit_block(xid) when
    # the fork is chosen, drop_block(xid) when it is abandoned
    return result


def replay_block(
    funk: Funk,
    *,
    slot: int,
    entries: list[tuple[int, bytes, list[bytes]]],
    poh_seed: bytes,
    parent_bank_hash: bytes = b"\x00" * 32,
    parent_xid: bytes | None = None,
    publish: bool = False,
    status_cache=None,
    ancestors: set[int] | None = None,
    slot_hashes: list[tuple[int, bytes]] | None = None,
) -> BlockResult | None:
    """The non-leader path: verify the PoH chain over wire entries, then
    execute the block (fd_replay's after_frag shape).  None = PoH fraud."""
    from firedancer_tpu.runtime import poh as fpoh

    ok, _segments = fpoh.replay_entries(poh_seed, entries)
    if not ok:
        return None
    txns = [p for _, _, txs in entries for p in txs]
    poh_hash = entries[-1][1] if entries else b"\x00" * 32
    return execute_block(
        funk,
        slot=slot,
        txns=txns,
        parent_bank_hash=parent_bank_hash,
        poh_hash=poh_hash,
        parent_xid=parent_xid,
        publish=publish,
        status_cache=status_cache,
        ancestors=ancestors,
        # the replayer's view of recent bank hashes — votes in this
        # block validate against it (empty would reject every vote)
        slot_hashes=slot_hashes,
    )
