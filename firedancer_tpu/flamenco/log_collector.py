"""Program log collector with Agave-compatible truncation.

Counterpart of /root/reference/src/flamenco/log_collector/ (0.7k LoC):
programs emit log lines during execution (the VM's sol_log syscalls);
the collector buffers them per transaction with a byte budget.  The
truncation rule is the protocol's: once the cumulative byte total would
exceed the limit, a single "Log truncated" marker replaces everything
further — partial lines are never emitted.
"""

from __future__ import annotations

DEFAULT_BYTES_LIMIT = 10_000
TRUNCATED_MARKER = "Log truncated"


class LogCollector:
    def __init__(self, bytes_limit: int | None = DEFAULT_BYTES_LIMIT):
        self.bytes_limit = bytes_limit
        self.lines: list[str] = []
        self.bytes_written = 0
        self.truncated = False

    def log(self, line: str | bytes) -> None:
        if isinstance(line, bytes):
            line = line.decode("utf-8", "replace")
        if self.truncated:
            return
        if self.bytes_limit is not None:
            cost = len(line)
            if self.bytes_written + cost > self.bytes_limit:
                self.truncated = True
                self.lines.append(TRUNCATED_MARKER)
                return
            self.bytes_written += cost
        self.lines.append(line)

    # the conventional wrappers programs/runtime emit
    def program_invoke(self, program_id: bytes, depth: int) -> None:
        self.log(f"Program {program_id.hex()} invoke [{depth}]")

    def program_success(self, program_id: bytes) -> None:
        self.log(f"Program {program_id.hex()} success")

    def program_failure(self, program_id: bytes, err: str) -> None:
        self.log(f"Program {program_id.hex()} failed: {err}")

    def sink(self) -> list:
        """A list-like adapter for the VM's log_sink parameter."""

        collector = self

        class _Sink(list):
            def append(self, item):
                collector.log(item)

        return _Sink()
