"""Config native program.

Capability parity with the reference's config program
(/root/reference/src/flamenco/runtime/program/fd_config_program.c; no
code shared): a config account stores an opaque payload plus a signer
list; a store overwrites the payload only when the required signers
actually signed the transaction.

Account data layout (this framework's own fixed encoding):

    u16 n_keys | n_keys x (32B pubkey | u8 is_signer) | payload

Instruction data mirrors the account layout (keys block + new payload).
Rules (Agave semantics, simplified to the capability):
  - an EMPTY (fresh) config account must itself sign the store;
  - an initialized account requires every is_signer key of its CURRENT
    keys block to have signed this instruction;
  - the instruction's keys block becomes the new stored block (authority
    rotation is a store with a different signer set).
"""

from __future__ import annotations

from firedancer_tpu.flamenco.programs import AcctError
from firedancer_tpu.protocol.base58 import b58_decode32

CONFIG_PROGRAM = b58_decode32("Config1111111111111111111111111111111111111")


def parse_keys(data: bytes) -> tuple[list[tuple[bytes, bool]], bytes]:
    """-> ([(pubkey, is_signer)], payload) from a keys block."""
    if len(data) < 2:
        raise AcctError("short config keys block")
    n = int.from_bytes(data[:2], "little")
    off = 2
    keys = []
    for _ in range(n):
        if off + 33 > len(data):
            raise AcctError("truncated config keys block")
        keys.append((bytes(data[off : off + 32]), bool(data[off + 32])))
        off += 33
    return keys, bytes(data[off:])


def build_keys(keys: list[tuple[bytes, bool]], payload: bytes) -> bytes:
    out = len(keys).to_bytes(2, "little")
    for pk, is_signer in keys:
        out += pk + bytes([1 if is_signer else 0])
    return out + payload


def config_program(executor, ctx, program_id, iaccts, data, *,
                   pda_signers):
    if not iaccts:
        raise AcctError("config store needs the config account")
    acct = ctx.accounts[iaccts[0].txn_idx]
    if not iaccts[0].is_writable:
        raise AcctError("config account not writable")
    if acct.owner != CONFIG_PROGRAM:
        raise AcctError("config account not owned by the config program")

    signers = {
        ctx.accounts[ia.txn_idx].key
        for ia in iaccts
        if ia.is_signer or ctx.accounts[ia.txn_idx].key in pda_signers
    }
    new_keys, _payload = parse_keys(data)  # validates the instruction
    if len(acct.data) >= 2:
        cur_keys, _ = parse_keys(bytes(acct.data))
    else:
        cur_keys = None
    if cur_keys is None or not cur_keys:
        # fresh account: it must sign its own first store
        if acct.key not in signers:
            raise AcctError("fresh config account must sign")
    else:
        for pk, is_signer in cur_keys:
            if is_signer and pk not in signers:
                raise AcctError("config store missing required signer")
    if len(data) > len(acct.data):
        raise AcctError("config store larger than account")
    acct.data = bytearray(data.ljust(len(acct.data), b"\x00"))
