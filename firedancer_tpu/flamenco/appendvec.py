"""Agave append-vec account storage format.

Capability parity target: the accounts/*.* files inside a real cluster
snapshot are AppendVecs — Agave's memory-mapped account store pages,
which the reference parses natively during snapshot restore
(/root/reference/src/flamenco/snapshot/ restore path; no code shared).
Together with the VoteState/StakeStateV2 codecs (agave_state.py) this
covers the account-data plane of real-snapshot ingestion; the remaining
piece is the bank manifest.

Entry layout (solana accounts-db StoredAccountMeta, stable):

    StoredMeta     write_version u64 | data_len u64 | pubkey 32B
    AccountMeta    lamports u64 | rent_epoch u64 | owner 32B |
                   executable u8 | 7B pad
    hash           32B (account hash; readers may ignore)
    data           data_len bytes
    -> next entry aligned to 8 bytes

A file is a sequence of entries; iteration stops at the first entry
whose pubkey region is all zeros past `current_len` (mmap slack) or at
end of file.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Iterator

_STORED = struct.Struct("<QQ32s")
_ACCOUNT = struct.Struct("<QQ32sB7x")
_HASH_SZ = 32
ENTRY_HDR = _STORED.size + _ACCOUNT.size + _HASH_SZ


class AppendVecError(ValueError):
    pass


@dataclass
class StoredAccount:
    pubkey: bytes
    lamports: int
    owner: bytes
    executable: bool
    rent_epoch: int
    data: bytes
    write_version: int = 0
    hash: bytes = b"\x00" * 32

    def to_value(self) -> bytes:
        """This framework's funk account encoding (runtime.acct_encode)."""
        from firedancer_tpu.flamenco.runtime import acct_encode

        return acct_encode(self.lamports, self.owner, self.executable,
                           self.data)


def _align8(n: int) -> int:
    return (n + 7) & ~7


def append_entry(out: bytearray, acc: StoredAccount) -> None:
    out += _STORED.pack(acc.write_version, len(acc.data), acc.pubkey)
    out += _ACCOUNT.pack(acc.lamports, acc.rent_epoch, acc.owner,
                         1 if acc.executable else 0)
    out += acc.hash
    out += acc.data
    pad = _align8(len(out)) - len(out)
    out += bytes(pad)


def write_appendvec(accounts: list[StoredAccount]) -> bytes:
    out = bytearray()
    for acc in accounts:
        append_entry(out, acc)
    return bytes(out)


def iter_appendvec(blob: bytes, *,
                   current_len: int | None = None,
                   max_data_len: int = 10 << 20) -> Iterator[StoredAccount]:
    """Yield every stored account; tolerant of trailing mmap slack
    (files are page-padded), strict inside the live region."""
    end = len(blob) if current_len is None else min(current_len, len(blob))
    off = 0
    while off + ENTRY_HDR <= end:
        wv, dlen, pubkey = _STORED.unpack_from(blob, off)
        if pubkey == b"\x00" * 32 and dlen == 0 and wv == 0:
            return  # zeroed slack tail
        if dlen > max_data_len:
            raise AppendVecError(f"entry data_len {dlen} over cap")
        lam, rent, owner, execu = _ACCOUNT.unpack_from(
            blob, off + _STORED.size
        )
        doff = off + ENTRY_HDR
        if doff + dlen > end:
            raise AppendVecError("entry data runs past the live region")
        h = blob[off + _STORED.size + _ACCOUNT.size : doff]
        yield StoredAccount(
            pubkey=pubkey, lamports=lam, owner=owner,
            executable=bool(execu & 1), rent_epoch=rent,
            data=bytes(blob[doff : doff + dlen]), write_version=wv,
            hash=bytes(h),
        )
        off = _align8(doff + dlen)


def load_into_funk(blob: bytes, funk, *, xid: bytes | None = None,
                   current_len: int | None = None) -> int:
    """Replay an append-vec into funk; LAST write (highest offset) wins
    for duplicate pubkeys, matching the store's append semantics.
    Zero-lamport entries are tombstones.  Returns entries applied."""
    n = 0
    for acc in iter_appendvec(blob, current_len=current_len):
        if acc.lamports == 0:
            try:
                funk.rec_remove(xid, acc.pubkey)
            except Exception:
                pass
        else:
            funk.rec_insert(xid, acc.pubkey, acc.to_value())
        n += 1
    return n
