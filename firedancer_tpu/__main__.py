"""fdctl-style CLI: `python -m firedancer_tpu <action>`.

Mirrors the reference's action table (/root/reference/src/app/fdctl/
main1.c: run / monitor / keys / configure / version, and fddev's bench):

    run        build the leader pipeline from a TOML config and drive it
               (--processes: one supervised OS process per stage;
               --sandbox: seccomp jail each stage); monitor table on exit
    monitor    live per-stage TUI attached to a running topology
    ready      block until every stage of a running topology is RUN
    metrics    Prometheus scrape surface over a running topology's shm
               metric segments (--once prints; --serve binds the
               metric-tile HTTP endpoint), from an uninvolved process
    trace      flight-recorder rings -> Chrome trace-event JSON (open
               the output in Perfetto / chrome://tracing)
    chaos      the scenario harness: adversarial load + fault injection
               + invariant checking over the full validator loop
               (`chaos list`; `chaos run <scenario> --seed S`)
    configure  host setup stages: check | init (shm, fds, cpus, THP...)
    keys       new <path> | pubkey <path> — identity keypair management
    bench      quick pipeline throughput measurement (bench.py has the
               full headline benchmark)
    warmup     AOT-compile the sharded serving step for a mesh shape
               through the persistent serve cache (leader boot-time
               obligation; `bench.py --multichip-serve` is the ladder)
    genesis    create | show a genesis blob (+ faucet key)
    snapshot   inspect a snapshot archive
    ledger     show | ingest | replay a stored ledger (bank-hash checks)
    backtest   replay a consensus scenario through ghost/tower
    config     print the effective layered configuration
    version    print the framework version

Every action takes --config <file.toml> where relevant (layered over the
embedded defaults, utils/config.py).
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import time

__version__ = "0.7.0"  # round 7: sharded serving plane


def _load_cfg(args):
    from firedancer_tpu.utils.config import load_config

    return load_config(args.config)


def cmd_run(args) -> int:
    from firedancer_tpu.utils.platform import enable_compile_cache, force_cpu_backend

    if args.cpu:
        force_cpu_backend()
    enable_compile_cache()
    if getattr(args, "processes", False):
        return _run_processes(args)
    from firedancer_tpu.models.leader import build_leader_pipeline_from_config

    cfg = _load_cfg(args)
    pipe = build_leader_pipeline_from_config(
        cfg,
        pool_size=args.txns,
        gen_limit=args.txns,
        batch=min(cfg.verify.batch, 256),
        max_msg_len=256,
    )
    rpc_srv = None
    try:  # the pipeline must close even if the RPC bind fails (EADDRINUSE)
        if args.rpc_port is not None:
            from firedancer_tpu.runtime.rpc import PipelineView, RpcServer

            rpc_srv = RpcServer(
                PipelineView(pipeline=pipe), port=args.rpc_port
            )
            print(f"# rpc listening on {rpc_srv.addr}", file=sys.stderr)
        print(f"# leader pipeline: {len(pipe.verifies)} verify, "
              f"{len(pipe.banks)} bank stages; {args.txns} txns", file=sys.stderr)
        t0 = time.time()
        pipe.run(until_txns=args.txns, max_iters=2_000_000)
        dt = time.time() - t0
        executed = sum(b.metrics.get("txn_exec") for b in pipe.banks)
        print(f"{'stage':<10}{'in':>10}{'out':>10}{'extra':>30}")
        for s in pipe.stages:
            m = s.metrics
            extra = ""
            if s is pipe.pack:
                extra = f"microblocks={m.get('microblocks')}"
            if s is pipe.shred:
                extra = f"fec_sets={m.get('fec_sets')}"
            print(f"{s.name:<10}{m.get('frags_in'):>10}{m.get('frags_out'):>10}"
                  f"{extra:>30}")
        print(f"# {executed} txns committed in {dt:.2f}s "
              f"({executed / dt:.0f} txn/s)")
        return 0 if executed == args.txns else 1
    finally:
        if rpc_srv is not None:
            rpc_srv.close()
        pipe.close()


def _run_processes(args) -> int:
    """The fdctl-run model: every stage its own supervised OS process
    over shm links, optional per-stage jail, monitor table at exit."""
    from firedancer_tpu.models.leader_topo import build_leader_topology
    from firedancer_tpu.runtime import topo as ft
    from firedancer_tpu.runtime.stage import Stage

    sandbox = {"rlimits": {"nofile": 512}} if args.sandbox else None
    topo = build_leader_topology(
        n_txns=args.txns, pool_size=args.txns, batch=16, sandbox=sandbox,
    )
    h = ft.launch(topo)
    try:
        print(f"# {len(h.procs)} stage processes; descriptor "
              f"fdtpu_run_{h.uid}.json"
              + (" (sandboxed)" if sandbox else ""), file=sys.stderr)
        ok = h.supervise(
            until=lambda h: h.cncs["store"].diag(Stage.DIAG_FRAGS_IN) > 0,
            timeout_s=600,
            heartbeat_timeout_s=300,
        )
        print(h.format_monitor())
        h.halt()
        return 0 if ok else 1
    finally:
        h.close()


def cmd_keys(args) -> int:
    from firedancer_tpu.ops.ref import ed25519_ref as ref
    from firedancer_tpu.protocol.base58 import b58_encode

    if args.action == "new":
        secret = os.urandom(32)
        with open(args.path, "wb") as f:
            os.fchmod(f.fileno(), 0o600)
            f.write(secret)
        print(f"wrote identity key to {args.path}")
        print(f"pubkey: {b58_encode(ref.public_key(secret))}")
        return 0
    secret = open(args.path, "rb").read()
    if len(secret) != 32:
        print("malformed key file", file=sys.stderr)
        return 1
    print(b58_encode(ref.public_key(secret)))
    return 0


def cmd_bench(args) -> int:
    from firedancer_tpu.utils.platform import enable_compile_cache, force_cpu_backend

    if args.cpu:
        force_cpu_backend()
    enable_compile_cache()
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import bench as bench_mod

    import jax

    out = bench_mod.run_pipeline_bench(jax.devices()[0].platform)
    print(json.dumps(out))
    return 0


def cmd_warmup(args) -> int:
    """AOT-compile the sharded serving step for a mesh shape, through the
    repo-local persistent serve cache (utils/platform.enable_serve_cache):
    the leader's boot-time obligation, run BEFORE a slot, so traffic never
    waits on XLA.  Second runs load from cache in seconds — pass
    --assert-warm S to fail (exit 2) when the compile/load took longer,
    which is how CI proves the cache-hit path works."""
    from firedancer_tpu.utils.platform import (
        enable_serve_cache,
        force_cpu_backend,
    )

    if not args.real:
        force_cpu_backend(device_count=max(args.devices, 8))
    cache_dir = enable_serve_cache()
    from firedancer_tpu.parallel.serve import ServeConfig, ServePlane

    cfg = ServeConfig(
        n_devices=args.devices,
        batch_per_shard=args.batch_per_shard,
        max_msg_len=args.max_msg_len,
        poh_iters=args.poh_iters,
    )
    plane = ServePlane(cfg)
    compile_s = plane.warmup()
    print(json.dumps({
        "serve_step": cfg.cache_key(),
        "devices": args.devices,
        "batch": cfg.batch,
        "compile_s": round(compile_s, 2),
        "cache_dir": cache_dir,
    }))
    if args.assert_warm is not None and compile_s > args.assert_warm:
        print(f"warmup: compile/load took {compile_s:.1f}s "
              f"> --assert-warm {args.assert_warm}s (cache miss?)",
              file=sys.stderr)
        return 2
    return 0


def cmd_genesis(args) -> int:
    """fddev dev's bootstrap half: create genesis (+ faucet key) or
    inspect an existing blob."""
    from firedancer_tpu.flamenco import genesis as fg
    from firedancer_tpu.ops.ref import ed25519_ref as ref

    if args.action == "create":
        import os
        import secrets

        faucet_secret = secrets.token_bytes(32)
        blob = fg.genesis_create(
            faucet_pubkey=ref.public_key(faucet_secret),
            faucet_lamports=args.lamports,
        )
        # secret written only after the blob builds, owner-read-only
        # (the cmd_keys discipline: a faucet key is a signing key)
        with open(args.path + ".faucet", "wb") as f:
            os.fchmod(f.fileno(), 0o600)
            f.write(faucet_secret)
        with open(args.path, "wb") as f:
            f.write(blob)
        print(f"genesis {args.path} hash={fg.genesis_hash(blob).hex()} "
              f"faucet-key={args.path}.faucet")
        return 0
    blob = open(args.path, "rb").read()
    g = fg.genesis_parse(blob)
    print(f"hash:            {fg.genesis_hash(blob).hex()}")
    print(f"hashes_per_tick: {g.hashes_per_tick}")
    print(f"ticks_per_slot:  {g.ticks_per_slot}")
    print(f"slots_per_epoch: {g.slots_per_epoch}")
    print(f"accounts:        {len(g.accounts)}")
    return 0


def cmd_snapshot(args) -> int:
    """Snapshot inspection (the operator-facing face of
    flamenco/snapshot.py; creation happens via the runtime).  Falls back
    to the REAL Agave manifest dialect when the archive is a genuine
    cluster snapshot."""
    from firedancer_tpu.flamenco import snapshot as snap
    from firedancer_tpu.flamenco.types import CodecError

    try:
        man, accounts = snap.snapshot_read(args.path)
    except (snap.SnapshotError, CodecError) as internal_err:
        # not the internal dialect -> try the real Agave manifest; if
        # that fails too, surface BOTH causes, not a misleading second
        # error alone
        try:
            funk, m, summary = snap.agave_snapshot_load(args.path)
        except Exception as agave_err:
            raise SystemExit(
                f"not an internal-dialect archive ({internal_err}) and "
                f"not an Agave archive ({agave_err})"
            )
        print(f"dialect:   agave")
        print(f"slot:      {summary['slot']} (epoch {summary['epoch']})")
        print(f"bank hash: {summary['bank_hash'].hex()}")
        print(f"accounts:  {summary['accounts']}")
        print(f"cap:       {summary['capitalization']}")
        print(f"votes:     {summary['vote_accounts']} vote accounts, "
              f"{summary['stake_delegations']} delegations")
        return 0
    kind = f"incremental (base slot {man.base_slot})" if man.base_slot else "full"
    print(f"slot:      {man.slot} ({kind})")
    print(f"bank hash: {man.bank_hash.hex()}")
    print(f"accounts:  {man.account_cnt}")
    if man.deleted:
        print(f"deletions: {len(man.deleted)}")
    from firedancer_tpu.flamenco.executor import acct_decode

    total = sum(acct_decode(v)[0] for v in accounts.values())
    print(f"lamports:  {total}")
    return 0


def cmd_config(args) -> int:
    import dataclasses

    cfg = _load_cfg(args)

    def dump(obj, indent=""):
        for f in dataclasses.fields(obj):
            v = getattr(obj, f.name)
            if dataclasses.is_dataclass(v):
                print(f"{indent}[{f.name}]")
                dump(v, indent)
            else:
                print(f"{indent}{f.name} = {v!r}")

    dump(cfg)
    return 0


def cmd_monitor(args) -> int:
    """fdctl monitor parity: attach to a live run's cnc regions and
    redraw per-stage rates in place (runtime/monitor.py)."""
    from firedancer_tpu.runtime.monitor import MonitorSession

    try:
        ses = MonitorSession.attach(args.descriptor)
    except (RuntimeError, OSError) as e:
        print(f"monitor: {e}", file=sys.stderr)
        return 1
    try:
        ses.run(interval_s=args.interval, iterations=args.iterations)
    finally:
        ses.close()
    return 0


def cmd_metrics(args) -> int:
    """The metric-tile position (fd_metric.c): attach to a live run's
    shm metric segments READ-ONLY and serve/print the Prometheus text
    exposition — a process the topology never knows about."""
    from firedancer_tpu.runtime.monitor import MonitorSession

    try:
        ses = MonitorSession.attach(args.descriptor)
    except (RuntimeError, OSError) as e:
        print(f"metrics: {e}", file=sys.stderr)
        return 1
    try:
        if not ses.registries():
            print("metrics: run exposes no metrics segments "
                  "(pre-metrics descriptor?)", file=sys.stderr)
            return 1
        if args.once:
            sys.stdout.write(ses.scrape())
            return 0
        from firedancer_tpu.utils.metrics import MetricsServer

        def resolve():
            # re-resolve the registry set on every scrape: if the run
            # behind the descriptor was replaced (or a metrics segment
            # joined late) the server must not keep exposing a stale
            # boot-time snapshot of counters
            ses.refresh()
            return ses.registries(), ses.shard_labels()

        srv = MetricsServer(ses.registries(), port=args.serve,
                            labels=ses.shard_labels(), resolver=resolve)
        try:
            host, port = srv.addr
            print(f"# serving /metrics on http://{host}:{port}/ (^C exits)",
                  file=sys.stderr)
            try:
                while True:
                    time.sleep(1.0)
            except KeyboardInterrupt:
                pass
        finally:
            srv.close()
        return 0
    finally:
        ses.close()


def cmd_trace(args) -> int:
    """Export flight-recorder rings as Chrome trace-event JSON: from a
    crash dump (--dump, written by the supervisor on any stage FAIL) or
    live from the newest running topology."""
    from firedancer_tpu.runtime import monitor as mon
    from firedancer_tpu.utils.metrics import flight_to_chrome_trace

    try:
        if args.dump is not None:
            with open(args.dump) as f:
                dump = json.load(f)
        elif args.descriptor is not None or mon.list_runs():
            from firedancer_tpu.runtime.monitor import MonitorSession

            ses = MonitorSession.attach(args.descriptor)
            try:
                dump = ses.flight_dump()
            finally:
                ses.close()
        else:
            dumps = mon.list_flight_dumps()
            if not dumps:
                print("trace: no live run and no flight dumps found",
                      file=sys.stderr)
                return 1
            print(f"# using newest flight dump {dumps[0]}", file=sys.stderr)
            with open(dumps[0]) as f:
                dump = json.load(f)
    except (RuntimeError, OSError, json.JSONDecodeError) as e:
        print(f"trace: {e}", file=sys.stderr)
        return 1
    trace = flight_to_chrome_trace(dump)
    with open(args.out, "w") as f:
        json.dump(trace, f)
    n = len(trace["traceEvents"])
    print(f"# wrote {n} trace events to {args.out}", file=sys.stderr)
    return 0


def cmd_slotreport(args) -> int:
    """Per-slot structured report over the native observability plane
    (runtime/slot_report.py): live session, post-mortem flight dump(s),
    or an in-process cluster run."""
    from firedancer_tpu.runtime import monitor as mon
    from firedancer_tpu.runtime import slot_report as sr

    try:
        if args.cluster:
            rep = sr.run_cluster_report(args.cluster, slots=args.slots,
                                        seed=args.seed)
        elif args.dump:
            reports = []
            for path in args.dump:
                with open(path) as f:
                    reports.append(sr.build_report(json.load(f)))
            rep = reports[0] if len(reports) == 1 \
                else sr.aggregate_reports(reports)
        elif args.descriptor is not None or mon.list_runs():
            from firedancer_tpu.runtime.monitor import MonitorSession

            ses = MonitorSession.attach(args.descriptor)
            try:
                rep = sr.report_from_session(ses)
            finally:
                ses.close()
        else:
            dumps = mon.list_flight_dumps()
            if not dumps:
                print("slotreport: no live run and no flight dumps found",
                      file=sys.stderr)
                return 1
            print(f"# using newest flight dump {dumps[0]}", file=sys.stderr)
            with open(dumps[0]) as f:
                rep = sr.build_report(json.load(f))
    except (RuntimeError, OSError, json.JSONDecodeError) as e:
        print(f"slotreport: {e}", file=sys.stderr)
        return 1
    if args.normalize:
        rep = sr.normalize(rep)
    text = sr.dumps(rep)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
        print(f"# wrote slot report to {args.out}", file=sys.stderr)
    else:
        sys.stdout.write(text)
    return 0


def cmd_ready(args) -> int:
    """fdctl ready parity: exit 0 once every stage is RUN, 1 on timeout
    or failure."""
    from firedancer_tpu.runtime.monitor import MonitorSession

    try:
        ses = MonitorSession.attach(args.descriptor)
    except (RuntimeError, OSError) as e:
        print(f"ready: {e}", file=sys.stderr)
        return 1
    try:
        ok = ses.wait_ready(timeout_s=args.timeout)
    finally:
        ses.close()
    print("ready" if ok else "not ready")
    return 0 if ok else 1


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="firedancer_tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    runp = sub.add_parser("run", help="drive the leader pipeline")
    runp.add_argument("--config", default=None)
    runp.add_argument("--txns", type=int, default=256)
    runp.add_argument("--cpu", action="store_true", help="force CPU backend")
    runp.add_argument(
        "--rpc-port", type=int, default=None,
        help="serve JSON-RPC (getTransactionCount/getSlot/...) during the run",
    )
    runp.add_argument(
        "--processes", action="store_true",
        help="run every stage as its own supervised OS process "
             "(the fdctl run model); implies --cpu in the children",
    )
    runp.add_argument(
        "--sandbox", action="store_true",
        help="with --processes: jail each stage (seccomp deny of "
             "spawn/exec/priv syscalls + rlimits)",
    )

    keysp = sub.add_parser("keys", help="identity keypair management")
    keysp.add_argument("action", choices=["new", "pubkey"])
    keysp.add_argument("path")

    benchp = sub.add_parser("bench", help="pipeline throughput bench")
    benchp.add_argument("--cpu", action="store_true")

    wup = sub.add_parser(
        "warmup",
        help="AOT-compile the sharded serving step (persistent cache)",
    )
    wup.add_argument("--devices", type=int, default=8,
                     help="mesh size (devices) to compile for")
    wup.add_argument("--batch-per-shard", type=int, default=32)
    wup.add_argument("--max-msg-len", type=int, default=256)
    wup.add_argument("--poh-iters", type=int, default=64)
    wup.add_argument("--real", action="store_true",
                     help="use real devices (default: forced CPU mesh)")
    wup.add_argument("--assert-warm", type=float, default=None, metavar="S",
                     help="exit 2 unless compile/load finished within S "
                          "seconds (the CI cache-hit proof)")

    cfgp = sub.add_parser("config", help="print effective configuration")
    cfgp.add_argument("--config", default=None)

    genp = sub.add_parser("genesis", help="create/inspect a genesis blob")
    genp.add_argument("action", choices=["create", "show"])
    genp.add_argument("path")
    genp.add_argument("--lamports", type=int, default=500_000_000_000_000)

    snapp = sub.add_parser("snapshot", help="inspect a snapshot archive")
    snapp.add_argument("path")

    cfgst = sub.add_parser(
        "configure", help="host setup stages: check or apply"
    )
    cfgst.add_argument("action", choices=["check", "init"])
    cfgst.add_argument("--config", default=None)

    btp = sub.add_parser(
        "backtest", help="replay a consensus scenario through ghost/tower"
    )
    btp.add_argument("--scenario", default=None,
                     help="scenario JSON (default: synthetic partition)")
    btp.add_argument("--seed", default=None)
    btp.add_argument("--total-stake", type=int, default=None)

    monp = sub.add_parser(
        "monitor", help="live per-stage TUI of a running topology"
    )
    monp.add_argument("--descriptor", default=None,
                      help="run descriptor path (default: newest live run)")
    monp.add_argument("--interval", type=float, default=1.0)
    monp.add_argument("--iterations", type=int, default=None,
                      help="sample count (default: until ^C)")

    readyp = sub.add_parser(
        "ready", help="block until every stage heartbeats in RUN"
    )
    readyp.add_argument("--descriptor", default=None)
    readyp.add_argument("--timeout", type=float, default=60.0)

    metp = sub.add_parser(
        "metrics", help="Prometheus scrape surface over a running topology"
    )
    metp.add_argument("--descriptor", default=None,
                      help="run descriptor path (default: newest live run)")
    g = metp.add_mutually_exclusive_group()
    g.add_argument("--once", action="store_true",
                   help="print one text-exposition snapshot and exit")
    g.add_argument("--serve", type=int, default=0, metavar="PORT",
                   help="serve /metrics over HTTP (0 = ephemeral port)")

    trcp = sub.add_parser(
        "trace", help="flight recorder -> Chrome trace JSON (Perfetto)"
    )
    trcp.add_argument("--out", default="trace.json")
    trcp.add_argument("--dump", default=None,
                      help="a flight dump written by the supervisor on FAIL"
                           " (default: live run, else newest dump)")
    trcp.add_argument("--descriptor", default=None,
                      help="run descriptor to snapshot live (optional)")

    srp = sub.add_parser(
        "slotreport",
        help="per-slot JSON report: seal/miss, sweep-phase p50/p99,"
             " native-vs-punt, funk writes, restarts",
    )
    srp.add_argument("--descriptor", default=None,
                     help="run descriptor to snapshot live (optional)")
    srp.add_argument("--dump", nargs="+", default=None, metavar="DUMP",
                     help="flight dump file(s); several -> aggregated"
                          " multi-node report")
    srp.add_argument("--cluster", type=int, default=0, metavar="N",
                     help="boot an N-validator in-process cluster and"
                          " report it (chaos/cluster.py)")
    srp.add_argument("--slots", type=int, default=6,
                     help="cluster mode: slots to run")
    srp.add_argument("--seed", type=int, default=7,
                     help="cluster mode: harness seed (same seed ->"
                          " byte-identical report)")
    srp.add_argument("--out", default=None,
                     help="write JSON here (default: stdout)")
    srp.add_argument("--normalize", action="store_true",
                     help="strip timing-dependent fields (CI determinism"
                          " diffs)")

    chp = sub.add_parser(
        "chaos",
        help="scenario harness: adversarial load + faults + invariants",
    )
    chp.add_argument("action", choices=["run", "list"])
    chp.add_argument("scenario", nargs="?", default=None,
                     help="scenario name (see `chaos list`)")
    chp.add_argument("--seed", type=int, default=0,
                     help="run seed; identical seeds -> identical "
                          "invariant summaries (the replay contract)")
    chp.add_argument("--duration", type=float, default=None,
                     help="wall-clock budget in seconds (scenario default"
                          " if omitted)")
    chp.add_argument("--clients", type=int, default=None,
                     help="connection-storm population size")

    ledp = sub.add_parser("ledger", help="ingest/inspect/replay a ledger")
    ledp.add_argument("action", choices=["show", "ingest", "replay"])
    ledp.add_argument("store", help="blockstore directory")
    ledp.add_argument("capture", nargs="?", default=None,
                      help="shredcap/pcap for ingest")
    ledp.add_argument("--funk-dir", default=None)
    ledp.add_argument("--poh-seed", default=None, help="hex 32B")
    ledp.add_argument("--record", default=None,
                      help="write per-slot bank hashes to this JSON")
    ledp.add_argument("--check", default=None,
                      help="diff bank hashes against this JSON")

    sub.add_parser("version", help="print version")

    args = p.parse_args(argv)
    if args.cmd == "run":
        return cmd_run(args)
    if args.cmd == "keys":
        return cmd_keys(args)
    if args.cmd == "bench":
        return cmd_bench(args)
    if args.cmd == "warmup":
        return cmd_warmup(args)
    if args.cmd == "config":
        return cmd_config(args)
    if args.cmd == "genesis":
        return cmd_genesis(args)
    if args.cmd == "snapshot":
        return cmd_snapshot(args)
    if args.cmd == "ledger":
        from firedancer_tpu import ledger as _ledger

        return _ledger.main(args)
    if args.cmd == "configure":
        from firedancer_tpu.utils import hostcfg
        from firedancer_tpu.utils.config import load_config

        return hostcfg.main(args, load_config(args.config))
    if args.cmd == "backtest":
        from firedancer_tpu.choreo import backtest as _bt

        return _bt.main(args)
    if args.cmd == "monitor":
        return cmd_monitor(args)
    if args.cmd == "ready":
        return cmd_ready(args)
    if args.cmd == "metrics":
        return cmd_metrics(args)
    if args.cmd == "trace":
        return cmd_trace(args)
    if args.cmd == "slotreport":
        from firedancer_tpu.utils.platform import force_cpu_backend

        force_cpu_backend()  # cluster mode must never cold-init a device
        return cmd_slotreport(args)
    if args.cmd == "chaos":
        from firedancer_tpu.utils.platform import (
            enable_compile_cache,
            force_cpu_backend,
        )

        force_cpu_backend()  # scenarios must never cold-init a device
        enable_compile_cache()
        from firedancer_tpu.chaos import scenario as _chaos

        return _chaos.main(args)
    if args.cmd == "version":
        print(f"firedancer_tpu {__version__}")
        return 0
    return 2


if __name__ == "__main__":
    sys.exit(main())
