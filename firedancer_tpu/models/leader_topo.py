"""The leader pipeline as a PROCESS topology (fdctl-run shape).

models/leader.py wires the flagship pipeline for the cooperative
in-process scheduler (tests, bench); this module wires the SAME stages
into runtime/topo's process runner — one OS process per stage over the
same shm links, cnc supervision, monitor — the reference's operational
model (fd_topo_run.c boots tiles as processes; run.c supervises).

Builders are MODULE-LEVEL functions (the topo runner spawns fresh
interpreters — see runtime/topo.py on why fork is unusable with XLA —
so every builder and its kwargs must pickle).  Each jax-using child
forces the CPU backend and joins the shared persistent compile cache
before its first dispatch.
"""

from __future__ import annotations

import hashlib

from firedancer_tpu.runtime import topo as ft
from firedancer_tpu.tango import shm


def _cpu():
    from firedancer_tpu.utils.platform import enable_compile_cache, force_cpu_backend

    force_cpu_backend()
    enable_compile_cache()


def build_benchg(links, cnc, *, pool_size, n_txns):
    from firedancer_tpu.runtime.benchg import BenchGStage, gen_transfer_pool

    return BenchGStage(
        gen_transfer_pool(pool_size),
        "benchg",
        outs=[shm.make_producer(links["gv"])],
        cnc=cnc,
        limit=n_txns,
    )


def build_verify(links, cnc, *, batch, precomputed=False):
    if not precomputed:
        _cpu()
    from firedancer_tpu.runtime.verify import VerifyStage

    return VerifyStage(
        "verify0",
        ins=[shm.make_consumer(links["gv"], lazy=32)],
        outs=[shm.make_producer(links["vd"])],
        cnc=cnc,
        batch=batch,
        max_msg_len=256,
        batch_deadline_s=0.002,
        precomputed_ok=precomputed,
    )


def build_router(links, cnc, *, n_shards):
    from firedancer_tpu.parallel.router import ShardRouterStage

    return ShardRouterStage(
        "router",
        ins=[shm.make_consumer(links["gv"], lazy=32)],
        outs=[shm.make_producer(links[f"sv{i}"]) for i in range(n_shards)],
        cnc=cnc,
        n_shards=n_shards,
    )


def build_verify_shard(links, cnc, *, shard_idx, batch, precomputed):
    if not precomputed:
        _cpu()
    from firedancer_tpu.runtime.verify import VerifyStage

    return VerifyStage(
        f"verify_s{shard_idx}",
        ins=[shm.make_consumer(links[f"sv{shard_idx}"], lazy=32)],
        outs=[shm.make_producer(links[f"vd{shard_idx}"])],
        cnc=cnc,
        batch=batch,
        max_msg_len=256,
        batch_deadline_s=0.002,
        precomputed_ok=precomputed,
    )


def build_dedup(links, cnc):
    from firedancer_tpu.runtime.dedup import DedupStage

    return DedupStage(
        "dedup",
        ins=[shm.make_consumer(links["vd"], lazy=32)],
        outs=[shm.make_producer(links["dp"])],
        cnc=cnc,
    )


def build_dedup_sharded(links, cnc, *, n_shards):
    from firedancer_tpu.runtime.dedup import DedupStage

    return DedupStage(
        "dedup",
        ins=[shm.make_consumer(links[f"vd{i}"], lazy=32) for i in range(n_shards)],
        outs=[shm.make_producer(links["dp"])],
        cnc=cnc,
    )


def build_pack(links, cnc, *, n_bank, slot_clock=None, shed_keep=None):
    from firedancer_tpu.runtime.pack_stage import PackStage

    return PackStage(
        "pack",
        ins=[shm.make_consumer(links["dp"], lazy=32)]
        + [shm.make_consumer(links[f"bd{b}"], lazy=8) for b in range(n_bank)],
        outs=[shm.make_producer(links[f"pb{b}"]) for b in range(n_bank)],
        cnc=cnc,
        bank_cnt=n_bank,
        # a process pipeline has real inter-stage latency: schedule as
        # soon as anything is pending
        min_pending=1,
        mb_deadline_s=0.0,
        clock=slot_clock,
        shed_keep=shed_keep,
    )


def build_pack_native(links, cnc, *, n_bank, txn_links, slot_clock=None,
                      shed_keep=None):
    """The fused native dedup+pack stage: consumes the verify output
    links directly (no dedup process) and runs native/fd_pack.cpp via
    one FFI crossing per burst.  The parent only wires this when
    pack/scheduler_native.available() said so pre-boot (the .so is
    already built; the child just loads it)."""
    from firedancer_tpu.runtime.pack_stage import NativePackStage

    return NativePackStage(
        "pack",
        ins=[shm.make_consumer(links[l], lazy=32) for l in txn_links]
        + [shm.make_consumer(links[f"bd{b}"], lazy=8) for b in range(n_bank)],
        outs=[shm.make_producer(links[f"pb{b}"]) for b in range(n_bank)],
        cnc=cnc,
        bank_cnt=n_bank,
        n_txn_ins=len(txn_links),
        min_pending=1,
        mb_deadline_s=0.0,
        clock=slot_clock,
        shed_keep=shed_keep,
    )


def build_bank(links, cnc, *, bank_idx, slot=1, slot_clock=None):
    # the bank process OWNS the live bank (its own funk + SlotExecution,
    # default_bank_ctx): the process topology therefore runs n_bank=1 —
    # multiple real-execution banks need the funk state shared, which the
    # cooperative pipeline gets in-process (models/leader.py) and a
    # multi-process topology would need a cross-process funk backend for
    # (the reference shares fd_funk in a wksp across tiles the same way)
    from firedancer_tpu.runtime.bank import BankStage, default_bank_ctx

    stage = BankStage(
        f"bank{bank_idx}",
        ins=[shm.make_consumer(links[f"pb{bank_idx}"], lazy=8)],
        outs=[
            shm.make_producer(links[f"bp{bank_idx}"]),
            shm.make_producer(links[f"bd{bank_idx}"]),
        ],
        cnc=cnc,
        bank_idx=bank_idx,
        ctx=default_bank_ctx(slot=slot),
        clock=slot_clock,
    )
    stage.require_credit = True
    return stage


def build_poh(links, cnc, *, n_bank, slot_clock=None):
    from firedancer_tpu.runtime.poh_stage import PohStage

    stage = PohStage(
        "poh",
        ins=[shm.make_consumer(links[f"bp{b}"], lazy=8) for b in range(n_bank)],
        outs=[shm.make_producer(links["ps"])],
        cnc=cnc,
        clock=slot_clock,
    )
    stage.require_credit = True
    return stage


def build_shred(links, cnc, *, secret, slot):
    _cpu()  # reedsol dispatches on device: never let a child init the tunnel
    from firedancer_tpu.ops.ref import ed25519_ref as ref
    from firedancer_tpu.runtime.shred_stage import ShredStage

    return ShredStage(
        "shred",
        ins=[shm.make_consumer(links["ps"], lazy=8)],
        outs=[shm.make_producer(links["ss"])],
        cnc=cnc,
        signer=lambda root: ref.sign(secret, root),
        secret=secret,  # arms the native shredder lane when available
        slot=slot,
        batch_target_sz=4096,
    )


def build_poh_shred_fused(links, cnc, *, n_bank, secret, slot,
                          slot_clock=None):
    """The fused poh+shred crash domain (runtime/shred_stage.
    FusedPohShredStage) as ONE process: the poh->shred ring hop ("ps")
    disappears, entries feed the shredder in-process, and the
    supervisor restarts clock and shredder together — entries can never
    be stranded on a ring between them."""
    _cpu()  # the shred half's reedsol dispatches on device
    from firedancer_tpu.ops.ref import ed25519_ref as ref
    from firedancer_tpu.runtime.shred_stage import FusedPohShredStage

    stage = FusedPohShredStage(
        "poh_shred",
        ins=[shm.make_consumer(links[f"bp{b}"], lazy=8)
             for b in range(n_bank)],
        outs=[shm.make_producer(links["ss"])],
        cnc=cnc,
        clock=slot_clock,
        signer=lambda root: ref.sign(secret, root),
        secret=secret,  # arms the native shredder lane when available
        shred_slot=slot,
        batch_target_sz=4096,
    )
    stage.require_credit = True
    return stage


def build_store(links, cnc, *, leader_pub):
    _cpu()  # the resolver's RS recover dispatches on device
    from firedancer_tpu.ops.ref import ed25519_ref as ref
    from firedancer_tpu.runtime.store import StoreStage

    return StoreStage(
        "store",
        ins=[shm.make_consumer(links["ss"], lazy=64)],
        cnc=cnc,
        verify_sig=lambda r, s: ref.verify(r, s, leader_pub),
    )


def build_leader_topology(
    *,
    n_txns: int = 64,
    pool_size: int = 64,
    batch: int = 32,
    n_bank: int = 1,
    leader_seed: bytes = b"leader",
    slot: int = 1,
    sandbox: dict | None = None,
    native_pack: bool | None = None,
    slot_clock=None,
    boot_grace_s: float = 0.0,
    shed_keep: int | None = None,
    verify_precomputed: bool = False,
    fuse_poh_shred: bool = False,
) -> ft.Topology:
    """sandbox: utils/sandbox.enter kwargs applied to EVERY stage child
    (the per-tile jail; fd_topo_run's seccomp step).  The default policy
    shape: {"rlimits": {"nofile": 512}} + the spawn/exec/priv deny list,
    with thread-creating clones allowed for XLA.

    native_pack: None = auto — when pack/scheduler_native.available()
    (checked HERE in the parent, which also builds the .so so children
    just load it), the dedup process disappears and the pack process
    runs the fused native dedup+pack lane over the verify link.

    slot_clock (runtime/slot_clock.SlotClockCfg): run the topology
    against the real wall-clock cadence.  The cfg is anchored HERE, in
    the parent, `boot_grace_s` into the future (children need real time
    to spawn — XLA imports take seconds on cold boxes), so every child
    derives the SAME slot boundaries from one shared monotonic epoch.
    With n_slots set on the cfg, the leader window ends ON THE SCHEDULE
    — poh stops sealing at the last slot's deadline regardless of how
    much load is still draining (the handoff contract); supervise with
    `until=leader_window_done(...)` to observe it.

    fuse_poh_shred: collapse poh and shred into ONE crash domain
    (FusedPohShredStage): the "ps" link and the separate shred process
    disappear, and the fused stage consumes the bank entry links and
    produces wire shreds directly.  Supervise with
    `leader_window_done(n, stage="poh_shred")` in this mode."""
    from firedancer_tpu.models.leader import resolve_native_pack
    from firedancer_tpu.ops.ref import ed25519_ref as ref

    # per-kind metric schemas: launch() sizes each stage's shm metrics
    # segment from these (and records them in the run descriptor, so a
    # scraper reconstructs the layout without importing these classes)
    from firedancer_tpu.runtime.bank import BankStage
    from firedancer_tpu.runtime.dedup import DedupStage
    from firedancer_tpu.runtime.pack_stage import PackStage
    from firedancer_tpu.runtime.poh_stage import PohStage
    from firedancer_tpu.runtime.verify import VerifyStage

    if slot_clock is not None:
        slot_clock = slot_clock.anchored(boot_grace_s)

    if n_bank != 1:
        # each bank process owns its own funk: two real-execution banks
        # in separate processes would commit into divergent state
        # machines (see build_bank) — refuse rather than diverge
        raise ValueError(
            "process topology supports exactly one bank stage until funk "
            "has a cross-process backend; the cooperative pipeline "
            "(models/leader.py) runs any bank count over the shared ctx"
        )

    use_native_pack = resolve_native_pack(native_pack)
    topo = ft.Topology()
    topo.link("gv", depth=1024, mtu=1232)
    topo.link("vd", depth=1024, mtu=4096)
    if not use_native_pack:
        topo.link("dp", depth=1024, mtu=4096)
    for b in range(n_bank):
        topo.link(f"pb{b}", depth=256, mtu=65536)
        topo.link(f"bp{b}", depth=256, mtu=65536)
        topo.link(f"bd{b}", depth=256, mtu=64)
    if not fuse_poh_shred:
        topo.link("ps", depth=1024, mtu=65536)
    topo.link("ss", depth=4096, mtu=1232)

    secret = hashlib.sha256(leader_seed).digest()
    leader_pub = ref.public_key(secret)

    # ins/outs mirror what each builder above actually wires — the
    # pre-boot topology checker (analysis FD1xx) validates the graph
    # against these declarations before launch() creates any shm.
    # pack is deliberately NOT credit_gated: it keeps draining the banks'
    # done-feedback (bd) links while backpressured on pb, which is what
    # breaks the pack<->bank cycle (FD107's rationale).
    sb = sandbox
    topo.stage("benchg", build_benchg, pool_size=pool_size, n_txns=n_txns,
               sandbox=sb, outs=["gv"])
    topo.stage("verify0", build_verify, batch=batch, sandbox=sb,
               precomputed=verify_precomputed,
               ins=["gv"], outs=["vd"], schema=VerifyStage.metrics_schema())
    if use_native_pack:
        topo.stage("pack", build_pack_native, n_bank=n_bank,
                   txn_links=["vd"], sandbox=sb,
                   slot_clock=slot_clock, shed_keep=shed_keep,
                   ins=["vd"] + [f"bd{b}" for b in range(n_bank)],
                   outs=[f"pb{b}" for b in range(n_bank)],
                   schema=PackStage.metrics_schema())
    else:
        topo.stage("dedup", build_dedup, sandbox=sb, ins=["vd"], outs=["dp"],
                   schema=DedupStage.metrics_schema())
        topo.stage("pack", build_pack, n_bank=n_bank, sandbox=sb,
                   slot_clock=slot_clock, shed_keep=shed_keep,
                   ins=["dp"] + [f"bd{b}" for b in range(n_bank)],
                   outs=[f"pb{b}" for b in range(n_bank)],
                   schema=PackStage.metrics_schema())
    for b in range(n_bank):
        topo.stage(f"bank{b}", build_bank, bank_idx=b, slot=slot, sandbox=sb,
                   slot_clock=slot_clock,
                   ins=[f"pb{b}"], outs=[f"bp{b}", f"bd{b}"],
                   credit_gated=True, schema=BankStage.metrics_schema())
    if fuse_poh_shred:
        from firedancer_tpu.runtime.shred_stage import FusedPohShredStage

        topo.stage("poh_shred", build_poh_shred_fused, n_bank=n_bank,
                   secret=secret, slot=slot, sandbox=sb,
                   slot_clock=slot_clock,
                   ins=[f"bp{b}" for b in range(n_bank)], outs=["ss"],
                   credit_gated=True,
                   schema=FusedPohShredStage.metrics_schema())
    else:
        topo.stage("poh", build_poh, n_bank=n_bank, sandbox=sb,
                   slot_clock=slot_clock,
                   ins=[f"bp{b}" for b in range(n_bank)], outs=["ps"],
                   credit_gated=True, schema=PohStage.metrics_schema())
        topo.stage("shred", build_shred, secret=secret, slot=slot,
                   sandbox=sb, ins=["ps"], outs=["ss"])
    topo.stage("store", build_store, leader_pub=leader_pub, sandbox=sb,
               ins=["ss"])
    return topo


def build_leader_topology_fused(**kw) -> ft.Topology:
    """build_leader_topology with the fusion knob on: the fused
    poh+shred crash domain as a checkable flagship variant — the
    default `--topo` spec fdlint's FD1xx (link/credit invariants) and
    FD4xx (crash-domain map) passes validate alongside the unfused
    topology."""
    kw.setdefault("fuse_poh_shred", True)
    return build_leader_topology(**kw)


def leader_window_done(n_slots: int, stage: str = "poh"):
    """An `until` predicate for TopologyHandle.supervise: the leader
    window is over once poh has resolved every scheduled slot — sealed
    or MISSED, both count; the handoff fires on the schedule, not on
    drain.  Reads the poh stage's shm metrics registry (values are at
    most one housekeeping interval stale, which is exactly the jitter
    budget the grace window already absorbs)."""

    def _done(handle) -> bool:
        reg = handle.met_views.get(stage, (None, None))[0]
        if reg is None:
            return False
        return (reg.get("slots_sealed") + reg.get("slot_missed")
                >= n_slots)

    return _done


def build_sharded_leader_topology(
    *,
    n_shards: int = 4,
    n_txns: int = 64,
    pool_size: int = 64,
    batch: int = 32,
    leader_seed: bytes = b"leader",
    slot: int = 1,
    sandbox: dict | None = None,
    verify_precomputed: bool = False,
    shard_depth: int = 512,
    native_pack: bool | None = None,
) -> ft.Topology:
    """The SHARDED serving topology (process form): ingress round-robins
    through an explicit shard router into per-shard rings, and one verify
    process per shard carries shard labels the whole observability plane
    understands (run descriptor -> scrape {stage="verify",shard=i} ->
    monitor aggregation).

        benchg -> gv -> router -> sv{i} -> verify_s{i} -> vd{i} -> dedup
               -> pack -> bank -> poh -> shred -> store

    verify_precomputed skips the device dispatch in the shard children
    (the host-machinery bench/test instrument — a spawned child would
    otherwise cold-compile the kernel per shard).  The mesh-sharded
    single-step serving plane is the COOPERATIVE form
    (models/leader.build_sharded_leader_pipeline); this topology is its
    process-isolation counterpart where each shard is a crash domain.
    """
    from firedancer_tpu.models.leader import resolve_native_pack
    from firedancer_tpu.ops.ref import ed25519_ref as ref
    from firedancer_tpu.parallel.router import ShardRouterStage
    from firedancer_tpu.runtime.bank import BankStage
    from firedancer_tpu.runtime.dedup import DedupStage
    from firedancer_tpu.runtime.pack_stage import PackStage
    from firedancer_tpu.runtime.poh_stage import PohStage
    from firedancer_tpu.runtime.verify import VerifyStage

    use_native_pack = resolve_native_pack(native_pack)
    n_bank = 1  # see build_leader_topology: one bank until funk is shared
    topo = ft.Topology()
    topo.link("gv", depth=1024, mtu=1232)
    for i in range(n_shards):
        topo.link(f"sv{i}", depth=shard_depth, mtu=1232)  # pow2 (FD104)
        topo.link(f"vd{i}", depth=shard_depth, mtu=4096)
    if not use_native_pack:
        topo.link("dp", depth=1024, mtu=4096)
    for b in range(n_bank):
        topo.link(f"pb{b}", depth=256, mtu=65536)
        topo.link(f"bp{b}", depth=256, mtu=65536)
        topo.link(f"bd{b}", depth=256, mtu=64)
    topo.link("ps", depth=1024, mtu=65536)
    topo.link("ss", depth=4096, mtu=1232)

    secret = hashlib.sha256(leader_seed).digest()
    leader_pub = ref.public_key(secret)

    sb = sandbox
    topo.stage("benchg", build_benchg, pool_size=pool_size, n_txns=n_txns,
               sandbox=sb, outs=["gv"])
    topo.stage("router", build_router, n_shards=n_shards, sandbox=sb,
               ins=["gv"], outs=[f"sv{i}" for i in range(n_shards)],
               credit_gated=True,
               schema=ShardRouterStage.metrics_schema_n(n_shards))
    for i in range(n_shards):
        topo.stage(f"verify_s{i}", build_verify_shard,
                   shard=i, logical="verify", shard_idx=i,
                   batch=batch, precomputed=verify_precomputed, sandbox=sb,
                   ins=[f"sv{i}"], outs=[f"vd{i}"],
                   schema=VerifyStage.metrics_schema())
    if use_native_pack:
        vd_links = [f"vd{i}" for i in range(n_shards)]
        topo.stage("pack", build_pack_native, n_bank=n_bank,
                   txn_links=vd_links, sandbox=sb,
                   ins=vd_links + [f"bd{b}" for b in range(n_bank)],
                   outs=[f"pb{b}" for b in range(n_bank)],
                   schema=PackStage.metrics_schema())
    else:
        topo.stage("dedup", build_dedup_sharded, n_shards=n_shards,
                   sandbox=sb,
                   ins=[f"vd{i}" for i in range(n_shards)], outs=["dp"],
                   schema=DedupStage.metrics_schema())
        topo.stage("pack", build_pack, n_bank=n_bank, sandbox=sb,
                   ins=["dp"] + [f"bd{b}" for b in range(n_bank)],
                   outs=[f"pb{b}" for b in range(n_bank)],
                   schema=PackStage.metrics_schema())
    for b in range(n_bank):
        topo.stage(f"bank{b}", build_bank, bank_idx=b, slot=slot, sandbox=sb,
                   ins=[f"pb{b}"], outs=[f"bp{b}", f"bd{b}"],
                   credit_gated=True, schema=BankStage.metrics_schema())
    topo.stage("poh", build_poh, n_bank=n_bank, sandbox=sb,
               ins=[f"bp{b}" for b in range(n_bank)], outs=["ps"],
               credit_gated=True, schema=PohStage.metrics_schema())
    topo.stage("shred", build_shred, secret=secret, slot=slot, sandbox=sb,
               ins=["ps"], outs=["ss"])
    topo.stage("store", build_store, leader_pub=leader_pub, sandbox=sb,
               ins=["ss"])
    return topo
