"""One FULL validator loop — the node the cluster harness boots N of.

Every subsystem here is the repo's real one, composed the way a
standalone validator composes them (the reference's fd_firedancer
topology, SURVEY §flamenco/§choreo/§disco), driven cooperatively so a
whole cluster fits one box deterministically:

  - cluster discovery: a real `runtime/gossip.GossipNode` over loopback
    UDP (CRDS push/pull, signed contact info) advertising this node's
    TVU and repair ports;
  - block intake: a TVU UDP socket feeding `runtime/fec_resolver`
    (per-shred merkle membership + one leader-signature check per FEC
    set against the wsample epoch schedule) into the flamenco
    `Blockstore`;
  - turbine: received shreds retransmit to this node's children per
    `protocol/shred_dest` (the stake-ordered tree every node derives
    identically from the epoch stakes); the leader sends each shred to
    its tree root.  Every arrival lands in a receipt ledger
    (slot/idx/sender/lane) so the harness can audit that shreds only
    ever travel tree-legal paths (or repair);
  - repair: a `runtime/repair.RepairServer` serving this node's
    blockstore, and a client that walks orphan chains (Orphan /
    HighestWindowIndex / WindowIndex with retry+backoff+peer rotation)
    verifying every repaired shred's merkle proof + leader signature
    before it enters block history;
  - replay + consensus: complete slots replay through
    `flamenco/runtime.replay_block` onto a funk fork tree tracked by
    choreo `Forks`, fork choice by choreo `Ghost`, voting through
    choreo `Tower`/`Voter` as REAL signed vote transactions on the
    wire; roots advance by a supermajority-depth rule that publishes
    funk + status cache and prunes ghost/forks;
  - leader: when the epoch schedule names this node, it executes its
    TPU inbox against the live bank (`SlotExecution` — the staged
    status-cache gate keeps resubmitted txns exactly-once across
    handoffs), builds real PoH entries, shreds them (reedsol parity +
    merkle + signature) and fans them out over the tree;
  - cold boot: `cold_boot_from_snapshot` rebuilds bank state from a
    peer's snapshot archive (flamenco/snapshot) and rejoins by
    repairing forward — the laggard-catchup path.
"""

from __future__ import annotations

import hashlib
import socket
from collections import deque
from dataclasses import dataclass

from firedancer_tpu.choreo.forks import Forks
from firedancer_tpu.choreo.ghost import Ghost
from firedancer_tpu.choreo.voter import Voter
from firedancer_tpu.flamenco.blockstore import Blockstore, StatusCache
from firedancer_tpu.flamenco.runtime import SlotExecution, replay_block
from firedancer_tpu.funk import Funk, make_funk
from firedancer_tpu.ops import bmtree
from firedancer_tpu.ops.ref import ed25519_ref as ref
from firedancer_tpu.protocol import shred as fs
from firedancer_tpu.protocol import txn as ft
from firedancer_tpu.protocol.shred_dest import NO_DEST, Dest, ShredDest
from firedancer_tpu.protocol.wsample import EpochLeaders, epoch_leaders
from firedancer_tpu.runtime import repair as fr
from firedancer_tpu.runtime.fec_resolver import FecResolver
from firedancer_tpu.runtime.gossip import GossipNode
from firedancer_tpu.runtime.poh import PohChain
from firedancer_tpu.runtime.poh_stage import build_entry, parse_entry
from firedancer_tpu.runtime.repair import RepairClient, RepairServer
from firedancer_tpu.runtime.shred_stage import deshred_entry_batch
from firedancer_tpu.runtime.shredder import EntryBatchMeta, Shredder
from firedancer_tpu.utils.rng import Rng

VOTE_MAGIC = b"FDVT"  # vote-txn datagram tag on the TVU wire

MAX_UDP = 65536


@dataclass(frozen=True)
class GenesisConfig:
    """What every validator of one cluster agrees on before slot 1:
    identities + stakes (the epoch-stake set the wsample leader schedule
    and the Turbine tree both derive from), funded accounts, and the
    recent blockhashes the txn gate honors."""

    stakes: tuple  # ((pubkey, stake), ...) sorted stake desc, then pubkey
    accounts: tuple = ()  # ((pubkey, lamports), ...)
    blockhashes: tuple = ()
    epoch: int = 0
    slot0: int = 1
    slot_cnt: int = 128

    @property
    def root_slot(self) -> int:
        return self.slot0 - 1

    @property
    def total_stake(self) -> int:
        return sum(s for _, s in self.stakes)

    def leaders(self) -> EpochLeaders:
        return epoch_leaders(self.epoch, self.slot0, self.slot_cnt,
                             list(self.stakes))


@dataclass
class ShredReceipt:
    """One shred arrival: the per-node receipt ledger row the turbine
    fanout audit replays the tree against."""

    slot: int
    idx: int
    is_data: bool
    fec_set_idx: int
    src: tuple  # (host, port) the datagram came from
    lane: str  # "turbine" | "repair"


class _RepairFace:
    """repair.RepairServer-compatible face over the flamenco Blockstore
    (get / highest) so one block history serves both replay and repair."""

    def __init__(self, bs: Blockstore):
        self._bs = bs

    def get(self, slot: int, idx: int):
        return self._bs.shreds.get((slot, idx))

    def highest(self, slot: int, min_idx: int = 0):
        m = self._bs.meta.get(slot)
        if m is None or not m.received:
            return None
        hi = max(m.received)
        if hi < min_idx:
            return None
        return self._bs.shreds.get((slot, hi))


class Validator:
    def __init__(
        self,
        secret: bytes,
        *,
        genesis: GenesisConfig,
        clock,  # () -> ms, the cluster's deterministic wallclock
        seed: int = 0,
        index: int = 0,
        fanout: int = 2,
        txns_per_microblock: int = 8,
        tick_hashes: int = 8,
        max_repair_attempts: int = 3,
        repair_spins: int = 400,
    ):
        self.secret = secret
        self.pubkey = ref.public_key(secret)
        self.genesis = genesis
        self.clock = clock
        self.index = index
        self.fanout = fanout
        self.txns_per_microblock = txns_per_microblock
        self.tick_hashes = tick_hashes
        self.max_repair_attempts = max_repair_attempts
        self.repair_spins = repair_spins
        self._stake_of = dict(genesis.stakes)
        self.stake = self._stake_of.get(self.pubkey, 0)
        self.lsched = genesis.leaders()

        # -- wire endpoints (all real loopback UDP) --------------------------
        self.tvu_sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:  # shred fan-in bursts: do not let the kernel drop silently
            self.tvu_sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF,
                                     1 << 20)
        except OSError:
            pass
        self.tvu_sock.bind(("127.0.0.1", 0))
        self.tvu_sock.setblocking(False)
        self.tpu_sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.tpu_sock.bind(("127.0.0.1", 0))
        self.tpu_sock.setblocking(False)
        self.blockstore = Blockstore()
        self.repair_server = RepairServer(_RepairFace(self.blockstore),
                                          secret)
        self.repair_client = RepairClient(secret,
                                          rng=Rng(seed, 0x4EA1 + index))
        self.gossip = GossipNode(
            secret,
            tvu_port=self.tvu_sock.getsockname()[1],
            repair_port=self.repair_server.addr[1],
            clock=clock,
        )
        self.gossip.set_stakes(dict(genesis.stakes))

        # -- bank state ------------------------------------------------------
        self.funk = make_funk()
        self.status_cache = StatusCache()
        self._apply_genesis()
        self.forks = Forks(genesis.root_slot)
        self.ghost = Ghost(genesis.root_slot)
        self.voter = Voter(vote_account=self.pubkey,
                           voter_pubkey=self.pubkey,
                           sign=lambda msg: ref.sign(secret, msg))
        self.resolver = FecResolver(max_inflight=64)
        self.shredder = Shredder(
            signer=lambda root: ref.sign(secret, root), shred_version=1)

        # -- ledgers / loop state -------------------------------------------
        self.blocks: dict[int, object] = {}  # slot -> BlockResult
        self.landed: dict[int, list[bytes]] = {}  # slot -> landed first-sigs
        self.receipts: list[ShredReceipt] = []
        self.rejected_sets = 0  # completed FEC sets failing the leader sig
        self.missed_slots: list[int] = []
        self.dead_slots: set[int] = set()  # gave up repairing
        self._repair_attempts: dict[int, int] = {}
        self._retransmitted: set[tuple[int, int]] = set()
        self._seen_slots: set[int] = set()
        self._pending_votes: dict[int, list] = {}  # slot -> [(pk, stake, bh)]
        self._applied_votes: dict[bytes, int] = {}  # voter pk -> latest slot
        self.tpu_pending: deque = deque()
        self.tpu_seen: set[bytes] = set()
        self._outbox: deque = deque()  # (addr, datagram)
        self.outbox_rate = 8  # datagrams sent per step
        self._dest_addrs: dict[bytes, tuple] = {}  # pubkey -> tvu addr
        self._sdest: ShredDest | None = None
        self.alive = True
        self.frozen = False
        self.vote_conflicts = 0
        self.cold_boots = 0
        self.repaired_shreds = 0
        self.repair_kinds: dict[str, int] = {}
        self.rooted_slots: list[int] = []  # published path, oldest first

    # -- genesis / identity --------------------------------------------------

    def _apply_genesis(self) -> None:
        from firedancer_tpu.flamenco.runtime import acct_build

        for pk, lamports in self.genesis.accounts:
            self.funk.rec_insert(None, pk, acct_build(lamports))
        for bh in self.genesis.blockhashes:
            self.status_cache.register_blockhash(bh, self.genesis.root_slot)

    @property
    def tvu_addr(self):
        return self.tvu_sock.getsockname()

    @property
    def tpu_addr(self):
        return self.tpu_sock.getsockname()

    def leader_for(self, slot: int) -> bytes | None:
        return self.lsched.leader_for_slot(slot)

    def is_leader(self, slot: int) -> bool:
        return self.leader_for(slot) == self.pubkey

    # -- turbine tree --------------------------------------------------------

    def build_dests(self, tvu_addrs: dict[bytes, tuple]) -> None:
        """Fix the turbine destination set: stake order comes from the
        EPOCH STAKES (identical on every node — tree agreement must not
        depend on gossip convergence); addresses come from gossip
        discovery.  Called once the harness sees full discovery."""
        self._dest_addrs = dict(tvu_addrs)
        dests = [Dest(pubkey=pk, stake=st) for pk, st in self.genesis.stakes]
        self._sdest = ShredDest(dests, self.lsched, self.pubkey)

    def dest_table_from_gossip(self) -> dict[bytes, tuple]:
        out = {self.pubkey: self.tvu_addr}
        for pk, info in self.gossip.table.items():
            out[pk] = (socket.inet_ntoa(info.ip4.to_bytes(4, "big")),
                       info.tvu_port)
        return out

    def _dest_pk(self, i: int) -> bytes:
        return self._sdest.dests[i].pubkey

    # -- the cooperative loop ------------------------------------------------

    def step(self) -> None:
        """One sweep: wire in, wire out, replay, root housekeeping."""
        if not self.alive:
            return
        if self.frozen:
            # a frozen node's NIC drops: drain and discard so the queues
            # never deliver stale traffic at thaw (the laggard fault)
            self._drain_discard()
            return
        self.gossip.poll()
        self.repair_server.poll()
        self.poll_wire()
        self.drain_outbox()
        self.try_replay()

    def poll_wire(self, burst: int = 64) -> None:
        """TVU (shreds + votes) and TPU (txn submissions) intake."""
        for _ in range(burst):
            try:
                data, src = self.tvu_sock.recvfrom(MAX_UDP)
            except (BlockingIOError, InterruptedError):
                break
            if data[:4] == VOTE_MAGIC:
                self._on_vote(bytes(data[4:]))
            else:
                self._on_shred(bytes(data), src, lane="turbine")
        for _ in range(burst):
            try:
                data, _src = self.tpu_sock.recvfrom(MAX_UDP)
            except (BlockingIOError, InterruptedError):
                break
            self._on_tpu(bytes(data))

    def _drain_discard(self) -> None:
        for sock in (self.tvu_sock, self.tpu_sock,
                     self.gossip.sock, self.repair_server.sock):
            for _ in range(256):
                try:
                    sock.recvfrom(MAX_UDP)
                except (BlockingIOError, InterruptedError):
                    break

    def drain_outbox(self) -> None:
        for _ in range(self.outbox_rate):
            if not self._outbox:
                return
            addr, dg = self._outbox.popleft()
            self.tvu_sock.sendto(dg, addr)

    def close(self) -> None:
        self.alive = False
        for sock in (self.tvu_sock, self.tpu_sock):
            sock.close()
        self.gossip.close()
        self.repair_server.close()
        self.repair_client.close()

    # -- shred ingest + turbine retransmit -----------------------------------

    def _on_shred(self, buf: bytes, src, lane: str) -> None:
        s = fs.parse(buf)
        if s is None:
            return
        self.receipts.append(ShredReceipt(
            slot=s.slot, idx=s.idx, is_data=s.is_data,
            fec_set_idx=s.fec_set_idx, src=src, lane=lane))
        # repair watches SEEN slots, not just blockstore-partial ones: a
        # set stuck in the resolver (no coding shred yet — the leader
        # died before parity went out) is invisible to the blockstore
        # but must still drive repair toward recovery-or-missed
        self._seen_slots.add(s.slot)
        if lane == "turbine":
            key = (s.slot, s.idx if s.is_data else (1 << 32) + s.idx)
            if key not in self._retransmitted and self._sdest is not None:
                self._retransmitted.add(key)
                for ci in self._sdest.children_for(
                    s.slot, s.idx, s.is_data, fanout=self.fanout
                ):
                    addr = self._dest_addrs.get(self._dest_pk(ci))
                    if addr is not None:
                        self._outbox.append((addr, buf))
        out = self.resolver.add_shred(buf)
        if out is not None:
            self._on_fec_set(out)

    def _on_fec_set(self, st) -> None:
        """A completed FEC set: ONE leader-signature check against the
        epoch schedule gates the whole set into block history (the
        fd_fec_resolver amortization; membership proofs were checked
        per shred by the resolver)."""
        leader = self.leader_for(st.slot)
        sig = fs.parse(st.data_shreds[0]).signature(st.data_shreds[0])
        if leader is None or not ref.verify(st.merkle_root, sig, leader):
            self.rejected_sets += 1
            return
        for buf in st.data_shreds:
            self.blockstore.insert_shred(buf)

    def _verify_repaired(self, buf: bytes) -> bool:
        """A repaired shred arrives alone (no set context): full merkle
        membership + leader signature before it may enter block history
        — repair peers are untrusted."""
        s = fs.parse(buf)
        if s is None or not s.is_data:
            return False
        leader = self.leader_for(s.slot)
        if leader is None:
            return False
        leaf = bmtree.hash_leaf_full(s.merkle_leaf_data(buf))
        root = bmtree.verify_proof(leaf, s.idx - s.fec_set_idx,
                                   s.merkle_proof(buf))
        return ref.verify(root, s.signature(buf), leader)

    # -- votes ---------------------------------------------------------------

    def broadcast_vote(self, payload: bytes) -> None:
        dg = VOTE_MAGIC + payload
        for pk, addr in self._dest_addrs.items():
            if pk != self.pubkey:
                self._outbox.append((addr, dg))

    def _on_vote(self, payload: bytes) -> None:
        from firedancer_tpu.flamenco.vote_program import VOTE_IX
        from firedancer_tpu.flamenco.types import U32

        t = ft.txn_parse(payload)
        if t is None:
            return
        addrs = t.acct_addrs(payload)
        voter_pk = addrs[0]
        stake = self._stake_of.get(voter_pk, 0)
        if stake <= 0:
            return
        if not ref.verify(t.message(payload), t.signatures(payload)[0],
                          voter_pk):
            return
        instr = t.instrs[0]
        data = payload[instr.data_off : instr.data_off + instr.data_sz]
        tag, off = U32.decode(data, 0)
        if tag != 2:
            return
        vote, _ = VOTE_IX.decode(data, off)
        slot = vote.slots[-1]
        self.apply_vote(voter_pk, slot, stake, vote.hash)

    def apply_vote(self, voter_pk: bytes, slot: int, stake: int,
                   bank_hash: bytes) -> None:
        if self._applied_votes.get(voter_pk, -1) >= slot:
            return  # LMD: only newer votes move stake
        if slot <= self.ghost.root:
            return  # rooted history: nothing left to choose
        if slot not in self.ghost.nodes:
            # buffered until replay inserts the slot (partition heal:
            # the other side's votes arrive before its blocks replay)
            self._pending_votes.setdefault(slot, []).append(
                (voter_pk, stake, bank_hash))
            return
        blk = self.blocks.get(slot)
        if blk is not None and bank_hash != blk.bank_hash:
            self.vote_conflicts += 1
            return
        self._applied_votes[voter_pk] = slot
        self.ghost.vote(voter_pk, slot, stake)

    def _flush_pending_votes(self, slot: int) -> None:
        for voter_pk, stake, bank_hash in self._pending_votes.pop(slot, []):
            self.apply_vote(voter_pk, slot, stake, bank_hash)

    def is_ancestor(self, a: int, b: int) -> bool:
        """Ancestry oracle for the tower: the rooted chain is by
        definition an ancestor of everything live, and pruned slots are
        on no live fork — ghost's raw walk would KeyError on a tower
        vote older than the root (deep lockouts outlive root advance)."""
        if a <= self.ghost.root:
            return True
        if a not in self.ghost.nodes or b not in self.ghost.nodes:
            return False
        return self.ghost.is_ancestor(a, b)

    def ghost_weight(self, slot: int) -> int:
        """Weight oracle for the tower's threshold check: a pruned
        (rooted) slot holds the whole cluster by definition."""
        if slot in self.ghost.nodes:
            return self.ghost.weight(slot)
        return self.genesis.total_stake if slot <= self.ghost.root else 0

    def maybe_vote(self) -> None:
        """Vote for the ghost head through the tower's safety checks;
        an approved vote is a REAL signed vote txn on the wire."""
        head = self.ghost.head()
        if head == self.ghost.root or head not in self.blocks:
            return
        payload = self.voter.maybe_vote(
            head,
            self.genesis.blockhashes[0],
            is_ancestor=self.is_ancestor,
            ghost_weight=self.ghost_weight,
            total_stake=self.genesis.total_stake,
            bank_hash=self.blocks[head].bank_hash,
        )
        if payload is None:
            return
        self.apply_vote(self.pubkey, head, self.stake,
                        self.blocks[head].bank_hash)
        self.broadcast_vote(payload)

    # -- replay --------------------------------------------------------------

    def _parent_slot_of(self, slot: int) -> int | None:
        buf = self.blockstore.shreds.get((slot, 0))
        if buf is None:
            return None
        s = fs.parse(buf)
        return slot - s.parent_off

    def _ancestor_slots(self, parent_slot: int) -> set[int]:
        """The executing bank's full-chain ancestor set for the
        status-cache gate: the live fork path PLUS the rooted history —
        everything below the root is canonical by definition, so a txn
        rooted long ago must still answer ALREADY_PROCESSED when
        resubmitted (a root-relative set would forget it once the root
        advances past its landing slot)."""
        out = {parent_slot} | set(self.forks.ancestors(parent_slot))
        out.update(self.rooted_slots)
        out.add(self.genesis.root_slot)
        return out

    def try_replay(self) -> None:
        for slot in sorted(self.blockstore.meta):
            if slot <= self.forks.root_slot or slot in self.blocks:
                continue
            if slot in self.dead_slots:
                continue
            if not self.blockstore.is_complete(slot):
                continue
            parent = self._parent_slot_of(slot)
            if parent is None:
                continue
            if parent not in self.forks or not self.forks.get(parent).frozen:
                continue  # repair_tick walks the orphan chain
            self.replay_slot(slot, parent)
        self.maybe_vote()
        self.maybe_publish()

    def replay_slot(self, slot: int, parent_slot: int) -> bool:
        parent = self.forks.get(parent_slot)
        entries = [parse_entry(e) for e in deshred_entry_batch(
            self.blockstore.entry_batch_bytes(slot))]
        ancestors = self._ancestor_slots(parent_slot)
        res = replay_block(
            self.funk, slot=slot, entries=entries,
            poh_seed=parent.poh_hash,
            parent_bank_hash=parent.bank_hash, parent_xid=parent.xid,
            status_cache=self.status_cache, ancestors=ancestors,
        )
        if res is None:
            # PoH fraud: the block can never become part of this node's
            # chain; remember so replay doesn't spin on it
            self.dead_slots.add(slot)
            return False
        poh_hash = entries[-1][1] if entries else parent.poh_hash
        self.forks.insert(slot, parent_slot)
        self.forks.freeze(slot, xid=res.xid, bank_hash=res.bank_hash,
                          poh_hash=poh_hash)
        self.ghost.insert(slot, parent_slot)
        self.blocks[slot] = res
        self.landed[slot] = [
            ft.txn_parse(p).signatures(p)[0]
            for _n, _h, txns in entries for p in txns
        ]
        self._flush_pending_votes(slot)
        return True

    # -- root advance --------------------------------------------------------

    root_lag = 4  # head-to-root depth before a publish is considered

    def maybe_publish(self) -> None:
        """Advance the root to the head's `root_lag`-deep ancestor once a
        supermajority of stake is voting inside that subtree: funk +
        status cache publish the chain, ghost/forks prune everything
        else (fd_replay's funk_publish coordination)."""
        head = self.ghost.head()
        candidate = head
        for _ in range(self.root_lag):
            parent = self.ghost.nodes[candidate].parent
            if parent is None:
                break
            candidate = parent
        if candidate == self.ghost.root or candidate == self.genesis.root_slot:
            return
        if 3 * self.ghost.weight(candidate) < 2 * self.genesis.total_stake:
            return
        old_root = self.forks.root_slot
        path = [s for s in sorted(
            set(self.forks.ancestors(candidate)) | {candidate})
            if s > old_root]
        for s in path:
            if s in self.blocks:
                self.status_cache.commit_block(self.blocks[s].xid)
        self.funk.txn_publish(self.blocks[candidate].xid)
        pruned = self.forks.publish(candidate)
        # the published chain's funk txns are GONE (folded into root, the
        # children reparented to root): a later block parented exactly at
        # the new root must fork off funk's root (parent_xid=None), not
        # off a deleted xid
        self.forks.get(candidate).xid = None
        for s in pruned:
            if s in self.blocks and s not in path:
                self.status_cache.drop_block(self.blocks[s].xid)
        self.ghost.publish(candidate)
        self.rooted_slots.extend(path)

    @property
    def root_slot(self) -> int:
        return self.forks.root_slot

    def root_bank_hash(self) -> bytes:
        f = self.forks.get(self.forks.root_slot)
        return f.bank_hash

    def best_chain(self) -> list[int]:
        """Published history + the ghost-head fork, oldest first — the
        chain this node currently believes in."""
        out = []
        cur = self.ghost.head()
        while cur is not None and cur != self.ghost.root:
            out.append(cur)
            cur = self.ghost.nodes[cur].parent
        return self.rooted_slots + out[::-1]

    def chain_landed(self) -> set[bytes]:
        """First signatures of every txn landed on the best chain."""
        out: set[bytes] = set()
        for slot in self.best_chain():
            out.update(self.landed.get(slot, ()))
        return out

    # -- leader path ---------------------------------------------------------

    def _on_tpu(self, payload: bytes) -> None:
        t = ft.txn_parse(payload)
        if t is None:
            return
        sig = t.signatures(payload)[0]
        if sig in self.tpu_seen:
            return
        self.tpu_seen.add(sig)
        self.tpu_pending.append(payload)

    def produce_block(self, slot: int) -> bool:
        """Leader side: execute the TPU inbox on the fork-choice head,
        build PoH entries, shred, queue the turbine fan-out.  The block
        freezes locally immediately (the leader replays nothing)."""
        if self._sdest is None or slot in self.blocks:
            return False
        parent_slot = self.ghost.head()
        parent = self.forks.get(parent_slot)
        if not parent.frozen or slot <= parent_slot:
            return False
        txns = list(self.tpu_pending)
        self.tpu_pending.clear()
        # inbox dedup covers the PENDING window only: a txn whose first
        # landing died with a fork must re-enter when the client
        # resubmits it (the status-cache gate owns real dup rejection)
        self.tpu_seen.clear()
        ancestors = self._ancestor_slots(parent_slot)
        sx = SlotExecution(
            self.funk, slot=slot, parent_bank_hash=parent.bank_hash,
            parent_xid=parent.xid, status_cache=self.status_cache,
            ancestors=ancestors,
        )
        chain = PohChain(hash=parent.poh_hash)
        entries = []
        landed_sigs = []
        for off in range(0, len(txns), self.txns_per_microblock):
            group = txns[off : off + self.txns_per_microblock]
            payloads, sigs = [], []
            for p in group:
                t = ft.txn_parse(p)
                if t is None:
                    continue
                r = sx.execute(p, t)
                if r.fee > 0:  # landed (the entry-inclusion predicate)
                    payloads.append(p)
                    sigs.append(t.signatures(p)[0])
            if not payloads:
                continue
            chain.mixin(hashlib.sha256(b"".join(sigs)).digest())
            entries.append((1, chain.hash, payloads))
            landed_sigs.extend(sigs)
        # closing tick: the slot's clock keeps running past the last txn
        chain.append(self.tick_hashes)
        entries.append((self.tick_hashes, chain.hash, []))
        poh_hash = chain.hash
        res = sx.seal(poh_hash)
        self.forks.insert(slot, parent_slot)
        self.forks.freeze(slot, xid=sx.xid, bank_hash=res.bank_hash,
                          poh_hash=poh_hash)
        self.ghost.insert(slot, parent_slot)
        self.blocks[slot] = res
        self.landed[slot] = landed_sigs

        batch = bytearray()
        for e in entries:
            eb = build_entry(*e)
            batch += len(eb).to_bytes(4, "little")
            batch += eb
        parent_off = min(slot - parent_slot, 0xFFFF)
        sets = self.shredder.entry_batch_to_fec_sets(
            bytes(batch), slot=slot,
            meta=EntryBatchMeta(parent_offset=parent_off,
                                block_complete=True),
        )
        for st in sets:
            for buf in st.data_shreds:
                self.blockstore.insert_shred(buf)
            for buf in st.data_shreds + st.parity_shreds:
                s = fs.parse(buf)
                di = self._sdest.first_for(s.slot, s.idx, s.is_data)
                if di == NO_DEST:
                    continue
                addr = self._dest_addrs.get(self._dest_pk(di))
                if addr is not None:
                    self._outbox.append((addr, buf))
        self.maybe_vote()
        return True

    # -- repair (catch-up) ---------------------------------------------------

    def repair_peers(self) -> list[tuple]:
        """((addr, recipient_pubkey), ...) of live-looking peers, stake
        order — the gossip table is the live view (expired/dead peers
        fell out of it via GossipNode.housekeeping), and the recipient
        pubkey rides along because peers' signing repair servers refuse
        misdirected requests."""
        out = []
        for pk, _stake in self.genesis.stakes:
            info = self.gossip.table.get(pk)
            if info is None or pk == self.pubkey:
                continue
            addr = (socket.inet_ntoa(info.ip4.to_bytes(4, "big")),
                    info.repair_port)
            out.append((addr, pk))
        return out

    def _repair_one(self, peers, slot: int, idx: int, *, kind: str,
                    spin) -> bytes | None:
        self.repair_kinds[kind] = self.repair_kinds.get(kind, 0) + 1
        got = self.repair_client.request(
            peers, slot, idx, kind=kind, spin=spin,
            max_spins=self.repair_spins, retries=max(len(peers) - 1, 0),
        )
        if got is not None and self._verify_repaired(got):
            s = fs.parse(got)
            if s.slot != slot:
                # the client's nonce+slot validation already rejects
                # mismatched replies; this is the last-line boundary so a
                # future client change can never let a validly-signed
                # OTHER-slot shred count as progress for this request
                return None
            self.receipts.append(ShredReceipt(
                slot=s.slot, idx=s.idx, is_data=s.is_data,
                fec_set_idx=s.fec_set_idx,
                src=self.repair_client.last_peer or ("", 0),
                lane="repair"))
            self._seen_slots.add(s.slot)
            self.blockstore.insert_shred(got)
            self.repaired_shreds += 1
            return got
        return None

    def repair_tick(self, spin=None, *, current_slot: int | None = None,
                    budget: int = 8) -> int:
        """Bounded repair sweep: walk orphan chains back from known
        slots, then fill holes in incomplete past slots.  `spin` pumps
        the serving side (the harness: the REST of the cluster keeps
        running — catch-up happens under load).  Returns shreds
        recovered this sweep."""
        if self._sdest is None:
            return 0
        peers = self.repair_peers()
        if not peers:
            return 0
        got = 0
        # orphan walk: a slot we can see whose parent we lack
        known = set(self.blockstore.meta) | set(self.forks.slots())
        for slot in sorted(self.blockstore.meta):
            if got >= budget:
                break
            if slot <= self.forks.root_slot:
                continue
            parent = self._parent_slot_of(slot)
            if parent is None or parent <= self.forks.root_slot:
                continue
            if parent in known or parent in self.dead_slots:
                continue
            shred = self._repair_one(peers, parent, 0, kind="orphan",
                                     spin=spin)
            if shred is not None:
                got += 1
            else:
                self._bump_attempts(parent)
        # hole fill: incomplete (or resolver-stuck) slots behind the tip
        tip = current_slot if current_slot is not None else (
            max(set(self.blockstore.meta) | self._seen_slots, default=0))
        for slot in sorted(set(self.blockstore.meta) | self._seen_slots):
            if got >= budget:
                break
            if slot <= self.forks.root_slot:
                continue
            if slot >= tip or slot in self.dead_slots or slot in self.blocks:
                continue
            m = self.blockstore.meta.get(slot)
            if m is not None and m.complete:
                continue
            if m is None or m.last_index is None:
                # probe strictly PAST what we hold: a peer echoing back a
                # shred we already have is not progress, and a slot the
                # whole cluster only has a fragment of (leader died
                # mid-broadcast) must time out toward missed, not loop
                probe = (max(m.received, default=-1) + 1) if m else 0
                if self._repair_one(peers, slot, probe,
                                    kind="highest_window_index",
                                    spin=spin) is None:
                    self._bump_attempts(slot)
                    continue
                got += 1
                m = self.blockstore.meta[slot]
            for idx in m.missing():
                if got >= budget:
                    break
                if self._repair_one(peers, slot, idx, kind="window_index",
                                    spin=spin) is not None:
                    got += 1
                else:
                    self._bump_attempts(slot)
                    break
        return got

    def _bump_attempts(self, slot: int) -> None:
        n = self._repair_attempts.get(slot, 0) + 1
        self._repair_attempts[slot] = n
        if n >= self.max_repair_attempts:
            # nobody can serve it (leader died mid-broadcast): a MISSED
            # slot is an observation, not a fatal error
            self.dead_slots.add(slot)
            if slot not in self.missed_slots:
                self.missed_slots.append(slot)

    # -- snapshot cold boot --------------------------------------------------

    def write_snapshot(self, path: str) -> int:
        """Serve this node's published root as a snapshot archive (what
        a laggard cold-boots from)."""
        from firedancer_tpu.flamenco.snapshot import snapshot_write

        return snapshot_write(
            self.funk, path, slot=self.forks.root_slot,
            bank_hash=self.root_bank_hash(),
        )

    def cold_boot_from_snapshot(self, path: str) -> int:
        """Laggard catch-up, the heavy half: throw away local bank state
        and rebuild from a peer's snapshot — funk root at the snapshot
        slot, fresh fork/ghost trees rooted there — then rejoin by
        repairing forward.  Returns the snapshot slot."""
        from firedancer_tpu.flamenco.snapshot import snapshot_load

        funk, man = snapshot_load(path)
        self.funk = funk
        self.status_cache = StatusCache()
        for bh in self.genesis.blockhashes:
            self.status_cache.register_blockhash(bh, man.slot)
        self.forks = Forks(man.slot, root_bank_hash=man.bank_hash)
        # the snapshot's bank hash chains replay exactly like a locally
        # frozen parent; poh seed for the next slot comes from the next
        # block's shreds' parent chain (its producer used the real poh
        # hash, which rides IN the entries we replay — the chain check
        # seeds from the parent's poh_hash, so restore it from a peer's
        # fork record via repair of the root slot's last entry is not
        # needed: the harness guarantees root blocks carry poh in forks)
        self.ghost = Ghost(man.slot)
        from firedancer_tpu.choreo.tower import Tower

        self.voter.tower = Tower()
        self.voter.last_sent = man.slot
        self.blocks = {}
        self.landed = {}
        self.dead_slots = set()
        self._seen_slots = set()
        self.rooted_slots = []
        self._repair_attempts.clear()
        self._pending_votes.clear()
        self._applied_votes.clear()
        self.resolver = FecResolver(max_inflight=64)
        self.cold_boots += 1
        return man.slot

    def adopt_root_poh(self, poh_hash: bytes) -> None:
        """Cold boot rider: the snapshot manifest carries the bank hash
        but not the PoH tip; the harness hands it over from the serving
        peer's fork record (a real manifest's bank fields include it)."""
        self.forks.get(self.forks.root_slot).poh_hash = poh_hash


def make_cluster_genesis(
    n: int,
    *,
    seed: int = 0,
    base_stake: int = 1000,
    accounts: tuple = (),
    blockhashes: tuple = (),
    slot_cnt: int = 128,
    epoch: int = 0,
) -> tuple[GenesisConfig, list[bytes]]:
    """N identities with distinct, near-even stakes (uneven enough that
    weighted sampling is exercised, even enough that the wsample leader
    schedule rotates through several identities), in Agave stake order."""
    secrets = [hashlib.sha256(b"cluster-v-%d-%d" % (seed, i)).digest()
               for i in range(n)]
    pairs = []
    for i, sec in enumerate(secrets):
        pairs.append((ref.public_key(sec), base_stake + 7 * i))
    pairs.sort(key=lambda kv: (-kv[1], kv[0]))
    genesis = GenesisConfig(
        stakes=tuple(pairs), accounts=tuple(accounts),
        blockhashes=tuple(blockhashes), slot_cnt=slot_cnt, epoch=epoch,
    )
    return genesis, secrets
