"""The flagship model: the full leader TPU pipeline, assembled.

    benchg -> verify (TPU sigverify, xN round-robin) -> dedup
           -> pack -> bank xB -> poh -> shred -> store

This is the e2e slice of the reference's Frankendancer leader topology
(/root/reference/src/app/fdctl/run/topos/fd_frankendancer.c:96-111) with
ingress replaced by the synthetic generator (net/quic are later
milestones) and the store stage doubling as the FEC-resolver receive path
that proves the emitted shreds reassemble.  Stages talk over tango shm
links and are driven either by the in-process cooperative scheduler here
(tests, bench) or by the process topology runner.

Link map (names follow the reference's link table, fd_frankendancer.c:55-83):
    gen_verify      benchg -> verify xN (round-robin by seq)
    verify_dedup[i] verify i -> dedup (single-producer rings)
    dedup_pack      dedup -> pack
    pack_bank[b]    pack -> bank b (microblock frames)
    bank_poh[b]     bank b -> poh (executed microblocks)
    bank_done[b]    bank b -> pack (lock release; the reference uses
                    bank_busy fseqs, same role)
    poh_shred       poh -> shred (entries)
    shred_store     shred -> store (wire shreds)
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

from firedancer_tpu.ops.ref import ed25519_ref as ref
from firedancer_tpu.runtime.bank import BankCtx, BankStage, default_bank_ctx
from firedancer_tpu.runtime.benchg import BenchGStage, gen_transfer_pool
from firedancer_tpu.runtime.dedup import DedupStage
from firedancer_tpu.runtime.pack_stage import NativePackStage, PackStage
from firedancer_tpu.runtime.poh_stage import PohStage
from firedancer_tpu.runtime.shred_stage import ShredStage
from firedancer_tpu.runtime.store import StoreStage
from firedancer_tpu.runtime.verify import VerifyStage
from firedancer_tpu.tango import shm


def resolve_native_pack(native_pack: bool | None) -> bool:
    """None = auto: use the fused native pack+dedup lane when the .so is
    available and FDTPU_NATIVE_PACK != 0 (the same auto-detect posture as
    the bank stage's native executor lane)."""
    if native_pack is not None:
        return bool(native_pack)
    from firedancer_tpu.pack import scheduler_native as sn

    return sn.available()


@dataclass
class LeaderPipeline:
    stages: list
    links: list
    benchg: BenchGStage
    verifies: list[VerifyStage]
    dedup: DedupStage | None  # None on the fused native-pack lane
    pack: PackStage
    banks: list[BankStage]
    poh: PohStage
    shred: ShredStage
    store: StoreStage
    leader_pub: bytes
    bank_ctx: BankCtx = None
    router: object = None  # ShardRouterStage in the sharded-serving form
    plane: object = None  # parallel/serve.ServePlane when mesh-sharded

    def run(self, *, max_iters: int = 200_000, until_txns: int | None = None,
            finish: bool = True):
        """Cooperative round-robin until pack has accepted `until_txns`
        txns (or max_iters sweeps), then drain the whole pipe to the
        store.  finish=False leaves the pipe hot (benchmark warmup)."""
        for _ in range(max_iters):
            for s in self.stages:
                s.run_once()
            if (
                until_txns is not None
                and self.pack.metrics.get("txn_in") >= until_txns
            ):
                break
        if finish:
            self.finish()

    def finish(self, *, max_sweeps: int = 50_000) -> None:
        """Drain: verify flush -> pack force-flush -> stop the poh clock ->
        shred flush -> sweep until quiescent."""
        if hasattr(self.benchg, "limit"):
            self.benchg.limit = 0  # stop generating (socket ingress
            #                        has no generator to stop)
        for v in self.verifies:
            v.flush()
        self._sweep(max_sweeps)
        self.pack.flush()
        self._sweep(max_sweeps)
        # stop the clock so tick entries stop flowing, then final shred
        self.poh.hashes_per_iter = 0
        self._sweep(max_sweeps)
        self.shred.flush(block_complete=True)
        self._sweep(max_sweeps)

    def _sweep(self, max_sweeps: int) -> None:
        """Run non-generator stages until none makes frag progress."""
        stages = [s for s in self.stages if s is not self.benchg]
        for _ in range(max_sweeps):
            progressed = False
            for s in stages:
                progressed |= bool(s.run_once())
            # pack may be waiting on schedulability rather than frags
            self.pack.after_credit()
            if not progressed and not self.pack.pack.pending_cnt():
                break

    def seal(self):
        """End of slot: bank hash over the state every bank committed,
        chaining the final PoH entry hash (what replay_block reproduces
        from the wire entries alone)."""
        return self.bank_ctx.seal(self.poh.last_entry_hash)

    def close(self):
        # Drop every stage's Producer/Consumer link views FIRST: a
        # lingering Fseq/mcache numpy view pins the mmap, close() then
        # fails with BufferError, and at interpreter exit every
        # SharedMemory.__del__ retries and spews 'cannot close exported
        # pointers exist' into whatever artifact tail captured stderr
        # (the BENCH_r03-05 pollution).  Ordering is the fix: views die,
        # THEN the mappings close, THEN the names unlink.
        if hasattr(self.benchg, "sock"):
            self.benchg.close()  # socket ingress: fd + native client
        for s in self.stages:
            half = getattr(s, "shred_half", None)
            if half is not None:  # fused poh+shred: the inner stage's
                half.ins = []     # link views must die too
                half.outs = []
                half.drop_native_views()
            s.ins = []
            s.outs = []
            # the in-crossing metrics plane + drainer plan hold views
            # over the metric segments a caller may own (the latency-
            # budget fixture attaches its own) — same ordering rule
            s.drop_native_views()
        import gc

        gc.collect()
        for link in self.links:
            link.close()
            link.unlink()

    def report(self) -> dict:
        return {s.name: dict(s.metrics.counters) for s in self.stages}


def build_leader_pipeline_from_config(cfg, **overrides) -> "LeaderPipeline":
    """Topology derived from a typed Config (utils/config.py) — the
    config_parse -> topos/fd_frankendancer.c split."""
    kw = dict(
        n_verify=cfg.layout.verify_stage_count,
        n_bank=cfg.layout.bank_stage_count,
        batch=cfg.verify.batch,
        max_msg_len=cfg.verify.max_msg_len,
        depth=cfg.verify.receive_buffer_depth,
        batch_deadline_s=cfg.verify.batch_deadline_ms / 1e3,
    )
    kw.update(overrides)
    return build_leader_pipeline(**kw)


def build_leader_pipeline(
    *,
    n_verify: int = 1,
    n_bank: int = 2,
    pool_size: int = 512,
    gen_limit: int | None = None,
    batch: int = 128,
    max_msg_len: int = 256,
    depth: int = 1024,
    batch_deadline_s: float = 0.002,
    slot: int = 1,
    leader_seed: bytes = b"leader",
    verify_precomputed: bool = False,
    verify_comb_slots: int = 0,
    bank_ctx: BankCtx | None = None,
    keep_entries: bool = False,
    keep_sets: bool = True,
    native_pack: bool | None = None,
    slot_clock=None,
    shed_keep: int | None = None,
    fuse_poh_shred: bool = False,
    udp_ingress: bool = False,
) -> LeaderPipeline:
    """keep_sets=False releases the shred stage from materializing
    FecSets in Python, which lets it adopt the zero-Python sweep lane
    (bench uses this; tests that read pipe.shred.sets keep the
    default).

    slot_clock (runtime/slot_clock.SlotClockCfg or a built SlotClock)
    runs the pipeline against the real wall-clock slot cadence: poh
    paces ticks to the deadline and seals/misses slots on schedule,
    pack closes the block at each boundary (the unscheduled tail
    carries over; shed_keep arms the load-shedding degraded mode), and
    the banks observe the boundaries.

    udp_ingress=True puts a real localhost socket at the front instead
    of the in-process generator: UdpIngressStage (native recvmmsg sweep
    when the net lane is up) publishes datagrams into gen_verify, so an
    e2e window covers ingress -> verify -> ... -> store over actual
    network bytes.  The caller feeds txns at pipe.benchg.addr."""
    use_native_pack = resolve_native_pack(native_pack)
    if slot_clock is not None:
        from firedancer_tpu.runtime.slot_clock import SlotClockCfg

        if isinstance(slot_clock, SlotClockCfg):
            # ONE anchor for every stage: each resolve_clock below then
            # derives identical boundaries from the same epoch
            slot_clock = slot_clock.anchored()
    uid = shm.fresh_uid()
    links = []

    def mklink(name, mtu, n_consumers=1, d=None):
        link = shm.ShmLink.create(
            f"fdtpu_{name}_{uid}", depth=d or depth, mtu=mtu, n_fseq=n_consumers
        )
        links.append(link)
        return link

    gen_verify = mklink("gv", mtu=1232, n_consumers=n_verify)
    verify_dedup = [mklink(f"vd{i}", mtu=4096) for i in range(n_verify)]
    # the fused native lane has no dedup stage: pack consumes the verify
    # links directly and probes the tcache inside its insert crossing
    dedup_pack = None if use_native_pack else mklink("dp", mtu=4096)
    pack_bank = [mklink(f"pb{b}", mtu=65536) for b in range(n_bank)]
    bank_poh = [mklink(f"bp{b}", mtu=65536) for b in range(n_bank)]
    bank_done = [mklink(f"bd{b}", mtu=64) for b in range(n_bank)]
    # the fused poh+shred crash domain has no poh->shred ring hop
    poh_shred = None if fuse_poh_shred else mklink("ps", mtu=65536)
    shred_store = mklink("ss", mtu=1232, d=4096)

    secret = hashlib.sha256(leader_seed).digest()
    leader_pub = ref.public_key(secret)

    if udp_ingress:
        from firedancer_tpu.runtime.net import UdpIngressStage

        benchg = UdpIngressStage(
            "net", outs=[shm.make_producer(gen_verify)], rx_burst=64
        )
    else:
        pool = gen_transfer_pool(pool_size)
        benchg = BenchGStage(
            pool, "benchg", outs=[shm.make_producer(gen_verify)],
            limit=gen_limit
        )
    verifies = [
        VerifyStage(
            f"verify{i}",
            ins=[shm.make_consumer(gen_verify, fseq_idx=i, lazy=32)],
            outs=[shm.make_producer(verify_dedup[i])],
            shard_idx=i,
            shard_cnt=n_verify,
            batch=batch,
            max_msg_len=max_msg_len,
            batch_deadline_s=batch_deadline_s,
            precomputed_ok=verify_precomputed,
            comb_slots=verify_comb_slots,
        )
        for i in range(n_verify)
    ]
    if use_native_pack:
        dedup = None
        pack = NativePackStage(
            "pack",
            ins=[shm.make_consumer(l, lazy=32) for l in verify_dedup]
            + [shm.make_consumer(l, lazy=8) for l in bank_done],
            outs=[shm.make_producer(l) for l in pack_bank],
            bank_cnt=n_bank,
            n_txn_ins=n_verify,
            clock=slot_clock,
            shed_keep=shed_keep,
        )
    else:
        dedup = DedupStage(
            "dedup",
            ins=[shm.make_consumer(l, lazy=32) for l in verify_dedup],
            outs=[shm.make_producer(dedup_pack)],
        )
        pack = PackStage(
            "pack",
            ins=[shm.make_consumer(dedup_pack, lazy=32)]
            + [shm.make_consumer(l, lazy=8) for l in bank_done],
            outs=[shm.make_producer(l) for l in pack_bank],
            bank_cnt=n_bank,
            clock=slot_clock,
            shed_keep=shed_keep,
        )
    # ONE live bank shared by every bank stage (the Frankendancer shape:
    # all bank tiles commit into the same Agave bank over the FFI)
    if bank_ctx is None:
        bank_ctx = default_bank_ctx(slot=slot)
    banks = [
        BankStage(
            f"bank{b}",
            ins=[shm.make_consumer(pack_bank[b], lazy=8)],
            outs=[shm.make_producer(bank_poh[b]), shm.make_producer(bank_done[b])],
            bank_idx=b,
            ctx=bank_ctx,
            clock=slot_clock,
        )
        for b in range(n_bank)
    ]
    for bstage in banks:
        bstage.require_credit = True
    if fuse_poh_shred:
        from firedancer_tpu.runtime.shred_stage import FusedPohShredStage

        poh = FusedPohShredStage(
            "poh_shred",
            ins=[shm.make_consumer(l, lazy=8) for l in bank_poh],
            outs=[shm.make_producer(shred_store)],
            clock=slot_clock,
            signer=lambda root: ref.sign(secret, root),
            secret=secret,
            shred_slot=slot,
            keep_sets=keep_sets,
        )
        shred = poh.shred_half
    else:
        poh = PohStage(
            "poh",
            ins=[shm.make_consumer(l, lazy=8) for l in bank_poh],
            outs=[shm.make_producer(poh_shred)],
            clock=slot_clock,
        )
        shred = ShredStage(
            "shred",
            ins=[shm.make_consumer(poh_shred, lazy=8)],
            outs=[shm.make_producer(shred_store)],
            signer=lambda root: ref.sign(secret, root),
            secret=secret,  # arms the native shredder lane when available
            slot=slot,
            keep_sets=keep_sets,
        )
    poh.require_credit = True
    if keep_entries:
        poh.entries = []
    # the leader's own store trusts its own signing path (the reference's
    # shred tile only signature-verifies shreds arriving from OTHER
    # leaders on the retransmit path, fd_fec_resolver_new's NULL-signer
    # contract); receive-path resolvers (repair, turbine ingest, tests)
    # keep full verification
    store = StoreStage(
        "store",
        ins=[shm.make_consumer(shred_store, lazy=64)],
        verify_sig=None,
        trust_membership=True,
    )
    stages = [benchg, *verifies] + ([dedup] if dedup else []) \
        + [pack, *banks, poh] \
        + ([] if fuse_poh_shred else [shred]) + [store]
    return LeaderPipeline(
        stages=stages,
        links=links,
        benchg=benchg,
        verifies=verifies,
        dedup=dedup,
        pack=pack,
        banks=banks,
        poh=poh,
        shred=shred,
        store=store,
        leader_pub=leader_pub,
        bank_ctx=bank_ctx,
    )


def build_sharded_leader_pipeline(
    *,
    plane=None,
    n_shards: int = 4,
    batch_per_shard: int = 64,
    pool_size: int = 512,
    gen_limit: int | None = None,
    max_msg_len: int = 256,
    depth: int = 1024,
    shard_depth: int = 512,
    batch_deadline_s: float = 0.002,
    slot: int = 1,
    leader_seed: bytes = b"leader",
    n_bank: int = 2,
    bank_ctx: BankCtx | None = None,
    verify_precomputed: bool = False,
    hashes_per_tick: int = 64,
    native_pack: bool | None = None,
) -> LeaderPipeline:
    """The SHARDED serving pipeline (cooperative form): real leader
    traffic through the device mesh.

        benchg -> router -> sv{i} (per-shard rings, seq%N deterministic)
               -> sharded-verify (ONE stage, ONE pjit step over the mesh)
               -> dedup -> pack -> bank xB -> poh -> shred -> store

    The sharded-verify stage consumes all N per-shard rings and runs the
    plane's single compiled leader step (verify + reedsol + PoH lanes,
    partition specs matched across hops — parallel/serve.py); the shred
    stage's normal-shape FEC parity and the poh stage's tick-span
    self-audit ride the SAME plane.  Downstream of verify the host lane
    (dedup -> pack -> bank -> poh -> shred -> store) is byte-identical
    to the unsharded pipeline.

    plane: a prebuilt (ideally warmed) ServePlane; None builds one for
    `n_shards` devices.  hashes_per_tick doubles as the plane's PoH span
    length so tick spans match the compiled shape.
    """
    from firedancer_tpu.parallel.router import ShardRouterStage
    from firedancer_tpu.parallel.serve import (
        ServeConfig,
        ServePlane,
        ShardedVerifyStage,
    )

    if plane is None:
        plane = ServePlane(ServeConfig(
            n_devices=n_shards,
            batch_per_shard=batch_per_shard,
            max_msg_len=max_msg_len,
            poh_iters=hashes_per_tick,
        ))
    cfg = plane.cfg
    if cfg.n_devices != n_shards:
        raise ValueError(
            f"plane has {cfg.n_devices} shards, pipeline asked for {n_shards}"
        )

    uid = shm.fresh_uid()
    links = []

    def mklink(name, mtu, n_consumers=1, d=None):
        link = shm.ShmLink.create(
            f"fdtpu_{name}_{uid}", depth=d or depth, mtu=mtu, n_fseq=n_consumers
        )
        links.append(link)
        return link

    use_native_pack = resolve_native_pack(native_pack)
    gen_router = mklink("gv", mtu=1232)
    shard_rings = [
        mklink(f"sv{i}", mtu=1232, d=shard_depth) for i in range(n_shards)
    ]
    verify_dedup = mklink("vd", mtu=4096)
    dedup_pack = None if use_native_pack else mklink("dp", mtu=4096)
    pack_bank = [mklink(f"pb{b}", mtu=65536) for b in range(n_bank)]
    bank_poh = [mklink(f"bp{b}", mtu=65536) for b in range(n_bank)]
    bank_done = [mklink(f"bd{b}", mtu=64) for b in range(n_bank)]
    poh_shred = mklink("ps", mtu=65536)
    shred_store = mklink("ss", mtu=1232, d=4096)

    secret = hashlib.sha256(leader_seed).digest()
    leader_pub = ref.public_key(secret)

    pool = gen_transfer_pool(pool_size)
    benchg = BenchGStage(
        pool, "benchg", outs=[shm.make_producer(gen_router)], limit=gen_limit
    )
    router = ShardRouterStage(
        "router",
        ins=[shm.make_consumer(gen_router, lazy=32)],
        outs=[shm.make_producer(l) for l in shard_rings],
        n_shards=n_shards,
    )
    verify = ShardedVerifyStage(
        "verify",
        ins=[shm.make_consumer(l, lazy=32) for l in shard_rings],
        outs=[shm.make_producer(verify_dedup)],
        plane=plane,
        batch=cfg.batch_per_shard,
        batch_deadline_s=batch_deadline_s,
        precomputed_ok=verify_precomputed,
    )
    if use_native_pack:
        dedup = None
        pack = NativePackStage(
            "pack",
            ins=[shm.make_consumer(verify_dedup, lazy=32)]
            + [shm.make_consumer(l, lazy=8) for l in bank_done],
            outs=[shm.make_producer(l) for l in pack_bank],
            bank_cnt=n_bank,
        )
    else:
        dedup = DedupStage(
            "dedup",
            ins=[shm.make_consumer(verify_dedup, lazy=32)],
            outs=[shm.make_producer(dedup_pack)],
        )
        pack = PackStage(
            "pack",
            ins=[shm.make_consumer(dedup_pack, lazy=32)]
            + [shm.make_consumer(l, lazy=8) for l in bank_done],
            outs=[shm.make_producer(l) for l in pack_bank],
            bank_cnt=n_bank,
        )
    if bank_ctx is None:
        bank_ctx = default_bank_ctx(slot=slot)
    banks = [
        BankStage(
            f"bank{b}",
            ins=[shm.make_consumer(pack_bank[b], lazy=8)],
            outs=[shm.make_producer(bank_poh[b]), shm.make_producer(bank_done[b])],
            bank_idx=b,
            ctx=bank_ctx,
        )
        for b in range(n_bank)
    ]
    for bstage in banks:
        bstage.require_credit = True
    poh = PohStage(
        "poh",
        ins=[shm.make_consumer(l, lazy=8) for l in bank_poh],
        outs=[shm.make_producer(poh_shred)],
        hashes_per_tick=hashes_per_tick,
        plane=plane,
    )
    poh.require_credit = True
    shred = ShredStage(
        "shred",
        ins=[shm.make_consumer(poh_shred, lazy=8)],
        outs=[shm.make_producer(shred_store)],
        signer=lambda root: ref.sign(secret, root),
        slot=slot,
        keep_sets=True,
        plane=plane,
    )
    store = StoreStage(
        "store",
        ins=[shm.make_consumer(shred_store, lazy=64)],
        verify_sig=None,
        trust_membership=True,
    )
    stages = [benchg, router, verify] + ([dedup] if dedup else []) \
        + [pack, *banks, poh, shred, store]
    return LeaderPipeline(
        stages=stages,
        links=links,
        benchg=benchg,
        verifies=[verify],
        dedup=dedup,
        pack=pack,
        banks=banks,
        poh=poh,
        shred=shred,
        store=store,
        leader_pub=leader_pub,
        bank_ctx=bank_ctx,
        router=router,
        plane=plane,
    )
