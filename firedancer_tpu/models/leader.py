"""The flagship model: the leader TPU pipeline, assembled.

    benchg -> verify (TPU sigverify, xN round-robin) -> dedup -> pack

This is the e2e slice of the reference's Frankendancer leader topology
(/root/reference/src/app/fdctl/run/topos/fd_frankendancer.c:96-111) with
ingress replaced by the synthetic generator (net/quic stages are later
milestones).  Stages talk over tango shm links and are driven either by the
in-process cooperative scheduler here (tests, bench) or by the process
topology runner (own milestone).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field

from firedancer_tpu.runtime.benchg import BenchGStage, gen_transfer_pool
from firedancer_tpu.runtime.dedup import DedupStage
from firedancer_tpu.runtime.pack_stub import PackStubStage
from firedancer_tpu.runtime.verify import VerifyStage
from firedancer_tpu.tango import shm


@dataclass
class LeaderPipeline:
    stages: list
    links: list
    benchg: BenchGStage
    verifies: list[VerifyStage]
    dedup: DedupStage
    pack: PackStubStage

    def run(self, *, max_iters: int = 100_000, until_txns: int | None = None):
        """Cooperative round-robin scheduling until pack has seen
        `until_txns` txns or max_iters loop sweeps elapse."""
        for _ in range(max_iters):
            for s in self.stages:
                s.run_once()
            if until_txns is not None and self.pack.metrics.get("txn_in") >= until_txns:
                break
        for v in self.verifies:
            v.flush()
        # drain sweeps until quiescent: each run_once moves at most one frag
        # per stage, so sweep dedup/pack until neither makes progress (a
        # fixed sweep count loses the tail when verify flushes > count frags).
        while True:
            before = self.dedup.metrics.get("frags_in") + self.pack.metrics.get(
                "frags_in"
            )
            self.dedup.run_once()
            self.pack.run_once()
            after = self.dedup.metrics.get("frags_in") + self.pack.metrics.get(
                "frags_in"
            )
            if after == before:
                break
        self.pack.flush()

    def close(self):
        for link in self.links:
            link.close()
            link.unlink()

    def report(self) -> dict:
        return {s.name: dict(s.metrics.counters) for s in self.stages}


def build_leader_pipeline(
    *,
    n_verify: int = 1,
    pool_size: int = 512,
    gen_limit: int | None = None,
    batch: int = 128,
    max_msg_len: int = 256,
    depth: int = 1024,
    batch_deadline_s: float = 0.002,
) -> LeaderPipeline:
    uid = f"{os.getpid()}_{int(time.monotonic_ns() % 1_000_000)}"
    links = []

    def mklink(name, mtu, n_consumers=1):
        link = shm.ShmLink.create(
            f"fdtpu_{name}_{uid}", depth=depth, mtu=mtu, n_fseq=n_consumers
        )
        links.append(link)
        return link

    # gen -> verify: one link, verify stages shard by seq round-robin.
    gen_verify = mklink("gv", mtu=1232, n_consumers=n_verify)
    # verify -> dedup: one link per verify stage (single-producer rings).
    verify_dedup = [mklink(f"vd{i}", mtu=4096) for i in range(n_verify)]
    dedup_pack = mklink("dp", mtu=4096)
    pack_out = mklink("po", mtu=65536)

    pool = gen_transfer_pool(pool_size)
    benchg = BenchGStage(
        pool,
        "benchg",
        outs=[shm.Producer(gen_verify)],
        limit=gen_limit,
    )
    verifies = [
        VerifyStage(
            f"verify{i}",
            ins=[shm.Consumer(gen_verify, fseq_idx=i, lazy=32)],
            outs=[shm.Producer(verify_dedup[i])],
            shard_idx=i,
            shard_cnt=n_verify,
            batch=batch,
            max_msg_len=max_msg_len,
            batch_deadline_s=batch_deadline_s,
        )
        for i in range(n_verify)
    ]
    dedup = DedupStage(
        "dedup",
        ins=[shm.Consumer(l, lazy=32) for l in verify_dedup],
        outs=[shm.Producer(dedup_pack)],
    )
    pack = PackStubStage(
        "pack",
        ins=[shm.Consumer(dedup_pack, lazy=32)],
        outs=[shm.Producer(pack_out, reliable_fseq_idx=[])],
    )
    stages = [benchg, *verifies, dedup, pack]
    return LeaderPipeline(
        stages=stages,
        links=links,
        benchg=benchg,
        verifies=verifies,
        dedup=dedup,
        pack=pack,
    )
