"""The slot-clock plane: ONE wall-clock deadline authority for the leader.

The protocol's leader pipeline lives or dies by the 400 ms slot cadence
(/root/reference/src/app/fdctl/run/tiles/fd_poh.c derives every tick and
leader-rotation decision from the reckoning of wall-clock time against
the epoch schedule).  Until now this build's pipeline ran free — slots
sealed when the txn stream drained — so nothing could ever MISS a slot.
This module is the missing clock: a picklable config (`SlotClockCfg`)
that every stage of a topology anchors to the SAME monotonic epoch, and
a reader (`SlotClock`) that answers the only questions deadline code may
ask: which slot is it, when does it end, which ticks are due, and is a
slot past saving.

Design rules:

  - all arithmetic is integer nanoseconds off one anchor (`t0_ns`), so
    every process of a topology (CLOCK_MONOTONIC is system-wide on
    Linux) derives identical boundaries — there is no peer-to-peer
    clock agreement problem to have;
  - the cadence is CONFIGURABLE (400 ms real, compressed to tens of ms
    for tests) but the geometry is fixed at anchor time: slot s starts
    at t0 + (s - slot0)*slot_ns, full stop.  Load never moves a
    boundary — that is the whole point;
  - `now_fn` is injectable for unit tests (virtual time), defaulting to
    time.monotonic_ns — the same clock the frag timestamps use
    (tango/shm.now_ns);
  - this plane is the ONLY sanctioned deadline authority for stage
    code: fdlint FD215 flags blocking sleeps/waits inside frag
    callbacks and housekeeping hooks precisely so no stage invents a
    private clock to wait on.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, replace


@dataclass(frozen=True)
class SlotClockCfg:
    """Picklable slot-clock geometry (StageSpec.kwargs ride the spawn).

    `t0_ns` is the shared anchor: resolve it ONCE in the parent (via
    `anchored`) before handing the cfg to builders, or every child would
    anchor at its own boot instant and the clocks would disagree.
    `boot_grace_s` exists because spawned children take real time to
    boot (XLA import): anchoring the epoch slightly in the future means
    slot 0 of the window starts after the topology is actually up."""

    slot_ms: float = 400.0
    slot0: int = 1
    ticks_per_slot: int = 8
    # the leader window: seal slots [slot0, slot0 + n_slots) then stop
    # (handoff fires on this schedule, not on drain); None = unbounded
    n_slots: int | None = None
    # grace past the deadline before a slot is MISSED rather than sealed
    # late (jitter allowance, as a fraction of the slot)
    miss_grace_frac: float = 0.25
    t0_ns: int | None = None

    def anchored(self, boot_grace_s: float = 0.0,
                 now_ns: int | None = None) -> "SlotClockCfg":
        """Resolve the epoch anchor NOW (+ boot grace); idempotent when
        t0_ns is already set."""
        if self.t0_ns is not None:
            return self
        base = time.monotonic_ns() if now_ns is None else now_ns
        return replace(self, t0_ns=base + int(boot_grace_s * 1e9))

    def build(self, now_fn=None) -> "SlotClock":
        return SlotClock(self, now_fn=now_fn)


class SlotClock:
    """Deadline reader over an anchored cfg.  Pure integer-ns queries —
    cheap enough for before_credit/after_credit cadence (one clock read
    per sweep, never per frag: FD202)."""

    def __init__(self, cfg: SlotClockCfg, now_fn=None):
        if cfg.ticks_per_slot <= 0:
            raise ValueError("ticks_per_slot must be positive")
        if cfg.slot_ms <= 0:
            raise ValueError("slot_ms must be positive")
        self.cfg = cfg if cfg.t0_ns is not None else cfg.anchored()
        self._now_fn = now_fn or time.monotonic_ns
        self.slot_ns = max(int(cfg.slot_ms * 1e6), cfg.ticks_per_slot)
        self.tick_ns = self.slot_ns // cfg.ticks_per_slot
        self.grace_ns = int(self.slot_ns * cfg.miss_grace_frac)
        self.t0 = self.cfg.t0_ns

    # -- queries -------------------------------------------------------------

    def now(self) -> int:
        return self._now_fn()

    def slot_at(self, now_ns: int) -> int:
        """The slot whose window contains now (clamped to slot0 before
        the anchor — the boot-grace period belongs to the first slot)."""
        return self.cfg.slot0 + max(0, now_ns - self.t0) // self.slot_ns

    def start_of(self, slot: int) -> int:
        return self.t0 + (slot - self.cfg.slot0) * self.slot_ns

    def deadline_of(self, slot: int) -> int:
        return self.start_of(slot) + self.slot_ns

    def remaining_ns(self, slot: int, now_ns: int) -> int:
        return self.deadline_of(slot) - now_ns

    def ticks_due(self, slot: int, now_ns: int) -> int:
        """Ticks of `slot` that should have LANDED by now, in
        [0, ticks_per_slot] — tick k (1-based) is due at
        start + k*tick_ns."""
        d = now_ns - self.start_of(slot)
        if d <= 0:
            return 0
        return min(d // self.tick_ns, self.cfg.ticks_per_slot)

    def tick_deadline(self, slot: int, k: int) -> int:
        """When tick k (1-based) of `slot` is due to land."""
        return self.start_of(slot) + k * self.tick_ns

    def missed(self, slot: int, now_ns: int) -> bool:
        """Past saving: the deadline + grace has elapsed, so the slot is
        a MISS, not a late seal."""
        return now_ns > self.deadline_of(slot) + self.grace_ns

    # -- leader window -------------------------------------------------------

    def last_slot(self) -> int | None:
        if self.cfg.n_slots is None:
            return None
        return self.cfg.slot0 + self.cfg.n_slots - 1

    def in_window(self, slot: int) -> bool:
        last = self.last_slot()
        return last is None or slot <= last

    def window_end_ns(self) -> int | None:
        """The handoff instant: the last window slot's deadline."""
        last = self.last_slot()
        return None if last is None else self.deadline_of(last)

    def window_done(self, now_ns: int | None = None) -> bool:
        end = self.window_end_ns()
        if end is None:
            return False
        return (self.now() if now_ns is None else now_ns) >= end


def resolve_clock(clock) -> SlotClock | None:
    """Accept a SlotClockCfg (builders: the picklable form), a built
    SlotClock (tests with injected time), or None — the one coercion
    every clocked stage constructor uses."""
    if clock is None or isinstance(clock, SlotClock):
        return clock
    if isinstance(clock, SlotClockCfg):
        return clock.build()
    raise TypeError(f"clock must be SlotClockCfg | SlotClock | None, "
                    f"got {type(clock).__name__}")
