"""Per-hop latency budgets: the metrics plane as a RATCHET, not a dashboard.

ROADMAP item #4: the PR-5 observability plane records per-hop
`frag_latency_ns` histograms (now - tsorig per consumed frag, tsorig
stamped once at the origin stage), so regressions in hop latency are
measurable — this module declares the budgets and the check, and
tests/test_latency_budget.py enforces them in tier-1 after driving the
real pipeline.

Budgets are p50s over the shm metric registries, deliberately loose
(~5-10x the measured medians on the throttled 1-core CI class box) so
they catch REGRESSIONS — a stage reverting to per-frag batching, an
accumulation deadline wedged open, a lane silently falling back — not
scheduler noise.  Ratchet them down as the pipeline gets faster.
"""

from __future__ import annotations

# hop (stage name in the flagship cooperative pipeline) -> p50 budget, ns.
# "store" observes the whole ingress->...->store path (its tsorig is
# benchg's), so its row IS the e2e budget.
HOP_P50_BUDGET_NS: dict[str, int] = {
    "verify0": 200_000_000,   # ingress -> verify (batch close included)
    "dedup": 300_000_000,     # python lane only (fused lane has no hop)
    "pack": 400_000_000,      # ingress -> pack intake (dedup hop included)
    "bank0": 600_000_000,     # ingress -> commit (microblock close incl.)
    "store": 1_000_000_000,   # end to end
}


def check_hop_budgets(hists: dict[str, dict]) -> list[str]:
    """hists: stage name -> frag_latency_ns histogram dict (the
    MetricsRegistry.hist / Metrics.hist shape).  Returns human-readable
    violations; empty = within budget.  Stages without a budget row or
    without observations are skipped (a hop that consumed nothing has no
    p50; the caller asserts traffic separately)."""
    from firedancer_tpu.utils.metrics import hist_quantile

    out = []
    for name, budget in HOP_P50_BUDGET_NS.items():
        h = hists.get(name)
        if not h or not h.get("count"):
            continue
        p50 = hist_quantile(h, 0.5)
        if p50 > budget:
            out.append(
                f"{name}: p50 {p50 / 1e6:.1f}ms exceeds budget "
                f"{budget / 1e6:.1f}ms"
            )
    return out
