"""Per-hop latency budgets: the metrics plane as a RATCHET, not a dashboard.

ROADMAP item #4: the PR-5 observability plane records per-hop
`frag_latency_ns` histograms (now - tsorig per consumed frag, tsorig
stamped once at the origin stage), so regressions in hop latency are
measurable — this module declares the budgets and the check, and
tests/test_latency_budget.py enforces them in tier-1 after driving the
real pipeline.

Budgets are quantiles over the shm metric registries, deliberately loose
(~5-10x the measured figures on the throttled 1-core CI class box) so
they catch REGRESSIONS — a stage reverting to per-frag batching, an
accumulation deadline wedged open, a lane silently falling back — not
scheduler noise.  Ratchet them down as the pipeline gets faster.

Round 12 ratchet (ISSUE 16): the bank-endgame round took the flagship
pipeline from 19.0K to ~25.9K txn/s and the fixture's measured p50s sit
at verify 0.1ms / pack 2.2ms / bank+store ~37ms (one histogram bucket
edge), so every p50 budget halves.  The same round adds the TAIL table:
`HOP_P99_BUDGET_NS` guards the commit and end-to-end p99 — the
bench-round number the ISSUE watches (`commit_p99_ms` in the bank A/B
artifact) now has a tier-1 tripwire, not just an artifact row.  The
profile did NOT justify store flush-batching: the store hop is ~13% of
wall with the per-shred membership recompute already skipped on the
leader's own stream (`trust_membership`), so its budget tightens and
its code stays put.

Round 14 ratchet (ISSUE 19): the native shm storage plane moves the
committed-record write INTO the bank sweep crossing (the drain is
result-log accounting only), stepping the pipeline past 30K txn/s —
the bank p50 budget steps down to the new floor and the two tail rows
(commit, end-to-end) tighten with it.
"""

from __future__ import annotations

# hop (stage name in the flagship cooperative pipeline) -> p50 budget, ns.
# "store" observes the whole ingress->...->store path (its tsorig is
# benchg's), so its row IS the e2e budget.
HOP_P50_BUDGET_NS: dict[str, int] = {
    "verify0": 100_000_000,   # ingress -> verify (batch close included)
    "dedup": 150_000_000,     # python lane only (fused lane has no hop)
    "pack": 200_000_000,      # ingress -> pack intake (dedup hop included)
    "bank0": 250_000_000,     # ingress -> commit (microblock close incl.)
    "store": 450_000_000,     # end to end
}

# hop -> p99 budget, ns: the tail ratchet.  bank0's row is the commit
# p99 (ingress -> microblock commit, the bank A/B artifact's
# commit_p99_ms cousin); store's is the end-to-end tail.  Kept to the
# two hops whose tails the bench rounds actually track — a p99 on a
# mid-pipe hop would only re-measure its consumers' scheduling noise.
HOP_P99_BUDGET_NS: dict[str, int] = {
    "bank0": 500_000_000,
    "store": 700_000_000,
}


def check_hop_budgets(hists: dict[str, dict]) -> list[str]:
    """hists: stage name -> frag_latency_ns histogram dict (the
    MetricsRegistry.hist / Metrics.hist shape).  Returns human-readable
    violations; empty = within budget.  Stages without a budget row or
    without observations are skipped (a hop that consumed nothing has no
    quantile; the caller asserts traffic separately)."""
    from firedancer_tpu.utils.metrics import hist_quantile

    out = []
    for q, table in ((0.5, HOP_P50_BUDGET_NS), (0.99, HOP_P99_BUDGET_NS)):
        for name, budget in table.items():
            h = hists.get(name)
            if not h or not h.get("count"):
                continue
            v = hist_quantile(h, q)
            if v > budget:
                out.append(
                    f"{name}: p{int(q * 100)} {v / 1e6:.1f}ms exceeds "
                    f"budget {budget / 1e6:.1f}ms"
                )
    return out
