"""Batch-geometry autotuner for the verify stage (ISSUE 13).

The metrics plane already records, per verify stage, the batch-fill
histogram (elements per closed device batch), the msg-length histogram,
and the generic/cached element counters.  This module turns those
observations into a (batch, max_msg_len, comb split) recommendation —
the wiredancer path sizes its FPGA burst the same way, except here the
"burst" is a compiled XLA shape, so retuning costs a recompile and the
choice must be made from evidence, not per batch.

Pure and deterministic by contract: the same histogram state always
yields the same recommendation (tested), so a tuned stage is exactly as
reproducible as an untuned one and a recommendation computed offline
from a scraped snapshot matches what the live stage would pick.

The stage applies a recommendation only at a quiet point (no open
accumulator, no in-flight batches) and only when the autotune knob is
on; bench.py --kernel-ladder records the recommendation alongside every
capture so a future real-chip run can boot pre-tuned.
"""

from __future__ import annotations

from dataclasses import dataclass

from firedancer_tpu.utils import metrics as fm

# the discrete ladders a recommendation picks from: compiled shapes are
# expensive (one XLA compile each), so the tuner quantizes to a small
# menu rather than chasing the histogram exactly
BATCH_LADDER = (64, 128, 256, 512, 1024, 2048, 4096)
MSG_LEN_LADDER = (128, 256, 512, 1232)

# hysteresis: a recommendation must beat the current geometry by this
# factor of headroom before it is worth a recompile
FILL_TARGET_Q = 0.95  # size the batch so the p95 fill fits
MSG_LEN_Q = 0.99  # and the msg rows so the p99 length fits
COMB_SPLIT_MIN = 0.25  # cached lane earns its own batch above this share


@dataclass(frozen=True)
class Geometry:
    """One verify-stage shape choice (what a compile is keyed on)."""

    batch: int
    max_msg_len: int
    comb_split: bool  # keep a separate cached-signer batch lane

    def as_dict(self) -> dict:
        return {
            "batch": self.batch,
            "max_msg_len": self.max_msg_len,
            "comb_split": self.comb_split,
        }


def _ladder_at_least(ladder: tuple, v: float) -> int:
    """Smallest ladder rung >= v (the top rung when v overflows)."""
    for rung in ladder:
        if rung >= v:
            return rung
    return ladder[-1]


def recommend(
    fill_hist: dict,
    msg_len_hist: dict | None = None,
    *,
    batch_elems: int = 0,
    comb_elems: int = 0,
    current: Geometry | None = None,
) -> Geometry:
    """The deterministic recommendation from one metrics snapshot.

    fill_hist / msg_len_hist: histogram dicts as Metrics.hist() returns
    them ({"buckets", "counts", "sum", "count"}).  batch_elems /
    comb_elems: the stage's element counters (comb share decides the
    cached-lane split).  `current` supplies fallbacks for axes with no
    evidence yet (empty histograms keep the current choice).
    """
    cur = current or Geometry(256, 1232, True)

    # batch: size the fixed shape so the p95 observed fill fits — a
    # batch that always closes full wants headroom (the deadline never
    # fires), a batch that closes at 5% fill is paying pad-lane compute
    # for nothing.  hist_quantile interpolates within the bucket, which
    # is fine: the ladder quantizes the answer anyway.
    if fill_hist and fill_hist.get("count"):
        q = fm.hist_quantile(fill_hist, FILL_TARGET_Q)
        if q == float("inf"):  # fills above the top edge: take the top rung
            batch = BATCH_LADDER[-1]
        else:
            batch = _ladder_at_least(BATCH_LADDER, q)
    else:
        batch = cur.batch

    # max_msg_len: the compiled row height — every byte row is hashed,
    # so rows sized for 1232 when the traffic is 200-byte votes wastes
    # ~6x the sha work.  Oversize txns are dropped by the stage guard,
    # so the p99 ladder rung keeps the drop rate inside the tail.
    if msg_len_hist and msg_len_hist.get("count"):
        q = fm.hist_quantile(msg_len_hist, MSG_LEN_Q)
        if q == float("inf"):
            mml = MSG_LEN_LADDER[-1]
        else:
            mml = _ladder_at_least(MSG_LEN_LADDER, q)
    else:
        mml = cur.max_msg_len

    # cached-lane split: a separate comb batch only pays (two shapes,
    # two partial fills) when enough traffic actually rides it
    total = batch_elems or 0
    comb = comb_elems or 0
    if total > 0:
        split = (comb / total) >= COMB_SPLIT_MIN
    else:
        split = cur.comb_split

    return Geometry(batch=batch, max_msg_len=mml, comb_split=split)


def recommend_for_stage(stage, current: Geometry | None = None) -> Geometry:
    """The live-stage entry point: read the stage's OWN schema metrics
    (batch_fill + msg_len histograms, batch/comb element counters) and
    recommend.  Never touches device state."""
    m = stage.metrics
    try:
        fill = m.hist("batch_fill")
    except KeyError:  # pragma: no cover - schema-less test stages
        fill = {}
    try:
        mlh = m.hist("msg_len")
    except KeyError:  # pragma: no cover
        mlh = None
    return recommend(
        fill,
        mlh,
        batch_elems=m.get("batch_elems"),
        comb_elems=m.get("comb_elems"),
        current=current or Geometry(stage.batch, stage.max_msg_len,
                                    stage.comb_slots > 0),
    )
