"""Store stage: consumes wire shreds, resolves FEC sets, stores batches.

Pipeline position mirrors the reference's store tile
(/root/reference/src/app/fdctl/run/tiles/fd_store.c — shreds into the
blockstore) fused with the receive half of fd_fec_resolver.c: the e2e
pipeline publishes every shred onto the wire link and this stage proves
they reassemble — the same component a non-leader validator runs on
turbine ingress.

Inputs: ins[0] = shred -> store wire shreds.
State:  completed FEC sets per slot + reassembled entry-batch bytes.
"""

from __future__ import annotations

from firedancer_tpu.protocol import shred as fs
from .fec_resolver import FecResolver
from .stage import Stage


class StoreStage(Stage):
    def __init__(self, *args, verify_sig=None, blockstore=None,
                 trust_membership: bool = False, **kwargs):
        super().__init__(*args, **kwargs)
        # trust_membership: the leader's own store consuming its own
        # shred stream skips the per-shred merkle membership recompute
        # (~7 hashes/shred) — the fd_fec_resolver NULL-signer trust
        # boundary; receive-path stores keep full verification
        self.resolver = FecResolver(verify_sig=verify_sig, max_inflight=256,
                                    trust_membership=trust_membership)
        self.sets_by_slot: dict[int, list] = {}
        # optional persistent history (flamenco/blockstore.Blockstore):
        # every data shred lands there, making the slot replayable after
        # a restart (fd_store.c -> fd_blockstore insert path)
        self.blockstore = blockstore

    def after_frag(self, in_idx: int, meta, payload: bytes) -> None:
        out = self.resolver.add_shred(payload)
        self.metrics.inc("shreds_in")
        if out is not None:
            self.sets_by_slot.setdefault(out.slot, []).append(out)
            self.metrics.inc("sets_stored")
            if self.blockstore is not None:
                # persist only shreds of a RESOLVED set (FEC-complete,
                # leader-signature-checked): raw wire shreds must never
                # enter block history, or a forged (slot, idx) would
                # permanently displace the genuine shred (first-writer-
                # wins idempotency) and poison restart replay
                for buf in out.data_shreds:
                    self.blockstore.insert_shred(buf)

    def entry_batch_bytes(self, slot: int) -> bytes:
        """Reassembled data-shred payloads for `slot`, in fec_set order."""
        sets = sorted(self.sets_by_slot.get(slot, []), key=lambda s: s.fec_set_idx)
        out = bytearray()
        for st in sets:
            for buf in st.data_shreds:
                sh = fs.parse(buf)
                out += sh.payload(buf)
        return bytes(out)
