"""Per-link credit/depth autotuner (ISSUE 16).

Every stage samples each out ring's occupancy fraction
(1 - credits/depth) at housekeeping cadence (stage.py _housekeeping)
into per-out bucket counts over `OCC_EDGES`.  This module turns those
histograms into (depth, lazy) recommendations per link:

  - a link whose p99 occupancy crowds the top (>= HIGH_OCC) is a
    backpressure choke: double its depth up the ladder and HALVE the
    producing stage's housekeeping laziness so credits refresh before
    the ring fills again;
  - a link that never rises above LOW_OCC at p99 is oversized memory
    and cache traffic: step the depth down the ladder (floor 64) and
    relax the laziness;
  - anything in between keeps its current geometry (hysteresis — ring
    resizes are not free, so the tuner only moves on clear evidence).

Pure and deterministic by contract, exactly like verify_tune: the same
bucket counts always yield the same recommendation, so a tuned topology
is as reproducible as an untuned one and an offline recommendation from
a scraped snapshot matches what the live stage would pick.  Nothing
here resizes a live ring — shm rings are fixed at create — the output
feeds the NEXT topology build (bench records it per run).
"""

from __future__ import annotations

from dataclasses import dataclass

# occupancy-fraction bucket edges, shared with stage.py's sampler and
# the out_occupancy schema histogram (utils/metrics.stage_schema)
OCC_EDGES = (0.0625, 0.125, 0.25, 0.5, 0.75, 0.875, 0.9375, 1.0)

DEPTH_LADDER = (64, 128, 256, 512, 1024, 2048, 4096, 8192)
LAZY_LADDER = (8, 16, 32, 64, 128, 256)

OCC_Q = 0.99        # the tail that decides: sustained pressure, not spikes
HIGH_OCC = 0.75     # p99 at or above this -> grow
LOW_OCC = 0.125     # p99 at or below this -> shrink
MIN_EVIDENCE = 32   # samples before any move (cold stages keep defaults)


@dataclass(frozen=True)
class LinkTuning:
    """One out link's recommended geometry."""

    depth: int
    lazy: int

    def as_dict(self) -> dict:
        return {"depth": self.depth, "lazy": self.lazy}


def _quantile_edge(counts: list[int], q: float) -> float | None:
    """The OCC_EDGES edge at the q-quantile of the bucket counts
    (counts[i] <= edge i; the overflow bucket maps to 1.0).  None when
    there is no evidence."""
    total = sum(counts)
    if total <= 0:
        return None
    target = q * total
    acc = 0
    for i, c in enumerate(counts):
        acc += c
        if acc >= target:
            return OCC_EDGES[i] if i < len(OCC_EDGES) else 1.0
    return 1.0


def _ladder_step(ladder: tuple, v: int, direction: int) -> int:
    """The next rung up (+1) or down (-1) from the rung covering v;
    clamped at the ends.  v between rungs snaps to the smallest rung
    >= v first."""
    idx = 0
    for i, rung in enumerate(ladder):
        idx = i
        if rung >= v:
            break
    return ladder[max(0, min(len(ladder) - 1, idx + direction))]


def recommend_link(
    occ_counts: list[int], *, depth: int, lazy: int = 128
) -> LinkTuning:
    """The deterministic per-link recommendation from one sample set.

    occ_counts: bucket counts over OCC_EDGES (+1 overflow slot), as
    Stage.out_occupancy keeps per out.  depth/lazy: the link's current
    ring depth and the producing stage's housekeeping laziness."""
    q = _quantile_edge(occ_counts, OCC_Q)
    if q is None or sum(occ_counts) < MIN_EVIDENCE:
        return LinkTuning(depth=depth, lazy=lazy)
    if q >= HIGH_OCC:
        return LinkTuning(
            depth=_ladder_step(DEPTH_LADDER, depth, +1),
            lazy=_ladder_step(LAZY_LADDER, lazy, -1),
        )
    if q <= LOW_OCC:
        return LinkTuning(
            depth=_ladder_step(DEPTH_LADDER, depth, -1),
            lazy=_ladder_step(LAZY_LADDER, lazy, +1),
        )
    return LinkTuning(depth=depth, lazy=lazy)


def recommend_for_stage(stage) -> dict[int, LinkTuning]:
    """Per-out recommendations from a live stage's own samples.  Only
    outs with a sized link (depth known) appear.  Never touches ring
    state."""
    out: dict[int, LinkTuning] = {}
    for i, p in enumerate(stage.outs):
        if i >= len(stage.out_occupancy):
            break
        d = getattr(getattr(p, "link", None), "depth", 0)
        if not d:
            continue
        out[i] = recommend_link(
            stage.out_occupancy[i], depth=d, lazy=stage.lazy
        )
    return out


def recommend_topology(stages) -> dict[str, dict[int, dict]]:
    """The whole-pipeline snapshot (bench artifact form): stage name ->
    out idx -> {depth, lazy}."""
    return {
        s.name: {i: t.as_dict() for i, t in recommend_for_stage(s).items()}
        for s in stages
        if s.outs
    }
