"""PoH stage: the hash clock ticking between microblock mixins.

Pipeline position mirrors the reference's poh tile
(/root/reference/src/app/fdctl/run/tiles/fd_poh.c:1-300): hash
continuously, mix in each executed microblock from the banks, emit ticks
on the tick cadence, and forward entries downstream to shred.  Generation
is sequential host work by design (SURVEY §7.1 — the chain can't be
parallelized forward); *verification* of the produced chain batches onto
the TPU via runtime/poh.verify_segments_tpu, which the e2e test exercises.

Inputs:  ins[b] = bank b -> poh executed microblocks.
Outputs: outs[0] = poh -> shred entries.

Entry frame: u32 num_hashes | 32B poh_hash | u16 txn_cnt |
(u16 len || raw txn payload)* — the Solana entry triple (num_hashes since
the previous entry, the chain hash after this entry, the txns).  Ticks are
entries with txn_cnt = 0.
"""

from __future__ import annotations

from firedancer_tpu.tango.rings import MCache
from .poh import PohChain
from .stage import Stage


def build_entry(num_hashes: int, poh_hash: bytes, txns: list[bytes]) -> bytes:
    out = bytearray()
    out += num_hashes.to_bytes(4, "little")
    out += poh_hash
    out += len(txns).to_bytes(2, "little")
    for p in txns:
        out += len(p).to_bytes(2, "little")
        out += p
    return bytes(out)


def parse_entry(frame: bytes) -> tuple[int, bytes, list[bytes]]:
    num_hashes = int.from_bytes(frame[:4], "little")
    poh_hash = frame[4:36]
    cnt = int.from_bytes(frame[36:38], "little")
    txns = []
    o = 38
    for _ in range(cnt):
        ln = int.from_bytes(frame[o : o + 2], "little")
        o += 2
        txns.append(frame[o : o + ln])
        o += ln
    return num_hashes, poh_hash, txns


class PohStage(Stage):
    def __init__(
        self,
        *args,
        seed: bytes = b"\x00" * 32,
        hashes_per_tick: int = 64,
        ticks_per_slot: int = 8,
        hashes_per_iter: int = 16,
        plane=None,
        **kwargs,
    ):
        super().__init__(*args, **kwargs)
        self.chain = PohChain(hash=seed)
        self.hashes_per_tick = hashes_per_tick
        self.ticks_per_slot = ticks_per_slot
        self.hashes_per_iter = hashes_per_iter
        self._hashes_since_entry = 0
        self._tick_cnt = 0
        self.entries_out = 0
        # the slot's final entry hash (the poh_hash the bank hash chains);
        # entries is an optional in-memory record for replay tests
        self.last_entry_hash = seed
        self.entries: list[tuple[int, bytes, list[bytes]]] | None = None
        # serving plane (parallel/serve.ServePlane): full-tick pure-append
        # spans are parked on the plane and re-verified ON the mesh by the
        # next serving step — the leader auditing its own clock with the
        # same device program replay uses, at zero extra dispatches.  Spans
        # only match the compiled shape when a whole tick passed without a
        # mixin (poh_iters == hashes_per_tick); others are skipped.
        self.plane = plane
        self._span_start = seed

    # -- callbacks ----------------------------------------------------------

    def after_credit(self) -> None:
        """The clock: advance the chain a bounded amount per loop sweep so
        the cooperative scheduler stays fair (the reference hashes in
        after_credit exactly the same way, fd_poh.c)."""
        room = self.hashes_per_tick - (self.chain.hashcnt % self.hashes_per_tick)
        n = min(self.hashes_per_iter, room)
        if n <= 0:  # clock stopped (drain mode)
            return
        self.chain.append(n)
        self._hashes_since_entry += n
        if self.chain.hashcnt % self.hashes_per_tick == 0:
            self._emit_tick()

    def after_frag(self, in_idx: int, meta, payload: bytes) -> None:
        """A bank's executed microblock: mix its hash into the chain and
        emit the entry."""
        mixin = payload[:32]
        txn_cnt = int.from_bytes(payload[32:34], "little")
        txns = []
        o = 34
        for _ in range(txn_cnt):
            ln = int.from_bytes(payload[o : o + 2], "little")
            o += 2
            txns.append(payload[o : o + ln])
            o += ln
        self.chain.mixin(mixin)
        num_hashes = self._hashes_since_entry + 1  # mixin counts as one
        self._hashes_since_entry = 0
        self._span_start = self.chain.hash  # mixin breaks the append span
        self.metrics.inc("mixins")
        self.entries_out += 1
        self.last_entry_hash = self.chain.hash
        if self.entries is not None:
            self.entries.append((num_hashes, self.chain.hash, txns))
        self.publish(
            0,
            build_entry(num_hashes, self.chain.hash, txns),
            sig=self.chain.hashcnt,
            tsorig=int(meta[MCache.COL_TSORIG]),
        )

    # -- internals ----------------------------------------------------------

    def _emit_tick(self) -> None:
        self.chain.tick()
        self._tick_cnt += 1
        num_hashes = self._hashes_since_entry
        self._hashes_since_entry = 0
        if (
            self.plane is not None
            and num_hashes == self.plane.cfg.poh_iters
            and self.plane.queue_poh_span(self._span_start, self.chain.hash)
        ):
            self.metrics.inc("poh_spans_queued")
        self._span_start = self.chain.hash
        self.metrics.inc("ticks")
        self.entries_out += 1
        self.last_entry_hash = self.chain.hash
        if self.entries is not None:
            self.entries.append((num_hashes, self.chain.hash, []))
        self.publish(
            0, build_entry(num_hashes, self.chain.hash, []), sig=self.chain.hashcnt
        )

    def slot_complete(self) -> bool:
        return self._tick_cnt >= self.ticks_per_slot
