"""PoH stage: the hash clock ticking between microblock mixins.

Pipeline position mirrors the reference's poh tile
(/root/reference/src/app/fdctl/run/tiles/fd_poh.c:1-300): hash
continuously, mix in each executed microblock from the banks, emit ticks
on the tick cadence, and forward entries downstream to shred.  Generation
is sequential host work by design (SURVEY §7.1 — the chain can't be
parallelized forward); *verification* of the produced chain batches onto
the TPU via runtime/poh.verify_segments_tpu, which the e2e test exercises.

Inputs:  ins[b] = bank b -> poh executed microblocks.
Outputs: outs[0] = poh -> shred entries.

Entry frame: u32 num_hashes | 32B poh_hash | u16 txn_cnt |
(u16 len || raw txn payload)* — the Solana entry triple (num_hashes since
the previous entry, the chain hash after this entry, the txns).  Ticks are
entries with txn_cnt = 0.
"""

from __future__ import annotations

from firedancer_tpu.tango.rings import MCache
from firedancer_tpu.utils import metrics as fm
from .poh import PohChain
from .slot_clock import resolve_clock
from .stage import Stage


def build_entry(num_hashes: int, poh_hash: bytes, txns: list[bytes]) -> bytes:
    out = bytearray()
    out += num_hashes.to_bytes(4, "little")
    out += poh_hash
    out += len(txns).to_bytes(2, "little")
    for p in txns:
        out += len(p).to_bytes(2, "little")
        out += p
    return bytes(out)


def parse_entry(frame: bytes) -> tuple[int, bytes, list[bytes]]:
    num_hashes = int.from_bytes(frame[:4], "little")
    poh_hash = frame[4:36]
    cnt = int.from_bytes(frame[36:38], "little")
    txns = []
    o = 38
    for _ in range(cnt):
        ln = int.from_bytes(frame[o : o + 2], "little")
        o += 2
        txns.append(frame[o : o + ln])
        o += ln
    return num_hashes, poh_hash, txns


class PohStage(Stage):
    @classmethod
    def extra_schema(cls) -> fm.MetricsSchema:
        return (
            fm.MetricsSchema()
            .counter("ticks", "tick entries emitted")
            .counter("mixins", "microblock mixin entries emitted")
            .counter("poh_spans_queued", "full-tick spans parked for the"
                     " serving plane's on-mesh self-audit")
            .counter("slots_sealed",
                     "slots whose final tick landed at the deadline"
                     " (slot-clock mode)")
            .counter("slot_missed",
                     "slots whose boundary passed unsealed — the first-"
                     "class MISSED outcome, never a hang or a drop")
            .counter("slot_skipped_ticks",
                     "ticks never emitted because their slot was missed")
            .histogram(
                "slot_seal_lag_ns",
                fm.exp_buckets(1e4, 1e10, 19),
                "final-tick landing time past the slot deadline"
                " (the seal jitter the cadence tests bound)",
            )
        )

    def __init__(
        self,
        *args,
        seed: bytes = b"\x00" * 32,
        hashes_per_tick: int = 64,
        ticks_per_slot: int = 8,
        hashes_per_iter: int = 16,
        plane=None,
        clock=None,
        **kwargs,
    ):
        super().__init__(*args, **kwargs)
        self.chain = PohChain(hash=seed)
        self.hashes_per_tick = hashes_per_tick
        self.ticks_per_slot = ticks_per_slot
        self.hashes_per_iter = hashes_per_iter
        self._hashes_since_entry = 0
        self._tick_cnt = 0
        self.entries_out = 0
        # the slot's final entry hash (the poh_hash the bank hash chains);
        # entries is an optional in-memory record for replay tests
        self.last_entry_hash = seed
        self.entries: list[tuple[int, bytes, list[bytes]]] | None = None
        # serving plane (parallel/serve.ServePlane): full-tick pure-append
        # spans are parked on the plane and re-verified ON the mesh by the
        # next serving step — the leader auditing its own clock with the
        # same device program replay uses, at zero extra dispatches.  Spans
        # only match the compiled shape when a whole tick passed without a
        # mixin (poh_iters == hashes_per_tick); others are skipped.
        self.plane = plane
        self._span_start = seed
        # slot-clock mode (runtime/slot_clock): ticks PACED to the wall-
        # clock deadline, the slot sealed at its boundary regardless of
        # pending load, and a boundary that passes unsealable (frozen
        # stage, starved credits) becomes a slot_missed VALUE — the
        # pipeline skips to the scheduled slot and keeps going
        self._clock = resolve_clock(clock)
        if self._clock is not None:
            self.ticks_per_slot = self._clock.cfg.ticks_per_slot
            self.slot = self._clock.cfg.slot0
            self._slot_hash_base = 0
            self.window_closed = False

    # -- callbacks ----------------------------------------------------------

    def after_credit(self) -> None:
        """The clock: advance the chain a bounded amount per loop sweep so
        the cooperative scheduler stays fair (the reference hashes in
        after_credit exactly the same way, fd_poh.c).  In slot-clock mode
        the wall clock, not the txn stream, decides when ticks land and
        when the slot seals."""
        if self._clock is not None:
            self._clock_sweep(self._clock.now())
            return
        room = self.hashes_per_tick - (self.chain.hashcnt % self.hashes_per_tick)
        n = min(self.hashes_per_iter, room)
        if n <= 0:  # clock stopped (drain mode)
            return
        self.chain.append(n)
        self._hashes_since_entry += n
        if self.chain.hashcnt % self.hashes_per_tick == 0:
            self._emit_tick()

    # -- slot-clock mode -----------------------------------------------------

    def before_credit(self) -> None:
        """Miss detection must outrun backpressure: run_once skips
        after_credit while any output is starved, but a slot whose
        grace expired during the stall must STILL become a miss (the
        outcome is a value precisely because it needs no credit to be
        declared).  before_credit runs unconditionally every sweep."""
        if self._clock is None or self.window_closed:
            return
        now = self._clock.now()
        if self._clock.missed(self.slot, now):
            self._miss_slots(now)

    def _tick_progress(self) -> int:
        """Hashes into the CURRENT tick (slot-local; mixins may overshoot
        a boundary — the overshoot simply counts toward the next tick)."""
        return (self.chain.hashcnt - self._slot_hash_base
                - self._tick_cnt * self.hashes_per_tick)

    def _clock_sweep(self, now: int) -> None:
        clock = self._clock
        if self.window_closed:
            return
        if now >= clock.deadline_of(self.slot):
            # the boundary: seal NOW regardless of pending load — or,
            # past the grace, declare the slot missed and move on
            if clock.missed(self.slot, now):
                self._miss_slots(now)
            else:
                self._seal_rush()
            return  # pace the new slot from the next sweep on
        # paced hashing: tick k (1-based) may complete only once due;
        # catch-up after a stall is bounded per sweep (cooperative loop)
        for _ in range(4):
            if self._tick_cnt >= self.ticks_per_slot:
                return  # fully ticked; wait for the boundary roll
            k = self._tick_cnt + 1
            due = now >= clock.tick_deadline(self.slot, k)
            need = self.hashes_per_tick - self._tick_progress()
            if need > 0:
                cap = need if due else min(self.hashes_per_iter, need - 1)
                if cap > 0:
                    self.chain.append(cap)
                    self._hashes_since_entry += cap
            if not due or self._tick_progress() < self.hashes_per_tick:
                return
            if self.outs and self.outs[0].cr_avail <= 0:
                return  # starved: retry next sweep (the miss clock runs)
            self._emit_tick()

    def _seal_rush(self) -> None:
        """Deadline reached with the slot still open: land every
        remaining tick immediately (hashing is cheap; credits may not
        be) and roll to the next scheduled slot.  Called only inside the
        grace window — past it the slot is a miss, not a late seal."""
        clock = self._clock
        while self._tick_cnt < self.ticks_per_slot:
            if self.outs and self.outs[0].cr_avail <= 0:
                return  # retry next sweep; grace expiry turns this into a miss
            need = self.hashes_per_tick - self._tick_progress()
            if need > 0:
                self.chain.append(need)
                self._hashes_since_entry += need
            self._emit_tick()
        lag = clock.now() - clock.deadline_of(self.slot)
        self.metrics.inc("slots_sealed")
        self.metrics.observe("slot_seal_lag_ns", max(lag, 1))
        self.trace(fm.EV_SLOT_SEAL, self.slot)
        self._advance_slot(self.slot + 1)

    def _miss_slots(self, now: int) -> None:
        """The first-class MISSED outcome: the boundary (plus grace)
        passed before the slot's final tick could land — emit the event
        and the metric, skip the unsealed ticks, and continue cleanly at
        the slot the clock says is current."""
        clock = self._clock
        target = clock.slot_at(now)
        missed = max(target - self.slot, 1)
        skipped = (missed * self.ticks_per_slot) - self._tick_cnt
        for s in range(self.slot, self.slot + missed):
            self.trace(fm.EV_SLOT_MISSED, s)
        self.metrics.inc("slot_missed", missed)
        self.metrics.inc("slot_skipped_ticks", max(skipped, 0))
        self._advance_slot(self.slot + missed)

    def _advance_slot(self, slot: int) -> None:
        self.slot = slot
        self._tick_cnt = 0
        self._slot_hash_base = self.chain.hashcnt
        if not self._clock.in_window(slot):
            # the leader window ended: handoff fires on this schedule
            # (not on drain) — the clock plane stops sealing and the
            # supervisor observes slots_done via the metrics registry
            self.window_closed = True

    def slots_done(self) -> int:
        return (self.metrics.get("slots_sealed")
                + self.metrics.get("slot_missed"))

    def after_frag(self, in_idx: int, meta, payload: bytes) -> None:
        """A bank's executed microblock: mix its hash into the chain and
        emit the entry."""
        mixin = payload[:32]
        txn_cnt = int.from_bytes(payload[32:34], "little")
        txns = []
        o = 34
        for _ in range(txn_cnt):
            ln = int.from_bytes(payload[o : o + 2], "little")
            o += 2
            txns.append(payload[o : o + ln])
            o += ln
        self.chain.mixin(mixin)
        num_hashes = self._hashes_since_entry + 1  # mixin counts as one
        self._hashes_since_entry = 0
        self._span_start = self.chain.hash  # mixin breaks the append span
        self.metrics.inc("mixins")
        self.entries_out += 1
        self.last_entry_hash = self.chain.hash
        if self.entries is not None:
            self.entries.append((num_hashes, self.chain.hash, txns))
        self.publish(
            0,
            build_entry(num_hashes, self.chain.hash, txns),
            sig=self.chain.hashcnt,
            tsorig=int(meta[MCache.COL_TSORIG]),
        )

    # -- internals ----------------------------------------------------------

    def _emit_tick(self) -> None:
        self.chain.tick()
        self._tick_cnt += 1
        num_hashes = self._hashes_since_entry
        self._hashes_since_entry = 0
        if (
            self.plane is not None
            and num_hashes == self.plane.cfg.poh_iters
            and self.plane.queue_poh_span(self._span_start, self.chain.hash)
        ):
            self.metrics.inc("poh_spans_queued")
        self._span_start = self.chain.hash
        self.metrics.inc("ticks")
        self.entries_out += 1
        self.last_entry_hash = self.chain.hash
        if self.entries is not None:
            self.entries.append((num_hashes, self.chain.hash, []))
        self.publish(
            0, build_entry(num_hashes, self.chain.hash, []), sig=self.chain.hashcnt
        )

    def slot_complete(self) -> bool:
        return self._tick_cnt >= self.ticks_per_slot
