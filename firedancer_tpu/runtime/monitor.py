"""Operator surface: live monitor TUI + readiness gate.

Parity targets (no code shared): `fdctl monitor` — a terminal sampler
of every tile's cnc heartbeat, in/out sequence deltas and diag counters
(/root/reference/src/app/fdctl/monitor/monitor.c, workflow in
book/guide/tuning.md:212-238) — and `fdctl ready`, which blocks until
every tile heartbeats in the RUN state
(/root/reference/src/app/fdctl/ready.c).

A running topology advertises itself in a run descriptor
(`/tmp/fdtpu_run_<uid>.json`, written by runtime/topo.launch): stage
names + cnc shared-memory names.  `attach()` joins those cnc regions
READ-ONLY from any process, so the monitor and `ready` work exactly
like the reference's: against a live validator they did not start.
"""

from __future__ import annotations

import json
import os
import sys
import time
from dataclasses import dataclass
from multiprocessing import shared_memory

import numpy as np

from firedancer_tpu.tango import rings
from firedancer_tpu.tango.rings import CNC_SIG_FAIL, CNC_SIG_RUN, Cnc
from firedancer_tpu.utils import metrics as fm

RUN_DIR = os.environ.get("FDTPU_RUN_DIR", "/tmp")
_SIG_NAMES = {0: "BOOT", 1: "RUN", 2: "HALT", 3: "FAIL"}


def _attach_shm(name: str) -> shared_memory.SharedMemory:
    """Join a segment WITHOUT adopting ownership: CPython's resource
    tracker unlinks every tracked segment when its process exits, so a
    short-lived scraper (`fdtpu metrics --once`) would destroy the live
    topology's shm behind its back.  Observers must unregister — the
    segments belong to the launching supervisor (3.13's track=False,
    done by hand for this interpreter)."""
    s = shared_memory.SharedMemory(name=name)
    try:
        from multiprocessing import resource_tracker

        resource_tracker.unregister(s._name, "shared_memory")
    except Exception:
        pass  # tracker layout changed: worst case is the old behavior
    return s


def descriptor_path(uid: str) -> str:
    return os.path.join(RUN_DIR, f"fdtpu_run_{uid}.json")


# Mappings whose close() hit BufferError because the caller still held
# registry views (e.g. a MetricsServer scraping across a refresh()).
# Parked here so SharedMemory.__del__ never re-raises into the void;
# reaped on the next session close() once the views have died.
_ORPHANS: list = []


def _reap_orphans() -> None:
    for s in list(_ORPHANS):
        try:
            s.close()
        except BufferError:
            continue
        _ORPHANS.remove(s)


def flight_dump_path(uid: str) -> str:
    return os.path.join(RUN_DIR, f"fdtpu_flight_{uid}.json")


def list_flight_dumps() -> list[str]:
    """Flight-recorder dump paths, newest first (dumps outlive their
    runs deliberately — they are crash evidence)."""
    out = [
        os.path.join(RUN_DIR, fn)
        for fn in os.listdir(RUN_DIR)
        if fn.startswith("fdtpu_flight_") and fn.endswith(".json")
    ]
    return sorted(out, key=os.path.getmtime, reverse=True)


def write_descriptor(uid: str, stages: dict[str, str],
                     metrics: dict | None = None,
                     shards: dict | None = None) -> str:
    """stages: name -> cnc shm name; metrics: name -> {"shm": metrics
    segment shm name, "schema": schema_to_obj(...)}; shards: name ->
    {"shard": int, "logical": str} for sharded-serving stages (absent
    entries are unsharded).  Returns the path."""
    path = descriptor_path(uid)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"uid": uid, "pid": os.getpid(), "stages": stages,
                   "metrics": metrics or {}, "shards": shards or {}}, f)
    os.replace(tmp, path)
    return path


def remove_descriptor(uid: str) -> None:
    try:
        os.remove(descriptor_path(uid))
    except OSError:
        pass


def list_runs() -> list[str]:
    """Run descriptor paths, newest first, dead owners pruned."""
    out = []
    for fn in os.listdir(RUN_DIR):
        if not (fn.startswith("fdtpu_run_") and fn.endswith(".json")):
            continue
        p = os.path.join(RUN_DIR, fn)
        try:
            with open(p) as f:
                d = json.load(f)
            os.kill(int(d["pid"]), 0)  # owner alive?
        except (OSError, ValueError, KeyError):
            try:
                os.remove(p)
            except OSError:
                pass
            continue
        out.append(p)
    return sorted(out, key=os.path.getmtime, reverse=True)


@dataclass
class _Joined:
    name: str
    cnc: Cnc
    shm: shared_memory.SharedMemory
    # metrics-plane joins (None on descriptors that predate them or when
    # the segment failed to map — the cnc surface still works)
    registry: object = None  # fm.MetricsRegistry
    recorder: object = None  # fm.FlightRecorder
    met_shm: shared_memory.SharedMemory | None = None
    # sharded-serving labels (None/name on unsharded stages)
    shard: int | None = None
    logical: str | None = None


class MonitorSession:
    """Read-only join of a running topology's cnc + metrics regions."""

    def __init__(self, joined: list[_Joined], uid: str | None = None,
                 descriptor: str | None = None):
        self._joined = joined
        self.uid = uid
        # the path we attached through — refresh() re-reads it to detect
        # a replaced run or a metrics segment that failed to join
        self.descriptor = descriptor

    @classmethod
    def attach(cls, descriptor: str | None = None) -> "MonitorSession":
        """Join the given descriptor (path), or the newest live run."""
        if descriptor is None:
            runs = list_runs()
            if not runs:
                raise RuntimeError("no running fdtpu topology found")
            descriptor = runs[0]
        with open(descriptor) as f:
            d = json.load(f)
        joined = []
        met = d.get("metrics", {})
        shards = d.get("shards", {})
        for name, shm_name in d["stages"].items():
            s = _attach_shm(shm_name)
            cnc = Cnc(np.frombuffer(s.buf, dtype=rings.U64,
                                    count=2 + Cnc.NDIAG))
            j = _Joined(name, cnc, s)
            sh = shards.get(name)
            if sh:
                j.shard = sh.get("shard")
                j.logical = sh.get("logical", name)
            m = met.get(name)
            if m:
                ms = None
                try:
                    ms = _attach_shm(m["shm"])
                    schema = fm.schema_from_obj(m["schema"])
                    j.registry, j.recorder = fm.metrics_segment_attach(
                        ms.buf, schema
                    )
                    j.met_shm = ms
                except (OSError, ValueError, KeyError):
                    # metrics plane unavailable; cnc view still works —
                    # but never leak a mapping opened before the failure
                    if ms is not None and j.met_shm is None:
                        try:
                            ms.close()
                        except (OSError, BufferError):
                            pass
            joined.append(j)
        return cls(joined, uid=d.get("uid"), descriptor=descriptor)

    def close(self) -> None:
        for j in self._joined:
            # drop the numpy views before closing the mappings
            j.cnc.cells = np.zeros(2 + Cnc.NDIAG, dtype=rings.U64)
            j.shm.close()
            if j.met_shm is not None:
                j.registry = j.recorder = None
        import gc

        gc.collect()
        for j in self._joined:
            if j.met_shm is not None:
                try:
                    j.met_shm.close()
                except BufferError:
                    # a caller still holds registry views — park the
                    # mapping instead of orphaning it to a __del__ that
                    # would re-raise; reaped once the views die
                    _ORPHANS.append(j.met_shm)
                j.met_shm = None
        _reap_orphans()

    def refresh(self) -> bool:
        """Re-attach if the run behind our descriptor changed: a new uid
        (the run was replaced), a different stage set, or a metrics
        segment that failed to map at attach time and may exist now.

        An IN-PLACE restart (RestartPolicy respawn) reuses the same shm
        regions, so our mappings stay valid and this is a no-op — the
        stale case this guards is a scraper outliving the run it first
        joined (ISSUE 20 satellite 2).  Returns True when re-attached."""
        if self.descriptor is None:
            return False
        try:
            with open(self.descriptor) as f:
                d = json.load(f)
        except (OSError, ValueError):
            return False  # descriptor gone/torn — keep the old mappings
        joined_regs = {j.name for j in self._joined
                       if j.registry is not None}
        stale = (
            d.get("uid") != self.uid
            or set(d.get("stages", {})) != {j.name for j in self._joined}
            or bool(set(d.get("metrics", {})) - joined_regs)
        )
        if not stale:
            return False
        fresh = MonitorSession.attach(self.descriptor)
        self.close()
        self._joined = fresh._joined
        self.uid = fresh.uid
        return True

    # -- metrics plane ------------------------------------------------------

    def registries(self) -> dict:
        """{stage: MetricsRegistry} for every stage whose segment joined."""
        return {j.name: j.registry for j in self._joined
                if j.registry is not None}

    def shard_labels(self) -> dict:
        """{physical stage: {"stage": logical, "shard": i}} for sharded
        stages — the scrape relabeling that lets shards of one logical
        stage aggregate instead of fragmenting over physical names."""
        return {
            j.name: {"stage": j.logical or j.name, "shard": j.shard}
            for j in self._joined
            if j.shard is not None
        }

    def scrape(self) -> str:
        """The Prometheus text exposition over all joined stages (what
        `fdtpu metrics --once` prints and `--serve` serves); sharded
        stages carry {stage=<logical>,shard=<i>} labels."""
        return fm.render_prometheus(self.registries(),
                                    labels=self.shard_labels())

    def flight_records(self) -> dict:
        """{stage: [(ts_ns, event, arg), ...]} from the live rings."""
        return {j.name: j.recorder.records() for j in self._joined
                if j.recorder is not None}

    def flight_dump(self, reason: str = "live snapshot") -> dict:
        return fm.flight_dump_obj(
            self.uid or "?",
            {j.name: (j.registry, j.recorder) for j in self._joined
             if j.recorder is not None},
            failed=None, reason=reason,
        )

    # -- sampling -----------------------------------------------------------

    def sample(self, *, aggregate_shards: bool = False) -> list[dict]:
        """Per-stage liveness + counters.  aggregate_shards=True folds
        the N physical shards of each logical stage into ONE row (the
        monitor-TUI view): counters sum, heartbeat age is the WORST
        shard's, signal is FAIL if any shard failed (else the minimum —
        a still-BOOTing shard keeps the row at BOOT), and the latency
        percentiles come from the merged cross-shard histogram."""
        from firedancer_tpu.runtime.stage import Stage

        now = time.monotonic_ns()
        out = []
        groups: dict[str, list] = {}
        for j in self._joined:
            if aggregate_shards and j.shard is not None:
                groups.setdefault(j.logical or j.name, []).append(j)
                continue
            hb = j.cnc.last_heartbeat
            row = {
                "stage": j.name,
                "signal": j.cnc.signal,
                "heartbeat_age_ms": (now - hb) / 1e6 if hb else None,
                "in": j.cnc.diag(Stage.DIAG_FRAGS_IN),
                "out": j.cnc.diag(Stage.DIAG_FRAGS_OUT),
                "overrun": j.cnc.diag(Stage.DIAG_OVERRUN),
                "backpressure": j.cnc.diag(Stage.DIAG_BACKPRESSURE),
                "iters": j.cnc.diag(Stage.DIAG_ITER),
                "shard": j.shard,
            }
            row.update(fm.latency_row(j.registry))
            row["sweep_phases"] = fm.nsweep_phase_row([j.registry])
            out.append(row)
        for logical, js in groups.items():
            sigs = [j.cnc.signal for j in js]
            ages = [
                (now - j.cnc.last_heartbeat) / 1e6
                for j in js if j.cnc.last_heartbeat
            ]
            row = {
                "stage": f"{logical} x{len(js)}",
                "signal": (CNC_SIG_FAIL if CNC_SIG_FAIL in sigs
                           else min(sigs)),
                "heartbeat_age_ms": max(ages) if ages else None,
                "in": sum(j.cnc.diag(Stage.DIAG_FRAGS_IN) for j in js),
                "out": sum(j.cnc.diag(Stage.DIAG_FRAGS_OUT) for j in js),
                "overrun": sum(j.cnc.diag(Stage.DIAG_OVERRUN) for j in js),
                "backpressure": sum(
                    j.cnc.diag(Stage.DIAG_BACKPRESSURE) for j in js
                ),
                "iters": sum(j.cnc.diag(Stage.DIAG_ITER) for j in js),
                "shards": len(js),
            }
            row.update(fm.latency_row_merged([j.registry for j in js]))
            row["sweep_phases"] = fm.nsweep_phase_row(
                [j.registry for j in js])
            out.append(row)
        return out

    def all_running(self, *, max_heartbeat_age_s: float = 5.0) -> bool:
        for r in self.sample():
            if r["signal"] != CNC_SIG_RUN:
                return False
            age = r["heartbeat_age_ms"]
            if age is None or age > max_heartbeat_age_s * 1e3:
                return False
        return True

    def any_failed(self) -> bool:
        return any(r["signal"] == CNC_SIG_FAIL for r in self.sample())

    def wait_ready(self, *, timeout_s: float = 60.0,
                   poll_s: float = 0.05) -> bool:
        """Block until every stage heartbeats in RUN (the `ready`
        command).  False on timeout or any FAIL."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.any_failed():
                return False
            if self.all_running():
                return True
            time.sleep(poll_s)
        return False

    # -- rendering ----------------------------------------------------------

    @staticmethod
    def render(rows: list[dict], prev: list[dict] | None,
               dt_s: float) -> str:
        hdr = (f"{'stage':<14}{'state':<6}{'hb_ms':>8}{'in/s':>11}"
               f"{'out/s':>11}{'busy%':>7}{'ovrn':>7}{'bkp':>7}"
               f"{'p50 lat':>9}{'p99 lat':>9}{'sweep p50us':>16}")
        lines = [hdr, "-" * len(hdr)]
        prev_by = {r["stage"]: r for r in prev or []}
        for r in rows:
            p = prev_by.get(r["stage"])
            in_rate = out_rate = busy = float("nan")
            if p and dt_s > 0:
                in_rate = (r["in"] - p["in"]) / dt_s
                out_rate = (r["out"] - p["out"]) / dt_s
                diters = r["iters"] - p["iters"]
                dwork = r["in"] - p["in"] + r["out"] - p["out"]
                busy = 100.0 * dwork / diters if diters > 0 else 0.0
            hb = (f"{r['heartbeat_age_ms']:.1f}"
                  if r["heartbeat_age_ms"] is not None else "-")
            fmt = lambda v: "-" if v != v else f"{v:,.0f}"  # noqa: E731
            # cumulative per-stage latency percentiles from the shm
            # histogram (ms; "-" when the metrics plane is not joined)
            lines.append(
                f"{r['stage']:<14}{_SIG_NAMES.get(r['signal'], '?'):<6}"
                f"{hb:>8}{fmt(in_rate):>11}{fmt(out_rate):>11}"
                f"{fmt(busy):>7}{r['overrun']:>7}{r['backpressure']:>7}"
                f"{fm.format_latency_ms(r.get('lat_p50_ms')):>9}"
                f"{fm.format_latency_ms(r.get('lat_p99_ms')):>9}"
                f"{fm.format_phase_cell(r.get('sweep_phases') or {}):>16}"
            )
        return "\n".join(lines)

    def run(self, *, interval_s: float = 1.0, iterations: int | None = None,
            out=sys.stdout) -> None:
        """The live TUI loop: redraw-in-place sampler (^C exits)."""
        prev, prev_t = None, time.monotonic()
        first = True
        n = 0
        try:
            while iterations is None or n < iterations:
                rows = self.sample(aggregate_shards=True)
                now = time.monotonic()
                text = self.render(rows, prev, now - prev_t)
                if not first:
                    # move cursor up over the previous frame
                    out.write(f"\x1b[{text.count(chr(10)) + 1}A")
                out.write("\x1b[J" + text + "\n")
                out.flush()
                prev, prev_t, first = rows, now, False
                n += 1
                if iterations is None or n < iterations:
                    time.sleep(interval_s)
        except KeyboardInterrupt:
            pass
