"""ctypes binding for the native net sweep client (native/fd_net.cpp).

The ingress stage's QUIC short-header steady state in one FFI crossing
per datagram (ISSUE 18): DCID -> connection lookup over the interned
table, header-protection unmask, AES-128-GCM open (AES-NI + PCLMUL with
a scalar fallback, byte-identical to ops/aes.py), packet-number dedup,
STREAM frame walk and fd_tpu_reasm-style reassembly.  Whole txns land in
a reusable out arena with an (off, sz, sig, tsorig) table shaped for
fdr_publish_burst; the credit-gated publish retires only the published
prefix (`pop`), the unpublished tail stays queued in C — never dropped.

Everything the C side cannot fully own PUNTs back to the Python lane in
arrival order (long headers, unknown CIDs, migration, CRYPTO /
PATH_CHALLENGE / PATH_RESPONSE / CONNECTION_CLOSE / HANDSHAKE_DONE /
multi-range-ACK frame mixes): waltz/quic.py stays the single source of
truth for the control plane.  The binding is RX-only — consumed packets
surface as events (pn sync, single-range acks, flow-window deltas) the
stage replays into the authoritative Python Connection after every
crossing.

`FDTPU_NATIVE_NET=0` disables the lane; a missing toolchain degrades to
the Python per-datagram path via NativeUnavailable.  Differential parity
with the Python lane is the contract (tests/test_net_native.py).
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from firedancer_tpu.utils.nativebuild import NativeUnavailable, build_so

_SRC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
    "fd_net.cpp",
)
_SO = os.path.join(os.path.dirname(_SRC), "fd_net.so")

ENV_SWITCH = "FDTPU_NATIVE_NET"

# fdn_datagram return codes (fd_net.cpp enum)
RC_CONSUMED = 0
RC_PUNT = 1
RC_DROP = 2

# event rows (type, conn_idx, a, b)
EV_PKT = 1   # a = pn, b = flag (0 ack-eliciting, 1 dup, 2 bad-frame, 3 pure-ack)
EV_ACK = 2   # a = largest, b = first_range_len
EV_WIN = 3   # a = rx_consumed delta, b = rx_data_total delta

_EV_CAP = 4096
_OUT_CAP = 1024

_lib = None


def _load():
    global _lib
    if _lib is None:
        lib = ctypes.CDLL(build_so(_SRC, _SO))
        u8p = ctypes.POINTER(ctypes.c_uint8)
        i64p = ctypes.POINTER(ctypes.c_int64)
        u64 = ctypes.c_uint64
        i64 = ctypes.c_int64
        i32 = ctypes.c_int32
        u32 = ctypes.c_uint32
        vp = ctypes.c_void_p
        cp = ctypes.c_char_p
        lib.fdn_new.argtypes = [i32, i32]
        lib.fdn_new.restype = vp
        lib.fdn_delete.argtypes = [vp]
        lib.fdn_conn_add.argtypes = [vp, cp, u32, cp, cp, cp, i64p, i32,
                                     u64, u64]
        lib.fdn_conn_add.restype = i32
        lib.fdn_conn_remove.argtypes = [vp, i32]
        lib.fdn_conn_set_addr.argtypes = [vp, i32, u32]
        lib.fdn_conn_window.argtypes = [vp, i32, u64, u64]
        lib.fdn_conn_pn_add.argtypes = [vp, i32, i64]
        lib.fdn_datagram.argtypes = [vp, cp, i32, u32]
        lib.fdn_datagram.restype = i32
        lib.fdn_udp_sweep.argtypes = [vp, i32, i32]
        lib.fdn_udp_sweep.restype = i32
        lib.fdn_udp_sweep_scalar.argtypes = [vp, i32, i32]
        lib.fdn_udp_sweep_scalar.restype = i32
        lib.fdn_set_metrics.argtypes = [vp, vp]
        for name in ("fdn_counters_ptr", "fdn_events_ptr",
                     "fdn_out_tbl_ptr", "fdn_out_arena_ptr"):
            getattr(lib, name).argtypes = [vp]
            getattr(lib, name).restype = vp
        for name in ("fdn_counters_len", "fdn_events_count",
                     "fdn_out_count"):
            getattr(lib, name).argtypes = [vp]
            getattr(lib, name).restype = i32
        lib.fdn_events_clear.argtypes = [vp]
        lib.fdn_out_pop.argtypes = [vp, i32]
        lib.fdn_aes_ecb.argtypes = [cp, i32, cp, i32, cp]
        lib.fdn_aes_ecb.restype = i32
        lib.fdn_gcm_seal.argtypes = [cp, i32, cp, cp, i32, cp, i32, cp, cp]
        lib.fdn_gcm_seal.restype = i32
        lib.fdn_gcm_open.argtypes = [cp, i32, cp, cp, i32, cp, i32, cp, cp]
        lib.fdn_gcm_open.restype = i32
        lib.fdn_simd_features.argtypes = []
        lib.fdn_simd_features.restype = i32
        _lib = lib
    return _lib


def enabled() -> bool:
    """The env switch: FDTPU_NATIVE_NET=0 forces the Python lane."""
    return os.environ.get(ENV_SWITCH, "1") != "0"


def available() -> bool:
    """enabled AND the .so loads (toolchain-less hosts degrade to the
    Python per-datagram path gracefully)."""
    if not enabled():
        return False
    try:
        _load()
        return True
    except (NativeUnavailable, OSError, AttributeError):
        return False


# counter tail, in fd_net.cpp declaration order
_COUNTERS = ("rx_dgram", "consumed", "punt", "dup", "bad_packet", "txn",
             "oversz", "evicted", "flow_violation", "auth_fail",
             "udp_pkts", "aesni", "pclmul", "tail_retained")
COUNTER_IDX = {name: i for i, name in enumerate(_COUNTERS)}


class NetClient:
    """One ingress stage's native session: the interned connection
    table, the per-datagram fast path, and the zero-FFI event/out/counter
    views the stage drains after every crossing."""

    def __init__(self, *, max_conns: int, reasm_depth: int):
        lib = _load()
        self._lib = lib
        self._h = lib.fdn_new(max_conns, reasm_depth)
        if not self._h:
            raise NativeUnavailable("fdn_new failed")

        def view(ptr, n, dt):
            ct = (ctypes.c_uint64 * n) if dt == np.uint64 else \
                 (ctypes.c_uint8 * n)
            return np.frombuffer(ct.from_address(ptr), dtype=dt)

        ncnt = int(lib.fdn_counters_len(self._h))
        self.counters_view = view(int(lib.fdn_counters_ptr(self._h)),
                                  ncnt, np.uint64)
        self.events = view(int(lib.fdn_events_ptr(self._h)),
                           _EV_CAP * 4, np.uint64).reshape(_EV_CAP, 4)
        self.out_tbl = view(int(lib.fdn_out_tbl_ptr(self._h)),
                            _OUT_CAP * 4, np.uint64).reshape(_OUT_CAP, 4)
        self.arena_ptr = int(lib.fdn_out_arena_ptr(self._h))
        self.arena = view(self.arena_ptr, _OUT_CAP * (1232 + 48), np.uint8)

    # -- connection table ----------------------------------------------------

    def conn_add(self, dcid: bytes, addr_id: int, key: bytes, iv: bytes,
                 hp: bytes, ranges: list[tuple[int, int]],
                 rx_max_data: int, rx_data_total: int) -> int:
        """Install an ESTABLISHED connection's rx side; ranges seed the
        pn dedup window from the Python tracker.  -1 = table full (the
        conn simply stays on the Python lane)."""
        flat = (ctypes.c_int64 * (2 * len(ranges)))()
        for i, (lo, hi) in enumerate(ranges):
            flat[2 * i] = lo
            flat[2 * i + 1] = hi
        return int(self._lib.fdn_conn_add(
            self._h, bytes(dcid), addr_id, bytes(key), bytes(iv),
            bytes(hp), flat, len(ranges), rx_max_data, rx_data_total))

    def conn_remove(self, idx: int) -> None:
        self._lib.fdn_conn_remove(self._h, idx)

    def conn_set_addr(self, idx: int, addr_id: int) -> None:
        self._lib.fdn_conn_set_addr(self._h, idx, addr_id)

    def conn_window(self, idx: int, rx_max_data: int,
                    rx_data_total: int) -> None:
        self._lib.fdn_conn_window(self._h, idx, rx_max_data, rx_data_total)

    def conn_pn_add(self, idx: int, pn: int) -> None:
        self._lib.fdn_conn_pn_add(self._h, idx, pn)

    # -- the hot path --------------------------------------------------------

    def datagram(self, data: bytes, addr_id: int) -> int:
        """One datagram through the C fast path; RC_CONSUMED /
        RC_PUNT (run the Python lane on these bytes) / RC_DROP."""
        return int(self._lib.fdn_datagram(self._h, data, len(data),
                                          addr_id))

    def set_metrics(self, plane) -> None:
        """Arm the shm metrics plane (ISSUE 20): socket sweeps observe
        the drain phase and per-datagram decrypt+apply the callback
        phase, straight from C.  `plane` None disarms."""
        self._plane = plane  # keepalive: C holds the raw pointer
        self._lib.fdn_set_metrics(
            self._h, plane.ptr if plane is not None else None)

    def udp_sweep(self, fd: int, max_pkts: int) -> int:
        """One real recvmmsg syscall per burst, kernel-scattered
        straight into the out arena (per-packet iovec slots — no bounce
        buffer, no second copy); datagrams taken."""
        return int(self._lib.fdn_udp_sweep(self._h, fd, max_pkts))

    def udp_sweep_scalar(self, fd: int, max_pkts: int) -> int:
        """The byte-identical scalar fallback: one recv per datagram
        through a bounce buffer (the pre-recvmmsg shape).  Differential
        suites drive both paths over the same socket load."""
        return int(self._lib.fdn_udp_sweep_scalar(self._h, fd, max_pkts))

    # -- drain surface -------------------------------------------------------

    def event_count(self) -> int:
        return int(self._lib.fdn_events_count(self._h))

    def events_clear(self) -> None:
        self._lib.fdn_events_clear(self._h)

    def out_count(self) -> int:
        return int(self._lib.fdn_out_count(self._h))

    def out_pop(self, n: int) -> None:
        self._lib.fdn_out_pop(self._h, n)

    def out_txn(self, row: int) -> bytes:
        off = int(self.out_tbl[row, 0])
        sz = int(self.out_tbl[row, 1])
        return bytes(self.arena[off : off + sz])

    def counters(self) -> dict[str, int]:
        return {name: int(self.counters_view[i])
                for i, name in enumerate(_COUNTERS)}

    def close(self) -> None:
        if self._h:
            self.counters_view = self.events = self.out_tbl = None
            self.arena = None
            self._lib.fdn_delete(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


# -- standalone crypto surface (ops/aes.py acceleration) ----------------------


def aes_ecb_blocks(key: bytes, data: bytes) -> bytes:
    """AES-ECB over len(data)/16 blocks (ops/aes.py Aes.encrypt_block's
    accelerated body; callers validate lengths)."""
    lib = _load()
    n = len(data) // 16
    out = ctypes.create_string_buffer(16 * n)
    if lib.fdn_aes_ecb(key, len(key), data, n, out) != 0:
        raise ValueError("AES-128 or AES-256 keys only")
    return out.raw


def gcm_seal(key: bytes, iv: bytes, plaintext: bytes,
             aad: bytes) -> tuple[bytes, bytes]:
    lib = _load()
    ct = ctypes.create_string_buffer(max(len(plaintext), 1))
    tag = ctypes.create_string_buffer(16)
    if lib.fdn_gcm_seal(key, len(key), iv, aad, len(aad), plaintext,
                        len(plaintext), ct, tag) != 0:
        raise ValueError("AES-128 or AES-256 keys only")
    return ct.raw[: len(plaintext)], tag.raw[:16]


def gcm_open(key: bytes, iv: bytes, ciphertext: bytes, tag: bytes,
             aad: bytes) -> bytes | None:
    lib = _load()
    pt = ctypes.create_string_buffer(max(len(ciphertext), 1))
    rc = lib.fdn_gcm_open(key, len(key), iv, aad, len(aad), ciphertext,
                          len(ciphertext), tag, pt)
    if rc == -2:
        raise ValueError("AES-128 or AES-256 keys only")
    if rc != 0:
        return None
    return pt.raw[: len(ciphertext)]


def simd_features() -> int:
    """bit0 = AESNI, bit1 = PCLMUL (bench/test introspection)."""
    return int(_load().fdn_simd_features())
