"""Shredder: entry batches -> FEC sets of signed merkle shreds.

Behavioral port of /root/reference/src/disco/shred/fd_shredder.c with the
same Agave-compatible shredding policy (protocol constants):

  - 31840-byte "normal" FEC sets of 32 data shreds x 995-byte payloads
    while >= 2 normal sets of bytes remain; one odd-sized final set;
  - odd-set payload size from the tree-depth formula 1115 - 20*depth
    (the size table in fd_shredder.h:100-112);
  - parity counts from the data->parity table for d <= 32, else d
    (fd_shredder_data_to_parity_cnt);
  - per-shred flags: reference tick, DATA_COMPLETE on the batch's last
    shred, SLOT_COMPLETE when the batch ends the slot;
  - RS parity over the post-signature header+payload region, merkle tree
    over all d+p shreds' leaf regions, leader signature over the root,
    proof + signature written into every shred.

TPU-native twist: the reference computes one FEC set at a time with GFNI
Reed-Solomon; here all same-shape sets of an entry batch run together in
ONE bit-matmul reedsol.encode over (nsets, d, sz) — a whole entry batch is
a single parity dispatch regardless of set count.  Merkle trees are ~64
leaves each, host hashlib by default; ops/bmtree.layers_batch provides the
batched device path for wide fan-outs.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from firedancer_tpu.ops import bmtree, reedsol
from firedancer_tpu.protocol import shred as fs

NORMAL_FEC_SET_PAYLOAD_SZ = 31840
NORMAL_DATA_CNT = 32
NORMAL_PAYLOAD_PER_SHRED = 995

# data shred count -> parity shred count, d <= 32 (fd_shredder.h:30-34)
DATA_TO_PARITY = [
    0, 17, 18, 19, 19, 20, 21, 21,
    22, 23, 23, 24, 24, 25, 25, 26,
    26, 26, 27, 27, 28, 28, 29, 29,
    29, 30, 30, 31, 31, 31, 32, 32, 32,
]


def parity_cnt_for(data_cnt: int) -> int:
    return DATA_TO_PARITY[data_cnt] if data_cnt <= 32 else data_cnt


def count_fec_sets(sz: int) -> int:
    return max(sz, 2 * NORMAL_FEC_SET_PAYLOAD_SZ - 1) // NORMAL_FEC_SET_PAYLOAD_SZ


def _odd_set_payload_per_shred(remaining: int) -> int:
    """payload_bytes_per_shred for the odd-sized final set (always the
    largest legitimate value, fd_shredder.h:108-112)."""
    if remaining <= 9135:
        return 1015
    if remaining <= 31840:
        return 995
    if remaining <= 62400:
        return 975
    return 955


def count_data_shreds(sz: int) -> int:
    normal = count_fec_sets(sz) - 1
    remaining = sz - normal * NORMAL_FEC_SET_PAYLOAD_SZ
    per = _odd_set_payload_per_shred(remaining)
    return normal * NORMAL_DATA_CNT + max(1, (remaining + per - 1) // per)


def count_parity_shreds(sz: int) -> int:
    normal = count_fec_sets(sz) - 1
    remaining = sz - normal * NORMAL_FEC_SET_PAYLOAD_SZ
    per = _odd_set_payload_per_shred(remaining)
    d = max(1, (remaining + per - 1) // per)
    return normal * NORMAL_DATA_CNT + parity_cnt_for(d)


@dataclass
class EntryBatchMeta:
    """fd_entry_batch_meta_t analog."""

    parent_offset: int = 1
    reference_tick: int = 0
    block_complete: bool = False


@dataclass
class FecSet:
    """One produced FEC set: complete wire shreds + the signed root."""

    data_shreds: list[bytes]
    parity_shreds: list[bytes]
    merkle_root: bytes
    slot: int
    fec_set_idx: int


@dataclass
class Shredder:
    """Stateful across a slot: shred indices continue between batches.

    plane: a parallel/serve.ServePlane — when configured, normal-shape
    FEC groups (d=32) compute parity through the mesh-sharded RS program
    (sets sharded over the mesh, sz zero-padded to the compiled width);
    odd-shape tails keep the host lane, byte-identically.
    """

    signer: object  # callable(merkle_root: bytes) -> 64-byte signature
    shred_version: int = 0
    slot: int = -1
    data_idx_offset: int = 0
    parity_idx_offset: int = 0
    plane: object = None

    def __post_init__(self):
        # build/load the native RS encoder now, not when the first FEC
        # set of a leader slot is mid-flight (cold hosts shell out to g++)
        reedsol._host_lib()

    def entry_batch_to_fec_sets(
        self,
        entry_batch: bytes,
        *,
        slot: int,
        meta: EntryBatchMeta | None = None,
    ) -> list[FecSet]:
        """Shred a whole entry batch (init_batch + next_fec_set* +
        fini_batch in one call, batching the device work across sets)."""
        if not entry_batch:
            raise ValueError("empty entry batch")
        meta = meta or EntryBatchMeta()
        if slot != self.slot:
            self.data_idx_offset = 0
            self.parity_idx_offset = 0
            self.slot = slot

        # -- split into per-set chunks (reference chunking rule) -----------
        chunks = []
        offset = 0
        total = len(entry_batch)
        while offset < total:
            remaining = total - offset
            chunk = (
                NORMAL_FEC_SET_PAYLOAD_SZ
                if remaining >= 2 * NORMAL_FEC_SET_PAYLOAD_SZ
                else remaining
            )
            chunks.append((offset, chunk))
            offset += chunk

        sets: list[FecSet] = []
        plan = []
        data_base = self.data_idx_offset
        parity_base = self.parity_idx_offset
        for offset, chunk in chunks:
            per = _odd_set_payload_per_shred(chunk)
            d = max(1, (chunk + per - 1) // per)
            p = parity_cnt_for(d)
            depth = bmtree.depth(d + p) - 1  # proof length excludes root
            region = fs.data_payload_region_sz(depth)
            plan.append((offset, chunk, d, p, depth, region, data_base, parity_base))
            data_base += d
            parity_base += p
        self.data_idx_offset = data_base
        self.parity_idx_offset = parity_base

        # -- build unsigned data shreds host-side --------------------------
        built = []
        for set_i, (offset, chunk, d, p, depth, region, dbase, pbase) in enumerate(
            plan
        ):
            last_set = set_i == len(plan) - 1
            data_bufs = []
            off = offset
            end = offset + chunk
            for i in range(d):
                payload = entry_batch[off : min(off + region, end)]
                off += len(payload)
                last_in_batch = last_set and i == d - 1
                flags = meta.reference_tick & fs.DATA_REF_TICK_MASK
                if last_in_batch:
                    flags |= fs.DATA_FLAG_DATA_COMPLETE
                    if meta.block_complete:
                        flags |= fs.DATA_FLAG_SLOT_COMPLETE
                data_bufs.append(
                    fs.build_data_shred(
                        slot=slot,
                        idx=dbase + i,
                        version=self.shred_version,
                        fec_set_idx=dbase,
                        parent_off=meta.parent_offset,
                        flags=flags,
                        payload=payload,
                        merkle_proof_cnt=depth,
                    )
                )
            built.append(data_bufs)

        # -- batched RS parity: group same-shape sets into one encode ------
        parity_by_set: dict[int, np.ndarray] = {}
        groups: dict[tuple[int, int, int], list[int]] = {}
        for set_i, (_, _, d, p, depth, _, _, _) in enumerate(plan):
            elt_sz = fs.code_payload_sz(depth)
            groups.setdefault((d, p, elt_sz), []).append(set_i)
        for (d, p, elt_sz), idxs in groups.items():
            stack = np.zeros((len(idxs), d, elt_sz), dtype=np.uint8)
            for k, set_i in enumerate(idxs):
                for i, buf in enumerate(built[set_i]):
                    stack[k, i] = np.frombuffer(
                        bytes(buf[fs.SIGNATURE_SZ : fs.SIGNATURE_SZ + elt_sz]),
                        dtype=np.uint8,
                    )
            # host lane: one-to-few sets per batch is dispatch-bound on
            # the device path (native/fd_reedsol.cpp; parity-identical).
            # With a serving plane configured, normal-shape groups ride
            # the mesh-sharded RS program instead.
            if self.plane is not None:
                par = self.plane.encode_parity(stack, p)  # (nsets, p, elt_sz)
            else:
                par = reedsol.encode_host(stack, p)
            for k, set_i in enumerate(idxs):
                parity_by_set[set_i] = par[k]

        # -- assemble sets: parity shreds, merkle tree, sign, proofs -------
        for set_i, (_, _, d, p, depth, _, dbase, pbase) in enumerate(plan):
            data_bufs = built[set_i]
            parity_bufs = [
                fs.build_code_shred(
                    slot=slot,
                    idx=pbase + j,
                    version=self.shred_version,
                    fec_set_idx=dbase,
                    data_cnt=d,
                    code_cnt=p,
                    code_idx=j,
                    parity=parity_by_set[set_i][j].tobytes(),
                    merkle_proof_cnt=depth,
                )
                for j in range(p)
            ]
            leaves_full = [
                bmtree.hash_leaf_full(
                    bytes(b[fs.SIGNATURE_SZ : fs.merkle_off(b[fs.SIGNATURE_SZ])])
                )
                for b in data_bufs
            ] + [
                bmtree.hash_leaf_full(
                    bytes(b[fs.SIGNATURE_SZ : fs.merkle_off(b[fs.SIGNATURE_SZ])])
                )
                for b in parity_bufs
            ]
            layers = bmtree.tree_layers([x[: bmtree.NODE_SZ] for x in leaves_full])
            # the signature covers the UNTRUNCATED 32-byte root
            root = bmtree.root32_from_layers(layers, leaves_full)
            sig = self.signer(root)
            for i, buf in enumerate(data_bufs):
                fs.set_signature(buf, sig)
                fs.set_merkle_proof(buf, bmtree.get_proof(layers, i))
            for j, buf in enumerate(parity_bufs):
                fs.set_signature(buf, sig)
                fs.set_merkle_proof(buf, bmtree.get_proof(layers, d + j))
            sets.append(
                FecSet(
                    data_shreds=[bytes(b) for b in data_bufs],
                    parity_shreds=[bytes(b) for b in parity_bufs],
                    merkle_root=root,
                    slot=slot,
                    fec_set_idx=dbase,
                )
            )

        return sets
