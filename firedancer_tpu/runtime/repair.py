"""Shred repair: request missing shreds from peers over UDP.

The repair-protocol position of the reference
(/root/reference/src/flamenco/repair/fd_repair.c — request shreds the
turbine fan-out never delivered; served from the peer's blockstore).
Wire format is this framework's own compact framing (the reference
speaks Solana's repair protocol; protocol-exact encoding rides on this
same structure later):

    request:  "FDRP" | u8 1 | u64 slot | u32 shred_idx | u32 nonce |
              32B requester pubkey | 64B sig over the preceding bytes
    response: "FDRP" | u8 2 | u32 nonce | shred bytes

Requests are signed (the reference signs repair requests so servers can
prioritize staked peers); the server verifies before serving.  The
client validates that the response parses and matches the requested
(slot, idx) before handing it to the FEC resolver — repair peers are
untrusted; the resolver's merkle checks stay the real gate.
"""

from __future__ import annotations

import socket
import struct

from firedancer_tpu.ops.ref import ed25519_ref as ref
from firedancer_tpu.protocol import shred as fs

MAGIC = b"FDRP"
T_REQUEST = 1
T_RESPONSE = 2

_REQ = struct.Struct("<QII")  # slot, shred_idx, nonce


def encode_request(
    slot: int, shred_idx: int, nonce: int, pubkey: bytes, signer
) -> bytes:
    body = MAGIC + bytes([T_REQUEST]) + _REQ.pack(slot, shred_idx, nonce) + pubkey
    return body + signer(body)


def decode_request(buf: bytes):
    """-> (slot, shred_idx, nonce, pubkey) or None (bad frame/signature)."""
    if len(buf) != 4 + 1 + _REQ.size + 32 + 64:
        return None
    if buf[:4] != MAGIC or buf[4] != T_REQUEST:
        return None
    slot, idx, nonce = _REQ.unpack_from(buf, 5)
    pubkey = buf[5 + _REQ.size : 5 + _REQ.size + 32]
    sig = buf[-64:]
    if not ref.verify(buf[:-64], sig, pubkey):
        return None
    return slot, idx, nonce, pubkey


def encode_response(nonce: int, shred: bytes) -> bytes:
    return MAGIC + bytes([T_RESPONSE]) + struct.pack("<I", nonce) + shred


def decode_response(buf: bytes):
    """-> (nonce, shred bytes) or None."""
    if len(buf) < 9 or buf[:4] != MAGIC or buf[4] != T_RESPONSE:
        return None
    (nonce,) = struct.unpack_from("<I", buf, 5)
    return nonce, buf[9:]


class Blockstore:
    """Minimal shred-by-(slot, idx) store the server serves from (the
    blockstore's repair-facing face; StoreStage feeds it)."""

    def __init__(self):
        self._shreds: dict[tuple[int, int], bytes] = {}

    def put_set(self, fec_set) -> None:
        for buf in fec_set.data_shreds:
            s = fs.parse(buf)
            self._shreds[(s.slot, s.idx)] = bytes(buf)

    def put_shred(self, buf: bytes) -> None:
        s = fs.parse(buf)
        if s is not None and s.is_data:
            self._shreds[(s.slot, s.idx)] = bytes(buf)

    def get(self, slot: int, idx: int) -> bytes | None:
        return self._shreds.get((slot, idx))

    def __len__(self) -> int:
        return len(self._shreds)


class RepairServer:
    def __init__(self, store: Blockstore, *, host="127.0.0.1", port=0):
        self.store = store
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.bind((host, port))
        self.sock.setblocking(False)
        self.served = 0
        self.refused = 0

    @property
    def addr(self):
        return self.sock.getsockname()

    def poll(self, burst: int = 32) -> None:
        for _ in range(burst):
            try:
                data, src = self.sock.recvfrom(2048)
            except (BlockingIOError, InterruptedError):
                return
            req = decode_request(data)
            if req is None:
                self.refused += 1
                continue
            slot, idx, nonce, _pub = req
            shred = self.store.get(slot, idx)
            if shred is not None:
                self.sock.sendto(encode_response(nonce, shred), src)
                self.served += 1

    def close(self):
        self.sock.close()


class RepairClient:
    def __init__(self, identity_secret: bytes, *, signer=None):
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.setblocking(False)
        self.pubkey = ref.public_key(identity_secret)
        self._signer = signer or (lambda msg: ref.sign(identity_secret, msg))
        self._nonce = 0
        self.metrics = {"req": 0, "ok": 0, "bad_response": 0}

    def request(
        self, peer, slot: int, shred_idx: int, *, spin=None, max_spins=200_000
    ) -> bytes | None:
        """One request/response round trip; None on timeout/bad reply."""
        self._nonce += 1
        nonce = self._nonce
        self.sock.sendto(
            encode_request(slot, shred_idx, nonce, self.pubkey, self._signer), peer
        )
        self.metrics["req"] += 1
        for _ in range(max_spins):
            if spin is not None:
                spin()
            try:
                data, _src = self.sock.recvfrom(2048)
            except (BlockingIOError, InterruptedError):
                continue
            res = decode_response(data)
            if res is None or res[0] != nonce:
                self.metrics["bad_response"] += 1
                continue
            shred = res[1]
            s = fs.parse(shred)
            if s is None or s.slot != slot or s.idx != shred_idx:
                self.metrics["bad_response"] += 1
                continue
            self.metrics["ok"] += 1
            return shred
        return None

    def close(self):
        self.sock.close()
