"""Shred repair: request missing shreds from peers over UDP.

The repair-protocol position of the reference
(/root/reference/src/flamenco/repair/fd_repair.c — request shreds the
turbine fan-out never delivered; served from the peer's blockstore).
Round-3 upgrade: the wire format is Solana's ServeRepair protocol
(flamenco/repair_wire.py — signed RepairRequestHeader, WindowIndex /
HighestWindowIndex / Orphan requests, shred||nonce responses), replacing
the earlier compact framing.

Requests are signed (servers can prioritize staked peers); the server
verifies the header signature and the recipient pubkey before serving.
The client validates that the response parses and matches the requested
(slot, idx) before handing it to the FEC resolver — repair peers are
untrusted; the resolver's merkle checks stay the real gate.
"""

from __future__ import annotations

import socket
import time

from firedancer_tpu.flamenco import repair_wire as rw
from firedancer_tpu.ops.ref import ed25519_ref as ref
from firedancer_tpu.protocol import shred as fs
from firedancer_tpu.utils.rng import Rng


class Blockstore:
    """Minimal shred-by-(slot, idx) store the server serves from (the
    blockstore's repair-facing face; StoreStage feeds it)."""

    def __init__(self):
        self._shreds: dict[tuple[int, int], bytes] = {}
        self._max_idx: dict[int, int] = {}  # slot -> highest stored idx

    def _put(self, slot: int, idx: int, buf: bytes) -> None:
        self._shreds[(slot, idx)] = bytes(buf)
        if idx > self._max_idx.get(slot, -1):
            self._max_idx[slot] = idx

    def put_set(self, fec_set) -> None:
        for buf in fec_set.data_shreds:
            s = fs.parse(buf)
            self._put(s.slot, s.idx, buf)

    def put_shred(self, buf: bytes) -> None:
        s = fs.parse(buf)
        if s is not None and s.is_data:
            self._put(s.slot, s.idx, buf)

    def get(self, slot: int, idx: int) -> bytes | None:
        return self._shreds.get((slot, idx))

    def highest(self, slot: int, min_idx: int = 0) -> bytes | None:
        """The highest-index stored shred of `slot` at idx >= min_idx
        (the HighestWindowIndex serving rule); O(1) via the per-slot
        max-index map — the poll loop must not scan the whole store."""
        hi = self._max_idx.get(slot, -1)
        if hi < min_idx:
            return None
        return self._shreds.get((slot, hi))

    def __len__(self) -> int:
        return len(self._shreds)


class RepairServer:
    def __init__(self, store: Blockstore, identity_secret: bytes | None = None,
                 *, host="127.0.0.1", port=0):
        self.store = store
        self.pubkey = (
            ref.public_key(identity_secret) if identity_secret else None
        )
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.bind((host, port))
        self.sock.setblocking(False)
        self.served = 0
        self.refused = 0

    @property
    def addr(self):
        return self.sock.getsockname()

    def poll(self, burst: int = 32) -> None:
        for _ in range(burst):
            try:
                data, src = self.sock.recvfrom(2048)
            except (BlockingIOError, InterruptedError):
                return
            req = rw.verify_request(data)
            if req is None:
                self.refused += 1
                continue
            name, payload = req
            h = payload.header
            if self.pubkey is not None and h.recipient != self.pubkey:
                self.refused += 1  # misdirected request
                continue
            if name == "window_index":
                shred = self.store.get(payload.slot, payload.shred_index)
            elif name == "highest_window_index":
                shred = self.store.highest(payload.slot, payload.shred_index)
            else:  # orphan: serve the highest shred of the slot
                shred = self.store.highest(payload.slot)
            if shred is not None:
                self.sock.sendto(rw.encode_response(shred, h.nonce), src)
                self.served += 1

    def close(self):
        self.sock.close()


class RepairClient:
    def __init__(self, identity_secret: bytes, *, signer=None,
                 pubkey: bytes | None = None, rng: Rng | None = None):
        """`signer` (msg -> 64B sig) keeps the real key out-of-process
        (the sign-stage pattern); pass the matching `pubkey` with it.
        `rng` seeds the retry backoff jitter (utils/rng — deterministic
        per seed, never wall-clock entropy; FD209 discipline)."""
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.setblocking(False)
        self._secret = identity_secret
        self._signer = signer
        self.pubkey = pubkey or ref.public_key(identity_secret)
        self._nonce = 0
        self._rng = rng if rng is not None else Rng(0x52E7A12, 0)
        self.last_peer = None  # (host, port) that answered the last ok
        self.metrics = {"req": 0, "ok": 0, "bad_response": 0,
                        "timeout": 0, "retry": 0, "peer_rotated": 0}

    def _request(self, peer, name: str, payload) -> bytes:
        return rw.sign_request(self._secret, name, payload,
                               signer=self._signer)

    def _attempt(self, peer, slot: int, shred_idx: int, *, spin,
                 budget_spins: int, recipient: bytes, kind: str
                 ) -> bytes | None:
        """One signed request + one bounded wait window on one peer."""
        self._nonce += 1
        nonce = self._nonce
        header = rw.RepairRequestHeader(
            signature=bytes(64), sender=self.pubkey, recipient=recipient,
            timestamp=int(time.time() * 1000), nonce=nonce,
        )
        if kind == "window_index":
            payload = rw.WindowIndex(header, slot, shred_idx)
        elif kind == "highest_window_index":
            payload = rw.HighestWindowIndex(header, slot, shred_idx)
        else:
            payload = rw.Orphan(header, slot)
        self.sock.sendto(self._request(peer, kind, payload), peer)
        self.metrics["req"] += 1
        for _ in range(budget_spins):
            if spin is not None:
                spin()
            try:
                data, src = self.sock.recvfrom(2048)
            except (BlockingIOError, InterruptedError):
                continue
            res = rw.decode_response(data)
            if res is None or res[1] != nonce:
                # includes straggler replies to a timed-out earlier
                # attempt: the nonce check keeps them from satisfying
                # the current request with the wrong shred
                self.metrics["bad_response"] += 1
                continue
            shred = res[0]
            s = fs.parse(shred)
            if s is None or s.slot != slot or (
                kind == "window_index" and s.idx != shred_idx
            ):
                self.metrics["bad_response"] += 1
                continue
            self.metrics["ok"] += 1
            self.last_peer = src
            return shred
        self.metrics["timeout"] += 1
        return None

    def request(
        self, peer, slot: int, shred_idx: int, *, spin=None,
        max_spins=200_000, recipient: bytes = bytes(32), kind="window_index",
        retries: int = 0, backoff: float = 2.0,
    ) -> bytes | None:
        """Request/response round trip(s); None when every attempt timed
        out or produced only bad replies.

        `peer` is one (host, port) address or a LIST of entries, each an
        address or an (address, recipient_pubkey) pair (signing servers
        refuse misdirected requests, so the recipient must rotate with
        the peer).  The wait budget is `max_spins` for the first attempt
        and grows by `backoff`x per retry (+- up to 25% seeded jitter,
        so a fleet of catching-up validators does not re-ask a
        struggling server in lockstep); each retry ROTATES to the next
        peer in the list, so one dead repair peer costs one timeout
        window, not the whole catch-up.  Spin counts (not wall time) are
        the clock: the caller pumps the serving side via `spin`, which
        keeps runs seeded-deterministic."""
        peers = peer if isinstance(peer, list) else [peer]
        budget = max_spins
        for attempt in range(retries + 1):
            target = peers[attempt % len(peers)]
            if isinstance(target[0], str):
                t_addr, t_recipient = target, recipient
            else:
                t_addr, t_recipient = target
            if attempt:
                self.metrics["retry"] += 1
                if len(peers) > 1:
                    self.metrics["peer_rotated"] += 1
            got = self._attempt(t_addr, slot, shred_idx, spin=spin,
                                budget_spins=int(budget),
                                recipient=t_recipient, kind=kind)
            if got is not None:
                return got
            # exponential backoff with seeded jitter: 75%..125% of the
            # scaled window
            budget = budget * backoff * (0.75 + 0.5 * self._rng.float01())
        return None

    def close(self):
        self.sock.close()
