"""TPU stream reassembly: QUIC stream fragments -> whole transactions.

Counterpart of /root/reference/src/disco/quic/fd_tpu.h (fd_tpu_reasm_t):
the buffer between a stream transport and the verify stage.  A fixed pool
of reassembly slots accumulates per-stream fragments; a stream's slot
publishes one whole txn when the stream FINishes, and the pool reclaims
the least-recently-active slot under pressure (peers that open streams
and stall must not pin memory — the reference's slot-stealing rule).
Oversized streams (> TXN_MTU) cancel immediately.

The transport (QUIC when it lands; any stream framing today) calls:
    append(stream_key, data, fin) -> None | completed txn bytes
"""

from __future__ import annotations

from collections import OrderedDict

from firedancer_tpu.protocol.txn import TXN_MTU


class TpuReasm:
    _DEAD = None  # tombstone slot value: stream poisoned until FIN/reset

    def __init__(self, depth: int = 64, mtu: int = TXN_MTU):
        if depth <= 0:
            raise ValueError("depth must be positive")
        self.depth = depth
        self.mtu = mtu
        self._slots: OrderedDict[object, bytearray | None] = OrderedDict()
        self.metrics = {
            "published": 0,
            "oversz": 0,
            "evicted": 0,
            "cancelled": 0,
        }

    def append(self, key, data: bytes, fin: bool = False) -> bytes | None:
        """Accumulate stream bytes; returns the whole txn at FIN."""
        if key in self._slots:
            slot = self._slots[key]
            self._slots.move_to_end(key)
            if slot is self._DEAD:
                # poisoned (oversize) stream: swallow its continuation
                # frames so it can't churn fresh slots / evict honest
                # streams; the tombstone clears at FIN or reset
                if fin:
                    del self._slots[key]
                return None
        else:
            if len(self._slots) >= self.depth:
                # steal the least-recently-active slot (its stream stalls
                # out and will be dropped; QUIC-level retransmit recovers)
                self._slots.popitem(last=False)
                self.metrics["evicted"] += 1
            slot = bytearray()
            self._slots[key] = slot
        slot += data
        if len(slot) > self.mtu:
            self.metrics["oversz"] += 1
            if fin:  # stream ended at the crossing: nothing to poison
                del self._slots[key]
            else:  # poison the KEY so continuation frames can't churn
                # fresh slots and evict honest streams
                self._slots[key] = self._DEAD
            return None
        if not fin:
            return None
        del self._slots[key]
        self.metrics["published"] += 1
        return bytes(slot)

    def cancel(self, key) -> bool:
        """Transport-level stream reset: drop the slot (or tombstone)."""
        if key in self._slots:
            del self._slots[key]
            self.metrics["cancelled"] += 1
            return True
        return False

    def active(self) -> int:
        return len(self._slots)
