"""ctypes binding for the native bank stage client (native/fd_bank.cpp).

The bank stage's sweep-harness lane (ISSUE 16): fdb_frag_cb runs the
whole per-microblock hot path — frame parse, fd_exec_batch2 session
exec, PoH-mixin entry build, credit-gated entry + done publish — inside
one `fdr_sweep` crossing, with zero Python per frag on the eligible
path.  The C side talks to the OTHER native modules through function
pointers (fd_exec_native.so's fd_exec_batch2, fd_ring.so's
fdr_try_publish/fdr_refresh_credits — the fd_reedsol precedent), so the
runtime and ring protocols each keep exactly one native implementation.

Python's half is the RESULT LOG: every microblock the C side touches
appends a group — its committed execution records (funk is still the
authoritative store, so writes must land there) plus, for punts and
backpressure, the raw frame for in-order Python-lane resume.
BankStage.before_credit drains it via `take_log`/`parse_log`, applies
state through SlotExecution.native_apply_rec, resumes stashes, re-syncs
the session, and `clear_log` un-freezes the lane.

`FDTPU_NATIVE_BANK=0` disables the lane; a missing toolchain degrades
to the Python bank path via NativeUnavailable.
"""

from __future__ import annotations

import ctypes
import os
import struct

import numpy as np

from firedancer_tpu.utils.nativebuild import NativeUnavailable, build_so

_SRC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
    "fd_bank.cpp",
)
_SO = os.path.join(os.path.dirname(_SRC), "fd_bank.so")

ENV_SWITCH = "FDTPU_NATIVE_BANK"

_lib = None


def _load():
    global _lib
    if _lib is None:
        lib = ctypes.CDLL(build_so(_SRC, _SO))
        u64 = ctypes.c_uint64
        vp = ctypes.c_void_p
        cp = ctypes.c_char_p
        lib.fdb_stage_new.argtypes = [
            vp, vp, vp, vp, vp, vp, vp, vp, u64, cp, u64,
        ]
        lib.fdb_stage_new.restype = vp
        lib.fdb_stage_delete.argtypes = [vp]
        lib.fdb_stage_flags_off.restype = u64
        lib.fdb_stage_set_hdr.argtypes = [vp, cp, u64]
        lib.fdb_stage_set_hdr.restype = ctypes.c_int
        lib.fdb_stage_set_funk.argtypes = [vp, vp, vp, vp, cp, u64]
        lib.fdb_stage_set_funk.restype = ctypes.c_int
        lib.fdb_stage_set_metrics.argtypes = [vp, vp]
        lib.fdb_log_ptr.argtypes = [vp]
        lib.fdb_log_ptr.restype = vp
        lib.fdb_log_clear.argtypes = [vp]
        # fdb_frag_cb is resolved by ADDRESS for fdr_sweep, never called
        # from Python
        lib.fdb_frag_cb.restype = ctypes.c_int
        _lib = lib
    return _lib


def enabled() -> bool:
    """The env switch: FDTPU_NATIVE_BANK=0 forces the Python lane."""
    return os.environ.get(ENV_SWITCH, "1") != "0"


def available() -> bool:
    """enabled AND the .so loads (builds on demand; toolchain-less hosts
    degrade gracefully to the Python bank path)."""
    if not enabled():
        return False
    try:
        _load()
        return True
    except (NativeUnavailable, OSError, AttributeError):
        return False


def make_hdr(batch_ctx, *, gated: bool) -> bytes:
    """The FDX2 prefix the C side stamps into every request: the
    BatchContext env blob (lps, clock, slot hashes, recent blockhash,
    rent) + the steady-state gate section (flag 2 = keep the session's
    valid set, zero seen/refresh records — deltas ride the Python-side
    sync crossings instead)."""
    flag = 2 if gated else 0
    return bytes(batch_ctx._fixed) + struct.pack("<BIII", flag, 0, 0, 0)


# BankStageCtx flag+counter tail, in declaration order after log_sz; the
# offset comes from the C side (fdb_stage_flags_off) so the zero-FFI
# view can never drift from the struct layout
_COUNTERS = ("bank_mb_seen", "bank_mb_native", "bank_mb_stashed",
             "bank_txn_native", "bank_credit_waits", "bank_mb_dropped",
             "bank_funk_writes", "bank_funk_falls")

_GROUP_HEAD = struct.Struct("<QQQIBI")
_REC_HEAD = struct.Struct("<bQB")  # status | fee | n_writes


def parse_log(log: bytes) -> list:
    """Decode a drained result log into groups of
    (mb_seq, tsorig, lat_ns, n_done, published, recs, mb_raw) where
    recs = [(status, fee, [(acct_idx, value)])] — the fd_exec_batch2
    response records verbatim (writes is an empty tuple for stripped
    records), and mb_raw is the original microblock frame
    (runtime/bank.parse_microblock format)."""
    groups = []
    off = 0
    n = len(log)
    while off < n:
        mb_seq, tsorig, lat_ns, n_done, published, mb_sz = \
            _GROUP_HEAD.unpack_from(log, off)
        off += _GROUP_HEAD.size
        recs = []
        rec_unpack = _REC_HEAD.unpack_from
        for _ in range(n_done):
            status, fee, n_w = rec_unpack(log, off)
            off += 10
            if n_w:
                writes = []
                for _ in range(n_w):
                    idx = log[off]
                    vlen = int.from_bytes(log[off + 1:off + 5], "little")
                    off += 5
                    writes.append((idx, log[off:off + vlen]))
                    off += vlen
            else:
                # the native funk lane strips every record: share one
                # empty tuple instead of allocating a list per txn
                writes = ()
            recs.append((status, fee, writes))
        groups.append((mb_seq, tsorig, lat_ns, n_done, published,
                       recs, log[off:off + mb_sz]))
        off += mb_sz
    return groups


class StageClient:
    """The bank stage's sweep-harness client.  Constructed by BankStage
    when the lane is armed (exec session live AND both out producers
    native); exposes the fdr_sweep callback address, the result-log
    drain, and cheap struct reads for the stall flag + counters."""

    def __init__(self, session, hdr: bytes, ent_producer, done_producer,
                 *, bank_idx: int):
        from firedancer_tpu.flamenco import exec_native as fx
        from firedancer_tpu.tango import native as fn

        lib = _load()
        ring = fn._load()
        xlib = fx._load()
        self._lib = lib
        self._session = session          # keep the exec session alive
        self._ent_prod = ent_producer    # keep the NativeProducers alive
        self._done_prod = done_producer
        self._h = lib.fdb_stage_new(
            ctypes.c_void_p(session._h),
            ctypes.cast(xlib.fd_exec_batch2, ctypes.c_void_p),
            ctypes.cast(ent_producer._lsp, ctypes.c_void_p),
            ctypes.cast(ent_producer._pp, ctypes.c_void_p),
            ctypes.cast(done_producer._lsp, ctypes.c_void_p),
            ctypes.cast(done_producer._pp, ctypes.c_void_p),
            ctypes.cast(ring.fdr_try_publish, ctypes.c_void_p),
            ctypes.cast(ring.fdr_refresh_credits, ctypes.c_void_p),
            bank_idx, hdr, len(hdr),
        )
        if not self._h:
            raise NativeUnavailable("fdb_stage_new failed")
        self.cb = ctypes.cast(lib.fdb_frag_cb, ctypes.c_void_p)
        self.cb_ctx = ctypes.c_void_p(self._h)
        # zero-FFI reads: a u64 view over the ctx struct's flags+counters
        n_tail = 2 + len(_COUNTERS)
        self._tail = np.frombuffer(
            (ctypes.c_uint64 * n_tail).from_address(
                self._h + int(lib.fdb_stage_flags_off())
            ),
            dtype=np.uint64,
        )

    @property
    def log_sz(self) -> int:
        return int(self._tail[0])

    @property
    def stash_pending(self) -> bool:
        return bool(self._tail[1])

    def counters(self) -> dict[str, int]:
        return {name: int(self._tail[2 + i])
                for i, name in enumerate(_COUNTERS)}

    def set_hdr(self, hdr: bytes) -> None:
        """Re-stamp the env/gate prefix (slot roll: new clock + recent
        blockhash arm a fresh request header)."""
        if not self._lib.fdb_stage_set_hdr(self._h, hdr, len(hdr)):
            raise NativeUnavailable("fdb_stage_set_hdr failed")

    def set_funk(self, funk, xid: bytes | None) -> None:
        """Arm (or disarm: funk/xid None) the native funk plane: the C
        side writes committed records slot-direct into `funk`'s shm map
        and strips write payloads from the result log.  Called alongside
        set_hdr at every slot roll — the xid is the slot's funk fork."""
        if funk is None or xid is None:
            rc = self._lib.fdb_stage_set_funk(self._h, None, None, None,
                                              None, 0)
        else:
            from firedancer_tpu.funk import funk_native as fk

            flib = fk._load()
            rc = self._lib.fdb_stage_set_funk(
                self._h, ctypes.c_void_p(funk._h),
                ctypes.cast(flib.ffk_txn_slot, ctypes.c_void_p),
                ctypes.cast(flib.ffk_rec_insert_slot, ctypes.c_void_p),
                xid, len(xid),
            )
        if rc == 0:
            raise NativeUnavailable("fdb_stage_set_funk failed")

    def set_metrics(self, plane) -> None:
        """Arm the shm metrics plane (ISSUE 20): apply/publish brackets
        inside fdb_frag_cb accumulate into the SAME fdm_plane the sweep
        harness hands fdr_sweep, and per-txn commit latency observes
        into the stage's nbank_txn_lat_ns histogram in-crossing."""
        self._plane = plane  # keepalive: C holds the raw pointer
        self._lib.fdb_stage_set_metrics(
            self._h, plane.ptr if plane is not None else None)

    def take_log(self) -> bytes:
        """Copy out the pending result log (empty bytes when idle).
        Does NOT clear: call clear_log after the drain is fully applied
        — clearing is what un-freezes the native path."""
        sz = int(self._tail[0])
        if not sz:
            return b""
        return ctypes.string_at(self._lib.fdb_log_ptr(self._h), sz)

    def clear_log(self) -> None:
        self._lib.fdb_log_clear(self._h)

    def close(self) -> None:
        if self._h:
            self._tail = None
            self._lib.fdb_stage_delete(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
