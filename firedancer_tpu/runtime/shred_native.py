"""ctypes binding for the native shredder (native/fd_shred.cpp).

The shred stage's compute path in ONE FFI crossing per entry batch:
data-shred framing, GF(2^8) parity (the C++ side calls back into the
existing native/fd_reedsol.so kernel through a function pointer — one
native GF implementation), the SHA-256 merkle tree, and fixed-base-comb
ed25519 signing of the untruncated root.  Byte parity with
runtime/shredder.Shredder is the contract (tests/test_shred_native.py).

Two surfaces:

  - `NativeShredder`: a drop-in for Shredder — same
    `entry_batch_to_fec_sets` signature and FecSet results, so any
    Shredder consumer (tests, the keep_sets stage mode) can ride the
    lane without caring;
  - `StageClient`: the sweep-harness client (runtime/stage.py fdr_sweep)
    — owns the C-side entry accumulator + publish path so a full shred
    stage sweep executes with zero Python per frag.

`FDTPU_NATIVE_SHRED=0` disables the lane; a missing toolchain (or a
missing fd_reedsol.so — the parity kernel is a hard dependency of this
lane) degrades to the Python shredder via NativeUnavailable.  The
signer's expanded key (clamped scalar, prefix, compressed pubkey) comes
from ed25519_ref's key cache; the raw secret never crosses the FFI.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from firedancer_tpu.ops.ref import ed25519_ref as ref
from firedancer_tpu.utils.nativebuild import NativeUnavailable, build_so

from .shredder import FecSet, count_fec_sets

_SRC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
    "fd_shred.cpp",
)
_SO = os.path.join(os.path.dirname(_SRC), "fd_shred.so")

ENV_SWITCH = "FDTPU_NATIVE_SHRED"

_MIN_SZ = 1203
_MAX_SZ = 1228
_MAX_D = 67

_lib = None


def _load():
    global _lib
    if _lib is None:
        lib = ctypes.CDLL(build_so(_SRC, _SO))
        u64 = ctypes.c_uint64
        p64 = ctypes.POINTER(u64)
        pi64 = ctypes.POINTER(ctypes.c_int64)
        vp = ctypes.c_void_p
        cp = ctypes.c_char_p
        lib.fds_ctx_new.argtypes = [ctypes.c_uint, cp, cp, cp, vp]
        lib.fds_ctx_new.restype = vp
        lib.fds_ctx_delete.argtypes = [vp]
        lib.fds_shred_batch.argtypes = [
            vp, cp, u64, u64, ctypes.c_uint, ctypes.c_uint, ctypes.c_int,
            pi64, vp, u64, p64, u64, vp,
        ]
        lib.fds_shred_batch.restype = ctypes.c_int64
        lib.fds_stage_new.argtypes = [
            vp, vp, vp, vp, vp, u64, ctypes.c_uint, ctypes.c_uint, u64, u64,
        ]
        lib.fds_stage_new.restype = vp
        lib.fds_stage_delete.argtypes = [vp]
        lib.fds_stage_flags_off.restype = u64
        lib.fds_stage_set_slot.argtypes = [vp, u64]
        lib.fds_stage_set_metrics.argtypes = [vp, vp]
        lib.fds_stage_append.argtypes = [vp, cp, u64, u64]
        lib.fds_stage_flush.argtypes = [vp, ctypes.c_int]
        lib.fds_stage_flush.restype = ctypes.c_int
        # fds_frag_cb is resolved by ADDRESS for fdr_sweep, never called
        # from Python
        lib.fds_frag_cb.restype = ctypes.c_int
        _lib = lib
    return _lib


def enabled() -> bool:
    """The env switch: FDTPU_NATIVE_SHRED=0 forces the Python lane."""
    return os.environ.get(ENV_SWITCH, "1") != "0"


def _reedsol_fn():
    """Address of fd_reedsol_encode — the parity kernel this lane calls
    through a function pointer (the fd_pack/fd_tcache precedent)."""
    from firedancer_tpu.ops import reedsol

    lib = reedsol._host_lib()
    if lib is None:
        raise NativeUnavailable("native shredder needs fd_reedsol.so")
    return ctypes.cast(lib.fd_reedsol_encode, ctypes.c_void_p)


def available() -> bool:
    """enabled AND both .so's load (builds on demand; toolchain-less
    hosts degrade gracefully to the Python shredder)."""
    if not enabled():
        return False
    try:
        _load()
        _reedsol_fn()
        return True
    except (NativeUnavailable, OSError, AttributeError):
        return False


class _Ctx:
    """One signer's native shredder context (comb key + gen cache)."""

    def __init__(self, secret: bytes, shred_version: int):
        lib = _load()
        a, prefix, apk = ref._expanded(secret)
        self._lib = lib
        self._h = lib.fds_ctx_new(
            shred_version, a.to_bytes(32, "little"), prefix, apk,
            _reedsol_fn(),
        )
        if not self._h:
            raise NativeUnavailable("fds_ctx_new failed")

    def close(self) -> None:
        if self._h:
            self._lib.fds_ctx_delete(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativeShredder:
    """Drop-in for runtime/shredder.Shredder: one FFI crossing shreds a
    whole entry batch into wire-complete signed FEC sets.  Construct
    with the SECRET (not a signer callable) — the comb signing path
    needs the expanded key on the C++ side."""

    def __init__(self, *, secret: bytes, shred_version: int = 0):
        self._ctx = _Ctx(secret, shred_version)
        self.shred_version = shred_version
        self.slot = -1
        self.data_idx_offset = 0
        self.parity_idx_offset = 0
        self._idx = (ctypes.c_int64 * 2)()
        # reusable out arena + per-set meta/roots, grown on demand
        self._cap = 1 << 20
        self._out = ctypes.create_string_buffer(self._cap)
        self._meta = np.zeros((256, 4), dtype=np.uint64)
        self._roots = ctypes.create_string_buffer(32 * 256)

    def entry_batch_to_fec_sets(self, entry_batch: bytes, *, slot: int,
                                meta=None) -> list[FecSet]:
        from .shredder import EntryBatchMeta

        if not entry_batch:
            raise ValueError("empty entry batch")
        meta = meta or EntryBatchMeta()
        if slot != self.slot:
            self.data_idx_offset = 0
            self.parity_idx_offset = 0
            self.slot = slot
        n_sets = count_fec_sets(len(entry_batch)) + 1
        need = n_sets * _MAX_D * (_MIN_SZ + _MAX_SZ)
        if need > self._cap:
            self._cap = need
            self._out = ctypes.create_string_buffer(self._cap)
        if n_sets > self._meta.shape[0]:
            # no batch-size ceiling: the Python lane shreds any batch,
            # so the meta/roots tables grow with the plan bound
            self._meta = np.zeros((n_sets, 4), dtype=np.uint64)
            self._roots = ctypes.create_string_buffer(32 * n_sets)
        self._idx[0] = self.data_idx_offset
        self._idx[1] = self.parity_idx_offset
        lib = self._ctx._lib
        n = lib.fds_shred_batch(
            self._ctx._h, entry_batch, len(entry_batch), slot,
            meta.parent_offset, meta.reference_tick,
            1 if meta.block_complete else 0, self._idx,
            ctypes.cast(self._out, ctypes.c_void_p), self._cap,
            self._meta.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            self._meta.shape[0],
            ctypes.cast(self._roots, ctypes.c_void_p),
        )
        if n < 0:
            raise NativeUnavailable("fds_shred_batch failed (capacity)")
        self.data_idx_offset = int(self._idx[0])
        self.parity_idx_offset = int(self._idx[1])
        if n:
            # copy only the produced bytes (.raw would copy the whole
            # preallocated arena per batch)
            d_l, p_l, _, off_l = (int(x) for x in self._meta[n - 1])
            total = off_l + d_l * _MIN_SZ + p_l * _MAX_SZ
            raw = ctypes.string_at(self._out, total)
        else:
            raw = b""
        roots = ctypes.string_at(self._roots, 32 * n)
        sets: list[FecSet] = []
        for s in range(n):
            d, p, fec_idx, off = (int(x) for x in self._meta[s])
            data = [raw[off + i * _MIN_SZ: off + (i + 1) * _MIN_SZ]
                    for i in range(d)]
            cbase = off + d * _MIN_SZ
            parity = [raw[cbase + j * _MAX_SZ: cbase + (j + 1) * _MAX_SZ]
                      for j in range(p)]
            sets.append(FecSet(
                data_shreds=data,
                parity_shreds=parity,
                merkle_root=roots[32 * s: 32 * s + 32],
                slot=slot,
                fec_set_idx=fec_idx,
            ))
        return sets

    def close(self) -> None:
        self._ctx.close()


# ShredStageCtx counter tail, in declaration order after pending_flush;
# the flag's byte offset comes from the C side (fds_stage_flags_off) so
# the zero-FFI view can never drift from the struct layout
_COUNTERS = ("entries_in", "entry_batches", "fec_sets",
             "data_shreds_out", "parity_shreds_out", "frags_out",
             "backpressure", "batches_dropped")


class StageClient:
    """The shred stage's sweep-harness client: a C-side entry
    accumulator + batch-close + shred + publish path.  Constructed by
    ShredStage when the lane is armed (native shredder available AND the
    out producer is native); exposes the fdr_sweep callback address and
    cheap struct reads for the deferred-flush flag + counters."""

    def __init__(self, shredder_ctx: _Ctx, out_producer, *, slot: int,
                 parent_off: int = 1, ref_tick: int = 0,
                 batch_target: int = 16384, min_credits: int = 256):
        from firedancer_tpu.tango import native as fn

        lib = _load()
        ring = fn._load()
        self._lib = lib
        self._ctx = shredder_ctx  # keep the ShredCtx alive
        self._prod = out_producer  # keep the NativeProducer alive
        self._h = lib.fds_stage_new(
            shredder_ctx._h,
            ctypes.cast(out_producer._lsp, ctypes.c_void_p),
            ctypes.cast(out_producer._pp, ctypes.c_void_p),
            ctypes.cast(ring.fdr_try_publish, ctypes.c_void_p),
            ctypes.cast(ring.fdr_refresh_credits, ctypes.c_void_p),
            slot, parent_off, ref_tick, batch_target, min_credits,
        )
        if not self._h:
            raise NativeUnavailable("fds_stage_new failed")
        self.cb = ctypes.cast(lib.fds_frag_cb, ctypes.c_void_p)
        self.cb_ctx = ctypes.c_void_p(self._h)
        # zero-FFI reads: a u64 view over the ctx struct's flag+counters
        n_tail = 1 + len(_COUNTERS)
        self._tail = np.frombuffer(
            (ctypes.c_uint64 * n_tail).from_address(
                self._h + int(lib.fds_stage_flags_off())
            ),
            dtype=np.uint64,
        )

    @property
    def pending_flush(self) -> bool:
        return bool(self._tail[0])

    def counters(self) -> dict[str, int]:
        return {name: int(self._tail[1 + i])
                for i, name in enumerate(_COUNTERS)}

    def append(self, payload: bytes, tsorig: int) -> None:
        """Per-frag fallback (mixed-lane / lossy splice): forward into
        the SAME C-side buffer the sweep callback fills."""
        self._lib.fds_stage_append(self._h, payload, len(payload), tsorig)

    def flush(self, *, block_complete: bool) -> bool:
        return bool(self._lib.fds_stage_flush(
            self._h, 1 if block_complete else 0
        ))

    def retry_flush(self) -> bool:
        """Retry a credit-deferred flush with its ORIGINAL
        block_complete flag (the C side recorded it)."""
        return bool(self._lib.fds_stage_flush(self._h, -1))

    def set_slot(self, slot: int) -> None:
        self._lib.fds_stage_set_slot(self._h, slot)

    def set_metrics(self, plane) -> None:
        """Arm the shm metrics plane (ISSUE 20): shred/encode bursts
        and the wire loop attribute apply/publish phases in-crossing."""
        self._plane = plane  # keepalive: C holds the raw pointer
        self._lib.fds_stage_set_metrics(
            self._h, plane.ptr if plane is not None else None)

    def close(self) -> None:
        if self._h:
            self._tail = None
            self._lib.fds_stage_delete(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
