"""Sign stage + keyguard: the only holder of the validator private key.

Mirrors the reference's sign tile and keyguard broker
(/root/reference/src/app/fdctl/run/tiles/fd_sign.c,
src/disco/keyguard/fd_keyguard.h): every component that needs a
signature (shred merkle roots, gossip messages, votes, QUIC TLS
handshakes, repair requests) talks to ONE stage over a dedicated
request/response link pair; the private key never leaves this stage's
process.  Each request link is bound to a ROLE at topology-build time,
and the keyguard refuses payloads that don't match the role's shape —
a compromised shred stage cannot exfiltrate vote signatures
(fd_keyguard_payload_authorize).

Request frame: the raw payload to sign.  Response frame: the 64-byte
ed25519 signature.  Link MTUs mirror the reference's tiny sign links
(fd_frankendancer.c:78-82).
"""

from __future__ import annotations

from firedancer_tpu.ops.ref import ed25519_ref as ref
from .stage import Stage

ROLE_VOTER = 0
ROLE_GOSSIP = 1
ROLE_LEADER = 2  # block producer: signs 32-byte shred merkle roots
ROLE_QUIC = 3
ROLE_REPAIR = 4

MAX_REQ_SZ = 1232


def payload_authorize(role: int, payload: bytes) -> bool:
    """Role-gated payload acceptance (fd_keyguard_payload_authorize's
    shape rules, conservatively tightened for implemented roles)."""
    n = len(payload)
    if n == 0 or n > MAX_REQ_SZ:
        return False
    if role == ROLE_LEADER:
        return n == 32  # merkle roots only
    if role == ROLE_GOSSIP:
        # gossip signable payloads are small CRDS-ish blobs, never txn-like
        return n <= 256 and not payload[:1] == b"\x01"
    if role == ROLE_QUIC:
        return n == 130  # TLS-1.3 CertificateVerify transcript shape
    if role == ROLE_REPAIR:
        return n <= 160
    if role == ROLE_VOTER:
        return n <= MAX_REQ_SZ
    return False


class SignStage(Stage):
    """ins[i] = request link for role roles[i]; outs[i] = response link."""

    def __init__(self, *args, secret: bytes, roles: list[int], **kwargs):
        super().__init__(*args, **kwargs)
        if len(roles) != len(self.ins) or len(roles) != len(self.outs):
            raise ValueError("one role per request/response link pair")
        self._secret = secret
        self.public_key = ref.public_key(secret)
        self.roles = roles
        self.require_credit = True

    def after_frag(self, in_idx: int, meta, payload: bytes) -> None:
        if not payload_authorize(self.roles[in_idx], payload):
            self.metrics.inc("refused")
            return
        sig = ref.sign(self._secret, payload)
        self.publish(in_idx, sig, sig=int(meta[1]))
        self.metrics.inc("signed")


class KeyguardClient:
    """Blocking request/response helper over a sign link pair
    (fd_keyguard_client_sign).  `spin` is called while waiting so the
    cooperative scheduler can keep the sign stage running; the process
    runner passes None and genuinely blocks on the ring."""

    def __init__(self, producer, consumer, *, spin=None, max_spins: int = 1_000_000):
        self.producer = producer
        self.consumer = consumer
        self.spin = spin
        self.max_spins = max_spins
        self._req_seq = 0

    def sign(self, payload: bytes) -> bytes:
        from firedancer_tpu.tango.rings import MCache

        self._req_seq += 1
        if not self.producer.try_publish(payload, sig=self._req_seq):
            raise RuntimeError("sign request ring full")
        for _ in range(self.max_spins):
            res = self.consumer.poll()
            if isinstance(res, tuple):
                meta, sig = res
                # correlate by the echoed request seq: a stale response to
                # a timed-out earlier request must not answer THIS one (it
                # would sign the wrong payload forever after)
                if int(meta[MCache.COL_SIG]) != self._req_seq:
                    continue
                if len(sig) != 64:
                    raise RuntimeError("malformed sign response")
                return sig
            if self.spin is not None:
                self.spin()
        raise TimeoutError("sign stage did not respond")
