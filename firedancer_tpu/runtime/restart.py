"""Per-stage restart policy for the self-healing supervisor.

The reference's disco supervision model distinguishes a tile that died
once (respawn it in place — its workspace rings are intact) from a tile
that crash-loops (take the topology down and leave the evidence).  This
module is the policy half: bounded attempts with exponential backoff and
SEEDED jitter — the schedule for a given (seed, stage) is byte-identical
across runs (utils/rng, the RepairClient retry discipline), so chaos
scenarios that exercise restarts stay deterministic per seed.

The mechanism half lives in runtime/topo.TopologyHandle.supervise
(respawn + ring reattach) and runtime/stage.Stage.resume_from_rings
(cursor recovery + the exactly-once publish guard).
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass

from firedancer_tpu.utils.rng import Rng


@dataclass(frozen=True)
class RestartPolicy:
    """Bounded in-place restarts with deterministic backoff.

    attempt k (1-based) waits `backoff_base_s * backoff_mult**(k-1)`
    scaled by a seeded jitter in [1, 1 + jitter_frac) — jitter breaks
    thundering-herd respawns when several stages share a policy, and
    seeding it keeps same-seed runs byte-identical.  Past `max_restarts`
    the supervisor falls back to today's fail-fast + flight dump."""

    max_restarts: int = 3
    backoff_base_s: float = 0.05
    backoff_mult: float = 2.0
    jitter_frac: float = 0.5
    seed: int = 0

    def delay_s(self, stage: str, attempt: int) -> float:
        """Backoff before restart `attempt` (1-based) of `stage` —
        deterministic per (seed, stage, attempt)."""
        if attempt < 1:
            raise ValueError("attempt is 1-based")
        base = self.backoff_base_s * self.backoff_mult ** (attempt - 1)
        # one Rng per (stage, attempt): the schedule must not depend on
        # HOW MANY draws other stages made before this one
        r = Rng(self.seed, zlib.crc32(stage.encode()) ^ (attempt << 32))
        return base * (1.0 + self.jitter_frac * r.float01())

    def schedule(self, stage: str) -> list[float]:
        """The stage's full deterministic backoff schedule, in seconds."""
        return [self.delay_s(stage, a)
                for a in range(1, self.max_restarts + 1)]


def policy_for(restart, stage: str) -> RestartPolicy | None:
    """Resolve supervise(restart=...)'s argument: a single policy applies
    to every stage, a dict maps stage names (missing names -> no
    restart), None disables in-place restart entirely."""
    if restart is None:
        return None
    if isinstance(restart, RestartPolicy):
        return restart
    return restart.get(stage)
