"""Synthetic transaction generator stage (the reference's benchg tile:
src/app/fddev/tiles/fd_benchg.c) and the synthetic-load harness
(src/disco/verify/verify_synth_load.c).

Signing in pure python is slow (~15 ms/txn), so a pool of unique signed
transfer txns is pregenerated once and streamed in a cycle.  For dedup
realism every txn in the pool is unique (distinct lamports); cycling the
pool re-sends duplicates, which is exactly what the dedup stage is for —
size the pool >= the txns you intend to count as distinct.
"""

from __future__ import annotations

import hashlib

from firedancer_tpu.protocol import txn as ft
from .stage import Stage


def pool_payers(seed: bytes = b"benchg", n_payers: int = 8) -> list[tuple[bytes, bytes]]:
    """The pool's payer keypairs [(secret, pubkey)] — deterministic from
    the seed so a bank ctx can pre-fund them (genesis for the synthetic
    load)."""
    from firedancer_tpu.ops.ref import ed25519_ref as ref

    payers = []
    for k in range(n_payers):
        secret = hashlib.sha256(seed + b"payer%d" % k).digest()
        payers.append((secret, ref.public_key(secret)))
    return payers


def pool_blockhash(seed: bytes = b"benchg") -> bytes:
    return hashlib.sha256(seed + b"bh").digest()


def gen_transfer_pool(
    n: int, seed: bytes = b"benchg", n_payers: int = 8, n_dests: int = 64
) -> list[bytes]:
    """Pool of signed transfers rotating over `n_payers` payer keypairs and
    `n_dests` destinations (fd_benchg.c rotates accounts the same way so
    pack sees schedulable parallelism, not one serializing hot account)."""
    n_payers = max(1, min(n_payers, n))
    payers = pool_payers(seed, n_payers)
    blockhash = pool_blockhash(seed)
    return [
        ft.transfer_txn(
            payers[i % n_payers][0],
            hashlib.sha256(seed + b"to%d" % (i % n_dests)).digest(),
            1 + i,
            blockhash,
            from_pubkey=payers[i % n_payers][1],
        )
        for i in range(n)
    ]


class BenchGStage(Stage):
    """Streams a pregenerated txn pool round-robin at max rate."""

    def __init__(self, pool: list[bytes], *args, limit: int | None = None, **kwargs):
        super().__init__(*args, **kwargs)
        self.pool = pool
        self.limit = limit
        self._i = 0
        self._pool_ref = None  # strong ref: the pool the native form mirrors
        self._pool_buf = b""
        self._pool_tbl = None

    def _native_pool(self):
        """The pool in fdr_publish_pool form (joined buffer + (off, sz)
        rows), rebuilt only when self.pool is swapped — so the sweep's
        crossing carries zero per-frame Python work.  The cache holds a
        strong reference (identity check, not id(): a freed list's id is
        routinely reused by the replacement).  Payload sizes validate
        against the link mtu here, once per pool — fdr_publish_pool
        itself trusts the table (no per-frame bound check in C++)."""
        if self._pool_ref is not self.pool:
            import numpy as np

            if not self.pool:
                # the Python lane raises ZeroDivisionError at
                # `pool[i % 0]`; an empty table handed to C++ would be a
                # process-killing SIGFPE at `% pool_n` instead
                raise ValueError("BenchGStage pool is empty")
            mtu = self.outs[0].link.mtu
            tbl = np.empty((len(self.pool), 2), dtype=np.uint64)
            off = 0
            for k, payload in enumerate(self.pool):
                if len(payload) > mtu:
                    raise ValueError(
                        f"pool payload {k} ({len(payload)}B) exceeds link"
                        f" mtu {mtu}"
                    )
                tbl[k, 0] = off
                tbl[k, 1] = len(payload)
                off += len(payload)
            self._pool_buf = b"".join(self.pool)
            self._pool_tbl = tbl
            self._pool_ref = self.pool
        return self._pool_buf, self._pool_tbl

    def after_credit(self) -> None:
        # burst-publish: one txn per sweep starves the burst-draining
        # consumers downstream (stage.py run_once)
        n = max(1, self.burst)
        if self.limit is not None:
            n = min(n, self.limit - self._i)
        if n <= 0:
            return
        p = self.outs[0]
        pub_pool = getattr(p, "publish_pool", None)
        if pub_pool is None:
            for _ in range(n):
                if not self.publish(0, self.pool[self._i % len(self.pool)],
                                    sig=self._i):
                    return
                self._i += 1
                self.metrics.inc("txn_gen")
            return
        # native ring lane: the whole sweep's frames in ONE crossing
        # (tsorig stamped in C++ — this stage is the stream's origin)
        buf, tbl = self._native_pool()
        if self.ring_clock:
            import time as _time

            t0 = _time.perf_counter()
            done = pub_pool(buf, tbl, len(self.pool), self._i, n)
            self.ring_publish_s += _time.perf_counter() - t0
        else:
            done = pub_pool(buf, tbl, len(self.pool), self._i, n)
        self._i += done
        if done:
            self.metrics.inc("txn_gen", done)
            self.metrics.inc("frags_out", done)
        if done < n:
            self.metrics.inc("backpressure")
