"""Synthetic transaction generator stage (the reference's benchg tile:
src/app/fddev/tiles/fd_benchg.c) and the synthetic-load harness
(src/disco/verify/verify_synth_load.c).

Signing in pure python is slow (~15 ms/txn), so a pool of unique signed
transfer txns is pregenerated once and streamed in a cycle.  For dedup
realism every txn in the pool is unique (distinct lamports); cycling the
pool re-sends duplicates, which is exactly what the dedup stage is for —
size the pool >= the txns you intend to count as distinct.
"""

from __future__ import annotations

import hashlib

from firedancer_tpu.protocol import txn as ft
from .stage import Stage


def pool_payers(seed: bytes = b"benchg", n_payers: int = 8) -> list[tuple[bytes, bytes]]:
    """The pool's payer keypairs [(secret, pubkey)] — deterministic from
    the seed so a bank ctx can pre-fund them (genesis for the synthetic
    load)."""
    from firedancer_tpu.ops.ref import ed25519_ref as ref

    payers = []
    for k in range(n_payers):
        secret = hashlib.sha256(seed + b"payer%d" % k).digest()
        payers.append((secret, ref.public_key(secret)))
    return payers


def pool_blockhash(seed: bytes = b"benchg") -> bytes:
    return hashlib.sha256(seed + b"bh").digest()


def gen_transfer_pool(
    n: int, seed: bytes = b"benchg", n_payers: int = 8, n_dests: int = 64
) -> list[bytes]:
    """Pool of signed transfers rotating over `n_payers` payer keypairs and
    `n_dests` destinations (fd_benchg.c rotates accounts the same way so
    pack sees schedulable parallelism, not one serializing hot account)."""
    n_payers = max(1, min(n_payers, n))
    payers = pool_payers(seed, n_payers)
    blockhash = pool_blockhash(seed)
    return [
        ft.transfer_txn(
            payers[i % n_payers][0],
            hashlib.sha256(seed + b"to%d" % (i % n_dests)).digest(),
            1 + i,
            blockhash,
            from_pubkey=payers[i % n_payers][1],
        )
        for i in range(n)
    ]


class BenchGStage(Stage):
    """Streams a pregenerated txn pool round-robin at max rate."""

    def __init__(self, pool: list[bytes], *args, limit: int | None = None, **kwargs):
        super().__init__(*args, **kwargs)
        self.pool = pool
        self.limit = limit
        self._i = 0

    def after_credit(self) -> None:
        # burst-publish: one txn per sweep starves the burst-draining
        # consumers downstream (stage.py run_once)
        for _ in range(max(1, self.burst)):
            if self.limit is not None and self._i >= self.limit:
                return
            if not self.publish(0, self.pool[self._i % len(self.pool)],
                                sig=self._i):
                return
            self._i += 1
            self.metrics.inc("txn_gen")
