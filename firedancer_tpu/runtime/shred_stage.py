"""Shred stage: entries -> entry batches -> FEC sets -> wire shreds.

Pipeline position mirrors the reference's shred tile
(/root/reference/src/app/fdctl/run/tiles/fd_shred.c): accumulate poh
entries into an entry batch, run the shredder (reedsol parity + merkle +
leader signature), and publish every data+parity shred to the outgoing
link (the net/turbine hop in a full validator; tests resolve them back
with the FEC resolver).

Inputs:  ins[0] = poh -> shred entries.
Outputs: outs[0] = wire shreds (mtu >= 1228).

Entry batches close when the accumulated serialized entries reach
`batch_target_sz` (the reference bounds batches by pending shred budget)
or on flush at slot end.

Native lanes (ISSUE 11), chosen at construction when `secret` is given:

  - sweep mode: with the native shredder built, a native out producer,
    and no keep_sets/plane requirement, the stage registers a
    shred_native.StageClient as its sweep-harness client — the ENTIRE
    run_once sweep (drain entries -> accumulate -> batch close -> shred
    -> publish) is one fdr_sweep crossing with zero Python per frag,
    the reference's mux-run-loop shape.  The Python callbacks below
    remain the fallback surface (mixed-lane/lossy splices) and forward
    into the SAME C-side batch buffer, so the lanes cannot diverge.
  - batch mode: keep_sets/plane-less topologies that stay on the Python
    frag path still shred through NativeShredder — one FFI crossing per
    entry batch, byte-identical sets.

`FDTPU_NATIVE_SHRED=0` (or a toolchain-less host) restores the pure
Python shredder end to end.
"""

from __future__ import annotations

from firedancer_tpu.tango.rings import MCache
from .poh_stage import PohStage
from .shredder import EntryBatchMeta, FecSet, Shredder
from .stage import Stage


class ShredStage(Stage):
    def __init__(
        self,
        *args,
        signer,
        secret: bytes | None = None,
        slot: int = 1,
        shred_version: int = 1,
        batch_target_sz: int = 16384,
        keep_sets: bool = False,
        plane=None,
        **kwargs,
    ):
        super().__init__(*args, **kwargs)
        self._slot = slot
        self.batch_target_sz = batch_target_sz
        self.keep_sets = keep_sets
        self.sets: list[FecSet] = []  # retained for tests/observers
        self._buf = bytearray()
        self._buf_tsorig = 0
        # -- lane selection ---------------------------------------------------
        # the mesh-sharded parity path (plane) is the Python shredder's;
        # keep_sets needs materialized FecSets, so sweep mode is out
        self.shredder = None
        self._sweep_client = None
        self.native_shred = False
        if secret is not None and plane is None:
            from . import shred_native as sd

            if sd.available():
                try:
                    nshred = sd.NativeShredder(secret=secret,
                                               shred_version=shred_version)
                    self.shredder = nshred
                    self.native_shred = True
                    if not keep_sets and self.outs and type(
                        self.outs[0]
                    ).__name__ == "NativeProducer":
                        self._sweep_client = sd.StageClient(
                            nshred._ctx, self.outs[0], slot=slot,
                            batch_target=batch_target_sz,
                        )
                except sd.NativeUnavailable:
                    self.shredder = None
                    self.native_shred = False
        if self.shredder is None:
            self.shredder = Shredder(signer=signer,
                                     shred_version=shred_version, plane=plane)

    # slot is a property so the sweep client's C-side state (and its
    # slot-scoped shred index reset) tracks reassignment exactly like
    # the Python Shredder's `if slot != self.slot` check does per batch
    @property
    def slot(self) -> int:
        return self._slot

    @slot.setter
    def slot(self, v: int) -> None:
        self._slot = v
        if self._sweep_client is not None:
            self._sweep_client.set_slot(v)

    def after_frag(self, in_idx: int, meta, payload: bytes) -> None:
        c = self._sweep_client
        if c is not None:
            # fallback surface (mixed-lane / lossy splice): forward into
            # the C-side buffer the sweep callback fills — one state
            c.append(payload, int(meta[MCache.COL_TSORIG]))
            return
        # entries are appended verbatim: the entry frame IS this build's
        # entry-batch serialization (the reference ships bincode entries)
        self._buf += len(payload).to_bytes(4, "little")
        self._buf += payload
        ts = int(meta[MCache.COL_TSORIG])
        if ts and (self._buf_tsorig == 0 or ts < self._buf_tsorig):
            self._buf_tsorig = ts
        self.metrics.inc("entries_in")
        if len(self._buf) >= self.batch_target_sz and self._room():
            self._shred_batch(block_complete=False)

    def after_credit(self) -> None:
        c = self._sweep_client
        if c is not None:
            # batch deferred for credits in C: retry with the flag the
            # deferred flush recorded (block_complete survives the wait)
            if c.pending_flush:
                c.retry_flush()
            return
        # batch closed for size but deferred for credits: retry here
        if len(self._buf) >= self.batch_target_sz and self._room():
            self._shred_batch(block_complete=False)

    def during_housekeeping(self) -> None:
        c = self._sweep_client
        if c is not None:
            # C-side counters are authoritative in sweep mode: copy the
            # absolute values into the schema metrics at the same lazy
            # cadence every other stage metric has
            self.metrics.counters.update(c.counters())

    def _room(self) -> bool:
        """A batch bursts ~2 sets x ~65 shreds; don't start shredding unless
        the out ring can absorb it (dropping shreds mid-set wastes the set)."""
        return not self.outs or self.outs[0].cr_avail >= 256

    def flush(self, *, block_complete: bool = True) -> None:
        c = self._sweep_client
        if c is not None:
            c.flush(block_complete=block_complete)
            self.metrics.counters.update(c.counters())
            return
        if self._buf:
            self._shred_batch(block_complete=block_complete)

    def _shred_batch(self, *, block_complete: bool) -> None:
        batch = bytes(self._buf)
        self._buf = bytearray()
        tsorig = self._buf_tsorig
        self._buf_tsorig = 0
        sets = self.shredder.entry_batch_to_fec_sets(
            batch,
            slot=self.slot,
            meta=EntryBatchMeta(block_complete=block_complete),
        )
        self.metrics.inc("entry_batches")
        for st in sets:
            self.metrics.inc("fec_sets")
            if self.keep_sets:
                self.sets.append(st)
            if self.outs:
                # a whole FEC set's shreds in one ring crossing on the
                # native lane (~65 frames; _room() pre-gated the credits)
                items = [(buf, st.fec_set_idx, tsorig)
                         for buf in st.data_shreds]
                items += [(buf, st.fec_set_idx, tsorig)
                          for buf in st.parity_shreds]
                self.publish_burst_out(0, items)
                self.metrics.inc("data_shreds_out", len(st.data_shreds))
                self.metrics.inc("parity_shreds_out", len(st.parity_shreds))


class FusedPohShredStage(PohStage):
    """Fused poh+shred crash domain (ISSUE 16): ONE stage owns both the
    hash clock and the shredder, collapsing the poh->shred ring hop —
    each bank microblock's entry goes mixin -> entry batch -> FEC set
    inside a single run_once sweep, and ticks append to the same batch
    buffer with no intermediate ring crossing.

    Composition, not reimplementation: the PoH half IS PohStage (every
    slot-clock seal/miss semantic from PR 14 inherited verbatim); the
    shred half IS a ShredStage whose intake is called in-process where
    the unfused topology would publish to the poh_shred link.  The
    shred half's native sweep buffer (fd_shred.cpp stage_append closes
    batches at target size in C) still takes the entries, so the fused
    lane keeps the zero-Python shred path.  Crash-domain consequence:
    the supervisor restarts poh and shred together — entries can never
    be stranded on a ring between the two.

    outs[0] is the WIRE SHRED link (the unfused shred stage's out); the
    PoH half's credit checks therefore gate tick emission on the same
    downstream the shreds land on, which is exactly the backpressure
    the collapsed hop implies."""

    def __init__(self, *args, signer, secret: bytes | None = None,
                 shred_slot: int = 1, shred_version: int = 1,
                 batch_target_sz: int = 16384, keep_sets: bool = False,
                 shred_plane=None, **kwargs):
        super().__init__(*args, **kwargs)
        self.shred_half = ShredStage(
            f"{self.name}/shred", ins=[], outs=list(self.outs),
            signer=signer, secret=secret, slot=shred_slot,
            shred_version=shred_version, batch_target_sz=batch_target_sz,
            keep_sets=keep_sets, plane=shred_plane,
        )

    def publish(self, out_idx: int, payload: bytes, sig: int = 0,
                tsorig: int = 0) -> bool:
        """The collapsed hop: every entry the PoH half emits feeds the
        shredder in-process instead of crossing a ring."""
        meta = [0] * 8
        meta[MCache.COL_TSORIG] = tsorig
        self.shred_half.after_frag(0, meta, payload)
        self.metrics.inc("frags_out")  # unfused-poh metric parity
        return True

    def after_credit(self) -> None:
        super().after_credit()  # the clock: ticks / slot-clock sweep
        self.shred_half.after_credit()  # credit-deferred batch retry

    def during_housekeeping(self) -> None:
        self.shred_half.during_housekeeping()

    def flush(self, *, block_complete: bool = True) -> None:
        self.shred_half.flush(block_complete=block_complete)


def deshred_entry_batch(batch: bytes) -> list[bytes]:
    """Split a reassembled entry batch back into entry frames."""
    entries = []
    o = 0
    while o < len(batch):
        ln = int.from_bytes(batch[o : o + 4], "little")
        o += 4
        entries.append(batch[o : o + ln])
        o += ln
    return entries
