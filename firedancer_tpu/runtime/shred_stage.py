"""Shred stage: entries -> entry batches -> FEC sets -> wire shreds.

Pipeline position mirrors the reference's shred tile
(/root/reference/src/app/fdctl/run/tiles/fd_shred.c): accumulate poh
entries into an entry batch, run the shredder (reedsol parity + merkle +
leader signature), and publish every data+parity shred to the outgoing
link (the net/turbine hop in a full validator; tests resolve them back
with the FEC resolver).

Inputs:  ins[0] = poh -> shred entries.
Outputs: outs[0] = wire shreds (mtu >= 1228).

Entry batches close when the accumulated serialized entries reach
`batch_target_sz` (the reference bounds batches by pending shred budget)
or on flush at slot end.
"""

from __future__ import annotations

from firedancer_tpu.tango.rings import MCache
from .shredder import EntryBatchMeta, FecSet, Shredder
from .stage import Stage


class ShredStage(Stage):
    def __init__(
        self,
        *args,
        signer,
        slot: int = 1,
        shred_version: int = 1,
        batch_target_sz: int = 16384,
        keep_sets: bool = False,
        plane=None,
        **kwargs,
    ):
        super().__init__(*args, **kwargs)
        self.shredder = Shredder(signer=signer, shred_version=shred_version,
                                 plane=plane)
        self.slot = slot
        self.batch_target_sz = batch_target_sz
        self.keep_sets = keep_sets
        self.sets: list[FecSet] = []  # retained for tests/observers
        self._buf = bytearray()
        self._buf_tsorig = 0

    def after_frag(self, in_idx: int, meta, payload: bytes) -> None:
        # entries are appended verbatim: the entry frame IS this build's
        # entry-batch serialization (the reference ships bincode entries)
        self._buf += len(payload).to_bytes(4, "little")
        self._buf += payload
        ts = int(meta[MCache.COL_TSORIG])
        if ts and (self._buf_tsorig == 0 or ts < self._buf_tsorig):
            self._buf_tsorig = ts
        self.metrics.inc("entries_in")
        if len(self._buf) >= self.batch_target_sz and self._room():
            self._shred_batch(block_complete=False)

    def after_credit(self) -> None:
        # batch closed for size but deferred for credits: retry here
        if len(self._buf) >= self.batch_target_sz and self._room():
            self._shred_batch(block_complete=False)

    def _room(self) -> bool:
        """A batch bursts ~2 sets x ~65 shreds; don't start shredding unless
        the out ring can absorb it (dropping shreds mid-set wastes the set)."""
        return not self.outs or self.outs[0].cr_avail >= 256

    def flush(self, *, block_complete: bool = True) -> None:
        if self._buf:
            self._shred_batch(block_complete=block_complete)

    def _shred_batch(self, *, block_complete: bool) -> None:
        batch = bytes(self._buf)
        self._buf = bytearray()
        tsorig = self._buf_tsorig
        self._buf_tsorig = 0
        sets = self.shredder.entry_batch_to_fec_sets(
            batch,
            slot=self.slot,
            meta=EntryBatchMeta(block_complete=block_complete),
        )
        self.metrics.inc("entry_batches")
        for st in sets:
            self.metrics.inc("fec_sets")
            if self.keep_sets:
                self.sets.append(st)
            if self.outs:
                # a whole FEC set's shreds in one ring crossing on the
                # native lane (~65 frames; _room() pre-gated the credits)
                items = [(buf, st.fec_set_idx, tsorig)
                         for buf in st.data_shreds]
                items += [(buf, st.fec_set_idx, tsorig)
                          for buf in st.parity_shreds]
                self.publish_burst_out(0, items)
                self.metrics.inc("data_shreds_out", len(st.data_shreds))
                self.metrics.inc("parity_shreds_out", len(st.parity_shreds))


def deshred_entry_batch(batch: bytes) -> list[bytes]:
    """Split a reassembled entry batch back into entry frames."""
    entries = []
    o = 0
    while o < len(batch):
        ln = int.from_bytes(batch[o : o + 4], "little")
        o += 4
        entries.append(batch[o : o + ln])
        o += ln
    return entries
