"""The stage run loop — this framework's fd_mux_tile.

A Stage owns zero or more input links (as Consumers) and zero or more output
links (as Producers) and exposes the reference mux's callback set
(/root/reference/src/disco/mux/fd_mux.h:105-200):

    during_housekeeping()  — lazy out-of-band work (credits, fseq, heartbeat)
    before_credit()        — called every iteration before credit check
    after_credit()         — called when there is room to publish (batch
                             close / drain point for async device work)
    before_frag(in_idx, seq, sig) -> bool   — cheap filter (False = skip)
    during_frag(in_idx, meta, payload)      — speculative payload handling
    after_frag(in_idx, meta, payload)       — commit: process and publish

Differences from the reference, by design: the loop is cooperative
(`run_once` does one iteration) so a single process can drive a whole
topology deterministically in tests, while the process runner just calls
`run()`; and "device work" (TPU batches) is naturally asynchronous via jax
dispatch, so stages overlap host streaming with device compute without
extra threads.  Housekeeping is scheduled by iteration count rather than
tsc ticks (same randomized-lazy idea, fd_mux.c:389-474).
"""

from __future__ import annotations

import time
import zlib
from bisect import bisect_left

import numpy as np

from firedancer_tpu.tango import shm
from firedancer_tpu.tango.rings import CNC_SIG_HALT, CNC_SIG_RUN, Cnc, MCache
from firedancer_tpu.utils import metrics as fm
from .autotune import OCC_EDGES

_pc = time.perf_counter

# tango.native, resolved lazily: stages must boot (and the Python lane
# must run) in toolchain-less environments where the import-time .so
# build would fail
_native_mod = None
_native_probe_done = False


def _native_ring():
    global _native_mod, _native_probe_done
    if not _native_probe_done:
        _native_probe_done = True
        # one probe source of truth (shm's build-and-load cache); the env
        # switch is NOT consulted here — the drainer engages whenever the
        # stage's consumers actually ARE native, however they were made
        if shm._native_ring_available():
            from firedancer_tpu.tango import native as fn

            _native_mod = fn
    return _native_mod


class Metrics:
    """Per-stage metrics over a declared schema (utils/metrics.py).

    Two-tier design, the same split the reference gets in C for free:
    the PER-FRAG update path is plain dict/int arithmetic (a numpy u64
    scalar store costs ~20x a dict bump in Python, and frag-rate work
    cannot afford it), and `flush()` — called from the housekeeping pass
    alongside the cnc diag stores — copies the local state into the
    shm-backed MetricsRegistry a monitor/scrape process reads.  Readers
    therefore see values at most one lazy interval stale, exactly the
    staleness contract the cnc diag words already have.

    Counter names outside the schema still work (they stay local-only,
    like the old plain-dict Metrics); `observe()` requires a declared
    histogram.  `counters` stays a public dict for existing callers.
    """

    def __init__(self, schema: fm.MetricsSchema | None = None):
        self.schema = schema if schema is not None else fm.stage_schema()
        self.counters: dict[str, int] = {}
        # histogram state: plain lists + float sums; bisect_left over a
        # tuple of precomputed edges is ~10x cheaper than np.searchsorted
        self._hedges: dict[str, tuple] = {}
        self._hedges_np: dict[str, np.ndarray] = {}  # observe_batch lane
        self._hcounts: dict[str, list[int]] = {}
        self._hsums: dict[str, float] = {}
        for d in self.schema.defs:
            if d.native:
                # native-owned words (written in-line by a C sweep
                # client): building local state for them would make
                # flush() overwrite the C increments with zeros — the
                # facade never tracks them (fdlint FD219's contract)
                continue
            if d.kind == fm.HISTOGRAM:
                self._hedges[d.name] = d.buckets
                self._hcounts[d.name] = [0] * (len(d.buckets) + 1)
                self._hsums[d.name] = 0.0
        self.registry: fm.MetricsRegistry | None = None

    def inc(self, name: str, v: int = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + v

    def get(self, name: str) -> int:
        return self.counters.get(name, 0)

    def observe(self, name: str, value: float) -> None:
        c = self._hcounts[name]
        c[bisect_left(self._hedges[name], value)] += 1
        if value > 0:
            self._hsums[name] += value

    def observe_batch(self, name: str, values) -> None:
        """Vectorized observe() over a 1-D ndarray — the native
        burst-drain path observes a whole sweep's frag latencies from the
        returned meta table in one searchsorted+bincount instead of a
        clock read + bisect per frag."""
        edges = self._hedges_np.get(name)
        if edges is None:
            edges = self._hedges_np[name] = np.asarray(
                self._hedges[name], dtype=np.float64
            )
        c = self._hcounts[name]
        bc = np.bincount(
            np.searchsorted(edges, values, side="left"), minlength=len(c)
        )
        for j in np.flatnonzero(bc):
            c[j] += int(bc[j])
        self._hsums[name] += float(values[values > 0].sum())

    def hist(self, name: str) -> dict:
        return {
            "buckets": list(self._hedges[name]),
            "counts": list(self._hcounts[name]),
            "sum": self._hsums[name],
            "count": sum(self._hcounts[name]),
        }

    def quantile(self, name: str, q: float) -> float:
        return fm.hist_quantile(self.hist(name), q)

    # -- shm publication ----------------------------------------------------

    def attach(self, registry: fm.MetricsRegistry) -> None:
        """Bind the shm-backed registry (child boot path) and publish the
        current local state immediately."""
        self.registry = registry
        self.flush()

    def flush(self) -> None:
        """Publish local counters/histograms into the attached registry
        (no-op unattached).  Called from the stage housekeeping pass."""
        reg = self.registry
        if reg is None:
            return
        for name, (d, _off) in reg._off.items():
            if d.native:
                continue  # C-owned words: never overwrite from Python
            if d.kind == fm.HISTOGRAM:
                if name in self._hcounts:
                    reg.store_hist(name, self._hcounts[name],
                                   self._hsums[name])
            else:
                v = self.counters.get(name)
                if v is not None:
                    reg.store(name, v)


class Stage:
    def __init__(
        self,
        name: str,
        ins: list[shm.Consumer] | None = None,
        outs: list[shm.Producer] | None = None,
        cnc: Cnc | None = None,
        lazy: int = 128,
        seed: int = 0,
    ):
        self.name = name
        self.ins = ins or []
        self.outs = outs or []
        self.cnc = cnc or Cnc()
        self.metrics = Metrics(type(self).metrics_schema())
        # flight recorder: local ring by default; attach_observability
        # swaps in the shm-backed ring (replaying boot-time records) so
        # the record survives this process crashing
        self.recorder = fm.FlightRecorder(fm.FLIGHT_DEPTH)
        self.recorder.record(fm.EV_BOOT)
        self._bp_since: int | None = None  # iteration backpressure began
        self._hk_cnt = 0  # housekeeping passes (trace decimation)
        self.lazy = lazy
        # Stages that publish from after_frag set this so they never consume
        # an input frag they couldn't forward (losing e.g. a lock-release
        # message would wedge upstream; the reference makes such links
        # reliable via credit flow, fd_topo.h:99-101).
        self.require_credit = False
        # frags drained per run_once sweep (see run_once's burst loop)
        self.burst = 16
        # native ring plane: when every input is a NativeConsumer the
        # sweep drains through ONE fdr_drain FFI crossing (cached plan,
        # rebuilt when the input list changes — e.g. a chaos LossyConsumer
        # splice drops the stage back to the per-frag poll path)
        self._drainer: tuple | None = None
        # sweep-harness client (ISSUE 11): a stage that registers one (an
        # object with .cb/.cb_ctx — e.g. shred_native.StageClient) runs
        # its ENTIRE sweep through fdr_sweep: drain -> C stage callback
        # -> publish, zero Python per frag.  The fallback surfaces
        # (after_frag on mixed/lossy lanes) must forward into the same
        # C-side state so the two paths never diverge.
        self._sweep_client = None
        # in-crossing metrics plane (ISSUE 20): built lazily alongside
        # the drainer and handed into fdr_sweep so C records phase
        # histograms / counters / flight events from INSIDE the
        # crossing.  (registry-or-local, plane-or-None) — rebuilt when
        # attach_observability rebinds the registry.
        self._nplane: tuple | None = None
        # stage-extra native histogram the plane should bind as its
        # xlat slot (bank sets "nbank_txn_lat_ns")
        self.native_xlat_metric: str | None = None
        # in-place restart (runtime/topo supervisor respawn): out_idx ->
        # the ring's published-sig set, armed by resume_from_rings; the
        # publish guard suppresses re-published replay frags until the
        # stream passes the crash point (exactly-once on the wire)
        self._resume_guards: dict[int, set[int]] = {}
        # transactional progress (StageSpec.restartable): fseq advances
        # ONLY at safe points — end of a completed sweep and housekeeping
        # — never mid-poll, so a SIGKILL can never mark a frag consumed
        # whose downstream effects were not yet published
        self.safe_progress = False
        # ring-cost instrument (bench.py): when enabled, poll/drain and
        # publish time accumulate separately from stage compute
        self.ring_clock = False
        self.ring_poll_s = 0.0
        self.ring_publish_s = 0.0
        # crc32, not builtin hash(): str hashing is salted per process
        # (PYTHONHASHSEED), and spawned children must derive the SAME
        # housekeeping phase for a given (name, seed) as the parent and
        # as any restart — fdlint FD204 guards this.
        from firedancer_tpu.utils.rng import Rng

        self._rng = Rng(seed, zlib.crc32(name.encode()))
        # per-out occupancy bucket counts (OCC_EDGES geometry), sampled
        # in _housekeeping — runtime/autotune's per-link evidence
        self.out_occupancy: list[list[int]] = []
        self._next_housekeeping = 0
        self._iter = 0
        self._in_rr = 0  # round-robin input cursor
        self.cnc.signal = CNC_SIG_RUN

    # -- observability ------------------------------------------------------

    @classmethod
    def metrics_schema(cls) -> fm.MetricsSchema:
        """The stage KIND's metric layout: the shared stage-loop block
        plus whatever `extra_schema` adds.  topo.launch sizes the shm
        segment from this (via the StageSpec), so override extra_schema
        in subclasses rather than this."""
        s = fm.stage_schema()
        for d in cls.extra_schema().defs:
            s.defs.append(d)
        return s

    @classmethod
    def extra_schema(cls) -> fm.MetricsSchema:
        """Per-kind metric extensions (the per-tile block of metrics.xml)."""
        return fm.MetricsSchema()

    def trace(self, event: int, arg: int = 0) -> None:
        """Flight-recorder append (rare events only — never per frag)."""
        self.recorder.record(event, arg)

    def attach_observability(self, registry, recorder) -> None:
        """Bind the shm-backed metrics registry + flight ring (child boot
        path, after the builder ran)."""
        self.metrics.attach(registry)
        self.recorder.replay_into(recorder)
        self.recorder = recorder
        # the native plane (if one was already built) pointed at the old
        # words — drop it so the next sweep rebinds against the shm
        # segment (and the drainer plan with it)
        self._nplane = None
        self._drainer = None

    def _native_plane(self):
        """The stage's in-crossing metrics plane (NativePlane), built
        lazily against the attached shm registry — or a private local
        registry when the stage runs cooperatively without one, so the
        profiler works in-process too (bench's A/B windows).  None when
        the plane is disabled (FDTPU_NATIVE_METRICS=0) or the schema
        lacks the native block."""
        cached = self._nplane
        if cached is not None and cached[0] is self.metrics.registry:
            return cached[1]
        from . import native_metrics as nm

        plane = None
        reg = self.metrics.registry
        if nm.enabled():
            if reg is None:
                reg = fm.MetricsRegistry(self.metrics.schema)
                self.metrics.attach(reg)
            try:
                plane = nm.NativePlane(
                    reg, self.recorder,
                    xlat=self.native_xlat_metric,
                )
            except (nm.PlaneUnavailable, KeyError):
                plane = None
        self._nplane = (self.metrics.registry, plane)
        return plane

    def drop_native_views(self) -> None:
        """Terminal: release every native-plane reference holding views
        over an shm metrics segment (the plane itself, the drainer plan
        that embeds it, and the sweep client's keepalive), so a caller
        that owns the segment can close it without BufferError.  The
        stage must not sweep again after this."""
        self._nplane = None
        self._drainer = None
        client = self._sweep_client
        if client is not None and getattr(client, "_plane", None) is not None:
            set_metrics = getattr(client, "set_metrics", None)
            if set_metrics is not None:
                set_metrics(None)  # C drops its raw pointer too

    # -- in-place restart (supervisor respawn) -------------------------------

    def resume_from_rings(self) -> None:
        """Reattach this stage's cursors to its EXISTING shm rings after
        a supervisor respawn (runtime/topo supervise restart path):

          - every consumer resumes at the progress it last PUBLISHED to
            its fseq (frags consumed past that before the crash replay);
          - every producer resumes at the frontier recovered from its
            own mcache (never seq 0 — that would lap live consumers and
            clobber in-flight payloads), and its ring's published sigs
            arm the publish guard so replayed frags are suppressed
            rather than re-delivered.

        Exactly-once holds for stages whose output is a pure function of
        their input stream and whose frag sigs are unique within a ring
        depth (every pipeline link's are).  A SOURCE stage (no inputs)
        must derive its own progress from producer state — override this
        and read `self.outs[i].seq` (see chaos/scenario's gen stage)."""
        for c in self.ins:
            c.resume()
        self._resume_guards = {}
        for i, p in enumerate(self.outs):
            sigs = p.resume()
            if sigs:
                self._resume_guards[i] = sigs
        self.trace(fm.EV_RESTART, self._iter)

    def arm_safe_progress(self) -> None:
        """Make fseq publication TRANSACTIONAL for this stage (the
        restartable-stage contract, StageSpec.restartable): consumers
        stop auto-publishing progress mid-poll (their lazy interval is
        pushed out of reach) and run_once publishes it only after a
        sweep's frag effects are fully out.  A SIGKILL therefore leaves
        the fseq at a point where everything at or before it is on the
        wire — resume replays at-least-once and the publish guard dedups
        to exactly-once."""
        self.safe_progress = True
        for c in self.ins:
            c.set_lazy(1 << 62)

    def _commit_progress(self) -> None:
        for c in self.ins:
            c.publish_progress()

    def _guarded(self, out_idx: int, sig: int) -> bool:
        """True = this publish is a replay duplicate: swallow it.  The
        guard disarms at the first sig the pre-crash ring never carried
        (the replay has passed the crash point and everything after is
        new work)."""
        g = self._resume_guards.get(out_idx)
        if g is None:
            return False
        if sig in g:
            g.discard(sig)
            self.metrics.inc("restart_dedup")
            return True
        del self._resume_guards[out_idx]
        return False

    # -- callbacks (override in subclasses) ---------------------------------

    def during_housekeeping(self) -> None: ...

    def before_credit(self) -> None: ...

    def after_credit(self) -> None: ...

    def before_frag(self, in_idx: int, seq: int, sig: int) -> bool:
        return True

    def during_frag(self, in_idx: int, meta, payload: bytes) -> None: ...

    def after_frag(self, in_idx: int, meta, payload: bytes) -> None: ...

    # -- the loop -----------------------------------------------------------

    # cnc diagnostic word layout (read by the monitor, fd_cnc.h diag words)
    DIAG_FRAGS_IN = 0
    DIAG_FRAGS_OUT = 1
    DIAG_OVERRUN = 2
    DIAG_BACKPRESSURE = 3
    DIAG_ITER = 4

    def _housekeeping(self) -> None:
        for c in self.ins:
            c.publish_progress()
        for p in self.outs:
            p.refresh_credits()
        # per-link occupancy sample (1 - credits/depth) at housekeeping
        # cadence — the evidence the credit/depth autotuner
        # (runtime/autotune) sizes rings and laziness from.  Kept both
        # as the schema histogram (monitor/scrape) and as per-out bucket
        # counts (per-LINK resolution the aggregate hist can't give).
        if len(self.out_occupancy) != len(self.outs):
            self.out_occupancy = [
                [0] * (len(OCC_EDGES) + 1) for _ in self.outs
            ]
        for i, p in enumerate(self.outs):
            d = getattr(getattr(p, "link", None), "depth", 0)
            if d:
                occ = 1.0 - p.cr_avail / d
                self.metrics.observe("out_occupancy", occ)
                self.out_occupancy[i][bisect_left(OCC_EDGES, occ)] += 1
        self.cnc.heartbeat(time.monotonic_ns())
        m = self.metrics
        self.cnc.diag_set(self.DIAG_FRAGS_IN, m.get("frags_in"))
        self.cnc.diag_set(self.DIAG_FRAGS_OUT, m.get("frags_out"))
        self.cnc.diag_set(self.DIAG_OVERRUN, m.get("overrun"))
        self.cnc.diag_set(self.DIAG_BACKPRESSURE, m.get("backpressure"))
        self.cnc.diag_set(self.DIAG_ITER, self._iter)
        m.flush()  # publish schema metrics to the shm registry (if any)
        # decimated: one timeline tick per 32 passes, or the 512-slot
        # ring would hold nothing but housekeeping when a stage runs hot
        self._hk_cnt += 1
        if self._hk_cnt & 31 == 1:
            self.trace(fm.EV_HOUSEKEEPING, self._iter)
        self.during_housekeeping()
        # randomized lazy interval: [lazy/2, 3*lazy/2) iterations
        self._next_housekeeping = self._iter + self.lazy // 2 + self._rng.roll(
            max(self.lazy, 1)
        )

    def run_once(self) -> bool:
        """One loop iteration; returns True if any frag was processed."""
        self._iter += 1
        if self._iter >= self._next_housekeeping:
            self._housekeeping()
            if self.cnc.signal == CNC_SIG_HALT:
                return False
        self.before_credit()
        backpressured = any(p.cr_avail <= 0 for p in self.outs)
        if backpressured:
            for p in self.outs:  # stale credits? re-read consumer fseqs
                p.refresh_credits()
            backpressured = any(p.cr_avail <= 0 for p in self.outs)
        # backpressure onset/relief transitions ride the flight recorder
        # (a transition, not a per-frag event: two int compares per iter)
        if backpressured:
            if self._bp_since is None:
                self._bp_since = self._iter
                self.trace(fm.EV_BACKPRESSURE_ON, self._iter)
        elif self._bp_since is not None:
            self.trace(fm.EV_BACKPRESSURE_OFF, self._iter - self._bp_since)
            self._bp_since = None
        if not backpressured:
            self.after_credit()
        if self.require_credit and any(p.cr_avail <= 0 for p in self.outs):
            # Re-checked AFTER after_credit: it may have spent the last
            # credit (e.g. a poh tick entry), and consuming an input frag
            # we can't forward would silently drop it.
            self.metrics.inc("backpressure_stall")
            return False
        n_in = len(self.ins)
        if n_in:
            drainer = self._native_drainer()
            if drainer is not None:
                if self._sweep_client is not None:
                    progressed = self._native_sweep(drainer)
                else:
                    progressed = self._native_burst(drainer)
                if progressed and self.safe_progress:
                    # transactional commit: the drained sweep's effects
                    # are out, so the fseq may now cover it
                    self._commit_progress()
                return progressed
        progressed = False
        # burst-drain: up to `burst` frags per sweep.  One-frag sweeps
        # make the COOPERATIVE scheduler pay the whole loop overhead
        # (credits, housekeeping checks, empty polls of sibling inputs)
        # per frag — the dominant host-path cost at profile; the
        # reference's stem loop amortizes the same way in C.
        for _ in range(max(1, self.burst)):
            if progressed and self.require_credit and any(
                p.cr_avail <= 0 for p in self.outs
            ):
                break  # mid-burst credit exhaustion: stop cleanly
            got = False
            for k in range(n_in):
                idx = (self._in_rr + k) % n_in
                cons = self.ins[idx]
                seq = cons.seq
                if self.ring_clock:
                    _t = _pc()
                    res = cons.poll()
                    self.ring_poll_s += _pc() - _t
                else:
                    res = cons.poll()
                if res == shm.POLL_EMPTY:
                    continue
                if res == shm.POLL_OVERRUN:
                    self.metrics.inc("overrun")
                    # decimated: a sustained lap overruns per poll and
                    # would flood the flight ring (arg = running total,
                    # so the dump still shows the loss magnitude)
                    n = self.metrics.get("overrun")
                    if n & 63 == 1:
                        self.trace(fm.EV_OVERRUN, n)
                    progressed = True
                    got = True
                    break
                meta, payload = res
                progressed = True
                got = True
                if not self.before_frag(idx, seq, int(meta[MCache.COL_SIG])):
                    self.metrics.inc("filtered")
                else:
                    self.during_frag(idx, meta, payload)
                    self.after_frag(idx, meta, payload)
                    self.metrics.inc("frags_in")
                    # per-hop + e2e latency: tsorig is stamped once at the
                    # origin stage and carried through every ring, so this
                    # observation at the LAST stage is the whole-pipeline
                    # figure.  Cheap by construction: one vDSO clock read
                    # (the same cost Producer.try_publish already pays per
                    # frag) + a bisect over precomputed edges.
                    ts = int(meta[MCache.COL_TSORIG])
                    if ts:
                        lat = shm.now_ns() - ts
                        if lat >= 0:
                            self.metrics.observe("frag_latency_ns", lat)
                self._in_rr = (idx + 1) % n_in
                break
            if not got:
                break
        if progressed and self.safe_progress:
            self._commit_progress()
        return progressed

    # -- native ring burst path ---------------------------------------------

    def _native_drainer(self):
        """The cached fdr_drain/fdr_sweep plan when EVERY input is a
        native-ring consumer, else None (per-frag poll path — Python
        consumers, LossyConsumer shims, mixed lanes).  Keyed on the
        input objects AND the sweep client so a spliced/replaced input
        (or a re-armed client) rebuilds the plan."""
        cached = self._drainer
        client = self._sweep_client
        # list == compares elements by identity here (consumers define no
        # __eq__), so revalidation costs no allocation per sweep; a chaos
        # LossyConsumer splice (stage.ins[i] = shim) breaks the equality
        # and rebuilds the plan
        if cached is not None and cached[0] == self.ins \
                and cached[2] is client:
            return cached[1]
        drainer = None
        fn = _native_ring()
        if fn is not None and all(
            type(c) is fn.NativeConsumer for c in self.ins
        ):
            if client is not None:
                plane = self._native_plane()
                drainer = fn.SweepDrainer(self.ins, max(1, self.burst),
                                          client, plane)
                if plane is not None:
                    set_metrics = getattr(client, "set_metrics", None)
                    if set_metrics is not None:
                        # hand the plane into the stage's own C context
                        # too: apply/publish phase attribution + stage
                        # extras (bank's per-txn latency) write through it
                        set_metrics(plane)
            else:
                drainer = fn.BurstDrainer(self.ins, max(1, self.burst))
        self._drainer = (list(self.ins), drainer, client)
        return drainer

    def _native_sweep(self, drainer) -> bool:
        """One run_once sweep through the generic sweep harness: ONE FFI
        crossing drains every input AND runs the stage's registered C
        callback per frag (fdr_sweep) — drain table -> stage compute ->
        publish with zero Python per frag.  Python's per-sweep work is
        bookkeeping only: frags_in and the batched frag_latency_ns
        observation off the returned meta table."""
        max_frags = self.burst if self.burst > 0 else 1
        m = self.metrics
        # the crossing fuses drain + stage compute + publish: its time
        # is stage compute, not ring machinery — even under ring_clock
        # it is NOT clocked into ring_poll_s (the A/B ring split stays
        # honest)
        n, self._in_rr, d_ovr = drainer.sweep(self._in_rr, max_frags)
        if d_ovr:
            m.inc("overrun", d_ovr)
            tot = m.get("overrun")
            if (tot ^ (tot - d_ovr)) >> 6 or tot == d_ovr:
                self.trace(fm.EV_OVERRUN, tot)
        if n == 0:
            return d_ovr > 0
        m.inc("frags_in", n)
        ts_col = drainer.meta[:n, 5].astype(np.int64)
        lat = shm.now_ns() - ts_col
        ok = lat[(ts_col > 0) & (lat >= 0)]
        if ok.size:
            m.observe_batch("frag_latency_ns", ok)
        return True

    # drain-table batch hook: a stage may process a whole drained sweep
    # from the meta table + joined payload buffer in ONE call instead of
    # per-frag before/during/after dispatch (3 dynamic calls per frag on
    # the hot path).  Return (frags consumed, [tsorig...]) with the same
    # counting rules the per-frag loop has.  None = use the per-frag loop.
    sweep_frags = None

    def _native_burst(self, drainer) -> bool:
        """One run_once sweep over the native ring plane: ONE FFI
        crossing pulls up to `burst` frags from all inputs round-robin
        into the drainer's arena; frag callbacks then run over the
        returned meta table (after_frag semantics unchanged), and
        frag_latency_ns is batch-observed from the tsorig column — no
        per-frag Python timestamping."""
        max_frags = self.burst if self.burst > 0 else 1
        if self.require_credit and self.outs:
            # never pull a frag we may not be able to forward: each input
            # frag spends at most one credit per output link in every
            # stage that sets require_credit (router/bank/poh)
            cap = min(p.cr_avail for p in self.outs)
            if cap < max_frags:
                max_frags = cap
        if max_frags <= 0:
            return False
        m = self.metrics
        if self.ring_clock:
            _t = _pc()
            n, self._in_rr, d_ovr = drainer.drain(self._in_rr, max_frags)
            self.ring_poll_s += _pc() - _t
        else:
            n, self._in_rr, d_ovr = drainer.drain(self._in_rr, max_frags)
        if d_ovr:
            m.inc("overrun", d_ovr)
            tot = m.get("overrun")
            # decimated like the per-frag path: one timeline tick per
            # 64-overrun stride (arg = running total)
            if (tot ^ (tot - d_ovr)) >> 6 or tot == d_ovr:
                self.trace(fm.EV_OVERRUN, tot)
        if n == 0:
            return d_ovr > 0
        # one block conversion each: meta rows become plain-int lists
        # (python list indexing beats a numpy scalar read ~5x in the
        # per-frag loop below) and payloads one contiguous bytes copy
        # (frags land back-to-back in the arena, so the last frag's end
        # bounds them all; bytes slicing is then near-free per frag)
        rows = drainer.meta[:n].tolist()
        last = rows[n - 1]
        buf = drainer.arena[: last[2] + last[3]].tobytes()
        sweep_frags = self.sweep_frags
        if sweep_frags is not None:
            n_done, ts_done = sweep_frags(rows, buf)
            if n_done:
                m.inc("frags_in", n_done)
                ts_col = np.asarray(ts_done, dtype=np.int64)
                lat = shm.now_ns() - ts_col
                ok = lat[(ts_col > 0) & (lat >= 0)]
                if ok.size:
                    m.observe_batch("frag_latency_ns", ok)
            return True
        before_frag = self.before_frag
        during_frag = self.during_frag
        after_frag = self.after_frag
        n_done = 0
        ts_done: list[int] = []
        for row in rows:
            idx = row[7]
            if not before_frag(idx, row[0], row[1]):
                m.inc("filtered")
                continue
            off = row[2]
            payload = buf[off : off + row[3]]
            during_frag(idx, row, payload)
            after_frag(idx, row, payload)
            n_done += 1
            ts_done.append(row[5])
        if n_done:
            m.inc("frags_in", n_done)
            # batch latency observation: one clock read for the sweep
            ts_col = np.asarray(ts_done, dtype=np.int64)
            lat = shm.now_ns() - ts_col
            ok = lat[(ts_col > 0) & (lat >= 0)]
            if ok.size:
                m.observe_batch("frag_latency_ns", ok)
        return True

    def run(
        self,
        max_iters: int | None = None,
        *,
        idle_spins: int = 256,
        idle_sleep_s: float = 0.001,
    ) -> None:
        """The process-runner loop.  The reference spins with PAUSE on a
        DEDICATED core; without core pinning a hot spin just steals CPU
        from busy sibling stages, so after `idle_spins` empty iterations
        the loop naps briefly (progress resets the counter)."""
        it = 0
        idle = 0
        self.trace(fm.EV_RUN)
        while self.cnc.signal != CNC_SIG_HALT:
            if self.run_once():
                idle = 0
            else:
                idle += 1
                if idle >= idle_spins:
                    time.sleep(idle_sleep_s)
            it += 1
            if max_iters is not None and it >= max_iters:
                break
        self.trace(fm.EV_HALT, self._iter)
        self.metrics.flush()  # final state visible to post-mortem readers

    def halt(self) -> None:
        self.cnc.signal = CNC_SIG_HALT

    # -- helpers ------------------------------------------------------------

    def publish(
        self, out_idx: int, payload: bytes, sig: int = 0, tsorig: int = 0
    ) -> bool:
        if self._resume_guards and self._guarded(out_idx, sig):
            return True  # replay duplicate: already on the wire pre-crash
        p = self.outs[out_idx]
        if self.ring_clock:
            _t = _pc()
            ok = p.try_publish(payload, sig=sig, tsorig=tsorig)
            self.ring_publish_s += _pc() - _t
        else:
            ok = p.try_publish(payload, sig=sig, tsorig=tsorig)
        if ok:
            self.metrics.inc("frags_out")
        else:
            self.metrics.inc("backpressure")
        return ok

    def publish_burst_out(self, out_idx: int, items: list) -> int:
        """Publish a frame list [(payload, sig, tsorig), ...] on one
        output — ONE ring crossing on the native lane
        (fdr_publish_burst), an in-order per-frame loop on the Python
        lane.  Both stop at credit exhaustion; the shortfall counts as
        backpressure and stays with the caller.  Returns frames
        published."""
        if not items:
            return 0
        if self._resume_guards and out_idx in self._resume_guards:
            # replay window after an in-place restart: route through the
            # per-frame path so the publish guard sees every sig (the
            # guard disarms within one ring depth — not a hot path)
            n = 0
            for payload, sig, tsorig in items:
                if self._guarded(out_idx, sig):
                    n += 1
                    continue
                if not self.publish(out_idx, payload, sig=sig,
                                    tsorig=tsorig):
                    break
                n += 1
            return n
        p = self.outs[out_idx]
        burst = getattr(p, "publish_burst", None)
        # the native burst publishes through the metrics plane (ISSUE
        # 20): the crossing's duration observes into the stage's
        # publish-phase histogram from INSIDE C
        plane = self._native_plane() if burst is not None else None
        if self.ring_clock:
            _t = _pc()
            n = self._publish_items(p, burst, items, plane)
            self.ring_publish_s += _pc() - _t
        else:
            n = self._publish_items(p, burst, items, plane)
        if n:
            self.metrics.inc("frags_out", n)
        if n < len(items):
            self.metrics.inc("backpressure", len(items) - n)
        return n

    @staticmethod
    def _publish_items(p, burst, items, plane=None) -> int:
        if burst is not None:
            return burst(items, plane)
        n = 0
        for payload, sig, tsorig in items:
            if not p.try_publish(payload, sig=sig, tsorig=tsorig):
                break
            n += 1
        return n
